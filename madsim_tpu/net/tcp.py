"""Simulated TCP (reference: madsim/src/sim/net/tcp/).

`TcpListener`/`TcpStream` over a connect1 payload channel: writes are
buffered until flush (reference: stream.rs:137-187), EOF on channel
close, partition => connect refused / reads stall until unclogged
(reference: tcp/mod.rs tests :58-308)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

from .endpoint import Endpoint, PayloadReceiver, PayloadSender
from .network import Addr, ConnectionReset


class TcpStream:
    """Reference: tcp/stream.rs `TcpStream`."""

    def __init__(self, tx: PayloadSender, rx: PayloadReceiver, local_addr: Addr, peer_addr: Addr):
        self._tx = tx
        self._rx = rx
        self.local_addr = local_addr
        self.peer_addr = peer_addr
        self._wbuf = bytearray()
        self._rbuf = bytearray()
        self._eof = False

    @staticmethod
    async def connect(addr: Any) -> "TcpStream":
        """Reference: tcp/stream.rs:47-90."""
        ep = await Endpoint.bind(("0.0.0.0", 0))
        tx, rx = await ep.connect1(addr)
        from .network import parse_addr

        return TcpStream(tx, rx, ep.local_addr, parse_addr(addr))

    def write(self, data: bytes) -> int:
        """Buffered until flush (reference: stream.rs poll_write)."""
        self._wbuf.extend(data)
        return len(data)

    async def flush(self) -> None:
        if self._wbuf:
            payload, self._wbuf = bytes(self._wbuf), bytearray()
            self._tx.send(payload)

    async def write_all(self, data: bytes) -> None:
        self.write(data)
        await self.flush()

    async def read(self, n: int = 65536) -> bytes:
        """Up to n bytes; b"" at EOF (reference: stream.rs poll_read)."""
        while not self._rbuf and not self._eof:
            chunk = await self._rx.recv()
            if chunk is None:
                self._eof = True
                break
            self._rbuf.extend(chunk)
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    async def read_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = await self.read(n - len(out))
            if not chunk:
                raise ConnectionReset("early EOF in read_exact")
            out.extend(chunk)
        return bytes(out)

    def shutdown(self) -> None:
        self._tx.close()


class TcpListener:
    """Reference: tcp/listener.rs `TcpListener`."""

    def __init__(self, ep: Endpoint):
        self._ep = ep

    @staticmethod
    async def bind(addr: Any) -> "TcpListener":
        """Reference: tcp/listener.rs:34-50."""
        return TcpListener(await Endpoint.bind(addr))

    @property
    def local_addr(self) -> Addr:
        return self._ep.local_addr

    async def accept(self) -> Tuple[TcpStream, Addr]:
        """Reference: tcp/listener.rs:52-70."""
        tx, rx, peer = await self._ep.accept1()
        return TcpStream(tx, rx, self._ep.local_addr, peer), peer

    def close(self) -> None:
        self._ep.close()
