"""Typed request/response RPC over Endpoint tags
(reference: madsim/src/sim/net/rpc.rs + madsim-macros).

Shape parity with the reference:
  * a request type has a stable u64 ID derived from its name
    (reference: rpc.rs:82 `hash_str`; macro `#[derive(Request)]`
    madsim-macros/src/request.rs)
  * `call` sends (rsp_tag=random u64, req, data) on tag=ID and awaits
    rsp_tag (reference: rpc.rs:108-132)
  * `add_rpc_handler` runs a loop that spawns one task per request
    (reference: rpc.rs:143-167)

Python has no proc macros; `Request` subclassing replaces
`#[derive(Request)]`, and the `@service`/`@rpc` decorators replace
`#[madsim::service]` (madsim-macros/src/service.rs).
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable, Dict, Optional, Tuple, Type

from .. import rand
from .. import time as sim_time
from ..rand.philox import splitmix64
from ..task import spawn
from ..task.join import JoinHandle
from .endpoint import Endpoint
from .network import Addr


def hash_str(s: str) -> int:
    """Stable string -> u64 (reference: rpc.rs:82 const hash)."""
    h = 0xCBF29CE484222325  # FNV offset basis as a start value
    for b in s.encode():
        h = splitmix64(h ^ b)
    return h


class Request:
    """Base class for RPC requests (reference: rpc.rs:73-79 `Request` trait).

    Subclass and (optionally) set `Response`; the type ID is derived from
    the class name, like the derive macro hashes type name + rtype."""

    @classmethod
    def type_id(cls) -> int:
        # per-class cache (__dict__ check: subclasses must not inherit it)
        tid = cls.__dict__.get("_type_id_cache")
        if tid is None:
            tid = hash_str(f"{cls.__module__}.{cls.__qualname__}")
            cls._type_id_cache = tid
        return tid


Handler = Callable[..., Awaitable[Any]]


async def call(ep: Endpoint, dst: Any, req: Request, timeout: Optional[float] = None) -> Any:
    """RPC round trip (reference: rpc.rs:108-132 `call`/`call_with_data`)."""
    rsp, _data = await call_with_data(ep, dst, req, b"", timeout=timeout)
    return rsp


async def call_with_data(
    ep: Endpoint, dst: Any, req: Request, data: bytes, timeout: Optional[float] = None
) -> Tuple[Any, bytes]:
    rsp_tag = rand.thread_rng().next_u64()

    async def round_trip() -> Tuple[Any, bytes]:
        await ep.send_to_raw(dst, type(req).type_id(), (rsp_tag, req, data), kind="rpc_req")
        payload, _from = await ep.recv_from_raw(rsp_tag)
        rsp, rsp_data = payload
        return rsp, rsp_data

    if timeout is None:
        return await round_trip()
    # call_timeout (reference: rpc.rs:96)
    return await sim_time.timeout(timeout, round_trip())


def add_rpc_handler(ep: Endpoint, req_type: Type[Request], handler: Handler) -> JoinHandle:
    """Serve `req_type` on this endpoint: one spawned task per request
    (reference: rpc.rs:143-167)."""

    async def loop_() -> None:
        while True:
            payload, from_addr = await ep.recv_from_raw(req_type.type_id())
            rsp_tag, req, data = payload

            async def handle_one(rsp_tag=rsp_tag, req=req, data=data, from_addr=from_addr) -> None:
                result = await handler(req, data)
                if isinstance(result, tuple) and len(result) == 2 and isinstance(result[1], (bytes, bytearray)):
                    rsp, rsp_data = result
                else:
                    rsp, rsp_data = result, b""
                await ep.send_to_raw(from_addr, rsp_tag, (rsp, bytes(rsp_data)), kind="rpc_rsp")

            spawn(handle_one())

    return spawn(loop_())


# Ergonomic methods on Endpoint (the reference implements these as
# inherent methods on Endpoint in rpc.rs).
async def _ep_call(self: Endpoint, dst, req, timeout=None):
    return await call(self, dst, req, timeout=timeout)


async def _ep_call_with_data(self: Endpoint, dst, req, data, timeout=None):
    return await call_with_data(self, dst, req, data, timeout=timeout)


async def _ep_call_timeout(self: Endpoint, dst, req, timeout):
    return await call(self, dst, req, timeout=timeout)


def _ep_add_rpc_handler(self: Endpoint, req_type, handler):
    return add_rpc_handler(self, req_type, handler)


Endpoint.call = _ep_call  # type: ignore[attr-defined]
Endpoint.call_with_data = _ep_call_with_data  # type: ignore[attr-defined]
Endpoint.call_timeout = _ep_call_timeout  # type: ignore[attr-defined]
Endpoint.add_rpc_handler = _ep_add_rpc_handler  # type: ignore[attr-defined]


# -- service decorators (macro parity: #[madsim::service] / #[rpc]) ---------


def rpc(req_type: Type[Request]) -> Callable[[Handler], Handler]:
    """Mark a method as the handler for `req_type`
    (reference: madsim-macros/src/service.rs `#[rpc]`)."""

    def mark(fn: Handler) -> Handler:
        fn.__rpc_request_type__ = req_type  # type: ignore[attr-defined]
        return fn

    return mark


def service(cls: type) -> type:
    """Collect `@rpc` methods and add `serve_on(self, ep)`
    (reference: madsim-macros/src/service.rs `service2`)."""
    handlers: Dict[Type[Request], str] = {}
    for name in dir(cls):
        fn = getattr(cls, name, None)
        req_type = getattr(fn, "__rpc_request_type__", None)
        if req_type is not None:
            handlers[req_type] = name

    def serve_on(self, ep: Endpoint):
        import inspect

        joins = []
        for req_type, name in handlers.items():
            method = getattr(self, name)
            wants_data = len(inspect.signature(method).parameters) >= 2

            async def handler(req, data, method=method, wants_data=wants_data):
                if wants_data:
                    return await method(req, data)
                return await method(req)

            joins.append(add_rpc_handler(ep, req_type, handler))
        return joins

    cls.serve_on = serve_on  # type: ignore[attr-defined]
    cls.__rpc_handlers__ = handlers  # type: ignore[attr-defined]
    return cls
