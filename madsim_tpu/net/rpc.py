"""Typed request/response RPC over Endpoint tags
(reference: madsim/src/sim/net/rpc.rs + madsim-macros).

Shape parity with the reference:
  * a request type has a stable u64 ID derived from its name
    (reference: rpc.rs:82 `hash_str`; macro `#[derive(Request)]`
    madsim-macros/src/request.rs)
  * `call` sends (rsp_tag=random u64, req, data) on tag=ID and awaits
    rsp_tag (reference: rpc.rs:108-132)
  * `add_rpc_handler` runs a loop that spawns one task per request
    (reference: rpc.rs:143-167)

Python has no proc macros; `Request` subclassing replaces
`#[derive(Request)]`, and the `@service`/`@rpc` decorators replace
`#[madsim::service]` (madsim-macros/src/service.rs).
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable, Dict, Optional, Tuple, Type

from .. import rand
from .. import time as sim_time
from ..future import await_
from ..rand.philox import splitmix64
from ..task import spawn
from ..task.join import JoinHandle
from .endpoint import Endpoint
from .network import Addr, ConnectionReset, parse_addr


def hash_str(s: str) -> int:
    """Stable string -> u64 (reference: rpc.rs:82 const hash)."""
    h = 0xCBF29CE484222325  # FNV offset basis as a start value
    for b in s.encode():
        h = splitmix64(h ^ b)
    return h


class Request:
    """Base class for RPC requests (reference: rpc.rs:73-79 `Request` trait).

    Subclass and (optionally) set `Response`; the type ID is derived from
    the class name, like the derive macro hashes type name + rtype."""

    @classmethod
    def type_id(cls) -> int:
        # per-class cache (__dict__ check: subclasses must not inherit it)
        tid = cls.__dict__.get("_type_id_cache")
        if tid is None:
            tid = hash_str(f"{cls.__module__}.{cls.__qualname__}")
            cls._type_id_cache = tid
        return tid


Handler = Callable[..., Awaitable[Any]]


async def call(ep: Endpoint, dst: Any, req: Request, timeout: Optional[float] = None) -> Any:
    """RPC round trip (reference: rpc.rs:108-132 `call`/`call_with_data`)."""
    rsp, _data = await call_with_data(ep, dst, req, b"", timeout=timeout)
    return rsp


_NATIVE_MAILBOX = None
_NATIVE_RECV_DEADLINE = None
_native_resolved = False


def _resolve_native_rpc():
    """The fused recv-with-deadline pollable (hostcore.RecvDeadline):
    one native poll replaces the timeout()/race/inline-future tower on
    the RPC hot path. Resolved lazily, once."""
    global _NATIVE_MAILBOX, _NATIVE_RECV_DEADLINE, _native_resolved
    _native_resolved = True
    from .. import _native

    if _native.available():
        mod = _native.get_mod()
        _NATIVE_MAILBOX = mod.Mailbox
        _NATIVE_RECV_DEADLINE = mod.RecvDeadline


async def call_with_data(
    ep: Endpoint, dst: Any, req: Request, data: bytes, timeout: Optional[float] = None
) -> Tuple[Any, bytes]:
    if timeout is not None:
        if not _native_resolved:
            _resolve_native_rpc()
        net = ep._net
        nc = getattr(net, "_netcore", None)
        mb = ep._mailbox
        if (
            nc is not None
            and _NATIVE_RECV_DEADLINE is not None
            and type(mb) is _NATIVE_MAILBOX
        ):
            # fully fused native initiation: rsp-tag draw (the same
            # thread_rng().next_u64() the Python path makes), the
            # recv-with-deadline registration (anchored at call start,
            # like timeout() anchors before its first inner poll;
            # register-before-send is equivalent since the response
            # cannot arrive before the request leaves), and the send
            th = net.time
            resolved_dst = parse_addr(dst)
            wait, blocking = nc.rpc_call(
                mb, ep.node_id, ep.local_addr, resolved_dst,
                net.resolve_name(resolved_dst), type(req).type_id(), req,
                data, th.now_ns() + sim_time.to_ns(timeout),
            )
            if blocking is not None:
                _mode, delay_ns, payload = blocking
                await sim_time.sleep_ns(delay_ns)
                net._send_phase2(
                    ep.node_id, ep.local_addr, resolved_dst,
                    net.resolve_name(resolved_dst), type(req).type_id(),
                    payload, "rpc_req",
                )
            if ep._closed:
                # the Python path consumes the same draws and sends the
                # request, then raises at the recv step (recv_from_raw's
                # closed check) — mirror it exactly so the RNG streams
                # stay bit-identical across engines
                wait.drop()
                raise ConnectionReset("endpoint closed")
            msg = await await_(wait)
            if msg is None:
                raise TimeoutError(f"timed out after {timeout}s (virtual)")
            rsp, rsp_data = msg.payload
            return rsp, rsp_data

    rsp_tag = rand.thread_rng().next_u64()

    async def round_trip() -> Tuple[Any, bytes]:
        await ep.send_to_raw(dst, type(req).type_id(), (rsp_tag, req, data), kind="rpc_req")
        payload, _from = await ep.recv_from_raw(rsp_tag)
        rsp, rsp_data = payload
        return rsp, rsp_data

    if timeout is None:
        return await round_trip()
    # call_timeout (reference: rpc.rs:96)
    return await sim_time.timeout(timeout, round_trip())


async def _handle_one(ep: Endpoint, handler: Handler, rsp_tag, req, data, from_addr) -> None:
    result = await handler(req, data)
    if isinstance(result, tuple) and len(result) == 2 and isinstance(result[1], (bytes, bytearray)):
        rsp, rsp_data = result
    else:
        rsp, rsp_data = result, b""
    pend = ep.send_fast(from_addr, rsp_tag, (rsp, bytes(rsp_data)), "rpc_rsp")
    if pend is not None:
        await pend


_RPC_HANDLER_LOC = (__file__, "rpc-handler")  # static spawn-site key


def add_rpc_handler(ep: Endpoint, req_type: Type[Request], handler: Handler) -> JoinHandle:
    """Serve `req_type` on this endpoint: one spawned task per request
    (reference: rpc.rs:143-167)."""
    from .. import _context

    tid = req_type.type_id()

    async def loop_() -> None:
        mb = ep._mailbox
        # the loop's own node/executor are fixed for its lifetime
        ctx = _context.current()
        node = ctx.current_task.node
        ex_spawn = ctx.executor.spawn
        recv = mb.recv
        while True:
            if ep._closed:
                # recv_from_raw's per-call closed check: a closed
                # endpoint stops serving (buffered requests included)
                raise ConnectionReset("endpoint closed")
            msg = await await_(recv(tid))
            rsp_tag, req, data = msg.payload
            # fire-and-forget handler task: low-level spawn skips the
            # JoinHandle + caller-frame walk of the public task.spawn
            ex_spawn(
                _handle_one(ep, handler, rsp_tag, req, data, msg.from_addr),
                node,
                location=_RPC_HANDLER_LOC,
            )

    return spawn(loop_())


# Ergonomic methods on Endpoint (the reference implements these as
# inherent methods on Endpoint in rpc.rs).
async def _ep_call(self: Endpoint, dst, req, timeout=None):
    rsp, _data = await call_with_data(self, dst, req, b"", timeout=timeout)
    return rsp


async def _ep_call_with_data(self: Endpoint, dst, req, data, timeout=None):
    return await call_with_data(self, dst, req, data, timeout=timeout)


async def _ep_call_timeout(self: Endpoint, dst, req, timeout):
    rsp, _data = await call_with_data(self, dst, req, b"", timeout=timeout)
    return rsp


def _ep_add_rpc_handler(self: Endpoint, req_type, handler):
    return add_rpc_handler(self, req_type, handler)


Endpoint.call = _ep_call  # type: ignore[attr-defined]
Endpoint.call_with_data = _ep_call_with_data  # type: ignore[attr-defined]
Endpoint.call_timeout = _ep_call_timeout  # type: ignore[attr-defined]
Endpoint.add_rpc_handler = _ep_add_rpc_handler  # type: ignore[attr-defined]


# -- service decorators (macro parity: #[madsim::service] / #[rpc]) ---------


def rpc(req_type: Type[Request]) -> Callable[[Handler], Handler]:
    """Mark a method as the handler for `req_type`
    (reference: madsim-macros/src/service.rs `#[rpc]`)."""

    def mark(fn: Handler) -> Handler:
        fn.__rpc_request_type__ = req_type  # type: ignore[attr-defined]
        return fn

    return mark


def service(cls: type) -> type:
    """Collect `@rpc` methods and add `serve_on(self, ep)`
    (reference: madsim-macros/src/service.rs `service2`)."""
    handlers: Dict[Type[Request], str] = {}
    for name in dir(cls):
        fn = getattr(cls, name, None)
        req_type = getattr(fn, "__rpc_request_type__", None)
        if req_type is not None:
            handlers[req_type] = name

    def serve_on(self, ep: Endpoint):
        import inspect

        joins = []
        for req_type, name in handlers.items():
            method = getattr(self, name)
            wants_data = len(inspect.signature(method).parameters) >= 2

            async def handler(req, data, method=method, wants_data=wants_data):
                if wants_data:
                    return await method(req, data)
                return await method(req)

            joins.append(add_rpc_handler(ep, req_type, handler))
        return joins

    cls.serve_on = serve_on  # type: ignore[attr-defined]
    cls.__rpc_handlers__ = handlers  # type: ignore[attr-defined]
    return cls
