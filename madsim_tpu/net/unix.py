"""Unix domain sockets — FUNCTIONAL node-local IPC.

The reference declares this API with `todo!()` bodies
(madsim/src/sim/net/unix/{stream,datagram}.rs — C12 in SURVEY.md §2);
here it works, like the functional etcd watch and fs power_fail that
also go beyond the reference's stubs.

Semantics: paths are NODE-LOCAL (a Unix socket never crosses machines).
Binding registers the path in the node's namespace; `connect` is a
same-node rendezvous producing a connected byte-stream pair with the
TcpStream read/write surface. Killing a node wipes its namespace (the
tmpfs socket dir dies with the process) and EOFs the open pipes. All
scheduling nondeterminism comes from the executor — there is no wire,
so no latency/loss faults apply (matching real Unix sockets, which the
chaos fabric cannot partition either).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..future import PENDING, Pollable, Ready, await_
from .network import AddrInUse, ConnectionRefused, ConnectionReset


def _net():
    from ..plugin import simulator
    from . import NetSim

    return simulator(NetSim)


def _node_id() -> int:
    from ..task import current_node_id

    return current_node_id()


def _namespace(net, node_id: int) -> Dict[str, Any]:
    return net.unix_paths.setdefault(node_id, {})


class _QueueWait(Pollable):
    """The one wait shape every unix primitive needs: pop from the
    owner's `queue`, EOF as Ready(None) when `closed`, else park the
    waker (duplicate-registration guarded, like endpoint._PopFuture)."""

    __slots__ = ("owner",)

    def __init__(self, owner):
        self.owner = owner

    def poll(self, waker):
        o = self.owner
        if o.queue:
            return Ready(o.queue.popleft())
        if o.closed:
            return Ready(None)
        if waker not in o.wakers:
            o.wakers.append(waker)
        return PENDING

    def drop(self) -> None:
        pass


class _Waitable:
    """queue + closed + wakers, the _QueueWait contract."""

    def __init__(self) -> None:
        self.queue: Deque[Any] = deque()
        self.closed = False
        self.wakers: List[Callable[[], None]] = []

    def _wake(self) -> None:
        wakers, self.wakers = self.wakers, []
        for w in wakers:
            w()

    def _push(self, item) -> None:
        self.queue.append(item)
        self._wake()

    def close(self) -> None:
        self.closed = True
        self._wake()


class _Pipe(_Waitable):
    """One direction of a stream pair: byte chunks + EOF."""

    def push(self, data: bytes) -> None:
        if self.closed:
            raise ConnectionReset("unix stream closed")
        if data:
            self._push(bytes(data))


class UnixStream:
    """Connected byte stream (TcpStream surface: buffered write/flush,
    read/read_exact, EOF as b"")."""

    def __init__(self, rpipe: _Pipe, wpipe: _Pipe, local_addr: str, peer_addr: str):
        self._rpipe = rpipe
        self._wpipe = wpipe
        self.local_addr = local_addr
        self.peer_addr = peer_addr
        self._wbuf = bytearray()
        self._rbuf = bytearray()
        self._eof = False

    @staticmethod
    async def connect(path: str) -> "UnixStream":
        """Same-node rendezvous with a listener bound at `path`."""
        net = _net()
        node = _node_id()
        listener = _namespace(net, node).get(str(path))
        if not isinstance(listener, UnixListener) or listener.closed:
            raise ConnectionRefused(f"connect {path}: no such unix socket")
        a2b, b2a = _Pipe(), _Pipe()
        # track open pipes for EOF-on-kill; prune finished ones so a
        # long-lived node's connect churn doesn't accumulate
        pipes = net.unix_pipes.setdefault(node, [])
        pipes[:] = [p for p in pipes if not p.closed]
        pipes.extend([a2b, b2a])
        client = UnixStream(b2a, a2b, "", str(path))
        server = UnixStream(a2b, b2a, str(path), "")
        listener._push(server)
        return client

    def write(self, data: bytes) -> int:
        """Buffered until flush (TcpStream parity)."""
        self._wbuf.extend(data)
        return len(data)

    async def flush(self) -> None:
        if self._wbuf:
            payload, self._wbuf = bytes(self._wbuf), bytearray()
            self._wpipe.push(payload)

    async def write_all(self, data: bytes) -> None:
        self.write(data)
        await self.flush()

    async def read(self, n: int = 65536) -> bytes:
        """Up to n bytes; b"" at EOF."""
        while not self._rbuf and not self._eof:
            chunk = await await_(_QueueWait(self._rpipe))
            if chunk is None:
                self._eof = True
                break
            self._rbuf.extend(chunk)
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    async def read_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = await self.read(n - len(out))
            if not chunk:
                raise ConnectionReset("unix stream closed mid-read")
            out.extend(chunk)
        return bytes(out)

    def shutdown(self) -> None:
        self._wpipe.close()


class UnixListener(_Waitable):
    def __init__(self, path: str, net, node_id: int):
        super().__init__()
        self.path = path
        # the BINDING node's namespace — close() must unbind there even
        # when called from another task/node context
        self._net = net
        self._node_id = node_id

    @staticmethod
    async def bind(path: str) -> "UnixListener":
        net = _net()
        node = _node_id()
        ns = _namespace(net, node)
        path = str(path)
        if path in ns:
            raise AddrInUse(f"unix path already bound: {path}")
        listener = UnixListener(path, net, node)
        ns[path] = listener
        return listener

    async def accept(self) -> Tuple[UnixStream, str]:
        stream = await await_(_QueueWait(self))
        if stream is None:
            raise ConnectionReset("unix listener closed")
        return stream, stream.peer_addr

    def close(self) -> None:
        ns = _namespace(self._net, self._node_id)
        if ns.get(self.path) is self:
            del ns[self.path]
        # backlogged, never-accepted connections get reset (real Unix
        # resets the backlog on listener close) — without this the
        # connected client would block forever
        for stream in self.queue:
            stream._rpipe.close()
            stream._wpipe.close()
        self.queue.clear()
        super().close()


class UnixDatagram(_Waitable):
    def __init__(self, path: Optional[str], net=None, node_id: Optional[int] = None):
        super().__init__()
        self.path = path
        self._peer: Optional[str] = None
        self._net = net
        self._node_id = node_id

    @staticmethod
    async def bind(path: str) -> "UnixDatagram":
        net = _net()
        node = _node_id()
        ns = _namespace(net, node)
        path = str(path)
        if path in ns:
            raise AddrInUse(f"unix path already bound: {path}")
        sock = UnixDatagram(path, net, node)
        ns[path] = sock
        return sock

    @staticmethod
    async def unbound() -> "UnixDatagram":
        """Send-only socket (real API: UnixDatagram::unbound)."""
        return UnixDatagram(None)

    def connect(self, path: str) -> None:
        self._peer = str(path)

    async def send(self, data: bytes) -> int:
        if self._peer is None:
            raise ConnectionRefused("unix datagram not connected")
        return await self.send_to(self._peer, data)

    async def send_to(self, path: str, data: bytes) -> int:
        ns = _namespace(_net(), _node_id())
        dst = ns.get(str(path))
        if not isinstance(dst, UnixDatagram) or dst.closed:
            raise ConnectionRefused(f"send_to {path}: no such unix socket")
        dst._push((bytes(data), self.path or ""))
        return len(data)

    async def recv_from(self) -> Tuple[bytes, str]:
        item = await await_(_QueueWait(self))
        if item is None:
            raise ConnectionReset("unix datagram closed")
        return item

    async def recv(self) -> bytes:
        data, _from = await self.recv_from()
        return data

    def close(self) -> None:
        if self.path is not None and self._net is not None:
            ns = _namespace(self._net, self._node_id)
            if ns.get(self.path) is self:
                del ns[self.path]
        super().close()
