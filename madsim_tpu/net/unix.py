"""Unix domain sockets — intentionally unimplemented API stubs.

Parity with the reference, whose Unix socket bodies are `todo!()`
(reference: madsim/src/sim/net/unix/{stream,datagram}.rs — C12 in
SURVEY.md §2: "API exists, bodies todo!() — document as intentionally
unimplemented"). The types exist so code paths that merely name them
import cleanly; using them raises NotImplementedError.
"""

from __future__ import annotations

from typing import Any


class UnixStream:
    @staticmethod
    async def connect(path: str) -> "UnixStream":
        raise NotImplementedError("UnixStream is a stub, as in the reference (todo!())")


class UnixListener:
    @staticmethod
    async def bind(path: str) -> "UnixListener":
        raise NotImplementedError("UnixListener is a stub, as in the reference (todo!())")

    async def accept(self) -> Any:
        raise NotImplementedError("UnixListener is a stub, as in the reference (todo!())")


class UnixDatagram:
    @staticmethod
    async def bind(path: str) -> "UnixDatagram":
        raise NotImplementedError("UnixDatagram is a stub, as in the reference (todo!())")
