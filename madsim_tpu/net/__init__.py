"""Simulated network (reference: madsim/src/sim/net/).

`NetSim` owns the Network fabric + DNS + IPVS. The datagram send path is
rand_delay (0-5 us, buggified to 1-5 s at 10%) -> RPC hook filter ->
IPVS rewrite -> link test (clog/loss/latency) -> timer-scheduled
delivery at arrival time (reference: sim/net/mod.rs:287-334).
Connection streams (`connect1`) are reliable and ordered but re-test the
link per message and back off while partitioned (mod.rs:337-414).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..config import Config
from ..plugin import Simulator
from ..time import SEC, US
from .dns import DnsServer, lookup_host
from .endpoint import (
    Endpoint,
    IncomingConn,
    Mailbox,
    Message,
    PayloadChannel,
    PayloadReceiver,
    PayloadSender,
)
from .. import _context
from .. import time as sim_time
from .ipvs import IpVirtualServer, Scheduler, ServiceAddr
from .network import (
    Addr,
    AddrInUse,
    ConnectionRefused,
    ConnectionReset,
    Direction,
    NetError,
    Network,
    format_addr,
    parse_addr,
)

__all__ = [
    "NetSim",
    "Endpoint",
    "TcpListener",
    "TcpStream",
    "UdpSocket",
    "UnixDatagram",
    "UnixListener",
    "UnixStream",
    "Request",
    "rpc",
    "service",
    "hash_str",
    "PayloadSender",
    "PayloadReceiver",
    "Network",
    "Direction",
    "NetError",
    "AddrInUse",
    "ConnectionRefused",
    "ConnectionReset",
    "DnsServer",
    "lookup_host",
    "IpVirtualServer",
    "ServiceAddr",
    "Scheduler",
    "parse_addr",
    "format_addr",
]

# RPC drop hook: fn(src_addr, dst_addr, tag, payload) -> bool (True = keep)
Hook = Callable[[Addr, Addr, int, Any], bool]


# Imported at module bottom to finish wiring (rpc attaches Endpoint.call etc.).
from .tcp import TcpListener, TcpStream  # noqa: E402
from .udp import UdpSocket  # noqa: E402
from .unix import UnixDatagram, UnixListener, UnixStream  # noqa: E402
from .rpc import Request, hash_str, rpc, service  # noqa: E402


class NetSim(Simulator):
    """Reference: sim/net/mod.rs:84 `NetSim`."""

    def __init__(self, rng, time, config: Config):
        super().__init__(rng, time, config)
        self.network = Network(rng, time, config.net)
        self.dns = DnsServer()
        self.ipvs = IpVirtualServer()
        self._endpoints: Dict[int, List[Endpoint]] = {}
        self._channels: Dict[int, List[PayloadChannel]] = {}
        self._hooks_req: List[Hook] = []
        self._hooks_rsp: List[Hook] = []
        # Unix-domain namespace: per-node path -> listener/datagram
        # (node-local IPC; kill wipes the namespace like a tmpfs socket
        # dir) + open stream pipes for EOF-on-kill
        self.unix_paths: Dict[int, Dict[str, Any]] = {}
        self.unix_pipes: Dict[int, List[Any]] = {}
        # Per-node incarnation, bumped on every kill/restart reset. Timer-
        # scheduled datagram deliveries capture the sender's incarnation at
        # send time and drop at the wire moment if the node died in between
        # — matching the reference, where kill cancels the sender task
        # mid-rand_delay (sim/net/mod.rs:287-296).
        self._incarnation: Dict[int, int] = {}
        self._send_seq = 0
        # Native datagram hot path (hostcore.NetCore): the send -> wire
        # -> delivery moments run in C when the native RNG + clock cores
        # are live. State stays in THIS object (the core holds refs);
        # hooks/ipvs/DNS fall back to the Python path automatically.
        self._netcore = None
        from .. import _native

        rng_core = getattr(rng, "_core", None)
        time_core = getattr(time, "_core", None)
        if _native.available() and rng_core is not None and time_core is not None:
            from .. import _context
            from .endpoint import Message as _Msg

            self._netcore = _native.get_mod().NetCore(
                self, self.network, rng, rng_core, time_core, _Msg,
                _context.current,
            )

    # -- Simulator lifecycle ------------------------------------------------

    def create_node(self, node_id: int) -> None:
        self.network.create_node(node_id)

    def set_node_ip(self, node_id: int, ip: str) -> None:
        self.network.set_node_ip(node_id, ip)

    def reset_node(self, node_id: int) -> None:
        """Node kill/restart: close sockets + break connections
        (reference: mod.rs reset_node -> network.rs:142-148)."""
        self.network.reset_node(node_id)
        self._incarnation[node_id] = self._incarnation.get(node_id, 0) + 1
        for ep in self._endpoints.pop(node_id, []):
            ep._on_reset()
        for chan in self._channels.pop(node_id, []):
            chan.do_reset()
        # close (not just discard) the namespace entries: a waiter
        # parked in accept()/recv_from() from another context must see
        # reset, matching the EOF the stream pipes get below
        for sock in self.unix_paths.pop(node_id, {}).values():
            sock.close()
        for pipe in self.unix_pipes.pop(node_id, []):
            pipe.close()

    def register_endpoint(self, node_id: int, ep: Endpoint) -> None:
        self._endpoints.setdefault(node_id, []).append(ep)

    def unregister_endpoint(self, node_id: int, ep: Endpoint) -> None:
        eps = self._endpoints.get(node_id)
        if eps is not None:
            try:
                eps.remove(ep)
            except ValueError:
                pass

    # -- chaos API (reference: mod.rs:160-236) -------------------------------

    def clog_node(self, node_id: int, direction: str = Direction.Both) -> None:
        self.network.clog_node(node_id, direction)

    def unclog_node(self, node_id: int, direction: str = Direction.Both) -> None:
        self.network.unclog_node(node_id, direction)

    def clog_link(self, src: int, dst: int) -> None:
        """Directional partition src -> dst (reference: mod.rs:221)."""
        self.network.clog_link(src, dst)

    def unclog_link(self, src: int, dst: int) -> None:
        self.network.unclog_link(src, dst)

    def partition(self, group_a: List[int], group_b: List[int]) -> None:
        """Symmetric partition between two node groups (convenience)."""
        for a in group_a:
            for b in group_b:
                self.network.clog_link(a, b)
                self.network.clog_link(b, a)

    def heal(self, group_a: List[int], group_b: List[int]) -> None:
        for a in group_a:
            for b in group_b:
                self.network.unclog_link(a, b)
                self.network.unclog_link(b, a)

    def add_dns_record(self, name: str, ip: str) -> None:
        """Reference: mod.rs:226."""
        self.dns.add_record(name, ip)

    def global_ipvs(self) -> IpVirtualServer:
        """Reference: mod.rs:236."""
        return self.ipvs

    def hook_rpc_req(self, hook: Hook) -> None:
        """Drop-filter outbound messages (reference: mod.rs:245)."""
        self._hooks_req.append(hook)

    def hook_rpc_rsp(self, hook: Hook) -> None:
        """Reference: mod.rs:268. Applied to the same send path; the RPC
        layer routes responses through it by tag convention."""
        self._hooks_rsp.append(hook)

    def stat(self):
        return self.network.stat

    # -- send path ----------------------------------------------------------

    async def rand_delay(self) -> None:
        """Random processing delay before each send: 0-5 us, buggified to
        1-5 s with 10% probability (reference: mod.rs:287-296)."""
        if self.rng.buggify_with_prob(0.1):
            delay = self.rng.gen_range(1 * SEC, 5 * SEC)
        else:
            delay = self.rng.gen_range(0, 5 * US)
        await sim_time.sleep_ns(delay)

    def resolve_name(self, addr: Addr) -> Addr:
        """DNS-resolve a hostname destination (reference: addr.rs:225-247
        ToSocketAddrs resolution on every send/connect)."""
        host, port = addr
        if host == "localhost":
            return ("127.0.0.1", port)
        if host and not host[0].isdigit():
            ip = self.dns.lookup(host)
            if ip is None:
                raise NetError(f"failed to lookup address information: {host}")
            return (ip, port)
        return addr

    async def send_raw(
        self,
        src_node: int,
        src_addr: Addr,
        dst: Addr,
        tag: int,
        payload: Any,
        kind: Optional[str] = None,
    ) -> None:
        """Datagram send (reference: NetSim::send mod.rs:298-334).

        `kind` marks RPC traffic so request/response drop hooks apply to
        the right direction only (reference applies hooks by payload type,
        mod.rs:308-312).

        The 0-5 us processing delay normally runs as a TIMER callback,
        not a coroutine suspension: the wire outcome (hooks, clog/loss
        test, latency draw) still happens at t+delay like the reference,
        but the sender resumes immediately — two task polls cheaper per
        datagram on the executor's hot loop. Every 16th datagram keeps
        the reference's blocking await so a tight send loop still drives
        virtual time forward (without it, a loop that never awaits
        recv/sleep would starve the clock). The buggified 1-5 s delay
        always blocks: there the backpressure IS the injected chaos
        (reference: mod.rs:287-296)."""
        pend = self.send_fast(src_node, src_addr, dst, tag, payload, kind)
        if pend is not None:
            await pend

    def send_fast(
        self, src_node, src_addr, dst, tag, payload, kind=None
    ) -> Optional[Any]:
        """The non-async datagram send: returns None when the send was
        fully scheduled synchronously (the common case — zero coroutine
        frames on the hot path), or a coroutine the caller must await
        (the buggified 1-5 s / every-16th blocking-send cases, and the
        whole Python path when the native core is absent).

        DNS errors surface to the caller (reference: lookup failure is
        the send's error); hooks still observe the ORIGINAL destination
        the sender used, and clog/loss/latency stay at the wire moment."""
        resolved = self.resolve_name(dst)
        nc = self._netcore
        if nc is not None:
            out = nc.send(src_node, src_addr, dst, resolved, tag, payload, kind)
            if out is None:
                return None
            return self._send_blocking_tail(
                out[1], src_node, src_addr, dst, resolved, tag, payload, kind
            )
        return self._send_slow(src_node, src_addr, dst, resolved, tag, payload, kind)

    async def _send_blocking_tail(
        self, delay_ns, src_node, src_addr, dst, resolved, tag, payload, kind
    ) -> None:
        # the two blocking-send cases: the buggified 1-5 s chaos delay
        # and the every-16th suspension that keeps send-only loops
        # driving virtual time (kill cancels the sender here, like the
        # reference's rand_delay)
        await sim_time.sleep_ns(delay_ns)
        self._send_phase2(src_node, src_addr, dst, resolved, tag, payload, kind)

    async def _send_slow(
        self, src_node, src_addr, dst, resolved, tag, payload, kind
    ) -> None:
        """Pure-Python send path (no native core): same draws, same
        timer-scheduled wire moment."""
        if self.rng.buggify_with_prob(0.1):
            await sim_time.sleep_ns(self.rng.gen_range(1 * SEC, 5 * SEC))
            self._send_phase2(src_node, src_addr, dst, resolved, tag, payload, kind)
            return
        delay = self.rng.gen_range(0, 5 * US)
        self._send_seq += 1
        if self._send_seq % 16 == 0:
            await sim_time.sleep_ns(delay)
            self._send_phase2(src_node, src_addr, dst, resolved, tag, payload, kind)
            return
        incarnation = self._incarnation.get(src_node, 0)
        self.time.add_timer_ns(
            self.time.now_ns() + delay,
            lambda: self._send_phase2_guarded(
                src_node, src_addr, dst, resolved, tag, payload, kind,
                sender=(src_node, incarnation),
            ),
        )

    def _send_phase2_guarded(self, *args, sender=None) -> None:
        """Timer-context wrapper: a raising drop-hook must surface as a
        simulation panic (the standard loud-failure path), not unwind
        the executor's timer machinery.

        `sender=(node_id, incarnation)` drops the datagram if the sending
        node was killed or restarted after the send was issued — the
        reference gets this for free because kill cancels the sender task
        inside rand_delay; here the wire moment is a detached timer, so
        the liveness check is explicit."""
        if sender is not None:
            node_id, incarnation = sender
            if self._incarnation.get(node_id, 0) != incarnation:
                return  # sender died between send and wire moment
        try:
            self._send_phase2(*args)
        except BaseException as exc:  # noqa: BLE001 - routed, not swallowed
            _context.current().executor.panic = exc

    def _send_phase2(self, src_node, src_addr, dst, resolved, tag, payload, kind) -> None:
        """On-the-wire moment: drop hooks (seeing the sender's `dst`),
        IPVS rewrite, clog/loss/latency."""
        if kind == "rpc_req":
            hooks = self._hooks_req
        elif kind == "rpc_rsp":
            hooks = self._hooks_rsp
        else:
            hooks = []
        for hook in hooks:
            if not hook(src_addr, dst, tag, payload):
                return  # dropped by hook
        rewritten = self.ipvs.rewrite("udp", resolved)
        if rewritten is not None:
            resolved = rewritten
        msg = Message(tag, payload, (self._src_ip(src_node, resolved), src_addr[1]))
        self.network.try_send(
            src_node, src_addr, resolved, lambda sock: sock.deliver(msg), payload
        )

    def _src_ip(self, src_node: int, dst: Addr) -> str:
        """The source address a peer observes: loopback for local sends,
        the node IP otherwise."""
        if dst[0].startswith("127.") or dst[0] == "localhost":
            return "127.0.0.1"
        return self.network.node_ip.get(src_node, "0.0.0.0")

    # -- connection path (reference: mod.rs:337-414) ------------------------

    async def connect1(self, ep: Endpoint, dst: Addr) -> Tuple[PayloadSender, PayloadReceiver]:
        await self.rand_delay()
        dst = self.resolve_name(dst)
        rewritten = self.ipvs.rewrite("tcp", dst)
        if rewritten is not None:
            dst = rewritten
        resolved = self.network.resolve_dst(ep.node_id, dst)
        if resolved is None:
            raise ConnectionRefused(f"connection refused: {format_addr(dst)}")
        dst_node, sock = resolved
        if self.network.is_clogged(ep.node_id, dst_node):
            # A partition shows up as connect timeout -> refused.
            raise ConnectionRefused(f"connection refused (partitioned): {format_addr(dst)}")
        if not hasattr(sock, "new_connection"):
            raise ConnectionRefused(f"no listener at {format_addr(dst)}")

        fwd = PayloadChannel(self, ep.node_id, dst_node)  # client -> server
        bwd = PayloadChannel(self, dst_node, ep.node_id)  # server -> client
        # Each channel registers under BOTH ends: killing either node must
        # break the whole connection (reference: reset closes the stream).
        for node in (ep.node_id, dst_node):
            chans = self._channels.setdefault(node, [])
            # Amortized prune of dead channels keeps reset_node O(live).
            if len(chans) > 64 and len(chans) % 64 == 0:
                chans[:] = [c for c in chans if not (c.closed or c.reset)]
            chans.append(fwd)
            chans.append(bwd)

        client_addr = (self._src_ip(ep.node_id, dst), ep.local_addr[1])
        conn = IncomingConn(
            PayloadSender(bwd, client_addr), PayloadReceiver(fwd, client_addr), client_addr
        )
        _, latency = self.network.test_link(ep.node_id, dst_node, reliable=True)
        self.time.add_timer_ns(
            self.time.now_ns() + latency, lambda: sock.new_connection(conn)
        )
        return PayloadSender(fwd, dst), PayloadReceiver(bwd, dst)
