"""Simulated network (reference: madsim/src/sim/net/).

Phase B of the build plan (SURVEY.md §7) fills this package with the
Network fabric, NetSim simulator, Endpoint, TCP/UDP, DNS/IPVS and the
typed RPC layer.
"""

__all__ = []
