"""Endpoint — the tag-matched message socket
(reference: madsim/src/sim/net/endpoint.rs).

A UDP-like bound socket whose mailbox matches messages by u64 tag:
waiting receivers register per-tag cells, unmatched messages buffer
(reference :298-352). `send_to_raw` moves ANY Python object between sim
nodes zero-copy (the reference moves `Box<dyn Any>`); `send_to` restricts
to bytes for datagram realism. `connect1`/`accept1` create a pair of
reliable ordered payload channels for connection-oriented protocols
(reference :178-215).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, Dict, List, NamedTuple, Optional, Tuple

from .. import _context
from .. import time as sim_time
from ..errors import SimError
from ..future import PENDING, OneShotCell, Pollable, Ready, await_
from .network import (
    Addr,
    ConnectionRefused,
    ConnectionReset,
    NetError,
    format_addr,
    parse_addr,
)


class Message(NamedTuple):
    # a NamedTuple, not a __slots__ class: messages are minted on the
    # datagram hot path (incl. by the native NetCore) and tuple.__new__
    # skips the Python __init__ frame entirely
    tag: int
    payload: Any
    from_addr: Addr


class Mailbox:
    """Tag-matched mailbox (reference: endpoint.rs:298-352)."""

    def __init__(self) -> None:
        self.registered: List[Tuple[int, OneShotCell]] = []
        self.msgs: List[Message] = []

    def deliver(self, msg: Message) -> None:
        for i, (tag, cell) in enumerate(self.registered):
            if tag == msg.tag and not cell.is_set():
                del self.registered[i]
                cell.set(msg)
                return
        self.msgs.append(msg)

    def recv_cell(self, tag: int) -> OneShotCell:
        cell = OneShotCell()
        for i, msg in enumerate(self.msgs):
            if msg.tag == tag:
                del self.msgs[i]
                cell.set(msg)
                return cell
        self.registered.append((tag, cell))
        return cell

    def deregister(self, cell: OneShotCell) -> None:
        self.registered = [(t, c) for (t, c) in self.registered if c is not cell]

    def recv(self, tag: int) -> "_MailboxRecv":
        """Pollable for the next `tag` message (same surface as the
        native hostcore.Mailbox.recv)."""
        return _MailboxRecv(self, tag)


def _new_mailbox():
    """Native tag-matched mailbox when the toolchain built hostcore
    (one C object replaces the recv_cell/OneShotCell/_MailboxRecv stack
    on the RPC hot path); Python twin otherwise — same deliver/recv
    semantics, asserted by tests/test_native.py."""
    from .. import _native

    mod = _native.get_mod()
    return mod.Mailbox() if mod is not None else Mailbox()


class _MailboxRecv(Pollable):
    """Awaits a tag-matched message; deregisters on cancellation so an
    aborted receiver (e.g. a timed-out RPC call) cannot swallow a later
    message for the same tag."""

    __slots__ = ("mailbox", "cell", "returned")

    def __init__(self, mailbox: Mailbox, tag: int):
        self.mailbox = mailbox
        self.cell = mailbox.recv_cell(tag)
        self.returned = False

    def poll(self, waker: Callable[[], None]):
        r = self.cell.poll(waker)
        if r is not PENDING:
            self.returned = True
        return r

    def drop(self) -> None:
        if not self.returned:
            self.mailbox.deregister(self.cell)


class PayloadChannel:
    """One direction of a connect1 stream — reliable & ordered, but the
    receiver re-tests the link per message and backs off while partitioned
    (reference: sim/net/mod.rs:337-414)."""

    def __init__(self, net: "NetSimRef", src_node: int, dst_node: int):
        self.net = net
        self.src_node = src_node
        self.dst_node = dst_node
        self.buf: Deque[Any] = deque()
        self.closed = False  # sender closed (EOF)
        self.reset = False  # connection broken (node killed)
        self.wakers: List[Callable[[], None]] = []

    def _wake(self) -> None:
        wakers, self.wakers = self.wakers, []
        for w in wakers:
            w()

    def send(self, payload: Any) -> None:
        if self.reset:
            raise ConnectionReset("connection reset by peer")
        if self.closed:
            raise ConnectionReset("send on closed channel")
        self.buf.append(payload)
        self._wake()

    def close(self) -> None:
        self.closed = True
        self._wake()

    def do_reset(self) -> None:
        self.reset = True
        self.buf.clear()
        self._wake()


class _PopFuture(Pollable):
    __slots__ = ("chan",)

    def __init__(self, chan: PayloadChannel):
        self.chan = chan

    def poll(self, waker: Callable[[], None]):
        ch = self.chan
        if ch.reset:
            raise ConnectionReset("connection reset by peer")
        if ch.buf:
            return Ready(ch.buf.popleft())
        if ch.closed:
            return Ready(None)  # EOF
        if waker not in ch.wakers:
            ch.wakers.append(waker)
        return PENDING


class PayloadSender:
    """Reference: sim/net/mod.rs `PayloadSender`."""

    def __init__(self, chan: PayloadChannel, peer_addr: Addr):
        self._chan = chan
        self.peer_addr = peer_addr

    def send(self, payload: Any) -> None:
        self._chan.send(payload)

    def close(self) -> None:
        self._chan.close()

    def is_closed(self) -> bool:
        return self._chan.closed or self._chan.reset


class PayloadReceiver:
    """Reference: sim/net/mod.rs `PayloadReceiver`."""

    def __init__(self, chan: PayloadChannel, peer_addr: Addr):
        self._chan = chan
        self.peer_addr = peer_addr

    async def recv(self) -> Optional[Any]:
        """Next payload, or None on EOF. Backs off while the link is
        partitioned; applies per-message latency (reference :337-414)."""
        payload = await await_(_PopFuture(self._chan))
        if payload is None:
            return None
        net = self._chan.net
        # Back off while clogged: the message is "in flight" until the
        # partition heals (reference: backoff loop at mod.rs:390-400).
        while net.network.is_clogged(self._chan.src_node, self._chan.dst_node):
            await sim_time.sleep_ns(net.rng.gen_range(10_000_000, 100_000_000))
        _, latency = net.network.test_link(
            self._chan.src_node, self._chan.dst_node, reliable=True
        )
        await sim_time.sleep_ns(latency)
        return payload


class NetSimRef:
    """Typed alias for NetSim to avoid a circular import at runtime."""


class EndpointSocket:
    """The object registered in the Network socket table."""

    def __init__(self, endpoint: "Endpoint"):
        self.endpoint = endpoint

    def deliver(self, msg: Message) -> None:
        """Reference: endpoint.rs:310-322 `EndpointSocket::deliver`."""
        self.endpoint._mailbox.deliver(msg)

    def new_connection(self, conn: "IncomingConn") -> None:
        ep = self.endpoint
        ep._accept_queue.append(conn)
        if ep._accept_wakers:
            wakers, ep._accept_wakers = ep._accept_wakers, []
            for w in wakers:
                w()

    def on_reset(self) -> None:
        self.endpoint._on_reset()


class IncomingConn:
    __slots__ = ("tx", "rx", "peer_addr")

    def __init__(self, tx: PayloadSender, rx: PayloadReceiver, peer_addr: Addr):
        self.tx = tx
        self.rx = rx
        self.peer_addr = peer_addr


class _AcceptFuture(Pollable):
    __slots__ = ("ep",)

    def __init__(self, ep: "Endpoint"):
        self.ep = ep

    def poll(self, waker: Callable[[], None]):
        if self.ep._closed:
            raise ConnectionReset("endpoint closed")
        if self.ep._accept_queue:
            return Ready(self.ep._accept_queue.popleft())
        if waker not in self.ep._accept_wakers:
            self.ep._accept_wakers.append(waker)
        return PENDING


class Endpoint:
    """Reference: endpoint.rs:13 `Endpoint`."""

    def __init__(self, net, node_id: int, local_addr: Addr):
        self._net = net
        self.node_id = node_id
        self.local_addr = local_addr
        self.peer: Optional[Addr] = None
        self._mailbox = _new_mailbox()
        self._accept_queue: Deque[IncomingConn] = deque()
        self._accept_wakers: List[Callable[[], None]] = []
        self._closed = False
        self._socket = EndpointSocket(self)

    # -- construction -------------------------------------------------------

    @staticmethod
    async def bind(addr: Any) -> "Endpoint":
        """Bind on the current node (reference: endpoint.rs:23)."""
        from . import NetSim
        from ..plugin import simulator
        from ..task import current_node_id

        net = simulator(NetSim)
        node_id = current_node_id()
        parsed = parse_addr(addr)
        ep = Endpoint(net, node_id, parsed)
        bound = net.network.bind(node_id, parsed, ep._socket)
        ep.local_addr = bound
        net.register_endpoint(node_id, ep)
        return ep

    @staticmethod
    async def connect(addr: Any) -> "Endpoint":
        """Bind an ephemeral port and set default peer
        (reference: endpoint.rs:38)."""
        ep = await Endpoint.bind(("0.0.0.0", 0))
        ep.peer = parse_addr(addr)
        return ep

    async def send(self, tag: int, data: bytes) -> None:
        """Send to the default peer set by `connect`."""
        if self.peer is None:
            raise NetError("endpoint has no default peer; use connect()")
        await self.send_to(self.peer, tag, data)

    async def recv(self, tag: int) -> Any:
        """Receive from any sender on `tag` (peer-filtered recv is not in
        the reference either; the tag IS the conversation)."""
        payload, _ = await self.recv_from(tag)
        return payload

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._net.network.unbind(self.node_id, self.local_addr[1])
            self._net.unregister_endpoint(self.node_id, self)

    def _on_reset(self) -> None:
        self._closed = True

    # -- datagram API -------------------------------------------------------

    async def send_to(self, dst: Any, tag: int, data: bytes) -> None:
        """Reference: endpoint.rs:66 `send_to`."""
        await self.send_to_raw(dst, tag, bytes(data))

    async def recv_from(self, tag: int) -> Tuple[Any, Addr]:
        """Reference: endpoint.rs:85 `recv_from`."""
        payload, addr = await self.recv_from_raw(tag)
        return payload, addr

    async def send_to_raw(self, dst: Any, tag: int, payload: Any, kind: Optional[str] = None) -> None:
        """Move any object to the destination mailbox
        (reference: endpoint.rs:118-133 + NetSim::send mod.rs:298-334).
        `kind` ("rpc_req"/"rpc_rsp") routes RPC drop hooks."""
        pend = self.send_fast(dst, tag, payload, kind)
        if pend is not None:
            await pend

    def send_fast(self, dst: Any, tag: int, payload: Any, kind: Optional[str] = None):
        """Non-async send: None when fully scheduled, else a coroutine to
        await (see NetSim.send_fast) — the RPC hot path uses this to skip
        two coroutine frames per datagram."""
        return self._net.send_fast(
            self.node_id, self.local_addr, parse_addr(dst), tag, payload, kind
        )

    async def recv_from_raw(self, tag: int) -> Tuple[Any, Addr]:
        """Reference: endpoint.rs:135-147."""
        if self._closed:
            raise ConnectionReset("endpoint closed")
        msg: Message = await await_(self._mailbox.recv(tag))
        return msg.payload, msg.from_addr

    # -- connection API -----------------------------------------------------

    async def connect1(self, dst: Any) -> Tuple[PayloadSender, PayloadReceiver]:
        """Open a reliable bidirectional stream to a listening endpoint
        (reference: endpoint.rs:178 + mod.rs:337-388)."""
        return await self._net.connect1(self, parse_addr(dst))

    async def accept1(self) -> Tuple[PayloadSender, PayloadReceiver, Addr]:
        """Accept one incoming stream (reference: endpoint.rs:197)."""
        conn: IncomingConn = await await_(_AcceptFuture(self))
        return conn.tx, conn.rx, conn.peer_addr
