"""In-simulation DNS (reference: madsim/src/sim/net/dns.rs + addr.rs).

A per-simulation record table with `localhost` preloaded; `lookup_host`
is the DNS-aware resolver used by connect paths (reference:
addr.rs:225-247 vendored tokio `ToSocketAddrs`).
"""

from __future__ import annotations

from typing import Dict, List, Optional


class DnsServer:
    """Reference: dns.rs:6-27 `DnsServer`."""

    def __init__(self) -> None:
        self._records: Dict[str, str] = {"localhost": "127.0.0.1"}

    def add_record(self, name: str, ip: str) -> None:
        self._records[name] = ip

    def remove_record(self, name: str) -> None:
        self._records.pop(name, None)

    def lookup(self, name: str) -> Optional[str]:
        return self._records.get(name)


def _is_ip(host: str) -> bool:
    parts = host.split(".")
    return len(parts) == 4 and all(p.isdigit() for p in parts)


async def lookup_host(host: str) -> List[str]:
    """Resolve a hostname inside the simulation (reference: addr.rs:33-36).

    Accepts "name" or "name:port"; returns IPs (or "ip:port" strings when
    a port was given).
    """
    from . import NetSim
    from ..plugin import simulator

    name, sep, port = host.rpartition(":")
    if not sep:
        name, port = host, ""
    if _is_ip(name or host):
        return [host]
    net = simulator(NetSim)
    ip = net.dns.lookup(name or host)
    if ip is None:
        raise OSError(f"failed to lookup address information: {host}")
    return [f"{ip}:{port}" if port else ip]
