"""Network core — the message fabric state (reference: madsim/src/sim/net/network.rs).

Per-node IP + socket table, directional link state (clog node in/out,
clog link src->dst), per-message link test = clog check + Bernoulli
packet loss + uniform latency sample (reference :261-270), destination
resolution incl. 0.0.0.0 wildcard and loopback (:296-325), ephemeral
port allocation (:196-244), message stats (:101).

All latency arithmetic is integer nanoseconds drawn from the global RNG,
so the fabric is replayable on the TPU engine lane-for-lane.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..config import NetConfig
from ..errors import SimError

Addr = Tuple[str, int]  # (ip, port)


class NetError(SimError):
    pass


class AddrInUse(NetError):
    pass


class ConnectionRefused(NetError):
    pass


class ConnectionReset(NetError):
    pass


_ADDR_MEMO: dict = {}


def parse_addr(addr: Any) -> Addr:
    """Accept "ip:port", (ip, port), or bare port int."""
    if isinstance(addr, tuple):
        return (str(addr[0]), int(addr[1]))
    if isinstance(addr, int):
        return ("0.0.0.0", addr)
    if isinstance(addr, str):
        # per-string memo: address strings are a small finite set per
        # sim, and this sits on the datagram hot path
        got = _ADDR_MEMO.get(addr)
        if got is None:
            host, _, port = addr.rpartition(":")
            got = (host or "0.0.0.0", int(port))
            if len(_ADDR_MEMO) > 4096:
                _ADDR_MEMO.clear()
            _ADDR_MEMO[addr] = got
        return got
    raise ValueError(f"cannot parse address: {addr!r}")


def format_addr(addr: Addr) -> str:
    return f"{addr[0]}:{addr[1]}"


# Link-test outcomes
PASS = "pass"
CLOGGED = "clogged"
DROPPED = "dropped"


class Direction:
    """Reference: network.rs:108 `Direction`."""

    In = "in"
    Out = "out"
    Both = "both"


class Stat:
    """Reference: network.rs:98-102."""

    def __init__(self) -> None:
        self.msg_count = 0


class Network:
    """Fabric state shared by all sockets of one simulation
    (reference: network.rs:20 `Network`)."""

    def __init__(self, rng, time, config: NetConfig):
        self.rng = rng
        self.time = time
        self.config = config
        self.stat = Stat()
        self.node_ip: Dict[int, str] = {}
        self.ip_node: Dict[str, int] = {}
        # sockets[node_id][port] -> socket object (has .deliver(msg))
        self.sockets: Dict[int, Dict[int, Any]] = {}
        self.clogged_in: Set[int] = set()
        self.clogged_out: Set[int] = set()
        self.clogged_links: Set[Tuple[int, int]] = set()

    # -- topology -----------------------------------------------------------

    def create_node(self, node_id: int) -> None:
        self.sockets.setdefault(node_id, {})
        if node_id not in self.node_ip:
            # Auto-assign a unique IP; NodeBuilder.ip() overrides.
            self.set_node_ip(node_id, f"10.0.0.{node_id}")

    def set_node_ip(self, node_id: int, ip: str) -> None:
        old = self.node_ip.get(node_id)
        if old is not None:
            self.ip_node.pop(old, None)
        if ip in self.ip_node and self.ip_node[ip] != node_id:
            raise NetError(f"IP {ip} already assigned to node {self.ip_node[ip]}")
        self.node_ip[node_id] = ip
        self.ip_node[ip] = node_id

    def reset_node(self, node_id: int) -> None:
        """Close all sockets on node kill/restart (reference: network.rs:142-148)."""
        socks = self.sockets.get(node_id, {})
        for sock in list(socks.values()):
            close = getattr(sock, "on_reset", None)
            if close is not None:
                close()
        socks.clear()

    # -- partitions / chaos (reference: clog_* APIs) ------------------------

    def clog_node(self, node_id: int, direction: str = Direction.Both) -> None:
        if direction in (Direction.In, Direction.Both):
            self.clogged_in.add(node_id)
        if direction in (Direction.Out, Direction.Both):
            self.clogged_out.add(node_id)

    def unclog_node(self, node_id: int, direction: str = Direction.Both) -> None:
        if direction in (Direction.In, Direction.Both):
            self.clogged_in.discard(node_id)
        if direction in (Direction.Out, Direction.Both):
            self.clogged_out.discard(node_id)

    def clog_link(self, src: int, dst: int) -> None:
        self.clogged_links.add((src, dst))

    def unclog_link(self, src: int, dst: int) -> None:
        self.clogged_links.discard((src, dst))

    def is_clogged(self, src: int, dst: int) -> bool:
        return (
            src in self.clogged_out
            or dst in self.clogged_in
            or (src, dst) in self.clogged_links
        )

    def test_link(self, src: int, dst: int, reliable: bool = False) -> Tuple[str, int]:
        """Per-message link test (reference: network.rs:261-270).

        Returns (outcome, latency_ns). Reliable (connection) traffic is
        exempt from Bernoulli loss but still subject to clogging.
        """
        if self.is_clogged(src, dst):
            return (CLOGGED, 0)
        if not reliable and self.config.packet_loss_rate > 0.0:
            if self.rng.gen_bool(self.config.packet_loss_rate):
                return (DROPPED, 0)
        latency = self.rng.gen_range(
            self.config.send_latency_min_ns, self.config.send_latency_max_ns + 1
        )
        if self.config.delay_spike_prob > 0.0 and self.rng.gen_bool(
            self.config.delay_spike_prob
        ):
            # delay-spike window (config.py NetConfig): late, not lost
            latency += self.rng.gen_range(
                self.config.delay_spike_min_ns, self.config.delay_spike_max_ns
            )
        return (PASS, latency)

    # -- sockets ------------------------------------------------------------

    def bind(self, node_id: int, addr: Addr, socket: Any) -> Addr:
        """Bind a socket; port 0 allocates an ephemeral port
        (reference: network.rs:196-244)."""
        ip, port = addr
        if ip not in ("0.0.0.0", "127.0.0.1") and ip != self.node_ip.get(node_id):
            raise NetError(f"cannot bind {ip}: node {node_id} has IP {self.node_ip.get(node_id)}")
        socks = self.sockets.setdefault(node_id, {})
        if port == 0:
            # Deterministic ephemeral allocation from the global RNG.
            for _ in range(100):
                cand = self.rng.gen_range(32768, 61000)
                if cand not in socks:
                    port = cand
                    break
            else:  # pragma: no cover
                raise AddrInUse("no free ephemeral port")
        elif port in socks:
            raise AddrInUse(f"address already in use: {format_addr(addr)}")
        socks[port] = socket
        return (ip, port)

    def unbind(self, node_id: int, port: int) -> None:
        self.sockets.get(node_id, {}).pop(port, None)

    def resolve_dst(self, src_node: int, dst: Addr) -> Optional[Tuple[int, Any]]:
        """Find the destination node + socket (reference: network.rs:296-325).

        Handles loopback (127.x -> same node) and 0.0.0.0-bound wildcard
        sockets. Returns None when nothing listens.
        """
        ip, port = dst
        if ip.startswith("127.") or ip == "localhost":
            dst_node = src_node
        else:
            dst_node = self.ip_node.get(ip)
            if dst_node is None:
                return None
        sock = self.sockets.get(dst_node, {}).get(port)
        if sock is None:
            return None
        return (dst_node, sock)

    def try_send(
        self,
        src_node: int,
        src_addr: Addr,
        dst: Addr,
        deliver: Callable[[Any], None],
        payload: Any,
        reliable: bool = False,
    ) -> bool:
        """Datagram send: resolve, test link, schedule delivery at
        now+latency (reference: network.rs:296-325 + mod.rs:327-333).

        Returns False if the message was lost/clogged/no-listener
        (datagram semantics: silent drop).
        """
        resolved = self.resolve_dst(src_node, dst)
        if resolved is None:
            return False
        dst_node, sock = resolved
        outcome, latency = self.test_link(src_node, dst_node, reliable=reliable)
        if outcome != PASS:
            return False
        self.stat.msg_count += 1
        self.time.add_timer_ns(self.time.now_ns() + latency, lambda: deliver(sock))
        return True
