"""Multi-host seed-batch scale-out over DCN (jax.distributed).

The reference scales out with one OS thread per seed on one machine
(madsim/src/sim/runtime/builder.rs:121-160) and TCP/UCX real-mode
transports between machines (madsim/src/std/net/). The tpu-native
equivalent (SURVEY.md §2.9/§5.8): every host joins one jax.distributed
job, the seed-lane axis shards over the *global* device mesh (ICI within
a slice, DCN across slices/hosts), and the engine's fused segment runs
SPMD — each process computes only its lane shard, and only replicated
reductions (completed counts, the fixed-capacity failing-seed ring)
cross hosts.

Since the lane-axis mesh rebuild, this module is a thin veneer: the
engine's `run_stream(mesh=...)` path pins every StreamCarry leaf with
explicit `carry_shardings` (parallel/__init__.py) derived from the
declared CARRY_AXES table, and the 17 registered collectives
(analysis/srules.py COLLECTIVES) are the only cross-device traffic.
`run_stream_global` just builds the all-hosts mesh and delegates; the
single-host and multi-host code paths are the same jitted program.

Smoke-tested without TPU pods by running N processes on one machine with
virtual CPU devices (tests/test_multihost.py: 2 processes x 4 devices,
Gloo collectives) — the same code path a v5e multi-host job takes.

Env-driven setup (mirrors the MADSIM_TEST_* harness style):
  MADSIM_TPU_COORDINATOR  host:port of process 0
  MADSIM_TPU_NUM_PROCS    total process count
  MADSIM_TPU_PROC_ID      this process's id
On managed TPU pods (GKE/queued resources), call `initialize()` with no
arguments — jax auto-detects the cluster.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import SEED_AXIS, make_mesh, seed_sharding

_ENV_COORD = "MADSIM_TPU_COORDINATOR"
_ENV_NPROCS = "MADSIM_TPU_NUM_PROCS"
_ENV_PID = "MADSIM_TPU_PROC_ID"


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join (or start) the distributed job. Idempotent. Arguments fall
    back to MADSIM_TPU_* env vars, then to jax's cluster auto-detection
    (TPU pod metadata)."""
    if getattr(initialize, "_done", False):
        return
    coordinator_address = coordinator_address or os.environ.get(_ENV_COORD)
    if num_processes is None and os.environ.get(_ENV_NPROCS):
        num_processes = int(os.environ[_ENV_NPROCS])
    if process_id is None and os.environ.get(_ENV_PID):
        process_id = int(os.environ[_ENV_PID])
    try:
        # NOTE: must run before anything touches the XLA backend —
        # including jax.devices()/process_count(), so no jax-based
        # "already initialized" probe is possible here
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already" not in str(e).lower():
            raise
    initialize._done = True  # type: ignore[attr-defined]


def global_mesh():
    """1-D "batch" (lane-axis) mesh over every device in the job (all
    hosts)."""
    return make_mesh(jax.devices())


def global_seeds(n_seeds: int, seed_start: int = 0, mesh=None) -> jax.Array:
    """uint32 [seed_start, seed_start+n) sharded over the global mesh.
    Each process materializes only its local shard."""
    mesh = mesh if mesh is not None else global_mesh()
    axis = mesh.shape[SEED_AXIS]
    if n_seeds % axis != 0:
        raise ValueError(f"n_seeds ({n_seeds}) must be a multiple of the global device count ({axis})")

    def local_shard(index):
        return np.arange(seed_start, seed_start + n_seeds, dtype=np.uint32)[index]

    return jax.make_array_from_callback((n_seeds,), seed_sharding(mesh), local_shard)


def run_stream_global(
    engine,
    n_seeds: int,
    batch: int = 1024,
    segment_steps: int = 256,
    seed_start: int = 0,
    max_steps: int = 10_000,
    mesh=None,
    **stream_kwargs,
) -> dict:
    """Seed streaming sharded over the global (all-hosts) mesh: every
    process runs the identical SPMD pipelined executor — device-side
    supersegments, donated carry, K-deep dispatch (run_stream kwargs
    `pipelined` / `segments_per_dispatch` / `dispatch_depth` / `donate`
    pass through) — and the host loops stay in lockstep because every
    decision they make reads replicated counters. Only the counters
    poll and the ring drains cross DCN, each a few hundred bytes, so
    the steady state is collective-free exactly like the single-host
    path. Returns run_stream's dict (identical on every process).
    """
    mesh = mesh if mesh is not None else global_mesh()
    axis = mesh.shape[SEED_AXIS]
    if batch % axis != 0:
        raise ValueError(
            f"batch ({batch}) must be a multiple of the global device count ({axis})"
        )
    return engine.run_stream(
        n_seeds,
        batch=batch,
        segment_steps=segment_steps,
        seed_start=seed_start,
        max_steps=max_steps,
        mesh=mesh,
        **stream_kwargs,
    )


def run_batch_global(
    engine,
    n_seeds: int,
    seed_start: int = 0,
    max_steps: int = 10_000,
    fail_capacity: int = 1024,
    mesh=None,
) -> dict:
    """Run a globally-sharded seed batch SPMD across every host and
    return host-local results: completion/failure counts plus up to
    `fail_capacity` failing (seed, code) pairs, identical on every
    process (replicated reductions — the only cross-host traffic).
    """
    mesh = mesh if mesh is not None else global_mesh()
    seeds = global_seeds(n_seeds, seed_start, mesh)
    res = jax.jit(partial(engine.run_batch, max_steps=max_steps))(seeds)

    replicated = NamedSharding(mesh, P())

    # The audited cross-lane baseline of this (pre-pipelined-executor)
    # module, kept as the simple one-shot alternative to the stream
    # path. Each op carries its S-rule collective annotation; the
    # registry entries (analysis/srules.py COLLECTIVES, multihost-*)
    # record the all-reduce each is under NamedSharding(mesh, P('batch')):
    # the ranks scan + masked ring gather stay the ONLY cross-host
    # data movement (failing lanes only, never a full [L] all-gather),
    # and the completion count is already a psum by virtue of the
    # replicated out_shardings.
    @partial(jax.jit, out_shardings=replicated)
    def stats(r):
        from ..perf import xprof

        mask = r.failed
        with xprof.collective_scope("multihost-fail-ranks"):
            # madsim: collective(multihost-fail-ranks, reduce=scan)
            csum = jnp.cumsum(mask.astype(jnp.int32))
        n_fail = csum[-1] if mask.shape[0] else jnp.int32(0)
        want = jnp.arange(fail_capacity, dtype=jnp.int32) + 1
        src = jnp.clip(
            jnp.searchsorted(csum, want, side="left").astype(jnp.int32),
            0,
            max(mask.shape[0] - 1, 0),
        )
        fill = want <= n_fail
        with xprof.collective_scope("multihost-completed-sum"):
            # madsim: collective(multihost-completed-sum, reduce=sum)
            completed = r.done.sum(dtype=jnp.int32)
        with xprof.collective_scope("multihost-fail-ring"):
            # madsim: collective(multihost-fail-ring, reduce=gather)
            fail_seeds = jnp.where(fill, r.seeds[src], 0)
            # madsim: collective(multihost-fail-ring, reduce=gather)
            fail_codes = jnp.where(fill, r.fail_code[src], 0)
        return {
            "completed": completed,
            "failed": n_fail,
            "fail_seeds": fail_seeds,
            "fail_codes": fail_codes,
        }

    out = jax.device_get(stats(res))
    n_fail = int(out["failed"])
    listed = min(n_fail, fail_capacity)
    return {
        "completed": int(out["completed"]),
        "failed": n_fail,
        "failing": [
            (int(s), int(c))
            for s, c in zip(out["fail_seeds"][:listed], out["fail_codes"][:listed])
        ],
        "truncated": n_fail > fail_capacity,
        "processes": jax.process_count(),
        "global_devices": jax.device_count(),
    }
