"""Seed-batch parallelism over the device mesh.

The scaling axis of a DST framework is *seeds*, not tensors (SURVEY.md
§2.9): lanes are embarrassingly parallel, so sharding the lane dimension
over a 1-D mesh axis "seeds" scales linearly over ICI (intra-slice) and
DCN (multi-slice) with zero collectives inside the loop — only the final
result gather crosses chips. This replaces the reference's
one-thread-per-seed harness (madsim/src/sim/runtime/builder.rs:121-160)
and its TCP/UCX real-mode backends (madsim/src/std/net/) as the
distributed execution story.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SEED_AXIS = "seeds"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, axis "seeds"."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (SEED_AXIS,))

def seed_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(SEED_AXIS))


def shard_seeds(seeds, mesh: Mesh):
    """Place a seed batch sharded over the mesh's "seeds" axis; the
    engine's whole state inherits the lane sharding by propagation.

    Validates the mesh and batch shape up front so every sharding entry
    point gets a clear error instead of a raw XLA one. On a multi-host
    (jax.distributed) mesh, each process materializes only its local
    shard — device_put can't place onto non-addressable devices."""
    if SEED_AXIS not in mesh.shape:
        raise ValueError(
            f'mesh has no "{SEED_AXIS}" axis (axes: {tuple(mesh.shape)}); '
            f"build it with parallel.make_mesh(...)"
        )
    axis = mesh.shape[SEED_AXIS]
    n = len(seeds)
    if n % axis != 0:
        raise ValueError(
            f"seed batch ({n}) must be a multiple of the mesh's "
            f'"{SEED_AXIS}" axis size ({axis})'
        )
    sharding = seed_sharding(mesh)
    if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
        # madsim: allow(T001) — deliberate one-time host
        # materialization at stream START (multi-host placement needs
        # the full batch host-side to slice per-process shards); not in
        # the per-segment steady state the T-rules guard
        host = np.asarray(seeds)
        return jax.make_array_from_callback(host.shape, sharding, lambda idx: host[idx])
    return jax.device_put(seeds, sharding)


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k
