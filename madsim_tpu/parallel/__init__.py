"""Seed-batch parallelism over the device mesh.

The scaling axis of a DST framework is *seeds*, not tensors (SURVEY.md
§2.9): lanes are embarrassingly parallel, so sharding the lane dimension
over a 1-D mesh axis "batch" scales linearly over ICI (intra-slice) and
DCN (multi-slice) with zero collectives inside the per-event loop — only
segment-boundary reductions (the 17 registered collectives in
analysis/srules.py COLLECTIVES) and the final result gather cross chips.
This replaces the reference's one-thread-per-seed harness
(madsim/src/sim/runtime/builder.rs:121-160) and its TCP/UCX real-mode
backends (madsim/src/std/net/) as the distributed execution story.

The placement contract is the S-rule carry-axis table
(`analysis.srules.CARRY_AXES`): every "lane" leaf is lane-leading
[L, ...] and shards `NamedSharding(mesh, P(LANE_AXIS))`; every "global"
leaf (scalars, result rings, the OR-folded coverage map) replicates
`P()`. `carry_shardings` below derives the per-leaf sharding pytree
from that table, so the executed placement and the machine-checked
declaration are one artifact — a new carry leaf without a CARRY_AXES
row fails here at trace time AND in `lint` (S002).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: the 1-D lane-sharding mesh axis. Named "batch" (the SNIPPETS.md
#: [1]/[2] idiom and the srules note) — one logical seed batch spans
#: the axis; `SEED_AXIS` is the pre-rebuild alias, kept for callers.
LANE_AXIS = "batch"
SEED_AXIS = LANE_AXIS


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, axis "batch"."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (LANE_AXIS,))

def seed_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(LANE_AXIS))


def shard_seeds(seeds, mesh: Mesh):
    """Place a seed batch sharded over the mesh's "batch" axis; the
    engine's streaming quartet then pins every StreamCarry leaf with
    `carry_shardings` (explicit in/out_shardings, not propagation).

    Validates the mesh and batch shape up front so every sharding entry
    point gets a clear error instead of a raw XLA one. On a multi-host
    (jax.distributed) mesh, each process materializes only its local
    shard — device_put can't place onto non-addressable devices."""
    if LANE_AXIS not in mesh.shape:
        raise ValueError(
            f'mesh has no "{LANE_AXIS}" axis (axes: {tuple(mesh.shape)}); '
            f"build it with parallel.make_mesh(...)"
        )
    axis = mesh.shape[LANE_AXIS]
    n = len(seeds)
    if n % axis != 0:
        raise ValueError(
            f"seed batch ({n}) must be a multiple of the mesh's "
            f'"{LANE_AXIS}" axis size ({axis})'
        )
    sharding = seed_sharding(mesh)
    if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
        # madsim: allow(T001) — deliberate one-time host
        # materialization at stream START (multi-host placement needs
        # the full batch host-side to slice per-process shards); not in
        # the per-segment steady state the T-rules guard
        host = np.asarray(seeds)
        return jax.make_array_from_callback(host.shape, sharding, lambda idx: host[idx])
    return jax.device_put(seeds, sharding)


def _path_field(entry) -> Optional[str]:
    """The attribute/dict-key name of one pytree path entry, or None
    for unnamed entries (sequence indices)."""
    for attr in ("name", "key"):
        v = getattr(entry, attr, None)
        if v is not None:
            return str(v)
    return None


def carry_shardings(mesh: Mesh, carry_tree):
    """The per-leaf NamedSharding pytree for a StreamCarry (aval or
    value): "lane" leaves (per the declared `analysis.srules.CARRY_AXES`
    table) shard their leading [L] dim over the "batch" axis, "global"
    leaves replicate. Passed as jit in_shardings AND out_shardings on
    the stream quartet, so per-lane state never moves between devices
    inside a dispatch — the only cross-device traffic is the registered
    collectives, which XLA places at segment boundaries because that is
    where lane values fold into replicated leaves.

    Raises on a carry field with no CARRY_AXES row: adding carry state
    forces an axis decision (the same contract lint's S002 enforces
    statically)."""
    from ..analysis.srules import CARRY_AXES  # jax-free, no cycle

    lane = NamedSharding(mesh, P(LANE_AXIS))
    repl = NamedSharding(mesh, P())
    carry_table = CARRY_AXES["StreamCarry"]
    state_table = CARRY_AXES["LaneState"]

    def place(path, leaf):
        top = _path_field(path[0]) if path else None
        if top == "state":
            field = _path_field(path[1]) if len(path) > 1 else None
            axis = state_table.get(field)
            table = f"LaneState.{field}"
        else:
            field, axis = top, carry_table.get(top)
            table = f"StreamCarry.{field}"
        if axis is None:
            raise KeyError(
                f"{table} has no analysis/srules.py CARRY_AXES row — "
                f"declare the new leaf lane-leading or global before "
                f"meshing it (S002)"
            )
        return lane if axis == "lane" else repl

    return jax.tree_util.tree_map_with_path(place, carry_tree)


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k
