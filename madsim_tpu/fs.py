"""Simulated filesystem (reference: madsim/src/sim/fs.rs).

Per-node in-memory inode map with positional read/write, metadata and
read-only enforcement. Write durability is modeled with a working copy
(page cache) and a durable copy per inode: all mutations (including
create-truncate) hit the working copy, `sync_all`/`sync_data` snapshot
it durable, and a node kill/restart triggers `power_fail`, restoring the
working copy from durable — the behavior the reference marks TODO
(fs.rs:50-53,:205-207) but whose hook it already wires to reset_node.
"""

from __future__ import annotations

from typing import Dict, Optional

from . import _context
from .errors import SimError
from .plugin import Simulator


class FsError(SimError):
    pass


class INode:
    """Reference: fs.rs:125 `INode`.

    Two copies model durability: `working` is what the running node
    reads/writes (a page cache), `durable` is what survives power
    failure. `sync_all` snapshots working -> durable; `power_fail`
    restores working <- durable. All mutations — content writes,
    truncation, and the namespace ops create/unlink — are working-level
    until synced: an unsynced create vanishes on power failure and an
    unsynced unlink rolls back."""

    __slots__ = ("durable", "working", "readonly", "exists_durable", "removed")

    def __init__(self) -> None:
        self.durable = bytearray()
        self.working = bytearray()
        self.readonly = False
        self.exists_durable = False  # creation not yet fsynced
        self.removed = False  # unlinked in the working view

    def sync(self) -> None:
        self.durable = bytearray(self.working)
        if not self.removed:
            self.exists_durable = True

    def power_fail(self) -> None:
        self.working = bytearray(self.durable)
        self.removed = False  # an unsynced unlink rolls back


class FsSim(Simulator):
    """Reference: fs.rs:24 `FsSim`."""

    def __init__(self, rng, time, config):
        super().__init__(rng, time, config)
        self._nodes: Dict[int, Dict[str, INode]] = {}

    def create_node(self, node_id: int) -> None:
        self._nodes.setdefault(node_id, {})

    def reset_node(self, node_id: int) -> None:
        """Node kill/restart: trigger power-fail semantics
        (reference: fs.rs:38-40 — TODO in the reference as well)."""
        self.power_fail(node_id)

    def power_fail(self, node_id: int) -> None:
        """Drop all unsynced state — content AND namespace ops
        (reference: fs.rs:50-53 marks this TODO; implemented here).
        Synced data survives."""
        files = self._nodes.get(node_id, {})
        for path in [p for p, ino in files.items() if not ino.exists_durable]:
            del files[path]  # unsynced creations vanish
        for inode in files.values():
            inode.power_fail()

    def fs_of(self, node_id: int) -> Dict[str, INode]:
        return self._nodes.setdefault(node_id, {})


def _current_fs() -> Dict[str, INode]:
    from .plugin import simulator
    from .task import current_node_id

    return simulator(FsSim).fs_of(current_node_id())


class Metadata:
    def __init__(self, size: int, readonly: bool):
        self._size = size
        self._readonly = readonly

    def len(self) -> int:
        return self._size

    def is_readonly(self) -> bool:
        return self._readonly


class File:
    """Positional-I/O file handle (reference: fs.rs:68 `FsNodeHandle`/File)."""

    def __init__(self, inode: INode, writable: bool):
        self._inode = inode
        self._writable = writable

    @staticmethod
    async def open(path: str) -> "File":
        fs = _current_fs()
        inode = fs.get(path)
        if inode is None or inode.removed:
            raise FsError(f"file not found: {path}")
        return File(inode, writable=not inode.readonly)

    @staticmethod
    async def create(path: str) -> "File":
        fs = _current_fs()
        inode = fs.get(path)
        if inode is None:
            inode = INode()
            fs[path] = inode
        if inode.readonly:
            raise FsError(f"file is read-only: {path}")
        inode.working = bytearray()  # truncate is unsynced like any write
        inode.removed = False  # re-creating an unlinked name (unsynced)
        return File(inode, writable=True)

    async def read_at(self, buf_len: int, offset: int) -> bytes:
        return bytes(self._inode.working[offset : offset + buf_len])

    async def read_all(self) -> bytes:
        return bytes(self._inode.working)

    async def write_all_at(self, data: bytes, offset: int) -> None:
        """Working-copy write: lost on power_fail until sync_all."""
        if not self._writable or self._inode.readonly:
            raise FsError("file is read-only")
        buf = self._inode.working
        end = offset + len(data)
        if len(buf) < end:
            buf.extend(b"\x00" * (end - len(buf)))
        buf[offset:end] = data

    async def set_len(self, size: int) -> None:
        if not self._writable or self._inode.readonly:
            raise FsError("file is read-only")
        buf = self._inode.working
        if len(buf) > size:
            del buf[size:]
        else:
            buf.extend(b"\x00" * (size - len(buf)))

    async def sync_all(self) -> None:
        """Flush to durable storage (reference: fsync)."""
        self._inode.sync()

    sync_data = sync_all

    async def metadata(self) -> Metadata:
        return Metadata(len(self._inode.working), self._inode.readonly)


async def read(path: str) -> bytes:
    f = await File.open(path)
    return await f.read_all()


async def write(path: str, data: bytes) -> None:
    """Convenience write: durable on return (create + write + sync)."""
    f = await File.create(path)
    await f.write_all_at(data, 0)
    await f.sync_all()


async def remove_file(path: str) -> None:
    """Unlink: working-level until power failure or durable GC — an
    unsynced unlink rolls back on crash."""
    fs = _current_fs()
    inode = fs.get(path)
    if inode is None or inode.removed:
        raise FsError(f"file not found: {path}")
    if inode.exists_durable:
        inode.removed = True
    else:
        del fs[path]  # never durable: gone outright


async def metadata(path: str) -> Metadata:
    fs = _current_fs()
    inode = fs.get(path)
    if inode is None or inode.removed:
        raise FsError(f"file not found: {path}")
    return Metadata(len(inode.working), inode.readonly)


def set_readonly(path: str, readonly: bool = True) -> None:
    """Test helper mirroring the reference's read-only enforcement."""
    fs = _current_fs()
    if path not in fs:
        raise FsError(f"file not found: {path}")
    fs[path].readonly = readonly
