"""CLI harness — seed exploration, replay, determinism checking.

Build-plan step 7 (SURVEY.md §7): the env-driven multi-seed runner +
determinism-check mode, as a command line:

  python -m madsim_tpu explore --machine raft --seeds 4096 [--faults 2]
  python -m madsim_tpu replay  --machine raft --seed 1234 [--tail 30]
  python -m madsim_tpu check   --machine kv   --seeds 64
  python -m madsim_tpu bench   [--lanes 4096]

`explore` prints failing seeds (the reference prints
`MADSIM_TEST_SEED=...` repro hints; here the seed IS the repro:
`replay --seed N` shows the full event trace).
"""

from __future__ import annotations

# madsim: allow-file(D001) — every wall-clock read in this module goes
# through the deliberately named `import time as wall` alias and only
# measures host throughput (seeds/s, elapsed_s) or stamps report
# metadata; nothing feeds simulation state. Virtual time lives in the
# engine.
import argparse
import contextlib
import dataclasses
import json
import logging
import os
import sys


def build_machine(name: str, nodes: int = 0):
    """CLI machine registry — also the resolver corpus entries use to
    rebuild their machine from (name, nodes). The demo-* entries are
    deliberately buggy variants (each models a classic bug class) so the
    hunt -> shrink -> replay -> corpus workflow is demonstrable without
    writing a protocol first."""
    from .models.echo import EchoMachine
    from .models.etcd import EtcdMachine
    from .models.etcd_mvcc import EtcdMvccMachine
    from .models.gossip import GossipMachine
    from .models.kafka_group import KafkaGroupMachine, NoFencingGroupMachine
    from .models.kv import KvMachine
    from .models.mq import MqMachine
    from .models.multipaxos import MultiPaxosMachine, NoPromiseCheckMultiPaxos
    from .models.paxos import NoPromiseCheckPaxos, PaxosMachine
    from .models.raft import RaftMachine
    from .models.raft_compact import RaftCompactMachine, TornSnapshotRaftCompact
    from .models.s3 import S3Machine
    from .models.twopc import TwoPcMachine

    class DoubleGrantEtcd(EtcdMachine):
        CHECK_OWNER_ON_CAMPAIGN = False  # non-atomic election txn

    class OvercommitRaft(RaftMachine):
        COMMIT_TO_LOG_LEN = True  # Raft §5.3 commit-bound bug

    class QuorumOffByOneRaft(RaftMachine):
        QUORUM_OFF_BY_ONE = True  # commit below majority (needs group faults)

    class VolatileCommitRaft(RaftMachine):
        PERSIST_COMMIT_NOT_LOG = True  # durable commitIndex, volatile log
        #                                (caught only by --strict-restart)

    class DupVoteRaft(RaftMachine):
        DUP_VOTE_COUNT = True  # per-message vote tally (caught by dup chaos)

    class NoDedupMvcc(EtcdMvccMachine):
        NO_DEDUP = True  # retransmits double-apply (needs storms/dir clogs)

    class PrematureGiveupMvcc(EtcdMvccMachine):
        PREMATURE_GIVEUP = True  # deadline-RPC timeout mishandling
        #                          (reachable only by the delay kind)

    class ArrivalOrderS3(S3Machine):
        CONCAT_ARRIVAL_ORDER = True  # complete concats in upload order

    class AbortLeakS3(S3Machine):
        ABORT_KEEPS_PARTS = True  # abort leaks the session's parts

    class EarlyExpiryS3(S3Machine):
        LC_EARLY_HALF = True  # lifecycle expires at half the configured age

    class TombstoneLeakS3(S3Machine):
        LC_TOMBSTONE_LEAK = True  # expiry clears existence but not content

    class NoDedupS3(S3Machine):
        NO_DEDUP = True  # retried puts double-apply

    class DupAckGossip(GossipMachine):
        DUP_ACK_COUNT = True  # quorum tally counts duplicate acks

    machines = {
        "echo": lambda: EchoMachine(rounds=10),
        "raft": lambda: RaftMachine(num_nodes=nodes or 5, log_capacity=8),
        "kv": lambda: KvMachine(num_nodes=nodes or 4),
        "mq": lambda: MqMachine(num_nodes=nodes or 4),
        "etcd": lambda: EtcdMachine(num_nodes=nodes or 4),
        "etcd-mvcc": lambda: EtcdMvccMachine(num_nodes=nodes or 4),
        "twopc": lambda: TwoPcMachine(num_nodes=nodes or 4),
        "group": lambda: KafkaGroupMachine(num_nodes=nodes or 4),
        "paxos": lambda: PaxosMachine(num_nodes=nodes or 5),
        "multipaxos": lambda: MultiPaxosMachine(num_nodes=nodes or 5),
        "demo-nopromise-paxos": lambda: NoPromiseCheckPaxos(num_nodes=nodes or 5),
        "demo-doublegrant-etcd": lambda: DoubleGrantEtcd(
            num_nodes=nodes or 4, target_gens=99, target_writes=9999
        ),
        "demo-overcommit-raft": lambda: OvercommitRaft(
            num_nodes=nodes or 5, log_capacity=8
        ),
        "demo-nofencing-group": lambda: NoFencingGroupMachine(num_nodes=nodes or 4),
        "demo-quorumoffbyone-raft": lambda: QuorumOffByOneRaft(
            num_nodes=nodes or 5, log_capacity=8
        ),
        "demo-volatilecommit-raft": lambda: VolatileCommitRaft(
            num_nodes=nodes or 5, log_capacity=8
        ),
        "demo-dupvote-raft": lambda: DupVoteRaft(
            num_nodes=nodes or 5, log_capacity=8
        ),
        "raft-compact": lambda: RaftCompactMachine(
            num_nodes=nodes or 5, log_capacity=8
        ),
        "demo-tornsnapshot-raft": lambda: TornSnapshotRaftCompact(
            num_nodes=nodes or 5, log_capacity=8
        ),
        "demo-nodedup-mvcc": lambda: NoDedupMvcc(num_nodes=nodes or 4),
        "demo-giveup-mvcc": lambda: PrematureGiveupMvcc(num_nodes=nodes or 4),
        "demo-nopromise-multipaxos": lambda: NoPromiseCheckMultiPaxos(
            num_nodes=nodes or 5
        ),
        "s3": lambda: S3Machine(num_nodes=nodes or 4),
        "gossip": lambda: GossipMachine(num_nodes=nodes or 33),
        "demo-dupack-gossip": lambda: DupAckGossip(num_nodes=nodes or 33),
        "demo-arrivalorder-s3": lambda: ArrivalOrderS3(num_nodes=nodes or 4),
        "demo-abortleak-s3": lambda: AbortLeakS3(num_nodes=nodes or 4),
        "demo-earlyexpiry-s3": lambda: EarlyExpiryS3(num_nodes=nodes or 4),
        "demo-tombstoneleak-s3": lambda: TombstoneLeakS3(num_nodes=nodes or 4),
        "demo-nodedup-s3": lambda: NoDedupS3(num_nodes=nodes or 4),
    }
    if name not in machines:
        sys.exit(f"unknown machine {name!r}; choose from {sorted(machines)}")
    return machines[name]()


def _build_engine(args):
    # engine construction (the engine/flax import chain, model init,
    # device constants, first backend touch) lands on the host
    # timeline: it is real wall time a --perf-timeline run would
    # otherwise report as unattributed
    from .perf.recorder import maybe_span

    with maybe_span("engine_build"):
        from .engine import Engine, EngineConfig, FaultPlan

        return _build_engine_inner(args, Engine, EngineConfig, FaultPlan)


def _build_engine_inner(args, Engine, EngineConfig, FaultPlan):
    machine = build_machine(args.machine, args.nodes)
    cfg = EngineConfig(
        # guided hunts pin the 4-bit coverage band layout so the slot
        # space stays identical across fault-vocabulary escalations
        # (madsim_tpu/search); 0 keeps the derived layout — bit-for-bit
        # the HEAD behavior — for every unguided run
        cov_band_bits_min=4 if getattr(args, "guided", False) else 0,
        # round, not truncate: a shrunk repro prints horizon_us/1e6 and
        # float truncation would shave the failing event off the horizon
        horizon_us=round(args.horizon * 1e6),
        queue_capacity=args.queue,
        packet_loss_rate=args.loss,
        rng_stream=getattr(args, "rng_stream", 2),
        flight_recorder=bool(getattr(args, "flight_recorder", False)),
        coverage=bool(getattr(args, "coverage", False)),
        # None = keep the engine default (buffered); 0 = the unbuffered
        # escape hatch (per-event map scatter); maps bit-identical either way
        **({} if getattr(args, "cov_buffer", None) is None
           else {"cov_buffer": int(args.cov_buffer)}),
        provenance=bool(getattr(args, "provenance", False)),
        compile_cache_dir=getattr(args, "compile_cache", None),
        faults=FaultPlan(
            n_faults=args.faults,
            # explicit --fault-tmax keeps fault draws stable when a shrunk
            # repro command passes a smaller --horizon
            t_max_us=args.fault_tmax or int(args.horizon * 0.6e6) or 1,
            dur_min_us=100_000,
            dur_max_us=800_000,
            strict_restart=bool(getattr(args, "strict_restart", False)),
            **_fault_kind_flags(args),
        ),
    )
    return Engine(machine, cfg)


def _fault_kind_flags(args) -> dict:
    # default-tolerant: programmatic callers and pre-round-3 recorded
    # argsets may lack the flag; absent == legacy pair,kill. The
    # vocabulary is the shared madsim_tpu/kinds.py table (lint rule
    # G004 asserts this parser binds it rather than a drifting copy).
    from .kinds import CLI_KIND_TO_FLAG

    raw = getattr(args, "fault_kinds", "pair,kill")
    kinds = {k.strip() for k in raw.split(",") if k.strip()}
    known = {name for name, _field in CLI_KIND_TO_FLAG}
    if not kinds <= known:
        sys.exit(f"unknown fault kinds {sorted(kinds - known)}; choose from {sorted(known)}")
    if kinds == {"dup"} and args.faults > 0:
        sys.exit(
            "dup is per-delivery chaos, not a scheduled fault: with "
            "--faults > 0 pick at least one scheduled kind too "
            "(e.g. --fault-kinds pair,kill,dup), or pass --faults 0"
        )
    return {field: name in kinds for name, field in CLI_KIND_TO_FLAG}


def fault_kinds_str(fp) -> str:
    """The --fault-kinds value that reproduces a FaultPlan's vocabulary
    (the inverse of _fault_kind_flags; shrink prints it after kind
    ablation so the repro line matches the MINIMIZED plan)."""
    from .kinds import CLI_KIND_TO_FLAG

    return ",".join(
        name for name, field in CLI_KIND_TO_FLAG if getattr(fp, field)
    ) or "pair"


def _repro_line(args, seed) -> str:
    """A replay command that reproduces `seed` exactly — including the
    resolved --fault-tmax, which is load-bearing: without it a replay
    with a different --horizon would draw a different fault schedule."""
    tmax = args.fault_tmax or int(args.horizon * 0.6e6) or 1
    return (
        f"reproduce: python -m madsim_tpu replay --machine {args.machine} "
        f"--seed {seed} --nodes {args.nodes} --horizon {args.horizon} "
        f"--queue {args.queue} --faults {args.faults} --loss {args.loss} "
        f"--fault-tmax {tmax} "
        f"--fault-kinds {getattr(args, 'fault_kinds', 'pair,kill')} "
        f"--rng-stream {getattr(args, 'rng_stream', 2)} "
        + ("--strict-restart " if getattr(args, "strict_restart", False) else "")
        + (
            f"--devices {args.devices} "
            if getattr(args, "devices", 0)
            else ""
        )
        + f"--max-steps {args.max_steps}"
    )


@contextlib.contextmanager
def _perf_session(args):
    """`--perf-timeline PATH` / `--xla-profile DIR` wrapper around a
    whole subcommand: a PerfRecorder publishes itself for the engine's
    span instrumentation (madsim_tpu/perf/recorder.py) and the Chrome/
    Perfetto host timeline + summary land AFTER the command's own
    output; `--xla-profile` additionally wraps the run in
    `jax.profiler.trace` (device/XLA-level profile for tensorboard).
    The timeline is written even when the command fails — a failing
    run's wall-clock profile is exactly what you want to look at."""
    path = getattr(args, "perf_timeline", None)
    xla_dir = getattr(args, "xla_profile", None)
    if not path and not xla_dir:
        yield None
        return
    rec = None
    try:
        with contextlib.ExitStack() as stack:
            if xla_dir:
                import jax

                stack.enter_context(jax.profiler.trace(xla_dir))
            if path:
                from .perf.recorder import PerfRecorder

                rec = stack.enter_context(
                    PerfRecorder(meta={"cmd": getattr(args, "cmd", None)})
                )
            yield rec
    finally:
        if rec is not None and rec.wall_us:
            n = rec.write(path)
            s = rec.summary()
            print(
                f"host timeline: {n} spans, "
                f"{100 * s['span_coverage']:.0f}% of {s['wall_s']:.1f}s "
                f"wall attributed -> {path} (open in https://ui.perfetto.dev)"
            )
            print(f"host verdict: {rec.verdict()}")
        if xla_dir:
            print(f"xla profile -> {xla_dir} (tensorboard --logdir {xla_dir})")


def _stream_kwargs(args) -> dict:
    """Pipelined-executor knobs shared by explore/hunt/bench (default:
    pipelined + donated; --no-pipeline restores the r5 per-segment
    driver, kept for one release)."""
    kw = {
        "pipelined": not getattr(args, "no_pipeline", False),
        "segments_per_dispatch": getattr(args, "segments_per_dispatch", 8),
        "dispatch_depth": getattr(args, "dispatch_depth", 4),
        "donate": not getattr(args, "no_donate", False),
    }
    n = getattr(args, "devices", 0)
    if n:
        import jax

        from .parallel import make_mesh

        devs = jax.devices()
        if n > len(devs):
            raise SystemExit(
                f"--devices {n}: only {len(devs)} devices visible (on CPU, "
                f"set XLA_FLAGS=--xla_force_host_platform_device_count={n})"
            )
        kw["mesh"] = make_mesh(devs[:n])
    return kw


def _print_fr_stats(stats) -> None:
    """One metrics line when the flight recorder rode the stream."""
    fr = stats.get("flight_recorder")
    if not fr:
        return
    inj = ", ".join(f"{k}={v}" for k, v in fr["faults_injected"].items() if v)
    extra = "".join(
        f", {label} {fr[key]}"
        for key, label in (
            ("dup_injected", "dups"), ("amnesia_restarts", "amnesia restarts"),
        )
        if fr.get(key)
    )
    print(
        f"flight recorder: faults injected [{inj or 'none'}]{extra}, "
        f"queue hwm {fr['queue_hwm']}, clogged-links hwm {fr['clog_links_hwm']}, "
        f"killed hwm {fr['killed_hwm']}"
    )


def _make_emitter(args):
    """StatsEmitter bound to --stats BASE (also $MADSIM_TPU_STATS):
    BASE.jsonl (history), BASE.prom (Prometheus textfile), BASE.json
    (latest snapshot — what `serve --service stats` exposes).
    `args.stats_labels` (set by the fleet worker, not a CLI flag)
    namespaces the Prometheus gauges per job."""
    base = getattr(args, "stats", None) or os.environ.get("MADSIM_TPU_STATS")
    if not base:
        return None
    from .tracing import StatsEmitter

    return StatsEmitter(base, labels=getattr(args, "stats_labels", None))


def _print_cov_stats(stats) -> None:
    """One coverage line when the map rode the stream."""
    cov = stats.get("coverage")
    if not cov:
        return
    bands = ", ".join(f"{k}={v}" for k, v in cov["by_band"].items() if v)
    print(
        f"coverage: {cov['slots_hit']}/{cov['slots_total']} slots "
        f"({100 * cov['fraction']:.2f}%) [{bands or 'none'}]"
    )


def _print_attribution(stats) -> None:
    """One fault-attribution line when provenance rode the run: how many
    failures causally implicate each chaos kind."""
    att = stats.get("fault_attribution")
    if att is None:
        return
    kinds = ", ".join(f"{k}={v}" for k, v in att.items())
    print(f"fault attribution: [{kinds or 'no failures'}]")


def _batch_heartbeat(bi, planned, completed, el, failing, infra, abandoned,
                     device_count=1, escalation=None, cov_txt=""):
    """The per-batch heartbeat line (format pinned in tests): batch
    index, throughput, the device count the unit spanned (meshed hunts
    read differently from single-device ones in the same log), failure
    tallies, the guided escalation rung when one exists, and the
    coverage delta."""
    esc_txt = f", escalation {escalation}" if escalation is not None else ""
    return (
        f"batch {bi}/{planned}: {completed} seeds in {el:.1f}s "
        f"({completed / el:.0f} seeds/s) on {device_count} device(s), "
        f"{failing} failing so far, {infra} infra, {abandoned} abandoned"
        f"{esc_txt}{cov_txt}"
    )


def _device_count(args) -> int:
    """Devices a streaming unit spans: `--devices N` meshes over N, 0
    means the classic unsharded single-device path."""
    return int(getattr(args, "devices", 0) or 0) or 1


def _stream_batches(eng, args, purpose="explore"):
    """Chunked streaming driver shared by explore/hunt: run the seed
    budget as batches of `--batch` seeds (each one run_stream call), so
    long hunts are observable — a heartbeat log line per batch (at
    --log-level info), a StatsEmitter record per batch (--stats), a
    cumulative coverage map, and the `--stop-on-plateau N` early exit
    when N consecutive batches add zero new coverage slots.

    Returns an aggregate dict shaped like run_stream's result, plus
    "batches_run"/"batches_planned"/"plateau"/"elapsed_s" (and
    "coverage_map" when the engine's coverage gate is on).
    """
    import numpy as np
    import time as wall

    if getattr(args, "guided", False):
        # coverage-feedback search (madsim_tpu/search): same aggregate
        # shape, same checkpoint file, same stats feed — but every
        # batch's seed vector is chosen by the bias state instead of
        # streamed sequentially. Guidance OFF never reaches this
        # import, so the streaming path below stays byte-identical to
        # HEAD by construction.
        from .search.guided import run_guided

        return run_guided(eng, args, purpose=purpose)

    log = logging.getLogger(f"madsim_tpu.{purpose}")
    emitter = _make_emitter(args)
    plateau_n = int(getattr(args, "stop_on_plateau", 0) or 0)
    detector = None
    if plateau_n:
        if not getattr(args, "coverage", False):
            sys.exit(
                "--stop-on-plateau needs --coverage: the plateau signal "
                "IS the coverage curve"
            )
        from .runtime.coverage import PlateauDetector

        detector = PlateauDetector(plateau_n)

    sk = _stream_kwargs(args)
    batch = min(args.seeds, args.batch)
    planned = -(-args.seeds // batch)  # ceil

    agg = {
        "completed": 0,
        "failing": [],
        "infra": [],
        "abandoned": [],
        "seeds_consumed": 0,
        "stats": {},
        # seed -> violation provenance word (--provenance; stays empty
        # otherwise)
        "provenance": {},
    }
    cov_map = None
    cursor = args.seed
    plateaued = False
    start_bi = 0

    # --checkpoint PATH: restore per-batch progress recorded by an
    # interrupted run (atomic JSON, runtime/checkpoint.py). Batch i
    # always consumes the same seed range, so cursor + aggregates are
    # the whole resumable state — the finished report is identical to
    # the uninterrupted run's.
    ckpt_path = getattr(args, "checkpoint", None)
    stop_after = int(getattr(args, "stop_after_batches", 0) or 0)
    if ckpt_path:
        from .runtime.checkpoint import check_fingerprint, load_checkpoint

        ck = load_checkpoint(ckpt_path)
        if ck is not None:
            err = check_fingerprint(ck, args)
            if err:
                sys.exit(f"--checkpoint {ckpt_path}: {err}")
            agg["completed"] = int(ck["completed"])
            agg["seeds_consumed"] = int(ck["seeds_consumed"])
            agg["failing"] = [tuple(x) for x in ck["failing"]]
            agg["infra"] = [tuple(x) for x in ck["infra"]]
            agg["abandoned"] = list(ck["abandoned"])
            agg["provenance"] = {
                int(k): int(v) for k, v in (ck.get("prov") or {}).items()
            }
            cursor = int(ck["cursor"])
            start_bi = int(ck["batch"])
            plateaued = bool(ck.get("plateau", False))
            if ck.get("cov_b64"):
                from .runtime.coverage import decode_map

                cov_map = decode_map(ck["cov_b64"], eng.config.cov_slots_log2)
            if detector is not None and ck.get("detector"):
                d = ck["detector"]
                detector.best = int(d["best"])
                detector.streak = int(d["streak"])
                detector.batches = int(d["batches"])
            if ck.get("done"):
                print(
                    f"checkpoint {ckpt_path}: run already complete "
                    f"({start_bi}/{planned} batches, "
                    f"{agg['completed']} seeds) — nothing to resume"
                )
            else:
                print(f"resumed at batch {start_bi + 1}/{planned} "
                      f"({agg['completed']} seeds already completed)")
                log.info(
                    "checkpoint %s: resumed at batch %d/%d",
                    ckpt_path, start_bi + 1, planned,
                )

    def _save_ckpt(bi_done: int, done_flag: bool) -> None:
        if not ckpt_path:
            return
        from .runtime.checkpoint import fingerprint_from_args, save_checkpoint
        from .runtime.coverage import encode_map

        save_checkpoint(
            ckpt_path,
            {
                "fingerprint": fingerprint_from_args(args),
                "batch": bi_done,
                "planned": planned,
                "cursor": cursor,
                "completed": agg["completed"],
                "seeds_consumed": agg["seeds_consumed"],
                "failing": [list(x) for x in agg["failing"]],
                "infra": [list(x) for x in agg["infra"]],
                "abandoned": list(agg["abandoned"]),
                "prov": {str(k): v for k, v in agg["provenance"].items()},
                "cov_b64": encode_map(cov_map) if cov_map is not None else None,
                "detector": (
                    {
                        "best": detector.best,
                        "streak": detector.streak,
                        "batches": detector.batches,
                    }
                    if detector is not None else None
                ),
                "plateau": plateaued,
                "done": done_flag,
            },
        )

    # compile + warm outside the timed loop (same discipline as before)
    eng.run_stream(1, batch=batch, segment_steps=384, max_steps=args.max_steps, **sk)

    t_start = wall.perf_counter()
    bi = start_bi - 1
    for bi in range(start_bi, planned):
        chunk = min(batch, args.seeds - agg["completed"])
        if chunk <= 0:
            _save_ckpt(bi, True)  # seed budget already consumed: complete
            break
        t0 = wall.perf_counter()
        out = eng.run_stream(
            chunk, batch=min(batch, chunk), segment_steps=384,
            seed_start=cursor, max_steps=args.max_steps, **sk,
        )
        el = max(wall.perf_counter() - t0, 1e-9)
        cursor += out["seeds_consumed"]
        agg["completed"] += out["completed"]
        agg["seeds_consumed"] += out["seeds_consumed"]
        agg["failing"].extend(out["failing"])
        agg["infra"].extend(out["infra"])
        agg["abandoned"].extend(out["abandoned"])
        agg["provenance"].update(out.get("provenance", {}))
        agg["stats"] = out["stats"]
        new_slots = 0
        slots_hit = 0
        if "coverage_map" in out:
            m = np.asarray(out["coverage_map"])
            prev = 0 if cov_map is None else int(cov_map.sum())
            cov_map = m if cov_map is None else (cov_map | m)
            slots_hit = int(cov_map.sum())
            new_slots = slots_hit - prev
        cov_txt = (
            f", coverage {slots_hit} slots (+{new_slots})"
            if cov_map is not None else ""
        )
        log.info("%s", _batch_heartbeat(
            bi + 1, planned, out["completed"], el,
            len(agg["failing"]), len(agg["infra"]), len(agg["abandoned"]),
            device_count=_device_count(args), cov_txt=cov_txt,
        ))
        if emitter is not None:
            rec = {
                "kind": f"{purpose}_batch",
                "machine": args.machine,
                "batch": bi + 1,
                "batches": planned,
                "completed": agg["completed"],
                "batch_completed": out["completed"],
                "seeds_per_sec": round(out["completed"] / el, 1),
                "failing": len(agg["failing"]),
                "infra": len(agg["infra"]),
                "abandoned": len(agg["abandoned"]),
            }
            if cov_map is not None:
                rec["coverage"] = {
                    "slots_hit": slots_hit, "new_slots": new_slots,
                }
            if "flight_recorder" in out["stats"]:
                rec["flight_recorder"] = out["stats"]["flight_recorder"]
            emitter.emit(rec)
        if detector is not None and detector.update(slots_hit):
            plateaued = True
        _save_ckpt(bi + 1, plateaued)
        if plateaued:
            log.info(
                "coverage plateau: no new slots for %d consecutive "
                "batches — stopping after batch %d/%d",
                plateau_n, bi + 1, planned,
            )
            break
        if stop_after and bi + 1 >= stop_after:
            # deliberate early stop (CI checkpoint smoke / operational
            # "hunt in slices"): the checkpoint above has done=False,
            # so the next --checkpoint run resumes at batch bi+2
            log.info(
                "stopping after batch %d/%d (--stop-after-batches %d; "
                "resumable via --checkpoint)", bi + 1, planned, stop_after,
            )
            break
    else:
        _save_ckpt(planned, True)

    agg["elapsed_s"] = wall.perf_counter() - t_start
    agg["batches_run"] = bi + 1
    agg["batches_planned"] = planned
    agg["plateau"] = plateaued
    if cov_map is not None:
        agg["coverage_map"] = cov_map
        from .runtime.coverage import coverage_dict

        agg["stats"] = dict(agg["stats"])
        agg["stats"]["coverage"] = {
            **coverage_dict(
                cov_map, eng.config.cov_slots_log2,
                band_bits=eng.cov_band_bits,
            ),
            "plateau": plateaued,
            "plateau_patience": plateau_n,
        }
    if agg["provenance"]:
        # per-kind fault attribution over the finds: how many failures
        # causally implicate each chaos kind — the machine-readable
        # "why" marginal the stats JSONL and `/stats` service expose
        from .engine.provenance import kind_counts

        agg["stats"] = dict(agg["stats"])
        agg["stats"]["fault_attribution"] = kind_counts(eng, agg["provenance"])
    if emitter is not None:
        emitter.emit(
            {
                "kind": f"{purpose}_summary",
                "machine": args.machine,
                "completed": agg["completed"],
                "failing": len(agg["failing"]),
                "infra": len(agg["infra"]),
                "abandoned": len(agg["abandoned"]),
                "batches_run": agg["batches_run"],
                "batches_planned": planned,
                "plateau": plateaued,
                "elapsed_s": round(agg["elapsed_s"], 2),
                **(
                    {"coverage": agg["stats"]["coverage"]}
                    if cov_map is not None else {}
                ),
                **(
                    {"fault_attribution": agg["stats"]["fault_attribution"]}
                    if "fault_attribution" in agg["stats"] else {}
                ),
            }
        )
        emitter.close()
    return agg


def _write_coverage_out(eng, args, agg) -> None:
    """`hunt --coverage-out PATH`: persist the cumulative map for
    cross-run diffing (`madsim_tpu coverage PATH --diff OLD`)."""
    path = getattr(args, "coverage_out", None)
    if not path:
        return
    if "coverage_map" not in agg:
        sys.exit("--coverage-out needs --coverage and --stream")
    import time as wall

    from .runtime.coverage import make_coverage_doc, save_coverage_doc

    doc = make_coverage_doc(
        {args.machine: agg["coverage_map"]},
        eng.config.cov_slots_log2,
        band_bits=eng.cov_band_bits,
        meta={
            "seeds": args.seeds,
            "seed_start": args.seed,
            "completed": agg["completed"],
            "fault_kinds": getattr(args, "fault_kinds", "pair,kill"),
            "ts": round(wall.time(), 3),
        },
    )
    save_coverage_doc(path, doc)
    cov = agg["stats"]["coverage"]
    print(
        f"coverage map: {cov['slots_hit']}/{cov['slots_total']} slots "
        f"-> {path}"
    )


def _split_infra(failing):
    """Partition (seed, code) pairs into (findings, infra): OVERFLOW is
    a fixed-shape capacity abort — an infrastructure artifact that says
    "rerun with a bigger --queue", never a protocol finding."""
    from .engine import OVERFLOW

    pairs = list(failing)
    findings = [(s, c) for s, c in pairs if c != OVERFLOW]
    infra = [(s, c) for s, c in pairs if c == OVERFLOW]
    return findings, infra


def _find_failing(eng, args, purpose="hunt"):
    """Run the seed batch (streaming or fixed) and return
    (failing [(seed, code), ...], infra [(seed, code), ...],
    abandoned_count, aggregate) where aggregate is _stream_batches'
    result dict (empty for the fixed path)."""
    if args.stream:
        agg = _stream_batches(eng, args, purpose=purpose)
        return agg["failing"], agg["infra"], len(agg["abandoned"]), agg
    import jax.numpy as jnp

    seeds = jnp.arange(args.seed, args.seed + args.seeds, dtype=jnp.uint32)
    res = eng.make_runner(max_steps=args.max_steps)(seeds)
    failing, infra = _split_infra(
        (int(s), int(c))
        for s, c in zip(
            eng.failing_seeds(res).tolist(), res.fail_code[res.failed].tolist()
        )
    )
    agg = {"stats": {}, "provenance": {}}
    if eng.config.provenance:
        agg["provenance"] = {
            int(s): int(p)
            for s, p in zip(
                eng.failing_seeds(res).tolist(),
                res.fail_prov[res.failed].tolist(),
            )
        }
        from .engine.provenance import kind_counts

        agg["stats"]["fault_attribution"] = kind_counts(eng, agg["provenance"])
    return failing, infra, 0, agg


def cmd_explore(args) -> int:
    import jax.numpy as jnp

    if getattr(args, "multihost", False):
        # join the jax.distributed job (MADSIM_TPU_COORDINATOR/NUM_PROCS/
        # PROC_ID, or pod auto-detect) and shard the batch globally
        from .parallel import multihost, pad_to_multiple

        multihost.initialize()
        import jax as _jax

        eng = _build_engine(args)
        n = pad_to_multiple(args.seeds, _jax.device_count())
        out = multihost.run_batch_global(
            eng, n, seed_start=args.seed, max_steps=args.max_steps
        )
        # results are replicated on every process — only rank 0 reports
        if _jax.process_index() == 0:
            print(
                f"explored {n} seeds over {out['processes']} processes / "
                f"{out['global_devices']} devices ({out['completed']} completed), "
                f"{out['failed']} failing"
            )
            if out["failing"]:
                print(f"failing seeds: {out['failing'][:20]}"
                      f"{' ...' if out['truncated'] else ''}")
        return 1 if out["failing"] else 0

    eng = _build_engine(args)
    if args.stream:
        # seed streaming: finished lanes refill with fresh seeds — the
        # high-throughput path for large batches (bench.py's path),
        # chunked into --batch-seed batches so long runs heartbeat,
        # emit stats and can stop on a coverage plateau
        out = _stream_batches(eng, args, purpose="explore")
        el = out["elapsed_s"]
        failing = out["failing"]
        st = out["stats"]
        plateau_txt = (
            f" [stopped early: coverage plateau after batch "
            f"{out['batches_run']}/{out['batches_planned']}]"
            if out["plateau"] else ""
        )
        print(
            f"streamed {out['completed']} seeds in {el:.1f}s "
            f"({out['completed']/max(el, 1e-9):.0f} seeds/s), {len(failing)} failing, "
            f"{len(out['abandoned'])} abandoned"
            + (f", {len(out['infra'])} infra (queue overflow)" if out["infra"] else "")
            + plateau_txt
        )
        print(
            f"executor: {st['device_segments']} segments, "
            f"{st['host_syncs']} host syncs, {st['drains']} drains "
            f"(pipelined={st['pipelined']}, donation={st['donation']}, "
            f"depth={st['dispatch_depth']}x{st['segments_per_dispatch']})"
        )
        _print_fr_stats(st)
        _print_cov_stats(st)
        _print_attribution(st)
        if failing:
            codes = sorted({c for _s, c in failing})
            print(f"failure codes: {codes}")
            print(f"failing seeds: {[s for s, _ in failing[:20]]}"
                  f"{' ...' if len(failing) > 20 else ''}")
            print(_repro_line(args, failing[0][0]))
            return 1
        return 0

    seeds = jnp.arange(args.seed, args.seed + args.seeds, dtype=jnp.uint32)
    res = eng.make_runner(max_steps=args.max_steps)(seeds)
    failing = eng.failing_seeds(res).tolist()
    n_done = int(res.done.sum())
    print(f"explored {len(seeds.tolist())} seeds ({n_done} completed), "
          f"{len(failing)} failing")
    if getattr(args, "coverage", False):
        import numpy as np

        from .runtime.coverage import coverage_dict, unpack_map

        m = unpack_map(
            np.bitwise_or.reduce(np.asarray(res.cov["map"]), axis=0),
            eng.config.cov_slots_log2,
        )
        _print_cov_stats(
            {"coverage": coverage_dict(
                m, eng.config.cov_slots_log2, band_bits=eng.cov_band_bits
            )}
        )
    if failing:
        codes = sorted({int(c) for c in res.fail_code.tolist() if c != 0})
        print(f"failure codes: {codes}")
        print(f"failing seeds: {failing[:20]}{' ...' if len(failing) > 20 else ''}")
        print(_repro_line(args, failing[0]))
        return 1
    return 0


def cmd_hunt(args) -> int:
    """explore -> shrink -> corpus: every found failing seed becomes a
    durable "open" regression entry with its minimized config."""
    from .engine import audit, corpus, shrink

    if getattr(args, "guided", False):
        if not args.stream:
            sys.exit("--guided needs --stream (the chunked batch loop "
                     "is where the feedback lives)")
        if not getattr(args, "coverage", False):
            sys.exit("--guided needs --coverage: the bias signal IS the "
                     "live coverage map")
    eng = _build_engine(args)
    failing, infra, abandoned, agg = _find_failing(eng, args, purpose="hunt")
    stream_stats = agg.get("stats", {})
    hunted = agg.get("completed", args.seeds)
    plateau_txt = ""
    if agg.get("plateau"):
        # honest reporting: a plateaued hunt ran FEWER seeds than asked
        plateau_txt = (
            f" [coverage plateau: stopped after batch "
            f"{agg['batches_run']}/{agg['batches_planned']} — "
            f"{max(0, args.seeds - hunted)} budgeted seeds not run]"
        )
    print(
        f"hunted {hunted} seeds: {len(failing)} failing"
        + (f", {abandoned} abandoned (over --max-steps)" if abandoned else "")
        + (
            f", {len(infra)} infra artifacts (queue overflow — rerun "
            f"with a bigger --queue; not recorded as findings)"
            if infra else ""
        )
        + plateau_txt
    )
    _print_fr_stats(stream_stats)
    _print_cov_stats(stream_stats)
    _print_attribution(stream_stats)
    guided_rec = agg.get("guided") or {}
    if guided_rec:
        g = stream_stats.get("guided", {})
        print(
            f"guided: escalation step {g.get('escalation', 0)}, "
            f"{g.get('parents', 0)} corpus parents, "
            f"{g.get('mutants', 0)} mutants over {g.get('batches', 0)} "
            f"batches (trail recorded"
            + (" in checkpoint)" if getattr(args, "checkpoint", None)
               else ")")
        )
    _write_coverage_out(eng, args, agg)
    entries = corpus.load(args.corpus)
    known = {e.key for e in entries}
    added = 0
    # Shrink one representative per distinct fail code (high-find-rate
    # hunts surface thousands of seeds of the SAME bug; shrinking five
    # copies of one code is pure waste). --all-seeds restores the
    # first-N behavior for deliberately sampling one code's seeds.
    if getattr(args, "all_seeds", False):
        to_shrink = failing[: args.limit]
    else:
        by_code: dict = {}
        for seed, code in failing:
            by_code.setdefault(code, []).append(seed)
        to_shrink = [(s[0], c) for c, s in sorted(by_code.items())][: args.limit]
        shrinking = {c for _s, c in to_shrink}
        for code, seeds_of in sorted(by_code.items()):
            verb = (
                f"shrinking seed {seeds_of[0]}" if code in shrinking
                else "beyond --limit, not shrunk"
            )
            print(f"  code {code}: {len(seeds_of)} seeds ({verb})")
    esc_by_seed = {
        int(k): int(v)
        for k, v in (guided_rec.get("failing_escalation") or {}).items()
    } if guided_rec else {}
    for seed, code in to_shrink:
        # a guided find made under an escalated vocabulary only
        # reproduces under that vocabulary: shrink (and the corpus
        # entry's config) start from the escalation step's engine, and
        # kind ablation then minimizes it honestly
        shrink_eng = eng
        if esc_by_seed.get(seed):
            from .search.guided import engine_for_escalation

            shrink_eng = engine_for_escalation(eng, esc_by_seed[seed])
        try:
            # the device-harvested provenance word (when the gate rode
            # the hunt) seeds the guided candidate order; shrink still
            # verifies every candidate by honest replay
            sr = shrink(
                shrink_eng, seed, max_steps=args.max_steps,
                prov_word=agg.get("provenance", {}).get(seed),
            )
        except ValueError as exc:
            # device-flagged but not reproducing on the host replay —
            # report it (that drift is itself a finding) and keep going
            print(f"  ! seed {seed} code {code}: {exc}")
            continue
        entry = corpus.CorpusEntry(
            machine=args.machine,
            nodes=args.nodes,
            seed=seed,
            fail_code=code,
            status=corpus.STATUS_OPEN,
            config=sr.shrunk,
            max_steps=sr.steps + 1,
            note=sr.summary(),
        )
        if entry.key in known:
            print(f"  = corpus: seed {seed} code {code} already recorded")
            continue
        # every new entry carries its digest trail + environment
        # fingerprint from birth, so future rot is auditable
        entry, _trail = audit.record_entry(entry, build_machine)
        known.add(entry.key)
        entries.append(entry)
        added += 1
        print(f"  + corpus: {sr.summary()}")
    if added:
        corpus.save(args.corpus, entries)
    if len(to_shrink) < (len(failing) if getattr(args, "all_seeds", False)
                         else len({c for _s, c in failing})):
        print(f"  (further failing codes/seeds not shrunk; raise --limit)")
    print(f"{added} new entries in {args.corpus}")
    return 1 if failing else 0


def cmd_regress(args) -> int:
    """Re-verify every corpus entry against its status contract: open
    entries must still reproduce their exact failure; fixed entries must
    keep passing. `--promote` flips open entries that no longer fail."""
    from .engine import corpus

    entries = corpus.load(args.corpus)
    if not entries:
        print(f"corpus {args.corpus} is empty")
        return 0
    bad = 0
    changed = False
    for i, e in enumerate(entries):
        try:
            out = corpus.check(e, build_machine)
        except SystemExit:
            # unknown machine name (renamed registry entry / foreign
            # corpus) must not kill the run — later entries still get
            # checked and pending --promote updates still get saved
            print(f"[FAIL] {e.machine} seed {e.seed}: unknown machine in registry")
            bad += 1
            continue
        tag = "ok " if out.ok else "FAIL"
        print(f"[{tag}] {e.machine} seed {e.seed} code {e.fail_code} ({e.status}): {out.verdict}")
        if not out.ok:
            if args.promote and e.status == corpus.STATUS_OPEN and not out.failed:
                entries[i] = dataclasses.replace(e, status=corpus.STATUS_FIXED)
                changed = True
                print(f"       promoted to {corpus.STATUS_FIXED}")
            else:
                bad += 1
    if changed:
        corpus.save(args.corpus, entries)
        print(f"corpus updated: {args.corpus}")
    print(f"{len(entries) - bad}/{len(entries)} entries satisfied")
    return 1 if bad else 0


def cmd_replay(args) -> int:
    from .engine import replay

    eng = _build_engine(args)
    if getattr(args, "diff_seed", None) is not None:
        # schedule-fork debugger: replay both seeds, print the first
        # diverging step with context (typical use: a failing seed vs
        # its nearest passing neighbor)
        from .engine.replay import replay_diff

        replay_diff(
            eng, args.seed, args.diff_seed, max_steps=args.max_steps,
            context=args.diff_context,
        )
        return 0
    rp = replay(eng, args.seed, max_steps=args.max_steps)
    events = rp.trace[-args.tail :] if args.tail else rp.trace
    for ev in events:
        print(ev)
    status = f"FAILED (code {rp.fail_code})" if rp.failed else "ok"
    print(f"seed {args.seed}: {status}, {len(rp.trace)} events, "
          f"t={int(rp.state.now_us)}us")
    return 1 if rp.failed else 0


def cmd_trace(args) -> int:
    """Replay one seed and export its virtual-time event timeline:
    Chrome/Perfetto trace_event JSON (--perfetto, opens in
    ui.perfetto.dev / chrome://tracing with one row per node) and/or
    structured JSONL (--jsonl, one object per event)."""
    from .engine import replay
    from .engine.trace_export import write_jsonl, write_perfetto

    if not args.perfetto and not args.jsonl:
        sys.exit("trace needs at least one of --perfetto PATH / --jsonl PATH")
    eng = _build_engine(args)
    n_nodes = eng.machine.NUM_NODES
    if args.perfetto:
        # lineage-capturing replay: the queue sequence numbers plus the
        # per-step push watermarks reconstruct every send->delivery
        # edge, so the export draws flow arrows (works with the
        # provenance gate off — message causality is free)
        from .engine.provenance import replay_with_lineage

        rp, lineage = replay_with_lineage(eng, args.seed, max_steps=args.max_steps)
        flows = [
            (lineage.trace[i], lineage.trace[j])
            for i, j in lineage.message_flows()
        ]
        n = write_perfetto(
            args.perfetto, rp.trace,
            machine=args.machine, seed=args.seed, num_nodes=n_nodes,
            flows=flows,
        )
        print(f"wrote {n} events ({len(flows)} message flows) to "
              f"{args.perfetto} (perfetto trace_event; "
              f"open in https://ui.perfetto.dev)")
    else:
        rp = replay(eng, args.seed, max_steps=args.max_steps)
    if args.jsonl:
        n = write_jsonl(args.jsonl, rp.trace, machine=args.machine, seed=args.seed)
        print(f"wrote {n} events to {args.jsonl} (JSONL)")
    status = f"FAILED (code {rp.fail_code})" if rp.failed else "ok"
    print(f"seed {args.seed}: {status}, {len(rp.trace)} events, "
          f"t={int(rp.state.now_us)}us")
    return 1 if rp.failed else 0


def cmd_why(args) -> int:
    """Answer "why did this seed fail?": replay with causal provenance +
    lineage reconstruction, decode the violation's provenance word to
    the implicated scheduled faults (kind, virtual time, target), cut
    the trace to the violation's past cone, and render the causal chain
    as text (stdout / --out), machine-readable JSON (--json), and a
    Perfetto timeline with flow arrows + the cone highlighted
    (--perfetto)."""
    from .engine.provenance import implicated, render_why, replay_with_lineage
    from .engine.trace_export import write_perfetto

    args.provenance = True  # the whole point of `why`
    if getattr(args, "seed_pos", None) is not None:
        args.seed = args.seed_pos
    eng = _build_engine(args)
    rp, lineage = replay_with_lineage(eng, args.seed, max_steps=args.max_steps)
    if not rp.failed:
        print(
            f"seed {args.seed} does not fail under this config (within "
            f"{args.max_steps} steps) — nothing to explain; pass the "
            f"repro line's exact flags"
        )
        return 2
    word = int(rp.state.fail_prov)
    att = implicated(eng, args.seed, word)
    cone = lineage.past_cone(len(lineage.trace) - 1)
    text = render_why(
        eng, args.seed, rp, lineage, cone, att, max_events=args.tail
    )
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"causal chain -> {args.out}")
    if args.json:
        doc = {
            "machine": args.machine,
            "seed": args.seed,
            "fail_code": rp.fail_code,
            "fail_time_us": int(rp.state.now_us),
            "prov_word": word,
            "implicated_kinds": list(att.kinds),
            "implicated_faults": [
                {
                    "index": f.index,
                    "kind": f.kind_name,
                    "t_apply_us": f.t_apply_us,
                    "t_undo_us": f.t_undo_us,
                    "target": f.target,
                }
                for f in att.faults
            ],
            "cone_events": len(cone),
            "trace_events": len(lineage.trace),
        }
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"attribution JSON -> {args.json}")
    if args.perfetto:
        cone_idx = set(cone)
        cone_steps = {lineage.trace[i].step for i in cone}
        flows = [
            (lineage.trace[i], lineage.trace[j])
            for i, j in lineage.message_flows()
            if j in cone_idx
        ]
        n = write_perfetto(
            args.perfetto, rp.trace,
            machine=args.machine, seed=args.seed,
            num_nodes=eng.machine.NUM_NODES,
            flows=flows, highlight=cone_steps,
        )
        print(
            f"wrote {n} events ({len(flows)} causal flows, cone "
            f"highlighted) to {args.perfetto} (open in "
            f"https://ui.perfetto.dev)"
        )
    return 0


def cmd_audit(args) -> int:
    """Replay every corpus entry and bisect its recorded digest trail to
    the first divergent checkpoint (the corpus-rot diagnosis). With
    --record, re-record trails + environment metadata at HEAD instead —
    refusing entries whose behavioral outcome no longer matches their
    status contract (recording those would bake the rot in)."""
    from .engine import audit, corpus

    entries = corpus.load(args.corpus)
    if not entries:
        print(f"corpus {args.corpus} is empty")
        return 0
    bad = 0
    changed = False
    for i, e in enumerate(entries):
        try:
            if args.record:
                new, trail = audit.record_entry(
                    e, build_machine, every=args.digest_every
                )
                if e.status == corpus.STATUS_OPEN:
                    contract_ok = trail.failed and trail.fail_code == e.fail_code
                else:  # STATUS_FIXED must pass
                    contract_ok = not trail.failed
                if not contract_ok:
                    got = (
                        f"fails with code {trail.fail_code}"
                        if trail.failed else "passes"
                    )
                    print(f"[FAIL] {e.machine} seed {e.seed}: replay {got}, "
                          f"which breaks its {e.status!r} contract — NOT "
                          f"recording (fix or re-hunt the entry first)")
                    bad += 1
                    continue
                entries[i] = new
                changed = True
                print(f"[rec ] {e.machine} seed {e.seed} code {e.fail_code}: "
                      f"{len(new.digests)} checkpoints every {new.digest_every} "
                      f"steps, final step {new.digest_final[0]}")
                continue
            out = audit.audit_entry(e, build_machine)
        except SystemExit:
            print(f"[FAIL] {e.machine} seed {e.seed}: unknown machine in registry")
            bad += 1
            continue
        tag = {"match": "ok  ", "no-digests": "??  ", "diverged": "DIVG"}[out.status]
        print(f"[{tag}] {e.machine} seed {e.seed} code {e.fail_code}: {out.verdict}")
        if not out.ok:
            bad += 1
    if changed:
        corpus.save(args.corpus, entries)
        print(f"corpus updated: {args.corpus}")
    print(f"{len(entries) - bad}/{len(entries)} entries satisfied")
    return 1 if bad else 0


def cmd_shrink(args) -> int:
    from .engine import shrink

    eng = _build_engine(args)
    try:
        sr = shrink(eng, args.seed, max_steps=args.max_steps)
    except ValueError as exc:
        print(exc)
        return 2
    print(sr.summary())
    f = sr.shrunk.faults
    print(
        f"minimal repro: python -m madsim_tpu replay --machine {args.machine} "
        f"--seed {args.seed} --nodes {args.nodes} "
        f"--horizon {sr.shrunk.horizon_us / 1e6} --queue {sr.shrunk.queue_capacity} "
        f"--faults {f.n_faults} --fault-tmax {f.t_max_us} "
        f"--loss {sr.shrunk.packet_loss_rate} --max-steps {sr.steps} "
        # kinds from the SHRUNK plan — ablation may have dropped some
        f"--fault-kinds {fault_kinds_str(f)} "
        + ("--strict-restart " if f.strict_restart else "")
        + f"--rng-stream {sr.shrunk.rng_stream}"
    )
    return 0


def cmd_check(args) -> int:
    import jax.numpy as jnp

    from .errors import NonDeterminism

    eng = _build_engine(args)
    seeds = jnp.arange(args.seed, args.seed + args.seeds, dtype=jnp.uint32)
    try:
        eng.check_determinism(seeds, max_steps=args.max_steps)
    except NonDeterminism as exc:
        print(f"FAIL: {exc}")
        return 1
    print(f"determinism check passed for {args.seeds} seeds")
    return 0


def cmd_coverage(args) -> int:
    """Render a persisted coverage map (`hunt --coverage-out`): total
    slots hit, per-band (event class / fault kind) marginals, the
    thinnest (band x model-phase) cells — the steer-here signal — and,
    with --diff, what a second run added over the first. Pure host-side
    numpy: works without an accelerator stack. `--json` emits the same
    tables machine-readably — the thinnest-cell list there is the
    EXACT artifact the guided-search bias layer consumes
    (runtime/coverage.top_uncovered), so operators and the bias state
    read one truth."""
    from .runtime.coverage import load_coverage_doc, render_report

    try:
        doc = load_coverage_doc(args.doc)
        diff_doc = load_coverage_doc(args.diff) if args.diff else None
    except (OSError, ValueError, KeyError) as exc:
        sys.exit(f"coverage: {exc}")
    if getattr(args, "json", False):
        from .runtime.coverage import (
            coverage_dict, diff_maps, doc_band_bits, doc_maps, top_uncovered,
        )

        L = doc["slots_log2"]
        bb = doc_band_bits(doc)
        other = doc_maps(diff_doc) if diff_doc is not None else {}
        out = {"slots_log2": L, "band_bits": bb, "maps": {}}
        for name, m in doc_maps(doc).items():
            entry = {
                **coverage_dict(m, L, band_bits=bb),
                "thinnest": top_uncovered(m, L, top=args.top, band_bits=bb),
            }
            if name in other:
                dd = diff_maps(other[name], m)
                entry["diff"] = {
                    "new": dd["only_b"], "lost": dd["only_a"],
                    "shared": dd["both"],
                }
            out["maps"][name] = entry
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    print(render_report(doc, top=args.top, diff_doc=diff_doc))
    return 0


def _serve_stats(args) -> int:
    """`serve --service stats`: a tiny HTTP endpoint over the
    StatsEmitter's files — GET /stats returns the latest run snapshot
    (BASE.json), GET /metrics the Prometheus textfile (BASE.prom) — so
    dashboards poll an endpoint instead of parsing logs. Plain stdlib
    http.server; read-only; no sim/jax imports."""
    import http.server

    base = args.stats or os.environ.get("MADSIM_TPU_STATS") or "madsim_stats"
    routes = {
        "/stats": (base + ".json", "application/json"),
        "/metrics": (base + ".prom", "text/plain; version=0.0.4"),
    }

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            path = self.path.split("?", 1)[0].rstrip("/") or "/stats"
            if path == "/healthz":
                body, ctype = b"ok\n", "text/plain"
            elif path in routes:
                fname, ctype = routes[path]
                try:
                    with open(fname, "rb") as f:
                        body = f.read()
                except OSError:
                    self.send_error(
                        404, f"no stats recorded yet ({fname} missing)"
                    )
                    return
            else:
                self.send_error(404, "routes: /stats /metrics /healthz")
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *a):  # route access logs to logging
            logging.getLogger("madsim_tpu.serve").debug(fmt, *a)

    # shared daemon glue (fleet/httpd.py): --port-file writes the
    # realized port atomically so tests/workers discover a host:0 bind
    # without racing, and SIGTERM now closes the server as gracefully
    # as Ctrl-C always did
    from .fleet import httpd

    srv, host, port = httpd.bind(args.addr, Handler)
    print(
        f"stats serving on {host}:{port} "
        f"(GET /stats /metrics /healthz; files {base}.json/.prom)",
        flush=True,
    )
    return httpd.run_http_server(
        srv, port_file=getattr(args, "port_file", None)
    )


def cmd_lint(args) -> int:
    """Static determinism & contract analysis (madsim_tpu/analysis/).
    Runs jax-free except the C-rule import half (--no-import-check
    disables it)."""
    from .analysis.cli import main as lint_main

    return lint_main(args)


def cmd_serve(args) -> int:
    """Run an L5 service server over real TCP (production mode) — the
    counterpart of the reference's real etcd/kafka/S3 endpoints. Apps
    written against `services.*` clients connect unmodified.

    SECURITY: the wire format is pickle (like the reference real-mode
    Endpoint uses bincode, but pickle can execute code on load) — bind
    only on trusted networks / localhost."""
    if args.service == "stats":
        # observability endpoint over StatsEmitter files: no sim
        # networking involved, so no real-mode requirement
        return _serve_stats(args)
    from . import dual

    if dual.MODE != "real":
        sys.exit(
            "serve needs production networking: re-run as\n"
            f"  MADSIM_TPU_MODE=real python -m madsim_tpu serve "
            f"--service {args.service} --addr {args.addr}"
        )
    import asyncio

    async def run_server() -> None:
        if getattr(args, "grpc", False):
            if args.service != "etcd":
                sys.exit("--grpc is only available for --service etcd")
            from .services.etcd.real_gateway import EtcdGrpcGateway

            gw = EtcdGrpcGateway()
            port = await gw.start(args.addr)
            host = args.addr.rsplit(":", 1)[0]
            print(f"etcd serving on {host}:{port} (genuine gRPC wire)", flush=True)
            await gw.wait()
            return
        if getattr(args, "http", False):
            if args.service != "s3":
                sys.exit("--http is only available for --service s3")
            from .services.s3.real_gateway import S3HttpGateway

            gw = S3HttpGateway()
            port = await gw.start(args.addr)
            host = args.addr.rsplit(":", 1)[0]
            print(f"s3 serving on {host}:{port} (genuine S3 REST wire)", flush=True)
            await gw.wait()
            return
        if getattr(args, "wire", False):
            if args.service != "kafka":
                sys.exit("--wire is only available for --service kafka")
            from .services.kafka.wire_gateway import KafkaWireGateway

            host = args.addr.rsplit(":", 1)[0]
            # Metadata/FindCoordinator responses must name an address
            # clients can CONNECT to — a 0.0.0.0 bind is not one (real
            # brokers split listeners from advertised.listeners too)
            advertise = getattr(args, "advertise", None) or (
                host if host and host != "0.0.0.0" else "127.0.0.1"
            )
            gw = KafkaWireGateway(advertised_host=advertise)
            port = await gw.start(args.addr)
            gw.advertised_port = port
            print(
                f"kafka serving on {host or '127.0.0.1'}:{port} "
                f"(genuine Kafka wire, advertising {advertise}:{port})",
                flush=True,
            )
            await gw.wait()
            return
        if args.service == "etcd":
            from .services.etcd import SimServer

            server = SimServer()
        elif args.service == "kafka":
            from .services.kafka import SimBroker

            server = SimBroker()
        elif args.service == "s3":
            from .services.s3 import SimServer as S3Server

            server = S3Server()
        else:
            sys.exit(f"unknown service {args.service!r}")

        def on_bound(ep) -> None:
            # the ready line prints the ACTUAL bound address (supports
            # --addr host:0) and only after the socket exists
            host, port = ep.local_addr
            print(f"{args.service} serving on {host}:{port} (real TCP)", flush=True)

        await server.serve(args.addr, on_bound=on_bound)

    try:
        asyncio.run(run_server())
    except KeyboardInterrupt:
        pass
    return 0


def _format_fleet_event(ev: dict, t0: float) -> str:
    """One `fleet watch` line per event: relative seconds (wall deltas
    between recorded timestamps — no clock is read here), the event
    type, and the payload fields that aren't already in the prefix."""
    ts = float(ev.get("ts") or t0)
    skip = {"seq", "ts", "type", "job"}
    detail = " ".join(
        f"{k}={ev[k]}" for k in sorted(ev) if k not in skip
        and ev[k] is not None
    )
    return f"+{ts - t0:9.2f}s  {ev.get('type', '?'):<16} {detail}".rstrip()


def _fleet_watch(client, addr: str, args) -> int:
    """`fleet watch JOB`: tail the job's SSE event stream and print one
    line per event, exiting 0 once the stream's `end` frame reports a
    terminal state. Push, not poll — the server parks between events."""
    t0 = None
    for frame in client.iter_events(addr, args.job, since=args.since):
        data = frame.get("data")
        if frame.get("event") == "end":
            state = (data or {}).get("state") if isinstance(data, dict) else "?"
            print(f"-- job {args.job} reached terminal state "
                  f"{state!r} --")
            return 0
        if not isinstance(data, dict):
            continue
        if t0 is None:
            t0 = float(data.get("ts") or 0.0)
        print(_format_fleet_event(data, t0), flush=True)
    # stream generator returned without an end frame (server gone mid-
    # tail after retries) — surface it
    print(f"fleet watch: stream for {args.job} closed before a "
          f"terminal state", file=sys.stderr)
    return 1


def _fleet_timeline(client, addr: str, args, retries: int) -> int:
    """`fleet timeline JOB`: fetch the merged control-plane + worker
    Perfetto timeline and write it next to the invoker."""
    doc = client.timeline(addr, args.job, retries=retries)
    out_path = args.out or f"{args.job}.timeline.perfetto.json"
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    summary = doc.get("madsim_fleet_timeline_summary", {})
    n_ev = len(doc.get("traceEvents", []))
    frac = float(summary.get("attribution") or 0.0)
    print(f"timeline: {n_ev} trace events "
          f"({summary.get('events', 0)} lifecycle events, "
          f"{summary.get('worker_spans', 0)} worker spans), "
          f"{frac * 100.0:.0f}% of job wall clock attributed "
          f"-> {out_path} (open in https://ui.perfetto.dev)")
    return 0


def _fleet_profile(client, addr: str, args, retries: int) -> int:
    """`fleet profile JOB`: fetch the three-clock merge — the
    timeline's host plane joined with the worker's device-profile
    capture and failing-lane virtual trace (whichever the store has;
    the worker records them when run under MADSIM_TPU_XPROF=1)."""
    doc = client.profile(addr, args.job, retries=retries)
    out_path = args.out or f"{args.job}.profile.perfetto.json"
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    summary = doc.get("madsim_xprof_summary", {})
    tracks = summary.get("tracks", {})
    present = ", ".join(k for k in ("host", "device", "virtual")
                        if tracks.get(k)) or "none"
    print(f"profile: {len(doc.get('traceEvents', []))} trace events, "
          f"tracks present: {present}, "
          f"{summary.get('sync_points', 0)} sync points, "
          f"{float(summary.get('attribution') or 0.0) * 100.0:.0f}% "
          f"attributed -> {out_path} (open in https://ui.perfetto.dev)")
    if not (tracks.get("device") or tracks.get("virtual")):
        print("hint: run the worker with MADSIM_TPU_XPROF=1 to record "
              "the device profile and the failing lane's virtual trace")
    return 0


def _fleet_top_render(doc: dict) -> str:
    """One screenful of farm state from a /queue document. Pure
    formatting — jax-free, storeless, testable."""
    counts = doc.get("counts", {})
    head = "fleet top — " + " ".join(
        f"{k}:{counts[k]}" for k in sorted(counts) if counts[k]
    ) if counts else "fleet top — queue empty"
    if doc.get("degraded"):
        head += "  [DEGRADED: index-served while load-shedding]"
    cols = (f"{'JOB':<14} {'STATE':<11} {'MACHINE':<18} {'BATCH':>7} "
            f"{'FAIL':>4} {'SLOTS':>6} {'RUNG':>4} {'MOM':>3} "
            f"{'WORKER':<10} LAST EVENT")
    jobs = doc.get("jobs", [])
    lines = [head]
    farm = doc.get("farm")
    if farm:
        # the contention plane: shed state, index honesty, and each
        # worker's lost claim races / refused zombie writes
        bits = [f"shed:{'YES' if farm.get('shed') else 'no'}"]
        if farm.get("queue_log_lag") is not None:
            bits.append(f"lag:{farm['queue_log_lag']}")
        for wid, ws in sorted((farm.get("workers") or {}).items()):
            bits.append(
                f"{wid}[units:{ws.get('units_done', 0)} "
                f"conflicts:{ws.get('claim_conflicts', 0)} "
                f"fenced:{ws.get('fenced_writes', 0)}]"
            )
        lines.append("farm — " + " ".join(bits))
    lines += [cols] if jobs else []
    for s in jobs:
        mom = s.get("momentum") or {}
        last = s.get("last_event") or {}
        planned = s.get("batches_planned")
        batch = (f"{s.get('batches_run', 0)}/{planned}" if planned
                 else str(s.get("batches_run", 0)))
        lines.append(
            f"{s.get('id', '?'):<14} {s.get('state', '?'):<11} "
            f"{str(s.get('machine', '?'))[:18]:<18} "
            f"{batch:>7} "
            f"{s.get('failing') or 0:>4} "
            f"{s.get('coverage_slots') or 0:>6} "
            f"{s.get('escalation') or 0:>4} "
            f"{'*' if mom.get('active') else '.':>3} "
            f"{str(s.get('worker') or '-')[:10]:<10} "
            f"{last.get('type', '-')}"
        )
    return "\n".join(lines)


def _fleet_top(client, addr: str, args, retries: int) -> int:
    """`fleet top`: a one-screen live farm view rendered purely from
    /queue (momentum and last-event are attached server-side, so this
    verb needs no store access and stays jax-free). `--once` prints a
    single frame for scripts/CI; otherwise redraws every --interval."""
    import time as wall

    while True:
        print(_fleet_top_render(client.queue(addr, retries=retries)),
              flush=True)
        if args.once:
            return 0
        wall.sleep(max(0.2, args.interval))
        print()


def cmd_fleet(args) -> int:
    """The hunt-farm service (madsim_tpu/fleet): a durable job store +
    queue, a lease-based worker that slices jobs into checkpointed
    batch units, and a jax-free HTTP control plane + client verbs.
    Only `fleet worker` touches jax; serve/submit/status/result/cancel/
    queue/watch/timeline/profile/top run on boxes with no accelerator
    stack."""
    sub = args.fleet_cmd
    if sub == "serve":
        from .fleet import api

        return api.serve(args.root, args.addr, port_file=args.port_file,
                         sweep_interval_s=args.sweep_interval)
    if sub == "worker":
        from .fleet.worker import FleetWorker

        driver = None
        if args.driver == "synthetic":
            from .fleet.chaos import synthetic_driver as driver
        worker = FleetWorker(
            args.root,
            worker_id=args.worker_id or f"w{os.getpid()}",
            lease_ttl_s=args.lease_ttl,
            poll_s=args.poll,
            max_attempts=args.max_attempts,
            backoff_base_s=args.backoff_base,
            driver=driver,
            reclaim=not args.no_reclaim,
        )
        return worker.run(drain=args.drain, max_units=args.max_units)
    if sub == "fsck":
        from .fleet import fsck as fsck_mod

        rep = fsck_mod.fsck(
            args.root,
            fix=not args.dry_run,
            reclaim=args.reclaim,
            release_quarantined=args.release_quarantined,
        )
        if args.json:
            print(json.dumps(rep, indent=1, sort_keys=True))
        else:
            print(fsck_mod.render(rep))
        # lint-style exit: 0 clean, 1 when corruption was found (even
        # if a fixing run just quarantined it — the operator should
        # look at the .corrupt files)
        return 1 if rep["corrupt"] else 0
    if sub == "chaos":
        from .fleet import chaos as chaos_mod

        failures = []
        for chaos_seed in range(args.seed, args.seed + max(1, args.sweep)):
            res = chaos_mod.run_chaos(
                chaos_seed,
                profile=args.profile,
                out_dir=args.out,
                real=args.real,
                rounds=args.rounds or None,
                jobs=args.jobs or None,
                keep=args.keep,
                workers=getattr(args, "workers", 1),
            )
            if not res["ok"]:
                failures.append(res)
        if failures:
            for res in failures:
                print(f"chaos seed {res['seed']}: "
                      f"{len(res['violations'])} violation(s)")
            return 1
        n = max(1, args.sweep)
        print(f"fleet chaos: {n} seed(s) ok "
              f"(profile {args.profile}, first seed {args.seed})")
        return 0
    from .fleet import client

    try:
        addr = client.resolve_addr(args.addr, getattr(args, "port_file", None))
        retries = 0 if getattr(args, "no_retry", False) else client.DEFAULT_RETRIES
        if sub == "submit":
            from .fleet.store import SPEC_FIELDS

            spec = {k: getattr(args, k) for k in SPEC_FIELDS}
            out = client.submit(
                addr, spec, priority=args.priority,
                deadline_s=args.deadline,
                tenant=getattr(args, "tenant", None), retries=retries,
            )
            # stdout is exactly the job id — script-composable
            # (`JOB=$(python -m madsim_tpu fleet submit ...)`)
            print(out["id"])
            return 0
        if sub == "status":
            print(json.dumps(
                client.status(addr, args.job, feed=args.feed,
                              wait=getattr(args, "wait", 0) or 0,
                              retries=retries),
                indent=1, sort_keys=True))
            return 0
        if sub == "result":
            doc = client.result(addr, args.job, retries=retries)
            print(json.dumps(doc, indent=1, sort_keys=True))
            return 0 if doc.get("state") != "failed" else 1
        if sub == "cancel":
            print(json.dumps(client.cancel(addr, args.job, retries=retries),
                             indent=1, sort_keys=True))
            return 0
        if sub == "queue":
            print(json.dumps(client.queue(addr, retries=retries),
                             indent=1, sort_keys=True))
            return 0
        if sub == "watch":
            return _fleet_watch(client, addr, args)
        if sub == "timeline":
            return _fleet_timeline(client, addr, args, retries)
        if sub == "profile":
            return _fleet_profile(client, addr, args, retries)
        if sub == "top":
            return _fleet_top(client, addr, args, retries)
        raise AssertionError(f"unhandled fleet verb {sub!r}")
    except (client.FleetClientError, RuntimeError, OSError) as exc:
        print(f"fleet {sub}: {exc}", file=sys.stderr)
        return 1


def cmd_perf(args) -> int:
    """Host wall-clock observatory: run a streaming workload with the
    PerfRecorder active (main() wires `args.perf_timeline = args.out`
    before the command runs) and report what the wall clock went to —
    compile vs blocked-on-device (counters_poll/ring_drain) vs the
    host-side Python between dispatches. The Perfetto timeline +
    verdict print via the shared --perf-timeline epilogue."""
    eng = _build_engine(args)
    agg = _stream_batches(eng, args, purpose="perf")
    st = agg["stats"]
    el = agg["elapsed_s"]
    print(
        f"streamed {agg['completed']} seeds in {el:.1f}s "
        f"({agg['completed'] / max(el, 1e-9):.0f} seeds/s), "
        f"{len(agg['failing'])} failing"
    )
    print(
        f"executor: {st['device_segments']} segments, "
        f"{st['host_syncs']} host syncs, {st['drains']} drains "
        f"(pipelined={st['pipelined']}, donation={st['donation']})"
    )
    if "device_memory" in st:
        mem = st["device_memory"]
        print(
            "device memory: "
            + ", ".join(f"{k}={v}" for k, v in sorted(mem.items()))
        )
    return 0


def _cmd_prof_compile(args) -> int:
    """`prof compile`: the compile autopsy — trace_s / lower_s /
    backend_s per streaming fn at this shape, plus cost_analysis
    flops/bytes and memory_analysis peak bytes, keyed by the same
    `cache_subkey` bench.py warms. One JSON line + a table."""
    import jax

    from .compile_cache import cache_subkey

    eng = _build_engine(args)
    sk = _stream_kwargs(args)
    rows = eng.stream_compile_autopsy(
        batch=args.batch,
        segment_steps=384,
        max_steps=args.max_steps,
        segments_per_dispatch=sk["segments_per_dispatch"],
        donate=sk["donate"],
        mesh=sk.get("mesh"),
    )
    subkey = cache_subkey(
        gates={
            "rng_stream": eng.config.rng_stream,
            "flight_recorder": eng.config.flight_recorder,
            "coverage": eng.config.coverage,
            "provenance": eng.config.provenance,
        },
        lanes=args.batch,
        segment_steps=384,
        devices=sk["mesh"].size if sk.get("mesh") else 1,
    )
    print(json.dumps({
        "metric": "prof_compile_autopsy",
        "machine": args.machine,
        "platform": jax.devices()[0].platform,
        "cache_subkey": subkey,
        "lanes": args.batch,
        "fns": rows,
    }))
    hdr = f"{'fn':<14}{'trace_s':>9}{'lower_s':>9}{'backend_s':>11}{'flops':>14}{'bytes':>14}{'peak_bytes':>12}"
    print(hdr)
    for r in rows:
        print(
            f"{r['label']:<14}{r['trace_s']:>9.3f}{r['lower_s']:>9.3f}"
            f"{r['backend_s']:>11.3f}"
            f"{(r['flops'] if r['flops'] is not None else float('nan')):>14.3g}"
            f"{(r['bytes_accessed'] if r['bytes_accessed'] is not None else float('nan')):>14.3g}"
            f"{(r['peak_bytes'] if r['peak_bytes'] is not None else 0):>12}"
        )
    tot = {k: sum(r[k] for r in rows) for k in ("trace_s", "lower_s", "backend_s")}
    bound = max(tot, key=lambda k: tot[k])
    print(
        f"total: trace {tot['trace_s']:.3f}s, lower {tot['lower_s']:.3f}s, "
        f"backend {tot['backend_s']:.3f}s -> {bound.split('_')[0]}-dominated "
        f"(subkey {subkey})"
    )
    return 0


def cmd_prof(args) -> int:
    """The three-clock profiler (madsim_tpu/perf/xprof.py): stream a
    hunt batch with MADSIM_TPU_XPROF on — device-phase TraceAnnotations,
    clock-sync markers at dispatch/poll boundaries, a jax.profiler
    device capture — and, with --merge, align host wall-clock spans,
    the device profile and the failing lane's virtual-time trace into
    ONE Perfetto session. `prof compile` prints the per-stage compile
    autopsy instead."""
    import tempfile

    from .perf import xprof
    from .perf.recorder import PerfRecorder

    if getattr(args, "action", None) == "compile":
        return _cmd_prof_compile(args)

    # the gate must be on before any stream fn is traced; _stream_fns
    # keys its cache on it, so this process re-traces with the scopes in
    os.environ[xprof.ENV_GATE] = "1"
    eng = _build_engine(args)
    sk = _stream_kwargs(args)
    logdir = args.profile_dir or tempfile.mkdtemp(prefix="madsim-xprof-")
    rec = PerfRecorder(meta={
        "cmd": "prof", "machine": args.machine, "seeds": args.seeds,
        "batch": args.batch,
    })
    # recorder INSIDE the capture: the profiler's stop/export cost (a
    # multi-MB artifact parse+write) stays off the hunt's wall clock,
    # so the attribution fraction measures the hunt, not the profiler
    with xprof.device_trace(logdir):
        with rec:
            out = eng.run_stream(
                args.seeds, batch=args.batch, seed_start=args.seed,
                max_steps=args.max_steps, **sk,
            )
    wall_s = rec.wall_us / 1e6
    print(
        f"streamed {out['completed']} seeds in {wall_s:.1f}s "
        f"({out['completed'] / max(wall_s, 1e-9):.0f} seeds/s), "
        f"{len(out['failing'])} failing"
    )
    artifact = xprof.find_device_trace(logdir)
    dev = xprof.load_device_events(artifact) if artifact else []
    if dev:
        print(f"device profile: {len(dev)} events ({artifact})")
    else:
        print("device profile: no artifact (backend without profiler export)")

    if not args.merge:
        n = rec.write(args.out)
        print(
            f"host timeline: {n} spans -> {args.out} "
            f"(pass --merge for the three-clock plane)"
        )
        print(f"host verdict: {rec.verdict()}")
        return 0

    # virtual-time track: the failing lane when the hunt surfaced one,
    # else the batch's first seed — timestamps stay in VIRTUAL µs
    vseed = args.trace_seed
    if vseed is None:
        vseed = out["failing"][0][0] if out["failing"] else args.seed
    from .engine import replay
    from .engine.trace_export import trace_event_dict

    rp = replay(eng, int(vseed), max_steps=args.max_steps)
    vdoc = trace_event_dict(
        rp.trace, machine=args.machine, seed=int(vseed),
        num_nodes=eng.machine.NUM_NODES,
    )
    doc = xprof.merge_plane(
        rec.chrome_trace(), dev, vdoc,
        meta={"machine": args.machine, "virtual_seed": int(vseed)},
    )
    n = xprof.write_doc(doc, args.out)
    s = doc["madsim_xprof_summary"]
    print(json.dumps({"metric": "prof_merge", **s}))
    tracks = "+".join(k for k, v in s["tracks"].items() if v)
    print(
        f"merged plane: {n} events ({tracks}), "
        f"{100 * s['attribution']:.0f}% of {s['host_wall_us'] / 1e6:.1f}s "
        f"wall attributed across {s['sync_points']} sync points "
        f"-> {args.out} (open in https://ui.perfetto.dev)"
    )
    return 0


_AB_GATES = ("flight_recorder", "coverage", "provenance", "clog-packed",
             "rng-stream", "coverage-unbuffered")


def cmd_bench_ab(args) -> int:
    """Interleaved A/B cost of ONE engine gate: ABAB… alternating reps
    in one process over identical seed ranges, median of PAIRED deltas
    with a seeded-bootstrap 95% CI and an exact sign test
    (madsim_tpu/perf/ab.py) — the protocol that replaced the one-rep
    step_cost after it misread the provenance gate by 13x on this
    drifting box (PR 7's receipt: 8% single-rep vs 0.61% interleaved).
    Prints one JSON line + a human summary."""
    import jax

    from .engine import Engine
    from .perf.ab import interleaved_ab
    from .perf.recorder import current_recorder

    eng = _build_engine(args)
    base = eng.config
    if args.gate == "rng-stream":
        cfg_a = dataclasses.replace(base, rng_stream=3)
        cfg_b = dataclasses.replace(base, rng_stream=2)
        label_a, label_b = "rng_stream=3", "rng_stream=2"
    elif args.gate == "coverage-unbuffered":
        # the r12 escape hatch's own cost: the flush-on-freeze buffered
        # fold (cov_buffer default) vs the old per-event map scatter
        # (cov_buffer=0) with coverage ON in both — final maps are
        # bit-identical, so the delta is pure fold mechanics
        cfg_a = dataclasses.replace(base, coverage=True)
        cfg_b = dataclasses.replace(base, coverage=True, cov_buffer=0)
        label_a, label_b = "cov_buffer=on", "cov_buffer=0"
    else:
        field = args.gate.replace("-", "_")
        cfg_a = dataclasses.replace(base, **{field: True})
        cfg_b = dataclasses.replace(base, **{field: False})
        label_a, label_b = f"{field}=on", f"{field}=off"
    lanes = args.lanes or 1024
    n_rep = args.seeds or 2 * lanes
    sk = _stream_kwargs(args)
    runs = {}
    for tag, cfg in (("a", cfg_a), ("b", cfg_b)):
        run = Engine(eng.machine, cfg).make_stream_runner(
            batch=lanes, segment_steps=384, max_steps=args.max_steps, **sk
        )
        # compile + one full untimed rep: the harness measures steady
        # state, never compilation or a cold first rep
        run(1)
        run(n_rep, seed_start=500_000)
        runs[tag] = run

    res = interleaved_ab(
        lambda s: runs["a"](n_rep, seed_start=s)["completed"],
        lambda s: runs["b"](n_rep, seed_start=s)["completed"],
        pairs=args.reps,
        seed_start=args.seed,
        seeds_per_rep=4 * n_rep,
        label_a=label_a,
        label_b=label_b,
        recorder=current_recorder(),
    )
    print(json.dumps({
        "metric": f"{args.gate}_ab_delta_pct",
        "gate": args.gate,
        "machine": args.machine,
        "platform": jax.devices()[0].platform,
        "lanes": lanes,
        "seeds_per_rep": n_rep,
        **res.to_dict(),
    }))
    print(res.summary())
    return 0


def _cmd_bench_report(args) -> int:
    """`bench report`: render the BENCH_HISTORY.jsonl trend (seeding it
    from the legacy BENCH_r*.json series when absent). Pure stdlib — no
    jax, works on a box with no accelerator stack."""
    from .perf import history

    path = args.history or history.DEFAULT_BASENAME
    rows = history.load_or_seed(path)
    print(history.render_report(rows))
    return 0


def cmd_bench(args) -> int:
    if getattr(args, "action", None) == "report":
        return _cmd_bench_report(args)
    if args.lanes < 0 or args.reps < 1 or args.seeds < 1:
        sys.exit("bench needs --lanes >= 1 (or 0 = default), --reps >= 1, --seeds >= 1")
    if not getattr(args, "machine", None):
        import bench  # repo-root bench.py when run from checkout

        argv = ["bench.py"]
        if args.lanes or args.reps != 3:
            argv.append(str(args.lanes or 8192))
        if args.reps != 3:
            argv.append(str(args.reps))
        sys.argv = argv
        bench.main()
        return 0

    # per-machine throughput: stream `--seeds` with the same statistical
    # discipline as the flagship bench (compile + warm, median of reps)
    import statistics
    import time as wall

    import jax

    eng = _build_engine(args)
    lanes = args.lanes or 8192
    n = max(args.seeds, lanes)
    run = eng.make_stream_runner(
        batch=lanes, segment_steps=384, max_steps=args.max_steps,
        **_stream_kwargs(args),
    )
    run(64)
    run(n, seed_start=500_000)
    rates = []
    fails = 0
    out = None
    for r in range(args.reps):
        t0 = wall.perf_counter()
        out = run(n, seed_start=args.seed + r * 4 * n)
        rates.append(out["completed"] / (wall.perf_counter() - t0))
        fails += len(out["failing"]) + len(out["infra"])
    st = out["stats"]
    print(json.dumps({
        "metric": f"{args.machine}_seeds_per_sec",
        "value": round(statistics.median(rates), 1),
        "unit": "seeds/sec",
        "platform": jax.devices()[0].platform,
        "diagnostics": {
            "reps": [round(x, 1) for x in rates],
            "failing_total": fails,
            "lanes": lanes,
            "queue_capacity": args.queue,
            "fault_kinds": getattr(args, "fault_kinds", "pair,kill"),
            "host_syncs": st["host_syncs"],
            "device_segments": st["device_segments"],
            "dispatch_depth": st["dispatch_depth"],
            "segments_per_dispatch": st["segments_per_dispatch"],
            "donation": st["donation"],
            "pipelined": st["pipelined"],
        },
    }))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="madsim_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    def obs_flags(p):
        """Observability flags (every subcommand): logging + recorder."""
        p.add_argument(
            "--log-level", default=os.environ.get("MADSIM_TPU_LOG"),
            help="wire init_tracing at this level (DEBUG/INFO/...; also "
            "$MADSIM_TPU_LOG) — log lines carry the sim span context",
        )
        p.add_argument(
            "--log-jsonl", default=None, metavar="PATH",
            help="also sink logs as structured JSONL to PATH",
        )

    def common(p):
        obs_flags(p)
        p.add_argument("--machine", default="raft")
        p.add_argument("--nodes", type=int, default=0)
        p.add_argument("--seed", type=int, default=0)
        p.add_argument("--horizon", type=float, default=5.0, help="virtual seconds")
        p.add_argument("--queue", type=int, default=96)
        p.add_argument("--faults", type=int, default=2)
        p.add_argument("--loss", type=float, default=0.0)
        p.add_argument("--max-steps", type=int, default=3000)
        p.add_argument(
            "--fault-tmax", type=int, default=0,
            help="fault injection window in us (0 = 60%% of horizon)",
        )
        p.add_argument(
            "--fault-kinds", default="pair,kill",
            help="comma list of fault kinds to draw from: "
            "pair,kill,dir,group,storm,delay,pause,skew,dup,torn,"
            "heal-asym (default pair,kill; any other kind switches to "
            "the v2 schedule derivation; dup is per-delivery Bernoulli "
            "duplication, not a scheduled window; torn restarts damage "
            "durable state per Machine.torn_spec(); heal-asym "
            "partitions heal one direction at a time)",
        )
        p.add_argument(
            "--strict-restart", action="store_true",
            help="crash-with-amnesia restarts: a restarted node keeps "
            "ONLY the leaves its Machine.durable_spec() contract marks "
            "durable — the engine wipes the rest generically, so "
            "illegally-kept volatile state becomes findable",
        )
        p.add_argument(
            "--rng-stream", type=int, default=2, choices=(2, 3),
            help="per-step RNG stream version: 2 = legacy split-chain "
            "(default; replays every recorded seed), 3 = counter-based "
            "(one threefry per event — faster; new hunts should use it; "
            "corpus entries record the version either way)",
        )
        p.add_argument(
            "--compile-cache", default=os.environ.get("MADSIM_TPU_COMPILE_CACHE"),
            help="JAX persistent compilation cache directory (also "
            "$MADSIM_TPU_COMPILE_CACHE): pay each compile once per "
            "machine, not once per process",
        )
        p.add_argument(
            "--flight-recorder", action="store_true",
            help="engine flight recorder: rolling per-lane trace digests "
            "+ checkpoint ring + on-device fault/queue metrics (results "
            "are bit-identical either way; see `audit`)",
        )
        p.add_argument(
            "--coverage", action="store_true",
            help="scenario-coverage telemetry: per-lane AFL-style hit "
            "maps over (model abstract state, event kind, fault "
            "context), OR-reduced on device at stream harvest (results "
            "are bit-identical either way; enables --stop-on-plateau "
            "and `coverage` reports)",
        )
        p.add_argument(
            "--cov-buffer", type=int, default=None, metavar="N",
            help="coverage slot-buffer depth per lane (default: engine "
            "default; 0 = unbuffered escape hatch, the per-event map "
            "scatter — final maps are bit-identical either way)",
        )
        p.add_argument(
            "--provenance", action="store_true",
            help="causal provenance: every queued event and node "
            "carries a 32-bit lineage word (one bit per scheduled "
            "fault, ORed along deliveries); failures decode to the "
            "implicated faults in hunt reports, shrink uses attribution "
            "to order its candidates, and `why` renders the causal "
            "chain (results are bit-identical either way)",
        )
        p.add_argument(
            "--stats", default=None, metavar="BASE",
            help="StatsEmitter base path (also $MADSIM_TPU_STATS): "
            "stream per-batch stats to BASE.jsonl + Prometheus textfile "
            "BASE.prom + latest-snapshot BASE.json (what `serve "
            "--service stats` exposes)",
        )

    def stream_flags(p):
        """Pipelined streaming-executor knobs (explore/hunt/bench)."""
        p.add_argument(
            "--no-pipeline", action="store_true",
            help="use the r5 per-segment driver (one blocking host sync "
            "per segment) instead of the pipelined executor",
        )
        p.add_argument(
            "--segments-per-dispatch", type=int, default=8,
            help="segments fused into one device dispatch (supersegment)",
        )
        p.add_argument(
            "--dispatch-depth", type=int, default=4,
            help="async dispatches in flight between blocking counter polls",
        )
        p.add_argument(
            "--no-donate", action="store_true",
            help="disable StreamCarry buffer donation (keeps the r5 "
            "copy-per-call behavior; results are bit-identical either way)",
        )
        p.add_argument(
            "--devices", type=int, default=0, metavar="N",
            help="span the hunt over the first N devices as one jitted "
            "SPMD program (a 1-D 'batch' mesh; lane leaves sharded, "
            "global leaves replicated). Results are byte-identical at "
            "any N; batch must be a multiple of N. 0 = unsharded "
            "single-device path (the default). On a CPU-only box, "
            "force virtual devices with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N",
        )
        p.add_argument(
            "--stop-on-plateau", type=int, default=0, metavar="N",
            help="with --coverage: stop the run early when N consecutive "
            "seed batches add zero new coverage slots (the saturation "
            "signal — more seeds are no longer finding new scenarios); "
            "reported honestly in the summary",
        )
        p.add_argument(
            "--stop-after-batches", type=int, default=0, metavar="N",
            help="deliberately stop after N seed batches (the run stays "
            "resumable via --checkpoint; CI's interrupt/resume smoke and "
            "'hunt in slices' both use this)",
        )
        p.add_argument(
            "--perf-timeline", default=None, metavar="PATH",
            help="record the HOST wall-clock timeline of this run "
            "(compile/dispatch/counters_poll/ring_drain/checkpoint/"
            "stats spans + dispatch-gap idle accounting) as Chrome/"
            "Perfetto trace_event JSON at PATH, with a bound verdict "
            "(compile- vs device- vs dispatch-gap-bound) printed after "
            "the run — the real-time complement of `trace`'s "
            "virtual-time view",
        )
        p.add_argument(
            "--xla-profile", default=None, metavar="DIR",
            help="additionally wrap the run in jax.profiler.trace(DIR) "
            "— a device/XLA-level profile for tensorboard/xprof "
            "(heavier than --perf-timeline; opt-in)",
        )

    p = sub.add_parser("explore", help="run a seed batch, report failing seeds")
    common(p)
    p.add_argument("--seeds", type=int, default=1024)
    p.add_argument(
        "--stream", action="store_true",
        help="seed-streaming path (refill finished lanes; for large batches)",
    )
    p.add_argument("--batch", type=int, default=8192, help="lanes per streaming batch")
    stream_flags(p)
    p.add_argument(
        "--multihost", action="store_true",
        help="shard the batch over a jax.distributed job "
             "(MADSIM_TPU_COORDINATOR/NUM_PROCS/PROC_ID env vars)",
    )
    p.set_defaults(fn=cmd_explore)

    p = sub.add_parser("replay", help="bit-identical replay of one seed with trace")
    common(p)
    p.add_argument("--tail", type=int, default=30, help="print last N events (0=all)")
    p.add_argument(
        "--devices", type=int, default=0,
        help="accepted for repro-line fidelity (hunts record the mesh "
        "size they ran at); replay is single-lane and byte-identical "
        "at any device count, so the value is recorded but unused",
    )
    p.add_argument(
        "--diff-seed", type=int, default=None,
        help="also replay this seed and print where the two event "
        "schedules first diverge (debugging: failing seed vs its "
        "nearest passing neighbor)",
    )
    p.add_argument("--diff-context", type=int, default=3,
                   help="events of context around the divergence")
    p.set_defaults(fn=cmd_replay)

    p = sub.add_parser(
        "trace",
        help="replay one seed and export its virtual-time event timeline "
        "(Perfetto trace_event JSON / structured JSONL)",
    )
    common(p)
    p.add_argument(
        "--perfetto", default=None, metavar="PATH",
        help="write Chrome/Perfetto trace_event JSON (one thread row per "
        "node, instants at virtual microseconds; open in ui.perfetto.dev)",
    )
    p.add_argument(
        "--jsonl", default=None, metavar="PATH",
        help="write one JSON object per event (grep/jq-able)",
    )
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("shrink", help="minimize a failing seed's config")
    common(p)
    p.set_defaults(fn=cmd_shrink)

    p = sub.add_parser(
        "why",
        help="explain a failing seed: replay with causal provenance, "
        "name the implicated faults (kind, time, target), and render "
        "the violation's past cone as text / JSON / Perfetto flows",
    )
    common(p)
    p.add_argument(
        "seed_pos", nargs="?", type=int, default=None, metavar="SEED",
        help="the failing seed (equivalent to --seed; pass the repro "
        "line's remaining flags so the schedule matches)",
    )
    p.add_argument(
        "--tail", type=int, default=30,
        help="cone events to print (0 = the whole cone)",
    )
    p.add_argument(
        "--out", default=None, metavar="PATH",
        help="also write the rendered causal chain to PATH",
    )
    p.add_argument(
        "--json", default=None, metavar="PATH",
        help="write machine-readable attribution JSON "
        "(implicated kinds/faults, prov word, cone size)",
    )
    p.add_argument(
        "--perfetto", default=None, metavar="PATH",
        help="write the timeline with causal flow arrows and the past "
        "cone highlighted (args.cone=true; open in ui.perfetto.dev)",
    )
    p.set_defaults(fn=cmd_why)

    p = sub.add_parser(
        "hunt", help="explore + shrink + record failing seeds in the corpus"
    )
    common(p)
    p.add_argument("--seeds", type=int, default=1024)
    p.add_argument("--stream", action="store_true", help="seed-streaming hunt")
    p.add_argument("--batch", type=int, default=8192, help="lanes per streaming batch")
    stream_flags(p)
    p.add_argument("--corpus", default="corpus.json")
    p.add_argument("--limit", type=int, default=5, help="max seeds to shrink+record")
    p.add_argument(
        "--checkpoint", default=None, metavar="PATH",
        help="with --stream: persist per-batch progress (seed cursor, "
        "failures, coverage map, plateau state) to PATH after every "
        "batch; an interrupted hunt re-run with the same arguments "
        "resumes exactly where it stopped ('resumed at batch k/n')",
    )
    p.add_argument(
        "--coverage-out", default=None, metavar="PATH",
        help="with --coverage --stream: persist the hunt's cumulative "
        "coverage map as JSON for cross-run diffing "
        "(`madsim_tpu coverage PATH --diff OLD`)",
    )
    p.add_argument(
        "--all-seeds",
        action="store_true",
        help="shrink the first --limit failing seeds even when they share "
        "a fail code (default: one representative per distinct code)",
    )
    p.add_argument(
        "--guided", action="store_true",
        help="coverage-feedback search (needs --stream --coverage): "
        "every batch's seed vector is chosen — half mutated children "
        "of seeds that hit new coverage slots (candidates scored by a "
        "bias state fed from the live map's thin bands and, with "
        "--provenance, the fault kinds in failure lineages), half "
        "fresh sequential exploration; with --stop-on-plateau N a "
        "plateau escalates the fault vocabulary along the recorded "
        "ladder instead of stopping. The (seed schedule, bias state) "
        "trail is recorded in the checkpoint and stats feed, so a "
        "guided hunt resumes and replays byte-identically; guidance "
        "off is bit-identical to the unguided streaming path",
    )
    p.set_defaults(fn=cmd_hunt)

    p = sub.add_parser(
        "regress",
        help="re-verify every corpus entry (open must reproduce, fixed must pass)",
    )
    obs_flags(p)
    p.add_argument("--corpus", default="corpus.json")
    p.add_argument(
        "--promote", action="store_true",
        help="flip open entries that no longer fail to fixed",
    )
    p.set_defaults(fn=cmd_regress)

    p = sub.add_parser(
        "audit",
        help="bisect every corpus entry's recorded digest trail to the "
        "first divergent checkpoint (corpus-rot diagnosis); --record "
        "re-records trails + env metadata at HEAD",
    )
    obs_flags(p)
    p.add_argument("--corpus", default="corpus.json")
    p.add_argument(
        "--record", action="store_true",
        help="re-record digest trails (refuses entries whose outcome "
        "broke their status contract)",
    )
    p.add_argument(
        "--digest-every", type=int, default=64,
        help="checkpoint cadence in steps when recording",
    )
    p.set_defaults(fn=cmd_audit)

    p = sub.add_parser("check", help="engine determinism self-check")
    common(p)
    p.add_argument("--seeds", type=int, default=64)
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser(
        "bench",
        help="flagship benchmark (one JSON line); with --machine, a "
        "streaming throughput bench of any registered machine; "
        "`bench report` renders the BENCH_HISTORY.jsonl trend (jax-free)",
    )
    common(p)  # one source of truth for the engine flags
    p.add_argument(
        "action", nargs="?", choices=("report",), default=None,
        help="report: render the drift-aware bench history trend "
        "(per-capture delta vs its own comparable neighbor — same "
        "platform/lanes/gates/host; seeds the history from the legacy "
        "BENCH_r*.json series on first use)",
    )
    p.add_argument("--lanes", type=int, default=0)
    p.add_argument("--seeds", type=int, default=16384, help="seeds per rep")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument(
        "--history", default=None, metavar="PATH",
        help="bench history JSONL to render/append "
        "(default ./BENCH_HISTORY.jsonl)",
    )
    stream_flags(p)
    # bench-specific defaults: no machine = the flagship bench.py, and
    # timed seed ranges start clear of the validation sweeps
    p.set_defaults(fn=cmd_bench, machine=None, seed=1_000_000)

    p = sub.add_parser(
        "bench-ab",
        help="interleaved A/B cost of one engine gate: ABAB… paired "
        "reps over identical seed ranges in one process; median paired "
        "delta with bootstrap 95%% CI + sign test (one JSON line). The "
        "drift-robust replacement for single-rep gate costing",
    )
    common(p)
    p.add_argument(
        "--gate", required=True, choices=_AB_GATES,
        help="the gate to cost: A runs it on, B off (rng-stream: "
        "A=v3 vs B=v2); every other engine flag comes from the usual "
        "options, so you can cost a gate on top of any configuration",
    )
    p.add_argument("--lanes", type=int, default=1024, help="lanes per streaming batch")
    p.add_argument(
        "--seeds", type=int, default=0,
        help="seeds per rep (0 = 2*lanes)",
    )
    p.add_argument(
        "--reps", type=int, default=4, metavar="PAIRS",
        help="A/B rep PAIRS (4 pairs ≈ the PR-7 hand protocol; 2 is "
        "the CI smoke minimum)",
    )
    stream_flags(p)
    p.set_defaults(fn=cmd_bench_ab, seed=3_000_000)

    p = sub.add_parser(
        "perf",
        help="host wall-clock observatory: stream a workload with the "
        "PerfRecorder on and write the Perfetto host timeline "
        "(compile/dispatch/poll/drain spans + dispatch-gap idle), with "
        "a compile- vs device- vs dispatch-gap-bound verdict",
    )
    common(p)
    p.add_argument("out", help="host-timeline Perfetto JSON output path")
    p.add_argument("--seeds", type=int, default=2048)
    p.add_argument("--batch", type=int, default=512, help="lanes per streaming batch")
    stream_flags(p)
    p.set_defaults(fn=cmd_perf)

    p = sub.add_parser(
        "prof",
        help="the three-clock profiler: stream a hunt batch with "
        "device-phase annotations + a jax.profiler capture on "
        "(MADSIM_TPU_XPROF), and with --merge align host spans, the "
        "device profile and a failing lane's virtual-time trace into "
        "one Perfetto session; `prof compile` prints the per-stage "
        "compile autopsy (trace/lower/backend + flops/bytes)",
    )
    common(p)
    p.add_argument(
        "action", nargs="?", choices=("compile",), default=None,
        help="compile: autopsy the streaming quartet's compile at this "
        "shape instead of running a profiled stream",
    )
    p.add_argument(
        "--out", default="prof.perfetto.json",
        help="output trace path (host timeline, or the merged "
        "three-clock plane with --merge; .gz compresses)",
    )
    p.add_argument(
        "--merge", action="store_true",
        help="write ONE merged Perfetto session: host + device + "
        "virtual tracks, clock-sync aligned",
    )
    p.add_argument("--seeds", type=int, default=2048)
    p.add_argument("--batch", type=int, default=512, help="lanes per streaming batch")
    p.add_argument(
        "--trace-seed", type=int, default=None,
        help="seed for the virtual-time track (default: first failing "
        "seed of the profiled batch, else --seed)",
    )
    p.add_argument(
        "--profile-dir", default=None, metavar="DIR",
        help="keep the raw jax.profiler logdir here (default: a "
        "throwaway tempdir)",
    )
    stream_flags(p)
    p.set_defaults(fn=cmd_prof)

    p = sub.add_parser(
        "coverage",
        help="render a persisted scenario-coverage map (total %%, "
        "per-band marginals, thinnest fault x phase cells, per-model "
        "breakdown); --diff OLD shows what a run added over another",
    )
    p.add_argument("doc", help="coverage JSON written by `hunt --coverage-out`")
    p.add_argument(
        "--diff", default=None, metavar="OLD",
        help="baseline coverage doc to diff against (new/lost/shared slots)",
    )
    p.add_argument("--top", type=int, default=8,
                   help="thinnest band x phase cells to list")
    p.add_argument(
        "--json", action="store_true",
        help="machine-readable output: per-map slots/by-band summary "
        "plus the thinnest-cell table (the same "
        "runtime/coverage.top_uncovered artifact the guided-search "
        "bias layer reads)",
    )
    p.set_defaults(fn=cmd_coverage)

    p = sub.add_parser(
        "serve",
        help="run an L5 service over real TCP (MADSIM_TPU_MODE=real); "
        "pickle wire format — trusted networks only. `--service stats` "
        "serves the last run's StatsEmitter snapshot over HTTP instead "
        "(/stats JSON + /metrics Prometheus; any mode)",
    )
    p.add_argument("--service", default="etcd",
                   choices=["etcd", "kafka", "s3", "stats"])
    p.add_argument("--addr", default="127.0.0.1:23790", help="host:port (port 0 = ephemeral)")
    p.add_argument(
        "--stats", default=None, metavar="BASE",
        help="stats service only: StatsEmitter base path to serve "
        "(default $MADSIM_TPU_STATS or ./madsim_stats)",
    )
    p.add_argument(
        "--port-file", default=None, metavar="PATH",
        help="stats service only: atomically write the realized port to "
        "PATH after binding (with --addr host:0, tests and fleet "
        "workers discover the daemon without racing its stdout)",
    )
    p.add_argument(
        "--grpc",
        action="store_true",
        help="etcd only: serve the genuine etcd v3 gRPC wire protocol "
        "(etcdserverpb over grpc.aio) instead of the pickle sim protocol",
    )
    p.add_argument(
        "--http",
        action="store_true",
        help="s3 only: serve the genuine S3 REST wire protocol "
        "instead of the pickle sim protocol",
    )
    p.add_argument(
        "--wire",
        action="store_true",
        help="kafka only: serve the genuine Kafka wire protocol "
        "(ApiVersions/Metadata/Produce/Fetch/group APIs) instead of the "
        "pickle sim protocol",
    )
    p.add_argument(
        "--advertise",
        default=None,
        help="kafka --wire only: hostname to advertise in Metadata/"
        "FindCoordinator responses (defaults to the bind host, or "
        "127.0.0.1 when binding 0.0.0.0)",
    )
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "fleet",
        help="the hunt farm: DST as a continuously operating service — "
        "a durable job store + queue (JSON-on-disk, atomic, "
        "fingerprinted), `worker` (leases jobs, runs checkpointed "
        "batch units packed by warm-compile subkey, shrinks + files "
        "finds), `serve` (jax-free HTTP control plane: POST /jobs, "
        "GET /jobs/{id}[/result|/events|/timeline], DELETE /jobs/{id}, "
        "/queue /metrics /healthz) and thin client verbs, including "
        "the observatory (`watch` SSE tail, `timeline` Perfetto "
        "merge, `top` farm view)",
    )
    fl = p.add_subparsers(dest="fleet_cmd", required=True)

    def fleet_root(q):
        q.add_argument(
            "--root", default=os.environ.get("MADSIM_TPU_FLEET_ROOT", "fleet"),
            help="fleet state directory (jobs/, corpus.json; also "
            "$MADSIM_TPU_FLEET_ROOT)",
        )

    def fleet_client_flags(q):
        q.add_argument(
            "--addr", default=None,
            help="control-plane host:port (default $MADSIM_TPU_FLEET_ADDR "
            "or 127.0.0.1:8142)",
        )
        q.add_argument(
            "--port-file", default=None, metavar="PATH",
            help="resolve the daemon as 127.0.0.1:<port read from PATH> "
            "(the file `fleet serve --port-file` writes atomically)",
        )
        q.add_argument(
            "--no-retry", action="store_true",
            help="fail fast instead of retrying transient HTTP errors "
            "(connection refused during a server restart, 502/503/504) "
            "with seeded-jitter backoff",
        )

    q = fl.add_parser("serve", help="jax-free HTTP control plane over a fleet root")
    obs_flags(q)
    fleet_root(q)
    q.add_argument("--addr", default="127.0.0.1:8142",
                   help="bind host:port (port 0 = ephemeral)")
    q.add_argument(
        "--port-file", default=None, metavar="PATH",
        help="atomically write the realized port to PATH after binding",
    )
    q.add_argument(
        "--sweep-interval", type=float, default=5.0,
        help="seconds between lease-reclamation supervisor sweeps "
        "(expired worker leases requeue their jobs with backoff, or "
        "quarantine at the attempt cap; 0 disables)",
    )
    q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser(
        "worker",
        help="lease jobs and run them one checkpointed batch unit at a "
        "time (kill -9 loses at most one batch; jobs sharing a "
        "cache_subkey run back-to-back on the warm jit)",
    )
    obs_flags(q)
    fleet_root(q)
    q.add_argument("--worker-id", default=None,
                   help="stable lease identity (default w<pid>; reusing an "
                   "id reclaims its own leases immediately after a crash)")
    q.add_argument("--lease-ttl", type=float, default=60.0,
                   help="seconds before a dead worker's jobs become "
                   "reclaimable")
    q.add_argument("--poll", type=float, default=0.5,
                   help="idle store-poll interval in seconds")
    q.add_argument("--drain", action="store_true",
                   help="exit once every job is terminal (CI/batch mode) "
                   "instead of serving forever")
    q.add_argument("--max-units", type=int, default=0,
                   help="exit after N work units (deterministic "
                   "interruption for tests; 0 = unlimited)")
    q.add_argument(
        "--compile-cache", default=os.environ.get("MADSIM_TPU_COMPILE_CACHE"),
        help="JAX persistent compilation cache directory (also "
        "$MADSIM_TPU_COMPILE_CACHE) — a warm cache makes a fresh "
        "worker productive in seconds",
    )
    q.add_argument(
        "--perf-timeline", default=None, metavar="PATH",
        help="record the worker's host timeline (per-unit fleet_unit "
        "spans with job ids wrapping the usual compile/dispatch/poll "
        "spans) as Perfetto trace_event JSON",
    )
    q.add_argument(
        "--max-attempts", type=int, default=3,
        help="consecutive deaths/hard failures before a job is "
        "quarantined as poison (exception + batch index + repro "
        "recorded on the job)",
    )
    q.add_argument(
        "--backoff-base", type=float, default=2.0,
        help="requeue backoff base: a job that died attempt k waits "
        "base * 2^(k-1) seconds before it can be leased again",
    )
    q.add_argument(
        "--no-reclaim", action="store_true",
        help="skip the lease-reclamation sweep at each poll (rely on "
        "`fleet serve`'s supervisor thread / `fleet fsck --reclaim`)",
    )
    q.add_argument(
        "--driver", choices=("real", "synthetic"), default="real",
        help="'synthetic' replaces the jitted streaming path with the "
        "jax-free deterministic stand-in (chaos harness / farm tests "
        "only: same checkpoint+stats machinery, no engine)",
    )
    q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser("submit", help="submit a hunt job; prints the job id")
    obs_flags(q)
    fleet_client_flags(q)
    q.add_argument("--machine", required=True)
    q.add_argument("--nodes", type=int, default=0)
    q.add_argument("--seed", type=int, default=0, help="seed-range start")
    q.add_argument("--seeds", type=int, default=1024, help="seed budget")
    q.add_argument("--batch", type=int, default=256,
                   help="lanes per batch unit (the checkpoint granularity)")
    q.add_argument("--horizon", type=float, default=5.0)
    q.add_argument("--max-steps", type=int, default=3000)
    q.add_argument("--queue", type=int, default=96)
    q.add_argument("--faults", type=int, default=2)
    q.add_argument("--loss", type=float, default=0.0)
    q.add_argument("--fault-tmax", type=int, default=0)
    q.add_argument("--fault-kinds", default="pair,kill")
    q.add_argument("--rng-stream", type=int, default=2, choices=(2, 3))
    q.add_argument("--strict-restart", action="store_true")
    q.add_argument("--coverage", action="store_true")
    q.add_argument("--provenance", action="store_true")
    q.add_argument("--flight-recorder", action="store_true")
    q.add_argument("--stop-on-plateau", type=int, default=0)
    q.add_argument(
        "--guided", action="store_true",
        help="coverage-feedback search (needs --coverage): the worker "
        "evolves this job's seed corpus AFL-style, biases fault draws "
        "toward thin coverage cells / implicated kinds, and escalates "
        "the vocabulary on plateau; the (seed schedule, bias state) "
        "trail rides the job checkpoint, so interrupt/resume and "
        "worker replacement reproduce byte-identically",
    )
    q.add_argument("--shrink-limit", type=int, default=5,
                   help="max distinct-code finds to shrink + file")
    q.add_argument(
        "--devices", type=int, default=0, metavar="N",
        help="span each batch unit over the first N devices as one "
        "jitted SPMD program (the lane-axis mesh; 0 = unsharded). "
        "Part of the warm-compile grouping key",
    )
    q.add_argument("--priority", type=int, default=0,
                   help="higher runs earlier (and may pay a compile switch)")
    q.add_argument("--deadline", type=float, default=None,
                   help="relative deadline in wall seconds; the worker "
                   "stops the job when it passes")
    q.add_argument(
        "--tenant", default=None,
        help="admission-accounting identity: the server's per-tenant "
        "token bucket ($MADSIM_TPU_FLEET_RATE_LIMIT) charges this name; "
        "a 429 refusal names it and the client retries after the "
        "server's Retry-After",
    )
    q.set_defaults(fn=cmd_fleet)

    for verb, hlp in (
        ("status", "job document + live per-batch feed"),
        ("result", "find + shrunk repro + why attribution (terminal jobs)"),
        ("cancel", "cancel a job (queued dies now; running at the next "
                   "unit boundary)"),
    ):
        q = fl.add_parser(verb, help=hlp)
        obs_flags(q)
        fleet_client_flags(q)
        q.add_argument("job", help="job id (from `fleet submit`)")
        if verb == "status":
            q.add_argument("--feed", type=int, default=20,
                           help="live-feed rows to include")
            q.add_argument(
                "--wait", type=float, default=0, metavar="S",
                help="long-poll: the server holds the request up to S "
                "seconds (capped server-side) and answers as soon as "
                "the job document or its stats feed changes — clients "
                "stop busy-polling GET /jobs/{id}",
            )
        q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser("queue", help="state counts + per-job summaries")
    obs_flags(q)
    fleet_client_flags(q)
    q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser(
        "watch",
        help="tail a job's lifecycle event stream over SSE (push, not "
        "poll: the server parks between events), one line per event; "
        "exits 0 when the job reaches a terminal state",
    )
    obs_flags(q)
    fleet_client_flags(q)
    q.add_argument("job", help="job id (from `fleet submit`)")
    q.add_argument("--since", type=int, default=0, metavar="SEQ",
                   help="resume the tail after event SEQ (0 = replay "
                   "the full event log first)")
    q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser(
        "timeline",
        help="merge the job's lifecycle events with every worker's "
        "span dump (correlated by job id as trace id) into one "
        "Perfetto timeline: queue-wait, per-batch progress and worker "
        "internals on a shared wall clock",
    )
    obs_flags(q)
    fleet_client_flags(q)
    q.add_argument("job", help="job id (from `fleet submit`)")
    q.add_argument("--out", default=None, metavar="PATH",
                   help="output trace path (default "
                   "<job>.timeline.perfetto.json)")
    q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser(
        "profile",
        help="the three-clock merge for a job: host timeline + the "
        "worker's device-profile capture + the failing lane's "
        "virtual-time trace (recorded when the worker runs under "
        "MADSIM_TPU_XPROF=1), aligned by xprof clock-sync markers "
        "into one Perfetto session",
    )
    obs_flags(q)
    fleet_client_flags(q)
    q.add_argument("job", help="job id (from `fleet submit`)")
    q.add_argument("--out", default=None, metavar="PATH",
                   help="output trace path (default "
                   "<job>.profile.perfetto.json)")
    q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser(
        "top",
        help="one-screen farm view rendered from /queue (state counts, "
        "per-job batch/find/coverage/escalation progress, momentum, "
        "lease holder, last event) — jax-free, needs only the HTTP "
        "control plane",
    )
    obs_flags(q)
    fleet_client_flags(q)
    q.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="seconds between redraws")
    q.add_argument("--once", action="store_true",
                   help="print a single frame and exit (scripts/CI)")
    q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser(
        "fsck",
        help="scan the job store + fleet corpus for truncated/"
        "unparseable/fingerprint-inconsistent files, quarantine them "
        "to *.corrupt with a per-file verdict, remove stale atomic-"
        "write tmp files, and rebuild the queue counts; exit 0 clean "
        "/ 1 corruption found",
    )
    obs_flags(q)
    fleet_root(q)
    q.add_argument("--dry-run", action="store_true",
                   help="scan + report only; quarantine/remove nothing")
    q.add_argument("--reclaim", action="store_true",
                   help="also run the lease-reclamation sweep (requeue "
                   "jobs whose worker lease expired, or quarantine at "
                   "the attempt cap)")
    q.add_argument("--release-quarantined", action="store_true",
                   help="re-queue quarantined jobs (attempt counter "
                   "reset; the quarantine post-mortem stays on the "
                   "doc)")
    q.add_argument("--json", action="store_true",
                   help="machine-readable report instead of text")
    q.set_defaults(fn=cmd_fleet)

    q = fl.add_parser(
        "chaos",
        help="attack a scratch farm with a seeded schedule of process-"
        "level faults (SIGKILL worker/server at the k-th store write, "
        "torn in-flight writes, lease-clock jumps, client calls "
        "through a bounced server) and assert the recovery "
        "invariants: no accepted job lost, every resumed job's final "
        "report byte-identical to an unperturbed oracle run; a "
        "failing seed reproduces from its printed line forever",
    )
    obs_flags(q)
    q.add_argument("--seed", type=int, default=0,
                   help="chaos schedule seed (the repro key)")
    q.add_argument("--sweep", type=int, default=1,
                   help="run N consecutive seeds starting at --seed")
    q.add_argument("--profile",
                   choices=("kill", "torn", "mixed", "spans", "claims"),
                   default="mixed",
                   help="fault-mix weighting of the schedule ('claims' "
                   "weights the contention plane: claim races, zombie "
                   "resumes, single-victim lease jumps, torn queue.log "
                   "appends)")
    q.add_argument("--workers", type=int, default=1,
                   help="race N workers against the one store every "
                   "worker round (adds the contention invariants: no "
                   "(job, batch, gen) executed twice, no find filed "
                   "twice, reports still byte-identical to the "
                   "1-worker oracle)")
    q.add_argument("--rounds", type=int, default=0,
                   help="override the schedule's round count (0 = from "
                   "the seed)")
    q.add_argument("--jobs", type=int, default=0,
                   help="override the number of tenant jobs (0 = from "
                   "the seed)")
    q.add_argument("--real", action="store_true",
                   help="drive real echo-machine engines instead of "
                   "the jax-free synthetic driver (slow: each worker "
                   "restart pays a jax import; finds are filed and "
                   "regress-replayed)")
    q.add_argument("--out", default=None, metavar="DIR",
                   help="keep the farm, schedule.json and fsck.json "
                   "under DIR (default: a temp dir, removed when the "
                   "seed passes)")
    q.add_argument("--keep", action="store_true",
                   help="keep the scratch farm even on success")
    q.set_defaults(fn=cmd_fleet)

    p = sub.add_parser(
        "lint",
        help="static determinism & contract analysis: D-rules "
        "(wall-clock/entropy/set-order/callback hazards, AST-only), "
        "C-rules (Machine contract: handler purity, durable/torn spec "
        "congruence, coverage projection), G-rules (fault-kind mirror "
        "and RNG-layout cross-checks), and the whole-program families "
        "— L (jax-free layer map), T (traced-value taint/donation), "
        "R (static RNG ledger), S (sharding readiness: lane-axis "
        "dataflow vs the collective registry). Exit 0 clean / "
        "1 findings / 2 usage error — pre-commit friendly",
    )
    from .analysis.cli import add_lint_args

    add_lint_args(p)
    p.set_defaults(fn=cmd_lint)

    args = parser.parse_args(argv)
    if getattr(args, "log_level", None) or getattr(args, "log_jsonl", None):
        from .tracing import init_tracing

        init_tracing(
            getattr(args, "log_level", None) or "INFO",
            jsonl_path=getattr(args, "log_jsonl", None),
        )
    if args.cmd == "perf":
        # the out positional IS the host timeline: cmd_perf runs under
        # the same --perf-timeline session as explore/hunt/bench
        args.perf_timeline = args.out
    jax_free = args.cmd in ("serve", "coverage", "lint") or (
        # `bench report` renders history with no jax import at all
        args.cmd == "bench" and getattr(args, "action", None) == "report"
    ) or (
        # the whole fleet control plane (serve + client verbs + fsck +
        # chaos orchestration) is jax-free by contract; only a worker
        # with the real driver runs engines — the chaos harness's
        # synthetic-driver workers stay jax-free so a fleet-chaos round
        # costs milliseconds, not a jax import per incarnation
        args.cmd == "fleet" and (
            args.fleet_cmd != "worker"
            or getattr(args, "driver", "real") == "synthetic"
        )
    )
    if getattr(args, "multihost", False):
        # distributed init must precede ANY backend access — including
        # the watchdog's own device probe, which would pin a
        # single-process backend
        from .parallel import multihost

        multihost.initialize()
    elif not jax_free:
        from ._backend_watchdog import ensure_live_backend

        cli_args = list(argv) if argv is not None else sys.argv[1:]
        ensure_live_backend(argv=["-m", "madsim_tpu"] + cli_args)
    if not jax_free:
        # Warm-start priming: wire the persistent compilation cache
        # (--compile-cache / $MADSIM_TPU_COMPILE_CACHE) BEFORE the
        # subcommand's first jit, so hunt/explore/bench-ab warmups
        # read and write the cache from their very first compile —
        # enabling is first-directory-wins per process, and an engine
        # constructed before the cache was bound would pay a full
        # cold build that the fleet then never reuses.
        from .compile_cache import enable_compile_cache

        enable_compile_cache(getattr(args, "compile_cache", None))
    with _perf_session(args):
        return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
