"""Small shared utilities for the TPU engine."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tree_where(pred, on_true, on_false):
    """Elementwise select over two identical pytrees; `pred` is a scalar or
    lane-vector broadcast against each leaf's leading dim."""

    def sel(a, b):
        p = pred
        # broadcast pred over trailing dims
        while p.ndim < a.ndim:
            p = p[..., None]
        return jnp.where(p, a, b)

    return jax.tree.map(sel, on_true, on_false)


def set2d(arr, i, j, value):
    """`arr.at[i, j].set(value)` for traced (i, j) via an outer mask —
    XLA's scatter emitter rejects multi-operand dynamic indices (and the
    mask form vectorizes better under vmap anyway)."""
    n0, n1 = arr.shape
    mask = (jnp.arange(n0)[:, None] == i) & (jnp.arange(n1)[None, :] == j)
    return jnp.where(mask, value, arr)


def tree_stack_fields(tree, n):
    """Broadcast each leaf to a leading dim of n (used to replicate an
    initial node state over N nodes)."""
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (n,) + jnp.shape(x)), tree)
