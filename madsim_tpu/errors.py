"""Error types of the simulation framework.

Reference parity: madsim panics (Rust) become typed exceptions here —
e.g. the executor's "all tasks will block forever" panic
(reference: madsim/src/sim/task/mod.rs:250) is `Deadlock`, the
determinism checker's "non-determinism detected" panic
(reference: madsim/src/sim/rand.rs:65-90) is `NonDeterminism`.
"""

from __future__ import annotations


class SimError(Exception):
    """Base class for all framework errors."""


class Deadlock(SimError):
    """No runnable task and no pending timer while the main future is alive.

    Reference: madsim/src/sim/task/mod.rs:250 "all tasks will block forever".
    """


class TimeLimitExceeded(SimError):
    """Virtual time passed the limit set by `Runtime.set_time_limit`.

    Reference: madsim/src/sim/runtime/mod.rs:148 + builder time_limit.
    """


class NonDeterminism(SimError):
    """The RNG draw log diverged between two runs of the same seed.

    Reference: madsim/src/sim/rand.rs:65-90 ("non-determinism detected").
    """


class JoinError(SimError):
    """Awaiting a JoinHandle of a task that was cancelled or panicked.

    Reference: madsim/src/sim/task/join.rs.
    """

    def __init__(self, message: str, *, cancelled: bool = False, cause: BaseException | None = None):
        super().__init__(message)
        self.cancelled = cancelled
        self.cause = cause

    def is_cancelled(self) -> bool:
        return self.cancelled

    def is_panic(self) -> bool:
        return not self.cancelled


class SendError(SimError):
    """Channel send on a closed channel."""


class RecvError(SimError):
    """Channel receive on a closed-and-drained channel."""


class TryRecvError(SimError):
    """Non-blocking receive found no message."""

    def __init__(self, message: str = "empty", *, disconnected: bool = False):
        super().__init__(message)
        self.disconnected = disconnected
