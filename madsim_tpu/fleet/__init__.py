"""The hunt fleet — DST as a continuously operating farm.

The paper's batch-entry convention (`MADSIM_TEST_SEED`/`MADSIM_TEST_NUM`
driving thousands of seeds per invocation) is a CLI-shaped API for
exactly one user. This package is the *service* shape the ROADMAP north
star asks for: CI fleets and many users submitting concurrent hunts
against a long-lived, warm-compiled engine. Everything here is
composition of library pieces that already exist — fingerprinted
`hunt --checkpoint` resume, StatsEmitter JSONL/Prometheus, the plateau
detector, `cache_subkey`-routed warm compiles, PerfRecorder timelines,
`shrink` + `why` attribution — plus the three things that make them a
daemon:

* `store` — a durable job store + queue: JSON-on-disk with atomic
  writes (the `runtime/checkpoint.py` discipline), a full lifecycle
  state machine (queued -> compiling -> running -> plateaued/exhausted/
  found -> shrunk -> filed, plus cancelled/failed), worker leases with
  expiry, and an argument fingerprint so a resumed worker refuses
  drifted job definitions exactly like checkpoints do.
* `allocator` — the multi-tenant lane allocator: one work unit = one
  seed batch of one job; jobs sharing an engine `cache_subkey` are
  packed back-to-back so they reuse the warm jit (never two engine
  configs in flight at once on a 1-core box), with priority/deadline
  deciding which subkey group runs.
* `worker` — `python -m madsim_tpu fleet worker`: leases jobs, runs
  them one batch-sized unit at a time through the existing checkpoint
  machinery (a `kill -9` mid-job loses at most one batch), honors
  plateau/deadline/cancel stops, and on a find runs `shrink` +
  provenance attribution and files the result as a corpus entry with
  its minimal repro line and filed-by-job metadata.
* `api` + `client` — the jax-free control plane: `fleet serve` (stdlib
  `http.server`, extending the `serve --service stats` pattern) with
  POST /jobs, GET /jobs/{id} (live per-batch feed), GET
  /jobs/{id}/result, DELETE /jobs/{id}, GET /queue, /metrics,
  /healthz; `fleet submit|status|result|cancel|queue` wrap it, each
  retrying transient errors with seeded-jitter backoff. The server
  runs the lease-reclamation supervisor sweep; /healthz reports store
  integrity (a read-only fsck scan), queue depth, stale leases and
  quarantined jobs.
* `fsck` — the store doctor: per-file verdicts over every artifact
  (truncated/unparseable/fingerprint-inconsistent -> quarantined to
  `*.corrupt`; stale atomic-write tmps removed; queue counts rebuilt),
  plus `--reclaim` and `--release-quarantined`.
* `chaos` — the farm tested with its own medicine: one seeded RNG
  derives a schedule of worker SIGKILLs at the k-th store write, torn
  in-flight writes, checkpoint corruption, lease-clock jumps and
  server bounces, then asserts no accepted job lost, byte-identical
  recovery vs an unperturbed oracle farm, and a clean final fsck; a
  failing seed reproduces forever from its printed line.

Self-healing (PR 12): expired leases requeue their jobs with
exponential backoff (checkpoint preserved — <=1 batch lost across
worker REPLACEMENT, not just restart); N consecutive deaths or hard
failures quarantine a poison job with its exception, batch index and
exact repro command instead of wedging the farm; OOM-class failures
halve the lane count (re-deriving the warm-compile subkey) before
burning poison attempts; every durable write is fsync'd atomic
(`runtime/atomicio`), and every reader tolerates a torn file by
construction (typed errors, lenient quarantining checkpoint loads).

The determinism contract makes the farm auditable: any job's find
replays from its recorded repro line alone (`regress` on the fleet
corpus), and a whole job re-run is fully described by
(fingerprint, seed schedule) — both recorded in the store.
"""

from .store import (  # noqa: F401
    Job,
    JobStore,
    STATES,
    TERMINAL,
    spec_to_args,
)
