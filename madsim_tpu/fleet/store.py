"""Durable job store + queue — JSON-on-disk, atomic, fingerprinted.

One job = one file under `<root>/jobs/<id>.json`, written with the
`runtime/checkpoint.py` discipline (tmp + rename) so a kill mid-write
leaves the previous document intact and the jax-free control plane
never serves a torn read. The store IS the wire between the API server
and the worker: POST /jobs writes a `queued` document, the worker polls
the directory — no RPC, and both sides survive restarts for free.

Lifecycle state machine::

    queued -> compiling -> running -> plateaued | exhausted | found
                                      found -> shrunk -> filed
    (queued|compiling|running|found) -> cancelled
    (compiling|running|found|shrunk) -> failed

Every job records the same argument FINGERPRINT the checkpoint
machinery uses (`runtime/checkpoint.fingerprint_from_args` over the
spec), plus a sha256 of the normalized spec: a worker that leases a job
whose spec no longer hashes to its recorded fingerprint refuses it —
exactly like a `--checkpoint` resume refuses a drifted command line —
instead of silently blending two different hunts.

Pure host-side stdlib — no jax import anywhere in this module, so the
`fleet serve` control plane stays jax-free.
"""

from __future__ import annotations

# madsim: allow-file(D001) — submit/lease/history wall-clock stamps are
# this host-side service's contract (lease expiry, deadlines, audit
# trail); nothing here feeds simulation state. Virtual time lives in
# the engine, and a job's *results* are a pure function of
# (fingerprint, seed schedule), both recorded below.
import contextlib
import dataclasses
import hashlib
import json
import os
import re
import time
from types import SimpleNamespace
from typing import Callable, Dict, List, Optional

from ..runtime.checkpoint import fingerprint_from_args

try:  # POSIX file locks guard read-modify-write; no-op elsewhere
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX fallback
    fcntl = None

# -- lifecycle ---------------------------------------------------------------

QUEUED = "queued"
COMPILING = "compiling"
RUNNING = "running"
PLATEAUED = "plateaued"   # coverage plateau stop, no finds
EXHAUSTED = "exhausted"   # seed budget (or deadline) consumed, no finds
FOUND = "found"           # finds harvested, shrink pending
SHRUNK = "shrunk"         # finds minimized, filing pending
FILED = "filed"           # corpus entries + result written
CANCELLED = "cancelled"
FAILED = "failed"

STATES = (QUEUED, COMPILING, RUNNING, PLATEAUED, EXHAUSTED, FOUND,
          SHRUNK, FILED, CANCELLED, FAILED)
TERMINAL = frozenset({PLATEAUED, EXHAUSTED, FILED, CANCELLED, FAILED})
#: states a worker may hold a lease in (crash recovery re-leases these)
LEASABLE = frozenset({QUEUED, COMPILING, RUNNING, FOUND, SHRUNK})

_TRANSITIONS: Dict[str, frozenset] = {
    # queued -> failed: a job can be refused before compiling (unknown
    # machine, fingerprint drift detected at lease time)
    QUEUED: frozenset({COMPILING, CANCELLED, FAILED}),
    COMPILING: frozenset({RUNNING, FAILED, CANCELLED}),
    RUNNING: frozenset({PLATEAUED, EXHAUSTED, FOUND, FAILED, CANCELLED}),
    FOUND: frozenset({SHRUNK, FAILED, CANCELLED}),
    SHRUNK: frozenset({FILED, FAILED}),
    PLATEAUED: frozenset(),
    EXHAUSTED: frozenset(),
    FILED: frozenset(),
    CANCELLED: frozenset(),
    FAILED: frozenset(),
}

# -- job spec ----------------------------------------------------------------

#: whitelisted spec fields -> (type, default). Mirrors the hunt CLI;
#: `batch` defaults to the CI shape (256 lanes) where a warm worker
#: compiles in ~4 s, not the flagship 8192.
SPEC_FIELDS = {
    "machine": (str, None),          # required
    "nodes": (int, 0),
    "seed": (int, 0),
    "seeds": (int, 1024),
    "batch": (int, 256),
    "horizon": (float, 5.0),
    "max_steps": (int, 3000),
    "queue": (int, 96),
    "faults": (int, 2),
    "loss": (float, 0.0),
    "fault_tmax": (int, 0),
    "fault_kinds": (str, "pair,kill"),
    "rng_stream": (int, 2),
    "strict_restart": (bool, False),
    "coverage": (bool, False),
    "provenance": (bool, False),
    "flight_recorder": (bool, False),
    "stop_on_plateau": (int, 0),
    "shrink_limit": (int, 5),
}

SEGMENT_STEPS = 384  # the streaming driver's pinned segment shape


def normalize_spec(spec: dict) -> dict:
    """Validate + default a job spec. Raises ValueError (the API maps it
    to 400) on unknown fields, a missing machine, or type mismatches."""
    unknown = sorted(set(spec) - set(SPEC_FIELDS))
    if unknown:
        raise ValueError(
            f"unknown spec fields {unknown}; known: {sorted(SPEC_FIELDS)}"
        )
    out = {}
    for name, (typ, default) in SPEC_FIELDS.items():
        v = spec.get(name, default)
        if v is None:
            raise ValueError(f"spec field {name!r} is required")
        if typ is bool:
            if not isinstance(v, bool):
                raise ValueError(f"spec field {name!r} must be a bool, got {v!r}")
        elif typ is float:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"spec field {name!r} must be a number, got {v!r}")
            v = float(v)
        elif typ is int:
            if isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(f"spec field {name!r} must be an int, got {v!r}")
        elif typ is str:
            if not isinstance(v, str) or not v:
                raise ValueError(f"spec field {name!r} must be a non-empty string")
        out[name] = v
    if out["seeds"] < 1 or out["batch"] < 1:
        raise ValueError("spec needs seeds >= 1 and batch >= 1")
    if out["stop_on_plateau"] and not out["coverage"]:
        raise ValueError(
            "stop_on_plateau needs coverage: the plateau signal IS the "
            "coverage curve"
        )
    return out


def spec_to_args(spec: dict, **overrides) -> SimpleNamespace:
    """The args namespace `__main__._build_engine` / `_stream_batches`
    expect, built from a job spec. The fleet worker drives the SAME
    chunked streaming driver the `hunt` CLI uses — one code path, one
    fingerprint function, one checkpoint format."""
    ns = SimpleNamespace(
        **spec,
        stream=True,
        no_pipeline=False,
        segments_per_dispatch=8,
        dispatch_depth=4,
        no_donate=False,
        compile_cache=None,
        checkpoint=None,
        stats=None,
        stats_labels=None,
        stop_after_batches=0,
        all_seeds=False,
        limit=spec.get("shrink_limit", 5),
    )
    for k, v in overrides.items():
        setattr(ns, k, v)
    return ns


def job_fingerprint(spec: dict) -> dict:
    """The resume-safety fingerprint: the checkpoint machinery's field
    set computed over the spec, so the job store and the job's
    `--checkpoint` file refuse drift with one voice."""
    return fingerprint_from_args(spec_to_args(spec))


def spec_sha(spec: dict) -> str:
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()
    ).hexdigest()


def job_subkey(spec: dict) -> str:
    """The warm-start cache subkey this job's engine compiles under
    (compile_cache.cache_subkey over the gate tuple / stream version /
    lane shape). Computed ONCE at submit with `import_jax=False` (a
    fixed `jax-unknown-` prefix): the control plane stays jax-free, and
    the allocator only needs EQUALITY to pack same-compile jobs
    back-to-back — jax's internal key still discriminates versions for
    the persistent cache entries themselves."""
    from ..compile_cache import cache_subkey

    return cache_subkey(
        import_jax=False,
        gates={
            "flight_recorder": spec["flight_recorder"],
            "coverage": spec["coverage"],
            "provenance": spec["provenance"],
        },
        rng_stream=spec["rng_stream"],
        lanes=spec["batch"],
        segment_steps=SEGMENT_STEPS,
    )


def engine_key(spec: dict) -> str:
    """Everything that shapes the COMPILED streaming program (model,
    vocabulary, gates, lane shape) — jobs with equal keys can share one
    live Engine instance in a worker. Seed budget/cursor are excluded:
    they are runtime inputs, not compiled structure."""
    fields = (
        "machine", "nodes", "horizon", "queue", "faults", "loss",
        "fault_tmax", "fault_kinds", "rng_stream", "strict_restart",
        "coverage", "provenance", "flight_recorder", "batch",
    )
    return json.dumps({f: spec[f] for f in fields}, sort_keys=True)


# -- the job document --------------------------------------------------------


@dataclasses.dataclass
class Job:
    id: str
    spec: dict
    fingerprint: dict
    fingerprint_sha: str
    subkey: str
    state: str = QUEUED
    priority: int = 0
    deadline_ts: Optional[float] = None
    ts_submit: float = 0.0
    history: list = dataclasses.field(default_factory=list)
    lease: Optional[dict] = None
    cancel_requested: bool = False
    progress: dict = dataclasses.field(default_factory=dict)
    result: Optional[dict] = None
    error: Optional[str] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["version"] = 1
        return d

    @staticmethod
    def from_dict(d: dict) -> "Job":
        d = dict(d)
        d.pop("version", None)
        return Job(**d)

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL


class JobStore:
    """Directory layout under `root`::

        jobs/<id>.json         the job document (atomic writes)
        jobs/<id>.lock         flock guard for read-modify-write
        jobs/<id>.ckpt.json    the job's hunt checkpoint (worker-owned)
        jobs/<id>.stats.*      the job's StatsEmitter feed (jsonl/prom/json)
        corpus.json            filed finds (corpus.CorpusEntry records)
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.jobs_dir = os.path.join(self.root, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)

    # -- paths ---------------------------------------------------------------

    def job_path(self, job_id: str) -> str:
        if not re.fullmatch(r"[A-Za-z0-9._-]+", job_id):
            raise KeyError(f"malformed job id {job_id!r}")
        return os.path.join(self.jobs_dir, f"{job_id}.json")

    def ckpt_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, f"{job_id}.ckpt.json")

    def stats_base(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, f"{job_id}.stats")

    @property
    def corpus_path(self) -> str:
        return os.path.join(self.root, "corpus.json")

    # -- locking + atomic IO -------------------------------------------------

    @contextlib.contextmanager
    def _locked(self, name: str):
        path = os.path.join(self.jobs_dir, name + ".lock")
        f = open(path, "a")
        try:
            if fcntl is not None:
                fcntl.flock(f, fcntl.LOCK_EX)
            yield
        finally:
            if fcntl is not None:
                fcntl.flock(f, fcntl.LOCK_UN)
            f.close()

    def _write(self, job: Job) -> None:
        path = self.job_path(job.id)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(job.to_dict(), f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    # -- submit / read -------------------------------------------------------

    def submit(self, spec: dict, *, priority: int = 0,
               deadline_s: Optional[float] = None) -> Job:
        """Validate + enqueue a job. `deadline_s` is relative seconds
        from submit; the store records the ABSOLUTE wall deadline."""
        spec = normalize_spec(spec)
        now = time.time()
        with self._locked(".store"):
            seq = 1 + max(
                (int(m.group(1)) for m in (
                    re.match(r"j(\d+)-", fn)
                    for fn in os.listdir(self.jobs_dir)
                ) if m),
                default=0,
            )
            sha = spec_sha(spec)
            job = Job(
                id=f"j{seq:04d}-{sha[:8]}",
                spec=spec,
                fingerprint=job_fingerprint(spec),
                fingerprint_sha=sha,
                subkey=job_subkey(spec),
                priority=int(priority),
                deadline_ts=(now + float(deadline_s)) if deadline_s else None,
                ts_submit=round(now, 3),
                history=[[round(now, 3), QUEUED]],
            )
            self._write(job)
        return job

    def get(self, job_id: str) -> Job:
        path = self.job_path(job_id)
        try:
            with open(path) as f:
                return Job.from_dict(json.load(f))
        except FileNotFoundError:
            raise KeyError(f"no such job {job_id!r}") from None

    def list(self) -> List[Job]:
        out = []
        for fn in sorted(os.listdir(self.jobs_dir)):
            # strict id match: the directory also holds each job's
            # .ckpt.json checkpoint and .stats.json snapshot
            m = re.fullmatch(r"(j\d+-[0-9a-f]{8})\.json", fn)
            if m:
                with contextlib.suppress(KeyError, json.JSONDecodeError):
                    out.append(self.get(m.group(1)))
        return out

    def counts(self) -> Dict[str, int]:
        c = {s: 0 for s in STATES}
        for j in self.list():
            c[j.state] = c.get(j.state, 0) + 1
        return c

    # -- guarded mutation ----------------------------------------------------

    def _update(self, job_id: str, fn: Callable[[Job], None]) -> Job:
        with self._locked(job_id):
            job = self.get(job_id)
            fn(job)
            self._write(job)
        return job

    def transition(self, job_id: str, to: str, *, error: Optional[str] = None,
                   result: Optional[dict] = None,
                   progress: Optional[dict] = None) -> Job:
        """Move a job along the lifecycle; illegal edges raise."""
        if to not in STATES:
            raise ValueError(f"unknown state {to!r}")

        def mut(job: Job) -> None:
            if to not in _TRANSITIONS[job.state]:
                raise ValueError(
                    f"illegal transition {job.state} -> {to} for {job.id}"
                )
            job.state = to
            job.history.append([round(time.time(), 3), to])
            if error is not None:
                job.error = error
            if result is not None:
                job.result = result
            if progress is not None:
                job.progress = {**job.progress, **progress}
            if to in TERMINAL:
                job.lease = None

        return self._update(job_id, mut)

    def update_progress(self, job_id: str, progress: dict) -> Job:
        return self._update(
            job_id, lambda j: j.progress.update(progress)
        )

    def request_cancel(self, job_id: str) -> Job:
        """Queued jobs cancel immediately; in-flight jobs get the flag
        and the worker finalizes at the next unit boundary."""

        def mut(job: Job) -> None:
            if job.terminal:
                return
            job.cancel_requested = True
            if job.state == QUEUED:
                job.state = CANCELLED
                job.history.append([round(time.time(), 3), CANCELLED])
                job.lease = None

        return self._update(job_id, mut)

    # -- leases --------------------------------------------------------------

    def try_lease(self, job_id: str, worker: str, ttl_s: float) -> Optional[Job]:
        """Claim (or renew/reclaim) a job for `worker`. Returns the job
        when the lease is held, None when another worker's unexpired
        lease blocks it. A worker always reclaims its OWN lease
        immediately (restart-after-SIGKILL without waiting out the ttl)."""
        now = time.time()
        claimed: List[Optional[Job]] = [None]

        def mut(job: Job) -> None:
            if job.state not in LEASABLE:
                return
            lease = job.lease
            if (lease and lease["worker"] != worker
                    and lease["expires_ts"] > now):
                return
            job.lease = {
                "worker": worker,
                "expires_ts": round(now + ttl_s, 3),
                "ttl_s": ttl_s,
            }
            claimed[0] = job

        self._update(job_id, mut)
        return claimed[0]

    def renew_lease(self, job_id: str, worker: str) -> None:
        def mut(job: Job) -> None:
            if job.lease and job.lease["worker"] == worker:
                job.lease["expires_ts"] = round(
                    time.time() + job.lease["ttl_s"], 3
                )

        self._update(job_id, mut)

    # -- drift refusal -------------------------------------------------------

    def fingerprint_mismatch(self, job: Job) -> Optional[str]:
        """None when the job's spec still hashes to its recorded
        fingerprint; otherwise a message naming EVERY drifted field —
        the same shape the checkpoint refusal prints, surfaced verbatim
        as the job's `failed` reason."""
        want = job_fingerprint(job.spec)
        diffs = [
            f"{f} (recorded {job.fingerprint.get(f)!r}, now {want.get(f)!r})"
            for f in sorted(set(want) | set(job.fingerprint))
            if job.fingerprint.get(f) != want.get(f)
        ]
        if spec_sha(job.spec) != job.fingerprint_sha and not diffs:
            diffs = ["spec hash (non-fingerprint field edited)"]
        if not diffs:
            return None
        return (
            f"job {job.id}: spec drifted since submit — refusing to run; "
            "differing: " + ", ".join(diffs)
        )

    # -- live feed -----------------------------------------------------------

    def read_feed(self, job_id: str, last: int = 20) -> List[dict]:
        """The job's live per-batch coverage/failure feed: the tail of
        its StatsEmitter JSONL, parsed. Missing file = empty feed (the
        job has not started streaming yet)."""
        path = self.stats_base(job_id) + ".jsonl"
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            return []
        out = []
        for line in lines[-max(0, last):]:
            with contextlib.suppress(json.JSONDecodeError):
                out.append(json.loads(line))
        return out
