"""Durable job store + queue — JSON-on-disk, atomic, fingerprinted.

One job = one file under `<root>/jobs/<id>.json`, written with the
`runtime/checkpoint.py` discipline (tmp + rename) so a kill mid-write
leaves the previous document intact and the jax-free control plane
never serves a torn read. The store IS the wire between the API server
and the worker: POST /jobs writes a `queued` document, the worker polls
the directory — no RPC, and both sides survive restarts for free.

Lifecycle state machine::

    queued -> compiling -> running -> plateaued | exhausted | found
                                      found -> shrunk -> filed
    (queued|compiling|running|found) -> cancelled
    (compiling|running|found|shrunk) -> failed
    (queued|compiling|running|found|shrunk) -> queued       (requeue)
    (queued|compiling|running|found|shrunk) -> quarantined  (poison)
    quarantined -> queued                                   (release)

A *requeue* is the supervisor path: an expired worker lease (the worker
died, or its clock jumped past the ttl) or a worker-reported hard
failure sends the job back to `queued` with the lease cleared, the
checkpoint preserved (the next worker resumes at <=1 lost batch) and an
exponential backoff stamped in `requeue_after_ts`. The `attempt`
counter counts CONSECUTIVE deaths — any completed unit resets it — and
at `max_attempts` the job is declared poison and moves to the terminal
`quarantined` state carrying the last exception, the batch index it
died in, and the exact repro command, instead of wedging the farm
forever. `release_quarantined` is the explicit operator edge back.

Every job records the same argument FINGERPRINT the checkpoint
machinery uses (`runtime/checkpoint.fingerprint_from_args` over the
spec), plus a sha256 of the normalized spec: a worker that leases a job
whose spec no longer hashes to its recorded fingerprint refuses it —
exactly like a `--checkpoint` resume refuses a drifted command line —
instead of silently blending two different hunts.

Pure host-side stdlib — no jax import anywhere in this module, so the
`fleet serve` control plane stays jax-free.
"""

from __future__ import annotations

# madsim: allow-file(D001) — submit/lease/history wall-clock stamps are
# this host-side service's contract (lease expiry, deadlines, audit
# trail); nothing here feeds simulation state. Virtual time lives in
# the engine, and a job's *results* are a pure function of
# (fingerprint, seed schedule), both recorded below.
import contextlib
import dataclasses
import hashlib
import json
import os
import re
import time
from types import SimpleNamespace
from typing import Callable, Dict, List, Optional

from ..runtime.atomicio import (
    append_text,
    atomic_write_json,
    atomic_write_text,
    create_exclusive,
)
from ..runtime.checkpoint import fingerprint_from_args
from . import events as fleet_events

try:  # POSIX file locks guard read-modify-write; no-op elsewhere
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX fallback
    fcntl = None

# -- lifecycle ---------------------------------------------------------------

QUEUED = "queued"
COMPILING = "compiling"
RUNNING = "running"
PLATEAUED = "plateaued"   # coverage plateau stop, no finds
EXHAUSTED = "exhausted"   # seed budget (or deadline) consumed, no finds
FOUND = "found"           # finds harvested, shrink pending
SHRUNK = "shrunk"         # finds minimized, filing pending
FILED = "filed"           # corpus entries + result written
CANCELLED = "cancelled"
FAILED = "failed"
QUARANTINED = "quarantined"  # poison: N consecutive deaths/hard failures

STATES = (QUEUED, COMPILING, RUNNING, PLATEAUED, EXHAUSTED, FOUND,
          SHRUNK, FILED, CANCELLED, FAILED, QUARANTINED)
TERMINAL = frozenset({PLATEAUED, EXHAUSTED, FILED, CANCELLED, FAILED,
                      QUARANTINED})
#: states a worker may hold a lease in (crash recovery re-leases these)
LEASABLE = frozenset({QUEUED, COMPILING, RUNNING, FOUND, SHRUNK})

#: consecutive deaths/hard failures before a job is declared poison
MAX_ATTEMPTS = 3
#: requeue backoff: base * 2^(attempt-1) seconds
REQUEUE_BACKOFF_BASE_S = 2.0

_TRANSITIONS: Dict[str, frozenset] = {
    # queued -> failed: a job can be refused before compiling (unknown
    # machine, fingerprint drift detected at lease time); queued ->
    # quarantined: the 3rd lease death can land before the worker ever
    # reached compiling
    QUEUED: frozenset({COMPILING, CANCELLED, FAILED, QUARANTINED}),
    COMPILING: frozenset({RUNNING, FAILED, CANCELLED, QUEUED, QUARANTINED}),
    RUNNING: frozenset({PLATEAUED, EXHAUSTED, FOUND, FAILED, CANCELLED,
                        QUEUED, QUARANTINED}),
    FOUND: frozenset({SHRUNK, FAILED, CANCELLED, QUEUED, QUARANTINED}),
    SHRUNK: frozenset({FILED, FAILED, QUEUED, QUARANTINED}),
    PLATEAUED: frozenset(),
    EXHAUSTED: frozenset(),
    FILED: frozenset(),
    CANCELLED: frozenset(),
    FAILED: frozenset(),
    # terminal for every automatic path; the one edge out is the
    # explicit operator release (`fleet fsck --release-quarantined`)
    QUARANTINED: frozenset({QUEUED}),
}

# -- job spec ----------------------------------------------------------------

#: whitelisted spec fields -> (type, default). Mirrors the hunt CLI;
#: `batch` defaults to the CI shape (256 lanes) where a warm worker
#: compiles in ~4 s, not the flagship 8192.
SPEC_FIELDS = {
    "machine": (str, None),          # required
    "nodes": (int, 0),
    "seed": (int, 0),
    "seeds": (int, 1024),
    "batch": (int, 256),
    "horizon": (float, 5.0),
    "max_steps": (int, 3000),
    "queue": (int, 96),
    "faults": (int, 2),
    "loss": (float, 0.0),
    "fault_tmax": (int, 0),
    "fault_kinds": (str, "pair,kill"),
    "rng_stream": (int, 2),
    "strict_restart": (bool, False),
    "coverage": (bool, False),
    "provenance": (bool, False),
    "flight_recorder": (bool, False),
    "stop_on_plateau": (int, 0),
    "shrink_limit": (int, 5),
    # coverage-feedback search (madsim_tpu/search): the worker evolves
    # the job's seed corpus, biases draws toward thin coverage cells /
    # lineage-implicated kinds, and escalates the vocabulary on
    # plateau; the (seed schedule, bias state) trail rides the job
    # checkpoint so resume/replacement replays are byte-identical
    "guided": (bool, False),
    # span the hunt over the first N devices as one jitted SPMD
    # program (the lane-axis mesh; 0 = unsharded). Part of the
    # warm-compile grouping key: a mesh job and a single-device job
    # compile different programs and must never share a group
    "devices": (int, 0),
}

SEGMENT_STEPS = 384  # the streaming driver's pinned segment shape


def normalize_spec(spec: dict) -> dict:
    """Validate + default a job spec. Raises ValueError (the API maps it
    to 400) on unknown fields, a missing machine, or type mismatches."""
    unknown = sorted(set(spec) - set(SPEC_FIELDS))
    if unknown:
        raise ValueError(
            f"unknown spec fields {unknown}; known: {sorted(SPEC_FIELDS)}"
        )
    out = {}
    for name, (typ, default) in SPEC_FIELDS.items():
        v = spec.get(name, default)
        if v is None:
            raise ValueError(f"spec field {name!r} is required")
        if typ is bool:
            if not isinstance(v, bool):
                raise ValueError(f"spec field {name!r} must be a bool, got {v!r}")
        elif typ is float:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"spec field {name!r} must be a number, got {v!r}")
            v = float(v)
        elif typ is int:
            if isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(f"spec field {name!r} must be an int, got {v!r}")
        elif typ is str:
            if not isinstance(v, str) or not v:
                raise ValueError(f"spec field {name!r} must be a non-empty string")
        out[name] = v
    if out["seeds"] < 1 or out["batch"] < 1:
        raise ValueError("spec needs seeds >= 1 and batch >= 1")
    if out["stop_on_plateau"] and not out["coverage"]:
        raise ValueError(
            "stop_on_plateau needs coverage: the plateau signal IS the "
            "coverage curve"
        )
    if out["guided"] and not out["coverage"]:
        raise ValueError(
            "guided needs coverage: the bias signal IS the live map"
        )
    if out["devices"] < 0:
        raise ValueError("spec field 'devices' must be >= 0 (0 = unsharded)")
    if out["devices"] and out["batch"] % out["devices"]:
        raise ValueError(
            f"batch ({out['batch']}) must be a multiple of devices "
            f"({out['devices']}): lanes shard evenly over the mesh axis"
        )
    return out


def spec_to_args(spec: dict, **overrides) -> SimpleNamespace:
    """The args namespace `__main__._build_engine` / `_stream_batches`
    expect, built from a job spec. The fleet worker drives the SAME
    chunked streaming driver the `hunt` CLI uses — one code path, one
    fingerprint function, one checkpoint format."""
    ns = SimpleNamespace(
        **spec,
        stream=True,
        no_pipeline=False,
        segments_per_dispatch=8,
        dispatch_depth=4,
        no_donate=False,
        compile_cache=None,
        checkpoint=None,
        stats=None,
        stats_labels=None,
        stop_after_batches=0,
        all_seeds=False,
        limit=spec.get("shrink_limit", 5),
    )
    for k, v in overrides.items():
        setattr(ns, k, v)
    return ns


def job_fingerprint(spec: dict) -> dict:
    """The resume-safety fingerprint: the checkpoint machinery's field
    set computed over the spec, so the job store and the job's
    `--checkpoint` file refuse drift with one voice."""
    return fingerprint_from_args(spec_to_args(spec))


def spec_sha(spec: dict) -> str:
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()
    ).hexdigest()


def job_subkey(spec: dict) -> str:
    """The warm-start cache subkey this job's engine compiles under
    (compile_cache.cache_subkey over the gate tuple / stream version /
    lane shape). Computed ONCE at submit with `import_jax=False` (a
    fixed `jax-unknown-` prefix): the control plane stays jax-free, and
    the allocator only needs EQUALITY to pack same-compile jobs
    back-to-back — jax's internal key still discriminates versions for
    the persistent cache entries themselves."""
    from ..compile_cache import cache_subkey

    return cache_subkey(
        import_jax=False,
        gates={
            "flight_recorder": spec["flight_recorder"],
            "coverage": spec["coverage"],
            "provenance": spec["provenance"],
        },
        rng_stream=spec["rng_stream"],
        lanes=spec["batch"],
        segment_steps=SEGMENT_STEPS,
        # mesh topology: a d8 job and an unsharded job compile disjoint
        # programs, so the allocator must never pack them back-to-back.
        # .get: docs persisted before the mesh rebuild have no field
        # and stay in the unsharded group
        devices=spec.get("devices") or None,
    )


def repro_cmd(spec: dict, *, batch_index: Optional[int] = None) -> str:
    """The exact `hunt` command reproducing this job's stream — or,
    with `batch_index`, the single batch it died in (batch i always
    consumes the same seed range, so one batch is a complete repro).
    Recorded verbatim in quarantine documents: a poisoned job must be
    debuggable from its doc alone, with no farm running.

    Guided jobs cannot be sliced to one batch (their batch seed
    vectors are bias-chosen, not sequential ranges) — the full-run
    command reproduces the identical schedule deterministically, so
    that is the honest repro."""
    start, seeds = spec["seed"], spec["seeds"]
    if spec.get("guided"):
        batch_index = None
    if batch_index is not None:
        start = spec["seed"] + batch_index * spec["batch"]
        seeds = max(1, min(spec["batch"], spec["seeds"] - batch_index * spec["batch"]))
    parts = [
        f"python -m madsim_tpu hunt --stream --machine {spec['machine']}",
        f"--nodes {spec['nodes']}", f"--seed {start}", f"--seeds {seeds}",
        f"--batch {spec['batch']}", f"--horizon {spec['horizon']}",
        f"--max-steps {spec['max_steps']}", f"--queue {spec['queue']}",
        f"--faults {spec['faults']}", f"--loss {spec['loss']}",
        f"--fault-tmax {spec['fault_tmax']}",
        f"--fault-kinds {spec['fault_kinds']}",
        f"--rng-stream {spec['rng_stream']}",
    ]
    if spec.get("devices"):
        parts.append(f"--devices {spec['devices']}")
    for flag, key in (("--strict-restart", "strict_restart"),
                      ("--coverage", "coverage"),
                      ("--provenance", "provenance"),
                      ("--flight-recorder", "flight_recorder"),
                      ("--guided", "guided")):
        if spec.get(key):
            parts.append(flag)
    return " ".join(parts)


def engine_key(spec: dict) -> str:
    """Everything that shapes the COMPILED streaming program (model,
    vocabulary, gates, lane shape) — jobs with equal keys can share one
    live Engine instance in a worker. Seed budget/cursor are excluded:
    they are runtime inputs, not compiled structure."""
    fields = (
        "machine", "nodes", "horizon", "queue", "faults", "loss",
        "fault_tmax", "fault_kinds", "rng_stream", "strict_restart",
        "coverage", "provenance", "flight_recorder", "batch",
    )
    key = {f: spec[f] for f in fields}
    # mesh size shapes the compiled program (explicit shardings are in
    # the jit); .get keeps pre-mesh docs readable (unsharded group)
    key["devices"] = spec.get("devices", 0)
    return json.dumps(key, sort_keys=True)


# -- the job document --------------------------------------------------------


class CorruptJobFile(RuntimeError):
    """A job document exists on disk but cannot be read (truncated,
    unparseable, or schema-broken). Raised instead of the raw decode
    error so every reader can distinguish "no such job" (KeyError)
    from "run `fleet fsck`" — the API maps this to 503, `list()` skips
    the file, and fsck quarantines it to `*.corrupt`."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"{path}: {detail} — run `fleet fsck`")
        self.path = path
        self.detail = detail


class FencedWrite(RuntimeError):
    """A store mutation carried a fencing token from a dead lease
    generation: the job was reclaimed (and possibly re-leased) since
    this worker last held it. The write was REJECTED and counted —
    nothing of it was merged. The worker's only correct response is to
    abandon the unit; the current holder owns the job now."""

    def __init__(self, job_id: str, worker: str, gen: int, op: str):
        super().__init__(
            f"job {job_id}: {op} from {worker!r} gen {gen} rejected — "
            f"lease was reclaimed; abandon the unit"
        )
        self.job_id = job_id
        self.worker = worker
        self.gen = gen
        self.op = op


@dataclasses.dataclass
class Job:
    id: str
    spec: dict
    fingerprint: dict
    fingerprint_sha: str
    subkey: str
    state: str = QUEUED
    priority: int = 0
    deadline_ts: Optional[float] = None
    ts_submit: float = 0.0
    history: list = dataclasses.field(default_factory=list)
    lease: Optional[dict] = None
    cancel_requested: bool = False
    progress: dict = dataclasses.field(default_factory=dict)
    result: Optional[dict] = None
    error: Optional[str] = None
    #: consecutive deaths/hard failures since the last completed unit
    #: (a completed unit resets it — deaths are only poison when
    #: consecutive)
    attempt: int = 0
    #: wall timestamp before which the job may not be leased (requeue
    #: backoff); None = leasable now
    requeue_after_ts: Optional[float] = None
    #: post-mortems of every death [{ts, reason, worker, state,
    #: error, batch_index, attempt}] — the quarantine doc quotes the
    #: fatal tail of this list
    deaths: list = dataclasses.field(default_factory=list)
    #: OOM lane-count backoff records [{ts, from_batch, to_batch,
    #: error, worker}]
    degraded: list = dataclasses.field(default_factory=list)
    #: set when state == quarantined: {reason, error, batch_index,
    #: attempts, deaths, repro}
    quarantine: Optional[dict] = None
    n_requeues: int = 0
    n_lease_reclaims: int = 0
    #: monotonic fencing token: bumped every time the lease passes to
    #: a NEW hold (first lease, takeover, or re-lease after a
    #: reclaim). The live lease dict carries the current value as
    #: ``lease["gen"]``; a worker's renewal/progress writes CAS
    #: against it, so a reclaimed ("zombie") hold can never resurrect
    #: its lease or merge state the next holder doesn't expect.
    lease_gen: int = 0
    #: observability-class tally (never feeds job results): store
    #: writes rejected because they carried a dead lease generation.
    #: Claim-race losses are counted worker-side (`workers/<id>.json`)
    #: — the loser's whole point is to back off without taking the
    #: job's lock.
    n_fenced_writes: int = 0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["version"] = 1
        return d

    @staticmethod
    def from_dict(d: dict) -> "Job":
        d = dict(d)
        d.pop("version", None)
        return Job(**d)

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL


class JobStore:
    """Directory layout under `root`::

        jobs/<id>.json         the job document (atomic writes)
        jobs/<id>.lock         flock guard for read-modify-write
        jobs/<id>.ckpt.json    the job's hunt checkpoint (worker-owned)
        jobs/<id>.stats.*      the job's StatsEmitter feed (jsonl/prom/json)
        jobs/<id>.events.jsonl the job-lifecycle event log (append-only)
        jobs/<id>.spans.jsonl  worker PerfRecorder span dumps (append-only)
        jobs/<id>.device.trace.json.gz  worker device-profile capture
                               (MADSIM_TPU_XPROF=1 units only)
        jobs/<id>.vtrace.json  failing lane's virtual-time trace (ditto)
        jobs/<id>.claim        O_EXCL claim file (contention arbiter;
                               advisory — the flock stays authoritative)
        corpus.json            filed finds (corpus.CorpusEntry records)
        queue.log              append-only queue index (rebuildable
                               from the job docs; docs stay the truth)
        workers/<id>.json      per-worker observability counters
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.jobs_dir = os.path.join(self.root, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)
        # in-memory materialization of queue.log: row per job, refreshed
        # incrementally (stat + read-the-new-bytes) on every poll
        self._qrows: Dict[str, dict] = {}
        self._qlog_pos = 0
        self._qlog_ino: Optional[int] = None

    # -- paths ---------------------------------------------------------------

    def job_path(self, job_id: str) -> str:
        if not re.fullmatch(r"[A-Za-z0-9._-]+", job_id):
            raise KeyError(f"malformed job id {job_id!r}")
        return os.path.join(self.jobs_dir, f"{job_id}.json")

    def ckpt_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, f"{job_id}.ckpt.json")

    def stats_base(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, f"{job_id}.stats")

    def events_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, f"{job_id}.events.jsonl")

    def spans_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, f"{job_id}.spans.jsonl")

    def device_trace_path(self, job_id: str) -> str:
        """The worker's last device-profile capture (Chrome JSON, gz) —
        written only when the worker runs under MADSIM_TPU_XPROF=1."""
        return os.path.join(self.jobs_dir, f"{job_id}.device.trace.json.gz")

    def vtrace_path(self, job_id: str) -> str:
        """The first failing lane's VIRTUAL-time Perfetto doc (same
        gate as the device trace; times are simulated µs, never wall)."""
        return os.path.join(self.jobs_dir, f"{job_id}.vtrace.json")

    def claim_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, f"{job_id}.claim")

    @property
    def corpus_path(self) -> str:
        return os.path.join(self.root, "corpus.json")

    @property
    def queue_log_path(self) -> str:
        return os.path.join(self.root, "queue.log")

    @property
    def workers_dir(self) -> str:
        return os.path.join(self.root, "workers")

    def worker_stats_path(self, worker_id: str) -> str:
        if not re.fullmatch(r"[A-Za-z0-9._-]+", worker_id):
            raise KeyError(f"malformed worker id {worker_id!r}")
        return os.path.join(self.workers_dir, f"{worker_id}.json")

    def write_worker_stats(self, worker_id: str, doc: dict) -> None:
        """Per-worker observability counters (claim conflicts, fenced
        writes, polls...). Throwaway-on-crash quality: no fsync, and
        nothing in the store depends on them."""
        os.makedirs(self.workers_dir, exist_ok=True)
        atomic_write_json(self.worker_stats_path(worker_id), doc,
                          fsync=False)

    def read_worker_stats(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        try:
            names = sorted(os.listdir(self.workers_dir))
        except FileNotFoundError:
            return out
        for fn in names:
            if not fn.endswith(".json"):
                continue
            with contextlib.suppress(OSError, json.JSONDecodeError,
                                     UnicodeDecodeError):
                with open(os.path.join(self.workers_dir, fn)) as f:
                    out[fn[:-len(".json")]] = json.load(f)
        return out

    # -- locking + atomic IO -------------------------------------------------

    @contextlib.contextmanager
    def _locked(self, name: str):
        path = os.path.join(self.jobs_dir, name + ".lock")
        f = open(path, "a")
        try:
            if fcntl is not None:
                fcntl.flock(f, fcntl.LOCK_EX)
            yield
        finally:
            if fcntl is not None:
                fcntl.flock(f, fcntl.LOCK_UN)
            f.close()

    def _write(self, job: Job) -> None:
        # shared crash-safe discipline (tmp + fsync + rename +
        # dir-fsync): a kill — or a power cut — mid-write leaves the
        # previous document, and the chaos harness injects its torn
        # writes at exactly this point
        atomic_write_json(self.job_path(job.id), job.to_dict())
        # mirror the queue-relevant fields into the append-only index
        # log. Best-effort by design: the doc above is the source of
        # truth, a missed or torn record only makes the index lag, and
        # the sweep/fsck re-sync it. Appends from different jobs' locks
        # interleave whole records (single O_APPEND write).
        with contextlib.suppress(OSError):
            append_text(self.queue_log_path,
                        json.dumps(self._queue_record(job), sort_keys=True,
                                   separators=(",", ":")) + "\n",
                        fsync=False)

    # -- the queue log (rebuildable index; the docs stay the truth) ----------

    @staticmethod
    def _queue_record(job: Job) -> dict:
        """One queue-log row: exactly the fields a lease poll filters
        and ranks on, so a reader answers "what can I claim?" without
        touching any job document."""
        lease = job.lease or {}
        return {
            "job": job.id,
            "state": job.state,
            "subkey": job.subkey,
            "priority": job.priority,
            "deadline_ts": job.deadline_ts,
            "requeue_after_ts": job.requeue_after_ts,
            "worker": lease.get("worker"),
            "lease_expires_ts": lease.get("expires_ts"),
            "gen": job.lease_gen,
            "plateau": bool(job.progress.get("plateau")),
            "ts": round(time.time(), 3),
        }

    def queue_rows(self) -> Dict[str, dict]:
        """The in-memory queue index: job id -> latest queue-log row.
        Refresh is O(new bytes): stat the log, read only what grew
        since the last call, keep at most one unterminated tail line
        unconsumed (it may be mid-append; the next append heals it).
        Unparseable lines are skipped — same torn-tolerance contract as
        the event-log readers. A store without a log yet (pre-index
        farms) gets one built from the docs, so the NEXT poll is
        O(1)."""
        path = self.queue_log_path
        try:
            stt = os.stat(path)
        except FileNotFoundError:
            self.rebuild_queue_log()
            try:
                stt = os.stat(path)
            except FileNotFoundError:  # pragma: no cover - read-only fs
                return dict(self._qrows)
        if stt.st_ino != self._qlog_ino or stt.st_size < self._qlog_pos:
            # replaced (rebuild) or truncated (torn-tail repair): rescan
            self._qrows, self._qlog_pos = {}, 0
            self._qlog_ino = stt.st_ino
        if stt.st_size > self._qlog_pos:
            with open(path, "rb") as f:
                f.seek(self._qlog_pos)
                chunk = f.read()
            cut = chunk.rfind(b"\n")
            if cut >= 0:
                for line in chunk[:cut].split(b"\n"):
                    if not line.strip():
                        continue
                    try:
                        row = json.loads(line)
                        self._qrows[row["job"]] = row
                    except (json.JSONDecodeError, UnicodeDecodeError,
                            KeyError, TypeError):
                        continue  # torn/foreign line: skip, never crash
                self._qlog_pos += cut + 1
        return self._qrows

    def rebuild_queue_log(self) -> int:
        """Write a fresh queue.log from the job documents (one row per
        job, sorted ids) — the fsck repair and the lazy migration path
        for stores that predate the log. Atomic replace, so concurrent
        readers see either the old log or the new one."""
        with self._locked(".store"):
            jobs = self.list()
            lines = [
                json.dumps(self._queue_record(j), sort_keys=True,
                           separators=(",", ":"))
                for j in sorted(jobs, key=lambda j: j.id)
            ]
            text = "\n".join(lines) + ("\n" if lines else "")
            atomic_write_text(self.queue_log_path, text, fsync=False)
        self._qrows, self._qlog_pos, self._qlog_ino = {}, 0, None
        return len(lines)

    @staticmethod
    def _row_stale(row: Optional[dict], job: "Job") -> bool:
        """A row misrepresents its job when the poll-relevant fields —
        state, lease holder, lease generation — disagree with the doc.
        (A row showing a leased job as free sends every poller into a
        claim conflict; state alone would miss that.)"""
        if row is None:
            return True
        lease = job.lease or {}
        return (row.get("state") != job.state
                or row.get("worker") != lease.get("worker")
                or row.get("gen", 0) != job.lease_gen)

    def queue_log_lag(self) -> int:
        """How many jobs the index currently misrepresents: doc state
        or lease differs from (or is missing from) the log's last
        word. O(n) — for sweeps, fsck and /healthz, never the poll
        path."""
        rows = self.queue_rows()
        return sum(1 for job in self.list()
                   if self._row_stale(rows.get(job.id), job))

    def sync_queue_log(self) -> int:
        """Append correction rows for any job the log misrepresents
        (e.g. the doc write landed but the process died before the
        mirror append). Called from the serve sweep and fsck — both
        already pay the O(n) doc scan."""
        rows = self.queue_rows()
        fixed = 0
        for job in self.list():
            if self._row_stale(rows.get(job.id), job):
                with contextlib.suppress(OSError):
                    append_text(self.queue_log_path,
                                json.dumps(self._queue_record(job),
                                           sort_keys=True,
                                           separators=(",", ":")) + "\n",
                                fsync=False)
                fixed += 1
        return fixed

    # -- submit / read -------------------------------------------------------

    def submit(self, spec: dict, *, priority: int = 0,
               deadline_s: Optional[float] = None) -> Job:
        """Validate + enqueue a job. `deadline_s` is relative seconds
        from submit; the store records the ABSOLUTE wall deadline."""
        spec = normalize_spec(spec)
        now = time.time()
        with self._locked(".store"):
            seq = 1 + max(
                (int(m.group(1)) for m in (
                    re.match(r"j(\d+)-", fn)
                    for fn in os.listdir(self.jobs_dir)
                ) if m),
                default=0,
            )
            sha = spec_sha(spec)
            job = Job(
                id=f"j{seq:04d}-{sha[:8]}",
                spec=spec,
                fingerprint=job_fingerprint(spec),
                fingerprint_sha=sha,
                subkey=job_subkey(spec),
                priority=int(priority),
                deadline_ts=(now + float(deadline_s)) if deadline_s else None,
                ts_submit=round(now, 3),
                history=[[round(now, 3), QUEUED]],
            )
            self._write(job)
            self._emit(job.id, [
                {"type": "submitted", "machine": spec["machine"],
                 "seeds": spec["seeds"], "batch": spec["batch"],
                 "priority": job.priority, "subkey": job.subkey},
                {"type": "queued"},
            ])
        return job

    def get(self, job_id: str) -> Job:
        """Read a job document. Raises KeyError when it does not exist
        and CorruptJobFile when it exists but cannot be read — a torn
        or schema-broken file must surface as "run fsck", never as an
        uncaught decode error deep in a worker or API handler."""
        path = self.job_path(job_id)
        try:
            with open(path) as f:
                return Job.from_dict(json.load(f))
        except FileNotFoundError:
            raise KeyError(f"no such job {job_id!r}") from None
        except (json.JSONDecodeError, UnicodeDecodeError) as exc:
            raise CorruptJobFile(path, f"unparseable JSON ({exc})") from None
        except TypeError as exc:
            raise CorruptJobFile(path, f"schema mismatch ({exc})") from None

    def list(self) -> List[Job]:
        out = []
        for fn in sorted(os.listdir(self.jobs_dir)):
            # strict id match: the directory also holds each job's
            # .ckpt.json checkpoint and .stats.json snapshot
            m = re.fullmatch(r"(j\d+-[0-9a-f]{8})\.json", fn)
            if m:
                # a corrupt document never takes the farm down: the
                # sweep/allocator simply do not see it until fsck
                # quarantines or an operator repairs it
                with contextlib.suppress(KeyError, CorruptJobFile):
                    out.append(self.get(m.group(1)))
        return out

    def counts(self) -> Dict[str, int]:
        c = {s: 0 for s in STATES}
        for j in self.list():
            c[j.state] = c.get(j.state, 0) + 1
        return c

    # -- the event log (observability-class; never feeds results) ------------

    def _emit(self, job_id: str, pending: List[dict]) -> None:
        """Append pending event records to the job's lifecycle log.
        Called under the same per-job lock as the mutation that
        produced them, so the log is the authoritative ordered history.
        Emission failure never breaks the store (the chaos harness
        SIGKILLs exactly here on purpose)."""
        if not pending or not fleet_events.enabled():
            return
        path = self.events_path(job_id)
        for ev in pending:
            ev = dict(ev)
            type_ = ev.pop("type")
            with contextlib.suppress(OSError):
                fleet_events.emit_event(path, type_, job=job_id, **ev)

    def emit_job_event(self, job_id: str, type_: str, *,
                       worker: Optional[str] = None, **fields) -> None:
        """Milestone events that do not mutate the job document (find,
        shrink_started/shrink_done): the worker reports them through
        the store so they take the same per-job lock — and therefore
        the same total order — as the lifecycle events."""
        if not fleet_events.enabled():
            return
        with self._locked(job_id):
            with contextlib.suppress(OSError):
                fleet_events.emit_event(self.events_path(job_id), type_,
                                        job=job_id, worker=worker, **fields)

    def read_events(self, job_id: str, since: int = 0) -> List[dict]:
        return fleet_events.read_events(self.events_path(job_id), since)

    # -- guarded mutation ----------------------------------------------------

    def _update(self, job_id: str, fn: Callable[[Job], None],
                pending_events: Optional[List[dict]] = None) -> Job:
        with self._locked(job_id):
            job = self.get(job_id)
            fn(job)
            self._write(job)
            if pending_events:
                self._emit(job_id, pending_events)
        return job

    def _fenced(self, job: Job, worker: Optional[str], gen: Optional[int],
                op: str, ev: List[dict]) -> bool:
        """The fence: a mutation carrying a token (worker, gen) goes
        through only while that exact generation is the live lease.
        Rejections are counted on the document and logged as a `fenced`
        event — observability, never results — and the caller raises
        FencedWrite so the zombie learns it lost the job. No token
        (gen None) means an operator/supervisor mutation: not fenced."""
        if gen is None:
            return False
        lease = job.lease
        if lease and lease["worker"] == worker and lease.get("gen", 0) == gen:
            return False
        job.n_fenced_writes += 1
        ev.append({"type": "fenced", "worker": worker, "gen": gen,
                   "op": op, "holder": lease["worker"] if lease else None,
                   "holder_gen": job.lease_gen})
        return True

    def transition(self, job_id: str, to: str, *, error: Optional[str] = None,
                   result: Optional[dict] = None,
                   progress: Optional[dict] = None,
                   worker: Optional[str] = None,
                   gen: Optional[int] = None) -> Job:
        """Move a job along the lifecycle; illegal edges raise. When
        the caller holds a lease it passes its fencing token (worker,
        gen): a reclaimed generation's transition raises FencedWrite
        and mutates nothing but the rejection counter."""
        if to not in STATES:
            raise ValueError(f"unknown state {to!r}")

        ev: List[dict] = []
        fenced: List[bool] = [False]

        def mut(job: Job) -> None:
            if self._fenced(job, worker, gen, f"transition->{to}", ev):
                fenced[0] = True
                return
            if to not in _TRANSITIONS[job.state]:
                raise ValueError(
                    f"illegal transition {job.state} -> {to} for {job.id}"
                )
            rec = {"type": to, "from": job.state}
            if job.lease:
                rec["worker"] = job.lease["worker"]
            if error is not None:
                rec["error"] = error
            ev.append(rec)
            job.state = to
            job.history.append([round(time.time(), 3), to])
            if error is not None:
                job.error = error
            if result is not None:
                job.result = result
            if progress is not None:
                job.progress = {**job.progress, **progress}
            if to in TERMINAL:
                job.lease = None

        out = self._update(job_id, mut, ev)
        if fenced[0]:
            raise FencedWrite(job_id, worker or "?", gen, f"transition->{to}")
        if to in TERMINAL:
            self._clear_claim(job_id)
        return out

    def request_cancel(self, job_id: str) -> Job:
        """Queued jobs cancel immediately; in-flight jobs get the flag
        and the worker finalizes at the next unit boundary."""

        ev: List[dict] = []

        def mut(job: Job) -> None:
            if job.terminal:
                return
            job.cancel_requested = True
            if job.state == QUEUED:
                ev.append({"type": "cancelled", "from": job.state})
                job.state = CANCELLED
                job.history.append([round(time.time(), 3), CANCELLED])
                job.lease = None
            else:
                ev.append({"type": "cancel_requested"})

        out = self._update(job_id, mut, ev)
        if out.terminal:
            self._clear_claim(job_id)
        return out

    # -- leases --------------------------------------------------------------

    def _read_claim(self, job_id: str) -> Optional[dict]:
        try:
            with open(self.claim_path(job_id)) as f:
                doc = json.loads(f.read())
            return doc if isinstance(doc, dict) else None
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None

    def _clear_claim(self, job_id: str) -> None:
        with contextlib.suppress(OSError):
            os.unlink(self.claim_path(job_id))

    def try_lease(self, job_id: str, worker: str, ttl_s: float, *,
                  info: Optional[dict] = None) -> Optional[Job]:
        """Claim (or renew/reclaim) a job for `worker`. Returns the job
        when the lease is held, None when another worker's unexpired
        lease blocks it or the job is in requeue backoff. A worker
        always reclaims its OWN lease immediately (restart-after-
        SIGKILL without waiting out the ttl).

        Contention discipline: a `jobs/<id>.claim` file created
        O_EXCL-style arbitrates N workers racing the same pick — the
        kernel picks exactly one winner and every loser returns None
        *without taking the job's lock* (`info["outcome"] ==
        "claim-conflict"`; the caller backs off with seeded jitter). A
        claim whose holder no longer has a live lease on the doc is
        dead weight (crashed claimant or reclaimed generation): the
        contender falls through to the flock, which stays the
        authoritative arbiter, and overwrites it on success. `info`,
        when passed, receives the outcome for the caller's counters."""
        now = time.time()
        claimed: List[Optional[Job]] = [None]
        claim = self.claim_path(job_id)
        won_create = create_exclusive(
            claim,
            json.dumps({"worker": worker, "ts": round(now, 3)},
                       sort_keys=True) + "\n",
            fsync=False,
        )
        if not won_create:
            holder = self._read_claim(job_id)
            if holder and holder.get("worker") not in (None, worker):
                try:
                    cur: Optional[Job] = self.get(job_id)
                except (KeyError, CorruptJobFile):
                    cur = None
                lease = cur.lease if cur else None
                if (lease and lease["worker"] == holder.get("worker")
                        and lease["expires_ts"] > now):
                    # live claim, live lease: a genuine race lost
                    if info is not None:
                        info["outcome"] = "claim-conflict"
                        info["holder"] = lease["worker"]
                    return None
                # stale claim (dead generation / claimant died between
                # claim and lease): arbitrate under the lock below

        def mut(job: Job) -> None:
            if job.state not in LEASABLE:
                return
            if job.requeue_after_ts and job.requeue_after_ts > now:
                return  # still backing off from its last death
            lease = job.lease
            if (lease and lease["worker"] != worker
                    and lease["expires_ts"] > now):
                return
            if not (lease and lease["worker"] == worker):
                # a NEW holder (first lease, takeover, or re-lease
                # after a reclaim cleared it) starts a new lease
                # generation and is an event; a worker re-claiming its
                # own lease is just a renewal and keeps the generation
                job.lease_gen += 1
                ev.append({"type": "leased", "worker": worker,
                           "ttl_s": ttl_s, "attempt": job.attempt,
                           "gen": job.lease_gen})
            job.lease = {
                "worker": worker,
                "expires_ts": round(now + ttl_s, 3),
                "ttl_s": ttl_s,
                "gen": job.lease_gen,
            }
            claimed[0] = job

        ev: List[dict] = []
        try:
            self._update(job_id, mut, ev)
        except (KeyError, CorruptJobFile):
            if won_create:
                self._clear_claim(job_id)
            raise
        got = claimed[0]
        if got is not None:
            # stamp the claim with the winning hold (atomic replace —
            # the O_EXCL race is settled once the lease is on the doc);
            # fsck judges claim staleness by this generation
            atomic_write_text(
                claim,
                json.dumps({"worker": worker, "gen": got.lease_gen,
                            "expires_ts": got.lease["expires_ts"]},
                           sort_keys=True) + "\n",
                fsync=False,
            )
            if info is not None:
                info["outcome"] = "leased"
        else:
            if won_create:
                # we arbitrated the claim but the doc said no (backoff,
                # terminal, foreign lease): leave nothing behind
                self._clear_claim(job_id)
            if info is not None:
                info.setdefault("outcome", "not-leasable")
        return got

    def renew_lease(self, job_id: str, worker: str,
                    gen: Optional[int] = None) -> bool:
        """Heartbeat renewal as a compare-and-swap on the lease
        generation. `reclaim_expired` can fire between a live worker's
        last read and its renewal: worker-identity alone would then
        either no-op silently (lease cleared) or — worse, when the
        same worker re-leased in between — resurrect a hold from a
        dead generation. The CAS renews only while `worker` still
        holds generation `gen` and reports the outcome, so the caller
        learns it lost the job instead of streaming on. `gen=None`
        checks worker identity only (pre-fencing callers)."""
        renewed = [False]

        def mut(job: Job) -> None:
            lease = job.lease
            if not (lease and lease["worker"] == worker):
                return
            if gen is not None and lease.get("gen", 0) != gen:
                return
            lease["expires_ts"] = round(time.time() + lease["ttl_s"], 3)
            renewed[0] = True

        self._update(job_id, mut)
        return renewed[0]

    # -- deaths, requeue, quarantine -----------------------------------------

    def note_progress(self, job_id: str, worker: str, progress: dict,
                      event_fields: Optional[dict] = None,
                      gen: Optional[int] = None) -> Job:
        """A unit completed: merge progress, reset the consecutive-
        failure counter (deaths are only poison when consecutive) and
        renew the lease — one locked write, so the worker's per-unit
        store-write sequence stays deterministic for the chaos
        harness's write counter. `event_fields` carries the worker's
        batch telemetry (seeds/s, elapsed, device count) into the
        `batch_done` event.

        `gen` is the worker's fencing token: a reclaimed generation's
        progress raises FencedWrite and merges NOTHING — a zombie must
        not resurrect the lease, reset the attempt counter, or clobber
        the current holder's progress. Pre-fencing callers (gen None)
        keep the worker-identity-only lease renewal."""
        ev: List[dict] = []
        fenced: List[bool] = [False]

        def mut(job: Job) -> None:
            if self._fenced(job, worker, gen, "note_progress", ev):
                fenced[0] = True
                return
            was_plateau = bool(job.progress.get("plateau"))
            job.progress = {**job.progress, **progress}
            job.attempt = 0
            job.requeue_after_ts = None
            if job.lease and job.lease["worker"] == worker:
                job.lease["expires_ts"] = round(
                    time.time() + job.lease["ttl_s"], 3
                )
            rec = {"type": "batch_done", "worker": worker,
                   "batch": job.progress.get("batches_run"),
                   "coverage_slots": job.progress.get("coverage_slots"),
                   "escalation": job.progress.get("escalation"),
                   "failing": job.progress.get("failing")}
            if job.lease:
                rec["gen"] = job.lease.get("gen", 0)
            rec.update(event_fields or {})
            ev.append(rec)
            if not was_plateau and bool(job.progress.get("plateau")):
                ev.append({"type": "plateau", "worker": worker,
                           "batch": job.progress.get("batches_run")})

        out = self._update(job_id, mut, ev)
        if fenced[0]:
            raise FencedWrite(job_id, worker, gen, "note_progress")
        return out

    def record_death(self, job_id: str, *, reason: str,
                     worker: Optional[str] = None,
                     error: Optional[str] = None,
                     batch_index: Optional[int] = None,
                     max_attempts: int = MAX_ATTEMPTS,
                     backoff_base_s: float = REQUEUE_BACKOFF_BASE_S,
                     lease_reclaim: bool = False,
                     require_expired_lease: bool = False,
                     gen: Optional[int] = None) -> Optional[Job]:
        """One worker death (expired lease) or worker-reported hard
        failure on this job: bump the consecutive-attempt counter and
        either requeue with exponential backoff — checkpoint preserved,
        so the next worker resumes at <=1 lost batch — or, at
        `max_attempts`, quarantine with the full post-mortem (last
        exception, batch index, repro command). Returns the updated job,
        or None when the guarded re-check made this a no-op (e.g. the
        lease was renewed between the sweep's scan and the lock).

        A worker SELF-reporting a failure passes its fencing token:
        a zombie's death report from a dead generation must not clear
        the current holder's lease or burn an attempt on a job someone
        else is running — it is counted and dropped (returns None,
        no raise: the reporter was abandoning the job anyway)."""
        now = time.time()
        done: List[Optional[Job]] = [None]

        def mut(job: Job) -> None:
            if self._fenced(job, worker, gen, "record_death", ev):
                return
            if job.state not in LEASABLE:
                return
            if require_expired_lease and not (
                job.lease and job.lease["expires_ts"] <= now
            ):
                return
            job.attempt += 1
            if lease_reclaim:
                job.n_lease_reclaims += 1
            job.deaths.append({
                "ts": round(now, 3),
                "reason": reason,
                "worker": worker,
                "state": job.state,
                "error": error,
                "batch_index": batch_index,
                "attempt": job.attempt,
            })
            job.lease = None
            if error is not None:
                job.error = error
            if job.attempt >= max_attempts:
                job.quarantine = {
                    "reason": (
                        f"{job.attempt} consecutive failed attempts "
                        f"({reason})"
                    ),
                    "error": error,
                    "batch_index": batch_index,
                    "attempts": job.attempt,
                    "deaths": job.deaths[-max_attempts:],
                    "repro": repro_cmd(job.spec, batch_index=batch_index),
                }
                job.state = QUARANTINED
                job.history.append([round(now, 3), QUARANTINED])
                job.requeue_after_ts = None
                ev.append({"type": "quarantined", "worker": worker,
                           "reason": job.quarantine["reason"],
                           "batch": batch_index})
            else:
                job.n_requeues += 1
                job.requeue_after_ts = round(
                    now + backoff_base_s * (2 ** (job.attempt - 1)), 3
                )
                if job.state != QUEUED:
                    job.state = QUEUED
                    job.history.append([round(now, 3), QUEUED])
                ev.append({"type": "requeued", "cause": reason,
                           "worker": worker, "attempt": job.attempt,
                           "backoff_s": round(
                               backoff_base_s * (2 ** (job.attempt - 1)), 3),
                           "batch": batch_index})
            done[0] = job

        ev: List[dict] = []
        self._update(job_id, mut, ev)
        if done[0] is not None:
            self._clear_claim(job_id)  # the lease is gone either way
        return done[0]

    def reclaim_expired(self, *, max_attempts: int = MAX_ATTEMPTS,
                        backoff_base_s: float = REQUEUE_BACKOFF_BASE_S,
                        via_index: bool = False) -> List[dict]:
        """The supervisor sweep: every non-terminal job whose worker
        lease expired is a worker death — requeue it (or quarantine at
        the attempt cap) via `record_death`. Runs in `fleet serve`'s
        sweep thread, in `fleet fsck --reclaim`, and at the top of every
        worker lease poll, so a farm with ANY live component reclaims.
        Returns one action record per reclaimed job.

        `via_index=True` sweeps from the queue-log index instead of
        re-reading every document — the worker-poll variant, O(1) when
        nothing expired. Safe against a lagging index: `record_death`
        re-validates the expiry under the job's lock, so a stale row
        is a no-op (a MISSING row is healed by the serve sweep's
        `sync_queue_log`, which runs the full-scan variant)."""
        now = time.time()
        actions = []
        if via_index:
            sweep = [
                SimpleNamespace(
                    id=row["job"], state=row.get("state"),
                    lease=(
                        {"worker": row.get("worker"),
                         "expires_ts": row.get("lease_expires_ts")}
                        if row.get("worker") else None
                    ),
                    error=None,
                )
                for row in list(self.queue_rows().values())
            ]
        else:
            sweep = self.list()
        for job in sweep:
            if job.state not in LEASABLE or not job.lease:
                continue
            if (job.lease["expires_ts"] or 0) > now:
                continue
            dead_worker = job.lease["worker"]
            try:
                out = self.record_death(
                    job.id,
                    reason="lease expired",
                    worker=dead_worker,
                    error=job.error,
                    batch_index=self._ckpt_batch(job.id),
                    max_attempts=max_attempts,
                    backoff_base_s=backoff_base_s,
                    lease_reclaim=True,
                    require_expired_lease=True,
                )
            except (KeyError, CorruptJobFile):
                continue  # index row outlived its doc: fsck's problem
            if out is not None:
                actions.append({
                    "job": out.id,
                    "worker": dead_worker,
                    "outcome": out.state,
                    "attempt": out.attempt,
                    "requeue_after_ts": out.requeue_after_ts,
                })
        return actions

    def release_quarantined(self, job_id: str) -> Job:
        """The explicit operator edge out of quarantine: back to
        `queued` with the attempt counter reset. The quarantine
        post-mortem stays on the document (audit trail) until a fresh
        quarantine overwrites it."""

        ev: List[dict] = []

        def mut(job: Job) -> None:
            if job.state != QUARANTINED:
                raise ValueError(
                    f"job {job.id} is {job.state}, not quarantined"
                )
            job.state = QUEUED
            job.history.append([round(time.time(), 3), QUEUED])
            job.attempt = 0
            job.requeue_after_ts = None
            job.n_requeues += 1
            ev.append({"type": "requeued",
                       "cause": "released from quarantine"})

        return self._update(job_id, mut, ev)

    def degrade_lanes(self, job_id: str, *, error: str,
                      worker: Optional[str] = None,
                      gen: Optional[int] = None) -> Job:
        """OOM lane-count backoff: halve the job's `batch` and requeue
        it, instead of burning attempts on a shape that cannot
        allocate. `batch` is a fingerprint field, so the fingerprint /
        spec sha / warm-compile subkey are re-derived and re-recorded
        (a deliberate, audited re-spec — NOT silent drift), and the old
        checkpoint — whose fingerprint no longer matches — is removed:
        the job restarts its seed schedule at the smaller shape.
        Correctness over progress; the degradation is recorded in
        `job.degraded`."""
        new_batch: List[int] = [0]
        ev: List[dict] = []
        fenced: List[bool] = [False]

        def mut(job: Job) -> None:
            if self._fenced(job, worker, gen, "degrade_lanes", ev):
                fenced[0] = True
                return
            if job.terminal:
                return
            nb = max(1, job.spec["batch"] // 2)
            new_batch[0] = nb
            job.degraded.append({
                "ts": round(time.time(), 3),
                "from_batch": job.spec["batch"],
                "to_batch": nb,
                "error": error,
                "worker": worker,
            })
            ev.append({"type": "degraded", "worker": worker,
                       "from_batch": job.spec["batch"], "to_batch": nb})
            job.spec = {**job.spec, "batch": nb}
            job.fingerprint = job_fingerprint(job.spec)
            job.fingerprint_sha = spec_sha(job.spec)
            job.subkey = job_subkey(job.spec)
            job.lease = None
            job.requeue_after_ts = None
            job.n_requeues += 1
            if job.state != QUEUED:
                job.state = QUEUED
                job.history.append([round(time.time(), 3), QUEUED])
            ev.append({"type": "requeued", "cause": "lane degradation",
                       "worker": worker})

        out = self._update(job_id, mut, ev)
        if fenced[0]:
            raise FencedWrite(job_id, worker or "?", gen, "degrade_lanes")
        self._clear_claim(job_id)  # requeued: the hold is over
        with contextlib.suppress(OSError):
            os.remove(self.ckpt_path(job_id))
        return out

    def _ckpt_batch(self, job_id: str) -> Optional[int]:
        """Best-effort batch index from the job's checkpoint (for death
        post-mortems); None when there is no readable checkpoint."""
        try:
            with open(self.ckpt_path(job_id)) as f:
                return int(json.load(f).get("batch", 0))
        except (OSError, ValueError, TypeError):
            return None

    def stale_leases(self) -> int:
        """How many non-terminal jobs hold an expired lease right now
        (the `/healthz` gauge; the next sweep will reclaim them)."""
        now = time.time()
        return sum(
            1 for j in self.list()
            if j.state in LEASABLE and j.lease
            and j.lease["expires_ts"] <= now
        )

    # -- drift refusal -------------------------------------------------------

    def fingerprint_mismatch(self, job: Job) -> Optional[str]:
        """None when the job's spec still hashes to its recorded
        fingerprint; otherwise a message naming EVERY drifted field —
        the same shape the checkpoint refusal prints, surfaced verbatim
        as the job's `failed` reason."""
        want = job_fingerprint(job.spec)
        diffs = [
            f"{f} (recorded {job.fingerprint.get(f)!r}, now {want.get(f)!r})"
            for f in sorted(set(want) | set(job.fingerprint))
            if job.fingerprint.get(f) != want.get(f)
        ]
        if spec_sha(job.spec) != job.fingerprint_sha and not diffs:
            diffs = ["spec hash (non-fingerprint field edited)"]
        if not diffs:
            return None
        return (
            f"job {job.id}: spec drifted since submit — refusing to run; "
            "differing: " + ", ".join(diffs)
        )

    # -- live feed -----------------------------------------------------------

    def read_feed(self, job_id: str, last: int = 20) -> List[dict]:
        """The job's live per-batch coverage/failure feed: the tail of
        its StatsEmitter JSONL, parsed. Missing file = empty feed (the
        job has not started streaming yet)."""
        path = self.stats_base(job_id) + ".jsonl"
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            return []
        out = []
        for line in lines[-max(0, last):]:
            with contextlib.suppress(json.JSONDecodeError):
                out.append(json.loads(line))
        return out
