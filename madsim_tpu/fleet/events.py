"""The fleet's flight recorder — an append-only job-lifecycle event log.

One ``jobs/<id>.events.jsonl`` per job: a typed, `seq`-monotonic,
wall-stamped record of every state-machine transition plus the
batch/find/shrink milestones in between. The store emits events at the
same call sites that already hold the per-job lock, so the log is the
authoritative *ordered* history of a job — what the 30 s long-poll can
only sample, the log records.

Three consumers ride on it (all jax-free, all host-side):

* **push, not poll** — `GET /jobs/{id}/events?since=SEQ` tails the log
  as Server-Sent Events, so a CI caller sees `find` at find-time;
* **cross-process trace correlation** — the job id doubles as a trace
  id; `timeline_doc` merges these lifecycle events with the worker's
  span dump into one Perfetto timeline spanning both processes;
* **SLO metrics** — `/metrics` histograms (queue wait, time to first
  find, lane-seconds and batches per find) are pure deltas over this
  log, computed at scrape time, never stored.

Durability discipline: records are appended with
`runtime.atomicio.append_text` (fsync'd, newline-healing). Appends are
deliberately NOT atomic — a crash mid-append leaves a torn line in the
real file. `read_events` skips torn records, `fleet fsck` verdicts the
file `torn-tail` without quarantining (same policy as stats feeds),
and `last_seq` re-anchors past the damage, so the sequence stays
monotonic across any number of mid-append deaths. That torn-tolerant
JSONL-not-a-DB shape is the point: the log must survive exactly the
crashes the fleet is built to inject.

Determinism: events are observability-class. Nothing here feeds specs,
fingerprints, seed schedules, the corpus, or job reports — a run with
events disabled (``MADSIM_TPU_FLEET_EVENTS=0``) produces byte-identical
reports to one with events enabled.
"""

# madsim: allow-file(D001) — wall timestamps ARE this module's contract
# (exactly like perf/recorder.py): every event carries the host wall
# clock so operators can correlate the log with CI logs, Prometheus
# scrapes and worker Perfetto dumps. No timestamp ever reaches a spec,
# a fingerprint, or a seed schedule.

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterator, List, Optional

from ..runtime.atomicio import append_text

#: the closed event taxonomy (ARCHITECTURE.md "Fleet observability").
#: Lifecycle events are named after the state-machine states they
#: enter; milestone events mark progress inside a state.
EVENT_TYPES = (
    # lifecycle (state entered)
    "submitted", "queued", "compiling", "running", "plateaued",
    "exhausted", "found", "shrunk", "filed", "cancelled", "failed",
    "quarantined",
    # lease / scheduling milestones ("fenced" = a write from a dead
    # lease generation was rejected and counted, never merged)
    "leased", "requeued", "degraded", "cancel_requested", "fenced",
    # progress milestones
    "batch_done", "plateau", "find", "shrink_started", "shrink_done",
)

#: lifecycle events that end a job (mirrors store.TERMINAL)
TERMINAL_EVENTS = frozenset({
    "plateaued", "exhausted", "filed", "cancelled", "failed",
    "quarantined",
})

#: lifecycle events that open a queue-wait interval (until next lease)
_QUEUE_EVENTS = ("submitted", "requeued")

#: events that open a named lifecycle slice in the merged timeline
_SLICE_OPENERS = frozenset({
    "leased", "compiling", "running", "plateaued", "exhausted", "found",
    "shrunk", "filed", "cancelled", "failed", "quarantined",
})

_TAIL_BYTES = 8192


def enabled() -> bool:
    """Event emission kill-switch. On by default; ``=0`` disables every
    append (the determinism acceptance test runs both ways and asserts
    byte-identical job reports)."""
    return os.environ.get("MADSIM_TPU_FLEET_EVENTS", "1") != "0"


def last_seq(path: str) -> int:
    """Highest `seq` recorded in the log (0 when absent/empty). Reads
    only the file tail and parses backwards, skipping torn records, so
    a mid-append crash never resets the sequence."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - _TAIL_BYTES))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return 0
    for line in reversed(tail.splitlines()):
        try:
            rec = json.loads(line)
            return int(rec["seq"])
        except (ValueError, KeyError, TypeError):
            continue
    return 0


def tail_event(path: str) -> Optional[dict]:
    """The last parseable event record (None when absent/empty) — a
    tail read, cheap enough for per-job queue summaries."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - _TAIL_BYTES))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "seq" in rec:
            return rec
    return None


def emit_event(path: str, type_: str, *, job: Optional[str] = None,
               worker: Optional[str] = None, **fields) -> dict:
    """Append one event record and return it. `seq` continues from the
    log's current tail; `ts` is the host wall clock (observability
    only). Compact one-line JSON, fsync'd append."""
    assert type_ in EVENT_TYPES, f"unknown event type {type_!r}"
    rec: Dict[str, object] = {
        "seq": last_seq(path) + 1,
        "ts": round(time.time(), 3),
        "type": type_,
    }
    if job is not None:
        rec["job"] = job
    if worker is not None:
        rec["worker"] = worker
    for k, v in sorted(fields.items()):
        if v is not None:
            rec[k] = v
    append_text(path, json.dumps(rec, sort_keys=True,
                                 separators=(",", ":")) + "\n")
    return rec


def read_events(path: str, since: int = 0) -> List[dict]:
    """All events with `seq > since`, in file order. Torn or
    unparseable lines are skipped (they are expected append damage,
    never an error), as are records missing a usable `seq`."""
    out: List[dict] = []
    try:
        with open(path, "r") as f:
            lines = f.read().splitlines()
    except OSError:
        return out
    for line in lines:
        try:
            rec = json.loads(line)
            seq = int(rec["seq"])
        except (ValueError, KeyError, TypeError):
            continue
        if seq > since:
            out.append(rec)
    return out


def iter_jsonl(path: str) -> Iterator[dict]:
    """Lenient JSONL reader for sibling feeds (span dumps): yields each
    parseable dict line, skips torn records."""
    try:
        with open(path, "r") as f:
            lines = f.read().splitlines()
    except OSError:
        return
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            yield rec


# -- SLO derivation (scrape-time deltas; nothing is ever stored) ----------


def slo_observations(events: List[dict]) -> Dict[str, float]:
    """Per-job SLO observations derived purely from event deltas.

    * ``queue_wait_s``      — first `submitted`/`requeued` → next `leased`
    * ``time_to_first_find_s`` — `submitted` → first `find`
    * ``lane_seconds_per_find`` — Σ batch elapsed × device_count up to
      the first find (the lane-time the find cost)
    * ``batches_per_find``  — batches dispatched up to the first find

    Keys are present only when the underlying events exist, so a job
    with no finds contributes nothing to the find histograms.
    """
    obs: Dict[str, float] = {}
    submitted_ts: Optional[float] = None
    waiting_since: Optional[float] = None
    lane_s = 0.0
    batches = 0
    for ev in events:
        t, ts = ev.get("type"), ev.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if t == "submitted":
            submitted_ts = submitted_ts if submitted_ts is not None else ts
            waiting_since = waiting_since if waiting_since is not None else ts
        elif t == "requeued":
            waiting_since = ts
        elif t == "leased":
            if waiting_since is not None and "queue_wait_s" not in obs:
                obs["queue_wait_s"] = max(0.0, ts - waiting_since)
            waiting_since = None
        elif t == "batch_done":
            batches += 1
            lane_s += (float(ev.get("elapsed_s") or 0.0)
                       * max(1, int(ev.get("device_count") or 1)))
        elif t == "find" and "time_to_first_find_s" not in obs:
            if submitted_ts is not None:
                obs["time_to_first_find_s"] = max(0.0, ts - submitted_ts)
            obs["lane_seconds_per_find"] = lane_s
            obs["batches_per_find"] = float(max(1, batches))
    return obs


# -- cross-process timeline merge (Perfetto / chrome://tracing) -----------


def _us(ts: float, t_base: float) -> int:
    return int(round((ts - t_base) * 1e6))


def timeline_doc(job_doc: dict, events: List[dict],
                 span_records: List[dict]) -> dict:
    """One Perfetto timeline per job across the serve/worker boundary.

    pid 0 is the control plane's view: lifecycle slices tiling
    submit → terminal (queue waits named ``queue_wait``, every other
    slice named after the state), per-batch slices reconstructed from
    `batch_done` deltas, shrink bracketed by its start/done events, and
    every event as an instant. pid 1..N are the workers' `PerfRecorder`
    span dumps, re-anchored from their wall_t0 onto the shared wall
    clock — the job id is the trace id that joins the two processes.

    The summary's ``attribution`` is the fraction of the job's wall
    clock covered by named lifecycle slices (the PR 9 ≥90% bar, now
    spanning both processes).
    """
    traceEvents: List[dict] = []
    job_id = job_doc.get("id", "?")
    ts_events = [e for e in events if isinstance(e.get("ts"), (int, float))]
    if not ts_events:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "madsim_fleet_timeline_summary": {
                    "job": job_id, "attribution": 0.0, "wall_s": 0.0,
                    "events": 0, "worker_spans": 0}}
    t_base = ts_events[0]["ts"]
    t_end = ts_events[-1]["ts"]

    traceEvents.append({"ph": "M", "pid": 0, "tid": 0,
                        "name": "process_name",
                        "args": {"name": "fleet control plane"}})
    traceEvents.append({"ph": "M", "pid": 0, "tid": 0,
                        "name": "thread_name", "args": {"name": "lifecycle"}})
    traceEvents.append({"ph": "M", "pid": 0, "tid": 1,
                        "name": "thread_name", "args": {"name": "progress"}})

    # lifecycle slices: tile the wall clock with named intervals
    slices: List[tuple] = []  # (start_ts, end_ts, name, args)
    cursor: Optional[tuple] = None  # (start_ts, name, args)
    for ev in ts_events:
        t, ts = ev["type"], ev["ts"]
        if t in _QUEUE_EVENTS:
            nxt = ("queue_wait", {"cause": t})
        elif t in _SLICE_OPENERS:
            # a lease or a state-entry event opens the next interval
            # ("queued" is folded into the queue_wait its "submitted"
            # or "requeued" sibling already opened)
            nxt = (t, {k: v for k, v in ev.items()
                       if k not in ("seq", "ts", "type", "job")})
        else:
            nxt = None
        if nxt is not None:
            if cursor is not None:
                slices.append((cursor[0], ts, cursor[1], cursor[2]))
            cursor = (ts, nxt[0], nxt[1])
            if t in TERMINAL_EVENTS:
                cursor = None
    if cursor is not None:
        slices.append((cursor[0], t_end, cursor[1], cursor[2]))
    for start, end, name, args in slices:
        traceEvents.append({
            "ph": "X", "pid": 0, "tid": 0, "name": name, "cat": "lifecycle",
            "ts": _us(start, t_base), "dur": max(1, _us(end, t_base) -
                                                 _us(start, t_base)),
            "args": dict(args, trace_id=job_id)})

    # progress thread: batch slices (reconstructed from elapsed_s),
    # shrink bracket, and every event as an instant
    shrink_start: Optional[float] = None
    for ev in ts_events:
        t, ts = ev["type"], ev["ts"]
        if t == "batch_done":
            el = float(ev.get("elapsed_s") or 0.0)
            traceEvents.append({
                "ph": "X", "pid": 0, "tid": 1, "cat": "progress",
                "name": f"batch {ev.get('batch', '?')}",
                "ts": _us(ts - el, t_base), "dur": max(1, int(el * 1e6)),
                "args": {k: ev[k] for k in
                         ("seeds_per_sec", "coverage_slots", "escalation",
                          "device_count") if k in ev}})
        elif t == "shrink_started":
            shrink_start = ts
        elif t == "shrink_done" and shrink_start is not None:
            traceEvents.append({
                "ph": "X", "pid": 0, "tid": 1, "cat": "progress",
                "name": "shrink", "ts": _us(shrink_start, t_base),
                "dur": max(1, _us(ts, t_base) - _us(shrink_start, t_base)),
                "args": {k: ev[k] for k in ("finds", "shrunk") if k in ev}})
            shrink_start = None
        traceEvents.append({
            "ph": "i", "pid": 0, "tid": 1, "name": t, "cat": "event",
            "ts": _us(ts, t_base), "s": "t",
            "args": {"seq": ev.get("seq"), "worker": ev.get("worker")}})

    # worker span dumps, re-anchored via their wall_t0
    n_spans = 0
    workers: Dict[str, int] = {}
    for rec in span_records:
        wall_t0 = rec.get("wall_t0")
        if not isinstance(wall_t0, (int, float)):
            continue
        wid = str(rec.get("worker", "worker"))
        is_new = wid not in workers
        pid = workers.setdefault(wid, 1 + len(workers))
        offset = _us(wall_t0, t_base)
        if is_new:
            traceEvents.append({"ph": "M", "pid": pid, "tid": 0,
                                "name": "process_name",
                                "args": {"name": f"worker {wid}"}})
            traceEvents.append({"ph": "M", "pid": pid, "tid": 0,
                                "name": "thread_name",
                                "args": {"name": "host"}})
        for sp in rec.get("spans") or []:
            try:
                if sp.get("dur") is None:
                    # recorder instants (e.g. the xprof ``madsim.sync``
                    # clock-sync markers) ride along as ph "i" so the
                    # /profile merge can align the device clock on them
                    traceEvents.append({
                        "ph": "i", "s": "t", "pid": pid, "tid": 0,
                        "cat": "worker", "name": str(sp["name"]),
                        "ts": offset + int(sp["ts"]),
                        "args": dict(sp.get("args") or {},
                                     trace_id=job_id)})
                else:
                    traceEvents.append({
                        "ph": "X", "pid": pid, "tid": 0, "cat": "worker",
                        "name": str(sp["name"]),
                        "ts": offset + int(sp["ts"]),
                        "dur": max(1, int(sp["dur"])),
                        "args": dict(sp.get("args") or {},
                                     trace_id=job_id)})
                n_spans += 1
            except (KeyError, TypeError, ValueError):
                continue

    wall_s = max(0.0, t_end - t_base)
    covered = _interval_union_s(
        [(s, e) for s, e, _n, _a in slices]) if slices else 0.0
    attribution = 1.0 if wall_s <= 0 else min(1.0, covered / wall_s)
    return {
        "traceEvents": traceEvents,
        "displayTimeUnit": "ms",
        "madsim_fleet_timeline_summary": {
            "job": job_id,
            "trace_id": job_id,
            "attribution": round(attribution, 4),
            "wall_s": round(wall_s, 3),
            "events": len(ts_events),
            "worker_spans": n_spans,
            "state": job_doc.get("state"),
        },
    }


def _interval_union_s(intervals: List[tuple]) -> float:
    total = 0.0
    last_end = None
    for start, end in sorted(intervals):
        if last_end is None or start > last_end:
            total += max(0.0, end - start)
            last_end = end
        elif end > last_end:
            total += end - last_end
            last_end = end
    return total
