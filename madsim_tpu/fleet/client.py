"""Thin stdlib HTTP client for the fleet control plane.

`python -m madsim_tpu fleet submit|status|result|cancel|queue` wrap
these calls; scripts can import them directly. Discovery mirrors the
server side: `--addr host:port`, or `--port-file PATH` (the file
`fleet serve --port-file` / `serve --port-file` writes atomically)
resolves to `127.0.0.1:<port>` without racing the daemon's startup.

Jax-free by construction — the client runs on boxes with no
accelerator stack at all.
"""

from __future__ import annotations

import json
import os
import random
import time
import urllib.error
import urllib.request
from typing import Iterator, Optional, Tuple

from . import httpd

DEFAULT_ADDR = "127.0.0.1:8142"

#: HTTP statuses worth retrying: the server is restarting or shedding
#: load, not rejecting the request. Every other status (400 validation,
#: 404, 409 not-terminal-yet) fails immediately — retrying a refusal
#: only hides it. 429 is retried too, but on the server's own schedule:
#: the admission layer names its price (Retry-After header +
#: `retry_after_s` body field) and the client honors it instead of
#: guessing with exponential backoff.
TRANSIENT_HTTP = frozenset({502, 503, 504})
DEFAULT_RETRIES = 5
RETRY_BACKOFF_S = 0.1
RETRY_BACKOFF_MAX_S = 2.0


class FleetClientError(RuntimeError):
    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        #: the server's Retry-After in seconds (429 admission refusals;
        #: the JSON body's sub-second `retry_after_s` wins over the
        #: header's integer rendering), None when the server named none
        self.retry_after = retry_after


def resolve_addr(addr: Optional[str] = None,
                 port_file: Optional[str] = None,
                 wait_s: float = 5.0) -> str:
    """Pick the daemon address: explicit --addr wins, then --port-file
    (polled up to `wait_s` — the file appears atomically once the
    server has bound), then $MADSIM_TPU_FLEET_ADDR, then the default."""
    if addr:
        return addr
    if port_file:
        # madsim: allow(D001) — host-side startup-discovery poll
        deadline = time.monotonic() + wait_s
        while True:
            try:
                return f"127.0.0.1:{httpd.read_port_file(port_file)}"
            except (OSError, ValueError):
                if time.monotonic() > deadline:  # madsim: allow(D001)
                    raise RuntimeError(
                        f"port file {port_file!r} did not appear within "
                        f"{wait_s}s — is the daemon running?"
                    ) from None
                time.sleep(0.05)  # madsim: allow(D001)
    return os.environ.get("MADSIM_TPU_FLEET_ADDR", DEFAULT_ADDR)


def _request_once(addr: str, method: str, path: str,
                  body: Optional[dict], timeout: float) -> Tuple[int, dict]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f"http://{addr}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as exc:
        payload = exc.read().decode(errors="replace")
        retry_after = None
        try:
            doc = json.loads(payload)
            msg = doc.get("error", payload)
            if doc.get("retry_after_s") is not None:
                retry_after = float(doc["retry_after_s"])
        except (json.JSONDecodeError, TypeError, ValueError):
            msg = payload
        if retry_after is None:
            try:
                retry_after = float(exc.headers.get("Retry-After"))
            except (TypeError, ValueError):
                retry_after = None
        raise FleetClientError(exc.code, msg, retry_after) from None


def request(addr: str, method: str, path: str,
            body: Optional[dict] = None,
            timeout: float = 30.0,
            retries: int = DEFAULT_RETRIES) -> Tuple[int, dict]:
    """One control-plane call, with transient-failure retry: connection
    refused/reset (the daemon is restarting — `fleet serve` comes back
    on the same port-file), socket timeouts, and 502/503/504 are
    retried up to `retries` times with seeded-jitter exponential
    backoff; every other HTTP error raises immediately. `retries=0`
    (the `--no-retry` escape hatch) restores fail-fast.

    The jitter RNG is SEEDED from (method, path) — the repo's
    discipline extends to its backoff schedules: two runs of the same
    verb jitter identically, so a chaos failure replays.

    Caveat: a connection cut AFTER the server processed a POST but
    before the response arrived retries into a second submit (two
    identical jobs, distinct ids). The store runs both to the same
    byte-identical report, so the cost is compute, not correctness."""
    rng = random.Random(f"fleet-client {method} {path}")
    attempt = 0
    while True:
        try:
            return _request_once(addr, method, path, body, timeout)
        except FleetClientError as exc:
            retryable = exc.status in TRANSIENT_HTTP or exc.status == 429
            if not retryable or attempt >= retries:
                raise
            if exc.status == 429 and exc.retry_after is not None:
                # admission refusal: wait what the server asked, plus
                # seeded jitter so a shed burst doesn't re-arrive as
                # one synchronized herd
                time.sleep(exc.retry_after  # madsim: allow(D001)
                           + RETRY_BACKOFF_S * rng.random())
                attempt += 1
                continue
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError):
            # URLError wraps ECONNREFUSED during a server restart
            if attempt >= retries:
                raise
        delay = min(RETRY_BACKOFF_S * (2 ** attempt), RETRY_BACKOFF_MAX_S)
        time.sleep(delay * (0.5 + rng.random()))  # madsim: allow(D001)
        attempt += 1


def submit(addr: str, spec: dict, *, priority: int = 0,
           deadline_s: Optional[float] = None,
           tenant: Optional[str] = None,
           retries: int = DEFAULT_RETRIES) -> dict:
    doc = {"spec": spec, "priority": priority}
    if deadline_s:
        doc["deadline_s"] = deadline_s
    if tenant:
        doc["tenant"] = tenant  # admission accounting, not spec
    _, out = request(addr, "POST", "/jobs", doc, retries=retries)
    return out


def status(addr: str, job_id: str, feed: int = 20, wait: float = 0,
           retries: int = DEFAULT_RETRIES) -> dict:
    """Job doc + live feed. `wait > 0` long-polls: the server holds the
    request until the job document or its stats feed changes (or the
    window — capped server-side — elapses), so watchers make one
    request per state change instead of busy-polling. The client
    timeout stretches past the wait window."""
    path = f"/jobs/{job_id}?feed={feed}"
    if wait:
        path += f"&wait={wait:g}"
    _, out = request(addr, "GET", path, timeout=30.0 + float(wait),
                     retries=retries)
    return out


def result(addr: str, job_id: str,
           retries: int = DEFAULT_RETRIES) -> dict:
    _, out = request(addr, "GET", f"/jobs/{job_id}/result",
                     retries=retries)
    return out


def cancel(addr: str, job_id: str,
           retries: int = DEFAULT_RETRIES) -> dict:
    _, out = request(addr, "DELETE", f"/jobs/{job_id}", retries=retries)
    return out


def queue(addr: str, retries: int = DEFAULT_RETRIES) -> dict:
    _, out = request(addr, "GET", "/queue", retries=retries)
    return out


def timeline(addr: str, job_id: str,
             retries: int = DEFAULT_RETRIES) -> dict:
    """The merged cross-process Perfetto timeline for a job."""
    _, out = request(addr, "GET", f"/jobs/{job_id}/timeline",
                     retries=retries)
    return out


def profile(addr: str, job_id: str,
            retries: int = DEFAULT_RETRIES) -> dict:
    """The three-clock merged profile for a job: the timeline's host
    plane + the worker's device-profile capture and failing-lane
    virtual trace (present when the worker ran under
    MADSIM_TPU_XPROF=1), aligned by xprof clock-sync markers."""
    _, out = request(addr, "GET", f"/jobs/{job_id}/profile",
                     retries=retries)
    return out


# -- the SSE tail (push, not poll) ----------------------------------------


def parse_sse(fp) -> Iterator[dict]:
    """Parse a Server-Sent-Events byte stream into
    `{"id", "event", "data"}` frames (data JSON-decoded when possible).
    Factored off the socket so the parser unit-tests against a
    BytesIO."""
    frame: dict = {}
    for raw in fp:
        line = raw.decode("utf-8", errors="replace").rstrip("\r\n")
        if not line:
            if "data" in frame or "event" in frame:
                data = frame.get("data")
                try:
                    frame["data"] = json.loads(data) if data else None
                except json.JSONDecodeError:
                    pass  # leave the raw string — the caller decides
                yield frame
            frame = {}
            continue
        if line.startswith(":"):
            continue  # SSE comment / keepalive
        key, _, value = line.partition(":")
        value = value[1:] if value.startswith(" ") else value
        if key == "data" and "data" in frame:
            frame["data"] += "\n" + value
        elif key in ("id", "event", "data", "retry"):
            frame[key] = value
    if "data" in frame or "event" in frame:
        yield frame


def iter_events(addr: str, job_id: str, since: int = 0,
                timeout: float = 45.0) -> Iterator[dict]:
    """Tail a job's event stream: yields each SSE frame, transparently
    reconnecting with `since=<last id>` when the server's tail-poll
    window closes the stream. Ends (without reconnecting) after an
    `end` frame — the job reached a terminal state — or an `error`
    frame. The per-request timeout must outlast the server's
    WAIT_CAP_S window."""
    cursor = int(since)
    while True:
        req = urllib.request.Request(
            f"http://{addr}/jobs/{job_id}/events?since={cursor}",
            headers={"Accept": "text/event-stream"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                for frame in parse_sse(resp):
                    ev = frame.get("event")
                    if frame.get("id"):
                        try:
                            cursor = max(cursor, int(frame["id"]))
                        except ValueError:
                            pass
                    if ev == "error":
                        data = frame.get("data")
                        msg = (data or {}).get("error") if isinstance(
                            data, dict) else str(data)
                        raise FleetClientError(503, msg or "stream error")
                    yield frame
                    if ev == "end":
                        return
        except urllib.error.HTTPError as exc:
            payload = exc.read().decode(errors="replace")
            try:
                msg = json.loads(payload).get("error", payload)
            except json.JSONDecodeError:
                msg = payload
            raise FleetClientError(exc.code, msg) from None
        # stream closed without `end`: the tail-poll window elapsed —
        # reconnect from the cursor (push-not-poll with bounded parks)
