"""The fleet-chaos harness — test the farm with its own medicine.

The repo's whole thesis (PAPER.md) is that recovery code is exactly the
code you cannot trust until you have injected every failure
deterministically. The fleet IS recovery code — leases, requeues,
quarantine, fsck — so it gets the same treatment the simulated
protocols get: a seeded schedule of process-level faults, derived from
ONE RNG so a failing seed reproduces forever, with the invariants
checked after every schedule:

* **no accepted job is ever lost** — every submitted job reaches a
  real terminal state with a result, never `failed`/`cancelled`, and a
  healthy job quarantined by genuinely-consecutive deaths is released
  and completes;
* **byte-identical recovery** — each job's final `result.report` is
  byte-identical to an unperturbed oracle farm's run of the same spec
  (the PR-11 resume guarantee, now across worker replacement, torn
  writes and lease-clock jumps);
* **the store heals** — the final fsck leaves zero corrupt files and
  zero stale tmp files;
* (`--real` only) **every filed find still `regress`-replays**.

The fault vocabulary (`derive_schedule`):

``kill_worker``   SIGKILL the worker at its k-th store write (injected
                  at the shared `runtime/atomicio` write point — "at
                  step k" is an instrumented, replayable place, not a
                  wall-clock race)
``torn_write``    the kill lands mid-write: b bytes of the k-th payload
                  reach the tmp file, the rename never runs — the
                  atomicity claim under test is that the final path
                  keeps its previous version
``corrupt_ckpt``  external corruption: truncate a checkpoint's FINAL
                  file at byte b (what a dying disk — not the farm's
                  own fsync'd writes — can produce); the lenient reader
                  must quarantine it and restart the stream
``lease_jump``    jump the lease clock: expire every live lease on
                  disk, then run the reclamation sweep (requeue with
                  backoff / quarantine at the cap)
``server_bounce`` SIGKILL `fleet serve`, issue a client verb INTO the
                  outage (the seeded-jitter retry must carry it), then
                  restart the server on the same port — a bounce-window
                  submit grows the accepted-jobs set the invariants
                  track
``sigterm_worker``  graceful kill: SIGTERM the worker at its k-th
                  store write (same instrumented injection point as
                  ``kill_worker``). The invariant under test is the
                  crash-flush path: the dying worker must leave a
                  non-empty span dump — its open spans materialized
                  as ``partial`` — so the killed unit's
                  `fleet timeline` is never empty
``clean_units``   run k units with no fault (progress resets the
                  consecutive-attempt counter — quarantine only fires
                  on genuinely consecutive deaths)
``kill_event_append``  the kill lands mid-append to a job's
                  `.events.jsonl`: b bytes of the k-th event record
                  reach the REAL file (appends are fsync'd but not
                  atomic, by design), then SIGKILL — the next append's
                  healing newline must confine the torn record to its
                  own line, readers skip it, and the job's lifecycle
                  (and byte-identical report) must be unaffected
``torn_events``   external truncation of a job's `.events.jsonl` at a
                  JSON-structural boundary — fsck must REPORT the torn
                  tail without quarantining the log (it is an append-
                  mode observability stream, not sim state)

The ``claims`` profile (PR 20) races the contention plane itself and
only makes sense with ``--workers N`` > 1 (it still passes at 1 —
the races just never fire):

``claim_race``    SIGKILL one contender at its k-th O_EXCL claim-file
                  create — the other racers must arbitrate around the
                  corpse's stale claim (flock stays authoritative)
``zombie_resume`` SIGSTOP one worker at its k-th CHECKPOINT write (a
                  path written outside every store flock), expire its
                  lease, let a new holder reclaim and finish the job,
                  then SIGCONT the zombie — every resumed write must
                  be REFUSED by its dead fencing generation, counted
                  on the doc, never merged
``lease_jump_one``  jump the lease clock for ONE worker's holdings
                  only (the suspended-VM case): its jobs reclaim while
                  every other lease stays live
``torn_queue_log``  the kill lands mid-append to the shared queue.log:
                  a torn tail reaches the REAL file — index readers
                  must leave it unconsumed, pollers fall back to the
                  docs, and fsck rebuilds the log from them

With ``--workers N`` every worker-running round launches N synthetic
workers CONCURRENTLY against one store (ids ``chaos-w0..``, the armed
chaos plan on a seeded choice of one), and two contention invariants
join the originals: **no (job, batch, generation) is executed by two
workers** (batch_done events are the witness) and **no find is filed
twice** (corpus keys stay unique). The final reports must STILL be
byte-identical to the 1-worker oracle — contention is not allowed to
change a single result byte.

By default workers run the jax-free **synthetic driver** below — the
deterministic stand-in for `_stream_batches` that drives the REAL
checkpoint, stats-emitter and store machinery (the farm paths under
test) without an engine, so one chaos round costs milliseconds and a
32-seed sweep is a lunch break, not a day. `--real` swaps in echo-
machine engines end to end.

Jax-free by contract (the orchestrator and the synthetic driver import
no engine code); `random.Random(seed)` is the repo-sanctioned seeded
constructor.
"""

from __future__ import annotations

# madsim: allow-file(D001) — the orchestrator babysits real processes:
# subprocess timeouts, bounce windows and drain deadlines are host
# wall-clock by nature. Nothing here feeds simulation state; the
# schedule itself is a pure function of the seed.
import contextlib
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import List, Optional

from . import client as fleet_client
from . import fsck as fsck_mod
from .store import (
    FAILED,
    CANCELLED,
    QUARANTINED,
    TERMINAL,
    JobStore,
)

CHAOS_ENV = "MADSIM_TPU_FLEET_CHAOS"

#: action weights per profile (satellite: CI pins one kill-heavy and
#: one torn-heavy seed)
_PROFILES = {
    "kill": (("kill_worker", 5), ("torn_write", 1), ("corrupt_ckpt", 1),
             ("lease_jump", 2), ("server_bounce", 1), ("clean_units", 2),
             ("kill_event_append", 2), ("torn_events", 1)),
    "torn": (("kill_worker", 1), ("torn_write", 5), ("corrupt_ckpt", 2),
             ("lease_jump", 1), ("server_bounce", 1), ("clean_units", 2),
             ("kill_event_append", 1), ("torn_events", 2)),
    "mixed": (("kill_worker", 2), ("torn_write", 2), ("corrupt_ckpt", 1),
              ("lease_jump", 2), ("server_bounce", 1), ("clean_units", 2),
              ("kill_event_append", 1), ("torn_events", 1)),
    # satellite (PR 19): the graceful-kill profile exercises the
    # partial-span crash flush — a NEW profile so the pinned seeds of
    # the profiles above keep their schedules byte-identical
    "spans": (("sigterm_worker", 5), ("kill_worker", 1),
              ("lease_jump", 1), ("clean_units", 2)),
    # PR 20: the contention profile — claim races, zombie resumes,
    # single-worker lease jumps and torn queue-log tails. A NEW
    # profile (same precedent as "spans") so kill/torn/mixed pinned
    # seeds keep their schedules byte-identical
    "claims": (("claim_race", 4), ("zombie_resume", 2),
               ("lease_jump_one", 2), ("torn_queue_log", 2),
               ("kill_worker", 1), ("clean_units", 2)),
}


# -- the synthetic driver ----------------------------------------------------


def synthetic_driver(worker, job, args) -> None:
    """Deterministic jax-free stand-in for one `_stream_batches` unit.

    Everything the farm touches is REAL — the fingerprinted checkpoint
    (strict load + `check_fingerprint` refusal, atomic save), the
    per-job StatsEmitter feed, the store lifecycle the caller drives —
    only the engine between them is simulated: batch results are a pure
    function of (spec, batch index), which is exactly the determinism
    contract the byte-identical oracle invariant needs.

    Magic machine names (farm test fixtures):

    * ``chaos-poison``  raises every attempt once batch index 1 (the
      second batch) is reached — the canonical poison job
    * ``chaos-oom``     raises an OOM-marked error while ``batch`` > 16
      — exercises the lane-count backoff
    * ``chaos-find``    one deterministic failing seed in batch 0 —
      exercises found -> shrunk -> filed under chaos
    """
    import sys as _sys

    from ..runtime.checkpoint import (
        check_fingerprint,
        fingerprint_from_args,
        load_checkpoint,
        save_checkpoint,
    )
    from ..tracing import StatsEmitter

    spec = job.spec
    ck = load_checkpoint(args.checkpoint)
    if ck is not None:
        err = check_fingerprint(ck, args)
        if err:
            _sys.exit(f"--checkpoint {args.checkpoint}: {err}")
    bi = int(ck["batch"]) if ck else 0
    machine = spec["machine"]
    if machine == "chaos-poison" and bi >= 1:
        raise RuntimeError(
            f"poison: model raised in batch {bi + 1} (synthetic fixture)"
        )
    if machine == "chaos-oom" and spec["batch"] > 16:
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: out of memory allocating synthetic "
            f"buffer at {spec['batch']} lanes (fixture)"
        )
    planned = -(-spec["seeds"] // spec["batch"])
    chunk = min(spec["batch"], spec["seeds"] - bi * spec["batch"])
    completed = (int(ck["completed"]) if ck else 0) + chunk
    cursor = (int(ck["cursor"]) if ck else spec["seed"]) + chunk
    failing = [tuple(x) for x in ck["failing"]] if ck else []
    if machine == "chaos-find" and bi == 0:
        failing.append((spec["seed"] + 3, 7))
    done = completed >= spec["seeds"]
    emitter = StatsEmitter(args.stats, labels=args.stats_labels)
    emitter.emit({
        "kind": "fleet_batch", "machine": machine, "batch": bi + 1,
        "batches": planned, "completed": completed,
        "batch_completed": chunk, "failing": len(failing), "infra": 0,
        "abandoned": 0,
    })
    if done:
        emitter.emit({
            "kind": "fleet_summary", "machine": machine,
            "completed": completed, "failing": len(failing), "infra": 0,
            "abandoned": 0, "batches_run": bi + 1,
            "batches_planned": planned, "plateau": False,
        })
    emitter.close()
    save_checkpoint(args.checkpoint, {
        "fingerprint": fingerprint_from_args(args),
        "batch": bi + 1, "planned": planned, "cursor": cursor,
        "completed": completed, "seeds_consumed": completed,
        "failing": [list(x) for x in failing], "infra": [],
        "abandoned": [], "prov": {}, "cov_b64": None, "detector": None,
        "plateau": False, "done": done,
    })


# -- schedule derivation -----------------------------------------------------


def derive_schedule(seed: int, *, profile: str = "mixed",
                    rounds: Optional[int] = None,
                    jobs: Optional[int] = None,
                    real: bool = False) -> dict:
    """The whole attack, derived up front from one RNG — printed,
    persisted as `schedule.json`, and replayable from the seed alone."""
    if profile not in _PROFILES:
        raise ValueError(f"unknown profile {profile!r}; "
                         f"known: {sorted(_PROFILES)}")
    rng = random.Random(f"fleet-chaos-{seed}")
    n_jobs = jobs or rng.randint(2, 3)
    specs = []
    for i in range(n_jobs):
        if real:
            spec = {"machine": "echo", "seeds": 64, "batch": 32,
                    "faults": 0, "horizon": 1.0, "max_steps": 300}
        else:
            spec = {
                "machine": rng.choice(("chaos-echo", "chaos-find")),
                "seeds": rng.choice((48, 96)),
                "batch": rng.choice((16, 32)),
                "faults": 0,
            }
        specs.append(spec)
    actions, weights = zip(*_PROFILES[profile])
    n_rounds = rounds or rng.randint(5, 8)
    events: List[dict] = []
    for i in range(n_rounds):
        action = rng.choices(actions, weights=weights, k=1)[0]
        ev: dict = {"round": i, "action": action}
        if action == "kill_worker":
            ev["at_write"] = rng.randint(1, 16)
        elif action == "sigterm_worker":
            # counts CHECKPOINT writes only (see run_chaos): those
            # happen strictly mid-unit, where the worker's SIGTERM
            # flush handler is installed and spans are open — a
            # lease-write kill would have nothing to flush by design
            ev["at_write"] = rng.randint(1, 6)
        elif action == "torn_write":
            ev["at_write"] = rng.randint(1, 16)
            ev["at_byte"] = rng.randint(0, 200)
        elif action == "corrupt_ckpt":
            ev["job_index"] = rng.randrange(n_jobs)
            ev["at_byte"] = rng.randint(0, 160)
        elif action == "server_bounce":
            ev["verb"] = rng.choice(("queue", "submit"))
            if ev["verb"] == "submit":
                ev["spec"] = (
                    {"machine": "echo", "seeds": 64, "batch": 32,
                     "faults": 0, "horizon": 1.0, "max_steps": 300}
                    if real else
                    {"machine": "chaos-echo", "seeds": 48, "batch": 16,
                     "faults": 0}
                )
        elif action == "clean_units":
            ev["units"] = rng.randint(1, 3)
        elif action == "kill_event_append":
            # count only .events.jsonl appends; the torn prefix lands
            # in the REAL file (appends are not atomic, by design)
            ev["at_write"] = rng.randint(1, 6)
            ev["at_byte"] = rng.randint(0, 80)
        elif action == "torn_events":
            ev["job_index"] = rng.randrange(n_jobs)
            ev["cut"] = rng.randint(2, 25)
        elif action == "claim_race":
            # the k-th O_EXCL claim create (the .claim match counts
            # nothing else) — k small: claims happen once per lease
            ev["at_claim"] = rng.randint(1, 3)
        elif action == "zombie_resume":
            # counts CHECKPOINT writes only: .ckpt saves happen outside
            # every store flock, so a stopped zombie wedges nobody
            ev["at_write"] = rng.randint(1, 4)
        elif action == "torn_queue_log":
            ev["at_write"] = rng.randint(1, 6)
            ev["at_byte"] = rng.randint(0, 80)
        # lease_jump_one carries no params: the victim worker is
        # whoever holds a live lease when the round fires
        events.append(ev)
    return {"seed": seed, "profile": profile, "real": real,
            "specs": specs, "events": events}


# -- process plumbing --------------------------------------------------------


def _start_server(root: str, port_file: str,
                  addr: str = "127.0.0.1:0") -> subprocess.Popen:
    with contextlib.suppress(OSError):
        os.remove(port_file)
    proc = subprocess.Popen(
        [sys.executable, "-m", "madsim_tpu", "fleet", "serve",
         "--root", root, "--addr", addr, "--port-file", port_file,
         # the harness drives reclamation itself (lease_jump events) so
         # same-seed runs keep a deterministic attempt history; the
         # sweep thread has its own in-process tests
         "--sweep-interval", "0"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    return proc


def _worker_cmd(root: str, *, worker_id: str, max_units: int,
                real: bool, backoff_base_s: float,
                lease_ttl_s: float) -> tuple:
    cmd = [sys.executable, "-m", "madsim_tpu", "fleet", "worker",
           "--root", root, "--worker-id", worker_id, "--poll", "0.02",
           "--lease-ttl", str(lease_ttl_s),
           "--backoff-base", str(backoff_base_s),
           # always drain-capable: a unit-budgeted round on an already-
           # finished farm must exit, not idle-poll into the timeout
           "--drain"]
    if not real:
        cmd += ["--driver", "synthetic"]
    if max_units:
        cmd += ["--max-units", str(max_units)]
    return tuple(cmd)


def _worker_env(chaos: Optional[dict]) -> dict:
    env = dict(os.environ)
    env.pop(CHAOS_ENV, None)
    if chaos is not None:
        env[CHAOS_ENV] = json.dumps(chaos)
    return env


def _run_worker(root: str, *, chaos: Optional[dict] = None,
                max_units: int = 0, worker_id: str = "chaos-w",
                real: bool = False, backoff_base_s: float = 0.05,
                lease_ttl_s: float = 30.0,
                timeout_s: float = 120.0) -> subprocess.CompletedProcess:
    """One worker incarnation. An armed chaos plan makes it SIGKILL
    itself at the scheduled write (rc -9); otherwise it exits 0 after
    draining / its unit budget."""
    return subprocess.run(
        _worker_cmd(root, worker_id=worker_id, max_units=max_units,
                    real=real, backoff_base_s=backoff_base_s,
                    lease_ttl_s=lease_ttl_s),
        env=_worker_env(chaos), timeout=timeout_s,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def _spawn_worker(root: str, *, worker_id: str,
                  chaos: Optional[dict] = None, max_units: int = 0,
                  real: bool = False, backoff_base_s: float = 0.05,
                  lease_ttl_s: float = 30.0) -> subprocess.Popen:
    """Popen variant of `_run_worker` for rounds that run several
    workers at once (or need to signal one mid-flight)."""
    return subprocess.Popen(
        _worker_cmd(root, worker_id=worker_id, max_units=max_units,
                    real=real, backoff_base_s=backoff_base_s,
                    lease_ttl_s=lease_ttl_s),
        env=_worker_env(chaos),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _race_workers(root: str, worker_ids, *, plans: Optional[dict] = None,
                  max_units: int = 0, real: bool = False,
                  backoff_base_s: float = 0.05,
                  lease_ttl_s: float = 30.0,
                  timeout_s: float = 120.0) -> dict:
    """Launch every worker in `worker_ids` CONCURRENTLY against one
    store — the genuine N-claimants race the tentpole is about.
    `plans` optionally arms a chaos plan on specific worker ids.
    Returns {worker_id: returncode} (a worker that outlives the
    timeout is killed and reported as -9)."""
    procs = {
        wid: _spawn_worker(root, worker_id=wid,
                           chaos=(plans or {}).get(wid),
                           max_units=max_units, real=real,
                           backoff_base_s=backoff_base_s,
                           lease_ttl_s=lease_ttl_s)
        for wid in worker_ids
    }
    deadline = time.monotonic() + timeout_s
    rcs = {}
    for wid, p in procs.items():
        try:
            p.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        rcs[wid] = p.returncode
    return rcs


def _wait_stopped(pid: int, timeout_s: float = 30.0) -> bool:
    """Poll /proc until the process is SIGSTOPped (state T) or gone.
    True = it is stopped and safe to operate around; False = it exited
    first (the write budget outlived the unit — nothing to zombify)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(f"/proc/{pid}/stat") as f:
                state = f.read().rsplit(")", 1)[1].split()[0]
        except (OSError, IndexError):
            return False
        if state in ("T", "t"):
            return True
        if state == "Z":
            return False
        time.sleep(0.02)
    return False


def _expire_leases(root: str, worker: Optional[str] = None) -> int:
    """The lease-clock jump: rewrite live leases as already expired
    (what a suspended worker VM looks like to the farm). With
    `worker`, only THAT worker's holdings jump — the single-victim
    variant the claims profile uses."""
    store = JobStore(root)

    def mut(j) -> None:
        if j.lease is not None and (
                worker is None or j.lease.get("worker") == worker):
            j.lease["expires_ts"] = 0.0

    n = 0
    for job in store.list():
        if job.lease is None:
            continue
        if worker is not None and job.lease.get("worker") != worker:
            continue
        store._update(job.id, mut)
        n += 1
    return n


def _tear_events_tail(path: str, cut: int) -> bool:
    """External truncation of an append-mode event log, mid-record and
    ON a JSON-structural character boundary inside the final record —
    the adversarial cut positions (a prefix like `{"seq": 7, "ts":`) a
    real torn disk write leaves behind. The invariants under test:
    fsck REPORTS the torn tail without quarantining the log, readers
    skip the torn line, and the next append's healing newline keeps
    later records parseable."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    body = data.rstrip(b"\n")
    if not body:
        return False
    last_nl = body.rfind(b"\n")
    last = body[last_nl + 1:]
    # structural positions within the last record; never 0 — an empty
    # tail would be a clean file, not a torn one
    marks = [i for i, c in enumerate(last) if c in b'{}[]:,"' and i > 0]
    if not marks:
        return False
    target = max(1, len(last) - cut)
    pos = min(marks, key=lambda i: abs(i - target))
    with open(path, "r+b") as f:
        f.truncate(last_nl + 1 + pos)
    return True


def _partial_span_dumped(root: str) -> bool:
    """True when any job's span dump holds a span tagged ``partial`` —
    the marker `PerfRecorder.open_spans` stamps on spans that were
    still open when a dying worker's SIGTERM flush materialized them."""
    store = JobStore(root)
    for job in store.list():
        try:
            with open(store.spans_path(job.id)) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            for sp in rec.get("spans") or ():
                if (sp.get("args") or {}).get("partial"):
                    return True
    return False


def _truncate_file(path: str, at_byte: int) -> bool:
    """External-corruption simulation: cut a FINAL file (never what the
    farm's own fsync'd atomic writes produce). Clamped below the
    closing `}\\n` so the result is guaranteed unparseable."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    with open(path, "r+b") as f:
        f.truncate(min(at_byte, max(0, size - 3)))
    return True


def _contention_violations(root: str) -> List[str]:
    """The two multi-worker invariants, read back from the artifacts.

    * **no (job, batch, generation) executed by two unfenced workers**
      — every accepted `batch_done` event names its worker and the
      lease generation that authorized it; two workers landing the
      same (batch, gen) means a zombie write was merged instead of
      fenced. (The same batch under DIFFERENT generations is the
      legitimate requeue-and-retry path.)
    * **no find filed twice** — corpus entry keys
      (machine, nodes, seed, fail_code) stay unique even when racing
      workers both reach the filing path (parsed straight from
      corpus.json; chaos stays jax-free by contract).
    """
    out: List[str] = []
    store = JobStore(root)
    for job in store.list():
        owners: dict = {}
        for ev in store.read_events(job.id):
            if ev.get("type") != "batch_done":
                continue
            key = (ev.get("batch"), ev.get("gen"))
            w = ev.get("worker")
            prev = owners.setdefault(key, w)
            if prev != w:
                out.append(
                    f"job {job.id} batch {key[0]} gen {key[1]} executed "
                    f"by two unfenced workers: {prev} and {w}"
                )
    try:
        with open(store.corpus_path) as f:
            entries = json.load(f).get("entries", [])
    except (OSError, json.JSONDecodeError, AttributeError):
        entries = []
    seen: dict = {}
    for e in entries:
        key = (e.get("machine"), e.get("nodes"), e.get("seed"),
               e.get("fail_code"))
        seen[key] = seen.get(key, 0) + 1
    for key, n in seen.items():
        if n > 1:
            out.append(f"find filed {n} times: corpus key {key}")
    return out


# -- the orchestrator --------------------------------------------------------


def run_chaos(seed: int, *, profile: str = "mixed",
              out_dir: Optional[str] = None, real: bool = False,
              rounds: Optional[int] = None, jobs: Optional[int] = None,
              keep: bool = False, backoff_base_s: float = 0.05,
              recovery_rounds: int = 8, workers: int = 1) -> dict:
    """Run one seeded chaos schedule against a scratch farm and check
    every invariant. Returns the result dict ({"ok", "violations",
    ...}); prints the exact reproduction line on failure.

    `workers` > 1 turns every worker-running round into an N-way race
    against one store (the armed chaos plan rides a seeded choice of
    contender), adds the contention invariants, and still demands the
    final reports byte-identical to the 1-worker oracle."""
    sched = derive_schedule(seed, profile=profile, rounds=rounds,
                            jobs=jobs, real=real)
    ephemeral = out_dir is None
    workdir = (
        tempfile.mkdtemp(prefix=f"fleet-chaos-{seed}-") if ephemeral
        else os.path.join(out_dir, f"seed{seed}")
    )
    os.makedirs(workdir, exist_ok=True)
    root = os.path.join(workdir, "farm")
    oracle_root = os.path.join(workdir, "oracle")
    port_file = os.path.join(workdir, "serve.port")
    with open(os.path.join(workdir, "schedule.json"), "w") as f:
        json.dump(sched, f, indent=1, sort_keys=True)
    worker_timeout = 600.0 if real else 120.0
    violations: List[str] = []
    job_ids: List[str] = []
    oracle_specs: List[dict] = []

    def _note(msg: str) -> None:
        print(f"chaos[{seed}]: {msg}", flush=True)

    n_workers = max(1, int(workers))
    wids = (["chaos-w"] if n_workers == 1
            else [f"chaos-w{i}" for i in range(n_workers)])
    # which contender carries the armed plan is itself seeded —
    # a failing (seed, workers) pair replays the same victim forever
    race_rng = random.Random(f"fleet-chaos-race {seed} {n_workers}")

    def _worker_round(*, chaos: Optional[dict] = None,
                      max_units: int = 0) -> dict:
        """One worker-running round: a single incarnation at
        --workers 1 (byte-identical to the pre-race harness), a
        genuine N-way race otherwise. Returns {worker_id: rc}."""
        if n_workers == 1:
            p = _run_worker(root, chaos=chaos, max_units=max_units,
                            worker_id=wids[0], real=real,
                            backoff_base_s=backoff_base_s,
                            timeout_s=worker_timeout)
            return {wids[0]: p.returncode}
        plans = ({race_rng.choice(wids): chaos}
                 if chaos is not None else None)
        return _race_workers(root, wids, plans=plans,
                             max_units=max_units, real=real,
                             backoff_base_s=backoff_base_s,
                             timeout_s=worker_timeout)

    def _rcs_str(rcs: dict) -> str:
        return ",".join(str(rc) for rc in rcs.values())

    server = _start_server(root, port_file)
    try:
        addr = fleet_client.resolve_addr(None, port_file, wait_s=30.0)
        for spec in sched["specs"]:
            job_ids.append(fleet_client.submit(addr, spec)["id"])
            oracle_specs.append(spec)
        _note(f"submitted {len(job_ids)} jobs; "
              f"{len(sched['events'])} scheduled events")

        for ev in sched["events"]:
            action = ev["action"]
            if action == "kill_worker":
                rcs = _worker_round(
                    chaos={"kill_at_write": ev["at_write"],
                           "match": root})
                _note(f"round {ev['round']}: kill_worker at write "
                      f"{ev['at_write']} -> rc {_rcs_str(rcs)}")
            elif action == "sigterm_worker":
                rcs = _worker_round(
                    chaos={"sigterm_at_write": ev["at_write"],
                           "match": ".ckpt"})
                died = -signal.SIGTERM in rcs.values()
                flushed = _partial_span_dumped(root)
                # the satellite invariant: a gracefully killed worker
                # leaves its open spans behind, tagged partial (if the
                # write budget outlived the unit the worker exits
                # clean and there is nothing to assert)
                if died and not flushed:
                    violations.append(
                        f"round {ev['round']}: SIGTERM'd worker left "
                        f"no partial span dump"
                    )
                _note(f"round {ev['round']}: sigterm_worker at write "
                      f"{ev['at_write']} -> rc {_rcs_str(rcs)} "
                      f"(partial spans {'flushed' if flushed else 'absent'})")
            elif action == "torn_write":
                rcs = _worker_round(
                    chaos={"torn_at_write": [ev["at_write"],
                                             ev["at_byte"]],
                           "match": root})
                _note(f"round {ev['round']}: torn_write "
                      f"[{ev['at_write']}, {ev['at_byte']}] -> "
                      f"rc {_rcs_str(rcs)}")
            elif action == "corrupt_ckpt":
                if ev["job_index"] < len(job_ids):
                    jid = job_ids[ev["job_index"]]
                    hit = _truncate_file(
                        JobStore(root).ckpt_path(jid), ev["at_byte"]
                    )
                    _note(f"round {ev['round']}: corrupt_ckpt {jid} "
                          f"at byte {ev['at_byte']} "
                          f"({'hit' if hit else 'no file yet'})")
            elif action == "lease_jump":
                n = _expire_leases(root)
                acts = fsck_mod.fsck(
                    root, fix=True, reclaim=True,
                    backoff_base_s=backoff_base_s,
                ).get("reclaimed", [])
                _note(f"round {ev['round']}: lease_jump expired "
                      f"{n} lease(s), sweep reclaimed {len(acts)}")
            elif action == "server_bounce":
                server.send_signal(signal.SIGKILL)
                server.wait()
                box: dict = {}

                def _call(ev=ev, box=box) -> None:
                    try:
                        if ev["verb"] == "submit":
                            box["out"] = fleet_client.submit(
                                addr, ev["spec"]
                            )
                        else:
                            box["out"] = fleet_client.queue(addr)
                    except Exception as exc:  # surfaced as a violation
                        box["err"] = f"{type(exc).__name__}: {exc}"

                t = threading.Thread(target=_call, daemon=True)
                t.start()
                time.sleep(0.3)  # the call is now inside the outage
                host_port = addr  # same port: the retry must land
                server = _start_server(root, port_file,
                                       addr=host_port)
                t.join(timeout=30)
                if t.is_alive() or "err" in box:
                    violations.append(
                        f"client {ev['verb']} did not survive the "
                        f"server bounce: {box.get('err', 'timed out')}"
                    )
                elif ev["verb"] == "submit":
                    job_ids.append(box["out"]["id"])
                    oracle_specs.append(ev["spec"])
                _note(f"round {ev['round']}: server_bounce + "
                      f"{ev['verb']} -> "
                      f"{box.get('out', {}).get('id', 'ok')}")
            elif action == "clean_units":
                rcs = _worker_round(max_units=ev["units"])
                _note(f"round {ev['round']}: clean_units "
                      f"{ev['units']} -> rc {_rcs_str(rcs)}")
            elif action == "kill_event_append":
                # the SIGKILL lands mid-append to an events.jsonl: the
                # match filter counts ONLY event-log appends, and the
                # torn prefix reaches the real file before the kill
                rcs = _worker_round(
                    chaos={"torn_at_write": [ev["at_write"],
                                             ev["at_byte"]],
                           "match": ".events.jsonl"})
                _note(f"round {ev['round']}: kill_event_append "
                      f"[{ev['at_write']}, {ev['at_byte']}] -> "
                      f"rc {_rcs_str(rcs)}")
            elif action == "claim_race":
                # one contender dies AT its k-th O_EXCL claim create;
                # the survivors must arbitrate around the stale claim
                rcs = _worker_round(
                    chaos={"kill_at_write": ev["at_claim"],
                           "match": ".claim"})
                _note(f"round {ev['round']}: claim_race kill at claim "
                      f"{ev['at_claim']} -> rc {_rcs_str(rcs)}")
            elif action == "torn_queue_log":
                # the kill lands mid-append to the SHARED queue.log:
                # the torn tail reaches the real file, readers must
                # leave it unconsumed, fsck rebuilds from the docs
                rcs = _worker_round(
                    chaos={"torn_at_write": [ev["at_write"],
                                             ev["at_byte"]],
                           "match": "queue.log"})
                _note(f"round {ev['round']}: torn_queue_log "
                      f"[{ev['at_write']}, {ev['at_byte']}] -> "
                      f"rc {_rcs_str(rcs)}")
            elif action == "lease_jump_one":
                # the suspended-VM case, single victim: jump ONE
                # worker's lease clock, leave every other lease live
                holders = sorted({
                    (j.lease or {}).get("worker")
                    for j in JobStore(root).list()
                    if j.lease is not None
                } - {None})
                victim = race_rng.choice(holders) if holders else None
                if victim is None:
                    _note(f"round {ev['round']}: lease_jump_one "
                          f"(no live leases; skipped)")
                else:
                    n = _expire_leases(root, worker=victim)
                    acts = fsck_mod.fsck(
                        root, fix=True, reclaim=True,
                        backoff_base_s=backoff_base_s,
                    ).get("reclaimed", [])
                    _note(f"round {ev['round']}: lease_jump_one "
                          f"{victim} expired {n} lease(s), sweep "
                          f"reclaimed {len(acts)}")
            elif action == "zombie_resume":
                # SIGSTOP a worker at a checkpoint write (outside every
                # store flock), steal its jobs, then SIGCONT it — the
                # zombie's resumed writes must die on the fence
                zombie_id = race_rng.choice(wids)
                rescue_ids = [w for w in wids if w != zombie_id] or [
                    f"{zombie_id}-rescue"]
                z = _spawn_worker(
                    root, worker_id=zombie_id,
                    chaos={"sigstop_at_write": ev["at_write"],
                           "match": ".ckpt"},
                    real=real, backoff_base_s=backoff_base_s)
                stopped = _wait_stopped(z.pid, timeout_s=worker_timeout)
                if stopped:
                    n = _expire_leases(root, worker=zombie_id)
                    fsck_mod.fsck(root, fix=True, reclaim=True,
                                  backoff_base_s=backoff_base_s)
                    rcs = _race_workers(
                        root, rescue_ids, real=real,
                        backoff_base_s=backoff_base_s,
                        timeout_s=worker_timeout)
                    os.kill(z.pid, signal.SIGCONT)
                else:
                    n, rcs = 0, {}
                try:
                    z.wait(timeout=worker_timeout)
                except subprocess.TimeoutExpired:
                    z.kill()
                    z.wait()
                what = (f"stopped, {n} lease(s) stolen, rescue rc "
                        f"{_rcs_str(rcs)}" if stopped
                        else "outlived its write budget")
                _note(f"round {ev['round']}: zombie_resume {zombie_id} "
                      f"at ckpt write {ev['at_write']} ({what}); "
                      f"zombie rc {z.returncode}")
            elif action == "torn_events":
                if ev["job_index"] < len(job_ids):
                    jid = job_ids[ev["job_index"]]
                    hit = _tear_events_tail(
                        JobStore(root).events_path(jid), ev["cut"]
                    )
                    _note(f"round {ev['round']}: torn_events {jid} "
                          f"cut {ev['cut']} "
                          f"({'hit' if hit else 'no events yet'})")

        # -- recovery: the farm must converge with no faults armed ----------
        store = JobStore(root)
        for r in range(recovery_rounds):
            fsck_mod.fsck(root, fix=True, reclaim=True,
                          release_quarantined=True,
                          backoff_base_s=backoff_base_s)
            _worker_round()
            jobs_now = {j.id: j for j in store.list()}
            missing = [jid for jid in job_ids if jid not in jobs_now]
            if not missing and all(
                j.state in TERMINAL and j.state != QUARANTINED
                for j in jobs_now.values()
            ):
                break
            time.sleep(0.2)
        else:
            violations.append(
                f"farm did not converge in {recovery_rounds} recovery "
                f"rounds"
            )
    finally:
        with contextlib.suppress(OSError):
            server.send_signal(signal.SIGKILL)
            server.wait()

    # -- final fsck must leave a clean store --------------------------------
    final_rep = fsck_mod.fsck(root, fix=True, reclaim=True)
    with open(os.path.join(workdir, "fsck.json"), "w") as f:
        json.dump(final_rep, f, indent=1, sort_keys=True)
    rescan = fsck_mod.scan(JobStore(root))
    if rescan["corrupt"] or rescan["stale_tmp"]:
        violations.append(
            f"store not clean after fsck: {rescan['corrupt']} corrupt, "
            f"{rescan['stale_tmp']} stale tmp"
        )

    # -- invariants: contention plane (gen-aware witnesses) -----------------
    violations.extend(_contention_violations(root))

    # -- invariant: no accepted job lost ------------------------------------
    store = JobStore(root)
    reports = {}
    for jid in job_ids:
        try:
            job = store.get(jid)
        except KeyError:
            violations.append(f"accepted job {jid} LOST (no document)")
            continue
        if job.state not in TERMINAL:
            violations.append(f"job {jid} not terminal: {job.state}")
        elif job.state in (FAILED, CANCELLED, QUARANTINED):
            violations.append(
                f"job {jid} ended {job.state}: {job.error or job.quarantine}"
            )
        elif not job.result or "report" not in job.result:
            violations.append(f"job {jid} terminal without a report")
        else:
            reports[jid] = job.result["report"]

    # -- invariant: byte-identical to the unperturbed oracle ----------------
    oracle_ids: List[str] = []
    if not violations:
        ostore = JobStore(oracle_root)
        for spec in oracle_specs:
            oracle_ids.append(ostore.submit(spec).id)
        _run_worker(oracle_root, real=real,
                    backoff_base_s=backoff_base_s,
                    timeout_s=worker_timeout)
        for jid, oid in zip(job_ids, oracle_ids):
            try:
                oracle_report = ostore.get(oid).result["report"]
            except (KeyError, TypeError):
                violations.append(f"oracle job {oid} has no report")
                continue
            got = json.dumps(reports[jid], sort_keys=True)
            want = json.dumps(oracle_report, sort_keys=True)
            if got != want:
                violations.append(
                    f"job {jid} report diverged from oracle {oid}:\n"
                    f"  chaos:  {got}\n  oracle: {want}"
                )

    # -- invariant (--real): filed finds regress-replay ---------------------
    corpus = os.path.join(root, "corpus.json")
    if real and not violations and os.path.exists(corpus):
        p = subprocess.run(
            [sys.executable, "-m", "madsim_tpu", "regress",
             "--corpus", corpus],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=1200,
        )
        if p.returncode != 0:
            violations.append(
                f"filed finds failed regress replay:\n{p.stdout[-2000:]}"
            )

    result = {
        "ok": not violations,
        "seed": seed,
        "profile": profile,
        "workers": n_workers,
        "violations": violations,
        "jobs": job_ids,
        "workdir": workdir,
        "requeues": sum(j.n_requeues for j in store.list()),
        "lease_reclaims": sum(j.n_lease_reclaims for j in store.list()),
    }
    with open(os.path.join(workdir, "result.json"), "w") as f:
        json.dump(result, f, indent=1, sort_keys=True)
    if violations:
        repro = (
            f"python -m madsim_tpu fleet chaos --seed {seed} "
            f"--profile {profile}"
            + (" --real" if real else "")
            + (f" --rounds {rounds}" if rounds else "")
            + (f" --jobs {jobs}" if jobs else "")
            + (f" --workers {n_workers}" if n_workers > 1 else "")
        )
        print(
            f"FLEET CHAOS FAILURE (seed {seed}): "
            f"{len(violations)} violation(s)\n"
            + "\n".join(f"  - {v}" for v in violations)
            + f"\nreproduce forever with:\n  {repro}\n"
            f"artifacts: {workdir}",
            flush=True,
        )
    else:
        _note(
            f"ok — {len(job_ids)} jobs survived "
            f"{len(sched['events'])} faults "
            f"({result['requeues']} requeues, "
            f"{result['lease_reclaims']} lease reclaims); reports "
            f"byte-identical to oracle"
        )
        if ephemeral and not keep:
            shutil.rmtree(workdir, ignore_errors=True)
            result["workdir"] = None
    return result
