"""Shared HTTP daemon glue — realized-port files + graceful SIGTERM.

Both observability endpoints (`serve --service stats` and
`fleet serve`) are stdlib `ThreadingHTTPServer` daemons that tests and
fleet workers need to discover WITHOUT racing: binding `--addr host:0`
already prints the realized port, but a supervisor parsing stdout is a
race. `--port-file PATH` writes the realized port atomically after the
socket exists — a poller sees either no file or a complete port.

Graceful shutdown: historically only KeyboardInterrupt closed the
server; a systemd/docker/CI `SIGTERM` killed it mid-response with the
socket unclosed. `run_http_server` installs a SIGTERM handler that
breaks `serve_forever` the same way Ctrl-C does, then closes the
listening socket in `finally`.

Stdlib-only (no jax): safe to import from any control-plane process.
"""

from __future__ import annotations

import http.server
import signal
from typing import Optional, Tuple


def write_port_file(path: str, port: int) -> None:
    """Atomic (shared `runtime/atomicio` discipline): a discovery
    poller never reads a torn or empty port file."""
    from ..runtime.atomicio import atomic_write_text

    atomic_write_text(path, f"{port}\n")


def read_port_file(path: str) -> int:
    with open(path) as f:
        return int(f.read().strip())


def bind(addr: str, handler) -> Tuple[http.server.ThreadingHTTPServer, str, int]:
    """Parse `host:port` (port 0 = ephemeral), bind, and return
    (server, host, realized_port)."""
    host, port = addr.rsplit(":", 1)
    srv = http.server.ThreadingHTTPServer((host, int(port)), handler)
    return srv, host, srv.server_address[1]


def run_http_server(
    srv: http.server.ThreadingHTTPServer,
    *,
    port_file: Optional[str] = None,
) -> int:
    """Serve until KeyboardInterrupt or SIGTERM, then close gracefully.
    Writes `port_file` (realized port) before serving. Returns 0."""
    if port_file:
        write_port_file(port_file, srv.server_address[1])

    def _on_term(signum, frame):  # SIGTERM == Ctrl-C: drain and close
        raise KeyboardInterrupt

    prev = None
    try:
        prev = signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # pragma: no cover - not the main thread
        prev = None
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
        if prev is not None:
            signal.signal(signal.SIGTERM, prev)
    return 0
