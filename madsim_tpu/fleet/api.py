"""The fleet control plane — a jax-free stdlib HTTP API over the store.

Extends the `serve --service stats` pattern (plain `http.server`,
read-only files, no sim/jax imports) to a read/write job API::

    POST   /jobs             submit {"spec": {...}, "priority", "deadline_s"}
                             (a bare spec object also works)
    GET    /jobs             = /queue
    GET    /queue            state counts + per-job summaries
    GET    /jobs/{id}        full job doc + live feed (?feed=N batch rows
                             from the job's StatsEmitter JSONL; ?wait=S
                             long-polls — the response is held until the
                             job document or its feed changes, so
                             watchers stop busy-polling)
    GET    /jobs/{id}/result find + shrunk repro + `why` attribution
                             (409 until the job reaches a terminal state)
    DELETE /jobs/{id}        cancel (queued dies now; running at the next
                             unit boundary)
    GET    /metrics          Prometheus: fleet gauges (job states,
                             requeues/lease-reclaims/quarantine) +
                             every job's own StatsEmitter textfile,
                             label-namespaced
    GET    /healthz          liveness + store integrity (read-only fsck
                             scan: corrupt files, queue depth, stale
                             leases, quarantined jobs; 503 when the
                             store needs `fleet fsck`)

Everything the API serves is an atomic-rename artifact (job docs,
StatsEmitter snapshots), so no response can observe a torn write — and
because the store is the wire, the API keeps answering while a worker
is mid-dispatch (they share only the filesystem).

`FleetAPI.handle()` is the whole router, separated from the socket so
handler tests run against a store in a tmpdir with zero networking.
"""

from __future__ import annotations

import http.server
import json
import logging
import os
import re
import threading
import time
from typing import Optional, Tuple

from . import httpd
from .store import CorruptJobFile, JobStore, STATES, TERMINAL

_LOG = logging.getLogger("madsim_tpu.fleet.api")

_JOB_RE = re.compile(r"^/jobs/([A-Za-z0-9._-]+)(/result)?$")


def _json(status: int, doc) -> Tuple[int, str, bytes]:
    body = (json.dumps(doc, indent=1, sort_keys=True) + "\n").encode()
    return status, "application/json", body


def _err(status: int, msg: str) -> Tuple[int, str, bytes]:
    return _json(status, {"error": msg})


def _job_summary(job) -> dict:
    return {
        "id": job.id,
        "state": job.state,
        "machine": job.spec["machine"],
        "seeds": job.spec["seeds"],
        "priority": job.priority,
        "subkey": job.subkey,
        "cancel_requested": job.cancel_requested,
        "batches_run": job.progress.get("batches_run", 0),
        "batches_planned": job.progress.get("batches_planned"),
        "failing": job.progress.get("failing", 0),
        # live search state (the scheduler's inputs, surfaced): the
        # plateau verdict, the cumulative slots-hit count, and — for
        # guided jobs — the current escalation rung
        "plateau": bool(job.progress.get("plateau", False)),
        "coverage_slots": job.progress.get("coverage_slots"),
        "guided": bool(job.spec.get("guided", False)),
        "escalation": job.progress.get("escalation"),
    }


class FleetAPI:
    def __init__(self, store: JobStore):
        self.store = store

    # -- router --------------------------------------------------------------

    def handle(self, method: str, path: str,
               body: Optional[bytes] = None) -> Tuple[int, str, bytes]:
        path, _, query = path.partition("?")
        path = path.rstrip("/") or "/"
        try:
            if path == "/healthz" and method == "GET":
                return self._healthz()
            if path == "/metrics" and method == "GET":
                return 200, "text/plain; version=0.0.4", self._metrics()
            if path in ("/queue", "/jobs") and method == "GET":
                return self._queue()
            if path == "/jobs" and method == "POST":
                return self._submit(body)
            m = _JOB_RE.match(path)
            if m:
                job_id, result = m.group(1), bool(m.group(2))
                if result and method == "GET":
                    return self._result(job_id)
                if not result and method == "GET":
                    return self._status(job_id, query)
                if not result and method == "DELETE":
                    return self._cancel(job_id)
            return _err(
                404,
                "routes: GET /queue /jobs/{id} /jobs/{id}/result /metrics "
                "/healthz; POST /jobs; DELETE /jobs/{id}",
            )
        except KeyError as exc:
            return _err(404, str(exc.args[0]) if exc.args else "not found")
        except ValueError as exc:
            return _err(400, str(exc))
        except CorruptJobFile as exc:
            # a torn/garbled document on disk is an operator problem,
            # never an unhandled 500: name the file and the fix
            return _err(503, str(exc))

    # -- endpoints -----------------------------------------------------------

    def _submit(self, body: Optional[bytes]) -> Tuple[int, str, bytes]:
        try:
            doc = json.loads((body or b"").decode() or "{}")
        except json.JSONDecodeError as exc:
            return _err(400, f"body is not JSON: {exc}")
        if not isinstance(doc, dict):
            return _err(400, "body must be a JSON object")
        spec = doc.get("spec", None)
        if spec is None:
            # bare-spec convenience: {"machine": ...} without the wrapper
            spec = {k: v for k, v in doc.items()
                    if k not in ("priority", "deadline_s")}
        job = self.store.submit(
            spec,
            priority=int(doc.get("priority", 0) or 0),
            deadline_s=doc.get("deadline_s"),
        )
        return _json(201, {"id": job.id, "state": job.state,
                           "subkey": job.subkey})

    def _queue(self) -> Tuple[int, str, bytes]:
        jobs = self.store.list()
        return _json(200, {
            "counts": {s: n for s, n in self.store.counts().items() if n},
            "jobs": [_job_summary(j) for j in jobs],
        })

    #: ?wait=S ceiling — a long-poll never parks a server thread
    #: longer than this (clients re-issue; the stdlib server is
    #: threading, so parked watchers don't block other requests)
    WAIT_CAP_S = 30.0
    #: change-detection poll cadence while a ?wait request is parked
    WAIT_TICK_S = 0.2

    def _state_token(self, job_id: str) -> tuple:
        """A cheap change token for (job doc, stats feed): file sizes +
        mtimes. Both artifacts are atomic-rename writes, so any state
        change moves the token."""
        token = []
        for path in (self.store.job_path(job_id),
                     self.store.stats_base(job_id) + ".jsonl"):
            try:
                st = os.stat(path)
                token.append((st.st_mtime_ns, st.st_size))
            except OSError:
                token.append(None)
        return tuple(token)

    def _status(self, job_id: str, query: str) -> Tuple[int, str, bytes]:
        job = self.store.get(job_id)
        feed_n = 20
        m = re.search(r"(?:^|&)feed=(\d+)", query)
        if m:
            feed_n = min(int(m.group(1)), 1000)
        wait_s = 0.0
        m = re.search(r"(?:^|&)wait=([0-9.]+)", query)
        if m:
            try:
                wait_s = min(float(m.group(1)), self.WAIT_CAP_S)
            except ValueError:
                wait_s = 0.0
        changed = None
        if wait_s > 0 and not job.terminal:
            # long-poll: park until the job document or its stats feed
            # changes (atomic-rename artifacts — no torn observation),
            # or the window elapses. Terminal jobs answer immediately:
            # nothing will ever change again.
            start_token = self._state_token(job_id)
            deadline = time.monotonic() + wait_s  # madsim: allow(D001)
            changed = False
            while time.monotonic() < deadline:  # madsim: allow(D001)
                time.sleep(self.WAIT_TICK_S)  # madsim: allow(D001)
                if self._state_token(job_id) != start_token:
                    changed = True
                    break
            job = self.store.get(job_id)  # freshest doc after the park
        doc = job.to_dict()
        doc["feed"] = self.store.read_feed(job_id, last=feed_n)
        if changed is not None:
            doc["wait"] = {"waited": True, "changed": changed}
        return _json(200, doc)

    def _result(self, job_id: str) -> Tuple[int, str, bytes]:
        job = self.store.get(job_id)
        if job.state not in TERMINAL:
            return _err(
                409,
                f"job {job_id} is {job.state}; results exist once the job "
                f"reaches a terminal state ({', '.join(sorted(TERMINAL))})",
            )
        return _json(200, {
            "id": job.id,
            "state": job.state,
            "error": job.error,
            "result": job.result,
        })

    def _cancel(self, job_id: str) -> Tuple[int, str, bytes]:
        job = self.store.request_cancel(job_id)
        return _json(200, {
            "id": job.id,
            "state": job.state,
            "cancel_requested": job.cancel_requested,
        })

    # -- health --------------------------------------------------------------

    def _healthz(self) -> Tuple[int, str, bytes]:
        """Liveness + store integrity in one probe: a read-only fsck
        scan (per-file verdicts summarized, nothing mutated) plus the
        farm gauges. 200 only while every artifact is readable; a
        corrupt store answers 503 with the count and the fix, so a
        `curl -f` health check trips exactly when `fleet fsck` has
        work to do."""
        from . import fsck

        rep = fsck.scan(self.store)
        ok = rep["corrupt"] == 0
        doc = {
            "ok": ok,
            "store": {
                "files_scanned": rep["files_scanned"],
                "corrupt_files": rep["corrupt"],
                "drifted_jobs": rep["drifted"],
                "stale_tmp": rep["stale_tmp"],
                "torn_tails": rep["torn_tails"],
            },
            "queue_depth": rep["queue_depth"],
            "stale_leases": rep["stale_leases"],
            "quarantined_jobs": rep["quarantined"],
            **({} if ok else {"fix": "run `fleet fsck --root "
                              f"{self.store.root}`"}),
        }
        return _json(200 if ok else 503, doc)

    # -- metrics -------------------------------------------------------------

    def _metrics(self) -> bytes:
        """Fleet-level gauges plus every job's own StatsEmitter
        Prometheus textfile. Per-job files are label-namespaced by the
        worker (`{job="<id>"}`), so concatenation is a valid exposition
        — `# TYPE` lines are deduped across files."""
        lines = ["# madsim_tpu fleet control plane"]
        jobs = self.store.list()
        counts = self.store.counts()
        lines.append("# TYPE madsim_tpu_fleet_jobs gauge")
        for s in STATES:
            lines.append(f'madsim_tpu_fleet_jobs{{state="{s}"}} {counts.get(s, 0)}')
        # the self-healing counters: requeues (all causes), lease
        # reclaims (the sweep's share of them) and the quarantine gauge
        lines.append("# TYPE madsim_tpu_fleet_requeues_total counter")
        lines.append(
            f"madsim_tpu_fleet_requeues_total "
            f"{sum(j.n_requeues for j in jobs)}"
        )
        lines.append("# TYPE madsim_tpu_fleet_lease_reclaims_total counter")
        lines.append(
            f"madsim_tpu_fleet_lease_reclaims_total "
            f"{sum(j.n_lease_reclaims for j in jobs)}"
        )
        lines.append("# TYPE madsim_tpu_fleet_quarantined_jobs gauge")
        lines.append(
            f"madsim_tpu_fleet_quarantined_jobs "
            f"{counts.get('quarantined', 0)}"
        )
        seen_types = {"madsim_tpu_fleet_jobs",
                      "madsim_tpu_fleet_requeues_total",
                      "madsim_tpu_fleet_lease_reclaims_total",
                      "madsim_tpu_fleet_quarantined_jobs"}
        for job in jobs:
            prom = self.store.stats_base(job.id) + ".prom"
            if not os.path.exists(prom):
                continue
            try:
                with open(prom) as f:
                    for line in f.read().splitlines():
                        if line.startswith("# TYPE "):
                            name = line.split()[2]
                            if name in seen_types:
                                continue
                            seen_types.add(name)
                        elif line.startswith("#"):
                            continue
                        lines.append(line)
            except OSError:
                continue
        return ("\n".join(lines) + "\n").encode()


def make_handler(api: FleetAPI):
    class Handler(http.server.BaseHTTPRequestHandler):
        def _dispatch(self, method: str) -> None:
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else None
            status, ctype, payload = api.handle(method, self.path, body)
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):  # noqa: N802 (stdlib API name)
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

        def do_DELETE(self):  # noqa: N802
            self._dispatch("DELETE")

        def log_message(self, fmt, *a):  # route access logs to logging
            _LOG.debug(fmt, *a)

    return Handler


def serve(root: str, addr: str, port_file: Optional[str] = None,
          sweep_interval_s: float = 5.0) -> int:
    """`fleet serve` entry: bind (port 0 supported), announce the
    realized port (stdout + optional --port-file), serve until
    SIGTERM/Ctrl-C, close gracefully. A daemon supervisor thread runs
    the lease-reclamation sweep every `sweep_interval_s` (0 disables):
    expired worker leases requeue their jobs with backoff — or
    quarantine at the attempt cap — so the farm heals even while no
    worker is alive to sweep for itself."""
    store = JobStore(root)
    stop = threading.Event()

    def _sweep() -> None:
        while not stop.wait(sweep_interval_s):
            try:
                for act in store.reclaim_expired():
                    print(
                        f"sweep: reclaimed {act['job']} from dead "
                        f"worker {act['worker']} -> {act['outcome']} "
                        f"(attempt {act['attempt']})", flush=True,
                    )
            except Exception:  # the farm outlives a bad sweep pass
                _LOG.exception("lease-reclamation sweep failed")

    srv, host, port = httpd.bind(addr, make_handler(FleetAPI(store)))
    print(
        f"fleet control plane on {host}:{port} (root {store.root}; "
        f"GET /queue /jobs/{{id}} /jobs/{{id}}/result /metrics /healthz, "
        f"POST /jobs, DELETE /jobs/{{id}}; lease sweep every "
        f"{sweep_interval_s:g}s)",
        flush=True,
    )
    sweeper = None
    if sweep_interval_s > 0:
        sweeper = threading.Thread(
            target=_sweep, daemon=True, name="fleet-lease-sweep"
        )
        sweeper.start()
    try:
        return httpd.run_http_server(srv, port_file=port_file)
    finally:
        stop.set()
        if sweeper is not None:
            sweeper.join(timeout=2)
