"""The fleet control plane — a jax-free stdlib HTTP API over the store.

Extends the `serve --service stats` pattern (plain `http.server`,
read-only files, no sim/jax imports) to a read/write job API::

    POST   /jobs             submit {"spec": {...}, "priority", "deadline_s",
                             "tenant"} (a bare spec object also works).
                             Admission-controlled: per-tenant token-bucket
                             rate limits ($MADSIM_TPU_FLEET_RATE_LIMIT /
                             _RATE_BURST), a queue-depth cap
                             ($MADSIM_TPU_FLEET_MAX_QUEUE_DEPTH) and a
                             load-shed threshold
                             ($MADSIM_TPU_FLEET_SHED_DEPTH) answer 429
                             with a `Retry-After` header and a
                             `retry_after_s` body field instead of
                             accepting work the farm can't absorb — the
                             write queue forms in the clients' seeded-
                             jitter retry loops, so every 201 the server
                             ever sent stays durable (zero accepted-job
                             loss).
    GET    /jobs             = /queue
    GET    /queue            state counts + per-job summaries
    GET    /jobs/{id}        full job doc + live feed (?feed=N batch rows
                             from the job's StatsEmitter JSONL; ?wait=S
                             long-polls — the response is held until the
                             job document or its feed changes, so
                             watchers stop busy-polling)
    GET    /jobs/{id}/result find + shrunk repro + `why` attribution
                             (409 until the job reaches a terminal state)
    GET    /jobs/{id}/events the job-lifecycle event log. Push, not
                             poll: a client sending `Accept:
                             text/event-stream` gets Server-Sent Events
                             tailed live from the log (?since=SEQ
                             resumes; the stream ends with `event: end`
                             at a terminal state, or closes at the
                             ?wait=S / WAIT_CAP_S window for the client
                             to reconnect). Plain GET returns the same
                             records as a one-shot JSON document
                             (?since=SEQ filter, ?wait=S parks until
                             new events arrive — same deadline
                             machinery as the /jobs/{id} long-poll).
    GET    /jobs/{id}/timeline  the merged Perfetto timeline: control-
                             plane lifecycle events + the worker's
                             PerfRecorder span dumps, joined by the job
                             id as trace id (queue-wait, compile,
                             per-batch dispatch, shrink — one picture
                             across both processes).
    GET    /jobs/{id}/profile   the three-clock merge: the timeline's
                             host plane + the worker's device-profile
                             dump and failing-lane virtual trace
                             (present when the worker ran under
                             MADSIM_TPU_XPROF=1), aligned by
                             `perf/xprof.py` clock-sync markers.
    DELETE /jobs/{id}        cancel (queued dies now; running at the next
                             unit boundary)
    GET    /metrics          Prometheus: fleet gauges (job states,
                             requeues/lease-reclaims/quarantine) +
                             every job's own StatsEmitter textfile,
                             label-namespaced
    GET    /healthz          liveness + store integrity (read-only fsck
                             scan: corrupt files, queue depth, stale
                             leases, quarantined jobs; 503 when the
                             store needs `fleet fsck` — and while the
                             farm is load-shedding writes, so a probe
                             sees the degradation). Also surfaces the
                             contention plane: per-worker claim-conflict
                             and fenced-write counts, queue-log lag, and
                             the shed state.

    While load-shedding, GET /jobs and /queue serve a degraded summary
    straight from the queue index (no per-job doc reads, no momentum) —
    reads stay cheap exactly when the farm is drowning.

Everything the API serves is an atomic-rename artifact (job docs,
StatsEmitter snapshots), so no response can observe a torn write — and
because the store is the wire, the API keeps answering while a worker
is mid-dispatch (they share only the filesystem).

`FleetAPI.handle()` is the whole router, separated from the socket so
handler tests run against a store in a tmpdir with zero networking.
"""

from __future__ import annotations

import http.server
import json
import logging
import math
import os
import re
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from . import events as fleet_events
from . import httpd
from .store import CorruptJobFile, JobStore, STATES, TERMINAL

_LOG = logging.getLogger("madsim_tpu.fleet.api")

_JOB_RE = re.compile(
    r"^/jobs/([A-Za-z0-9._-]+)(/result|/events|/timeline|/profile)?$")


def _json(status: int, doc) -> Tuple[int, str, bytes]:
    body = (json.dumps(doc, indent=1, sort_keys=True) + "\n").encode()
    return status, "application/json", body


def _err(status: int, msg: str) -> Tuple[int, str, bytes]:
    return _json(status, {"error": msg})


def _query_int(query: str, key: str, default: int) -> int:
    m = re.search(rf"(?:^|&){key}=(\d+)", query)
    return int(m.group(1)) if m else default


def _query_wait(query: str, cap: float) -> float:
    m = re.search(r"(?:^|&)wait=([0-9.]+)", query)
    if not m:
        return 0.0
    try:
        return min(float(m.group(1)), cap)
    except ValueError:
        return 0.0


def _sse_frame(ev: dict) -> bytes:
    """One Server-Sent-Events frame per event record: `id` carries the
    seq (the client's reconnect cursor), `event` the type, `data` the
    full record."""
    data = json.dumps(ev, sort_keys=True, separators=(",", ":"))
    return (f"id: {ev.get('seq', 0)}\nevent: {ev.get('type', 'event')}\n"
            f"data: {data}\n\n").encode()


def _job_summary(job) -> dict:
    return {
        "id": job.id,
        "state": job.state,
        "machine": job.spec["machine"],
        "seeds": job.spec["seeds"],
        "priority": job.priority,
        "subkey": job.subkey,
        "cancel_requested": job.cancel_requested,
        "batches_run": job.progress.get("batches_run", 0),
        "batches_planned": job.progress.get("batches_planned"),
        "failing": job.progress.get("failing", 0),
        # live search state (the scheduler's inputs, surfaced): the
        # plateau verdict, the cumulative slots-hit count, and — for
        # guided jobs — the current escalation rung
        "plateau": bool(job.progress.get("plateau", False)),
        "coverage_slots": job.progress.get("coverage_slots"),
        "guided": bool(job.spec.get("guided", False)),
        "escalation": job.progress.get("escalation"),
        # worker liveness for `fleet top`: who holds the lease and when
        # it lapses (expired + non-terminal = the sweep's next customer)
        "worker": (job.lease or {}).get("worker"),
        "lease_expires_ts": (job.lease or {}).get("expires_ts"),
        "attempt": job.attempt,
    }


class _FileCache:
    """Parsed-artifact cache keyed by (mtime_ns, size): a /metrics
    scrape of an unchanged store does ZERO re-parses — the per-job
    Prometheus textfiles and event logs are only re-read when their
    stat signature moves. `parses` counts loader invocations (the unit
    tests pin it)."""

    def __init__(self) -> None:
        self._entries: Dict[str, tuple] = {}
        self.parses = 0

    def get(self, path: str, loader: Callable[[str], object]):
        try:
            st = os.stat(path)
        except OSError:
            self._entries.pop(path, None)
            return None
        key = (st.st_mtime_ns, st.st_size)
        ent = self._entries.get(path)
        if ent is not None and ent[0] == key:
            return ent[1]
        self.parses += 1
        value = loader(path)
        self._entries[path] = (key, value)
        return value


def _parse_prom(path: str) -> List[tuple]:
    """Pre-parse a Prometheus textfile into (kind, metric_name, line)
    rows; `# TYPE` dedup across files happens at render time."""
    rows: List[tuple] = []
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return rows
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            rows.append(("type", line.split()[2], line))
        elif line.startswith("#"):
            continue
        else:
            rows.append(("metric", None, line))
    return rows


class _TokenBucket:
    """One tenant's admission budget: `rate` tokens/s refill up to
    `burst`. `take()` spends one token or returns how long until one
    exists — that number IS the Retry-After the client is told."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.ts = time.monotonic()  # madsim: allow(D001)

    def take(self) -> float:
        now = time.monotonic()  # madsim: allow(D001)
        self.tokens = min(self.burst,
                          self.tokens + (now - self.ts) * self.rate)
        self.ts = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate


class FleetAPI:
    #: Retry-After answered while shedding or depth-capped — depth
    #: recovers at drain speed, not token-refill speed, so the hint is
    #: a flat "come back soon" rather than a bucket computation
    SHED_RETRY_S = 1.0

    def __init__(self, store: JobStore):
        self.store = store
        self._prom_cache = _FileCache()
        self._events_cache = _FileCache()
        self._bench_cache = _FileCache()
        # -- admission control (all knobs default OFF: unset/0 keeps
        # the pre-admission behavior byte-for-byte) -----------------------
        env = os.environ.get
        self.rate_limit = float(env("MADSIM_TPU_FLEET_RATE_LIMIT") or 0)
        self.rate_burst = (float(env("MADSIM_TPU_FLEET_RATE_BURST") or 0)
                           or max(self.rate_limit, 1.0))
        self.max_queue_depth = int(
            env("MADSIM_TPU_FLEET_MAX_QUEUE_DEPTH") or 0)
        self.shed_depth = int(env("MADSIM_TPU_FLEET_SHED_DEPTH") or 0)
        self._admission_lock = threading.Lock()
        self._buckets: Dict[str, _TokenBucket] = {}
        #: tenant -> {admitted, rate_limited, depth_limited, shed}
        self._admission: Dict[str, Dict[str, int]] = {}
        self.shedding = False
        self.sheds_total = 0

    # -- admission -----------------------------------------------------------

    def _queue_depth(self) -> int:
        """Backlog from the queue index, not the docs: admission stays
        O(1) per request even at a 10k-job store."""
        return sum(1 for row in self.store.queue_rows().values()
                   if row.get("state") not in TERMINAL)

    def _update_shed(self, depth: int) -> bool:
        """Enter shed at depth >= $MADSIM_TPU_FLEET_SHED_DEPTH, leave
        as soon as the backlog drains below it. 0/unset never sheds."""
        with self._admission_lock:
            want = bool(self.shed_depth) and depth >= self.shed_depth
            if want and not self.shedding:
                self.sheds_total += 1
            self.shedding = want
            return want

    def _count_admission(self, tenant: str, outcome: str) -> None:
        with self._admission_lock:
            per = self._admission.setdefault(tenant, {})
            per[outcome] = per.get(outcome, 0) + 1

    def _reject(self, tenant: str, reason: str, retry_after_s: float,
                depth: int) -> Tuple[int, str, bytes]:
        self._count_admission(tenant, reason)
        return _json(429, {
            "error": f"admission refused ({reason}); retry after "
                     f"{retry_after_s:g}s",
            "reason": reason,
            "tenant": tenant,
            "queue_depth": depth,
            "retry_after_s": round(retry_after_s, 3),
        })

    def _job_events(self, job_id: str) -> List[dict]:
        """The job's event log via the stat-keyed cache (scrapes and
        queue renders re-parse only what changed)."""
        evs = self._events_cache.get(
            self.store.events_path(job_id),
            lambda p: fleet_events.read_events(p))
        return evs if isinstance(evs, list) else []

    # -- router --------------------------------------------------------------

    def handle(self, method: str, path: str,
               body: Optional[bytes] = None) -> Tuple[int, str, bytes]:
        path, _, query = path.partition("?")
        path = path.rstrip("/") or "/"
        try:
            if path == "/healthz" and method == "GET":
                return self._healthz()
            if path == "/metrics" and method == "GET":
                return 200, "text/plain; version=0.0.4", self._metrics()
            if path in ("/queue", "/jobs") and method == "GET":
                return self._queue()
            if path == "/jobs" and method == "POST":
                return self._submit(body)
            m = _JOB_RE.match(path)
            if m:
                job_id, sub = m.group(1), m.group(2) or ""
                if sub == "/result" and method == "GET":
                    return self._result(job_id)
                if sub == "/events" and method == "GET":
                    return self._events(job_id, query)
                if sub == "/timeline" and method == "GET":
                    return self._timeline(job_id)
                if sub == "/profile" and method == "GET":
                    return self._profile(job_id)
                if not sub and method == "GET":
                    return self._status(job_id, query)
                if not sub and method == "DELETE":
                    return self._cancel(job_id)
            return _err(
                404,
                "routes: GET /queue /jobs/{id} /jobs/{id}/result "
                "/jobs/{id}/events /jobs/{id}/timeline /jobs/{id}/profile "
                "/metrics /healthz; POST /jobs; DELETE /jobs/{id}",
            )
        except KeyError as exc:
            return _err(404, str(exc.args[0]) if exc.args else "not found")
        except ValueError as exc:
            return _err(400, str(exc))
        except CorruptJobFile as exc:
            # a torn/garbled document on disk is an operator problem,
            # never an unhandled 500: name the file and the fix
            return _err(503, str(exc))

    # -- endpoints -----------------------------------------------------------

    def _submit(self, body: Optional[bytes]) -> Tuple[int, str, bytes]:
        try:
            doc = json.loads((body or b"").decode() or "{}")
        except json.JSONDecodeError as exc:
            return _err(400, f"body is not JSON: {exc}")
        if not isinstance(doc, dict):
            return _err(400, "body must be a JSON object")
        tenant = str(doc.get("tenant") or "default")
        spec = doc.get("spec", None)
        if spec is None:
            # bare-spec convenience: {"machine": ...} without the wrapper
            spec = {k: v for k, v in doc.items()
                    if k not in ("priority", "deadline_s", "tenant")}
        # admission, cheapest check first, all reads from the index:
        # shed beats depth beats rate (a shedding farm refuses even
        # tenants with tokens to spend)
        depth = self._queue_depth()
        if self._update_shed(depth):
            return self._reject(tenant, "shed", self.SHED_RETRY_S, depth)
        if self.max_queue_depth and depth >= self.max_queue_depth:
            return self._reject(tenant, "depth_limited",
                                self.SHED_RETRY_S, depth)
        if self.rate_limit:
            with self._admission_lock:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = _TokenBucket(
                        self.rate_limit, self.rate_burst)
                wait = bucket.take()
            if wait > 0:
                return self._reject(tenant, "rate_limited",
                                    max(wait, 0.001), depth)
        self._count_admission(tenant, "admitted")
        job = self.store.submit(
            spec,
            priority=int(doc.get("priority", 0) or 0),
            deadline_s=doc.get("deadline_s"),
        )
        return _json(201, {"id": job.id, "state": job.state,
                           "subkey": job.subkey})

    def _farm(self, *, degraded: bool) -> dict:
        """The contention plane for `fleet top` and /healthz: per-worker
        claim-conflict / fenced-write counts (the workers mirror them to
        workers/<id>.json), the queue-log lag, and the shed state. The
        O(n) lag scan is skipped while degraded — that's the whole
        point of shedding."""
        farm: dict = {
            "shed": self.shedding,
            "workers": self.store.read_worker_stats(),
        }
        if not degraded:
            farm["queue_log_lag"] = self.store.queue_log_lag()
        return farm

    def _queue(self) -> Tuple[int, str, bytes]:
        if self._update_shed(self._queue_depth()):
            # degraded read: the queue index IS the response — one log
            # read, zero per-job doc/event/momentum I/O
            rows = self.store.queue_rows()
            counts: Dict[str, int] = {}
            for row in rows.values():
                s = row.get("state") or "?"
                counts[s] = counts.get(s, 0) + 1
            return _json(200, {
                "degraded": True,
                "counts": counts,
                "jobs": [
                    {"id": jid, "state": row.get("state"),
                     "worker": row.get("worker")}
                    for jid, row in sorted(rows.items())
                ],
                "farm": self._farm(degraded=True),
            })
        from .scheduler import job_momentum

        jobs = self.store.list()
        summaries = []
        for j in jobs:
            s = _job_summary(j)
            tail = fleet_events.tail_event(self.store.events_path(j.id))
            if tail:
                s["last_event"] = {k: tail.get(k)
                                   for k in ("seq", "ts", "type", "worker")}
            # the scheduler's live-search read, surfaced for `fleet top`
            s["momentum"] = job_momentum(self.store, j)
            summaries.append(s)
        return _json(200, {
            "counts": {s: n for s, n in self.store.counts().items() if n},
            "jobs": summaries,
            "farm": self._farm(degraded=False),
        })

    #: ?wait=S ceiling — a long-poll never parks a server thread
    #: longer than this (clients re-issue; the stdlib server is
    #: threading, so parked watchers don't block other requests)
    WAIT_CAP_S = 30.0
    #: change-detection poll cadence while a ?wait request is parked
    WAIT_TICK_S = 0.2

    def _state_token(self, job_id: str) -> tuple:
        """A cheap change token for (job doc, stats feed): file sizes +
        mtimes. Both artifacts are atomic-rename writes, so any state
        change moves the token."""
        token = []
        for path in (self.store.job_path(job_id),
                     self.store.stats_base(job_id) + ".jsonl"):
            try:
                st = os.stat(path)
                token.append((st.st_mtime_ns, st.st_size))
            except OSError:
                token.append(None)
        return tuple(token)

    def _status(self, job_id: str, query: str) -> Tuple[int, str, bytes]:
        job = self.store.get(job_id)
        feed_n = 20
        m = re.search(r"(?:^|&)feed=(\d+)", query)
        if m:
            feed_n = min(int(m.group(1)), 1000)
        wait_s = 0.0
        m = re.search(r"(?:^|&)wait=([0-9.]+)", query)
        if m:
            try:
                wait_s = min(float(m.group(1)), self.WAIT_CAP_S)
            except ValueError:
                wait_s = 0.0
        changed = None
        if wait_s > 0 and not job.terminal:
            # long-poll: park until the job document or its stats feed
            # changes (atomic-rename artifacts — no torn observation),
            # or the window elapses. Terminal jobs answer immediately:
            # nothing will ever change again.
            start_token = self._state_token(job_id)
            deadline = time.monotonic() + wait_s  # madsim: allow(D001)
            changed = False
            while time.monotonic() < deadline:  # madsim: allow(D001)
                time.sleep(self.WAIT_TICK_S)  # madsim: allow(D001)
                if self._state_token(job_id) != start_token:
                    changed = True
                    break
            job = self.store.get(job_id)  # freshest doc after the park
        doc = job.to_dict()
        doc["feed"] = self.store.read_feed(job_id, last=feed_n)
        if changed is not None:
            doc["wait"] = {"waited": True, "changed": changed}
        return _json(200, doc)

    def _result(self, job_id: str) -> Tuple[int, str, bytes]:
        job = self.store.get(job_id)
        if job.state not in TERMINAL:
            return _err(
                409,
                f"job {job_id} is {job.state}; results exist once the job "
                f"reaches a terminal state ({', '.join(sorted(TERMINAL))})",
            )
        return _json(200, {
            "id": job.id,
            "state": job.state,
            "error": job.error,
            "result": job.result,
        })

    # -- the event log on the wire -------------------------------------------

    def _events(self, job_id: str, query: str) -> Tuple[int, str, bytes]:
        """One-shot JSON view of the event log (`?since=SEQ` filter;
        `?wait=S` parks until new events arrive, same deadline
        machinery as the /jobs/{id} long-poll). The SSE view of the
        same log is `events_stream` (negotiated by Accept header at the
        socket layer)."""
        job = self.store.get(job_id)  # 404/503 before touching the log
        since = _query_int(query, "since", 0)
        wait_s = _query_wait(query, self.WAIT_CAP_S)
        evs = self.store.read_events(job_id, since)
        if not evs and wait_s > 0 and not job.terminal:
            deadline = time.monotonic() + wait_s  # madsim: allow(D001)
            while time.monotonic() < deadline:  # madsim: allow(D001)
                time.sleep(self.WAIT_TICK_S)  # madsim: allow(D001)
                evs = self.store.read_events(job_id, since)
                if evs:
                    break
            job = self.store.get(job_id)
        last = max([since] + [int(e["seq"]) for e in evs])
        return _json(200, {
            "job": job_id,
            "since": since,
            "last_seq": last,
            "state": job.state,
            "terminal": job.terminal,
            "events": evs,
        })

    def events_stream(self, job_id: str, since: int = 0,
                      wait_s: Optional[float] = None) -> Iterator[bytes]:
        """Server-Sent Events over the job's event log: replay
        everything past `since`, then tail the log at WAIT_TICK_S
        cadence — the `?wait=S` deadline machinery reused as the
        tail-poll window, so no server thread parks longer than
        WAIT_CAP_S per request (clients reconnect with
        `since=<last id>`). A terminal state drains the log one last
        time and closes with `event: end`."""
        cap = self.WAIT_CAP_S if wait_s is None else min(
            float(wait_s), self.WAIT_CAP_S)
        deadline = time.monotonic() + max(cap, 0.0)  # madsim: allow(D001)
        last = int(since)
        yield b"retry: 1000\n\n"
        while True:
            try:
                job = self.store.get(job_id)
            except (KeyError, CorruptJobFile) as exc:
                yield _sse_frame({"seq": last, "type": "error",
                                  "error": str(exc)})
                return
            for ev in self.store.read_events(job_id, last):
                last = max(last, int(ev.get("seq", last)))
                yield _sse_frame(ev)
            if job.terminal:
                # one last drain: events appended between the read and
                # the terminal-state observation must not be lost
                for ev in self.store.read_events(job_id, last):
                    last = max(last, int(ev.get("seq", last)))
                    yield _sse_frame(ev)
                yield (b"event: end\ndata: " + json.dumps(
                    {"job": job_id, "state": job.state,
                     "last_seq": last}).encode() + b"\n\n")
                return
            if time.monotonic() >= deadline:  # madsim: allow(D001)
                return  # window over; the client reconnects with since=
            time.sleep(self.WAIT_TICK_S)  # madsim: allow(D001)

    def _timeline(self, job_id: str) -> Tuple[int, str, bytes]:
        """The merged cross-process Perfetto timeline: lifecycle events
        (this process's log) + the worker's span dumps, joined by the
        job id as trace id."""
        job = self.store.get(job_id)
        evs = self.store.read_events(job_id)
        spans = list(fleet_events.iter_jsonl(self.store.spans_path(job_id)))
        return _json(200, fleet_events.timeline_doc(
            job.to_dict(), evs, spans))

    def _profile(self, job_id: str) -> Tuple[int, str, bytes]:
        """The three-clock merge over the store's artifacts: the
        /timeline doc (control-plane lifecycle + worker host spans,
        including the worker's ``madsim.sync`` instants) is the host
        plane; the worker's device-profile dump (written when it ran
        under MADSIM_TPU_XPROF=1) and its failing lane's virtual-time
        trace join it through `xprof.merge_plane` — the same alignment
        `prof --merge` does locally, served from the store. xprof's
        module level is stdlib-only, so this stays in the jax-free
        control plane; with no device/virtual artifacts on disk the
        response degrades to the host plane plus a summary saying so."""
        from ..perf import xprof

        job = self.store.get(job_id)
        evs = self.store.read_events(job_id)
        spans = list(fleet_events.iter_jsonl(self.store.spans_path(job_id)))
        host = fleet_events.timeline_doc(job.to_dict(), evs, spans)
        dev = xprof.load_device_events(self.store.device_trace_path(job_id))
        vdoc = None
        try:
            with open(self.store.vtrace_path(job_id)) as f:
                vdoc = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            vdoc = None
        doc = xprof.merge_plane(host, dev, vdoc, meta={
            "job": job_id, "trace_id": job_id, "source": "fleet",
            "state": job.state,
        })
        return _json(200, doc)

    def _cancel(self, job_id: str) -> Tuple[int, str, bytes]:
        job = self.store.request_cancel(job_id)
        return _json(200, {
            "id": job.id,
            "state": job.state,
            "cancel_requested": job.cancel_requested,
        })

    # -- health --------------------------------------------------------------

    def _healthz(self) -> Tuple[int, str, bytes]:
        """Liveness + store integrity in one probe: a read-only fsck
        scan (per-file verdicts summarized, nothing mutated) plus the
        farm gauges. 200 only while every artifact is readable; a
        corrupt store answers 503 with the count and the fix, so a
        `curl -f` health check trips exactly when `fleet fsck` has
        work to do."""
        from . import fsck

        rep = fsck.scan(self.store)
        shedding = self._update_shed(self._queue_depth())
        store_ok = rep["corrupt"] == 0
        # a shedding farm is alive but degraded: writes are being
        # refused, so the probe answers 503 until the backlog drains
        ok = store_ok and not shedding
        doc = {
            "ok": ok,
            "store": {
                "files_scanned": rep["files_scanned"],
                "corrupt_files": rep["corrupt"],
                "drifted_jobs": rep["drifted"],
                "stale_tmp": rep["stale_tmp"],
                "torn_tails": rep["torn_tails"],
                "stale_claims": rep.get("stale_claims", 0),
            },
            "queue_depth": rep["queue_depth"],
            "stale_leases": rep["stale_leases"],
            "quarantined_jobs": rep["quarantined"],
            "queue_log_lag": rep.get("queue_log_lag", 0),
            "shed": shedding,
            "workers": self.store.read_worker_stats(),
            **({} if store_ok else {"fix": "run `fleet fsck --root "
                                    f"{self.store.root}`"}),
            **({"degraded": "load-shedding writes; queue depth "
                f"{rep['queue_depth']} >= {self.shed_depth}"}
               if shedding else {}),
        }
        return _json(200 if ok else 503, doc)

    # -- metrics -------------------------------------------------------------

    def _metrics(self) -> bytes:
        """Fleet-level gauges plus every job's own StatsEmitter
        Prometheus textfile. Per-job files are label-namespaced by the
        worker (`{job="<id>"}`), so concatenation is a valid exposition
        — `# TYPE` lines are deduped across files."""
        lines = ["# madsim_tpu fleet control plane"]
        jobs = self.store.list()
        counts = self.store.counts()
        lines.append("# TYPE madsim_tpu_fleet_jobs gauge")
        for s in STATES:
            lines.append(f'madsim_tpu_fleet_jobs{{state="{s}"}} {counts.get(s, 0)}')
        # the self-healing counters: requeues (all causes), lease
        # reclaims (the sweep's share of them) and the quarantine gauge
        lines.append("# TYPE madsim_tpu_fleet_requeues_total counter")
        lines.append(
            f"madsim_tpu_fleet_requeues_total "
            f"{sum(j.n_requeues for j in jobs)}"
        )
        lines.append("# TYPE madsim_tpu_fleet_lease_reclaims_total counter")
        lines.append(
            f"madsim_tpu_fleet_lease_reclaims_total "
            f"{sum(j.n_lease_reclaims for j in jobs)}"
        )
        lines.append("# TYPE madsim_tpu_fleet_quarantined_jobs gauge")
        lines.append(
            f"madsim_tpu_fleet_quarantined_jobs "
            f"{counts.get('quarantined', 0)}"
        )
        # the contention plane: claim races lost (per-worker stats
        # docs), zombie writes refused by fencing (per-job docs), the
        # index's honesty, and the admission ledger
        wstats = self.store.read_worker_stats()
        lines.append("# TYPE madsim_tpu_fleet_claim_conflicts_total counter")
        lines.append(
            f"madsim_tpu_fleet_claim_conflicts_total "
            f"{sum(int(w.get('claim_conflicts', 0)) for w in wstats.values())}"
        )
        lines.append("# TYPE madsim_tpu_fleet_fenced_writes_total counter")
        lines.append(
            f"madsim_tpu_fleet_fenced_writes_total "
            f"{sum(j.n_fenced_writes for j in jobs)}"
        )
        lines.append("# TYPE madsim_tpu_fleet_queue_log_lag gauge")
        lines.append(
            f"madsim_tpu_fleet_queue_log_lag {self.store.queue_log_lag()}")
        lines.append("# TYPE madsim_tpu_fleet_shed gauge")
        lines.append(f"madsim_tpu_fleet_shed {int(self.shedding)}")
        lines.append("# TYPE madsim_tpu_fleet_sheds_total counter")
        lines.append(f"madsim_tpu_fleet_sheds_total {self.sheds_total}")
        with self._admission_lock:
            admission = {t: dict(per) for t, per in self._admission.items()}
        if admission:
            lines.append("# TYPE madsim_tpu_fleet_admission_total counter")
            for tenant in sorted(admission):
                for outcome in sorted(admission[tenant]):
                    lines.append(
                        f'madsim_tpu_fleet_admission_total'
                        f'{{tenant="{tenant}",outcome="{outcome}"}} '
                        f'{admission[tenant][outcome]}'
                    )
        self._slo_histograms(lines, jobs)
        self._bench_trajectory(lines)
        seen_types = {"madsim_tpu_fleet_jobs",
                      "madsim_tpu_fleet_requeues_total",
                      "madsim_tpu_fleet_lease_reclaims_total",
                      "madsim_tpu_fleet_quarantined_jobs",
                      "madsim_tpu_fleet_claim_conflicts_total",
                      "madsim_tpu_fleet_fenced_writes_total",
                      "madsim_tpu_fleet_queue_log_lag",
                      "madsim_tpu_fleet_shed",
                      "madsim_tpu_fleet_sheds_total",
                      "madsim_tpu_fleet_admission_total"}
        for job in jobs:
            # parsed-textfile cache keyed (path, mtime, size): a scrape
            # of an unchanged store re-parses nothing, so scrape cost
            # stops being O(jobs) parse work
            rows = self._prom_cache.get(
                self.store.stats_base(job.id) + ".prom", _parse_prom)
            for kind, name, line in rows or ():
                if kind == "type":
                    if name in seen_types:
                        continue
                    seen_types.add(name)
                lines.append(line)
        return ("\n".join(lines) + "\n").encode()

    #: SLO histogram buckets (seconds for the *_seconds metrics, plain
    #: counts for fleet_batches_per_find — same ladder, documented)
    SLO_BUCKETS = (0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                   300.0, 600.0)

    #: metric name -> per-job SLO observation key (events.slo_observations)
    SLO_METRICS = (
        ("madsim_tpu_fleet_queue_wait_seconds", "queue_wait_s"),
        ("madsim_tpu_fleet_time_to_first_find_seconds",
         "time_to_first_find_s"),
        ("madsim_tpu_fleet_lane_seconds_per_find", "lane_seconds_per_find"),
        ("madsim_tpu_fleet_batches_per_find", "batches_per_find"),
    )

    def _bench_trajectory(self, lines: List[str]) -> None:
        """The BENCH_HISTORY.jsonl trajectory as gauges: for each
        comparable-fingerprint group (platform + lanes + gate tuple +
        host — `perf/history.comparable`), the NEWEST row's throughput
        and warm compile, labeled by its tag. The scrape answers "what
        is this box's current bench baseline, and which capture set
        it" without shelling out to `bench report`; rows from other
        boxes/configs export as their own series instead of being
        averaged into noise. File resolution matches bench.py
        ($MADSIM_TPU_BENCH_HISTORY, else the repo's checked-in file);
        parsed via the stat-keyed cache — unchanged history, zero
        re-reads. Absent file → no series (a farm box without the repo
        checkout scrapes clean)."""
        from ..perf import history

        path = os.environ.get("MADSIM_TPU_BENCH_HISTORY") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            history.DEFAULT_BASENAME,
        )
        rows = self._bench_cache.get(path, history.load)
        if not rows:
            return
        # newest row per comparability group, file order == time order
        heads: List[dict] = []
        for row in rows:
            for i, head in enumerate(heads):
                if history.comparable(row.get("fingerprint"),
                                      head.get("fingerprint")):
                    heads[i] = row
                    break
            else:
                heads.append(row)
        series = (
            ("madsim_tpu_bench_seeds_per_sec", "value",
             "newest capture per comparable fingerprint"),
            ("madsim_tpu_bench_compile_s_warm", "compile_s_warm",
             "persistent-cache warm start, same grouping"),
        )
        for name, key, help_text in series:
            rendered = False
            for row in heads:
                val = row.get(key)
                if val is None:
                    continue  # e.g. no cache configured: no warm path
                fp = row.get("fingerprint") or {}
                labels = ",".join(
                    f'{k}="{v}"' for k, v in (
                        ("tag", row.get("tag", "?")),
                        ("platform", fp.get("platform", "?")),
                        ("lanes", fp.get("lanes", "?")),
                        ("host", fp.get("host") or "?"),
                    )
                )
                if not rendered:
                    lines.append(f"# HELP {name} {help_text}")
                    lines.append(f"# TYPE {name} gauge")
                    rendered = True
                lines.append(f"{name}{{{labels}}} {val:g}")

    def _slo_histograms(self, lines: List[str], jobs) -> None:
        """SLO metrics derived from the event log at scrape time —
        pure deltas over each job's events.jsonl (via the stat-keyed
        cache), nothing precomputed or stored. A job contributes to a
        histogram only once the underlying events exist (no finds →
        no find-latency sample)."""
        samples: Dict[str, List[float]] = {k: [] for _n, k in self.SLO_METRICS}
        for job in jobs:
            obs = fleet_events.slo_observations(self._job_events(job.id))
            for _name, key in self.SLO_METRICS:
                if key in obs:
                    samples[key].append(obs[key])
        for name, key in self.SLO_METRICS:
            vals = samples[key]
            lines.append(f"# TYPE {name} histogram")
            acc = 0
            for le in self.SLO_BUCKETS:
                acc = sum(1 for v in vals if v <= le)
                lines.append(f'{name}_bucket{{le="{le:g}"}} {acc}')
            lines.append(f'{name}_bucket{{le="+Inf"}} {len(vals)}')
            lines.append(f"{name}_sum {round(sum(vals), 6):g}")
            lines.append(f"{name}_count {len(vals)}")


def make_handler(api: FleetAPI):
    class Handler(http.server.BaseHTTPRequestHandler):
        def _dispatch(self, method: str) -> None:
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else None
            status, ctype, payload = api.handle(method, self.path, body)
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            if status == 429:
                # the admission verdict carries the precise wait in its
                # JSON body (retry_after_s); the header is the RFC's
                # integer delta-seconds rendering of the same number
                try:
                    ra = json.loads(payload).get("retry_after_s")
                except (json.JSONDecodeError, ValueError, AttributeError):
                    ra = None
                if ra is not None:
                    self.send_header("Retry-After",
                                     str(max(1, math.ceil(float(ra)))))
            self.end_headers()
            self.wfile.write(payload)

        def _maybe_stream_events(self) -> bool:
            """SSE content negotiation for /jobs/{id}/events: a client
            asking for `text/event-stream` gets the live tail — sent
            frame by frame, flushed per event, no Content-Length (the
            connection close delimits the stream; `fleet watch`
            reconnects with since=<last id>)."""
            path, _, query = self.path.partition("?")
            m = _JOB_RE.match(path.rstrip("/") or "/")
            if not (m and m.group(2) == "/events"
                    and "text/event-stream" in
                    (self.headers.get("Accept") or "")):
                return False
            since = _query_int(query, "since", 0)
            wait_s = _query_wait(query, FleetAPI.WAIT_CAP_S) or None
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            try:
                for frame in api.events_stream(m.group(1), since, wait_s):
                    self.wfile.write(frame)
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass  # watcher went away; nothing to clean up
            return True

        def do_GET(self):  # noqa: N802 (stdlib API name)
            if self._maybe_stream_events():
                return
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

        def do_DELETE(self):  # noqa: N802
            self._dispatch("DELETE")

        def log_message(self, fmt, *a):  # route access logs to logging
            _LOG.debug(fmt, *a)

    return Handler


def serve(root: str, addr: str, port_file: Optional[str] = None,
          sweep_interval_s: float = 5.0) -> int:
    """`fleet serve` entry: bind (port 0 supported), announce the
    realized port (stdout + optional --port-file), serve until
    SIGTERM/Ctrl-C, close gracefully. A daemon supervisor thread runs
    the lease-reclamation sweep every `sweep_interval_s` (0 disables):
    expired worker leases requeue their jobs with backoff — or
    quarantine at the attempt cap — so the farm heals even while no
    worker is alive to sweep for itself."""
    store = JobStore(root)
    stop = threading.Event()

    def _sweep() -> None:
        while not stop.wait(sweep_interval_s):
            try:
                for act in store.reclaim_expired():
                    print(
                        f"sweep: reclaimed {act['job']} from dead "
                        f"worker {act['worker']} -> {act['outcome']} "
                        f"(attempt {act['attempt']})", flush=True,
                    )
                # pay the O(n) index-healing scan here so the workers'
                # poll path never has to: any job the queue log
                # misrepresents (mirror append lost to a crash) gets a
                # correction row
                fixed = store.sync_queue_log()
                if fixed:
                    print(f"sweep: healed {fixed} stale queue-index "
                          f"row(s)", flush=True)
            except Exception:  # the farm outlives a bad sweep pass
                _LOG.exception("lease-reclamation sweep failed")

    srv, host, port = httpd.bind(addr, make_handler(FleetAPI(store)))
    print(
        f"fleet control plane on {host}:{port} (root {store.root}; "
        f"GET /queue /jobs/{{id}} /jobs/{{id}}/result /jobs/{{id}}/events "
        f"/jobs/{{id}}/timeline /jobs/{{id}}/profile /metrics /healthz, "
        f"POST /jobs, DELETE /jobs/{{id}}; lease sweep every "
        f"{sweep_interval_s:g}s)",
        flush=True,
    )
    sweeper = None
    if sweep_interval_s > 0:
        sweeper = threading.Thread(
            target=_sweep, daemon=True, name="fleet-lease-sweep"
        )
        sweeper.start()
    try:
        return httpd.run_http_server(srv, port_file=port_file)
    finally:
        stop.set()
        if sweeper is not None:
            sweeper.join(timeout=2)
