"""Multi-tenant lane allocator — pack work units by warm-compile key.

A work unit is one seed batch of one job. On a 1-core box the worker
runs exactly one unit at a time (never two engine configs in flight),
so the scheduling question is purely *ordering* — and the dominant cost
to order around is compilation: switching engine configs pays a trace +
compile (or at best a persistent-cache deserialize), while staying
within one `cache_subkey` group reuses the warm jit for free. So the
allocator is deliberately sticky:

* units from jobs sharing the in-flight job's `cache_subkey` are packed
  back-to-back (round-robin WITHIN the group, so concurrent tenants on
  the same compile all make batch-by-batch progress and their live
  feeds stream together);
* the worker only switches subkey groups when the current group drains,
  or when a strictly higher-priority job is waiting in another group
  (priority is allowed to pay the compile switch; fairness inside a
  priority level is not);
* which group starts first is decided by (priority desc, earliest
  deadline, submit order) over each group's best job;
* WITHIN the chosen group's equal-priority ring, the coverage-feedback
  scheduler (`fleet/scheduler.py`) reallocates lane-time: jobs whose
  live stats feed still shows new coverage slots (or that have no
  signal yet) are served before stalled ones — lane-time goes where
  bugs still hide, and a stalled job gets its lanes back the moment
  the active set drains.

Pure host-side policy over `Job` records + the optional momentum map
the worker reads for it — no jax, no IO here; the worker owns all
store writes. Unit-testable in microseconds.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .store import Job

_FAR_FUTURE = float("inf")


def _job_rank(job: Job) -> tuple:
    """Lower ranks run earlier: priority desc, deadline asc, id asc."""
    return (
        -job.priority,
        job.deadline_ts if job.deadline_ts is not None else _FAR_FUTURE,
        job.id,
    )


class LaneAllocator:
    """Stateful picker: remembers the in-flight subkey (stickiness) and
    the last job served per subkey (round-robin within the group)."""

    def __init__(self):
        self.current_subkey: Optional[str] = None
        self._last_served: dict = {}  # subkey -> job id

    def pick(self, candidates: List[Job],
             momentum: Optional[Dict[str, dict]] = None) -> Optional[Job]:
        """Choose the job whose next batch-sized unit runs now, or None
        when there is nothing runnable. `candidates` are jobs the
        worker can lease (non-terminal, lease available); `momentum`
        is the coverage-feedback map from `scheduler.momentum_for` —
        when present, active jobs (still finding new slots / no signal
        yet) outrank stalled ones within the equal-priority ring."""
        if not candidates:
            return None
        groups: dict = {}
        for job in candidates:
            groups.setdefault(job.subkey, []).append(job)
        best_of = {
            sk: min(jobs, key=_job_rank) for sk, jobs in groups.items()
        }
        # the globally best-ranked job defines the priority bar
        target_sk = min(best_of, key=lambda sk: _job_rank(best_of[sk]))
        sk = self.current_subkey
        if sk in groups and (
            best_of[target_sk].priority <= best_of[sk].priority
        ):
            # sticky: stay on the warm compile unless a strictly
            # higher-priority tenant waits elsewhere
            target_sk = sk
        self.current_subkey = target_sk
        group = sorted(groups[target_sk], key=_job_rank)
        top_priority = group[0].priority
        ring = [j for j in group if j.priority == top_priority]
        if momentum is not None:
            # lane-time goes where bugs still hide: serve the active
            # front; stalled jobs wait until the actives drain
            active = [
                j for j in ring
                if momentum.get(j.id, {}).get("active", True)
            ]
            if active:
                ring = active
        # round-robin within the (active front of the) equal-priority
        # ring, so concurrent productive tenants interleave
        last = self._last_served.get(target_sk)
        ids = [j.id for j in ring]
        if last in ids and len(ids) > 1:
            chosen = ring[(ids.index(last) + 1) % len(ids)]
        else:
            chosen = ring[0]
        self._last_served[target_sk] = chosen.id
        return chosen
