"""Store fsck — scan every fleet artifact, verdict each file, heal.

The store's crash-safety claim (`runtime/atomicio`: tmp + fsync +
rename + dir-fsync) means the farm itself never produces a torn file —
but disks lie, operators copy half a directory, and pre-fsync-era
artifacts exist. `fleet fsck` is the tool that makes corruption a
*reported, recoverable* condition instead of an uncaught exception
somewhere inside a worker:

* every file under `<root>/jobs/` plus the fleet corpus gets an exact
  per-file verdict — `ok`, `truncated` (JSON ends mid-document),
  `unparseable` (garbage mid-file), `bad-schema` (valid JSON, wrong
  shape), `fingerprint-inconsistent` (a checkpoint whose fingerprint
  does not match its owning job), `drifted` (a job doc whose spec no
  longer hashes to its recorded fingerprint), `stale-tmp` (an
  interrupted atomic write's tmp file), `torn-tail` (a JSONL feed
  whose final line is cut), `stale-claim` (a claim file from a dead
  lease generation — removed; the job flock is the authority),
  `index-stale` (queue.log disagrees with the job docs — rebuilt from
  the docs) or `unknown`;
* with `fix` (the CLI default; `--dry-run` scans only), unreadable
  files are quarantined to `<name>.corrupt` and stale tmp files are
  removed, then the queue's state counts are rebuilt from the
  surviving documents — the directory IS the queue index, so the
  rebuilt counts are the rebuilt index;
* `drifted` job docs are reported but left in place: the worker's
  fingerprint refusal fails them with a message naming every drifted
  field, which keeps the audit trail in the state machine instead of
  a sidecar file;
* `--reclaim` additionally runs the lease-reclamation sweep
  (`store.reclaim_expired`) and `--release-quarantined` re-queues
  quarantined jobs — together they are the full "heal the farm"
  operator verb.

`scan()` (read-only) also backs the control plane's `/healthz`, which
reports store integrity, queue depth, stale-lease count and
quarantined-job count.

Pure host-side stdlib, jax-free by contract (the corpus is validated
structurally from its JSON — `engine.corpus` is deliberately NOT
imported here).
"""

from __future__ import annotations

# madsim: allow-file(D001) — stale-lease detection compares recorded
# lease expiries against the host wall clock; this is supervisor-side
# service code, nothing feeds simulation state.
import json
import os
import time
from typing import List, Optional

from .store import (
    LEASABLE,
    QUARANTINED,
    QUEUED,
    STATES,
    Job,
    JobStore,
    job_fingerprint,
    spec_sha,
)

OK = "ok"
TRUNCATED = "truncated"
UNPARSEABLE = "unparseable"
BAD_SCHEMA = "bad-schema"
FP_INCONSISTENT = "fingerprint-inconsistent"
DRIFTED = "drifted"
STALE_TMP = "stale-tmp"
TORN_TAIL = "torn-tail"
STALE_CLAIM = "stale-claim"
INDEX_STALE = "index-stale"
UNKNOWN = "unknown"

#: verdicts that make a file unreadable — counted as corruption,
#: quarantined to *.corrupt by a fixing fsck
CORRUPT_VERDICTS = frozenset({TRUNCATED, UNPARSEABLE, BAD_SCHEMA,
                              FP_INCONSISTENT})

#: entry keys a corpus record must carry to be replayable
_CORPUS_ENTRY_KEYS = frozenset({"machine", "seed", "fail_code", "config"})


def _classify_json(text: str):
    """(doc, verdict, detail): `truncated` when the decode error sits at
    the end of the data (the tail is missing), `unparseable` when the
    damage is mid-file."""
    try:
        return json.loads(text), OK, ""
    except json.JSONDecodeError as exc:
        tail = exc.pos >= len(text.rstrip())
        return None, (TRUNCATED if tail else UNPARSEABLE), (
            f"{exc.msg} at byte {exc.pos}/{len(text)}"
        )


def _read(path: str):
    try:
        with open(path, "r", errors="replace") as f:
            return f.read(), None
    except OSError as exc:
        return None, str(exc)


def _check_job_doc(path: str, fn: str, finding: dict,
                   jobs_by_id: dict) -> None:
    text, err = _read(path)
    if text is None:
        finding.update(verdict=UNPARSEABLE, detail=err)
        return
    doc, verdict, detail = _classify_json(text)
    if verdict != OK:
        finding.update(verdict=verdict, detail=detail)
        return
    try:
        job = Job.from_dict(doc)
    except TypeError as exc:
        finding.update(verdict=BAD_SCHEMA, detail=str(exc))
        return
    expect_id = fn[: -len(".json")]
    if job.id != expect_id or job.state not in STATES:
        finding.update(
            verdict=BAD_SCHEMA,
            detail=f"id {job.id!r} / state {job.state!r} inconsistent "
                   f"with filename",
        )
        return
    jobs_by_id[job.id] = job
    try:
        drifted = (
            job_fingerprint(job.spec) != job.fingerprint
            or spec_sha(job.spec) != job.fingerprint_sha
        )
    except (KeyError, ValueError, TypeError) as exc:
        finding.update(verdict=BAD_SCHEMA, detail=f"spec: {exc}")
        return
    if drifted:
        finding.update(
            verdict=DRIFTED,
            detail="spec no longer matches its recorded fingerprint — "
                   "left in place; the worker fails it with the "
                   "field-by-field refusal",
        )


def _check_ckpt(path: str, fn: str, finding: dict, jobs_by_id: dict) -> None:
    from ..runtime.checkpoint import CKPT_REQUIRED_KEYS, CKPT_VERSION

    text, err = _read(path)
    if text is None:
        finding.update(verdict=UNPARSEABLE, detail=err)
        return
    doc, verdict, detail = _classify_json(text)
    if verdict != OK:
        finding.update(verdict=verdict, detail=detail)
        return
    if not isinstance(doc, dict) or doc.get("version") != CKPT_VERSION:
        finding.update(
            verdict=BAD_SCHEMA,
            detail=f"checkpoint version {doc.get('version') if isinstance(doc, dict) else doc!r}",
        )
        return
    missing = sorted(CKPT_REQUIRED_KEYS - doc.keys())
    if missing:
        finding.update(verdict=BAD_SCHEMA, detail=f"missing keys {missing}")
        return
    owner = jobs_by_id.get(fn[: -len(".ckpt.json")])
    if owner is not None and doc.get("fingerprint") != owner.fingerprint:
        finding.update(
            verdict=FP_INCONSISTENT,
            detail="checkpoint fingerprint != owning job's — a resume "
                   "would be refused; quarantining restarts the stream "
                   "from batch 0",
        )


def _check_jsonl(path: str, finding: dict,
                 torn_anywhere: bool = False) -> None:
    """`torn_anywhere=False` (stats feeds, rewritten whole): only the
    FINAL line may legitimately be cut — damage anywhere else is real
    corruption. `torn_anywhere=True` (event logs / span dumps, true
    fsync'd appends): a kill mid-append followed by the next append's
    newline-heal leaves torn records mid-file by design, so ANY set of
    bad lines is the reported-never-quarantined torn-tail verdict —
    every reader skips them and the seq chain stays monotonic."""
    text, err = _read(path)
    if text is None:
        finding.update(verdict=UNPARSEABLE, detail=err)
        return
    lines = text.splitlines()
    bad = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            json.loads(line)
        except json.JSONDecodeError:
            bad.append(i)
    if not bad:
        return
    if bad == [len(lines) - 1]:
        # a torn tail is the EXPECTED shape of an append-mode feed cut
        # mid-line; every reader skips it, so it is reported but never
        # quarantined
        finding.update(verdict=TORN_TAIL,
                       detail=f"final line {bad[0] + 1} cut mid-record")
    elif torn_anywhere:
        finding.update(verdict=TORN_TAIL,
                       detail=f"{len(bad)} torn record(s) at lines "
                              f"{[i + 1 for i in bad[:5]]} (append-mode "
                              "log; readers skip them)")
    else:
        finding.update(verdict=UNPARSEABLE,
                       detail=f"unparseable lines {bad[:5]}")


def _check_claim(path: str, fn: str, finding: dict, jobs_by_id: dict,
                 now: float) -> None:
    """A claim file is live iff it names the owning job's CURRENT
    unexpired lease holder (and, when stamped, its generation). Any
    other claim — torn stamp, dead generation, expired hold, no owning
    doc — is advisory garbage a fixing fsck removes. Removal is always
    safe: the per-job flock, not the claim, is the authoritative
    arbiter, so the worst a wrongly-removed claim costs is one extra
    lock round."""
    text, err = _read(path)
    if text is None:
        finding.update(verdict=STALE_CLAIM, detail=err)
        return
    doc, verdict, _detail = _classify_json(text)
    if verdict != OK or not isinstance(doc, dict):
        finding.update(
            verdict=STALE_CLAIM,
            detail="torn claim stamp (crash mid-claim); the job flock "
                   "arbitrates around it",
        )
        return
    job = jobs_by_id.get(fn[: -len(".claim")])
    lease = job.lease if job is not None else None
    live = (
        lease is not None
        and lease.get("worker") == doc.get("worker")
        and (lease.get("expires_ts") or 0) > now
        and doc.get("gen") in (None, lease.get("gen"))
    )
    if not live:
        finding.update(
            verdict=STALE_CLAIM,
            detail=f"claim by {doc.get('worker')!r} gen {doc.get('gen')} "
                   f"does not match a live lease — dead generation",
        )


def _check_corpus(path: str, finding: dict) -> None:
    text, err = _read(path)
    if text is None:
        finding.update(verdict=UNPARSEABLE, detail=err)
        return
    doc, verdict, detail = _classify_json(text)
    if verdict != OK:
        finding.update(verdict=verdict, detail=detail)
        return
    entries = doc.get("entries") if isinstance(doc, dict) else None
    if not isinstance(entries, list):
        finding.update(verdict=BAD_SCHEMA, detail="no entries list")
        return
    bad = [
        i for i, e in enumerate(entries)
        if not (isinstance(e, dict) and _CORPUS_ENTRY_KEYS <= e.keys())
    ]
    if bad:
        finding.update(
            verdict=BAD_SCHEMA,
            detail=f"entries {bad[:5]} missing replay keys "
                   f"{sorted(_CORPUS_ENTRY_KEYS)}",
        )


def scan(store: JobStore) -> dict:
    """Read-only integrity scan: per-file verdicts + farm gauges.
    Never mutates anything — safe to run from `/healthz` on every
    probe."""
    findings: List[dict] = []
    jobs_by_id: dict = {}
    now = time.time()
    names = sorted(os.listdir(store.jobs_dir))
    # job docs first: checkpoint fingerprint checks need their owners
    names.sort(key=lambda fn: 0 if fn.endswith(".json")
               and ".ckpt" not in fn and ".stats" not in fn else 1)
    for fn in names:
        path = os.path.join(store.jobs_dir, fn)
        finding = {"path": path, "file": fn, "verdict": OK, "detail": "",
                   "action": "none"}
        if fn.endswith(".lock") or fn.endswith(".corrupt"):
            continue  # lock files are contentless; .corrupt already swept
        elif fn.endswith(".tmp"):
            finding.update(
                verdict=STALE_TMP,
                detail="interrupted atomic write (rename never ran)",
            )
        elif fn.endswith(".ckpt.json"):
            _check_ckpt(path, fn, finding, jobs_by_id)
        elif fn.endswith(".stats.jsonl"):
            _check_jsonl(path, finding)
        elif fn.endswith(".events.jsonl") or fn.endswith(".spans.jsonl"):
            # append-only observability logs: torn records (even
            # mid-file, from a kill-mid-append + newline-heal) are
            # reported, never quarantined
            _check_jsonl(path, finding, torn_anywhere=True)
        elif fn.endswith(".stats.json"):
            text, err = _read(path)
            if text is None:
                finding.update(verdict=UNPARSEABLE, detail=err)
            else:
                _doc, verdict, detail = _classify_json(text)
                if verdict != OK:
                    finding.update(verdict=verdict, detail=detail)
        elif fn.endswith(".stats.prom"):
            pass  # text exposition; concatenator skips bad lines
        elif fn.endswith(".device.trace.json.gz"):
            pass  # binary profile capture; /profile tolerates garbage
        elif fn.endswith(".vtrace.json"):
            text, err = _read(path)
            if text is None:
                finding.update(verdict=UNPARSEABLE, detail=err)
            else:
                _doc, verdict, detail = _classify_json(text)
                if verdict != OK:
                    finding.update(verdict=verdict, detail=detail)
        elif fn.endswith(".claim"):
            _check_claim(path, fn, finding, jobs_by_id, now)
        elif fn.endswith(".json"):
            _check_job_doc(path, fn, finding, jobs_by_id)
        else:
            finding.update(verdict=UNKNOWN,
                           detail="not a fleet artifact")
        if finding["verdict"] != OK:
            findings.append(finding)
    if os.path.exists(store.corpus_path):
        finding = {"path": store.corpus_path, "file": "corpus.json",
                   "verdict": OK, "detail": "", "action": "none"}
        _check_corpus(store.corpus_path, finding)
        if finding["verdict"] != OK:
            findings.append(finding)
    # the log-structured queue index: torn tail (a crash mid-append —
    # readers already skip it) and index/doc disagreement are both
    # reported here; a fixing fsck rebuilds the log from the job docs,
    # which stay the source of truth
    qlag = 0
    if os.path.exists(store.queue_log_path):
        finding = {"path": store.queue_log_path, "file": "queue.log",
                   "verdict": OK, "detail": "", "action": "none"}
        _check_jsonl(store.queue_log_path, finding, torn_anywhere=True)
        qlag = store.queue_log_lag()
        if qlag:
            lag_detail = (f"{qlag} job(s) misrepresented by the index "
                          f"(doc state differs or row missing)")
            if finding["verdict"] == OK:
                finding.update(verdict=INDEX_STALE, detail=lag_detail)
            else:
                finding["detail"] += f"; {lag_detail}"
        if finding["verdict"] != OK:
            findings.append(finding)

    jobs = list(jobs_by_id.values())
    counts = {s: 0 for s in STATES}
    for j in jobs:
        counts[j.state] = counts.get(j.state, 0) + 1
    return {
        "root": store.root,
        "files_scanned": (len(names) + int(os.path.exists(store.corpus_path))
                          + int(os.path.exists(store.queue_log_path))),
        "findings": findings,
        "corrupt": sum(1 for f in findings
                       if f["verdict"] in CORRUPT_VERDICTS),
        "drifted": sum(1 for f in findings if f["verdict"] == DRIFTED),
        "stale_tmp": sum(1 for f in findings
                         if f["verdict"] == STALE_TMP),
        "torn_tails": sum(1 for f in findings
                          if f["verdict"] == TORN_TAIL),
        "stale_claims": sum(1 for f in findings
                            if f["verdict"] == STALE_CLAIM),
        "queue_log_lag": qlag,
        "counts": {s: n for s, n in counts.items() if n},
        "jobs": len(jobs),
        "queue_depth": counts.get(QUEUED, 0),
        "quarantined": counts.get(QUARANTINED, 0),
        "stale_leases": sum(
            1 for j in jobs
            if j.state in LEASABLE and j.lease
            and j.lease["expires_ts"] <= now
        ),
    }


def fsck(root: str, *, fix: bool = True, reclaim: bool = False,
         release_quarantined: bool = False,
         max_attempts: Optional[int] = None,
         backoff_base_s: Optional[float] = None) -> dict:
    """Scan + heal. With `fix`, unreadable files move to `*.corrupt`
    and stale tmp files are removed; the report's `counts` are then
    re-derived from the surviving documents (the rebuilt queue index).
    `reclaim` runs the lease-reclamation sweep; `release_quarantined`
    re-queues quarantined jobs (attempt counter reset)."""
    store = JobStore(root)
    report = scan(store)
    if fix:
        rebuilt = False
        for finding in report["findings"]:
            if finding["file"] == "queue.log":
                # torn tail or stale index, same repair: rewrite the
                # log from the job documents (the source of truth)
                if not rebuilt:
                    n = store.rebuild_queue_log()
                    rebuilt = True
                    finding["action"] = f"rebuilt from {n} job doc(s)"
            elif finding["verdict"] == STALE_CLAIM:
                os.remove(finding["path"])
                finding["action"] = "removed"
            elif finding["verdict"] in CORRUPT_VERDICTS:
                target = finding["path"] + ".corrupt"
                os.replace(finding["path"], target)
                finding["action"] = f"quarantined -> {target}"
            elif finding["verdict"] == STALE_TMP:
                os.remove(finding["path"])
                finding["action"] = "removed"
    if reclaim:
        kw = {}
        if max_attempts is not None:
            kw["max_attempts"] = max_attempts
        if backoff_base_s is not None:
            kw["backoff_base_s"] = backoff_base_s
        report["reclaimed"] = store.reclaim_expired(**kw)
    if release_quarantined:
        report["released"] = [
            store.release_quarantined(j.id).id
            for j in store.list() if j.state == QUARANTINED
        ]
    if fix:
        report["counts"] = {
            s: n for s, n in store.counts().items() if n
        }
        report["queue_depth"] = report["counts"].get(QUEUED, 0)
        report["quarantined"] = report["counts"].get(QUARANTINED, 0)
    return report


def render(report: dict) -> str:
    """Human-readable fsck report: one line per non-ok file, then the
    farm summary."""
    lines = [f"fleet fsck: {report['root']}"]
    for f in report["findings"]:
        act = f" [{f['action']}]" if f["action"] != "none" else ""
        lines.append(f"  {f['file']}: {f['verdict']} — {f['detail']}{act}")
    if not report["findings"]:
        lines.append("  all files ok")
    for key in ("reclaimed", "released"):
        for act in report.get(key, []):
            if key == "reclaimed":
                lines.append(
                    f"  reclaimed {act['job']} from {act['worker']} -> "
                    f"{act['outcome']} (attempt {act['attempt']})"
                )
            else:
                lines.append(f"  released {act} from quarantine")
    counts = ", ".join(f"{s}={n}" for s, n in report["counts"].items())
    lines.append(
        f"  {report['jobs']} jobs [{counts or 'none'}], "
        f"{report['corrupt']} corrupt, {report['drifted']} drifted, "
        f"{report['stale_tmp']} stale tmp, "
        f"{report['torn_tails']} torn tails, "
        f"{report.get('stale_claims', 0)} stale claims, "
        f"queue-log lag {report.get('queue_log_lag', 0)}, "
        f"{report['stale_leases']} stale leases"
    )
    return "\n".join(lines)
