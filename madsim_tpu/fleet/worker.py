"""The fleet worker — lease, slice, checkpoint, shrink, file.

`python -m madsim_tpu fleet worker --root DIR` turns the store's queue
into engine time. The loop:

1. **Lease.** Scan the store for leasable jobs (queued, or mid-flight
   with an expired/own lease — crash recovery), refuse any whose spec
   drifted from its recorded fingerprint (the checkpoint-refusal
   discipline, surfaced verbatim as the job's `failed` reason), and let
   the `LaneAllocator` pick the next work unit — packed by
   `cache_subkey` so tenants sharing a compile run back-to-back on the
   warm jit.
2. **Run one unit.** One unit = one seed batch, driven through the SAME
   chunked streaming driver the `hunt` CLI uses
   (`__main__._stream_batches` with `stop_after_batches = done + 1`):
   the job's fingerprinted `--checkpoint` file advances atomically
   after every batch, so a `kill -9` anywhere loses at most one batch
   and the resumed job's final report is byte-identical to an
   uninterrupted run. Per-batch stats stream to the job's own
   StatsEmitter feed (label-namespaced for the fleet /metrics).
3. **Finalize.** On budget exhaustion / coverage plateau / deadline /
   cancel, close the lifecycle: no finds -> `exhausted`/`plateaued`;
   finds -> `found` -> `shrink` one representative per distinct fail
   code (provenance-guided when the gate rode the hunt) -> `shrunk` ->
   file each as a corpus entry carrying filed-by-job metadata + its
   minimal repro line + `why` attribution -> `filed`.

Engine reuse: one live Engine per `engine_key` (model + vocabulary +
gates + lane shape), dropped when the allocator switches subkey groups
— never two engine configs in flight at once on a 1-core box. A
PerfRecorder session (`--perf-timeline`) wraps every unit in a
`fleet_unit` span with the job id, so warm-compile reuse is readable
straight off the host timeline (the second tenant's unit contains no
`compile` span at all).

Self-healing (the failure taxonomy — every path seeded-fault-tested by
`fleet chaos`):

* **Lease deaths.** Every lease poll starts with the store's
  `reclaim_expired` sweep: a job whose worker lease expired is requeued
  (checkpoint preserved — the next worker resumes at <=1 lost batch)
  with exponential backoff, or quarantined after `--max-attempts`
  consecutive deaths.
* **Hard failures** (engine raise): one poison attempt each —
  requeue/quarantine as above, with exception + batch index + exact
  repro command recorded on the job.
* **OOM-class failures**: lane-count backoff first — halve `batch`,
  re-derive the warm-compile subkey, record the degradation, reset the
  (now fingerprint-mismatched) checkpoint — before burning poison
  attempts; below MIN_DEGRADED_BATCH lanes OOM counts as hard.
* **Deterministic refusals** (fingerprint drift, SystemExit contract
  violations) go straight to `failed`: retrying cannot help.
* **Torn checkpoints** (external corruption — the fsync'd atomic
  writes never produce one) are quarantined to `*.corrupt` and the
  stream restarts from batch 0 instead of wedging in a refusal loop.
"""

from __future__ import annotations

# madsim: allow-file(D001) — the worker is host-side service code: it
# reads the wall clock only for lease renewal, deadline enforcement,
# idle polling and per-unit throughput logs. Nothing feeds simulation
# state; a job's results are a pure function of (fingerprint, seed
# schedule).
import contextlib
import importlib
import json
import logging
import os
import random
import time
from typing import Callable, List, Optional, Tuple

from .allocator import LaneAllocator
from .store import (
    CANCELLED,
    COMPILING,
    EXHAUSTED,
    FAILED,
    FILED,
    FOUND,
    LEASABLE,
    MAX_ATTEMPTS,
    PLATEAUED,
    QUARANTINED,
    QUEUED,
    REQUEUE_BACKOFF_BASE_S,
    RUNNING,
    SHRUNK,
    CorruptJobFile,
    FencedWrite,
    Job,
    JobStore,
    engine_key,
    repro_cmd,
    spec_to_args,
)

_LOG = logging.getLogger("madsim_tpu.fleet.worker")

#: substrings marking an allocation-class failure (jax surfaces device
#: OOM as XlaRuntimeError with a RESOURCE_EXHAUSTED status); these get
#: the lane-count backoff retry instead of burning poison attempts
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory",
                "OutOfMemory")

#: below this lane count OOM stops degrading and counts as a hard
#: failure — halving forever just hides a leak
MIN_DEGRADED_BATCH = 8


class FleetWorker:
    def __init__(self, root: str, *, worker_id: str = "w0",
                 lease_ttl_s: float = 60.0, poll_s: float = 0.5,
                 max_attempts: int = MAX_ATTEMPTS,
                 backoff_base_s: float = REQUEUE_BACKOFF_BASE_S,
                 driver: Optional[Callable] = None,
                 reclaim: bool = True):
        self.store = JobStore(root)
        self.alloc = LaneAllocator()
        self.worker_id = worker_id
        self.lease_ttl_s = lease_ttl_s
        self.poll_s = poll_s
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        #: optional batch-unit driver `(worker, job, args) -> None` that
        #: replaces the jitted `_stream_batches` path — the chaos
        #: harness's jax-free synthetic driver plugs in here; it must
        #: drive the SAME checkpoint + stats machinery
        self.driver = driver
        #: run the lease-reclamation sweep before every lease poll, so
        #: a farm whose only live component is a worker still requeues
        #: (the `fleet serve` sweep thread covers the other deployment)
        self.reclaim = reclaim
        self._engines: dict = {}          # engine_key -> Engine
        self._engine_subkey: Optional[str] = None
        #: fencing token for the unit in flight: the lease generation
        #: captured at claim time and threaded through every store
        #: mutation this worker makes for that job, so a write from a
        #: reclaimed (zombie) hold is refused instead of applied
        self._unit_gen: Optional[int] = None
        #: contention counters, mirrored to workers/<id>.json so the
        #: control plane (`fleet top`, /healthz, /metrics) can report
        #: per-worker claim-conflict and fenced-write tallies without
        #: ever taking a job lock
        self.claim_conflicts = 0
        self.fenced_writes = 0
        self.units_done = 0

    def _note_fenced(self, exc: "FencedWrite") -> None:
        """Count and surface a refused zombie write, then move on —
        abandoning the unit IS the correct recovery (the store already
        kept the new holder's state intact)."""
        self.fenced_writes += 1
        self._write_stats()
        print(f"worker {self.worker_id}: {exc}", flush=True)

    def _write_stats(self) -> None:
        with contextlib.suppress(OSError, ValueError):
            self.store.write_worker_stats(self.worker_id, {
                "worker": self.worker_id,
                "claim_conflicts": self.claim_conflicts,
                "fenced_writes": self.fenced_writes,
                "units_done": self.units_done,
                "ts": round(time.time(), 3),
            })

    # -- main loop -----------------------------------------------------------

    def run(self, *, drain: bool = False, max_units: int = 0) -> int:
        """Serve work units until stopped. `drain=True` exits once every
        job is terminal (waiting out foreign leases); `max_units=N`
        exits after N units (deterministic interruption for tests)."""
        units = 0
        while True:
            job = self._lease_next()
            if job is None:
                if drain and all(j.terminal for j in self.store.list()):
                    print(f"worker {self.worker_id}: drained", flush=True)
                    return 0
                time.sleep(self.poll_s)
                continue
            self._run_unit(job)
            self._unit_gen = None  # token never outlives its unit
            units += 1
            self.units_done = units
            self._write_stats()
            if max_units and units >= max_units:
                print(
                    f"worker {self.worker_id}: stopping after "
                    f"{units} unit(s) (--max-units)", flush=True,
                )
                return 0

    def _lease_next(self) -> Optional[Job]:
        if self.reclaim:
            for act in self.store.reclaim_expired(
                max_attempts=self.max_attempts,
                backoff_base_s=self.backoff_base_s,
                via_index=True,
            ):
                print(
                    f"reclaimed {act['job']} from dead worker "
                    f"{act['worker']} -> {act['outcome']} "
                    f"(attempt {act['attempt']})", flush=True,
                )
        now = time.time()
        # candidate filtering runs on the log-structured queue index:
        # one incremental read of queue.log's new tail, zero per-job
        # document opens for jobs the index already rules out. The
        # index is a hint, not an authority — survivors get their real
        # document re-checked, and `try_lease` arbitrates under the
        # job's lock anyway.
        cands = []
        for jid, row in sorted(self.store.queue_rows().items()):
            if row.get("state") not in LEASABLE:
                continue
            after = row.get("requeue_after_ts")
            if after and after > now:
                continue  # requeue backoff still running
            holder = row.get("worker")
            if (holder and holder != self.worker_id
                    and (row.get("lease_expires_ts") or 0) > now):
                continue  # someone else is (still) on it
            try:
                j = self.store.get(jid)
            except (KeyError, CorruptJobFile):
                continue  # stale index row; the serve sweep heals it
            if j.state not in LEASABLE:
                continue
            if j.requeue_after_ts and j.requeue_after_ts > now:
                continue
            lease = j.lease
            if (lease and lease["worker"] != self.worker_id
                    and lease["expires_ts"] > now):
                continue
            cands.append(j)
        # coverage-feedback reallocation: one momentum read per
        # candidate (its stats feed tail + progress mirror), so the
        # allocator serves jobs still finding new slots first
        from .scheduler import momentum_for

        picked = self.alloc.pick(cands, momentum=momentum_for(self.store, cands))
        if picked is None:
            return None
        info: dict = {}
        got = self.store.try_lease(
            picked.id, self.worker_id, self.lease_ttl_s, info=info)
        if got is not None:
            self._unit_gen = (got.lease or {}).get("gen")
            return got
        if info.get("outcome") == "claim-conflict":
            # lost the O_EXCL race: count it, tell the control plane,
            # and back off with seeded jitter so N losers do not
            # re-collide on the very next poll
            self.claim_conflicts += 1
            self._write_stats()
            print(
                f"worker {self.worker_id}: lost claim race for "
                f"{picked.id} to {info.get('holder')}", flush=True,
            )
            rng = random.Random(
                f"fleet-claim {self.worker_id} {picked.id} "
                f"{self.claim_conflicts}")
            time.sleep(min(self.poll_s, 0.05) * (0.5 + rng.random()))
        return None

    # -- one work unit -------------------------------------------------------

    def _run_unit(self, job: Job) -> None:
        import atexit
        import contextlib
        import signal
        import tempfile

        from ..perf import xprof
        from ..perf.recorder import PerfRecorder, current_recorder

        job = self.store.get(job.id)  # freshest doc (cancel flag, spec)
        lease = job.lease
        if lease and lease.get("worker") == self.worker_id:
            # fence token for every mutation this unit makes: the
            # generation of our OWN live hold at unit start. A job
            # entered without a lease (tests drive `_run_unit`
            # directly) keeps gen None — the store's legacy unfenced
            # semantics.
            self._unit_gen = lease.get("gen")
        # per-unit recorder: the job id doubles as the trace id, and
        # `wall_t0` anchors the recorder's perf_counter clock on the
        # wall clock so the control plane can merge these spans with
        # its lifecycle events (`fleet timeline`). An outer
        # `--perf-timeline` recorder still sees everything: the unit's
        # spans are absorbed back into it after the unit.
        outer = current_recorder()
        unit_rec = PerfRecorder(meta={
            "trace_id": job.id, "job": job.id, "worker": self.worker_id,
        })
        offset_us = outer._now_us() if outer is not None else 0.0
        wall_t0 = time.time()
        # crash flush: a SIGTERM'd (or atexit'd) worker dumps the
        # spans it has SO FAR — open spans materialized as partial —
        # before dying, so a killed unit's `fleet timeline` shows the
        # span it died inside instead of nothing. `dumped` makes the
        # flush once-only (the normal finally path is the same dump).
        dumped = [False]

        def _flush(signum=None, frame=None):
            if not dumped[0]:
                dumped[0] = True
                with contextlib.suppress(Exception):
                    self._dump_spans(job, unit_rec, wall_t0)
            if signum is not None:
                # restore the previous disposition and re-deliver so
                # the process still dies of SIGTERM (rc 143)
                signal.signal(signum, prev_term or signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        prev_term = None
        try:  # signal() only works on the main thread; tests use threads
            prev_term = signal.signal(signal.SIGTERM, _flush)
        except ValueError:
            pass
        atexit.register(_flush)
        # device-profile capture (MADSIM_TPU_XPROF=1 units): the
        # profiler session must OUTLIVE the recorder so its multi-second
        # stop/export never lands on the measured host wall
        cap_dir = tempfile.mkdtemp(prefix="madsim-fleet-xprof-") \
            if xprof.enabled() else None
        try:
            with (xprof.device_trace(cap_dir) if cap_dir
                  else contextlib.nullcontext()):
                with unit_rec:
                    with unit_rec.span("fleet_unit", job=job.id,
                                       subkey=job.subkey, trace_id=job.id):
                        self._run_unit_inner(job)
        except SystemExit as exc:
            # the streaming driver refuses drifted checkpoints (and
            # other contract violations) via sys.exit — deterministic
            # refusals, so retrying is pointless: surfaced verbatim as
            # the job's failed reason
            try:
                self._fail(job, str(exc) or "worker aborted (SystemExit)")
            except FencedWrite as fexc:
                self._note_fenced(fexc)
        except KeyboardInterrupt:
            raise
        except FencedWrite as exc:
            # the lease was reclaimed out from under this unit and the
            # store refused the zombie's write — the job belongs to a
            # newer generation now. Abandon the unit WITHOUT touching
            # the store again: _hard_failure's record_death would stomp
            # the new holder's lease.
            self._note_fenced(exc)
        except Exception as exc:  # one broken job must not kill the farm
            try:
                self._hard_failure(job, exc)
            except FencedWrite as fexc:
                self._note_fenced(fexc)
        finally:
            atexit.unregister(_flush)
            if prev_term is not None:
                signal.signal(signal.SIGTERM, prev_term)
            if outer is not None:
                outer.absorb(unit_rec, offset_us)
            if not dumped[0]:
                dumped[0] = True
                self._dump_spans(job, unit_rec, wall_t0)
            if cap_dir is not None:
                self._save_device_trace(job, cap_dir)

    def _run_unit_inner(self, job: Job) -> None:
        if job.cancel_requested:
            self._finalize_cancel(job)
            return
        drift = self.store.fingerprint_mismatch(job)
        if drift:
            self._fail(job, drift)
            return
        if job.deadline_ts is not None and time.time() > job.deadline_ts:
            self._finalize(job, stop_reason="deadline")
            return
        ck = self._load_ckpt(job)
        if ck is not None and ck.get("done"):
            # a previous worker died between the last batch and
            # finalization — nothing left to stream, just close out
            self._finalize(job)
            return
        self._stream_one_batch(job, ck)

    def _dump_spans(self, job: Job, rec, wall_t0: float) -> None:
        """Append the unit's span dump (one JSONL record per unit) to
        the store, for `fleet timeline`'s cross-process merge. Same
        torn-tolerant append discipline as the event log; disabled by
        the same switch, and never on the result path. Instants ride
        along with ``dur: null`` (the xprof clock-sync markers the
        /profile merge aligns on), and on the crash-flush path the
        recorder's still-open spans are materialized as partial."""
        from . import events as fleet_events
        from ..runtime.atomicio import append_text

        if not fleet_events.enabled():
            return
        spans_out = []
        for s in list(rec.spans) + rec.open_spans():
            spans_out.append(
                {"name": s["name"], "ts": round(s["ts"], 1),
                 "dur": None if s["dur"] is None else round(s["dur"], 1),
                 "depth": s["depth"], "args": s["args"]})
        if not spans_out:
            return
        doc = {
            "worker": self.worker_id,
            "job": job.id,
            "trace_id": job.id,
            "wall_t0": round(wall_t0, 6),
            "spans": spans_out,
            "counters": dict(sorted(rec.counters.items())),
        }
        try:
            append_text(self.store.spans_path(job.id),
                        json.dumps(doc, sort_keys=True,
                                   separators=(",", ":")) + "\n")
        except OSError:
            pass  # observability never takes a unit down

    def _save_device_trace(self, job: Job, cap_dir: str) -> None:
        """Move the unit's device-profile capture into the store
        (last-unit-wins — the /profile merge aligns whole-unit sync
        seqs, so mixing units would desynchronize the clocks). Never
        on the result path; the capture dir is always cleaned up."""
        import shutil

        from ..perf import xprof

        try:
            src = xprof.find_device_trace(cap_dir)
            if src:
                dst = self.store.device_trace_path(job.id)
                shutil.copyfile(src, dst + ".tmp")
                os.replace(dst + ".tmp", dst)
        except OSError:
            pass  # observability never takes a unit down
        finally:
            shutil.rmtree(cap_dir, ignore_errors=True)

    def _stream_one_batch(self, job: Job, ck: Optional[dict]) -> None:
        if job.state == QUEUED:
            job = self.store.transition(job.id, COMPILING,
                                        worker=self.worker_id,
                                        gen=self._unit_gen)
        t0 = time.perf_counter()
        batches_done = int(ck["batch"]) if ck else 0
        args = spec_to_args(
            job.spec,
            checkpoint=self.store.ckpt_path(job.id),
            stats=self.store.stats_base(job.id),
            stats_labels={"job": job.id},
            stop_after_batches=batches_done + 1,
        )
        if self.driver is not None:
            self.driver(self, job, args)
            eng, engine_label = None, "synthetic"
        else:
            from ..__main__ import _stream_batches

            eng, built = self._get_engine(job)
            _stream_batches(eng, args, purpose="fleet")
            engine_label = "built" if built else "cached"
        if job.state == COMPILING:
            job = self.store.transition(job.id, RUNNING,
                                        worker=self.worker_id,
                                        gen=self._unit_gen)
        prev_failing = int(job.progress.get("failing") or 0)
        ck = self._load_ckpt(job)
        progress = self._progress_from_ckpt(eng, ck)
        progress["engine"] = engine_label
        el = time.perf_counter() - t0
        device_count = int(job.spec.get("devices") or 0) or 1
        # one locked write: merge progress, reset the consecutive-
        # failure counter (this unit completed), renew the lease
        job = self.store.note_progress(
            job.id, self.worker_id, progress,
            gen=self._unit_gen,
            event_fields={
                "elapsed_s": round(el, 3),
                "seeds_per_sec": round(job.spec["batch"] / el, 1)
                if el > 0 else None,
                "device_count": device_count,
            })
        if progress["failing"] > prev_failing:
            # find-at-find-time: the event lands on the stream NOW,
            # while the job is still mid-flight — not at completion
            self.store.emit_job_event(
                job.id, "find", worker=self.worker_id,
                failing=progress["failing"],
                new_finds=progress["failing"] - prev_failing,
                batch=progress["batches_run"])
        print(
            f"unit {job.id}: batch {progress['batches_run']}"
            f"/{progress['batches_planned']}, "
            f"{progress['completed']} seeds total in {el:.1f}s, "
            f"engine {progress['engine']}, "
            f"{progress['failing']} failing so far",
            flush=True,
        )
        if ck and ck.get("done"):
            self._finalize(job)

    # -- engines -------------------------------------------------------------

    def _get_engine(self, job: Job) -> Tuple[object, bool]:
        """One live Engine per engine_key; the cache is flushed when the
        allocator moves to a different subkey group, so at most one
        compile family stays resident on the 1-core box."""
        if job.subkey != self._engine_subkey:
            self._engines.clear()
            self._engine_subkey = job.subkey
        key = engine_key(job.spec)
        eng = self._engines.get(key)
        if eng is not None:
            return eng, False
        from ..__main__ import _build_engine

        eng = _build_engine(spec_to_args(job.spec))
        self._engines[key] = eng
        return eng, True

    # -- checkpoint plumbing -------------------------------------------------

    def _load_ckpt(self, job: Job) -> Optional[dict]:
        """The FLEET's checkpoint reader is lenient by construction: a
        torn or schema-broken checkpoint (external corruption — the
        fsync'd atomic writes never produce one) is quarantined to
        `*.corrupt` and the job restarts its stream from batch 0,
        instead of wedging the farm in a refusal loop. The CLI's
        `--checkpoint` path keeps the strict loader — there the file
        was named deliberately and silence would throw away a hunt."""
        from ..runtime.checkpoint import CKPT_REQUIRED_KEYS, load_checkpoint

        path = self.store.ckpt_path(job.id)
        try:
            ck = load_checkpoint(path)
            if ck is not None and not CKPT_REQUIRED_KEYS <= ck.keys():
                missing = sorted(CKPT_REQUIRED_KEYS - ck.keys())
                raise ValueError(f"checkpoint missing keys {missing}")
        except (ValueError, json.JSONDecodeError) as exc:
            corrupt = path + ".corrupt"
            os.replace(path, corrupt)
            _LOG.error("job %s: checkpoint unreadable (%s) — quarantined "
                       "to %s", job.id, exc, corrupt)
            print(
                f"job {job.id}: checkpoint unreadable ({exc}) — "
                f"quarantined to {corrupt}; restarting the stream from "
                f"batch 0", flush=True,
            )
            return None
        return ck

    def _progress_from_ckpt(self, eng, ck: Optional[dict]) -> dict:
        if ck is None:
            return {"batches_run": 0, "batches_planned": None,
                    "completed": 0, "seeds_consumed": 0, "failing": 0,
                    "infra": 0, "abandoned": 0, "plateau": False,
                    "coverage_slots": None, "escalation": None}
        cov_slots = None
        if eng is not None and ck.get("cov_b64"):
            from ..runtime.coverage import decode_map

            cov_slots = int(
                decode_map(ck["cov_b64"], eng.config.cov_slots_log2).sum()
            )
        guided = ck.get("guided") or {}
        return {
            "batches_run": int(ck["batch"]),
            "batches_planned": int(ck["planned"]),
            "completed": int(ck["completed"]),
            "seeds_consumed": int(ck["seeds_consumed"]),
            "failing": len(ck["failing"]),
            "infra": len(ck["infra"]),
            "abandoned": len(ck["abandoned"]),
            "plateau": bool(ck.get("plateau", False)),
            "coverage_slots": cov_slots,
            # guided search state mirror (None for unguided jobs): the
            # escalation rung feeds `fleet status`/`queue` and the
            # scheduler's momentum read
            "escalation": (guided.get("bias") or {}).get("escalation"),
        }

    # -- finalization --------------------------------------------------------

    def _finalize_cancel(self, job: Job) -> None:
        ck = self._load_ckpt(job)
        report = self._report_from_ckpt(ck, "cancelled")
        self.store.transition(
            job.id, CANCELLED, result={"report": report, "finds": []},
            worker=self.worker_id, gen=self._unit_gen,
        )
        print(f"job {job.id}: cancelled "
              f"({report['completed']} seeds run)", flush=True)

    def _report_from_ckpt(self, ck: Optional[dict], stop_reason: str) -> dict:
        """The deterministic half of a job's result: everything here is
        a pure function of (fingerprint, seed schedule) — no wall
        times — so an interrupted+resumed job's report is byte-identical
        to an uninterrupted run's (asserted in tests and CI). Coverage
        slots are filled in by the caller when an engine exists to
        decode the map (cancel can land before any engine does)."""
        if ck is None:
            return {"batches_run": 0, "batches_planned": None,
                    "completed": 0, "seeds_consumed": 0, "failing": [],
                    "infra": [], "abandoned": 0, "plateau": False,
                    "coverage_slots": None, "stop_reason": stop_reason}
        report = {
            "batches_run": int(ck["batch"]),
            "batches_planned": int(ck["planned"]),
            "completed": int(ck["completed"]),
            "seeds_consumed": int(ck["seeds_consumed"]),
            "failing": sorted([int(s), int(c)] for s, c in ck["failing"]),
            "infra": sorted([int(s), int(c)] for s, c in ck["infra"]),
            "abandoned": len(ck["abandoned"]),
            "plateau": bool(ck.get("plateau", False)),
            "coverage_slots": None,
            "stop_reason": stop_reason,
        }
        if ck.get("guided"):
            # the (seed schedule, bias state) record rides the result:
            # a guided job is replayable from its result doc alone —
            # same contract as the checkpoint, surfaced to clients
            g = ck["guided"]
            report["guided"] = {
                "bias": g.get("bias"),
                "escalation": (g.get("bias") or {}).get("escalation"),
                "trail": g.get("trail", []),
            }
        return report

    def _finalize(self, job: Job, stop_reason: Optional[str] = None) -> None:
        ck = self._load_ckpt(job)
        if stop_reason is None:
            stop_reason = (
                "plateau" if (ck and ck.get("plateau")) else "exhausted"
            )
        report = self._report_from_ckpt(ck, stop_reason)
        failing = [(int(s), int(c)) for s, c in (ck["failing"] if ck else [])]
        if self.driver is None and ck and ck.get("cov_b64"):
            from ..runtime.coverage import decode_map

            eng, _built = self._get_engine(job)
            report["coverage_slots"] = int(
                decode_map(ck["cov_b64"], eng.config.cov_slots_log2).sum()
            )
        if job.state == QUEUED:
            # deadline hit before the first unit ever ran
            job = self.store.transition(job.id, COMPILING,
                                        worker=self.worker_id,
                                        gen=self._unit_gen)
        if job.state == COMPILING:
            job = self.store.transition(job.id, RUNNING,
                                        worker=self.worker_id,
                                        gen=self._unit_gen)
        if not failing:
            final = PLATEAUED if stop_reason == "plateau" else EXHAUSTED
            self.store.transition(
                job.id, final, result={"report": report, "finds": []},
                worker=self.worker_id, gen=self._unit_gen,
            )
            print(f"job {job.id}: {final} ({report['completed']} seeds, "
                  f"0 failing, stop={stop_reason})", flush=True)
            return
        job = self.store.transition(job.id, FOUND, progress={
            "failing": len(failing),
        }, worker=self.worker_id, gen=self._unit_gen)
        self.store.emit_job_event(
            job.id, "shrink_started", worker=self.worker_id,
            failing=len(failing))
        if self.driver is not None:
            # synthetic driver (chaos harness): exercise the found ->
            # shrunk -> filed lifecycle deterministically without an
            # engine — finds carry their repro line but are not filed
            # in the corpus (no EngineConfig exists to record)
            by_code: dict = {}
            for seed, code in failing:
                by_code.setdefault(int(code), []).append(int(seed))
            finds = [
                {"seed": seeds[0], "code": code,
                 "repro": repro_cmd(job.spec),
                 "note": "synthetic driver find (not filed)"}
                for code, seeds in sorted(by_code.items())
            ]
            self.store.emit_job_event(
                job.id, "shrink_done", worker=self.worker_id,
                finds=len(finds))
            job = self.store.transition(job.id, SHRUNK,
                                        worker=self.worker_id,
                                        gen=self._unit_gen)
            filed = 0
        else:
            eng, _built = self._get_engine(job)
            self._write_vtrace(job, eng, failing)
            finds = self._shrink_finds(job, eng, ck)
            self.store.emit_job_event(
                job.id, "shrink_done", worker=self.worker_id,
                finds=len(finds))
            job = self.store.transition(job.id, SHRUNK,
                                        worker=self.worker_id,
                                        gen=self._unit_gen)
            filed = self._file_finds(job, finds)
        self.store.transition(job.id, FILED, result={
            "report": report,
            "finds": finds,
            "corpus": self.store.corpus_path,
            "corpus_added": filed,
        }, worker=self.worker_id, gen=self._unit_gen)
        print(
            f"job {job.id}: filed {filed} corpus entr"
            f"{'y' if filed == 1 else 'ies'} from {len(failing)} failing "
            f"seeds (stop={stop_reason})", flush=True,
        )

    def _write_vtrace(self, job: Job, eng, failing: List[tuple]) -> None:
        """The third clock's fleet artifact: under MADSIM_TPU_XPROF=1 a
        job with finds gets its first failing seed's VIRTUAL-time
        Perfetto doc written to the store, so `/jobs/{id}/profile` can
        merge it (unshifted — simulated µs, never wall) with the host
        and device planes. Same observability contract as the span
        dump: failure here never takes the job down."""
        from ..perf import xprof

        if not xprof.enabled() or not failing:
            return
        try:
            from ..engine.replay import replay
            from ..engine.trace_export import trace_event_dict
            from ..runtime.atomicio import atomic_write_json

            seed = int(failing[0][0])
            rp = replay(eng, seed,
                        max_steps=int(job.spec.get("max_steps") or 10_000))
            doc = trace_event_dict(rp.trace, machine=job.spec["machine"],
                                   seed=seed,
                                   num_nodes=eng.machine.NUM_NODES)
            atomic_write_json(self.store.vtrace_path(job.id), doc)
        except Exception:
            _LOG.exception("job %s: virtual-trace export failed", job.id)

    # -- shrink + why + corpus ----------------------------------------------

    def _shrink_finds(self, job: Job, eng, ck: dict) -> List[dict]:
        """One representative per distinct fail code (the hunt CLI's
        dedup discipline), shrunk with the device-harvested provenance
        word seeding the candidate order, with `why`-style attribution
        decoded from the same word."""
        shrink_mod = importlib.import_module("madsim_tpu.engine.shrink")
        from ..__main__ import fault_kinds_str

        spec = job.spec
        prov = {int(k): int(v) for k, v in (ck.get("prov") or {}).items()}
        esc_by_seed = {
            int(k): int(v)
            for k, v in ((ck.get("guided") or {})
                         .get("failing_escalation") or {}).items()
        }
        by_code: dict = {}
        for seed, code in ck["failing"]:
            by_code.setdefault(int(code), []).append(int(seed))
        reps = [(seeds[0], code) for code, seeds in sorted(by_code.items())]
        reps = reps[: spec["shrink_limit"]]
        finds: List[dict] = []
        for seed, code in reps:
            doc: dict = {"seed": seed, "code": code}
            # a guided find made under an escalated vocabulary only
            # reproduces under that vocabulary — shrink (and the filed
            # entry's config) start from the escalation step's engine
            shrink_eng = eng
            if esc_by_seed.get(seed):
                from ..search.guided import engine_for_escalation

                shrink_eng = engine_for_escalation(eng, esc_by_seed[seed])
                doc["escalation"] = esc_by_seed[seed]
            try:
                sr = shrink_mod.shrink(
                    shrink_eng, seed, max_steps=spec["max_steps"],
                    prov_word=prov.get(seed),
                )
            except ValueError as exc:
                # device-flagged but not reproducing on the host replay:
                # record the drift (itself a finding), keep the job going
                doc["error"] = str(exc)
                finds.append(doc)
                continue
            f = sr.shrunk.faults
            doc["note"] = sr.summary()
            doc["max_steps"] = sr.steps + 1
            doc["shrunk"] = sr.shrunk
            doc["repro"] = (
                f"python -m madsim_tpu replay --machine {spec['machine']} "
                f"--seed {seed} --nodes {spec['nodes']} "
                f"--horizon {sr.shrunk.horizon_us / 1e6} "
                f"--queue {sr.shrunk.queue_capacity} "
                f"--faults {f.n_faults} --fault-tmax {f.t_max_us} "
                f"--loss {sr.shrunk.packet_loss_rate} "
                f"--max-steps {sr.steps} "
                f"--fault-kinds {fault_kinds_str(f)} "
                + ("--strict-restart " if f.strict_restart else "")
                + f"--rng-stream {sr.shrunk.rng_stream}"
            )
            if seed in prov:
                from ..engine.provenance import implicated

                att = implicated(shrink_eng, seed, prov[seed])
                doc["why"] = {
                    "prov_word": prov[seed],
                    "kinds": list(att.kinds),
                    "faults": [
                        {"index": ft.index, "kind": ft.kind_name,
                         "t_apply_us": ft.t_apply_us,
                         "t_undo_us": ft.t_undo_us, "target": ft.target}
                        for ft in att.faults
                    ],
                }
            finds.append(doc)
        return finds

    def _file_finds(self, job: Job, finds: List[dict]) -> int:
        """File each shrunk find as a corpus entry in the fleet corpus,
        carrying filed-by-job provenance in its meta (which
        `audit.record_entry` preserves alongside the environment
        fingerprint). Returns how many entries were added."""
        from ..__main__ import build_machine
        from ..engine import audit, corpus

        added = 0
        with self.store._locked(".corpus"):
            entries = corpus.load(self.store.corpus_path)
            known = {e.key for e in entries}
            for doc in finds:
                sr_cfg = doc.pop("shrunk", None)
                if sr_cfg is None:
                    continue  # shrink refused (host-replay drift)
                entry = corpus.CorpusEntry(
                    machine=job.spec["machine"],
                    nodes=job.spec["nodes"],
                    seed=doc["seed"],
                    fail_code=doc["code"],
                    status=corpus.STATUS_OPEN,
                    config=sr_cfg,
                    max_steps=doc["max_steps"],
                    note=doc["note"],
                    meta={
                        "filed_by": {
                            "job": job.id,
                            "worker": self.worker_id,
                            "fingerprint_sha": job.fingerprint_sha,
                        },
                        "repro": doc["repro"],
                        **(
                            {"why_kinds": doc["why"]["kinds"]}
                            if "why" in doc else {}
                        ),
                    },
                )
                doc["corpus_key"] = list(entry.key)
                if entry.key in known:
                    doc["corpus_status"] = "duplicate"
                    continue
                entry, _trail = audit.record_entry(entry, build_machine)
                known.add(entry.key)
                entries.append(entry)
                doc["corpus_status"] = "added"
                added += 1
            if added:
                corpus.save(self.store.corpus_path, entries)
        return added

    # -- failure taxonomy ----------------------------------------------------

    def _fail(self, job: Job, reason: str) -> None:
        """Deterministic refusal (fingerprint drift, contract
        violation): retrying cannot change the outcome, so the job goes
        straight to `failed` with the reason verbatim."""
        _LOG.error("job %s failed: %s", job.id, reason)
        print(f"job {job.id}: FAILED — {reason}", flush=True)
        job = self.store.get(job.id)
        if job.state in (QUEUED, COMPILING, RUNNING, FOUND, SHRUNK):
            self.store.transition(job.id, FAILED, error=reason,
                                  worker=self.worker_id,
                                  gen=self._unit_gen)

    @staticmethod
    def _is_oom(exc: BaseException) -> bool:
        return isinstance(exc, MemoryError) or any(
            m in str(exc) for m in _OOM_MARKERS
        )

    def _hard_failure(self, job: Job, exc: BaseException) -> None:
        """A worker-reported hard failure (engine raise, OOM) — the
        retryable class, unlike `_fail`'s deterministic refusals.
        OOM-class errors first get the lane-count backoff (halve lanes,
        re-derive the warm-compile subkey, record the degradation);
        everything else burns one poison attempt: requeue with
        exponential backoff, quarantine at the cap with exception +
        batch index + repro recorded."""
        err = f"{type(exc).__name__}: {exc}"
        _LOG.error("job %s unit failed: %s", job.id, err)
        batch_index = self.store._ckpt_batch(job.id)
        if self._is_oom(exc) and job.spec["batch"] > MIN_DEGRADED_BATCH:
            out = self.store.degrade_lanes(
                job.id, error=err, worker=self.worker_id,
                gen=self._unit_gen,
            )
            # the OOMing shape's engine may be the allocation itself —
            # drop the live cache before the smaller shape compiles
            self._engines.clear()
            self._engine_subkey = None
            print(
                f"job {job.id}: OOM-class failure ({err}); degraded "
                f"lanes {out.degraded[-1]['from_batch']} -> "
                f"{out.spec['batch']} and requeued (subkey re-derived, "
                f"checkpoint reset)", flush=True,
            )
            return
        out = self.store.record_death(
            job.id,
            reason="worker hard failure",
            worker=self.worker_id,
            error=err,
            batch_index=batch_index,
            max_attempts=self.max_attempts,
            backoff_base_s=self.backoff_base_s,
            gen=self._unit_gen,
        )
        if out is None:
            return  # raced a concurrent transition; nothing to record
        if out.state == QUARANTINED:
            print(
                f"job {job.id}: QUARANTINED after {out.attempt} "
                f"consecutive attempts — {err}\n"
                f"  died at batch index {out.quarantine['batch_index']}; "
                f"repro: {out.quarantine['repro']}", flush=True,
            )
        else:
            print(
                f"job {job.id}: attempt {out.attempt}/"
                f"{self.max_attempts} failed ({err}); requeued with "
                f"backoff", flush=True,
            )
