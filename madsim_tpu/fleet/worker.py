"""The fleet worker — lease, slice, checkpoint, shrink, file.

`python -m madsim_tpu fleet worker --root DIR` turns the store's queue
into engine time. The loop:

1. **Lease.** Scan the store for leasable jobs (queued, or mid-flight
   with an expired/own lease — crash recovery), refuse any whose spec
   drifted from its recorded fingerprint (the checkpoint-refusal
   discipline, surfaced verbatim as the job's `failed` reason), and let
   the `LaneAllocator` pick the next work unit — packed by
   `cache_subkey` so tenants sharing a compile run back-to-back on the
   warm jit.
2. **Run one unit.** One unit = one seed batch, driven through the SAME
   chunked streaming driver the `hunt` CLI uses
   (`__main__._stream_batches` with `stop_after_batches = done + 1`):
   the job's fingerprinted `--checkpoint` file advances atomically
   after every batch, so a `kill -9` anywhere loses at most one batch
   and the resumed job's final report is byte-identical to an
   uninterrupted run. Per-batch stats stream to the job's own
   StatsEmitter feed (label-namespaced for the fleet /metrics).
3. **Finalize.** On budget exhaustion / coverage plateau / deadline /
   cancel, close the lifecycle: no finds -> `exhausted`/`plateaued`;
   finds -> `found` -> `shrink` one representative per distinct fail
   code (provenance-guided when the gate rode the hunt) -> `shrunk` ->
   file each as a corpus entry carrying filed-by-job metadata + its
   minimal repro line + `why` attribution -> `filed`.

Engine reuse: one live Engine per `engine_key` (model + vocabulary +
gates + lane shape), dropped when the allocator switches subkey groups
— never two engine configs in flight at once on a 1-core box. A
PerfRecorder session (`--perf-timeline`) wraps every unit in a
`fleet_unit` span with the job id, so warm-compile reuse is readable
straight off the host timeline (the second tenant's unit contains no
`compile` span at all).
"""

from __future__ import annotations

# madsim: allow-file(D001) — the worker is host-side service code: it
# reads the wall clock only for lease renewal, deadline enforcement,
# idle polling and per-unit throughput logs. Nothing feeds simulation
# state; a job's results are a pure function of (fingerprint, seed
# schedule).
import importlib
import json
import logging
import time
from typing import List, Optional, Tuple

from .allocator import LaneAllocator
from .store import (
    CANCELLED,
    COMPILING,
    EXHAUSTED,
    FAILED,
    FILED,
    FOUND,
    LEASABLE,
    PLATEAUED,
    QUEUED,
    RUNNING,
    SHRUNK,
    Job,
    JobStore,
    engine_key,
    spec_to_args,
)

_LOG = logging.getLogger("madsim_tpu.fleet.worker")


class FleetWorker:
    def __init__(self, root: str, *, worker_id: str = "w0",
                 lease_ttl_s: float = 60.0, poll_s: float = 0.5):
        self.store = JobStore(root)
        self.alloc = LaneAllocator()
        self.worker_id = worker_id
        self.lease_ttl_s = lease_ttl_s
        self.poll_s = poll_s
        self._engines: dict = {}          # engine_key -> Engine
        self._engine_subkey: Optional[str] = None

    # -- main loop -----------------------------------------------------------

    def run(self, *, drain: bool = False, max_units: int = 0) -> int:
        """Serve work units until stopped. `drain=True` exits once every
        job is terminal (waiting out foreign leases); `max_units=N`
        exits after N units (deterministic interruption for tests)."""
        units = 0
        while True:
            job = self._lease_next()
            if job is None:
                if drain and all(j.terminal for j in self.store.list()):
                    print(f"worker {self.worker_id}: drained", flush=True)
                    return 0
                time.sleep(self.poll_s)
                continue
            self._run_unit(job)
            units += 1
            if max_units and units >= max_units:
                print(
                    f"worker {self.worker_id}: stopping after "
                    f"{units} unit(s) (--max-units)", flush=True,
                )
                return 0

    def _lease_next(self) -> Optional[Job]:
        now = time.time()
        cands = []
        for j in self.store.list():
            if j.state not in LEASABLE:
                continue
            lease = j.lease
            if (lease and lease["worker"] != self.worker_id
                    and lease["expires_ts"] > now):
                continue  # someone else is (still) on it
            cands.append(j)
        picked = self.alloc.pick(cands)
        if picked is None:
            return None
        return self.store.try_lease(picked.id, self.worker_id, self.lease_ttl_s)

    # -- one work unit -------------------------------------------------------

    def _run_unit(self, job: Job) -> None:
        from ..perf.recorder import maybe_span

        job = self.store.get(job.id)  # freshest doc (cancel flag, spec)
        try:
            if job.cancel_requested:
                self._finalize_cancel(job)
                return
            drift = self.store.fingerprint_mismatch(job)
            if drift:
                self._fail(job, drift)
                return
            if job.deadline_ts is not None and time.time() > job.deadline_ts:
                self._finalize(job, stop_reason="deadline")
                return
            ck = self._load_ckpt(job)
            if ck is not None and ck.get("done"):
                # a previous worker died between the last batch and
                # finalization — nothing left to stream, just close out
                self._finalize(job)
                return
            with maybe_span("fleet_unit", job=job.id, subkey=job.subkey):
                self._stream_one_batch(job, ck)
        except SystemExit as exc:
            # the streaming driver refuses drifted checkpoints (and
            # other contract violations) via sys.exit — surfaced
            # verbatim as the job's failed reason
            self._fail(job, str(exc) or "worker aborted (SystemExit)")
        except KeyboardInterrupt:
            raise
        except Exception as exc:  # one broken job must not kill the farm
            self._fail(job, f"{type(exc).__name__}: {exc}")

    def _stream_one_batch(self, job: Job, ck: Optional[dict]) -> None:
        from ..__main__ import _stream_batches

        if job.state == QUEUED:
            job = self.store.transition(job.id, COMPILING)
        t0 = time.perf_counter()
        eng, built = self._get_engine(job)
        batches_done = int(ck["batch"]) if ck else 0
        args = spec_to_args(
            job.spec,
            checkpoint=self.store.ckpt_path(job.id),
            stats=self.store.stats_base(job.id),
            stats_labels={"job": job.id},
            stop_after_batches=batches_done + 1,
        )
        _stream_batches(eng, args, purpose="fleet")
        if job.state == COMPILING:
            job = self.store.transition(job.id, RUNNING)
        ck = self._load_ckpt(job)
        progress = self._progress_from_ckpt(eng, ck)
        progress["engine"] = "built" if built else "cached"
        job = self.store.update_progress(job.id, progress)
        self.store.renew_lease(job.id, self.worker_id)
        el = time.perf_counter() - t0
        print(
            f"unit {job.id}: batch {progress['batches_run']}"
            f"/{progress['batches_planned']}, "
            f"{progress['completed']} seeds total in {el:.1f}s, "
            f"engine {progress['engine']}, "
            f"{progress['failing']} failing so far",
            flush=True,
        )
        if ck and ck.get("done"):
            self._finalize(job)

    # -- engines -------------------------------------------------------------

    def _get_engine(self, job: Job) -> Tuple[object, bool]:
        """One live Engine per engine_key; the cache is flushed when the
        allocator moves to a different subkey group, so at most one
        compile family stays resident on the 1-core box."""
        if job.subkey != self._engine_subkey:
            self._engines.clear()
            self._engine_subkey = job.subkey
        key = engine_key(job.spec)
        eng = self._engines.get(key)
        if eng is not None:
            return eng, False
        from ..__main__ import _build_engine

        eng = _build_engine(spec_to_args(job.spec))
        self._engines[key] = eng
        return eng, True

    # -- checkpoint plumbing -------------------------------------------------

    def _load_ckpt(self, job: Job) -> Optional[dict]:
        from ..runtime.checkpoint import load_checkpoint

        return load_checkpoint(self.store.ckpt_path(job.id))

    def _progress_from_ckpt(self, eng, ck: Optional[dict]) -> dict:
        if ck is None:
            return {"batches_run": 0, "batches_planned": None,
                    "completed": 0, "seeds_consumed": 0, "failing": 0,
                    "infra": 0, "abandoned": 0, "plateau": False,
                    "coverage_slots": None}
        cov_slots = None
        if ck.get("cov_b64"):
            from ..runtime.coverage import decode_map

            cov_slots = int(
                decode_map(ck["cov_b64"], eng.config.cov_slots_log2).sum()
            )
        return {
            "batches_run": int(ck["batch"]),
            "batches_planned": int(ck["planned"]),
            "completed": int(ck["completed"]),
            "seeds_consumed": int(ck["seeds_consumed"]),
            "failing": len(ck["failing"]),
            "infra": len(ck["infra"]),
            "abandoned": len(ck["abandoned"]),
            "plateau": bool(ck.get("plateau", False)),
            "coverage_slots": cov_slots,
        }

    # -- finalization --------------------------------------------------------

    def _finalize_cancel(self, job: Job) -> None:
        ck = self._load_ckpt(job)
        report = self._report_from_ckpt(ck, "cancelled")
        self.store.transition(
            job.id, CANCELLED, result={"report": report, "finds": []}
        )
        print(f"job {job.id}: cancelled "
              f"({report['completed']} seeds run)", flush=True)

    def _report_from_ckpt(self, ck: Optional[dict], stop_reason: str) -> dict:
        """The deterministic half of a job's result: everything here is
        a pure function of (fingerprint, seed schedule) — no wall
        times — so an interrupted+resumed job's report is byte-identical
        to an uninterrupted run's (asserted in tests and CI). Coverage
        slots are filled in by the caller when an engine exists to
        decode the map (cancel can land before any engine does)."""
        if ck is None:
            return {"batches_run": 0, "batches_planned": None,
                    "completed": 0, "seeds_consumed": 0, "failing": [],
                    "infra": [], "abandoned": 0, "plateau": False,
                    "coverage_slots": None, "stop_reason": stop_reason}
        return {
            "batches_run": int(ck["batch"]),
            "batches_planned": int(ck["planned"]),
            "completed": int(ck["completed"]),
            "seeds_consumed": int(ck["seeds_consumed"]),
            "failing": sorted([int(s), int(c)] for s, c in ck["failing"]),
            "infra": sorted([int(s), int(c)] for s, c in ck["infra"]),
            "abandoned": len(ck["abandoned"]),
            "plateau": bool(ck.get("plateau", False)),
            "coverage_slots": None,
            "stop_reason": stop_reason,
        }

    def _finalize(self, job: Job, stop_reason: Optional[str] = None) -> None:
        ck = self._load_ckpt(job)
        if stop_reason is None:
            stop_reason = (
                "plateau" if (ck and ck.get("plateau")) else "exhausted"
            )
        report = self._report_from_ckpt(ck, stop_reason)
        failing = [(int(s), int(c)) for s, c in (ck["failing"] if ck else [])]
        if ck and ck.get("cov_b64"):
            from ..runtime.coverage import decode_map

            eng, _built = self._get_engine(job)
            report["coverage_slots"] = int(
                decode_map(ck["cov_b64"], eng.config.cov_slots_log2).sum()
            )
        if job.state == QUEUED:
            # deadline hit before the first unit ever ran
            job = self.store.transition(job.id, COMPILING)
        if job.state == COMPILING:
            job = self.store.transition(job.id, RUNNING)
        if not failing:
            final = PLATEAUED if stop_reason == "plateau" else EXHAUSTED
            self.store.transition(
                job.id, final, result={"report": report, "finds": []}
            )
            print(f"job {job.id}: {final} ({report['completed']} seeds, "
                  f"0 failing, stop={stop_reason})", flush=True)
            return
        job = self.store.transition(job.id, FOUND, progress={
            "failing": len(failing),
        })
        eng, _built = self._get_engine(job)
        finds = self._shrink_finds(job, eng, ck)
        job = self.store.transition(job.id, SHRUNK)
        filed = self._file_finds(job, finds)
        self.store.transition(job.id, FILED, result={
            "report": report,
            "finds": finds,
            "corpus": self.store.corpus_path,
            "corpus_added": filed,
        })
        print(
            f"job {job.id}: filed {filed} corpus entr"
            f"{'y' if filed == 1 else 'ies'} from {len(failing)} failing "
            f"seeds (stop={stop_reason})", flush=True,
        )

    # -- shrink + why + corpus ----------------------------------------------

    def _shrink_finds(self, job: Job, eng, ck: dict) -> List[dict]:
        """One representative per distinct fail code (the hunt CLI's
        dedup discipline), shrunk with the device-harvested provenance
        word seeding the candidate order, with `why`-style attribution
        decoded from the same word."""
        shrink_mod = importlib.import_module("madsim_tpu.engine.shrink")
        from ..__main__ import fault_kinds_str

        spec = job.spec
        prov = {int(k): int(v) for k, v in (ck.get("prov") or {}).items()}
        by_code: dict = {}
        for seed, code in ck["failing"]:
            by_code.setdefault(int(code), []).append(int(seed))
        reps = [(seeds[0], code) for code, seeds in sorted(by_code.items())]
        reps = reps[: spec["shrink_limit"]]
        finds: List[dict] = []
        for seed, code in reps:
            doc: dict = {"seed": seed, "code": code}
            try:
                sr = shrink_mod.shrink(
                    eng, seed, max_steps=spec["max_steps"],
                    prov_word=prov.get(seed),
                )
            except ValueError as exc:
                # device-flagged but not reproducing on the host replay:
                # record the drift (itself a finding), keep the job going
                doc["error"] = str(exc)
                finds.append(doc)
                continue
            f = sr.shrunk.faults
            doc["note"] = sr.summary()
            doc["max_steps"] = sr.steps + 1
            doc["shrunk"] = sr.shrunk
            doc["repro"] = (
                f"python -m madsim_tpu replay --machine {spec['machine']} "
                f"--seed {seed} --nodes {spec['nodes']} "
                f"--horizon {sr.shrunk.horizon_us / 1e6} "
                f"--queue {sr.shrunk.queue_capacity} "
                f"--faults {f.n_faults} --fault-tmax {f.t_max_us} "
                f"--loss {sr.shrunk.packet_loss_rate} "
                f"--max-steps {sr.steps} "
                f"--fault-kinds {fault_kinds_str(f)} "
                + ("--strict-restart " if f.strict_restart else "")
                + f"--rng-stream {sr.shrunk.rng_stream}"
            )
            if seed in prov:
                from ..engine.provenance import implicated

                att = implicated(eng, seed, prov[seed])
                doc["why"] = {
                    "prov_word": prov[seed],
                    "kinds": list(att.kinds),
                    "faults": [
                        {"index": ft.index, "kind": ft.kind_name,
                         "t_apply_us": ft.t_apply_us,
                         "t_undo_us": ft.t_undo_us, "target": ft.target}
                        for ft in att.faults
                    ],
                }
            finds.append(doc)
        return finds

    def _file_finds(self, job: Job, finds: List[dict]) -> int:
        """File each shrunk find as a corpus entry in the fleet corpus,
        carrying filed-by-job provenance in its meta (which
        `audit.record_entry` preserves alongside the environment
        fingerprint). Returns how many entries were added."""
        from ..__main__ import build_machine
        from ..engine import audit, corpus

        added = 0
        with self.store._locked(".corpus"):
            entries = corpus.load(self.store.corpus_path)
            known = {e.key for e in entries}
            for doc in finds:
                sr_cfg = doc.pop("shrunk", None)
                if sr_cfg is None:
                    continue  # shrink refused (host-replay drift)
                entry = corpus.CorpusEntry(
                    machine=job.spec["machine"],
                    nodes=job.spec["nodes"],
                    seed=doc["seed"],
                    fail_code=doc["code"],
                    status=corpus.STATUS_OPEN,
                    config=sr_cfg,
                    max_steps=doc["max_steps"],
                    note=doc["note"],
                    meta={
                        "filed_by": {
                            "job": job.id,
                            "worker": self.worker_id,
                            "fingerprint_sha": job.fingerprint_sha,
                        },
                        "repro": doc["repro"],
                        **(
                            {"why_kinds": doc["why"]["kinds"]}
                            if "why" in doc else {}
                        ),
                    },
                )
                doc["corpus_key"] = list(entry.key)
                if entry.key in known:
                    doc["corpus_status"] = "duplicate"
                    continue
                entry, _trail = audit.record_entry(entry, build_machine)
                known.add(entry.key)
                entries.append(entry)
                doc["corpus_status"] = "added"
                added += 1
            if added:
                corpus.save(self.store.corpus_path, entries)
        return added

    # -- failure -------------------------------------------------------------

    def _fail(self, job: Job, reason: str) -> None:
        _LOG.error("job %s failed: %s", job.id, reason)
        print(f"job {job.id}: FAILED — {reason}", flush=True)
        job = self.store.get(job.id)
        if job.state in (QUEUED, COMPILING, RUNNING, FOUND, SHRUNK):
            self.store.transition(job.id, FAILED, error=reason)
