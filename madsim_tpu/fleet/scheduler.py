"""The coverage-feedback fleet scheduler — spend lane-time where bugs
still hide.

PR 11's allocator orders work purely by (warm-compile subkey,
priority, deadline); every job then runs its budget flat. This module
adds the missing signal: each job's LIVE search state, read from the
artifacts the worker already writes (the per-batch StatsEmitter JSONL
feed and the job document's progress mirror) — no new wire, no jax.

`job_momentum` distills a job to one record:

  * `new_slots_recent` — coverage slots added over the last
    `RECENT_BATCHES` batch rows of its stats feed (the "is this hunt
    still finding new scenarios" derivative);
  * `plateau` — the detector has fired and (for guided jobs) the
    escalation ladder is exhausted;
  * `escalation` — the guided vocabulary rung the job is on;
  * `active` — the allocation verdict: a job still adding slots, or
    one that has not produced a feed yet (it must get lane-time to
    bootstrap), or one that does not emit coverage at all (no signal
    is not a death sentence), outranks a stalled one.

`LaneAllocator.pick(..., momentum=...)` consumes these: within the
sticky warm-compile group's equal-priority ring, active jobs are
served before stalled ones (round-robin among actives, so concurrent
productive tenants still interleave). A stalled job is only starved
while some active job wants the lanes — exactly the reallocation the
ROADMAP's scheduler item asked for. Stalled jobs regain lanes the
moment the active set drains, so every budget still completes.

Determinism: a momentum read is a pure function of the on-disk feed +
job docs at poll time; the chaos harness's byte-identical-recovery
invariants are unaffected (allocation order was never part of a job's
recorded result — each job's report is a pure function of its own
(fingerprint, seed schedule)).
"""

from __future__ import annotations

from typing import Dict, List

from .store import Job, JobStore

#: feed rows (batches) the momentum derivative looks back over
RECENT_BATCHES = 5


def job_momentum(store: JobStore, job: Job) -> dict:
    """Distill one job's live search state from its stats feed + doc."""
    feed = store.read_feed(job.id, last=RECENT_BATCHES)
    batch_rows = [
        r for r in feed
        if str(r.get("kind", "")).endswith("_batch")
    ]
    new_slots = sum(
        int((r.get("coverage") or {}).get("new_slots", 0))
        for r in batch_rows
    )
    emits_coverage = any("coverage" in r for r in batch_rows)
    plateau = bool(job.progress.get("plateau"))
    escalation = job.progress.get("escalation")
    active = (not plateau) and (
        not batch_rows          # not started: bootstrap it
        or not emits_coverage   # no signal: never punish a blind job
        or new_slots > 0        # still finding new scenarios
    )
    return {
        "new_slots_recent": new_slots,
        "batches_seen": len(batch_rows),
        "plateau": plateau,
        "escalation": escalation,
        "active": active,
    }


def momentum_for(store: JobStore, jobs: List[Job]) -> Dict[str, dict]:
    """One momentum read per candidate job (the worker calls this once
    per lease poll and hands the result to the allocator)."""
    return {job.id: job_momentum(store, job) for job in jobs}
