"""Dual-build facade — the Python equivalent of `#[cfg(madsim)]`.

The reference's backbone pattern: every public crate re-exports either
the real implementation or the sim one depending on the `madsim` cfg
flag (reference: madsim/src/lib.rs:15-23, madsim-tokio/src/lib.rs:1-8).
Python selects at import time instead:

    # app.py — identical code for test and production
    from madsim_tpu.dual import net
    ep = await net.Endpoint.bind("0.0.0.0:500")

    MADSIM_TPU_MODE=sim  (default) -> simulated fabric, needs a Runtime
    MADSIM_TPU_MODE=real           -> asyncio TCP, runs anywhere
"""

from __future__ import annotations

import os

MODE = os.environ.get("MADSIM_TPU_MODE", "sim")

if MODE == "real":
    from . import real as net  # noqa: F401  (real.Endpoint)

    IS_SIM = False
else:
    from . import net  # noqa: F401  (sim Endpoint + fabric)

    IS_SIM = True

__all__ = ["net", "MODE", "IS_SIM"]
