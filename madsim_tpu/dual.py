"""Dual-build facade — the Python equivalent of `#[cfg(madsim)]`.

The reference's backbone pattern: every public crate re-exports either
the real implementation or the sim one depending on the `madsim` cfg
flag (reference: madsim/src/lib.rs:15-23, madsim-tokio/src/lib.rs:1-8,
madsim-etcd-client/src/lib.rs:1-8). Python selects at import time:

    # app.py — identical code for test and production
    from madsim_tpu.dual import net
    ep = await net.Endpoint.bind("0.0.0.0:500")

    MADSIM_TPU_MODE=sim  (default) -> simulated fabric, needs a Runtime
    MADSIM_TPU_MODE=real           -> asyncio TCP, runs anywhere

The L5 service clients/servers (`services.etcd/kafka/s3`) are built on
this facade, so an app using them runs unmodified against a real server
in real mode (`python -m madsim_tpu serve --service etcd`) — the
analogue of the reference's L5 crates re-exporting the real client.
`task`, `time`, and `rand` expose the subset of the sim surface the
services use, bound to asyncio/stdlib in real mode.
"""

from __future__ import annotations

import os

MODE = os.environ.get("MADSIM_TPU_MODE", "sim")

if MODE == "real":
    from . import real as net  # noqa: F401  (real.Endpoint)
    from .real.compat import rand, task, time  # noqa: F401

    IS_SIM = False
else:
    from . import net  # noqa: F401  (sim Endpoint + fabric)
    from . import rand, task, time  # noqa: F401

    IS_SIM = True

def real_passthrough_enabled() -> bool:
    """Gate for the genuine-backend probes in real mode
    (etcd gRPC / kafka ApiVersions / s3 HTTP). Default on; set
    MADSIM_TPU_REAL_PASSTHROUGH=0 to always use the sim-protocol
    servers and skip the probe latency."""
    return os.environ.get("MADSIM_TPU_REAL_PASSTHROUGH", "1").lower() not in (
        "0", "false", "off",
    )


__all__ = ["net", "task", "time", "rand", "MODE", "IS_SIM", "real_passthrough_enabled"]
