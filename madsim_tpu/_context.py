"""Thread-local simulation context.

Reference parity: madsim/src/sim/runtime/context.rs — a TLS slot holding
the current runtime `Handle` plus the currently-polled task. One OS
thread hosts at most one simulation at a time; the multi-seed harness
(`runtime.builder`) runs each seed's runtime on its own thread, exactly
like the reference (madsim/src/sim/runtime/builder.rs:121-160).
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from .rand import GlobalRng
    from .task.executor import Executor, TaskEntry
    from .time import TimeHandle

_tls = threading.local()


class SimContext:
    """Everything the currently-running simulation exposes via TLS."""

    def __init__(self, executor: "Executor"):
        self.executor = executor
        self.current_task: Optional["TaskEntry"] = None


def enter(ctx: SimContext) -> None:
    if getattr(_tls, "ctx", None) is not None:
        raise RuntimeError("a simulation is already running on this thread")
    _tls.ctx = ctx


def exit() -> None:
    _tls.ctx = None


def try_current() -> Optional[SimContext]:
    return getattr(_tls, "ctx", None)


def _not_in_sim() -> RuntimeError:
    return RuntimeError(
        "this API must be called from within a madsim_tpu simulation "
        "(inside `Runtime().block_on(...)`)"
    )


def current() -> SimContext:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        raise _not_in_sim()
    return ctx


def current_rng() -> "GlobalRng":
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        raise _not_in_sim()
    return ctx.executor.rng


def current_time() -> "TimeHandle":
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        raise _not_in_sim()
    return ctx.executor.time


def try_time_ns() -> Optional[int]:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return None
    return ctx.executor.time.now_ns()


def current_task() -> "TaskEntry":
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        raise _not_in_sim()
    task = ctx.current_task
    if task is None:
        raise RuntimeError("this API must be called from within a spawned task")
    return task
