"""FoundationDB-style cooperative fault injection.

Reference: madsim/src/sim/buggify.rs + sim/rand.rs:119-135.
`buggify()` fires with probability 25% at enabled buggify points; the
framework itself calls it on chaos-relevant paths (e.g. NetSim delays).
"""

from __future__ import annotations

from . import _context

DEFAULT_PROB = 0.25


def enable() -> None:
    _context.current_rng().buggify_enabled = True


def disable() -> None:
    _context.current_rng().buggify_enabled = False


def is_enabled() -> bool:
    return _context.current_rng().buggify_enabled


def buggify() -> bool:
    """True with 25% probability when buggify is enabled."""
    return _context.current_rng().buggify_with_prob(DEFAULT_PROB)


def buggify_with_prob(p: float) -> bool:
    return _context.current_rng().buggify_with_prob(p)
