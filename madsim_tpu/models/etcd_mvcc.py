"""MVCC etcd machine — the revision/txn/lease semantics of the L5 etcd
service (`services/etcd/service.py`, reference:
madsim-etcd-client/src/service.rs:191+) lifted into a TPU-engine
`Machine`, so the 10^3-seeds/s chip can hunt bugs in the *MVCC* logic,
not just leased-KV leader election (`models/etcd.py`).

Topology: node 0 is the MVCC server (fixed-capacity key table, revision
counter, lease slots); nodes 1..N-1 are clients, each running a
seed-derived program of ops — put / delete / txn-on-a-key-pair /
lease-grant / leased-put / keepalive — with at-least-once retry and a
monotone per-client request sequence the server dedups on (exactly-once
application, like etcd's revision-fenced retries).

MVCC semantics mirrored from `services/etcd/service.py`:
  * every applied write bumps `revision` by one (txn = one bump per
    write op, the sequential-`put` semantics of service.py `txn`)
  * `create_revision` sticks from the creating put; a put after delete
    re-creates (service.py put: `old.create_revision if old else rev`)
  * plain put detaches any lease; leased put attaches the client's slot
  * lease expiry sweeps lazily on server events (the observable
    behavior of service.rs:25-35's 1 s tick — any client-visible read
    is itself a server event, so laziness is invisible); expiry deletes
    attached keys, one revision bump per key (service.py lease_revoke
    calls delete(key) per key)

Invariants (fail codes):
  * REV_SKEW       — revision != 1 + applied mutations (monotonicity +
                     exactly-one-bump-per-write accounting)
  * TXN_ATOMICITY  — the txn key pair diverged: a txn applied half its
                     write set (both branches write BOTH pair keys)
  * LEASE_EARLY    — ghost-variable check: the sweep expired a lease
                     before its true (refresh-based) expiry time
  * DUP_APPLY      — server applied more puts to a client's key than
                     the client ever issued (retry applied twice)
  * MVCC_ORDER     — a live key's create_revision/mod_revision ordering
                     or mod_revision <= revision broke

Seeded bug variants (class flags, each a real etcd-class defect):
  * NO_DEDUP          — the server applies retransmits instead of
                        re-acking them: a retried put double-applies.
                        Needs an ack to vanish while its request
                        arrived, so it hides from the legacy fault
                        vocabulary at loss_rate=0 and surfaces under
                        loss storms / directional clogs (FaultPlan v2).
  * KEEPALIVE_NO_EXTEND — keepalive refreshes the bookkeeping TTL but
                        not the expiry the sweep consults (classic
                        lease bug); caught by LEASE_EARLY's ghost
                        `real_expire` the moment the sweep fires early.
  * PREMATURE_GIVEUP  — deadline-RPC client against a token-dedup
                        server: each op is sent ONCE with a 300 ms
                        deadline; on timeout the client reports FAILURE
                        to the application and moves on (timeout
                        mishandling), and the server dedups by
                        idempotency token (per-seq bitmap — exactly-once
                        per token, so a late DISTINCT token still
                        applies). The safety breach is an abandoned op
                        applying AFTER its failure was reported — a
                        write the application compensated for becomes
                        visible. The in-flight request must OUTLIVE the
                        give-up moment: loss destroys it, clogs/kills
                        block it at the link, so the class is reachable
                        ONLY by the K_DELAY spike (late but delivered) —
                        the delay vocabulary's exclusive find.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import (
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_timer_if,
    update_node,
)
from ..utils import set2d

SERVER = 0

# message types
M_REQ = 1
M_ACK = 2

# op kinds (client programs draw uniformly)
OP_PUT = 0
OP_DEL = 1
OP_TXN = 2
OP_GRANT = 3
OP_PUT_LEASED = 4
OP_KA = 5
N_OPS = 6

# fail codes
REV_SKEW = 201
TXN_ATOMICITY = 202
LEASE_EARLY = 203
DUP_APPLY = 204
MVCC_ORDER = 205
ABANDONED_WRITE = 206  # an op the client abandoned (reported failed) applied

RETRY_US = 100_000  # client retry/op-issue tick
GIVEUP_US = 300_000  # PREMATURE_GIVEUP variant: report failure after this
TTL_MIN_US = 300_000  # granted lease TTLs
TTL_SPAN_US = 500_000

# ack statuses
ST_OK = 0
ST_ERR = 1  # lease not found etc.


@struct.dataclass
class MvccState:
    # --- server row 0 (durable: etcd's store is raft-backed) -----------
    rev: jax.Array            # int32[N] MVCC revision (init 1)
    applied: jax.Array        # int32[N] mutations applied (ghost counter)
    val: jax.Array            # int32[N, K]
    ver: jax.Array            # int32[N, K] version; 0 = absent
    mod_rev: jax.Array        # int32[N, K]
    create_rev: jax.Array     # int32[N, K]
    key_lease: jax.Array      # int32[N, K] lease slot + 1; 0 = none
    puts_applied: jax.Array   # int32[N, K] ghost: puts ever applied per key
    lease_used: jax.Array     # int32[N, L] expiry the sweep consults; -1 = invalid
    lease_real: jax.Array     # int32[N, L] ghost: true refresh-based expiry
    lease_ttl: jax.Array      # int32[N, L] granted TTL us
    last_req: jax.Array       # int32[N, L] dedup: highest applied seq per client
    early_expiry: jax.Array   # bool[N] ghost flag: sweep fired before real expiry
    # --- client rows 1.. (durable journal: restart resumes the program)
    seq: jax.Array            # int32[N] current op seq (0 = none issued)
    acked: jax.Array          # int32[N] highest acked seq
    opk: jax.Array            # int32[N] current op kind
    oparg: jax.Array          # int32[N] current op arg (ttl for grant)
    issued_at: jax.Array      # int32[N] when the in-flight op was issued
    abandoned_seq: jax.Array  # int32[N] ghost: highest seq reported FAILED
    dirty_abandoned: jax.Array  # bool[N] ghost flag (server row): an
    #                             abandoned op applied post-abandonment
    applied_bits: jax.Array   # int32[N, 4] server token-dedup bitmap
    #                           (PREMATURE_GIVEUP's exactly-once-per-
    #                           token server; 128 seqs per client)
    puts_sent: jax.Array      # int32[N, K] ghost: unique put ops issued per key
    # --- bookkeeping ---------------------------------------------------
    epoch: jax.Array          # int32[N] timer epoch (invalidates stale timers)


class EtcdMvccMachine(Machine):
    """1 MVCC server + (N-1) clients; K = (N-1) client keys + a txn pair."""

    PAYLOAD_WIDTH = 5
    MAX_MSGS = 1
    MAX_TIMERS = 1

    # seeded bug variants (see module docstring)
    NO_DEDUP = False
    KEEPALIVE_NO_EXTEND = False
    PREMATURE_GIVEUP = False

    def __init__(self, num_nodes: int = 4, target_ops: int = 6):
        self.NUM_NODES = num_nodes
        self.n_clients = num_nodes - 1
        self.K = self.n_clients + 2  # per-client keys + txn pair
        self.L = self.n_clients
        self.target_ops = target_ops

    # -- state ----------------------------------------------------------------

    def init(self, rng_key) -> MvccState:
        n, k, l = self.NUM_NODES, self.K, self.L
        zn = jnp.zeros((n,), jnp.int32)
        zk = jnp.zeros((n, k), jnp.int32)
        zl = jnp.zeros((n, l), jnp.int32)
        return MvccState(
            rev=zn + 1,
            applied=zn,
            val=zk, ver=zk, mod_rev=zk, create_rev=zk, key_lease=zk,
            puts_applied=zk,
            lease_used=zl - 1, lease_real=zl - 1, lease_ttl=zl,
            last_req=zl,
            early_expiry=jnp.zeros((n,), bool),
            seq=zn, acked=zn, opk=zn, oparg=zn,
            issued_at=zn, abandoned_seq=zn,
            dirty_abandoned=jnp.zeros((n,), bool),
            applied_bits=jnp.zeros((n, 4), jnp.int32),
            puts_sent=zk,
            epoch=zn,
        )

    def restart_if(self, nodes: MvccState, i, cond, rng_key) -> MvccState:
        # Everything is durable: the server store is raft-backed (like
        # service.rs behind the sim fabric) and clients resume their
        # journaled program position. Restart only re-fires BOOT, which
        # bumps the epoch and re-arms the retry chain.
        return nodes

    # -- timers (clients only) -------------------------------------------------

    def _tid(self, nodes: MvccState, node):
        return jnp.int32(1) + 2 * nodes.epoch[node]

    def on_timer(self, nodes: MvccState, node, timer_id, now_us, rand_u32) -> Tuple[MvccState, Outbox]:
        outbox = self.empty_outbox()
        is_boot = timer_id == 0
        t_epoch = (timer_id - 1) // 2
        live = is_boot | (t_epoch == nodes.epoch[node])
        is_client = node != SERVER

        new_epoch = jnp.where(is_boot & live, nodes.epoch[node] + 1, nodes.epoch[node])
        nodes = update_node(nodes, node, epoch=new_epoch)

        done_c = nodes.acked[node] >= self.target_ops
        act = live & is_client & ~done_c

        # PREMATURE_GIVEUP variant (timeout mishandling): after GIVEUP_US
        # without an ack the client reports the op FAILED and moves on.
        # The ghost records the abandoned seq; the server flags any
        # post-abandonment apply of it (ABANDONED_WRITE).
        give_up = (
            jnp.bool_(self.PREMATURE_GIVEUP)
            & act
            & (nodes.seq[node] > nodes.acked[node])
            & (now_us - nodes.issued_at[node] >= GIVEUP_US)
        )
        nodes = update_node(
            nodes, node,
            abandoned_seq=jnp.where(
                give_up, nodes.seq[node], nodes.abandoned_seq[node]
            ),
        )

        # issue the next op once the current one is acked (or abandoned)
        need_new = act & ((nodes.acked[node] == nodes.seq[node]) | give_up)
        new_seq = nodes.seq[node] + 1
        kind = (rand_u32[0] % jnp.uint32(N_OPS)).astype(jnp.int32)
        ttl = jnp.int32(TTL_MIN_US) + (rand_u32[1] % jnp.uint32(TTL_SPAN_US)).astype(jnp.int32)
        seq_p = jnp.where(need_new, new_seq, nodes.seq[node])
        opk_p = jnp.where(need_new, kind, nodes.opk[node])
        arg_p = jnp.where(need_new, ttl, nodes.oparg[node])
        own_key = node - 1
        is_put_kind = (opk_p == OP_PUT) | (opk_p == OP_PUT_LEASED)
        puts_sent = jnp.where(
            need_new & is_put_kind,
            set2d(nodes.puts_sent, node, own_key, nodes.puts_sent[node, own_key] + 1),
            nodes.puts_sent,
        )
        nodes = nodes.replace(puts_sent=puts_sent)
        nodes = update_node(
            nodes, node, seq=seq_p, opk=opk_p, oparg=arg_p,
            issued_at=jnp.where(need_new, now_us, nodes.issued_at[node]),
        )

        # (re)send the in-flight op; re-arm the retry chain. The
        # PREMATURE_GIVEUP variant is a deadline-RPC client: each op is
        # sent exactly once at issue (no retransmits — the deadline,
        # not the retry loop, handles "failure").
        send = act & (seq_p > nodes.acked[node])
        if self.PREMATURE_GIVEUP:
            send = send & need_new
        outbox = send_if(
            outbox, 0, send, SERVER,
            make_payload(self.PAYLOAD_WIDTH, M_REQ, seq_p, opk_p, arg_p),
        )
        jitter = (rand_u32[2] % jnp.uint32(RETRY_US // 4)).astype(jnp.int32)
        delay = jnp.where(is_boot, jitter, jnp.int32(RETRY_US) + jitter)
        outbox = set_timer_if(
            outbox, 0, live & is_client & ~done_c, delay, self._tid(nodes, node)
        )
        return nodes, outbox

    # -- server ----------------------------------------------------------------

    def _sweep(self, nodes: MvccState, now_us) -> MvccState:
        """Lazy lease-expiry sweep (server row): invalidate expired
        leases and tombstone their attached keys, one revision bump per
        deleted key. Ghost check: firing before `lease_real` is the
        LEASE_EARLY bug."""
        used = nodes.lease_used[SERVER]
        expired = (used >= 0) & (used < now_us)
        early = expired & (nodes.lease_real[SERVER] > now_us)

        lease_of_key = nodes.key_lease[SERVER]  # [K], slot+1
        safe_slot = jnp.clip(lease_of_key - 1, 0, self.L - 1)
        kill = (nodes.ver[SERVER] > 0) & (lease_of_key > 0) & expired[safe_slot]
        n_del = jnp.sum(kill.astype(jnp.int32))
        new_rev = nodes.rev[SERVER] + n_del

        srow = jnp.arange(self.NUM_NODES) == SERVER
        krow = srow[:, None] & kill[None, :]
        lrow = srow[:, None] & expired[None, :]
        return nodes.replace(
            rev=jnp.where(srow, new_rev, nodes.rev),
            applied=jnp.where(srow, nodes.applied[SERVER] + n_del, nodes.applied),
            ver=jnp.where(krow, 0, nodes.ver),
            val=jnp.where(krow, 0, nodes.val),
            key_lease=jnp.where(krow, 0, nodes.key_lease),
            mod_rev=jnp.where(krow, new_rev, nodes.mod_rev),
            lease_used=jnp.where(lrow, -1, nodes.lease_used),
            lease_real=jnp.where(lrow, -1, nodes.lease_real),
            early_expiry=nodes.early_expiry | (srow & jnp.any(early)),
        )

    def _apply(self, nodes: MvccState, c, seq, kind, arg, now_us) -> Tuple[MvccState, jax.Array]:
        """Apply one deduped client op to the server row. Returns
        (state, status)."""
        n, K = self.NUM_NODES, self.K
        srow = jnp.arange(n) == SERVER
        ks = jnp.arange(K)
        own = ks == (c - 1)
        p0 = ks == (K - 2)
        p1 = ks == (K - 1)
        slot = c - 1  # the client's lease slot
        lease_ok = nodes.lease_used[SERVER, slot] >= 0

        rev0 = nodes.rev[SERVER]
        ver = nodes.ver[SERVER]
        live = ver > 0

        # which keys does this op write, and with what?
        is_put = kind == OP_PUT
        is_del = kind == OP_DEL
        is_txn = kind == OP_TXN
        is_pl = (kind == OP_PUT_LEASED) & lease_ok
        txn_then = (nodes.ver[SERVER, K - 2] % 2) == 0
        txn_val = jnp.where(txn_then, seq, -seq)

        put_mask = own & (is_put | is_pl)
        del_mask = own & is_del & live
        txn_mask = (p0 | p1) & is_txn

        # revision bumps: put 1, effective delete 1, txn 2 (sequential
        # puts, service.py txn); per-key mod_rev gets its own bump
        bump_at = jnp.where(
            put_mask | del_mask, 1, jnp.where(txn_mask, jnp.where(p0, 1, 2), 0)
        ).astype(jnp.int32)
        # total mutations this op applies:
        n_mut = (
            jnp.sum(put_mask.astype(jnp.int32))
            + jnp.sum(del_mask.astype(jnp.int32))
            + 2 * is_txn.astype(jnp.int32)
        )
        new_rev = rev0 + n_mut
        key_rev = rev0 + bump_at  # per-key assigned revision

        write_mask = put_mask | txn_mask
        was_absent = ~live
        new_val = jnp.where(txn_mask, txn_val, seq)

        vrow = srow[:, None]
        wm = vrow & write_mask[None, :]
        dm = vrow & del_mask[None, :]
        nodes = nodes.replace(
            val=jnp.where(wm, new_val[None, :], jnp.where(dm, 0, nodes.val)),
            ver=jnp.where(wm, (ver + 1)[None, :], jnp.where(dm, 0, nodes.ver)),
            mod_rev=jnp.where(wm | dm, key_rev[None, :], nodes.mod_rev),
            create_rev=jnp.where(
                wm & was_absent[None, :], key_rev[None, :], nodes.create_rev
            ),
            key_lease=jnp.where(
                wm, jnp.where(own & is_pl, slot + 1, 0)[None, :],
                jnp.where(dm, 0, nodes.key_lease),
            ),
            puts_applied=jnp.where(wm, nodes.puts_applied + 1, nodes.puts_applied),
            rev=jnp.where(srow, new_rev, nodes.rev),
            applied=jnp.where(srow, nodes.applied[SERVER] + n_mut, nodes.applied),
        )

        # lease ops
        is_grant = kind == OP_GRANT
        is_ka = (kind == OP_KA) & lease_ok
        ls = jnp.arange(self.L) == slot
        lrow = srow[:, None] & ls[None, :]
        expire = now_us + jnp.where(is_grant, arg, nodes.lease_ttl[SERVER, slot])
        set_used = is_grant | (is_ka & ~jnp.bool_(self.KEEPALIVE_NO_EXTEND))
        set_real = is_grant | is_ka
        nodes = nodes.replace(
            lease_used=jnp.where(lrow & set_used, expire, nodes.lease_used),
            lease_real=jnp.where(lrow & set_real, expire, nodes.lease_real),
            lease_ttl=jnp.where(lrow & is_grant, arg, nodes.lease_ttl),
        )

        err = ((kind == OP_PUT_LEASED) | (kind == OP_KA)) & ~lease_ok
        return nodes, jnp.where(err, ST_ERR, ST_OK).astype(jnp.int32)

    # -- messages --------------------------------------------------------------

    def on_message(self, nodes: MvccState, node, src, payload, now_us, rand_u32) -> Tuple[MvccState, Outbox]:
        outbox = self.empty_outbox()
        mtype, seq = payload[0], payload[1]

        # ---- server: REQ -------------------------------------------------
        is_req = (node == SERVER) & (mtype == M_REQ)
        swept = self._sweep(nodes, now_us)
        slot = jnp.clip(src - 1, 0, self.L - 1)
        if self.PREMATURE_GIVEUP:
            # token-dedup server (exactly-once per idempotency token): a
            # late DISTINCT seq still applies — which is precisely what
            # lets an abandoned op land after its failure was reported.
            # Deadline-RPC clients send each token exactly once, so a
            # seq past the 128-bit window is simply never a duplicate
            # (no clip-aliasing: out-of-window tokens apply unmarked).
            in_window = seq < 128
            word = jnp.clip(seq // 32, 0, 3)
            bit = jnp.int32(1) << jnp.clip(seq % 32, 0, 31)
            is_dup = in_window & ((swept.applied_bits[src, word] & bit) != 0)
        else:
            is_dup = jnp.where(
                jnp.bool_(self.NO_DEDUP), jnp.bool_(False),
                seq <= swept.last_req[SERVER, slot],
            )
        applied, status = self._apply(swept, src, seq, payload[2], payload[3], now_us)
        applied = applied.replace(
            last_req=set2d(
                applied.last_req, SERVER, slot,
                jnp.maximum(applied.last_req[SERVER, slot], seq),
            )
        )
        if self.PREMATURE_GIVEUP:
            token_row = (
                (jnp.arange(self.NUM_NODES)[:, None] == src)
                & (jnp.arange(4)[None, :] == word)
                & in_window
            )
            applied = applied.replace(
                applied_bits=jnp.where(
                    token_row, applied.applied_bits | bit, applied.applied_bits
                )
            )
        # ghost: applying an op its client already reported as FAILED is
        # the PREMATURE_GIVEUP safety breach (a compensated-for write
        # becoming visible) — only reachable by a late-but-delivered
        # request, i.e. the delay-spike fault kind
        late_abandoned = seq <= applied.abandoned_seq[src]
        applied = applied.replace(
            dirty_abandoned=jnp.where(
                (jnp.arange(self.NUM_NODES) == SERVER) & late_abandoned,
                True,
                applied.dirty_abandoned,
            ),
        )
        # select: request => swept(+applied unless dup); else untouched
        do_apply = is_req & ~is_dup
        pick = lambda ap, sw, old: jax.tree.map(  # noqa: E731
            lambda a, s, o: jnp.where(do_apply, a, jnp.where(is_req, s, o)), ap, sw, old
        )
        nodes = pick(applied, swept.replace(last_req=applied.last_req), nodes)
        outbox = send_if(
            outbox, 0, is_req, src,
            make_payload(
                self.PAYLOAD_WIDTH, M_ACK, seq,
                jnp.where(is_dup, ST_OK, status), nodes.rev[SERVER],
            ),
        )

        # ---- client: ACK -------------------------------------------------
        is_ack = (node != SERVER) & (mtype == M_ACK)
        nodes = update_node(
            nodes, node,
            acked=jnp.where(
                is_ack, jnp.maximum(nodes.acked[node], jnp.minimum(seq, nodes.seq[node])),
                nodes.acked[node],
            ),
        )
        return nodes, outbox

    # -- invariants / results --------------------------------------------------

    def invariant(self, nodes: MvccState, now_us):
        K = self.K
        rev = nodes.rev[SERVER]
        rev_skew = rev != 1 + nodes.applied[SERVER]

        txn_div = (nodes.val[SERVER, K - 2] != nodes.val[SERVER, K - 1]) | (
            nodes.ver[SERVER, K - 2] != nodes.ver[SERVER, K - 1]
        )

        early = nodes.early_expiry[SERVER]

        # server never applied more puts to a client key than issued
        client_keys = jnp.arange(self.n_clients)
        sent = nodes.puts_sent[client_keys + 1, client_keys]
        appl = nodes.puts_applied[SERVER, client_keys]
        dup = jnp.any(appl > sent)

        live = nodes.ver[SERVER] > 0
        order = jnp.any(
            live
            & (
                (nodes.mod_rev[SERVER] > rev)
                | (nodes.create_rev[SERVER] > nodes.mod_rev[SERVER])
                | (nodes.mod_rev[SERVER] < 1)
            )
        )

        dirty = nodes.dirty_abandoned[SERVER]

        ok = ~(rev_skew | txn_div | early | dup | order | dirty)
        code = jnp.where(
            rev_skew, REV_SKEW,
            jnp.where(txn_div, TXN_ATOMICITY,
                      jnp.where(early, LEASE_EARLY,
                                jnp.where(dup, DUP_APPLY,
                                          jnp.where(order, MVCC_ORDER,
                                                    jnp.where(dirty, ABANDONED_WRITE, 0))))),
        )
        return ok, code.astype(jnp.int32)

    def is_done(self, nodes: MvccState, now_us):
        base = jnp.all(nodes.acked[1:] >= self.target_ops)
        if self.PREMATURE_GIVEUP:
            # deadline-RPC semantics: an abandoned request can still be
            # in flight (spiked up to 5 s); hold the lane open so the
            # late arrival is observed — once the event queue drains the
            # engine completes the lane anyway (done |= ~any_valid)
            return base & (now_us >= jnp.int32(7_000_000))
        return base

    def summary(self, nodes: MvccState):
        return {
            "revision": nodes.rev[SERVER],
            "applied": nodes.applied[SERVER],
            "ops_acked": jnp.sum(nodes.acked[1:]),
        }
