"""Two-phase commit machine — the transaction-atomicity engine workload.

Node 0 is the coordinator; nodes 1..N-1 are participants (resource
managers). The coordinator drives MAX_TXN transactions sequentially:
PREPARE to all, collect votes (any NO => early abort), log the decision
durably, deliver COMMIT/ABORT until every participant acks, advance.
Participants vote YES/NO (NO with probability 1/8 from the event rand
word), log their vote durably, and unilaterally record ABORT the moment
they vote NO (presumed-abort, the standard optimisation). All logs
(votes, outcomes, decision, txn counter) survive restart faults; vote
collection and ack tracking are volatile and are rebuilt by retry ticks.

Checked invariant (code 120, ATOMICITY): for every transaction, no two
participants record different outcomes. This is the safety property 2PC
exists to provide; it holds under message loss, partitions and crash/
restart of any node *because* the decision is logged before delivery and
a NO vote forces a global abort. It breaks immediately for the classic
"eager" coordinator that presumes missing votes are YES (the
`EagerCommitTwoPc` variant in tests): a NO-voting participant has
already aborted unilaterally while the others are told to commit.

Reference workload class: madsim's multi-node integration tests of
commit protocols under chaos (tonic-example/tests/test.rs crash loops);
the reference has no 2PC model — this extends the engine's model family
beyond replication (raft) to atomic commitment.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..engine.machine import (
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_timer_if,
)

COORD = 0

# message types
M_PREP, M_VOTE, M_DEC, M_ACK = 1, 2, 3, 4

# outcomes / decisions
COMMIT, ABORT = 1, 2

# votes
V_YES, V_NO = 1, 2

# timers
T_BOOT, T_TICK = 0, 1

ATOMICITY = 120

TICK_US = 30_000


@struct.dataclass
class TwoPcState:
    # durable everywhere (write-ahead logs)
    cur_txn: jax.Array  # int32[N] coordinator's txn counter (row COORD)
    decision: jax.Array  # int32[N, MAX_TXN] coordinator decision log (row COORD)
    voted: jax.Array  # int32[N, MAX_TXN] participant vote log (0/V_YES/V_NO)
    outcome: jax.Array  # int32[N, MAX_TXN] participant outcome log (0/COMMIT/ABORT)
    # volatile (rebuilt by retries after restart)
    votes_recv: jax.Array  # int32[N] bitmask of participants whose vote arrived
    votes_yes: jax.Array  # int32[N] bitmask of YES votes among those
    acks: jax.Array  # int32[N] bitmask of participants that acked the decision


class TwoPcMachine(Machine):
    PAYLOAD_WIDTH = 4
    MAX_TIMERS = 1

    def __init__(self, num_nodes: int = 4, max_txn: int = 6):
        self.NUM_NODES = num_nodes
        self.MAX_TXN = max_txn
        self.MAX_MSGS = num_nodes - 1  # one static slot per peer
        # participant bitmask: bits 1..N-1
        self._full_mask = ((1 << num_nodes) - 1) & ~1

    def init(self, rng_key) -> TwoPcState:
        n, t = self.NUM_NODES, self.MAX_TXN
        z1 = jnp.zeros((n,), jnp.int32)
        z2 = jnp.zeros((n, t), jnp.int32)
        return TwoPcState(
            cur_txn=z1, decision=z2, voted=z2, outcome=z2,
            votes_recv=z1, votes_yes=z1, acks=z1,
        )

    def init_node(self, nodes: TwoPcState, i, rng_key) -> TwoPcState:
        """Legacy restart hook: same durable-WAL semantics as restart_if
        (every shipped model keeps this shim so subclasses built on the
        older hook inherit the right durability split)."""
        return self.restart_if(nodes, i, jnp.bool_(True), rng_key)

    def durable_spec(self) -> TwoPcState:
        """Crash-with-amnesia contract: every WAL (decision/vote/outcome
        logs + the txn counter) is durable, in-flight vote/ack
        collection is volatile."""
        return TwoPcState(
            cur_txn=True, decision=True, voted=True, outcome=True,
            votes_recv=False, votes_yes=False, acks=False,
        )

    def restart_if(self, nodes: TwoPcState, i, cond, rng_key) -> TwoPcState:
        """Logs are durable; only the in-flight collection state resets."""
        mask = (jnp.arange(self.NUM_NODES) == i) & cond
        reset = lambda arr: jnp.where(mask, 0, arr)  # noqa: E731
        return nodes.replace(
            votes_recv=reset(nodes.votes_recv),
            votes_yes=reset(nodes.votes_yes),
            acks=reset(nodes.acks),
        )

    # -- decision policy (overridable; the tests break it on purpose) --------

    def _all_votes_in(self, votes_recv) -> jax.Array:
        return votes_recv == self._full_mask

    # -- helpers --------------------------------------------------------------

    def _col(self, t) -> jax.Array:
        return jnp.arange(self.MAX_TXN) == t

    def _set_cell(self, arr, node, t, value, cond) -> jax.Array:
        """arr[node, t] = value where cond, as a masked select."""
        m = ((jnp.arange(arr.shape[0]) == node)[:, None]
             & self._col(t)[None, :] & cond)
        return jnp.where(m, jnp.int32(value), arr)

    def _pay(self, *vals) -> jax.Array:
        return make_payload(self.PAYLOAD_WIDTH, *vals)

    # -- timers ---------------------------------------------------------------

    def on_timer(self, nodes: TwoPcState, node, timer_id, now_us, rand_u32
                 ) -> Tuple[TwoPcState, Outbox]:
        outbox = self.empty_outbox()
        is_coord = node == COORD

        # boot/restart: only the coordinator drives; participants are reactive
        outbox = set_timer_if(
            outbox, 0, (timer_id == T_BOOT) & is_coord, TICK_US, T_TICK)

        is_tick = (timer_id == T_TICK) & is_coord
        t = jnp.minimum(nodes.cur_txn[COORD], self.MAX_TXN - 1)
        active = nodes.cur_txn[COORD] < self.MAX_TXN
        dec = nodes.decision[COORD, t]
        phase_vote = is_tick & active & (dec == 0)
        phase_dec = is_tick & active & (dec != 0)

        prep = self._pay(M_PREP, t)
        decmsg = self._pay(M_DEC, t, dec)
        for p in range(1, self.NUM_NODES):
            bit = jnp.int32(1 << p)
            need_vote = phase_vote & ((nodes.votes_recv[COORD] & bit) == 0)
            need_ack = phase_dec & ((nodes.acks[COORD] & bit) == 0)
            outbox = send_if(outbox, p - 1, need_vote, p, prep)
            outbox = send_if(outbox, p - 1, need_ack, p, decmsg)

        outbox = set_timer_if(outbox, 0, is_tick & active, TICK_US, T_TICK)
        return nodes, outbox

    # -- messages -------------------------------------------------------------

    def on_message(self, nodes: TwoPcState, node, src, payload, now_us, rand_u32
                   ) -> Tuple[TwoPcState, Outbox]:
        outbox = self.empty_outbox()
        mtype, mt = payload[0], payload[1]

        # ---- participant side ----
        is_part = node != COORD

        # PREPARE: vote once (durable), re-reply idempotently on duplicates
        is_prep = is_part & (mtype == M_PREP)
        prior = nodes.voted[node, mt]
        roll_no = (rand_u32[0] % jnp.uint32(8)) == 0
        fresh_vote = jnp.where(roll_no, V_NO, V_YES).astype(jnp.int32)
        vote = jnp.where(prior == 0, fresh_vote, prior)
        nodes = nodes.replace(
            voted=self._set_cell(nodes.voted, node, mt, vote, is_prep),
            # unilateral abort: a NO voter knows the txn cannot commit
            outcome=self._set_cell(
                nodes.outcome, node, mt, ABORT,
                is_prep & (vote == V_NO) & (nodes.outcome[node, mt] == 0)),
        )
        outbox = send_if(outbox, 0, is_prep, COORD,
                         self._pay(M_VOTE, mt, vote))

        # DECISION: record once (first write wins), always ack
        is_dec = is_part & (mtype == M_DEC)
        nodes = nodes.replace(
            outcome=self._set_cell(
                nodes.outcome, node, mt, payload[2],
                is_dec & (nodes.outcome[node, mt] == 0)),
        )
        outbox = send_if(outbox, 0, is_dec, COORD, self._pay(M_ACK, mt))

        # ---- coordinator side ----
        is_coord = node == COORD
        cur = nodes.cur_txn[COORD]
        t = jnp.minimum(cur, self.MAX_TXN - 1)
        current = (mt == cur) & (cur < self.MAX_TXN)
        bit = (jnp.int32(1) << src).astype(jnp.int32)

        # VOTE: collect; all-in or any-NO => decide + log + deliver now
        undecided = nodes.decision[COORD, t] == 0
        is_vote = is_coord & (mtype == M_VOTE) & current & undecided
        votes_recv = jnp.where(is_vote, nodes.votes_recv[COORD] | bit,
                               nodes.votes_recv[COORD])
        yes_bit = jnp.where(payload[2] == V_YES, bit, 0)
        votes_yes = jnp.where(is_vote, nodes.votes_yes[COORD] | yes_bit,
                              nodes.votes_yes[COORD])
        any_no = (votes_recv & ~votes_yes & jnp.int32(self._full_mask)) != 0
        decide = is_vote & (self._all_votes_in(votes_recv) | any_no)
        d = jnp.where(any_no, ABORT, COMMIT).astype(jnp.int32)
        row = jnp.arange(self.NUM_NODES) == COORD
        nodes = nodes.replace(
            votes_recv=jnp.where(row & is_vote, votes_recv, nodes.votes_recv),
            votes_yes=jnp.where(row & is_vote, votes_yes, nodes.votes_yes),
            decision=self._set_cell(nodes.decision, COORD, t, d, decide),
        )

        # ACK: collect; all acked => advance to the next transaction
        decided = nodes.decision[COORD, t] != 0
        is_ack = is_coord & (mtype == M_ACK) & current & decided
        acks = jnp.where(is_ack, nodes.acks[COORD] | bit, nodes.acks[COORD])
        advance = is_ack & (acks == self._full_mask)
        nodes = nodes.replace(
            acks=jnp.where(row & is_ack & ~advance, acks, jnp.where(
                row & advance, 0, nodes.acks)),
            cur_txn=jnp.where(row & advance, cur + 1, nodes.cur_txn),
            votes_recv=jnp.where(row & advance, 0, nodes.votes_recv),
            votes_yes=jnp.where(row & advance, 0, nodes.votes_yes),
        )

        # fast path: on decide, deliver the decision without waiting a tick;
        # on advance, prepare the next txn immediately (conditions disjoint)
        dec_now = self._pay(M_DEC, t, nodes.decision[COORD, t])
        prep_next = self._pay(M_PREP, jnp.minimum(cur + 1, self.MAX_TXN - 1))
        next_active = advance & (cur + 1 < self.MAX_TXN)
        for p in range(1, self.NUM_NODES):
            pb = jnp.int32(1 << p)
            deliver = decide & ((nodes.acks[COORD] & pb) == 0)
            outbox = send_if(outbox, p - 1, deliver, p, dec_now)
            outbox = send_if(outbox, p - 1, next_active, p, prep_next)
        return nodes, outbox

    # -- invariants / results -------------------------------------------------

    def invariant(self, nodes: TwoPcState, now_us):
        part = nodes.outcome[1:, :]  # participants only
        committed = jnp.any(part == COMMIT, axis=0)
        aborted = jnp.any(part == ABORT, axis=0)
        mixed = jnp.any(committed & aborted)
        ok = ~mixed
        return ok, jnp.where(ok, 0, ATOMICITY).astype(jnp.int32)

    def is_done(self, nodes: TwoPcState, now_us):
        return nodes.cur_txn[COORD] >= self.MAX_TXN

    def summary(self, nodes: TwoPcState):
        part = nodes.outcome[1:, :]
        all_commit = jnp.all(part == COMMIT, axis=0)
        all_abort = jnp.all(part == ABORT, axis=0)
        return {
            "txns": nodes.cur_txn[COORD],
            "committed": jnp.sum(all_commit.astype(jnp.int32)),
            "aborted": jnp.sum(all_abort.astype(jnp.int32)),
        }

    def coverage_projection(self, nodes: TwoPcState, now_us):
        """Scenario projection: txn index (phase, low 3 bits) x votes
        collected for the in-flight txn x abort pressure — the 2PC
        decision-tree axes (how deep into the workload, how close to a
        decision, has any txn gone the abort way)."""
        phase = jnp.clip(nodes.cur_txn[COORD], 0, 7)
        votes_in = jnp.clip(
            lax.population_count(nodes.votes_recv[COORD]), 0, 7
        )
        part = nodes.outcome[1:, :]
        aborted_txns = jnp.clip(
            jnp.sum(jnp.any(part == ABORT, axis=0).astype(jnp.int32)), 0, 3
        )
        committed_txns = jnp.clip(
            jnp.sum(jnp.any(part == COMMIT, axis=0).astype(jnp.int32)), 0, 7
        )
        return (
            phase | (votes_in << 3) | (aborted_txns << 6) | (committed_txns << 8)
        ).astype(jnp.uint32)
