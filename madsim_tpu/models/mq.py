"""Message-queue ordering machine — the rdkafka-class engine workload.

BASELINE.json config: "madsim-rdkafka producer/consumer ordering, 100k
seeds sharded over ICI". Node 0 is a single-partition broker with an
idempotent-producer protocol (dedup by per-producer expected seq, like
Kafka's producer idempotence); nodes 1..P are producers appending with
at-least-once retries; the last node is a consumer polling fetches.

Checked invariant (code 120, DUP_OR_GAP): the consumed stream contains
every producer's sequence exactly once, in order — i.e. per-producer
gapless monotonic delivery. The broker's log and dedup cursors are
durable across restart faults (Kafka persists partitions), and acks
carry the broker's cumulative cursor, so the invariant holds under
packet loss, partitions AND kill/restart; the `NoDedupBroker` test
variant (retries append duplicates) violates it, which is the
ordering-bug class the reference's kafka tests exist to catch.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import Machine, Outbox, make_payload, send_if, set_at, set_timer_if, update_node

BROKER = 0

# messages
M_PRODUCE, M_ACK, M_FETCH, M_BATCH = 1, 2, 3, 4

# timers
T_BOOT, T_PRODUCE, T_POLL, T_RETRY = 0, 1, 2, 3

DUP_OR_GAP = 120

PRODUCE_US = 30_000
POLL_US = 25_000
RETRY_US = 100_000


@struct.dataclass
class MqState:
    # broker
    log_producer: jax.Array  # int32[N, CAP] producer id per log slot
    log_seq: jax.Array  # int32[N, CAP]
    log_len: jax.Array  # int32[N]
    expected: jax.Array  # int32[N, N] broker's dedup cursor per producer
    # producers
    next_seq: jax.Array  # int32[N] next seq to produce
    inflight: jax.Array  # bool[N] waiting for ack
    # consumer
    offset: jax.Array  # int32[N] next log offset to fetch
    seen: jax.Array  # int32[N, N] consumer's per-producer next expected seq
    bad: jax.Array  # bool[N]


class MqMachine(Machine):
    """num_nodes = 1 broker + (num_nodes-2) producers + 1 consumer."""

    PAYLOAD_WIDTH = 5
    MAX_MSGS = 1
    MAX_TIMERS = 2

    def __init__(self, num_nodes: int = 4, log_capacity: int = 24, max_seq: int = 10):
        self.NUM_NODES = num_nodes
        self.log_capacity = log_capacity
        self.max_seq = max_seq
        self.consumer = num_nodes - 1

    def init(self, rng_key) -> MqState:
        n, cap = self.NUM_NODES, self.log_capacity
        z = jnp.zeros((n,), jnp.int32)
        return MqState(
            log_producer=jnp.zeros((n, cap), jnp.int32),
            log_seq=jnp.zeros((n, cap), jnp.int32),
            log_len=z,
            expected=jnp.zeros((n, n), jnp.int32),
            next_seq=z,
            inflight=jnp.zeros((n,), bool),
            offset=z,
            seen=jnp.zeros((n, n), jnp.int32),
            bad=jnp.zeros((n,), bool),
        )

    def init_node(self, nodes: MqState, i, rng_key) -> MqState:
        """Restart: broker durable (log + dedup cursors persist, like
        Kafka's on-disk partitions); producers/consumer reset volatile
        session state."""
        return self.restart_if(nodes, i, jnp.bool_(True), rng_key)

    def restart_if(self, nodes: MqState, i, cond, rng_key) -> MqState:
        n = self.NUM_NODES
        mask = (jnp.arange(n) == i) & (i != BROKER) & cond
        return nodes.replace(
            next_seq=jnp.where(mask, 0, nodes.next_seq),
            inflight=jnp.where(mask, False, nodes.inflight),
            offset=jnp.where(mask, 0, nodes.offset),
            seen=jnp.where(mask[:, None], 0, nodes.seen),
        )

    def _is_producer(self, node):
        return (node != BROKER) & (node != self.consumer)

    # -- broker-side append with dedup ---------------------------------------

    def _accepts(self, nodes: MqState, producer, seq) -> jax.Array:
        """Idempotence predicate — the single line the NoDedup bug variant
        overrides."""
        return seq == nodes.expected[BROKER, producer]

    def _append(self, nodes: MqState, producer, seq, do: jax.Array) -> MqState:
        fresh = do & self._accepts(nodes, producer, seq) & (
            nodes.log_len[BROKER] < self.log_capacity
        )
        slot = jnp.minimum(nodes.log_len[BROKER], self.log_capacity - 1)
        row_p = jnp.where(
            fresh, set_at(nodes.log_producer[BROKER], slot, producer), nodes.log_producer[BROKER]
        )
        row_s = jnp.where(fresh, set_at(nodes.log_seq[BROKER], slot, seq), nodes.log_seq[BROKER])
        exp_row = jnp.where(
            fresh,
            set_at(nodes.expected[BROKER], producer, seq + 1),
            nodes.expected[BROKER],
        )
        return nodes.replace(
            log_producer=set_at(nodes.log_producer, BROKER, row_p),
            log_seq=set_at(nodes.log_seq, BROKER, row_s),
            log_len=jnp.where(fresh, set_at(nodes.log_len, BROKER, nodes.log_len[BROKER] + 1), nodes.log_len),
            expected=set_at(nodes.expected, BROKER, exp_row),
        )

    # -- timers ---------------------------------------------------------------

    def on_timer(self, nodes: MqState, node, timer_id, now_us, rand_u32) -> Tuple[MqState, Outbox]:
        outbox = self.empty_outbox()
        is_boot = timer_id == T_BOOT
        is_prod = self._is_producer(node)
        is_cons = node == self.consumer

        outbox = set_timer_if(outbox, 0, is_boot & is_prod, PRODUCE_US, T_PRODUCE)
        outbox = set_timer_if(outbox, 0, is_boot & is_cons, POLL_US, T_POLL)

        # producer: send next seq when idle
        tick = (timer_id == T_PRODUCE) & is_prod
        start = tick & ~nodes.inflight[node] & (nodes.next_seq[node] < self.max_seq)
        produce = make_payload(self.PAYLOAD_WIDTH, M_PRODUCE, node, nodes.next_seq[node])
        outbox = send_if(outbox, 0, start, BROKER, produce)
        nodes = update_node(nodes, node, inflight=nodes.inflight[node] | start)
        outbox = set_timer_if(outbox, 0, tick, PRODUCE_US, T_PRODUCE)
        outbox = set_timer_if(outbox, 1, start, RETRY_US, T_RETRY)

        # producer retry (at-least-once)
        retry = (timer_id == T_RETRY) & is_prod & nodes.inflight[node]
        outbox = send_if(outbox, 0, retry, BROKER, produce)
        outbox = set_timer_if(outbox, 1, retry, RETRY_US, T_RETRY)

        # consumer: poll for the next offset
        poll = (timer_id == T_POLL) & is_cons
        fetch = make_payload(self.PAYLOAD_WIDTH, M_FETCH, node, nodes.offset[node])
        outbox = send_if(outbox, 0, poll, BROKER, fetch)
        outbox = set_timer_if(outbox, 0, poll, POLL_US, T_POLL)
        return nodes, outbox

    # -- messages -------------------------------------------------------------

    def on_message(self, nodes: MqState, node, src, payload, now_us, rand_u32) -> Tuple[MqState, Outbox]:
        outbox = self.empty_outbox()
        mtype = payload[0]

        # broker: PRODUCE -> append (dedup) + ack
        is_produce = (node == BROKER) & (mtype == M_PRODUCE)
        producer, seq = payload[1], payload[2]
        nodes = self._append(nodes, producer, seq, is_produce)
        # cumulative ack: "I have everything below `expected`" — a stale or
        # duplicate PRODUCE still gets an informative ack
        ack = make_payload(self.PAYLOAD_WIDTH, M_ACK, nodes.expected[BROKER, producer])
        outbox = send_if(outbox, 0, is_produce, producer, ack)

        # broker: FETCH -> return entry at offset (if any)
        is_fetch = (node == BROKER) & (mtype == M_FETCH)
        consumer, offset = payload[1], payload[2]
        have = offset < nodes.log_len[BROKER]
        slot = jnp.minimum(offset, self.log_capacity - 1)
        batch = make_payload(
            self.PAYLOAD_WIDTH, M_BATCH, offset,
            nodes.log_producer[BROKER, slot], nodes.log_seq[BROKER, slot],
        )
        outbox = send_if(outbox, 0, is_fetch & have, consumer, batch)

        # producer: cumulative ack advances next_seq; an ack that does not
        # cover the outstanding record keeps it inflight (retry continues),
        # so a full log degrades to retries, never to silent loss
        is_ack = self._is_producer(node) & (mtype == M_ACK)
        covers = payload[1] > nodes.next_seq[node]
        acked = is_ack & covers & nodes.inflight[node]
        nodes = update_node(
            nodes, node,
            inflight=nodes.inflight[node] & ~acked,
            next_seq=jnp.where(acked, payload[1], nodes.next_seq[node]),
        )

        # consumer: BATCH at the expected offset advances; check per-producer order
        is_batch = (node == self.consumer) & (mtype == M_BATCH)
        b_off, b_prod, b_seq = payload[1], payload[2], payload[3]
        take = is_batch & (b_off == nodes.offset[node])
        in_order = b_seq == nodes.seen[node, b_prod]
        nodes = update_node(
            nodes, node,
            offset=jnp.where(take, nodes.offset[node] + 1, nodes.offset[node]),
            bad=nodes.bad[node] | (take & ~in_order),
            seen=jnp.where(
                take & in_order,
                set_at(nodes.seen[node], b_prod, b_seq + 1),
                nodes.seen[node],
            ),
        )
        return nodes, outbox

    # -- invariants / results ---------------------------------------------------

    def invariant(self, nodes: MqState, now_us):
        ok = ~jnp.any(nodes.bad)
        return ok, jnp.where(ok, 0, DUP_OR_GAP).astype(jnp.int32)

    def is_done(self, nodes: MqState, now_us):
        total = (self.NUM_NODES - 2) * self.max_seq
        return nodes.offset[self.consumer] >= jnp.int32(min(total, self.log_capacity))

    def summary(self, nodes: MqState):
        return {
            "log_len": nodes.log_len[BROKER],
            "consumed": nodes.offset[self.consumer],
            "produced": jnp.sum(nodes.next_seq) - nodes.next_seq[BROKER] - nodes.next_seq[self.consumer],
        }
