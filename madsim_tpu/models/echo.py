"""Echo / hello-RPC machine: client pings, server echoes, K rounds.

The TPU-engine twin of the tonic-example hello workload
(reference: tonic-example/src/lib.rs:13-120 unary path): node 0 is the
client, node 1 the server. Client sends PING(n) on boot and after each
reply; done when K replies received. Invariant: replies arrive in order.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import BOOT, Machine, Outbox, make_payload, send_if, set_timer_if, update_node

PING = 1
PONG = 2

CLIENT = 0
SERVER = 1

# fail codes
BAD_ORDER = 100


@struct.dataclass
class EchoState:
    sent: jax.Array  # int32[N] pings sent (client)
    acked: jax.Array  # int32[N] replies received in order (client)
    served: jax.Array  # int32[N] pings served (server)
    bad: jax.Array  # bool[N] ordering violation observed


class EchoMachine(Machine):
    NUM_NODES = 2
    PAYLOAD_WIDTH = 4
    MAX_MSGS = 1
    MAX_TIMERS = 1

    def __init__(self, rounds: int = 10, retry_us: int = 100_000):
        self.rounds = rounds
        self.retry_us = retry_us

    def init(self, rng_key) -> EchoState:
        z = jnp.zeros((self.NUM_NODES,), jnp.int32)
        return EchoState(sent=z, acked=z, served=z, bad=jnp.zeros((self.NUM_NODES,), bool))

    def on_timer(self, nodes: EchoState, node, timer_id, now_us, rand_u32) -> Tuple[EchoState, Outbox]:
        outbox = self.empty_outbox()
        is_client = node == CLIENT
        # BOOT or retry timer: (re)send the current ping.
        seq = nodes.acked[CLIENT]
        payload = make_payload(self.PAYLOAD_WIDTH, PING, seq)
        want = is_client & (seq < self.rounds)
        outbox = send_if(outbox, 0, want, SERVER, payload)
        outbox = set_timer_if(outbox, 0, want, self.retry_us, 1)  # retry on loss
        nodes = update_node(nodes, CLIENT, sent=jnp.where(want, nodes.sent[CLIENT] + 1, nodes.sent[CLIENT]))
        return nodes, outbox

    def on_message(self, nodes: EchoState, node, src, payload, now_us, rand_u32) -> Tuple[EchoState, Outbox]:
        outbox = self.empty_outbox()
        mtype, seq = payload[0], payload[1]

        # Server: echo back.
        is_ping = (node == SERVER) & (mtype == PING)
        pong = make_payload(self.PAYLOAD_WIDTH, PONG, seq)
        outbox = send_if(outbox, 0, is_ping, CLIENT, pong)
        nodes = update_node(
            nodes, SERVER, served=jnp.where(is_ping, nodes.served[SERVER] + 1, nodes.served[SERVER])
        )

        # Client: accept in-order reply (retries make duplicates possible;
        # ahead-of-order is a protocol violation).
        is_pong = (node == CLIENT) & (mtype == PONG)
        in_order = seq == nodes.acked[CLIENT]
        ahead = seq > nodes.acked[CLIENT]
        nodes = update_node(
            nodes,
            CLIENT,
            acked=jnp.where(is_pong & in_order, nodes.acked[CLIENT] + 1, nodes.acked[CLIENT]),
            bad=nodes.bad[CLIENT] | (is_pong & ahead),
        )
        return nodes, outbox

    def invariant(self, nodes: EchoState, now_us):
        ok = ~jnp.any(nodes.bad)
        return ok, jnp.where(ok, 0, BAD_ORDER).astype(jnp.int32)

    def is_done(self, nodes: EchoState, now_us):
        return nodes.acked[CLIENT] >= self.rounds

    def summary(self, nodes: EchoState):
        return {"acked": nodes.acked[CLIENT], "served": nodes.served[SERVER]}
