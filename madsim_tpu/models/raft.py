"""Raft leader election + log replication as a TPU-engine Machine.

The MadRaft-class flagship workload (BASELINE.json: "MadRaft 3-node
leader election" / "5-node log replication + partition injection").
Single-entry AppendEntries, randomized election timeouts, heartbeats,
client appends modeled as a leader-side timer. Safe under partition AND
kill/restart chaos: term/votedFor/log survive restarts (stable storage),
volatile state resets — so `FaultPlan(allow_kill=True)` exercises true
crash-recovery.

On-device invariants (checked after every event):
  * ElectionSafety (code 101): at most one leader per term
  * LogMatching on committed prefixes (code 102)
  * CommitMonotonicity is implied by construction (commit only grows)

Timer ids are epoch-encoded (`tid = base + 4*epoch[node]`): a restart
bumps the node's epoch at BOOT so timer chains from a previous
incarnation die instead of double-arming — the fixed-shape analogue of
the reference dropping a killed node's timers with its futures
(madsim/src/sim/task/mod.rs:133-140).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..engine.machine import Machine, Outbox, make_payload, send_if, set_at, set_timer_if, update_node
from ..utils import set2d

# roles
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

# message types (payload[0])
M_RV, M_VOTE, M_AE, M_AER = 1, 2, 3, 4

# timer bases (payload[0] = base + 4*epoch; base 0 = engine BOOT)
T_BOOT, T_ELECTION, T_HEARTBEAT, T_CLIENT = 0, 1, 2, 3

# invariant failure codes
ELECTION_SAFETY = 101
LOG_MATCHING = 102

ELECTION_MIN_US = 150_000
ELECTION_MAX_US = 300_000
HEARTBEAT_US = 50_000
CLIENT_APPEND_US = 30_000


@struct.dataclass
class RaftState:
    # persistent (survives restart — stable storage)
    term: jax.Array  # int32[N]
    voted_for: jax.Array  # int32[N], -1 = none
    log_term: jax.Array  # int32[N, CAP+1]; slot 0 is the 0-sentinel
    log_len: jax.Array  # int32[N]
    epoch: jax.Array  # int32[N] timer epoch (persistent, bumped at BOOT)
    # volatile
    role: jax.Array  # int32[N]
    votes: jax.Array  # int32[N]
    elec_deadline: jax.Array  # int32[N] us
    commit: jax.Array  # int32[N]
    next_idx: jax.Array  # int32[N, N]
    match_idx: jax.Array  # int32[N, N]


class RaftMachine(Machine):
    PAYLOAD_WIDTH = 6
    MAX_TIMERS = 2

    # Follower commit bound on AppendEntries. False (correct, Raft §5.3
    # "index of last new entry"): commit caps at prev_idx(+1 with an
    # entry). True reproduces the classic overcommit bug — capping at
    # the follower's whole log length lets a stale divergent tail that
    # extends past the match point be committed. The engine found this
    # at seed 66531 of an 88k-seed sweep (LOG_MATCHING violated: one
    # node committed term-1 entries 6-8 where the cluster committed
    # term-2 ones); kept as a flag so the bug class stays testable.
    COMMIT_TO_LOG_LEN = False

    # Leader commit quorum. False (correct): an entry commits when
    # replicated on a strict majority. True reproduces a
    # quorum-off-by-one bug (commit at majority-1 acks, i.e. leader +
    # one follower on a 5-node cluster). Triggering a *safety* violation
    # needs the leader plus its one follower sustained-isolated from a
    # majority that elects and commits divergently — a 2/3 group split
    # clogs 6 links at once, unreachable for the legacy two-pair-clog
    # fault vocabulary; FaultPlan(allow_group=True) finds it (the
    # round-3 new-fault-kinds demo, see tests/test_engine.py).
    QUORUM_OFF_BY_ONE = False

    # Durable-state contract bug (the crash-with-amnesia demo). False
    # (correct, Raft §5.1): term/votedFor/log live in stable storage,
    # commitIndex is volatile. True flips the log and the commit index:
    # the node persists its commitIndex but NOT the log backing it —
    # the classic "fsync the metadata, forget the data" storage bug. A
    # plain kill/restart can't see it (the model's restart_if still
    # hand-resets the right fields); FaultPlan(strict_restart=True)
    # makes the CONTRACT the restart semantics, so the first restart
    # after any commit leaves commit pointing at a wiped log — caught
    # by the existing LogMatching checker (code 102), no new invariant
    # needed.
    PERSIST_COMMIT_NOT_LOG = False

    # Vote tally semantics. False (correct, Raft §5.2: a candidate wins
    # when a majority of SERVERS grant — distinct voters): `votes` holds
    # a bitmask of granting node ids (self-vote included) and the win
    # check popcounts it, so a re-delivered grant is idempotent. True
    # reproduces the duplicate-vote tally bug this model silently had
    # until PR-5's message-duplication chaos (FaultPlan.allow_dup) found
    # it: `votes` is a plain per-message counter, an at-least-once
    # network delivers one grant twice, and two leaders share a term
    # (ELECTION_SAFETY, code 101). Identical behavior on exactly-once
    # networks either way — every recorded no-dup seed replays unchanged.
    DUP_VOTE_COUNT = False

    def __init__(self, num_nodes: int = 5, log_capacity: int = 8):
        if num_nodes > 31:
            raise ValueError(
                "RaftMachine tracks granting voters as an int32 bitmask "
                "(dup-safe tally, Raft §5.2); num_nodes must be <= 31"
            )
        self.NUM_NODES = num_nodes
        self.MAX_MSGS = num_nodes - 1
        self.log_capacity = log_capacity
        self.majority = num_nodes // 2 + 1

    # -- state ---------------------------------------------------------------

    def init(self, rng_key) -> RaftState:
        n, cap = self.NUM_NODES, self.log_capacity
        z = jnp.zeros((n,), jnp.int32)
        return RaftState(
            term=z,
            voted_for=jnp.full((n,), -1, jnp.int32),
            log_term=jnp.zeros((n, cap + 1), jnp.int32),
            log_len=z,
            epoch=z,
            role=z,
            votes=z,
            elec_deadline=z,
            commit=z,
            next_idx=jnp.ones((n, n), jnp.int32),
            match_idx=jnp.zeros((n, n), jnp.int32),
        )

    def init_node(self, nodes: RaftState, i, rng_key) -> RaftState:
        """Restart: persistent state survives, volatile resets
        (Raft §5.1 stable storage semantics)."""
        return self.restart_if(nodes, i, jnp.bool_(True), rng_key)

    def durable_spec(self) -> RaftState:
        """Crash-with-amnesia contract (`FaultPlan.strict_restart`):
        term/votedFor/log are stable storage, the timer epoch is
        bookkeeping that must survive (it dies with the node's timers
        otherwise), everything else is volatile. The generic wipe under
        this spec is leaf-for-leaf identical to `restart_if` — strict
        ON/OFF is bit-identical for the honest machine (tests assert)."""
        log_durable = not self.PERSIST_COMMIT_NOT_LOG
        return RaftState(
            term=True,
            voted_for=True,
            log_term=log_durable,
            log_len=log_durable,
            epoch=True,
            role=False,
            votes=False,
            elec_deadline=False,
            commit=bool(self.PERSIST_COMMIT_NOT_LOG),
            next_idx=False,
            match_idx=False,
        )

    def restart_if(self, nodes: RaftState, i, cond, rng_key) -> RaftState:
        """Masked restart: cond folds into the row mask, so the engine's
        per-step fault branch costs row writes, not a full-tree select."""
        n = self.NUM_NODES
        row = (jnp.arange(n) == i) & cond
        set_row = lambda arr, v: jnp.where(row, v, arr)  # noqa: E731
        return nodes.replace(
            role=set_row(nodes.role, FOLLOWER),
            votes=set_row(nodes.votes, 0),
            elec_deadline=set_row(nodes.elec_deadline, 0),
            commit=set_row(nodes.commit, 0),
            next_idx=jnp.where(row[:, None], 1, nodes.next_idx),
            match_idx=jnp.where(row[:, None], 0, nodes.match_idx),
        )

    # -- helpers -------------------------------------------------------------

    def _peers(self, node):
        """The NUM_NODES-1 other node ids, as a static-shape vector."""
        n = self.NUM_NODES
        offs = jnp.arange(1, n, dtype=jnp.int32)
        return (node + offs) % n

    def _rand_timeout(self, rand_word):
        span = jnp.uint32(ELECTION_MAX_US - ELECTION_MIN_US)
        return jnp.int32(ELECTION_MIN_US) + (rand_word % span).astype(jnp.int32)

    def _pay(self, *vals):
        return make_payload(self.PAYLOAD_WIDTH, *vals)

    def _tid(self, nodes, node, base):
        return jnp.int32(base) + 4 * nodes.epoch[node]

    # vote-tally representation (see DUP_VOTE_COUNT): bitmask of voter
    # ids by default, plain counter for the seeded buggy variant

    def _vote_init(self, node):
        if self.DUP_VOTE_COUNT:
            return jnp.int32(1)
        return jnp.int32(1) << node

    def _vote_add(self, votes, src, counts):
        if self.DUP_VOTE_COUNT:
            return votes + jnp.where(counts, 1, 0)
        return jnp.where(counts, votes | (jnp.int32(1) << src), votes)

    def _vote_count(self, votes):
        if self.DUP_VOTE_COUNT:
            return votes
        return lax.population_count(votes.astype(jnp.uint32)).astype(jnp.int32)

    # -- timers --------------------------------------------------------------

    def on_timer(self, nodes: RaftState, node, timer_id, now_us, rand_u32) -> Tuple[RaftState, Outbox]:
        outbox = self.empty_outbox()
        base = timer_id % 4
        t_epoch = timer_id // 4
        # BOOT (engine-raw id 0) always valid; others require current epoch.
        is_boot = timer_id == T_BOOT
        live = is_boot | (t_epoch == nodes.epoch[node])

        # ---- BOOT: bump epoch, arm election + client timers ----
        new_epoch = jnp.where(is_boot & live, nodes.epoch[node] + 1, nodes.epoch[node])
        nodes = update_node(nodes, node, epoch=new_epoch)
        timeout = self._rand_timeout(rand_u32[0])
        boot_deadline = now_us + timeout
        nodes = update_node(
            nodes, node,
            elec_deadline=jnp.where(is_boot & live, boot_deadline, nodes.elec_deadline[node]),
        )
        outbox = set_timer_if(outbox, 0, is_boot & live, timeout, self._tid(nodes, node, T_ELECTION))
        outbox = set_timer_if(outbox, 1, is_boot & live, CLIENT_APPEND_US, self._tid(nodes, node, T_CLIENT))

        # ---- ELECTION ----
        is_elec = live & (base == T_ELECTION) & ~is_boot
        not_yet = now_us < nodes.elec_deadline[node]
        # re-arm at the postponed deadline (heartbeats push it forward)
        rearm_delay = jnp.maximum(nodes.elec_deadline[node] - now_us, 1)
        outbox = set_timer_if(outbox, 0, is_elec & not_yet, rearm_delay, self._tid(nodes, node, T_ELECTION))

        start = is_elec & ~not_yet & (nodes.role[node] != LEADER)
        new_term = nodes.term[node] + 1
        timeout2 = self._rand_timeout(rand_u32[1])
        nodes = update_node(
            nodes, node,
            term=jnp.where(start, new_term, nodes.term[node]),
            role=jnp.where(start, CANDIDATE, nodes.role[node]),
            voted_for=jnp.where(start, node, nodes.voted_for[node]),
            votes=jnp.where(start, self._vote_init(node), nodes.votes[node]),
            elec_deadline=jnp.where(start, now_us + timeout2, nodes.elec_deadline[node]),
        )
        outbox = set_timer_if(
            outbox, 0, is_elec & ~not_yet, timeout2, self._tid(nodes, node, T_ELECTION)
        )
        last_idx = nodes.log_len[node]
        last_term = nodes.log_term[node, last_idx]
        rv = self._pay(M_RV, nodes.term[node], node, last_idx, last_term)
        peers = self._peers(node)
        for s in range(self.MAX_MSGS):
            outbox = send_if(outbox, s, start, peers[s], rv)

        # ---- HEARTBEAT (leader replicates) ----
        is_hb = live & (base == T_HEARTBEAT) & ~is_boot
        is_leader = nodes.role[node] == LEADER
        do_hb = is_hb & is_leader
        outbox = set_timer_if(outbox, 1, do_hb, HEARTBEAT_US, self._tid(nodes, node, T_HEARTBEAT))
        for s in range(self.MAX_MSGS):
            peer = peers[s]
            ni = nodes.next_idx[node, peer]
            prev_idx = ni - 1
            prev_term = nodes.log_term[node, prev_idx]
            has_entry = ni <= nodes.log_len[node]
            entry_term = jnp.where(has_entry, nodes.log_term[node, jnp.minimum(ni, self.log_capacity)], 0)
            ae = self._pay(M_AE, nodes.term[node], prev_idx, prev_term, entry_term, nodes.commit[node])
            outbox = send_if(outbox, s, do_hb, peer, ae)

        # ---- CLIENT (leader appends an entry) ----
        is_client = live & (base == T_CLIENT) & ~is_boot
        outbox = set_timer_if(outbox, 1, is_client & ~do_hb, CLIENT_APPEND_US, self._tid(nodes, node, T_CLIENT))
        can_append = is_client & is_leader & (nodes.log_len[node] < self.log_capacity)
        new_len = nodes.log_len[node] + 1
        nodes = update_node(
            nodes, node,
            log_len=jnp.where(can_append, new_len, nodes.log_len[node]),
            log_term=jnp.where(
                can_append,
                set_at(
                    nodes.log_term[node],
                    jnp.minimum(new_len, self.log_capacity),
                    nodes.term[node],
                ),
                nodes.log_term[node],
            ),
        )
        nodes = nodes.replace(
            match_idx=jnp.where(
                can_append,
                set2d(nodes.match_idx, node, node, new_len),
                nodes.match_idx,
            )
        )
        return nodes, outbox

    # -- messages ------------------------------------------------------------

    def on_message(self, nodes: RaftState, node, src, payload, now_us, rand_u32) -> Tuple[RaftState, Outbox]:
        mtype = payload[0]
        branch = jnp.clip(mtype - 1, 0, 3)

        def rv_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, cand, last_idx, last_term = payload[1], payload[2], payload[3], payload[4]
            # step down on newer term
            newer = t > nodes.term[node]
            nodes = update_node(
                nodes, node,
                term=jnp.where(newer, t, nodes.term[node]),
                role=jnp.where(newer, FOLLOWER, nodes.role[node]),
                voted_for=jnp.where(newer, -1, nodes.voted_for[node]),
            )
            my_last = nodes.log_len[node]
            my_last_term = nodes.log_term[node, my_last]
            log_ok = (last_term > my_last_term) | ((last_term == my_last_term) & (last_idx >= my_last))
            can_vote = (nodes.voted_for[node] == -1) | (nodes.voted_for[node] == cand)
            grant = (t == nodes.term[node]) & can_vote & log_ok
            nodes = update_node(
                nodes, node,
                voted_for=jnp.where(grant, cand, nodes.voted_for[node]),
                elec_deadline=jnp.where(
                    grant, now_us + self._rand_timeout(rand_u32[0]), nodes.elec_deadline[node]
                ),
            )
            vote = self._pay(M_VOTE, nodes.term[node], grant.astype(jnp.int32))
            outbox = send_if(outbox, 0, jnp.bool_(True), src, vote)
            return nodes, outbox

        def vote_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, granted = payload[1], payload[2]
            newer = t > nodes.term[node]
            nodes = update_node(
                nodes, node,
                term=jnp.where(newer, t, nodes.term[node]),
                role=jnp.where(newer, FOLLOWER, nodes.role[node]),
                voted_for=jnp.where(newer, -1, nodes.voted_for[node]),
            )
            counts = (t == nodes.term[node]) & (nodes.role[node] == CANDIDATE) & (granted == 1)
            new_votes = self._vote_add(nodes.votes[node], src, counts)
            win = (
                counts
                & (self._vote_count(new_votes) >= self.majority)
                & (nodes.role[node] == CANDIDATE)
            )
            n = self.NUM_NODES
            nodes = update_node(nodes, node, votes=new_votes, role=jnp.where(win, LEADER, nodes.role[node]))
            # leader volatile state
            nodes = nodes.replace(
                next_idx=jnp.where(
                    win,
                    set_at(nodes.next_idx, node, jnp.full((n,), nodes.log_len[node] + 1, jnp.int32)),
                    nodes.next_idx,
                ),
                match_idx=jnp.where(
                    win,
                    set_at(
                        nodes.match_idx, node,
                        set_at(jnp.zeros((n,), jnp.int32), node, nodes.log_len[node]),
                    ),
                    nodes.match_idx,
                ),
            )
            # announce leadership immediately with heartbeats + arm timer
            peers = self._peers(node)
            prev_idx = nodes.log_len[node]
            prev_term = nodes.log_term[node, prev_idx]
            ae = self._pay(M_AE, nodes.term[node], prev_idx, prev_term, 0, nodes.commit[node])
            for s in range(self.MAX_MSGS):
                outbox = send_if(outbox, s, win, peers[s], ae)
            outbox = set_timer_if(outbox, 0, win, HEARTBEAT_US, self._tid(nodes, node, T_HEARTBEAT))
            return nodes, outbox

        def ae_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, prev_idx, prev_term, entry_term, leader_commit = (
                payload[1], payload[2], payload[3], payload[4], payload[5],
            )
            stale = t < nodes.term[node]
            newer = t > nodes.term[node]
            nodes = update_node(
                nodes, node,
                term=jnp.where(newer, t, nodes.term[node]),
                role=jnp.where(~stale, FOLLOWER, nodes.role[node]),
                voted_for=jnp.where(newer, -1, nodes.voted_for[node]),
                elec_deadline=jnp.where(
                    ~stale, now_us + self._rand_timeout(rand_u32[0]), nodes.elec_deadline[node]
                ),
            )
            log_ok = (prev_idx <= nodes.log_len[node]) & (nodes.log_term[node, prev_idx] == prev_term)
            ok = ~stale & log_ok
            has_entry = entry_term > 0
            slot = jnp.minimum(prev_idx + 1, self.log_capacity)
            existing_matches = (nodes.log_len[node] >= prev_idx + 1) & (
                nodes.log_term[node, slot] == entry_term
            )
            append = ok & has_entry
            new_len = jnp.where(
                append,
                jnp.where(existing_matches, jnp.maximum(nodes.log_len[node], prev_idx + 1), prev_idx + 1),
                nodes.log_len[node],
            )
            # Raft §5.3: commit caps at the index of the last entry THIS
            # AE verified (prev_idx, +1 if it carried an entry) — not at
            # the follower's log length, whose tail past the match point
            # may be stale (see COMMIT_TO_LOG_LEN above).
            last_new = prev_idx + jnp.where(has_entry, 1, 0)
            commit_cap = jnp.where(
                jnp.bool_(self.COMMIT_TO_LOG_LEN), new_len, jnp.minimum(last_new, new_len)
            )
            nodes = update_node(
                nodes, node,
                log_term=jnp.where(
                    append, set_at(nodes.log_term[node], slot, entry_term), nodes.log_term[node]
                ),
                log_len=new_len,
                commit=jnp.where(
                    ok,
                    jnp.maximum(nodes.commit[node], jnp.minimum(leader_commit, commit_cap)),
                    nodes.commit[node],
                ),
            )
            match = jnp.where(has_entry, prev_idx + 1, prev_idx)
            aer = self._pay(M_AER, nodes.term[node], ok.astype(jnp.int32), match)
            outbox = send_if(outbox, 0, jnp.bool_(True), src, aer)
            return nodes, outbox

        def aer_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, success, midx = payload[1], payload[2], payload[3]
            newer = t > nodes.term[node]
            nodes = update_node(
                nodes, node,
                term=jnp.where(newer, t, nodes.term[node]),
                role=jnp.where(newer, FOLLOWER, nodes.role[node]),
                voted_for=jnp.where(newer, -1, nodes.voted_for[node]),
            )
            is_lead = (nodes.role[node] == LEADER) & (t == nodes.term[node])
            good = is_lead & (success == 1)
            new_match = jnp.maximum(nodes.match_idx[node, src], midx)
            nodes = nodes.replace(
                match_idx=jnp.where(
                    good, set2d(nodes.match_idx, node, src, new_match), nodes.match_idx
                ),
                next_idx=jnp.where(
                    good,
                    set2d(nodes.next_idx, node, src, new_match + 1),
                    jnp.where(
                        is_lead & (success == 0),
                        set2d(
                            nodes.next_idx, node, src,
                            jnp.maximum(nodes.next_idx[node, src] - 1, 1),
                        ),
                        nodes.next_idx,
                    ),
                ),
            )
            # advance commit: highest idx replicated on a majority with
            # an entry from the current term (Raft §5.4.2)
            idxs = jnp.arange(self.log_capacity + 1, dtype=jnp.int32)  # [CAP+1]
            replicated = nodes.match_idx[node][None, :] >= idxs[:, None]  # [CAP+1, N]
            cnt = jnp.sum(replicated, axis=1)
            cur_term_entry = nodes.log_term[node] == nodes.term[node]  # [CAP+1]
            quorum = self.majority - 1 if self.QUORUM_OFF_BY_ONE else self.majority
            committable = (cnt >= quorum) & cur_term_entry & (idxs >= 1) & (idxs <= nodes.log_len[node])
            best = jnp.max(jnp.where(committable, idxs, 0))
            nodes = update_node(
                nodes, node,
                commit=jnp.where(good, jnp.maximum(nodes.commit[node], best), nodes.commit[node]),
            )
            return nodes, outbox

        return lax.switch(branch, [rv_branch, vote_branch, ae_branch, aer_branch], (nodes,))

    # -- invariants / results ------------------------------------------------

    def invariant(self, nodes: RaftState, now_us):
        n = self.NUM_NODES
        is_lead = nodes.role == LEADER
        same_term = nodes.term[:, None] == nodes.term[None, :]
        both_lead = is_lead[:, None] & is_lead[None, :] & ~jnp.eye(n, dtype=bool)
        elec_viol = jnp.any(both_lead & same_term)

        # Committed prefixes must agree pairwise. Checked per POSITION
        # instead of per pair — O(N*CAP), not O(N^2*CAP), and exactly
        # equivalent: nodes i, j disagree at a position k both have
        # committed iff, among the nodes whose commit reaches k, the
        # min and max log term at k differ (empty/singleton sets give
        # min >= max, never a violation). The invariant runs EVERY
        # event on every lane, so this is hot-path arithmetic.
        idxs = jnp.arange(self.log_capacity + 1, dtype=jnp.int32)
        committed = (idxs[None, :] >= 1) & (idxs[None, :] <= nodes.commit[:, None])
        big = jnp.int32(2**31 - 1)
        t_min = jnp.min(jnp.where(committed, nodes.log_term, big), axis=0)
        t_max = jnp.max(jnp.where(committed, nodes.log_term, -big), axis=0)
        log_viol = jnp.any(t_max > t_min)

        ok = ~(elec_viol | log_viol)
        code = jnp.where(elec_viol, ELECTION_SAFETY, jnp.where(log_viol, LOG_MATCHING, 0))
        return ok, code.astype(jnp.int32)

    def is_done(self, nodes: RaftState, now_us):
        # all nodes committed a full log => nothing left to explore
        return jnp.all(nodes.commit >= self.log_capacity)

    def summary(self, nodes: RaftState):
        return {
            "max_term": jnp.max(nodes.term),
            "max_commit": jnp.max(nodes.commit),
            "min_commit": jnp.min(nodes.commit),
            "num_leaders": jnp.sum((nodes.role == LEADER).astype(jnp.int32)),
        }

    def coverage_projection(self, nodes: RaftState, now_us):
        """Scenario projection (EngineConfig.coverage): term bucket
        (phase, low 3 bits) x leader count x committed-log divergence x
        cross-node term delta — the cluster-shape axes along which raft
        interleavings actually differ (which election round, split
        leadership, how far replicas disagree)."""
        term_b = jnp.clip(jnp.max(nodes.term), 0, 7)  # phase bits
        leaders = jnp.clip(
            jnp.sum((nodes.role == LEADER).astype(jnp.int32)), 0, 3
        )
        commit_div = jnp.clip(jnp.max(nodes.commit) - jnp.min(nodes.commit), 0, 7)
        term_delta = jnp.clip(jnp.max(nodes.term) - jnp.min(nodes.term), 0, 3)
        candidates = jnp.clip(
            jnp.sum((nodes.role == CANDIDATE).astype(jnp.int32)), 0, 3
        )
        return (
            term_b
            | (leaders << 3)
            | (commit_div << 5)
            | (term_delta << 8)
            | (candidates << 10)
        ).astype(jnp.uint32)
