"""Kafka consumer-group machine — the rdkafka consumer-group workload
as a batched engine Machine.

The host-engine analogue lives in services/kafka (GroupCoordinator with
rebalancing + fenced commits, exercised by tests/test_services.py); the
reference's integration suite is madsim-rdkafka/tests/test.rs. This
model proves the L5-class semantics run *batched on the TPU engine*:
thousands of seeds explore kill/restart and partition faults against a
group coordinator in lockstep, and failing seeds replay bit-identically
on the host replayer.

Topology: node 0 = broker + group coordinator (Kafka's group coordinator
IS a broker); nodes 1..C = consumer-group members. The topic has P
partitions, each pre-filled with `log_len` records (record identity is
(partition, offset), so no payload storage is needed).

Protocol (pull-based, 5 message kinds):
  * members heartbeat the coordinator; an unknown member's heartbeat is
    a join. Membership changes bump the generation and recompute a
    range assignment over joined members.
  * heartbeat responses carry (generation, assignment bitmask, committed
    offsets); a member seeing a new generation adopts the assignment and
    resumes every owned partition from its committed offset — the
    resume-from-committed rule that makes rebalancing lossless.
  * members fetch their owned partitions round-robin and auto-commit
    after each consumed record, tagged with their generation.
  * the coordinator fences commits: accepted only from the current
    generation's assigned owner (Kafka's ILLEGAL_GENERATION /
    FENCED_INSTANCE_ID checks). `NoFencingGroupMachine` drops that
    check — partitioned zombies then regress committed offsets, which
    is the bug class the invariant exists to catch.
  * a session timer expires members whose heartbeats stopped
    (kill/partition faults), bumping the generation.

Durability under engine faults: the coordinator's generation + committed
offsets survive restart (Kafka persists them in __consumer_offsets);
its member table is volatile (coordinator failover forces rejoins).
Members lose everything (positions must come back from committed).

Invariants (checked on-device after every event):
  * COMMIT_REGRESS (131): an accepted commit moved a committed offset
    backwards — impossible with fencing, the zombie signature without.
  * LOST_RECORD (130): some offset below a committed offset was never
    consumed by any member (at-least-once violated). Tracked with a
    ghost consumed-bitmap — spec-only auxiliary state, written at
    consume time, never read by the protocol.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import (
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_at,
    set_timer_if,
    update_node,
)

COORD = 0

# messages
M_HB, M_HB_RESP, M_FETCH, M_FETCH_RESP, M_COMMIT = 1, 2, 3, 4, 5

# timers
T_BOOT, T_SESSION, T_HB, T_POLL = 0, 1, 2, 3

LOST_RECORD = 130
COMMIT_REGRESS = 131

HB_US = 40_000
POLL_US = 17_000
SESSION_US = 150_000
SESSION_CHECK_US = 50_000


@struct.dataclass
class GroupState:
    # coordinator (row COORD); gen doubles as each member's adopted gen
    gen: jax.Array  # int32[N]
    joined: jax.Array  # bool[N]   coordinator's member table
    last_hb: jax.Array  # int32[N] coordinator's last-heartbeat time (us)
    assign_member: jax.Array  # int32[N, P] owning node id per partition (-1 none)
    committed: jax.Array  # int32[N, P] durable committed offsets (row COORD)
    commit_gen: jax.Array  # int32[N, P] generation of the last accepted commit
    bad_regress: jax.Array  # bool[N]  spec flag (row COORD)
    # members
    my_assign: jax.Array  # bool[N, P]
    position: jax.Array  # int32[N, P] next offset to consume
    poll_rr: jax.Array  # int32[N] round-robin partition cursor
    # ghost (spec-only): which (partition, offset) was ever consumed
    consumed: jax.Array  # bool[N, P, L] (row COORD)


class KafkaGroupMachine(Machine):
    """1 coordinator/broker + (num_nodes-1) group members."""

    MAX_MSGS = 1
    MAX_TIMERS = 2

    def __init__(self, num_nodes: int = 4, partitions: int = 2, log_len: int = 12):
        self.NUM_NODES = num_nodes
        self.P = partitions
        self.L = log_len
        self.PAYLOAD_WIDTH = max(5, 3 + partitions)

    # -- state ---------------------------------------------------------------

    def init(self, rng_key) -> GroupState:
        n, p, l = self.NUM_NODES, self.P, self.L
        return GroupState(
            gen=jnp.zeros((n,), jnp.int32),
            joined=jnp.zeros((n,), bool),
            last_hb=jnp.zeros((n,), jnp.int32),
            assign_member=jnp.full((n, p), -1, jnp.int32),
            committed=jnp.zeros((n, p), jnp.int32),
            commit_gen=jnp.zeros((n, p), jnp.int32),
            bad_regress=jnp.zeros((n,), bool),
            my_assign=jnp.zeros((n, p), bool),
            position=jnp.zeros((n, p), jnp.int32),
            poll_rr=jnp.zeros((n,), jnp.int32),
            consumed=jnp.zeros((n, p, l), bool),
        )

    def restart_if(self, nodes: GroupState, i, cond, rng_key) -> GroupState:
        n = self.NUM_NODES
        row = (jnp.arange(n) == i) & cond
        # coordinator restart: member table is volatile (all must rejoin);
        # gen/committed/ghost are durable. Member restart: session state
        # (adopted gen, assignment, positions) is volatile.
        member_row = row & (jnp.arange(n) != COORD)
        # the member table lives in the coordinator's row-space, so a
        # coordinator restart wipes the whole joined/last_hb vectors
        any_coord = cond & (i == COORD)
        joined = jnp.where(any_coord, False, nodes.joined)
        last_hb = jnp.where(any_coord, 0, nodes.last_hb)
        return nodes.replace(
            joined=joined,
            last_hb=last_hb,
            gen=jnp.where(member_row, 0, nodes.gen),
            my_assign=jnp.where(member_row[:, None], False, nodes.my_assign),
            position=jnp.where(member_row[:, None], 0, nodes.position),
            poll_rr=jnp.where(member_row, 0, nodes.poll_rr),
        )

    # -- coordinator helpers --------------------------------------------------

    def _rebalance_if(self, nodes: GroupState, cond) -> GroupState:
        """Bump generation + recompute the range assignment over joined
        members (node ids 1..N-1), under traced `cond`."""
        n, p = self.NUM_NODES, self.P
        joined = nodes.joined
        k = joined.sum(dtype=jnp.int32)
        ranks = jnp.cumsum(joined.astype(jnp.int32)) - 1  # rank among joined
        targets = jnp.mod(jnp.arange(p, dtype=jnp.int32), jnp.maximum(k, 1))
        match = joined[None, :] & (ranks[None, :] == targets[:, None])  # [P, N]
        assignment = jnp.where(k > 0, jnp.argmax(match, axis=1).astype(jnp.int32), -1)
        new_row = jnp.where(cond, assignment, nodes.assign_member[COORD])
        return nodes.replace(
            gen=set_at(nodes.gen, COORD, nodes.gen[COORD] + 1, cond),
            assign_member=set_at(nodes.assign_member, COORD, new_row),
        )

    def _commit_accepts(self, nodes: GroupState, src, c_gen, c_part) -> jax.Array:
        """Fencing predicate — the line NoFencingGroupMachine removes."""
        return (
            (c_gen == nodes.gen[COORD])
            & nodes.joined[src]
            & (nodes.assign_member[COORD, c_part] == src)
        )

    # -- timers ---------------------------------------------------------------

    def on_timer(self, nodes: GroupState, node, timer_id, now_us, rand_u32) -> Tuple[GroupState, Outbox]:
        outbox = self.empty_outbox()
        is_coord = node == COORD
        is_member = ~is_coord
        is_boot = timer_id == T_BOOT

        outbox = set_timer_if(outbox, 0, is_boot & is_coord, SESSION_CHECK_US, T_SESSION)
        outbox = set_timer_if(outbox, 0, is_boot & is_member, HB_US, T_HB)
        outbox = set_timer_if(outbox, 1, is_boot & is_member, POLL_US, T_POLL)

        # coordinator: expire silent members, rebalance if any left
        tick = (timer_id == T_SESSION) & is_coord
        expired = nodes.joined & (nodes.last_hb + SESSION_US < now_us)
        any_expired = tick & jnp.any(expired)
        nodes = nodes.replace(joined=jnp.where(any_expired, nodes.joined & ~expired, nodes.joined))
        nodes = self._rebalance_if(nodes, any_expired)
        outbox = set_timer_if(outbox, 0, tick, SESSION_CHECK_US, T_SESSION)

        # member: heartbeat (doubles as join)
        hb = (timer_id == T_HB) & is_member
        outbox = send_if(outbox, 0, hb, COORD, make_payload(self.PAYLOAD_WIDTH, M_HB))
        outbox = set_timer_if(outbox, 0, hb, HB_US, T_HB)

        # member: fetch the next owned partition (round-robin cursor)
        poll = (timer_id == T_POLL) & is_member
        rr = nodes.poll_rr[node]
        owned = nodes.my_assign[node]  # bool[P]
        # first owned partition at cursor >= rr (wrapping): rotate indices
        order = jnp.mod(rr + jnp.arange(self.P, dtype=jnp.int32), self.P)
        owned_rot = owned[order]
        pick = order[jnp.argmax(owned_rot)]
        has = jnp.any(owned)
        want = poll & has & (nodes.position[node, pick] < self.L)
        fetch = make_payload(self.PAYLOAD_WIDTH, M_FETCH, pick, nodes.position[node, pick])
        outbox = send_if(outbox, 0, want, COORD, fetch)
        nodes = update_node(nodes, node, poll_rr=jnp.where(poll, jnp.mod(pick + 1, self.P), rr))
        outbox = set_timer_if(outbox, 0, poll, POLL_US, T_POLL)
        return nodes, outbox

    # -- messages -------------------------------------------------------------

    def on_message(self, nodes: GroupState, node, src, payload, now_us, rand_u32) -> Tuple[GroupState, Outbox]:
        outbox = self.empty_outbox()
        mtype = payload[0]
        is_coord = node == COORD

        # coordinator: heartbeat / join
        hb = is_coord & (mtype == M_HB)
        new_member = hb & ~nodes.joined[src]
        nodes = nodes.replace(
            joined=set_at(nodes.joined, src, True, hb),
            last_hb=set_at(nodes.last_hb, src, now_us, hb),
        )
        nodes = self._rebalance_if(nodes, new_member)
        mask_bits = (
            (nodes.assign_member[COORD] == src).astype(jnp.int32)
            * (1 << jnp.arange(self.P, dtype=jnp.int32))
        ).sum()
        resp = make_payload(
            self.PAYLOAD_WIDTH, M_HB_RESP, nodes.gen[COORD], mask_bits,
            *[nodes.committed[COORD, p] for p in range(self.P)],
        )
        outbox = send_if(outbox, 0, hb, src, resp)

        # coordinator: fetch -> serve record identity if it exists
        fetch = is_coord & (mtype == M_FETCH)
        f_part, f_off = payload[1], payload[2]
        have = (f_off >= 0) & (f_off < self.L)
        resp_f = make_payload(self.PAYLOAD_WIDTH, M_FETCH_RESP, f_part, f_off)
        outbox = send_if(outbox, 0, fetch & have, src, resp_f)

        # coordinator: commit (fenced). Within one generation the owner's
        # commits are cumulative, so a lower offset is just a reordered
        # datagram (the real protocol rides ordered TCP) and is absorbed
        # with max(); a commit from a *different* generation starts a new
        # regime and overwrites — which is where an unfenced zombie's
        # stale offset regresses the partition.
        commit = is_coord & (mtype == M_COMMIT)
        c_gen, c_part, c_off = payload[1], payload[2], payload[3]
        accept = commit & self._commit_accepts(nodes, src, c_gen, c_part)
        part_clip = jnp.clip(c_part, 0, self.P - 1)
        same_regime = c_gen == nodes.commit_gen[COORD, part_clip]
        apply = accept & (~same_regime | (c_off > nodes.committed[COORD, part_clip]))
        regress = apply & (c_off < nodes.committed[COORD, part_clip])
        new_committed_row = set_at(nodes.committed[COORD], part_clip, c_off, apply)
        new_cgen_row = set_at(nodes.commit_gen[COORD], part_clip, c_gen, apply)
        nodes = nodes.replace(
            committed=set_at(nodes.committed, COORD, new_committed_row),
            commit_gen=set_at(nodes.commit_gen, COORD, new_cgen_row),
            bad_regress=set_at(nodes.bad_regress, COORD, nodes.bad_regress[COORD] | regress, commit),
        )

        # member: heartbeat response -> adopt new generation + resume
        is_member = node != COORD
        hb_resp = is_member & (mtype == M_HB_RESP)
        r_gen, r_mask = payload[1], payload[2]
        adopt = hb_resp & (r_gen != nodes.gen[node])
        new_assign = ((r_mask >> jnp.arange(self.P, dtype=jnp.int32)) & 1) != 0
        resume = jnp.stack([payload[3 + p] for p in range(self.P)])
        nodes = update_node(
            nodes, node,
            gen=jnp.where(adopt, r_gen, nodes.gen[node]),
            my_assign=jnp.where(adopt, new_assign, nodes.my_assign[node]),
            position=jnp.where(adopt, resume, nodes.position[node]),
        )

        # member: fetched record -> consume (ghost) + auto-commit
        fr = is_member & (mtype == M_FETCH_RESP)
        g_part, g_off = payload[1], payload[2]
        g_part_c = jnp.clip(g_part, 0, self.P - 1)
        take = fr & nodes.my_assign[node, g_part_c] & (g_off == nodes.position[node, g_part_c])
        # ghost consumed bitmap lives on the COORD row (spec-only)
        off_mask = jnp.arange(self.L) == jnp.clip(g_off, 0, self.L - 1)
        part_mask = jnp.arange(self.P) == g_part_c
        node_mask = jnp.arange(self.NUM_NODES) == COORD
        ghost_write = take & node_mask[:, None, None] & part_mask[None, :, None] & off_mask[None, None, :]
        consumed = nodes.consumed | ghost_write
        new_pos_row = set_at(nodes.position[node], g_part_c, g_off + 1, take)
        nodes = nodes.replace(
            consumed=consumed,
            position=set_at(nodes.position, node, new_pos_row),
        )
        commit_msg = make_payload(
            self.PAYLOAD_WIDTH, M_COMMIT, nodes.gen[node], g_part_c, g_off + 1
        )
        outbox = send_if(outbox, 0, take, COORD, commit_msg)
        return nodes, outbox

    # -- invariants / results --------------------------------------------------

    def invariant(self, nodes: GroupState, now_us):
        committed = nodes.committed[COORD]  # [P]
        in_range = jnp.all((committed >= 0) & (committed <= self.L))
        below = jnp.arange(self.L)[None, :] < committed[:, None]  # [P, L]
        all_consumed = jnp.all(jnp.where(below, nodes.consumed[COORD], True))
        lost = ~(in_range & all_consumed)
        regress = nodes.bad_regress[COORD]
        ok = ~(lost | regress)
        code = jnp.where(regress, COMMIT_REGRESS, jnp.where(lost, LOST_RECORD, 0))
        return ok, code.astype(jnp.int32)

    def is_done(self, nodes: GroupState, now_us):
        return jnp.all(nodes.committed[COORD] >= self.L)

    def summary(self, nodes: GroupState):
        return {
            "committed": nodes.committed[COORD],
            "generation": nodes.gen[COORD],
            "members": nodes.joined.sum(dtype=jnp.int32),
        }


class NoFencingGroupMachine(KafkaGroupMachine):
    """Bug variant: the coordinator accepts commits from any generation —
    the zombie-commit class that consumer-group fencing exists to stop.
    The engine finds seeds where a partitioned member's stale commit
    regresses a committed offset (COMMIT_REGRESS)."""

    def _commit_accepts(self, nodes: GroupState, src, c_gen, c_part) -> jax.Array:
        return jnp.bool_(True)
