"""Protocol models for the TPU engine.

`echo` — 2-node request/response (the tonic-example-class workload,
reference: tonic-example/tests/test.rs:22-120).
`raft` — MadRaft-class leader election + log replication, the flagship
benchmark workload (BASELINE.json configs).
`kv` — versioned KV store + retrying clients, session-monotonicity
invariant (the etcd-class kill/restart workload).
`mq` — idempotent-producer message queue, per-producer gapless ordering
invariant (the rdkafka-class workload).
`etcd` — leased-KV leader election (grant/campaign/keepalive over an
MVCC server), lease-safety invariant (the madsim-etcd-client service-
class workload, batched).
`twopc` — two-phase commit with durable write-ahead logs, transaction-
atomicity invariant (the atomic-commitment workload class).
`kafka_group` — consumer-group coordinator with generations, session
timeouts and fenced commits; at-least-once + no-commit-regression
invariants (the rdkafka consumer-group workload, batched).
`paxos` — single-decree Paxos with durable acceptors and dueling
proposers; agreement invariant via a ghost chosen-register.
`multipaxos` — multi-decree Paxos: a log of synod slots driven by
dueling proposers with LEARN propagation; per-slot agreement + learned-
log-consistency invariants (the second consensus family at MadRaft
depth).
`etcd_mvcc` — MVCC etcd server (revisions, txns, leases with ghost
expiry) + retrying clients; revision-accounting, txn-atomicity,
lease-expiry-safety and exactly-once invariants.
"""

from . import echo, etcd, etcd_mvcc, kafka_group, kv, mq, multipaxos, paxos, raft, twopc

__all__ = [
    "echo", "etcd", "etcd_mvcc", "kafka_group", "kv", "mq", "multipaxos",
    "paxos", "raft", "twopc",
]
