"""Raft with snapshotting + log compaction — the compound workload the
torn/lost-write fault kind mines.

Extends the flagship raft model (models/raft.py: leader election,
single-entry AppendEntries, randomized timeouts) with the classic
interaction-bug mine: every node periodically SNAPSHOTS its committed
prefix and trims the log ring behind it, and a leader whose follower has
fallen behind the trim point sends InstallSnapshot instead of
AppendEntries (Raft §7). The log ring is windowed: stored slot `s` of a
node holds the term of ABSOLUTE index `base + s`, slot 0 being the
boundary term at `base` itself; `snap_idx`/`snap_term` describe the
snapshot covering indices `[1, snap_idx]`. Honest compaction writes the
snapshot and the trim in one atomic event, so `snap_idx == base` always
— the load-bearing storage invariant torn-write faults attack.

On-device invariants (checked after every event):
  * ElectionSafety (code 101): at most one leader per term
  * LogMatching on committed prefixes (code 102), compaction-aware:
    (a) wherever two nodes both store and have both committed an
        absolute position, the terms must agree (the stored windows are
        aligned through each node's `base`);
    (b) snapshot coverage: a node's committed watermark may only stand
        on storage it can attest — `commit > snap_idx` with
        `base > snap_idx` means positions in `(snap_idx, base]` are
        claimed committed yet neither stored nor covered by the
        snapshot. Honest nodes keep `snap_idx == base` so (b) can never
        fire; a torn snapshot write (trim persisted, snapshot lost)
        trips it at the node's first re-commit.

The seeded bug (`demo-tornsnapshot-raft` / TornSnapshotRaftCompact):
the snapshot file write is not fsynced — its `torn_spec()` marks
`snap_idx`/`snap_term` TORN_LOSE while the trimmed log ring stays
atomic. A torn restart (`FaultPlan.allow_torn`, K_TORN) then lands the
node in exactly the state invariant (b) describes: trimmed log, no
snapshot. The honest machine declares no torn_spec — every durable
write atomic — so torn restarts degrade to the amnesia wipe and the
model survives the full chaos palette clean.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..engine.machine import (
    TORN_ATOMIC,
    TORN_LOSE,
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_at,
    set_timer_if,
    update_node,
)
from ..utils import set2d
from .raft import (
    CANDIDATE,
    CLIENT_APPEND_US,
    ELECTION_MAX_US,
    ELECTION_MIN_US,
    ELECTION_SAFETY,
    FOLLOWER,
    HEARTBEAT_US,
    LEADER,
    LOG_MATCHING,
    M_AE,
    M_AER,
    M_RV,
    M_VOTE,
    T_BOOT,
    T_CLIENT,
    T_ELECTION,
    T_HEARTBEAT,
)

# InstallSnapshot (Raft §7): payload (M_IS, term, snap_idx, snap_term)
M_IS = 5


@struct.dataclass
class RaftCompactState:
    # persistent (stable storage)
    term: jax.Array  # int32[N]
    voted_for: jax.Array  # int32[N], -1 = none
    log_term: jax.Array  # int32[N, CAP+1]; slot s = term at abs index base+s
    log_len: jax.Array  # int32[N] stored entries past base (last abs = base+len)
    base: jax.Array  # int32[N] trim boundary: entries <= base are compacted
    snap_idx: jax.Array  # int32[N] snapshot covers [1, snap_idx] (== base honest)
    snap_term: jax.Array  # int32[N] term at snap_idx
    epoch: jax.Array  # int32[N] timer epoch (persistent, bumped at BOOT)
    # volatile
    role: jax.Array  # int32[N]
    votes: jax.Array  # int32[N] granted-voter bitmask (dup-safe tally)
    elec_deadline: jax.Array  # int32[N] us
    commit: jax.Array  # int32[N] absolute watermark
    next_idx: jax.Array  # int32[N, N] absolute
    match_idx: jax.Array  # int32[N, N] absolute


class RaftCompactMachine(Machine):
    PAYLOAD_WIDTH = 6
    MAX_TIMERS = 2

    def __init__(
        self,
        num_nodes: int = 5,
        log_capacity: int = 8,
        compact_lag: int = 3,
        target_commit: int = 0,
    ):
        if num_nodes > 31:
            raise ValueError(
                "RaftCompactMachine tracks granting voters as an int32 "
                "bitmask (dup-safe tally, Raft §5.2); num_nodes must be "
                "<= 31"
            )
        if not 1 <= compact_lag <= log_capacity:
            raise ValueError("compact_lag must be in [1, log_capacity]")
        self.NUM_NODES = num_nodes
        self.MAX_MSGS = num_nodes - 1
        self.log_capacity = log_capacity
        self.compact_lag = compact_lag  # snapshot once commit-base reaches this
        self.target_commit = target_commit or 2 * log_capacity
        self.majority = num_nodes // 2 + 1

    # -- state ---------------------------------------------------------------

    def init(self, rng_key) -> RaftCompactState:
        n, cap = self.NUM_NODES, self.log_capacity
        z = jnp.zeros((n,), jnp.int32)
        return RaftCompactState(
            term=z,
            voted_for=jnp.full((n,), -1, jnp.int32),
            log_term=jnp.zeros((n, cap + 1), jnp.int32),
            log_len=z,
            base=z,
            snap_idx=z,
            snap_term=z,
            epoch=z,
            role=z,
            votes=z,
            elec_deadline=z,
            commit=z,
            next_idx=jnp.ones((n, n), jnp.int32),
            match_idx=jnp.zeros((n, n), jnp.int32),
        )

    def durable_spec(self) -> RaftCompactState:
        """term/votedFor/log window/trim boundary/snapshot metadata are
        stable storage; the timer epoch must survive (it dies with the
        node's timers otherwise); everything else is volatile. The
        generic amnesia wipe under this spec is leaf-for-leaf identical
        to `restart_if` (strict on/off bit-identical for the honest
        machine)."""
        return RaftCompactState(
            term=True, voted_for=True, log_term=True, log_len=True,
            base=True, snap_idx=True, snap_term=True, epoch=True,
            role=False, votes=False, elec_deadline=False, commit=False,
            next_idx=False, match_idx=False,
        )

    def restart_if(self, nodes: RaftCompactState, i, cond, rng_key) -> RaftCompactState:
        n = self.NUM_NODES
        row = (jnp.arange(n) == i) & cond
        set_row = lambda arr, v: jnp.where(row, v, arr)  # noqa: E731
        return nodes.replace(
            role=set_row(nodes.role, FOLLOWER),
            votes=set_row(nodes.votes, 0),
            elec_deadline=set_row(nodes.elec_deadline, 0),
            commit=set_row(nodes.commit, 0),
            next_idx=jnp.where(row[:, None], 1, nodes.next_idx),
            match_idx=jnp.where(row[:, None], 0, nodes.match_idx),
        )

    def init_node(self, nodes: RaftCompactState, i, rng_key) -> RaftCompactState:
        return self.restart_if(nodes, i, jnp.bool_(True), rng_key)

    # -- helpers -------------------------------------------------------------

    def _peers(self, node):
        n = self.NUM_NODES
        offs = jnp.arange(1, n, dtype=jnp.int32)
        return (node + offs) % n

    def _rand_timeout(self, rand_word):
        span = jnp.uint32(ELECTION_MAX_US - ELECTION_MIN_US)
        return jnp.int32(ELECTION_MIN_US) + (rand_word % span).astype(jnp.int32)

    def _pay(self, *vals):
        return make_payload(self.PAYLOAD_WIDTH, *vals)

    def _tid(self, nodes, node, base):
        return jnp.int32(base) + 4 * nodes.epoch[node]

    def _term_at(self, nodes, node, abs_idx):
        """Stored term at an absolute index, clipped into the node's
        window — callers gate on validity themselves."""
        rel = jnp.clip(abs_idx - nodes.base[node], 0, self.log_capacity)
        return nodes.log_term[node, rel]

    # granted-voter bitmask tally (dup-safe, mirrors models/raft.py)

    def _vote_init(self, node):
        return jnp.int32(1) << node

    def _vote_add(self, votes, src, counts):
        return jnp.where(counts, votes | (jnp.int32(1) << src), votes)

    def _vote_count(self, votes):
        return lax.population_count(votes.astype(jnp.uint32)).astype(jnp.int32)

    # -- timers --------------------------------------------------------------

    def on_timer(self, nodes: RaftCompactState, node, timer_id, now_us, rand_u32) -> Tuple[RaftCompactState, Outbox]:
        outbox = self.empty_outbox()
        cap = self.log_capacity
        tbase = timer_id % 4
        t_epoch = timer_id // 4
        is_boot = timer_id == T_BOOT
        live = is_boot | (t_epoch == nodes.epoch[node])

        # ---- BOOT: bump epoch, arm election + client timers ----
        new_epoch = jnp.where(is_boot & live, nodes.epoch[node] + 1, nodes.epoch[node])
        nodes = update_node(nodes, node, epoch=new_epoch)
        timeout = self._rand_timeout(rand_u32[0])
        nodes = update_node(
            nodes, node,
            elec_deadline=jnp.where(
                is_boot & live, now_us + timeout, nodes.elec_deadline[node]
            ),
        )
        outbox = set_timer_if(outbox, 0, is_boot & live, timeout, self._tid(nodes, node, T_ELECTION))
        outbox = set_timer_if(outbox, 1, is_boot & live, CLIENT_APPEND_US, self._tid(nodes, node, T_CLIENT))

        # ---- ELECTION ----
        is_elec = live & (tbase == T_ELECTION) & ~is_boot
        not_yet = now_us < nodes.elec_deadline[node]
        rearm_delay = jnp.maximum(nodes.elec_deadline[node] - now_us, 1)
        outbox = set_timer_if(outbox, 0, is_elec & not_yet, rearm_delay, self._tid(nodes, node, T_ELECTION))

        start = is_elec & ~not_yet & (nodes.role[node] != LEADER)
        new_term = nodes.term[node] + 1
        timeout2 = self._rand_timeout(rand_u32[1])
        nodes = update_node(
            nodes, node,
            term=jnp.where(start, new_term, nodes.term[node]),
            role=jnp.where(start, CANDIDATE, nodes.role[node]),
            voted_for=jnp.where(start, node, nodes.voted_for[node]),
            votes=jnp.where(start, self._vote_init(node), nodes.votes[node]),
            elec_deadline=jnp.where(start, now_us + timeout2, nodes.elec_deadline[node]),
        )
        outbox = set_timer_if(
            outbox, 0, is_elec & ~not_yet, timeout2, self._tid(nodes, node, T_ELECTION)
        )
        last_idx = nodes.base[node] + nodes.log_len[node]  # absolute
        last_term = nodes.log_term[node, nodes.log_len[node]]
        rv = self._pay(M_RV, nodes.term[node], node, last_idx, last_term)
        peers = self._peers(node)
        for s in range(self.MAX_MSGS):
            outbox = send_if(outbox, s, start, peers[s], rv)

        # ---- HEARTBEAT (leader replicates; snapshot when peer is
        #      behind the trim point) ----
        is_hb = live & (tbase == T_HEARTBEAT) & ~is_boot
        is_leader = nodes.role[node] == LEADER
        do_hb = is_hb & is_leader
        outbox = set_timer_if(outbox, 1, do_hb, HEARTBEAT_US, self._tid(nodes, node, T_HEARTBEAT))
        for s in range(self.MAX_MSGS):
            peer = peers[s]
            ni = nodes.next_idx[node, peer]  # absolute
            need_snap = ni <= nodes.base[node]  # entries trimmed away
            prev_idx = ni - 1
            prev_term = self._term_at(nodes, node, prev_idx)
            has_entry = ni <= nodes.base[node] + nodes.log_len[node]
            entry_term = jnp.where(has_entry, self._term_at(nodes, node, ni), 0)
            ae = self._pay(M_AE, nodes.term[node], prev_idx, prev_term, entry_term, nodes.commit[node])
            inst = self._pay(M_IS, nodes.term[node], nodes.snap_idx[node], nodes.snap_term[node])
            outbox = send_if(outbox, s, do_hb, peer, jnp.where(need_snap, inst, ae))

        # ---- CLIENT tick: compact own log, then (leader) append ----
        is_client = live & (tbase == T_CLIENT) & ~is_boot
        outbox = set_timer_if(outbox, 1, is_client & ~do_hb, CLIENT_APPEND_US, self._tid(nodes, node, T_CLIENT))

        # Compaction (every node, its own log): once the committed
        # prefix has outgrown compact_lag, snapshot AT the commit point
        # and trim the ring behind it. Snapshot metadata and trim are
        # written in this ONE event — the atomicity the torn fault tests.
        lag = nodes.commit[node] - nodes.base[node]  # <= log_len always
        do_compact = is_client & (lag >= self.compact_lag)
        shift = jnp.where(
            do_compact, jnp.clip(jnp.minimum(lag, nodes.log_len[node]), 0, cap), 0
        )
        srel = jnp.arange(cap + 1, dtype=jnp.int32)
        row = nodes.log_term[node]
        shifted = jnp.where(srel + shift <= cap, row[jnp.clip(srel + shift, 0, cap)], 0)
        boundary_term = row[jnp.clip(shift, 0, cap)]
        nodes = update_node(
            nodes, node,
            log_term=jnp.where(do_compact, shifted, row),
            log_len=jnp.where(do_compact, nodes.log_len[node] - shift, nodes.log_len[node]),
            base=jnp.where(do_compact, nodes.base[node] + shift, nodes.base[node]),
            snap_idx=jnp.where(do_compact, nodes.base[node] + shift, nodes.snap_idx[node]),
            snap_term=jnp.where(do_compact, boundary_term, nodes.snap_term[node]),
        )

        # leader client append (post-compaction state)
        can_append = is_client & is_leader & (nodes.log_len[node] < cap)
        new_len = nodes.log_len[node] + 1
        slot = jnp.clip(new_len, 0, cap)
        nodes = update_node(
            nodes, node,
            log_len=jnp.where(can_append, new_len, nodes.log_len[node]),
            log_term=jnp.where(
                can_append,
                set_at(nodes.log_term[node], slot, nodes.term[node]),
                nodes.log_term[node],
            ),
        )
        nodes = nodes.replace(
            match_idx=jnp.where(
                can_append,
                set2d(nodes.match_idx, node, node, nodes.base[node] + new_len),
                nodes.match_idx,
            )
        )
        return nodes, outbox

    # -- messages ------------------------------------------------------------

    def on_message(self, nodes: RaftCompactState, node, src, payload, now_us, rand_u32) -> Tuple[RaftCompactState, Outbox]:
        mtype = payload[0]
        branch = jnp.clip(mtype - 1, 0, 4)
        cap = self.log_capacity

        def step_down(nodes, t, also_follow):
            """Common term bookkeeping: adopt newer terms; `also_follow`
            additionally demotes on equal-term leader contact."""
            newer = t > nodes.term[node]
            return update_node(
                nodes, node,
                term=jnp.where(newer, t, nodes.term[node]),
                role=jnp.where(
                    newer | (also_follow & (t == nodes.term[node])),
                    FOLLOWER, nodes.role[node],
                ),
                voted_for=jnp.where(newer, -1, nodes.voted_for[node]),
            )

        def rv_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, cand, last_idx, last_term = payload[1], payload[2], payload[3], payload[4]
            nodes = step_down(nodes, t, jnp.bool_(False))
            my_last = nodes.base[node] + nodes.log_len[node]
            my_last_term = nodes.log_term[node, nodes.log_len[node]]
            log_ok = (last_term > my_last_term) | (
                (last_term == my_last_term) & (last_idx >= my_last)
            )
            can_vote = (nodes.voted_for[node] == -1) | (nodes.voted_for[node] == cand)
            grant = (t == nodes.term[node]) & can_vote & log_ok
            nodes = update_node(
                nodes, node,
                voted_for=jnp.where(grant, cand, nodes.voted_for[node]),
                elec_deadline=jnp.where(
                    grant, now_us + self._rand_timeout(rand_u32[0]), nodes.elec_deadline[node]
                ),
            )
            vote = self._pay(M_VOTE, nodes.term[node], grant.astype(jnp.int32))
            outbox = send_if(outbox, 0, jnp.bool_(True), src, vote)
            return nodes, outbox

        def vote_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, granted = payload[1], payload[2]
            nodes = step_down(nodes, t, jnp.bool_(False))
            counts = (t == nodes.term[node]) & (nodes.role[node] == CANDIDATE) & (granted == 1)
            new_votes = self._vote_add(nodes.votes[node], src, counts)
            win = (
                counts
                & (self._vote_count(new_votes) >= self.majority)
                & (nodes.role[node] == CANDIDATE)
            )
            n = self.NUM_NODES
            my_last = nodes.base[node] + nodes.log_len[node]
            nodes = update_node(
                nodes, node, votes=new_votes,
                role=jnp.where(win, LEADER, nodes.role[node]),
            )
            nodes = nodes.replace(
                next_idx=jnp.where(
                    win,
                    set_at(nodes.next_idx, node, jnp.full((n,), 0, jnp.int32) + my_last + 1),
                    nodes.next_idx,
                ),
                match_idx=jnp.where(
                    win,
                    set_at(
                        nodes.match_idx, node,
                        set_at(jnp.zeros((n,), jnp.int32), node, my_last),
                    ),
                    nodes.match_idx,
                ),
            )
            peers = self._peers(node)
            prev_term = nodes.log_term[node, nodes.log_len[node]]
            ae = self._pay(M_AE, nodes.term[node], my_last, prev_term, 0, nodes.commit[node])
            for s in range(self.MAX_MSGS):
                outbox = send_if(outbox, s, win, peers[s], ae)
            outbox = set_timer_if(outbox, 0, win, HEARTBEAT_US, self._tid(nodes, node, T_HEARTBEAT))
            return nodes, outbox

        def ae_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, prev_idx, prev_term, entry_term, leader_commit = (
                payload[1], payload[2], payload[3], payload[4], payload[5],
            )
            stale = t < nodes.term[node]
            nodes = step_down(nodes, t, jnp.bool_(True))
            nodes = update_node(
                nodes, node,
                elec_deadline=jnp.where(
                    ~stale, now_us + self._rand_timeout(rand_u32[0]), nodes.elec_deadline[node]
                ),
            )
            base = nodes.base[node]
            stored_last = base + nodes.log_len[node]
            prev_rel = prev_idx - base
            within = (prev_rel >= 0) & (prev_idx <= stored_last)
            match_here = within & (nodes.log_term[node, jnp.clip(prev_rel, 0, cap)] == prev_term)
            # prev below the trim point: the snapshot attests the whole
            # committed prefix, treat as matching (no entry to store)
            log_ok = match_here | (prev_idx < base)
            ok = ~stale & log_ok
            has_entry = entry_term > 0
            slot_rel = prev_rel + 1
            can_store = (slot_rel >= 1) & (slot_rel <= cap)
            slot = jnp.clip(slot_rel, 0, cap)
            existing_matches = (stored_last >= prev_idx + 1) & can_store & (
                nodes.log_term[node, slot] == entry_term
            )
            append = ok & has_entry & can_store
            new_last = jnp.where(
                append,
                jnp.where(
                    existing_matches,
                    jnp.maximum(stored_last, prev_idx + 1),
                    prev_idx + 1,
                ),
                stored_last,
            )
            # Raft §5.3 commit bound: cap at the last index THIS AE
            # verified, never the follower's own tail
            last_new = prev_idx + jnp.where(append, 1, 0)
            commit_cap = jnp.minimum(last_new, new_last)
            nodes = update_node(
                nodes, node,
                log_term=jnp.where(
                    append, set_at(nodes.log_term[node], slot, entry_term), nodes.log_term[node]
                ),
                log_len=new_last - base,
                commit=jnp.where(
                    ok,
                    jnp.maximum(nodes.commit[node], jnp.minimum(leader_commit, commit_cap)),
                    nodes.commit[node],
                ),
            )
            midx = jnp.where(
                append, prev_idx + 1,
                jnp.where(prev_idx < base, base, prev_idx),
            )
            aer = self._pay(M_AER, nodes.term[node], ok.astype(jnp.int32), midx)
            outbox = send_if(outbox, 0, jnp.bool_(True), src, aer)
            return nodes, outbox

        def aer_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, success, midx = payload[1], payload[2], payload[3]
            nodes = step_down(nodes, t, jnp.bool_(False))
            is_lead = (nodes.role[node] == LEADER) & (t == nodes.term[node])
            good = is_lead & (success == 1)
            new_match = jnp.maximum(nodes.match_idx[node, src], midx)
            nodes = nodes.replace(
                match_idx=jnp.where(
                    good, set2d(nodes.match_idx, node, src, new_match), nodes.match_idx
                ),
                next_idx=jnp.where(
                    good,
                    set2d(nodes.next_idx, node, src, new_match + 1),
                    jnp.where(
                        is_lead & (success == 0),
                        set2d(
                            nodes.next_idx, node, src,
                            jnp.maximum(nodes.next_idx[node, src] - 1, 1),
                        ),
                        nodes.next_idx,
                    ),
                ),
            )
            # advance commit: highest STORED idx replicated on a
            # majority with a current-term entry (Raft §5.4.2); indices
            # below base were committed before they compacted
            srel = jnp.arange(cap + 1, dtype=jnp.int32)
            abs_idx = nodes.base[node] + srel
            replicated = nodes.match_idx[node][None, :] >= abs_idx[:, None]
            cnt = jnp.sum(replicated, axis=1)
            cur_term_entry = nodes.log_term[node] == nodes.term[node]
            committable = (
                (cnt >= self.majority) & cur_term_entry
                & (srel >= 1) & (srel <= nodes.log_len[node])
            )
            best = jnp.max(jnp.where(committable, abs_idx, 0))
            nodes = update_node(
                nodes, node,
                commit=jnp.where(good, jnp.maximum(nodes.commit[node], best), nodes.commit[node]),
            )
            return nodes, outbox

        def is_branch(args):
            nodes, = args
            outbox = self.empty_outbox()
            t, s_idx, s_term = payload[1], payload[2], payload[3]
            stale = t < nodes.term[node]
            nodes = step_down(nodes, t, jnp.bool_(True))
            nodes = update_node(
                nodes, node,
                elec_deadline=jnp.where(
                    ~stale, now_us + self._rand_timeout(rand_u32[0]), nodes.elec_deadline[node]
                ),
            )
            base = nodes.base[node]
            apply = ~stale & (s_idx > nodes.commit[node])
            rel = s_idx - base
            have_boundary = (
                (rel >= 0) & (s_idx <= base + nodes.log_len[node])
                & (nodes.log_term[node, jnp.clip(rel, 0, cap)] == s_term)
            )
            retain = apply & have_boundary  # keep the suffix past s_idx
            shift = jnp.where(retain, jnp.clip(rel, 0, cap), 0)
            srel = jnp.arange(cap + 1, dtype=jnp.int32)
            row = nodes.log_term[node]
            shifted = jnp.where(srel + shift <= cap, row[jnp.clip(srel + shift, 0, cap)], 0)
            discard_row = jnp.where(srel == 0, s_term, 0)
            new_row = jnp.where(apply, jnp.where(retain, shifted, discard_row), row)
            new_len = jnp.where(
                apply,
                jnp.where(retain, base + nodes.log_len[node] - s_idx, 0),
                nodes.log_len[node],
            )
            nodes = update_node(
                nodes, node,
                log_term=new_row,
                log_len=new_len,
                base=jnp.where(apply, s_idx, base),
                snap_idx=jnp.where(apply, s_idx, nodes.snap_idx[node]),
                snap_term=jnp.where(apply, s_term, nodes.snap_term[node]),
                commit=jnp.where(apply, jnp.maximum(nodes.commit[node], s_idx), nodes.commit[node]),
            )
            aer = self._pay(
                M_AER, nodes.term[node], (~stale).astype(jnp.int32), s_idx
            )
            outbox = send_if(outbox, 0, jnp.bool_(True), src, aer)
            return nodes, outbox

        return lax.switch(
            branch, [rv_branch, vote_branch, ae_branch, aer_branch, is_branch], (nodes,)
        )

    # -- invariants / results ------------------------------------------------

    def invariant(self, nodes: RaftCompactState, now_us):
        n, cap = self.NUM_NODES, self.log_capacity
        is_lead = nodes.role == LEADER
        same_term = nodes.term[:, None] == nodes.term[None, :]
        both_lead = is_lead[:, None] & is_lead[None, :] & ~jnp.eye(n, dtype=bool)
        elec_viol = jnp.any(both_lead & same_term)

        # (a) committed stored windows agree pairwise: node i's slot s
        # holds absolute position base_i+s; find that position in j's
        # frame and compare terms wherever both store AND both committed
        # it. Slot 0 (the boundary term at base) participates — honest
        # compaction writes it from a committed entry.
        s = jnp.arange(cap + 1, dtype=jnp.int32)
        abs_i = nodes.base[:, None] + s[None, :]  # [N, S]
        known_i = (s[None, :] <= nodes.log_len[:, None]) & (abs_i >= 1)
        committed_i = known_i & (abs_i <= nodes.commit[:, None])
        rel_j = abs_i[:, None, :] - nodes.base[None, :, None]  # [N, N, S]
        known_j = (rel_j >= 0) & (rel_j <= nodes.log_len[None, :, None])
        committed_j = known_j & (abs_i[:, None, :] <= nodes.commit[None, :, None])
        tj = jnp.take_along_axis(
            jnp.broadcast_to(nodes.log_term[None, :, :], (n, n, cap + 1)),
            jnp.clip(rel_j, 0, cap),
            axis=2,
        )
        ti = jnp.broadcast_to(nodes.log_term[:, None, :], (n, n, cap + 1))
        log_viol = jnp.any(committed_i[:, None, :] & committed_j & (ti != tj))

        # (b) snapshot coverage: a committed watermark must stand on
        # attested storage — positions in (snap_idx, base] are neither
        # stored nor snapshot-covered, so committing past snap_idx with
        # base > snap_idx is data loss (the torn-snapshot signature;
        # honest nodes keep snap_idx == base and can never trip this)
        cover_viol = jnp.any(
            (nodes.base > nodes.snap_idx) & (nodes.commit > nodes.snap_idx)
        )

        ok = ~(elec_viol | log_viol | cover_viol)
        code = jnp.where(
            elec_viol, ELECTION_SAFETY,
            jnp.where(log_viol | cover_viol, LOG_MATCHING, 0),
        )
        return ok, code.astype(jnp.int32)

    def is_done(self, nodes: RaftCompactState, now_us):
        return jnp.all(nodes.commit >= self.target_commit)

    def summary(self, nodes: RaftCompactState):
        return {
            "max_term": jnp.max(nodes.term),
            "max_commit": jnp.max(nodes.commit),
            "min_commit": jnp.min(nodes.commit),
            "num_leaders": jnp.sum((nodes.role == LEADER).astype(jnp.int32)),
            "max_base": jnp.max(nodes.base),
        }

    def coverage_projection(self, nodes: RaftCompactState, now_us):
        """Raft's cluster-shape axes (term bucket / leaders / commit
        divergence) plus the compaction axes: how far trim boundaries
        diverge across nodes and how many snapshot generations the
        cluster is into — the interleavings that only exist because the
        log has a moving floor."""
        term_b = jnp.clip(jnp.max(nodes.term), 0, 7)  # phase bits
        leaders = jnp.clip(jnp.sum((nodes.role == LEADER).astype(jnp.int32)), 0, 3)
        commit_div = jnp.clip(jnp.max(nodes.commit) - jnp.min(nodes.commit), 0, 7)
        base_div = jnp.clip(jnp.max(nodes.base) - jnp.min(nodes.base), 0, 7)
        snap_gen = jnp.clip(jnp.max(nodes.base) // self.compact_lag, 0, 3)
        return (
            term_b
            | (leaders << 3)
            | (commit_div << 5)
            | (base_div << 8)
            | (snap_gen << 11)
        ).astype(jnp.uint32)


class TornSnapshotRaftCompact(RaftCompactMachine):
    """Seeded storage bug (`demo-tornsnapshot-raft`): the snapshot file
    write is never fsynced, so a crash can keep the trimmed log ring
    (atomic) while LOSING the snapshot covering everything behind it.
    Only a torn restart (`FaultPlan.allow_torn`) can surface it — plain
    kill/restart and even strict amnesia honor durable_spec, under which
    the snapshot metadata survives. The first re-commit after the torn
    restart trips the compaction-aware LogMatching checker (code 102):
    the node's watermark stands on positions neither stored nor
    attested."""

    def torn_spec(self) -> RaftCompactState:
        return RaftCompactState(
            term=TORN_ATOMIC, voted_for=TORN_ATOMIC,
            log_term=TORN_ATOMIC, log_len=TORN_ATOMIC, base=TORN_ATOMIC,
            snap_idx=TORN_LOSE, snap_term=TORN_LOSE,
            epoch=TORN_ATOMIC,
            role=TORN_ATOMIC, votes=TORN_ATOMIC, elec_deadline=TORN_ATOMIC,
            commit=TORN_ATOMIC, next_idx=TORN_ATOMIC, match_idx=TORN_ATOMIC,
        )
