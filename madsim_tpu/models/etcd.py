"""Leased-KV / leader-election machine — the service-class (L5) engine
workload, batched.

Models the madsim-etcd-client scenario family
(`/root/reference/madsim-etcd-client/tests/test.rs`: campaign/leader/
lease grant/keepalive over a SimServer with an MVCC store,
`src/service.rs:191+` leases `:25-35,:398,:466`, elections `:487+`) as a
TPU-engine `Machine`, so etcd-class workloads explore thousands of seeds
per batch instead of one-at-a-time on the host engine.

Topology: node 0 is the etcd-like server (durable MVCC revision counter,
per-client leases, one election); nodes 1..N-1 are clients that grant a
lease, campaign for leadership, keep their lease alive while leading,
and write revisioned values.

Lease-safety discipline (why the invariant is exact, not probabilistic):
the server expires a lease TTL after the last keepalive *receipt*; a
client stops believing in its leadership TTL after the last acked
keepalive *send* (requests echo their send time). Since receipt >= send
under non-negative network latency, a client's local deadline never
exceeds the server's expiry — so at every instant:

    believes_leader(c)  ==>  server.cur_owner == c
                             and server.cur_gen == c.believed_gen

Violations (code 120 LEASE_SAFETY) catch exactly the etcd bug classes
the reference's tests exist for: double-granted elections (campaign
ignoring a live owner), lease resurrection (keepalive reviving an
expired lease), and a server that loses its state on restart (the
durable store is what makes the honest machine safe — see
`VolatileEtcd` in tests/test_engine_etcd.py).

Timer ids are epoch-encoded like models/raft.py (a restart bumps the
node's epoch at BOOT) so kill/restart cannot double-arm tick chains.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import (
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_timer_if,
    update_node,
)

SERVER = 0

# message types (payload[0])
M_GRANT = 1       # client->server: grant/refresh my lease   [m, c, send_us]
M_GRANT_OK = 2    # server->client                            [m, c, send_us]
M_CAMPAIGN = 3    # client->server: try to become leader      [m, c, send_us]
M_WON = 4         # server->client: you own generation g      [m, c, send_us, g]
M_LOST = 5        # server->client: someone else leads
M_NO_LEASE = 6    # server->client: grant a lease first
M_KA = 7          # client->server: keepalive                 [m, c, send_us]
M_KA_OK = 8       # server->client: lease extended            [m, c, send_us]
M_KA_ERR = 9      # server->client: lease expired — stand down
M_PUT = 10        # leader->server: revisioned write          [m, c, send_us, g]
M_PUT_OK = 11     # server->client                            [m, c, send_us, rev]

# timer bases (tid = base + 4*epoch; engine-raw 0 == BOOT)
T_BOOT = 0
T_TICK = 1

LEASE_SAFETY = 120  # invariant code: two believed leaderships / stale gen

TTL_US = 300_000
TICK_US = 100_000


@struct.dataclass
class EtcdState:
    # --- server-owned (rows semantically owned by node 0; durable like
    # etcd's raft-backed store — kept across server restart) -------------
    srv_rev: jax.Array            # int32[N] MVCC revision (entry 0)
    srv_gen: jax.Array            # int32[N] election generation (entry 0)
    srv_owner: jax.Array          # int32[N] current leader client, -1 (entry 0)
    srv_lease_expiry: jax.Array   # int32[N] per-CLIENT lease expiry us (0 = none)
    # --- client-owned (volatile: reset on that client's restart) --------
    cl_has_lease: jax.Array       # bool[N] grant acked
    cl_deadline: jax.Array        # int32[N] local lease deadline (send-based)
    cl_leader: jax.Array          # bool[N] believes it leads...
    cl_gen: jax.Array             # int32[N] ...this generation
    cl_writes: jax.Array          # int32[N] acked writes
    cl_max_rev: jax.Array         # int32[N] highest revision observed
    # --- bookkeeping ----------------------------------------------------
    epoch: jax.Array              # int32[N] timer epoch (persistent)
    violated: jax.Array           # bool[N] server-detected safety breach


class EtcdMachine(Machine):
    """Honest leased-KV server + campaigning clients."""

    PAYLOAD_WIDTH = 5
    MAX_MSGS = 2   # leader tick sends keepalive + write
    MAX_TIMERS = 1

    # knobs subclassed by the buggy variants in tests
    CHECK_OWNER_ON_CAMPAIGN = True   # False: double-grant bug
    REVIVE_EXPIRED_LEASES = False    # True: resurrection bug (server-side)
    EXTEND_DEADLINE_ON_WON = False   # True: client lease-discipline bug

    def __init__(self, num_nodes: int = 4, target_gens: int = 3, target_writes: int = 10):
        self.NUM_NODES = num_nodes
        self.target_gens = target_gens
        self.target_writes = target_writes

    def init(self, rng_key) -> EtcdState:
        n = self.NUM_NODES
        z = jnp.zeros((n,), jnp.int32)
        f = jnp.zeros((n,), bool)
        return EtcdState(
            srv_rev=z, srv_gen=z, srv_owner=jnp.full((n,), -1, jnp.int32),
            srv_lease_expiry=z,
            cl_has_lease=f, cl_deadline=z, cl_leader=f, cl_gen=z,
            cl_writes=z, cl_max_rev=z,
            epoch=z, violated=f,
        )

    def init_node(self, nodes: EtcdState, i, rng_key) -> EtcdState:
        """Restart semantics: the server's store is durable (etcd persists
        revisions, leases and the election through restart —
        service.rs state lives behind raft); a client loses its session
        state. Epochs always survive (timer-chain bookkeeping)."""
        return self.restart_if(nodes, i, jnp.bool_(True), rng_key)

    def durable_spec(self) -> EtcdState:
        """Crash-with-amnesia contract: the server store (revision /
        generation / election / leases) is raft-backed and durable,
        client session state is volatile; epochs (timer bookkeeping)
        and the ghost violation flag survive."""
        return EtcdState(
            srv_rev=True, srv_gen=True, srv_owner=True,
            srv_lease_expiry=True,
            cl_has_lease=False, cl_deadline=False, cl_leader=False,
            cl_gen=False, cl_writes=False, cl_max_rev=False,
            epoch=True, violated=True,
        )

    def restart_if(self, nodes: EtcdState, i, cond, rng_key) -> EtcdState:
        n = self.NUM_NODES
        row = (jnp.arange(n) == i) & cond
        is_client = i != SERVER
        reset_i32 = lambda arr: jnp.where(row & is_client, 0, arr)  # noqa: E731
        reset_b = lambda arr: jnp.where(row & is_client, False, arr)  # noqa: E731
        return nodes.replace(
            cl_has_lease=reset_b(nodes.cl_has_lease),
            cl_deadline=reset_i32(nodes.cl_deadline),
            cl_leader=reset_b(nodes.cl_leader),
            cl_gen=reset_i32(nodes.cl_gen),
            cl_writes=reset_i32(nodes.cl_writes),
            cl_max_rev=reset_i32(nodes.cl_max_rev),
        )

    # -- helpers --------------------------------------------------------------

    def _tid(self, nodes: EtcdState, node, base):
        return jnp.int32(base) + 4 * nodes.epoch[node]

    def _lazy_expire(self, nodes: EtcdState, cond, now_us):
        """Depose the current leader if its lease lapsed (the tick task of
        service.rs:25-35 done lazily on server events — same observable
        behavior, no periodic server timer needed). `cond` gates the
        whole update (only server events expire)."""
        owner = nodes.srv_owner[SERVER]
        has_owner = owner >= 0
        safe_owner = jnp.maximum(owner, 0)
        lapsed = cond & has_owner & (nodes.srv_lease_expiry[safe_owner] <= now_us)
        return update_node(
            nodes, SERVER,
            srv_owner=jnp.where(lapsed, -1, owner),
            # key deletion is a new revision (MVCC: deletes are writes)
            srv_rev=nodes.srv_rev[SERVER] + jnp.where(lapsed, 1, 0),
        )

    # -- timers ---------------------------------------------------------------

    def on_timer(self, nodes: EtcdState, node, timer_id, now_us, rand_u32) -> Tuple[EtcdState, Outbox]:
        outbox = self.empty_outbox()
        is_boot = timer_id == T_BOOT
        t_epoch = timer_id // 4
        live = is_boot | (t_epoch == nodes.epoch[node])
        is_client = node != SERVER

        # BOOT: bump epoch, clients arm their tick chain
        new_epoch = jnp.where(is_boot & live, nodes.epoch[node] + 1, nodes.epoch[node])
        nodes = update_node(nodes, node, epoch=new_epoch)
        base = timer_id - 4 * t_epoch
        is_tick = live & ~is_boot & (base == T_TICK) & is_client

        # jittered tick keeps client phases decorrelated across a lane
        jitter = (rand_u32[0] % jnp.uint32(TICK_US // 2)).astype(jnp.int32)
        outbox = set_timer_if(
            outbox, 0, (is_boot | is_tick) & is_client,
            TICK_US + jitter, self._tid(nodes, node, T_TICK),
        )

        # local lease-safety discipline: stop believing past the deadline
        still_believes = nodes.cl_leader[node] & (now_us < nodes.cl_deadline[node])
        nodes = update_node(nodes, node, cl_leader=still_believes)

        # one request per tick (at-least-once; server ops are idempotent):
        #   no lease -> GRANT;  lease, not leader -> CAMPAIGN;
        #   leader   -> KA (+ a revisioned PUT in slot 1)
        want_grant = is_tick & ~nodes.cl_has_lease[node]
        want_campaign = is_tick & nodes.cl_has_lease[node] & ~still_believes
        want_ka = is_tick & still_believes

        pay = lambda m, *rest: make_payload(self.PAYLOAD_WIDTH, m, node, now_us, *rest)  # noqa: E731
        outbox = send_if(outbox, 0, want_grant, SERVER, pay(M_GRANT))
        outbox = send_if(outbox, 0, want_campaign, SERVER, pay(M_CAMPAIGN))
        outbox = send_if(outbox, 0, want_ka, SERVER, pay(M_KA))
        outbox = send_if(outbox, 1, want_ka, SERVER, pay(M_PUT, nodes.cl_gen[node]))
        return nodes, outbox

    # -- messages -------------------------------------------------------------

    def on_message(self, nodes: EtcdState, node, src, payload, now_us, rand_u32) -> Tuple[EtcdState, Outbox]:
        outbox = self.empty_outbox()
        mtype, client, send_us = payload[0], payload[1], payload[2]
        is_server = node == SERVER

        # ---------------- server ----------------
        srv = is_server
        nodes = self._lazy_expire(nodes, srv, now_us)

        c = jnp.clip(client, 0, self.NUM_NODES - 1)
        lease_live = nodes.srv_lease_expiry[c] > now_us

        # GRANT: (re)issue the client's lease, receipt-based expiry
        is_grant = srv & (mtype == M_GRANT)
        nodes = nodes.replace(
            srv_lease_expiry=jnp.where(
                (jnp.arange(self.NUM_NODES) == c) & is_grant,
                now_us + TTL_US,
                nodes.srv_lease_expiry,
            )
        )
        outbox = send_if(
            outbox, 0, is_grant, c,
            make_payload(self.PAYLOAD_WIDTH, M_GRANT_OK, c, send_us),
        )

        # CAMPAIGN: win iff no live owner (honest) and caller's lease lives
        is_camp = srv & (mtype == M_CAMPAIGN)
        owner = nodes.srv_owner[SERVER]
        already_owner = owner == c
        seat_free = owner < 0 if self.CHECK_OWNER_ON_CAMPAIGN else jnp.bool_(True)
        win_new = is_camp & lease_live & seat_free & ~already_owner
        # double-grant detection lives at the SERVER too: stealing a seat
        # whose owner still holds a live lease is the safety breach itself
        stolen = win_new & (owner >= 0)
        new_gen = nodes.srv_gen[SERVER] + jnp.where(win_new, 1, 0)
        nodes = update_node(
            nodes, SERVER,
            srv_gen=new_gen,
            srv_owner=jnp.where(win_new, c, owner),
            srv_rev=nodes.srv_rev[SERVER] + jnp.where(win_new, 1, 0),  # key create
            violated=nodes.violated[SERVER] | stolen,
        )
        won = is_camp & lease_live & (already_owner | win_new)
        outbox = send_if(
            outbox, 0, won, c,
            make_payload(self.PAYLOAD_WIDTH, M_WON, c, send_us, nodes.srv_gen[SERVER]),
        )
        outbox = send_if(
            outbox, 0, is_camp & lease_live & ~won, c,
            make_payload(self.PAYLOAD_WIDTH, M_LOST, c, send_us),
        )
        outbox = send_if(
            outbox, 0, is_camp & ~lease_live, c,
            make_payload(self.PAYLOAD_WIDTH, M_NO_LEASE, c, send_us),
        )

        # KEEPALIVE: extend live leases; expired ones answer KA_ERR
        # (REVIVE_EXPIRED_LEASES models the resurrection bug)
        is_ka = srv & (mtype == M_KA)
        may_extend = lease_live | jnp.bool_(self.REVIVE_EXPIRED_LEASES)
        nodes = nodes.replace(
            srv_lease_expiry=jnp.where(
                (jnp.arange(self.NUM_NODES) == c) & is_ka & may_extend,
                now_us + TTL_US,
                nodes.srv_lease_expiry,
            )
        )
        outbox = send_if(
            outbox, 0, is_ka & may_extend, c,
            make_payload(self.PAYLOAD_WIDTH, M_KA_OK, c, send_us),
        )
        outbox = send_if(
            outbox, 0, is_ka & ~may_extend, c,
            make_payload(self.PAYLOAD_WIDTH, M_KA_ERR, c, send_us),
        )

        # PUT: a revisioned write, accepted only from the current leader
        # at the current generation
        is_put = srv & (mtype == M_PUT)
        put_gen = payload[3]
        accept = is_put & (nodes.srv_owner[SERVER] == c) & (put_gen == nodes.srv_gen[SERVER])
        put_rev = nodes.srv_rev[SERVER] + jnp.where(accept, 1, 0)
        nodes = update_node(nodes, SERVER, srv_rev=put_rev)
        outbox = send_if(
            outbox, 0, accept, c,
            make_payload(self.PAYLOAD_WIDTH, M_PUT_OK, c, send_us, put_rev),
        )

        # ---------------- client ----------------
        cl = node != SERVER
        # lease liveness discipline first (see on_timer)
        believes = nodes.cl_leader[node] & (now_us < nodes.cl_deadline[node])

        got_grant = cl & (mtype == M_GRANT_OK)
        got_won = cl & (mtype == M_WON)
        got_ka_ok = cl & (mtype == M_KA_OK)
        got_ka_err = cl & (mtype == M_KA_ERR)
        got_no_lease = cl & (mtype == M_NO_LEASE)
        got_put_ok = cl & (mtype == M_PUT_OK)

        # send-based local deadline: the ack proves the server extended the
        # lease no earlier than send_us, so send_us + TTL is a safe lower
        # bound. ONLY lease operations (grant/keepalive) extend it — an
        # M_WON must not: campaigning doesn't refresh the lease server-side,
        # so extending on it lets belief outlive the server's expiry (a real
        # window this machine's own invariant caught during development —
        # kept as the EXTEND_DEADLINE_ON_WON bug variant).
        extend = got_grant | got_ka_ok | (
            got_won if self.EXTEND_DEADLINE_ON_WON else jnp.bool_(False)
        )
        new_deadline = jnp.maximum(nodes.cl_deadline[node], send_us + TTL_US)
        nodes = update_node(
            nodes, node,
            cl_has_lease=jnp.where(
                got_grant, True,
                jnp.where(got_ka_err | got_no_lease, False, nodes.cl_has_lease[node]),
            ),
            cl_deadline=jnp.where(extend, new_deadline, nodes.cl_deadline[node]),
            cl_leader=jnp.where(
                got_won, True,
                jnp.where(got_ka_err, False, believes),
            ),
            cl_gen=jnp.where(got_won, payload[3], nodes.cl_gen[node]),
            cl_writes=nodes.cl_writes[node] + jnp.where(got_put_ok, 1, 0),
            cl_max_rev=jnp.where(
                got_put_ok, jnp.maximum(nodes.cl_max_rev[node], payload[3]), nodes.cl_max_rev[node]
            ),
        )
        return nodes, outbox

    # -- invariants / termination ---------------------------------------------

    def invariant(self, nodes: EtcdState, now_us):
        """Lease safety: every believed leadership is the server's current
        one, and the server never observed a double grant."""
        idx = jnp.arange(self.NUM_NODES)
        believes = nodes.cl_leader & (now_us < nodes.cl_deadline) & (idx != SERVER)
        owner_ok = believes & (nodes.srv_owner[SERVER] == idx) & (nodes.srv_gen[SERVER] == nodes.cl_gen)
        bad = jnp.any(believes & ~owner_ok) | nodes.violated[SERVER]
        return ~bad, jnp.where(bad, LEASE_SAFETY, 0).astype(jnp.int32)

    def is_done(self, nodes: EtcdState, now_us):
        return (nodes.srv_gen[SERVER] >= self.target_gens) & (
            jnp.sum(nodes.cl_writes) >= self.target_writes
        )

    def summary(self, nodes: EtcdState):
        return {
            "generations": nodes.srv_gen[SERVER],
            "revision": nodes.srv_rev[SERVER],
            "writes_acked": jnp.sum(nodes.cl_writes),
        }

    def coverage_projection(self, nodes: EtcdState, now_us):
        """Scenario projection: election generation bucket (phase) x
        ownership/lease occupancy x believed-leader count x write
        progress — the lease-safety interleaving axes (handovers seen,
        split brain pressure, workload depth)."""
        gen_b = jnp.clip(nodes.srv_gen[SERVER], 0, 7)
        owner_set = (nodes.srv_owner[SERVER] >= 0).astype(jnp.int32)
        believers = jnp.clip(jnp.sum(nodes.cl_leader.astype(jnp.int32)), 0, 3)
        leases = jnp.clip(jnp.sum(nodes.cl_has_lease.astype(jnp.int32)), 0, 3)
        writes_b = jnp.clip(jnp.max(nodes.cl_writes), 0, 7)
        return (
            gen_b
            | (owner_set << 3)
            | (believers << 4)
            | (leases << 6)
            | (writes_b << 8)
        ).astype(jnp.uint32)
