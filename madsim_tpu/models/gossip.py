"""Quorum-committed epidemic broadcast at gossip scale — the round-5
larger-n machine (VERDICT r4 directive 6: every previous machine is 4-5
nodes; this one runs 16-60 nodes and exercises the two-word group-mask
path lifted in engine/core.py).

Protocol: R rumors, rumor r originated by node r % N. The origin seeds
its rumor at a staggered inject time and every node runs an anti-entropy
tick (push one random held rumor to one random peer). First receipt of a
rumor stores it, acks the ORIGIN, and forwards to FANOUT random peers
with a hop budget; duplicate receipts re-ack (at-least-once acks — the
duplicate-ack source the counting bug mishandles). The origin commits
the rumor once DISTINCT ackers reach a majority quorum.

Invariant (checked on-device after every event):
  * COMMIT_BELOW_QUORUM (160) — a committed rumor is held by fewer than
    quorum nodes. The rumor store is durable (restart keeps it), so
    holder counts are monotone and the check is sound: an honest origin
    commits only on distinct acks, and an ack implies a stored copy.

Seeded bug variant:
  * DUP_ACK_COUNT — the origin counts every ack instead of deduping by
    acker (the classic quorum-counting bug: retransmitted/duplicate
    acks inflate the tally), committing below quorum; found by any
    vocabulary that makes duplicate acks (partitions recover + re-ack,
    storms force re-receipt, delay spikes reorder), and caught at the
    exact commit event by the ghost holder count.

Scale notes (the SoA design's stress points this machine probes):
queue capacity must absorb fanout bursts (FANOUT forwards + ack per
receipt at 33+ nodes), and group-fault masks need > 30 bits — the
two-word encoding (payload args 1+2).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import (
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_timer_if,
    update_node,
)

M_RUMOR = 1
M_ACK = 2

COMMIT_BELOW_QUORUM = 160

GOSSIP_US = 50_000   # anti-entropy tick
INJECT_US = 150_000  # stagger between rumor injections
HOP_BUDGET = 4       # forward TTL on first receipt


@struct.dataclass
class GossipState:
    holds: jax.Array      # bool[N, R] durable rumor store
    committed: jax.Array  # bool[N, R] origin's commit flag (row = origin)
    ack_cnt: jax.Array    # int32[N, R] origin's ack tally (the bug target)
    acked_by: jax.Array   # bool[N, R, N] origin's distinct-acker table
    epoch: jax.Array      # int32[N] timer epoch


class GossipMachine(Machine):
    """N-node quorum broadcast (N defaults to 33 — past the old mask cap)."""

    PAYLOAD_WIDTH = 4
    MAX_MSGS = 4  # FANOUT forwards + 1 ack
    MAX_TIMERS = 1
    FANOUT = 3

    # seeded bug variant (module docstring)
    DUP_ACK_COUNT = False

    def __init__(self, num_nodes: int = 33, rumors: int = 6):
        self.NUM_NODES = num_nodes
        self.R = rumors
        self.QUORUM = num_nodes // 2 + 1

    # -- state ----------------------------------------------------------------

    def init(self, rng_key) -> GossipState:
        n, r = self.NUM_NODES, self.R
        return GossipState(
            holds=jnp.zeros((n, r), bool),
            committed=jnp.zeros((n, r), bool),
            ack_cnt=jnp.zeros((n, r), jnp.int32),
            acked_by=jnp.zeros((n, r, n), bool),
            epoch=jnp.zeros((n,), jnp.int32),
        )

    def restart_if(self, nodes: GossipState, i, cond, rng_key) -> GossipState:
        # everything durable (the rumor store persists — required for the
        # quorum invariant's monotone holder count); restart re-fires
        # BOOT, which bumps the epoch and re-arms the gossip tick
        return nodes

    def _origin(self, r):
        return jnp.mod(r, jnp.int32(self.NUM_NODES))

    # -- timers ---------------------------------------------------------------

    def on_timer(self, nodes: GossipState, node, timer_id, now_us, rand_u32) -> Tuple[GossipState, Outbox]:
        outbox = self.empty_outbox()
        is_boot = timer_id == 0
        t_epoch = (timer_id - 1) // 2
        live = is_boot | (t_epoch == nodes.epoch[node])

        new_epoch = jnp.where(is_boot & live, nodes.epoch[node] + 1, nodes.epoch[node])
        nodes = update_node(nodes, node, epoch=new_epoch)
        tid = jnp.int32(1) + 2 * nodes.epoch[node]

        n, R = self.NUM_NODES, self.R

        # inject: the earliest owned, due, not-yet-held rumor (origin
        # stores + fans out; its own copy counts toward quorum)
        rumors = jnp.arange(R, dtype=jnp.int32)
        owned = self._origin(rumors) == node
        due = now_us >= rumors * INJECT_US
        pending = owned & due & ~nodes.holds[node]
        inject = live & jnp.any(pending)
        rumor_inj = jnp.argmax(pending).astype(jnp.int32)

        # anti-entropy: push one random held rumor to one random peer
        held = nodes.holds[node]
        n_held = held.sum(dtype=jnp.int32)
        pick_rank = (
            rand_u32[0] % jnp.maximum(n_held, 1).astype(jnp.uint32)
        ).astype(jnp.int32)
        ranks = jnp.cumsum(held.astype(jnp.int32)) - 1
        rumor_push = jnp.argmax(held & (ranks == pick_rank)).astype(jnp.int32)
        push = live & ~inject & (n_held > 0)

        peer_off = 1 + (rand_u32[1] % jnp.uint32(n - 1)).astype(jnp.int32)
        peer = jnp.mod(node + peer_off, n)

        rumor_out = jnp.where(inject, rumor_inj, rumor_push)
        hop = jnp.where(inject, HOP_BUDGET, 1)
        inj_row = (
            (jnp.arange(n) == node)[:, None]
            & (jnp.arange(R) == rumor_inj)[None, :]
            & inject
        )
        # the origin's own stored copy is the tally's first member —
        # recorded in the acker table so a self-ack cannot double-count
        inj_cell = inj_row[:, :, None] & (jnp.arange(n) == node)[None, None, :]
        nodes = nodes.replace(
            holds=jnp.where(inj_row, True, nodes.holds),
            ack_cnt=jnp.where(inj_row, 1, nodes.ack_cnt),
            acked_by=jnp.where(inj_cell, True, nodes.acked_by),
        )
        # inject fans out to FANOUT peers; a plain tick pushes to one
        for s in range(self.FANOUT):
            mix = rand_u32[2] + jnp.uint32((s * 0x9E3779B9) & 0xFFFFFFFF)
            off = 1 + (mix % jnp.uint32(n - 1)).astype(jnp.int32)
            dst = jnp.mod(node + off, n)
            want = inject if s > 0 else (inject | push)
            dst = jnp.where(inject, dst, peer)
            outbox = send_if(
                outbox, s, want, dst,
                make_payload(self.PAYLOAD_WIDTH, M_RUMOR, rumor_out, hop),
            )
        jitter = (rand_u32[3] % jnp.uint32(GOSSIP_US // 4)).astype(jnp.int32)
        outbox = set_timer_if(
            outbox, 0, live, jnp.int32(GOSSIP_US) + jitter, tid
        )
        return nodes, outbox

    # -- messages -------------------------------------------------------------

    def on_message(self, nodes: GossipState, node, src, payload, now_us, rand_u32) -> Tuple[GossipState, Outbox]:
        outbox = self.empty_outbox()
        mtype, rumor, hop = payload[0], payload[1], payload[2]
        n, R = self.NUM_NODES, self.R
        rumor_c = jnp.clip(rumor, 0, R - 1)

        # ---- rumor receipt: store on first sight, ALWAYS ack the origin
        is_rumor = mtype == M_RUMOR
        first = is_rumor & ~nodes.holds[node, rumor_c]
        nodes = nodes.replace(
            holds=jnp.where(
                ((jnp.arange(n) == node)[:, None]
                 & (jnp.arange(R) == rumor_c)[None, :] & is_rumor),
                True, nodes.holds,
            )
        )
        origin = self._origin(rumor_c)
        outbox = send_if(
            outbox, 3, is_rumor, origin,
            make_payload(self.PAYLOAD_WIDTH, M_ACK, rumor_c, 0),
        )
        # forward on first receipt while hop budget remains
        fwd = first & (hop > 0)
        for s in range(self.FANOUT):
            off = 1 + ((rand_u32[s] ) % jnp.uint32(n - 1)).astype(jnp.int32)
            dst = jnp.mod(node + off, n)
            outbox = send_if(
                outbox, s, fwd, dst,
                make_payload(self.PAYLOAD_WIDTH, M_RUMOR, rumor_c, hop - 1),
            )

        # ---- ack receipt at the origin: dedup by acker, tally, commit
        is_ack = (mtype == M_ACK) & (self._origin(rumor_c) == node)
        known = nodes.acked_by[node, rumor_c, jnp.clip(src, 0, n - 1)]
        count_it = is_ack & (jnp.bool_(self.DUP_ACK_COUNT) | ~known)
        row = (jnp.arange(n) == node)[:, None] & (jnp.arange(R) == rumor_c)[None, :]
        cell = row[:, :, None] & (jnp.arange(n) == src)[None, None, :]
        new_cnt = nodes.ack_cnt[node, rumor_c] + 1
        # the tally already includes the origin's own copy (set at inject)
        commit_now = count_it & (new_cnt >= self.QUORUM)
        nodes = nodes.replace(
            acked_by=jnp.where(cell & is_ack, True, nodes.acked_by),
            ack_cnt=jnp.where(row & count_it, new_cnt, nodes.ack_cnt),
            committed=jnp.where(row & commit_now, True, nodes.committed),
        )
        return nodes, outbox

    # -- invariants / results --------------------------------------------------

    def invariant(self, nodes: GossipState, now_us):
        # a committed rumor must be held by >= quorum nodes, NOW (holds
        # are durable, so the count is monotone and the check is exact
        # at the commit event)
        holders = nodes.holds.sum(axis=0)  # [R] global truth
        origins = self._origin(jnp.arange(self.R, dtype=jnp.int32))
        committed = nodes.committed[origins, jnp.arange(self.R)]
        below = jnp.any(committed & (holders < self.QUORUM))
        return ~below, jnp.where(below, COMMIT_BELOW_QUORUM, 0).astype(jnp.int32)

    def is_done(self, nodes: GossipState, now_us):
        origins = self._origin(jnp.arange(self.R, dtype=jnp.int32))
        all_committed = jnp.all(nodes.committed[origins, jnp.arange(self.R)])
        return all_committed & jnp.all(nodes.holds)

    def summary(self, nodes: GossipState):
        origins = self._origin(jnp.arange(self.R, dtype=jnp.int32))
        return {
            "committed": nodes.committed[origins, jnp.arange(self.R)].sum(
                dtype=jnp.int32
            ),
            "coverage": nodes.holds.sum(dtype=jnp.int32),
            "acks": nodes.ack_cnt[origins, jnp.arange(self.R)].sum(dtype=jnp.int32),
        }
