"""KV-store consistency machine — the etcd-class engine workload.

BASELINE.json config: "madsim-etcd-client KV linearizability + node
kill/restart, 10k seeds". Node 0 is a versioned KV server with durable
state (survives restart faults, like etcd's disk); nodes 1..N-1 are
clients that PUT with at-least-once retries and then GET.

Checked invariant (code 110, STALE_READ): session monotonicity — a
client that has an acknowledged write at version v must never observe a
GET at version < v. Holds for a durable single-copy store under
partitions and kill/restart; breaks immediately if the store loses
acknowledged state (e.g. the `DurabilityBugKv` variant in tests that
drops state on restart), which is exactly the class of bug the workload
exists to catch.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import Machine, Outbox, make_payload, send_if, set_timer_if, update_node

SERVER = 0

# message types
M_PUT, M_PUT_OK, M_GET, M_GET_OK = 1, 2, 3, 4

# timers
T_BOOT, T_TICK, T_RETRY = 0, 1, 2

STALE_READ = 110

TICK_US = 40_000
RETRY_US = 120_000


@struct.dataclass
class KvState:
    # server (durable across restart)
    version: jax.Array  # int32[N] (only SERVER's entry is meaningful)
    value: jax.Array  # int32[N]
    # clients (volatile)
    acked_version: jax.Array  # int32[N] highest version acked to this client
    next_val: jax.Array  # int32[N]
    pending_kind: jax.Array  # int32[N] 0=none, M_PUT or M_GET
    pending_val: jax.Array  # int32[N]
    reqid: jax.Array  # int32[N]
    stale: jax.Array  # bool[N] violation observed


class KvMachine(Machine):
    PAYLOAD_WIDTH = 5
    MAX_MSGS = 1
    MAX_TIMERS = 2

    def __init__(self, num_nodes: int = 4):
        self.NUM_NODES = num_nodes

    def init(self, rng_key) -> KvState:
        n = self.NUM_NODES
        z = jnp.zeros((n,), jnp.int32)
        return KvState(
            version=z,
            value=z,
            acked_version=z,
            next_val=z,
            pending_kind=z,
            pending_val=z,
            reqid=z,
            stale=jnp.zeros((n,), bool),
        )

    def init_node(self, nodes: KvState, i, rng_key) -> KvState:
        """Restart: the server's store is durable; client sessions reset."""
        return self.restart_if(nodes, i, jnp.bool_(True), rng_key)

    def durable_spec(self) -> KvState:
        """Crash-with-amnesia contract: the store (version/value) is
        durable, client session state is volatile; the ghost violation
        flag survives (spec state, not node memory)."""
        return KvState(
            version=True,
            value=True,
            acked_version=False,
            next_val=False,
            pending_kind=False,
            pending_val=False,
            reqid=False,
            stale=True,
        )

    def restart_if(self, nodes: KvState, i, cond, rng_key) -> KvState:
        is_server = i == SERVER
        mask = (jnp.arange(self.NUM_NODES) == i) & ~is_server & cond
        reset = lambda arr: jnp.where(mask, 0, arr)  # noqa: E731
        return nodes.replace(
            acked_version=reset(nodes.acked_version),
            next_val=reset(nodes.next_val),
            pending_kind=reset(nodes.pending_kind),
            pending_val=reset(nodes.pending_val),
            reqid=reset(nodes.reqid),
        )

    # -- timers ---------------------------------------------------------------

    def on_timer(self, nodes: KvState, node, timer_id, now_us, rand_u32) -> Tuple[KvState, Outbox]:
        outbox = self.empty_outbox()
        is_client = node != SERVER
        is_boot = timer_id == T_BOOT

        # boot: clients start their op loop
        outbox = set_timer_if(outbox, 0, is_boot & is_client, TICK_US, T_TICK)

        idle = nodes.pending_kind[node] == 0
        # tick: issue next op — alternate PUT / GET by next_val parity
        is_tick = (timer_id == T_TICK) & is_client
        do_put = is_tick & idle & (nodes.next_val[node] % 2 == 0)
        do_get = is_tick & idle & (nodes.next_val[node] % 2 == 1)
        new_reqid = nodes.reqid[node] + 1
        put_val = node * 100_000 + nodes.next_val[node]

        nodes = update_node(
            nodes, node,
            pending_kind=jnp.where(do_put, M_PUT, jnp.where(do_get, M_GET, nodes.pending_kind[node])),
            pending_val=jnp.where(do_put, put_val, nodes.pending_val[node]),
            reqid=jnp.where(do_put | do_get, new_reqid, nodes.reqid[node]),
            next_val=jnp.where(do_put | do_get, nodes.next_val[node] + 1, nodes.next_val[node]),
        )
        # send the request; retry timer covers loss/partition/server-down
        put = make_payload(self.PAYLOAD_WIDTH, M_PUT, node, nodes.reqid[node], nodes.pending_val[node])
        get = make_payload(self.PAYLOAD_WIDTH, M_GET, node, nodes.reqid[node])
        outbox = send_if(outbox, 0, do_put, SERVER, put)
        outbox = send_if(outbox, 0, do_get, SERVER, get)
        outbox = set_timer_if(outbox, 0, is_tick, TICK_US, T_TICK)
        outbox = set_timer_if(outbox, 1, do_put | do_get, RETRY_US, T_RETRY)

        # retry: resend the pending op (at-least-once)
        is_retry = (timer_id == T_RETRY) & is_client & ~idle
        retry_put = is_retry & (nodes.pending_kind[node] == M_PUT)
        retry_get = is_retry & (nodes.pending_kind[node] == M_GET)
        rput = make_payload(self.PAYLOAD_WIDTH, M_PUT, node, nodes.reqid[node], nodes.pending_val[node])
        rget = make_payload(self.PAYLOAD_WIDTH, M_GET, node, nodes.reqid[node])
        outbox = send_if(outbox, 0, retry_put, SERVER, rput)
        outbox = send_if(outbox, 0, retry_get, SERVER, rget)
        outbox = set_timer_if(outbox, 1, is_retry, RETRY_US, T_RETRY)
        return nodes, outbox

    # -- messages -------------------------------------------------------------

    def on_message(self, nodes: KvState, node, src, payload, now_us, rand_u32) -> Tuple[KvState, Outbox]:
        outbox = self.empty_outbox()
        mtype = payload[0]

        # server side
        is_server = node == SERVER
        is_put = is_server & (mtype == M_PUT)
        client, reqid, val = payload[1], payload[2], payload[3]
        new_version = nodes.version[SERVER] + 1
        nodes = update_node(
            nodes, SERVER,
            version=jnp.where(is_put, new_version, nodes.version[SERVER]),
            value=jnp.where(is_put, val, nodes.value[SERVER]),
        )
        put_ok = make_payload(self.PAYLOAD_WIDTH, M_PUT_OK, 0, reqid, nodes.version[SERVER])
        outbox = send_if(outbox, 0, is_put, client, put_ok)

        is_get = is_server & (mtype == M_GET)
        get_ok = make_payload(
            self.PAYLOAD_WIDTH, M_GET_OK, 0, reqid, nodes.version[SERVER], nodes.value[SERVER]
        )
        outbox = send_if(outbox, 0, is_get, client, get_ok)

        # client side: accept replies matching the current reqid
        is_client = node != SERVER
        r_reqid, r_version = payload[2], payload[3]
        current = r_reqid == nodes.reqid[node]
        got_put_ok = is_client & (mtype == M_PUT_OK) & current & (nodes.pending_kind[node] == M_PUT)
        got_get_ok = is_client & (mtype == M_GET_OK) & current & (nodes.pending_kind[node] == M_GET)
        stale = got_get_ok & (r_version < nodes.acked_version[node])
        nodes = update_node(
            nodes, node,
            acked_version=jnp.where(
                got_put_ok | got_get_ok,
                jnp.maximum(nodes.acked_version[node], r_version),
                nodes.acked_version[node],
            ),
            pending_kind=jnp.where(got_put_ok | got_get_ok, 0, nodes.pending_kind[node]),
            stale=nodes.stale[node] | stale,
        )
        return nodes, outbox

    # -- invariants / results ---------------------------------------------------

    def invariant(self, nodes: KvState, now_us):
        ok = ~jnp.any(nodes.stale)
        return ok, jnp.where(ok, 0, STALE_READ).astype(jnp.int32)

    def summary(self, nodes: KvState):
        return {
            "server_version": nodes.version[SERVER],
            "total_acked": jnp.sum(nodes.acked_version),
        }

    def coverage_projection(self, nodes: KvState, now_us):
        """Scenario projection: server version bucket (phase) x worst
        client staleness lag x in-flight request pressure — the
        linearizability-relevant shape of a leased-KV interleaving."""
        ver = jnp.clip(nodes.version[SERVER], 0, 7)
        lag = jnp.clip(
            nodes.version[SERVER] - jnp.min(nodes.acked_version[1:]), 0, 7
        )
        pending = jnp.clip(
            jnp.sum((nodes.pending_kind[1:] != 0).astype(jnp.int32)), 0, 3
        )
        return (
            ver | (lag << 3) | (pending << 6) | (jnp.any(nodes.stale).astype(jnp.int32) << 8)
        ).astype(jnp.uint32)
