"""Multi-decree Paxos — a replicated LOG of synod instances, batched.

Round-3 answer to "single-decree only" (VERDICT r2 weak #5 / item 8):
the second consensus family at MadRaft depth. Every node is an acceptor
with durable per-slot (promised, accepted) state; nodes 0 and 1 are
proposers that drive a fixed log of `log_slots` decrees, one synod per
slot, racing each other under partitions / kills / storms. A proposer
that gets a slot chosen broadcasts LEARN and immediately moves to its
next unlearned slot (a short T_NEXT timer), so the log fills at RTT
pace while the rival's retries contend for the same slots with
ever-higher ballots — the leader-change dynamic the chaos schedule
stresses.

Invariants:
  * AGREEMENT_MULTI (150): at most one value ever chosen per slot —
    ghost per-slot chosen registers on row 0, written when a proposer
    observes a majority of ACCEPTED acks, never read by the protocol.
  * LEARN_DIVERGED (151): a node learned a value for a slot that
    differs from the slot's ghost chosen value (a broken learn path
    would let state machines execute divergent logs).

`NoPromiseCheckMultiPaxos` drops the acceptor's ballot guard on ACCEPT
(same classic bug as the single-decree variant) — under dueling
proposers + chaos, two values get majority-accepted in one slot.

Reference scenario family: consensus-under-chaos at MadRaft depth
(BASELINE.json workloads); single-decree sibling: models/paxos.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import (
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_timer_if,
    update_node,
)
from ..utils import set2d

# messages: [mtype, slot, b, v/acc_b, acc_v]
M_PREPARE, M_PROMISE, M_ACCEPT, M_ACCEPTED, M_NACK, M_LEARN = 1, 2, 3, 4, 5, 6

# timers
T_BOOT, T_PROPOSE, T_RETRY, T_NEXT = 0, 1, 2, 3

AGREEMENT_MULTI = 150
LEARN_DIVERGED = 151

PROPOSE_MIN_US = 20_000
PROPOSE_SPAN_US = 180_000
RETRY_MIN_US = 150_000
RETRY_SPAN_US = 250_000
NEXT_US = 15_000

IDLE, PREPARING, ACCEPTING = 0, 1, 2


@struct.dataclass
class MultiPaxosState:
    # acceptor (durable per-slot stable storage)
    promised: jax.Array    # int32[N, S] highest ballot promised (-1 none)
    acc_ballot: jax.Array  # int32[N, S] ballot of accepted value (-1 none)
    acc_value: jax.Array   # int32[N, S] accepted value (0 none)
    # learned log (durable; what a state machine would execute)
    learned: jax.Array     # int32[N, S] (0 = unknown)
    round: jax.Array       # int32[N] rising ballot round (durable)
    # proposer (volatile)
    phase: jax.Array       # int32[N]
    cur_slot: jax.Array    # int32[N] slot being driven
    ballot: jax.Array      # int32[N]
    promises: jax.Array    # int32[N]
    best_ballot: jax.Array # int32[N]
    best_value: jax.Array  # int32[N]
    accepts: jax.Array     # int32[N]
    # ghost chosen registers (row 0, spec-only)
    chosen_any: jax.Array  # bool[N, S]
    chosen_val: jax.Array  # int32[N, S]
    bad: jax.Array         # bool[N]


class MultiPaxosMachine(Machine):
    PAYLOAD_WIDTH = 6
    MAX_TIMERS = 2
    NUM_PROPOSERS = 2

    def __init__(self, num_nodes: int = 5, log_slots: int = 8):
        self.NUM_NODES = num_nodes
        self.MAX_MSGS = num_nodes - 1
        self.majority = num_nodes // 2 + 1
        self.S = log_slots

    def init(self, rng_key) -> MultiPaxosState:
        n, s = self.NUM_NODES, self.S
        zns = jnp.zeros((n, s), jnp.int32)
        z = jnp.zeros((n,), jnp.int32)
        return MultiPaxosState(
            promised=zns - 1,
            acc_ballot=zns - 1,
            acc_value=zns,
            learned=zns,
            round=z,
            phase=z,
            cur_slot=z,
            ballot=z - 1,
            promises=z,
            best_ballot=z - 1,
            best_value=z,
            accepts=z,
            chosen_any=jnp.zeros((n, s), bool),
            chosen_val=zns,
            bad=jnp.zeros((n,), bool),
        )

    def restart_if(self, nodes: MultiPaxosState, i, cond, rng_key) -> MultiPaxosState:
        """Acceptor slots, learned log and the round counter are stable
        storage; the proposer side restarts idle and re-derives its
        working slot from the learned log."""
        n = self.NUM_NODES
        row = (jnp.arange(n) == i) & cond
        set_row = lambda arr, v: jnp.where(row, v, arr)  # noqa: E731
        return nodes.replace(
            phase=set_row(nodes.phase, IDLE),
            cur_slot=set_row(nodes.cur_slot, self._first_unlearned(nodes, i)),
            ballot=set_row(nodes.ballot, -1),
            promises=set_row(nodes.promises, 0),
            best_ballot=set_row(nodes.best_ballot, -1),
            best_value=set_row(nodes.best_value, 0),
            accepts=set_row(nodes.accepts, 0),
        )

    # -- helpers --------------------------------------------------------------

    def _is_proposer(self, node):
        return node < self.NUM_PROPOSERS

    def _my_value(self, node, slot):
        return (slot + 1) * 16 + node + 1  # distinct non-zero per (slot, proposer)

    def _first_unlearned(self, nodes: MultiPaxosState, node):
        unk = nodes.learned[node] == 0
        return jnp.where(jnp.any(unk), jnp.argmax(unk), self.S).astype(jnp.int32)

    def _accept_guard(self, nodes: MultiPaxosState, node, slot, b) -> jax.Array:
        """The ballot check the bug variant drops."""
        return b >= nodes.promised[node, slot]

    def _learn(self, nodes: MultiPaxosState, node, slot, value, cond) -> MultiPaxosState:
        """Record a learned value and advance the working slot past the
        learned prefix."""
        unknown = cond & (nodes.learned[node, slot] == 0)
        nodes = nodes.replace(
            learned=jnp.where(unknown, set2d(nodes.learned, node, slot, value), nodes.learned)
        )
        nxt = self._first_unlearned(nodes, node)
        bump = cond & (slot == nodes.cur_slot[node])
        return update_node(
            nodes, node,
            cur_slot=jnp.where(bump, nxt, nodes.cur_slot[node]),
            phase=jnp.where(bump, IDLE, nodes.phase[node]),
        )

    def _start_prepare(self, nodes: MultiPaxosState, node, outbox: Outbox, cond) -> Tuple[MultiPaxosState, Outbox]:
        """Begin a new ballot for the current slot (self-promise +
        broadcast PREPARE). The round jumps past whatever our own
        acceptor promised for the slot so the ballot is always
        self-promisable."""
        n = self.NUM_NODES
        slot = jnp.minimum(nodes.cur_slot[node], self.S - 1)
        round_eff = jnp.maximum(
            nodes.round[node], (nodes.promised[node, slot] - node) // n + 1
        )
        new_ballot = round_eff * n + node
        nodes = update_node(
            nodes, node,
            phase=jnp.where(cond, PREPARING, nodes.phase[node]),
            ballot=jnp.where(cond, new_ballot, nodes.ballot[node]),
            round=jnp.where(cond, round_eff + 1, nodes.round[node]),
            promises=jnp.where(cond, 1, nodes.promises[node]),
            best_ballot=jnp.where(cond, nodes.acc_ballot[node, slot], nodes.best_ballot[node]),
            best_value=jnp.where(cond, nodes.acc_value[node, slot], nodes.best_value[node]),
            accepts=jnp.where(cond, 0, nodes.accepts[node]),
        )
        nodes = nodes.replace(promised=jnp.where(
            cond, set2d(nodes.promised, node, slot, new_ballot), nodes.promised
        ))
        prepare = make_payload(self.PAYLOAD_WIDTH, M_PREPARE, slot, new_ballot)
        peers = (node + jnp.arange(1, n, dtype=jnp.int32)) % n
        for i in range(self.MAX_MSGS):
            outbox = send_if(outbox, i, cond, peers[i], prepare)
        return nodes, outbox

    # -- timers ---------------------------------------------------------------

    def on_timer(self, nodes: MultiPaxosState, node, timer_id, now_us, rand_u32) -> Tuple[MultiPaxosState, Outbox]:
        outbox = self.empty_outbox()
        is_boot = timer_id == T_BOOT
        is_prop = self._is_proposer(node)

        delay = jnp.int32(PROPOSE_MIN_US) + (
            rand_u32[0] % jnp.uint32(PROPOSE_SPAN_US)
        ).astype(jnp.int32)
        outbox = set_timer_if(outbox, 0, is_boot & is_prop, delay, T_PROPOSE)

        fire = (timer_id == T_PROPOSE) | (timer_id == T_RETRY) | (timer_id == T_NEXT)
        behind = nodes.cur_slot[node] < self.S
        start = fire & is_prop & behind
        nodes, outbox = self._start_prepare(nodes, node, outbox, start)
        retry_delay = jnp.int32(RETRY_MIN_US) + (
            rand_u32[1] % jnp.uint32(RETRY_SPAN_US)
        ).astype(jnp.int32)
        outbox = set_timer_if(
            outbox, 1, (timer_id != T_NEXT) & fire & is_prop & behind, retry_delay, T_RETRY
        )
        return nodes, outbox

    # -- messages -------------------------------------------------------------

    def on_message(self, nodes: MultiPaxosState, node, src, payload, now_us, rand_u32) -> Tuple[MultiPaxosState, Outbox]:
        outbox = self.empty_outbox()
        mtype, slot = payload[0], jnp.clip(payload[1], 0, self.S - 1)
        n = self.NUM_NODES
        peers = (node + jnp.arange(1, n, dtype=jnp.int32)) % n

        # ---- acceptor: PREPARE -> PROMISE or NACK ----
        is_prep = mtype == M_PREPARE
        b = payload[2]
        grant = is_prep & (b > nodes.promised[node, slot])
        nodes = nodes.replace(promised=jnp.where(
            grant, set2d(nodes.promised, node, slot, b), nodes.promised
        ))
        promise = make_payload(
            self.PAYLOAD_WIDTH, M_PROMISE, slot, b,
            nodes.acc_ballot[node, slot], nodes.acc_value[node, slot],
        )
        outbox = send_if(outbox, 0, grant, src, promise)
        nack = make_payload(self.PAYLOAD_WIDTH, M_NACK, slot, b)
        outbox = send_if(outbox, 0, is_prep & ~grant, src, nack)

        # ---- proposer: PROMISE ----
        is_promise = (mtype == M_PROMISE) & self._is_proposer(node)
        p_b, p_accb, p_accv = payload[2], payload[3], payload[4]
        counts = (
            is_promise
            & (nodes.phase[node] == PREPARING)
            & (p_b == nodes.ballot[node])
            & (slot == jnp.minimum(nodes.cur_slot[node], self.S - 1))
        )
        better = counts & (p_accb > nodes.best_ballot[node])
        new_promises = nodes.promises[node] + jnp.where(counts, 1, 0)
        nodes = update_node(
            nodes, node,
            promises=new_promises,
            best_ballot=jnp.where(better, p_accb, nodes.best_ballot[node]),
            best_value=jnp.where(better, p_accv, nodes.best_value[node]),
        )
        quorum = counts & (new_promises >= self.majority)
        value = jnp.where(
            nodes.best_ballot[node] >= 0, nodes.best_value[node],
            self._my_value(node, slot),
        )
        self_ok = quorum & self._accept_guard(nodes, node, slot, nodes.ballot[node])
        nodes = update_node(
            nodes, node,
            phase=jnp.where(quorum, ACCEPTING, nodes.phase[node]),
            accepts=jnp.where(quorum, jnp.where(self_ok, 1, 0), nodes.accepts[node]),
        )
        nodes = nodes.replace(
            acc_ballot=jnp.where(
                self_ok, set2d(nodes.acc_ballot, node, slot, nodes.ballot[node]), nodes.acc_ballot
            ),
            acc_value=jnp.where(
                self_ok, set2d(nodes.acc_value, node, slot, value), nodes.acc_value
            ),
        )
        accept = make_payload(self.PAYLOAD_WIDTH, M_ACCEPT, slot, nodes.ballot[node], value)
        for i in range(self.MAX_MSGS):
            outbox = send_if(outbox, i, quorum, peers[i], accept)

        # ---- acceptor: ACCEPT -> ACCEPTED or NACK ----
        is_acc = mtype == M_ACCEPT
        a_b, a_v = payload[2], payload[3]
        take = is_acc & self._accept_guard(nodes, node, slot, a_b)
        nodes = nodes.replace(
            promised=jnp.where(
                take,
                set2d(nodes.promised, node, slot, jnp.maximum(a_b, nodes.promised[node, slot])),
                nodes.promised,
            ),
            acc_ballot=jnp.where(take, set2d(nodes.acc_ballot, node, slot, a_b), nodes.acc_ballot),
            acc_value=jnp.where(take, set2d(nodes.acc_value, node, slot, a_v), nodes.acc_value),
        )
        accepted = make_payload(self.PAYLOAD_WIDTH, M_ACCEPTED, slot, a_b, a_v)
        outbox = send_if(outbox, 0, take, src, accepted)

        # ---- proposer: ACCEPTED -> chosen on majority ----
        is_acked = (mtype == M_ACCEPTED) & self._is_proposer(node)
        k_b, k_v = payload[2], payload[3]
        counts2 = (
            is_acked
            & (nodes.phase[node] == ACCEPTING)
            & (k_b == nodes.ballot[node])
            & (slot == jnp.minimum(nodes.cur_slot[node], self.S - 1))
        )
        new_accepts = nodes.accepts[node] + jnp.where(counts2, 1, 0)
        chosen = counts2 & (new_accepts >= self.majority)
        nodes = update_node(nodes, node, accepts=new_accepts)

        # ghost per-slot chosen register (agreement check, row 0)
        conflict = chosen & nodes.chosen_any[0, slot] & (nodes.chosen_val[0, slot] != k_v)
        first = chosen & ~nodes.chosen_any[0, slot]
        nodes = nodes.replace(
            chosen_any=jnp.where(first, set2d(nodes.chosen_any, 0, slot, True), nodes.chosen_any),
            chosen_val=jnp.where(first, set2d(nodes.chosen_val, 0, slot, k_v), nodes.chosen_val),
            bad=jnp.where(conflict, nodes.bad | (jnp.arange(n) == 0), nodes.bad),
        )
        # learn locally, advance to the next slot soon, tell everyone
        nodes = self._learn(nodes, node, slot, k_v, chosen)
        learn = make_payload(self.PAYLOAD_WIDTH, M_LEARN, slot, k_v)
        for i in range(self.MAX_MSGS):
            outbox = send_if(outbox, i, chosen, peers[i], learn)
        outbox = set_timer_if(
            outbox, 0, chosen & (nodes.cur_slot[node] < self.S), NEXT_US, T_NEXT
        )

        # ---- anyone: LEARN ----
        is_learn = mtype == M_LEARN
        nodes = self._learn(nodes, node, slot, payload[2], is_learn)

        return nodes, outbox

    # -- invariants / results --------------------------------------------------

    def invariant(self, nodes: MultiPaxosState, now_us):
        agree_viol = nodes.bad[0]
        diverged = jnp.any(
            (nodes.learned != 0)
            & nodes.chosen_any[0][None, :]
            & (nodes.learned != nodes.chosen_val[0][None, :])
        )
        ok = ~(agree_viol | diverged)
        code = jnp.where(agree_viol, AGREEMENT_MULTI, jnp.where(diverged, LEARN_DIVERGED, 0))
        return ok, code.astype(jnp.int32)

    def is_done(self, nodes: MultiPaxosState, now_us):
        return jnp.all(nodes.learned[: self.NUM_PROPOSERS] != 0)

    def summary(self, nodes: MultiPaxosState):
        return {
            "slots_chosen": jnp.sum(nodes.chosen_any[0].astype(jnp.int32)),
            "max_round": jnp.max(nodes.round[: self.NUM_PROPOSERS]),
        }


class NoPromiseCheckMultiPaxos(MultiPaxosMachine):
    """Bug variant: acceptors take any ACCEPT regardless of their
    promise — dueling proposers + chaos get two values majority-accepted
    in one slot (AGREEMENT_MULTI)."""

    def _accept_guard(self, nodes: MultiPaxosState, node, slot, b) -> jax.Array:
        return jnp.bool_(True)
