"""Single-decree Paxos (synod) machine — the other classic consensus
protocol, batched.

Every node is an acceptor with durable (promised, accepted) state
(Paxos's stable-storage requirement survives engine kill/restart
faults); nodes 0 and 1 are also proposers, each proposing its own
distinct value, retrying with ever-higher ballots on timeout. Ballots
are globally unique via ballot = round * N + node.

Checked invariant (AGREEMENT, code 140): at most one value is ever
*chosen* (accepted by a majority at some ballot). Tracked with a ghost
chosen-register on row 0 — written whenever a proposer observes a
majority of ACCEPTED acks for its ballot, never read by the protocol.
`NoPromiseCheckPaxos` drops the acceptor's ballot guard on ACCEPT (the
classic implementation bug); under contention + partitions two
proposers then get distinct values chosen, which the engine flags and
replays bit-identically.

Reference scenario family: consensus-under-chaos, same class the
MadRaft workload covers for Raft (BASELINE.json).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import (
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_at,
    set_timer_if,
    update_node,
)

# messages
M_PREPARE, M_PROMISE, M_ACCEPT, M_ACCEPTED, M_NACK = 1, 2, 3, 4, 5

# timers
T_BOOT, T_PROPOSE, T_RETRY = 0, 1, 2

AGREEMENT = 140

PROPOSE_MIN_US = 20_000
PROPOSE_SPAN_US = 180_000
RETRY_MIN_US = 150_000
RETRY_SPAN_US = 250_000

IDLE, PREPARING, ACCEPTING, DECIDED = 0, 1, 2, 3


@struct.dataclass
class PaxosState:
    # acceptor (durable — Paxos stable storage)
    promised: jax.Array  # int32[N] highest ballot promised (-1 none)
    acc_ballot: jax.Array  # int32[N] ballot of accepted value (-1 none)
    acc_value: jax.Array  # int32[N] accepted value (0 none)
    # proposer (volatile)
    phase: jax.Array  # int32[N]
    ballot: jax.Array  # int32[N] current ballot
    round: jax.Array  # int32[N] retry round counter (durable would also be fine)
    promises: jax.Array  # int32[N] promise count this ballot
    best_ballot: jax.Array  # int32[N] highest accepted ballot among promises
    best_value: jax.Array  # int32[N] its value
    accepts: jax.Array  # int32[N] ACCEPTED count this ballot
    decided: jax.Array  # bool[N]
    # ghost chosen-register (spec-only, row 0)
    chosen_any: jax.Array  # bool[N]
    chosen_val: jax.Array  # int32[N]
    bad: jax.Array  # bool[N]


class PaxosMachine(Machine):
    PAYLOAD_WIDTH = 5
    MAX_TIMERS = 2
    NUM_PROPOSERS = 2

    def __init__(self, num_nodes: int = 5):
        self.NUM_NODES = num_nodes
        self.MAX_MSGS = num_nodes - 1
        self.majority = num_nodes // 2 + 1

    def init(self, rng_key) -> PaxosState:
        n = self.NUM_NODES
        z = jnp.zeros((n,), jnp.int32)
        neg = jnp.full((n,), -1, jnp.int32)
        return PaxosState(
            promised=neg,
            acc_ballot=neg,
            acc_value=z,
            phase=z,
            ballot=neg,
            round=z,
            promises=z,
            best_ballot=neg,
            best_value=z,
            accepts=z,
            decided=jnp.zeros((n,), bool),
            chosen_any=jnp.zeros((n,), bool),
            chosen_val=z,
            bad=jnp.zeros((n,), bool),
        )

    def durable_spec(self) -> PaxosState:
        """Crash-with-amnesia contract: acceptor state (promised /
        accepted) is Paxos stable storage, the proposer's round counter
        recovers from disk, the in-flight phase is volatile; the ghost
        chosen-register and violation flag are spec state."""
        return PaxosState(
            promised=True, acc_ballot=True, acc_value=True,
            phase=False, ballot=False, round=True,
            promises=False, best_ballot=False, best_value=False,
            accepts=False, decided=False,
            chosen_any=True, chosen_val=True, bad=True,
        )

    def restart_if(self, nodes: PaxosState, i, cond, rng_key) -> PaxosState:
        """Kill/restart: acceptor state is stable storage; the proposer
        side restarts idle (it will re-propose from its round counter,
        which also survives — a fresh higher ballot, like a real
        proposer recovering its ballot from disk)."""
        n = self.NUM_NODES
        row = (jnp.arange(n) == i) & cond
        set_row = lambda arr, v: jnp.where(row, v, arr)  # noqa: E731
        return nodes.replace(
            phase=set_row(nodes.phase, IDLE),
            ballot=set_row(nodes.ballot, -1),
            promises=set_row(nodes.promises, 0),
            best_ballot=set_row(nodes.best_ballot, -1),
            best_value=set_row(nodes.best_value, 0),
            accepts=set_row(nodes.accepts, 0),
            decided=jnp.where(row, False, nodes.decided),
        )

    def _is_proposer(self, node):
        return node < self.NUM_PROPOSERS

    def _my_value(self, node):
        return node + jnp.int32(1)  # distinct non-zero proposal values

    def _accept_guard(self, nodes: PaxosState, node, b) -> jax.Array:
        """Acceptor's ballot check on ACCEPT — the line the bug variant
        drops (accepting stale ballots breaks agreement)."""
        return b >= nodes.promised[node]

    # -- phase helpers (shared by timer + message handlers) ------------------

    def _start_prepare(self, nodes: PaxosState, node, outbox: Outbox, cond) -> Tuple[PaxosState, Outbox]:
        """Begin a new ballot: self-promise + broadcast PREPARE. The
        round jumps past whatever our own acceptor already promised, so
        the new ballot is always self-promisable (otherwise a proposer
        whose acceptor promised a rival's higher ballot would retry the
        same dead ballot forever)."""
        n = self.NUM_NODES
        round_eff = jnp.maximum(
            nodes.round[node], (nodes.promised[node] - node) // n + 1
        )
        new_ballot = round_eff * n + node
        nodes = update_node(
            nodes, node,
            phase=jnp.where(cond, PREPARING, nodes.phase[node]),
            ballot=jnp.where(cond, new_ballot, nodes.ballot[node]),
            round=jnp.where(cond, round_eff + 1, nodes.round[node]),
            promises=jnp.where(cond, 1, nodes.promises[node]),
            best_ballot=jnp.where(cond, nodes.acc_ballot[node], nodes.best_ballot[node]),
            best_value=jnp.where(cond, nodes.acc_value[node], nodes.best_value[node]),
            accepts=jnp.where(cond, 0, nodes.accepts[node]),
        )
        nodes = nodes.replace(promised=jnp.where(
            cond, set_at(nodes.promised, node, new_ballot), nodes.promised
        ))
        prepare = make_payload(self.PAYLOAD_WIDTH, M_PREPARE, new_ballot)
        peers = (node + jnp.arange(1, n, dtype=jnp.int32)) % n
        for s in range(self.MAX_MSGS):
            outbox = send_if(outbox, s, cond, peers[s], prepare)
        return nodes, outbox

    # -- timers ---------------------------------------------------------------

    def on_timer(self, nodes: PaxosState, node, timer_id, now_us, rand_u32) -> Tuple[PaxosState, Outbox]:
        outbox = self.empty_outbox()
        is_boot = timer_id == T_BOOT
        is_prop = self._is_proposer(node)

        delay = jnp.int32(PROPOSE_MIN_US) + (
            rand_u32[0] % jnp.uint32(PROPOSE_SPAN_US)
        ).astype(jnp.int32)
        outbox = set_timer_if(outbox, 0, is_boot & is_prop, delay, T_PROPOSE)

        fire = (timer_id == T_PROPOSE) | (timer_id == T_RETRY)
        start = fire & is_prop & ~nodes.decided[node]
        nodes, outbox = self._start_prepare(nodes, node, outbox, start)
        # retry timer: if still undecided later, go again with higher ballot
        retry_delay = jnp.int32(RETRY_MIN_US) + (
            rand_u32[1] % jnp.uint32(RETRY_SPAN_US)
        ).astype(jnp.int32)
        outbox = set_timer_if(outbox, 1, fire & is_prop, retry_delay, T_RETRY)
        return nodes, outbox

    # -- messages -------------------------------------------------------------

    def on_message(self, nodes: PaxosState, node, src, payload, now_us, rand_u32) -> Tuple[PaxosState, Outbox]:
        outbox = self.empty_outbox()
        mtype = payload[0]
        n = self.NUM_NODES

        # ---- acceptor: PREPARE -> PROMISE or NACK ----
        is_prep = mtype == M_PREPARE
        b = payload[1]
        grant = is_prep & (b > nodes.promised[node])
        nodes = nodes.replace(promised=jnp.where(
            grant, set_at(nodes.promised, node, b), nodes.promised
        ))
        promise = make_payload(
            self.PAYLOAD_WIDTH, M_PROMISE, b, nodes.acc_ballot[node], nodes.acc_value[node]
        )
        outbox = send_if(outbox, 0, grant, src, promise)
        nack = make_payload(self.PAYLOAD_WIDTH, M_NACK, b)
        outbox = send_if(outbox, 0, is_prep & ~grant, src, nack)

        # ---- proposer: PROMISE ----
        is_promise = (mtype == M_PROMISE) & self._is_proposer(node)
        p_b, p_accb, p_accv = payload[1], payload[2], payload[3]
        counts = is_promise & (nodes.phase[node] == PREPARING) & (p_b == nodes.ballot[node])
        better = counts & (p_accb > nodes.best_ballot[node])
        new_promises = nodes.promises[node] + jnp.where(counts, 1, 0)
        nodes = update_node(
            nodes, node,
            promises=new_promises,
            best_ballot=jnp.where(better, p_accb, nodes.best_ballot[node]),
            best_value=jnp.where(better, p_accv, nodes.best_value[node]),
        )
        quorum = counts & (new_promises >= self.majority)
        # constrained choice: highest accepted value among promises, else own
        value = jnp.where(nodes.best_ballot[node] >= 0, nodes.best_value[node], self._my_value(node))
        # self-accept (own acceptor, guard applies)
        self_ok = quorum & self._accept_guard(nodes, node, nodes.ballot[node])
        nodes = update_node(
            nodes, node,
            phase=jnp.where(quorum, ACCEPTING, nodes.phase[node]),
            accepts=jnp.where(quorum, jnp.where(self_ok, 1, 0), nodes.accepts[node]),
        )
        nodes = nodes.replace(
            acc_ballot=jnp.where(self_ok, set_at(nodes.acc_ballot, node, nodes.ballot[node]), nodes.acc_ballot),
            acc_value=jnp.where(self_ok, set_at(nodes.acc_value, node, value), nodes.acc_value),
        )
        accept = make_payload(self.PAYLOAD_WIDTH, M_ACCEPT, nodes.ballot[node], value)
        peers = (node + jnp.arange(1, n, dtype=jnp.int32)) % n
        for s in range(self.MAX_MSGS):
            outbox = send_if(outbox, s, quorum, peers[s], accept)

        # ---- acceptor: ACCEPT -> ACCEPTED or NACK ----
        is_acc = mtype == M_ACCEPT
        a_b, a_v = payload[1], payload[2]
        take = is_acc & self._accept_guard(nodes, node, a_b)
        nodes = nodes.replace(
            promised=jnp.where(take, set_at(nodes.promised, node, jnp.maximum(a_b, nodes.promised[node])), nodes.promised),
            acc_ballot=jnp.where(take, set_at(nodes.acc_ballot, node, a_b), nodes.acc_ballot),
            acc_value=jnp.where(take, set_at(nodes.acc_value, node, a_v), nodes.acc_value),
        )
        accepted = make_payload(self.PAYLOAD_WIDTH, M_ACCEPTED, a_b, a_v)
        outbox = send_if(outbox, 0, take, src, accepted)

        # ---- proposer: ACCEPTED -> chosen on majority ----
        is_acked = (mtype == M_ACCEPTED) & self._is_proposer(node)
        k_b, k_v = payload[1], payload[2]
        counts2 = is_acked & (nodes.phase[node] == ACCEPTING) & (k_b == nodes.ballot[node])
        new_accepts = nodes.accepts[node] + jnp.where(counts2, 1, 0)
        chosen = counts2 & (new_accepts >= self.majority)
        nodes = update_node(
            nodes, node,
            accepts=new_accepts,
            phase=jnp.where(chosen, DECIDED, nodes.phase[node]),
            decided=nodes.decided[node] | chosen,
        )
        # ghost chosen-register on row 0 (agreement check)
        conflict = chosen & nodes.chosen_any[0] & (nodes.chosen_val[0] != k_v)
        first = chosen & ~nodes.chosen_any[0]
        nodes = nodes.replace(
            chosen_any=jnp.where(first, set_at(nodes.chosen_any, 0, True), nodes.chosen_any),
            chosen_val=jnp.where(first, set_at(nodes.chosen_val, 0, k_v), nodes.chosen_val),
            bad=jnp.where(conflict, set_at(nodes.bad, 0, True), nodes.bad),
        )
        return nodes, outbox

    # -- invariants / results --------------------------------------------------

    def invariant(self, nodes: PaxosState, now_us):
        ok = ~nodes.bad[0]
        return ok, jnp.where(ok, 0, AGREEMENT).astype(jnp.int32)

    def is_done(self, nodes: PaxosState, now_us):
        return jnp.all(nodes.decided[: self.NUM_PROPOSERS])

    def summary(self, nodes: PaxosState):
        return {
            "chosen": nodes.chosen_any[0],
            "value": nodes.chosen_val[0],
            "rounds": nodes.round[: self.NUM_PROPOSERS].max(),
        }

    def coverage_projection(self, nodes: PaxosState, now_us):
        """Scenario projection: highest ballot bucket (phase) x
        proposer-phase spread x decisions landed x chosen-register state
        — the duel-shape axes (which round, are proposers racing, is a
        value locked in)."""
        ballot_b = jnp.clip(jnp.max(nodes.ballot), 0, 7)
        max_phase = jnp.clip(jnp.max(nodes.phase[: self.NUM_PROPOSERS]), 0, 3)
        decided_n = jnp.clip(
            jnp.sum(nodes.decided[: self.NUM_PROPOSERS].astype(jnp.int32)), 0, 3
        )
        promised_b = jnp.clip(jnp.max(nodes.promised) + 1, 0, 7)
        return (
            ballot_b
            | (max_phase << 3)
            | (decided_n << 5)
            | (nodes.chosen_any[0].astype(jnp.int32) << 7)
            | (promised_b << 8)
        ).astype(jnp.uint32)


class NoPromiseCheckPaxos(PaxosMachine):
    """Bug variant: acceptors accept any ACCEPT regardless of promised
    ballot — under dueling proposers + partitions, two distinct values
    get majority-accepted and AGREEMENT trips."""

    def _accept_guard(self, nodes: PaxosState, node, b) -> jax.Array:
        return jnp.bool_(True)
