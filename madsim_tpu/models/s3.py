"""S3 object-store machine — the multipart + lifecycle semantics of the
L5 S3 service (`services/s3/__init__.py`, reference:
madsim-aws-sdk-s3/src/server/service.rs:27-60+) lifted into a TPU-engine
`Machine`, completing the service-differential program (etcd-mvcc and
kafka-group shipped in round 4; VERDICT r4 directive 4).

Topology: node 0 is the S3 server; nodes 1..N-1 are clients, each
working a seed-derived program against its OWN object key — put /
delete / create-multipart / upload-part / complete / abort — with
at-least-once retry and a monotone per-client request sequence the
server dedups on.

Service semantics mirrored from `services/s3/__init__.py`:
  * `complete_multipart_upload` concatenates the uploaded parts in
    PART-NUMBER order (service: `b"".join(parts[n] for n in sorted(parts))`)
    and the session disappears; object content is modeled as an int32
    fold (h = h*31 + part_val in part order) the differential recomputes
    from the real service's bytes
  * `abort_multipart_upload` discards the session AND its parts
  * lifecycle: objects expire `OBJ_AGE_US` after last_modified
    (service `apply_lifecycle`: `last_modified <= now - days*86400`);
    incomplete multipart sessions abort `MPU_AGE_US` after creation
    (`abort_multipart_days`); the sweep runs lazily on server events —
    any client-visible observation is itself a server event, so the
    laziness is invisible (same argument as the etcd-mvcc machine)

Invariants (fail codes):
  * MPU_CONCAT  — a live object's content diverged from the ghost
                  expectation (completed object == concat of the parts
                  that were uploaded, in part-number order)
  * MPU_ORPHAN  — part storage non-empty with no active session
                  (abort/complete must not leak parts)
  * LC_EARLY    — ghost-variable check: lifecycle expired an object
                  before last_modified + OBJ_AGE_US
  * LC_PARTIAL  — an absent object still carries content (expiry or
                  delete tore the object down only partially)
  * DUP_APPLY   — the server applied more content-writing ops (put /
                  complete) to a client's key than the client issued

Seeded bug variants (one per invariant class, each a real S3-class
defect):
  * CONCAT_ARRIVAL_ORDER — complete concatenates parts in upload-arrival
                  order instead of part-number order; surfaces whenever
                  a client uploads parts out of order (MPU_CONCAT)
  * ABORT_KEEPS_PARTS — abort ends the session but leaks its parts
                  (MPU_ORPHAN)
  * LC_EARLY_HALF — the lifecycle sweep expires at half the configured
                  age (LC_EARLY, via the ghost expiry)
  * LC_TOMBSTONE_LEAK — expiry clears existence but not content
                  (LC_PARTIAL)
  * NO_DEDUP    — retransmitted puts double-apply (DUP_APPLY; needs an
                  ack to vanish while its request arrived — storms /
                  directional clogs)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..engine.machine import (
    Machine,
    Outbox,
    make_payload,
    send_if,
    set_timer_if,
    update_node,
)
from ..utils import set2d

SERVER = 0

M_REQ = 1
M_ACK = 2

# op kinds (client programs draw uniformly)
OP_PUT = 0
OP_DEL = 1
OP_CREATE = 2
OP_PART = 3
OP_COMPLETE = 4
OP_ABORT = 5
N_OPS = 6

# fail codes
MPU_CONCAT = 211
MPU_ORPHAN = 212
LC_EARLY = 213
LC_PARTIAL = 214
DUP_APPLY = 215

RETRY_US = 100_000
OBJ_AGE_US = 2_500_000   # lifecycle object expiration
MPU_AGE_US = 1_500_000   # lifecycle incomplete-multipart abort
LC_TICK_US = 500_000     # server lifecycle ticker (SimServer.lifecycle_interval)
OBSERVE_US = 4_000_000   # lanes watch the lifecycle phase before early-done

ST_OK = 0
ST_ERR = 1


@struct.dataclass
class S3State:
    # --- server row 0 (durable object store) ---------------------------
    obj_ver: jax.Array       # int32[N, K] write counter; 0 = absent
    obj_val: jax.Array       # int32[N, K] content fold (what the server built)
    obj_expected: jax.Array  # int32[N, K] ghost: honestly-computed content
    obj_mtime: jax.Array     # int32[N, K] last_modified (us)
    mpu_active: jax.Array    # int32[N, K] 1 = session open
    mpu_created: jax.Array   # int32[N, K] session creation time (us)
    mpu_mask: jax.Array      # int32[N, K] bitmask of uploaded part numbers
    part_val: jax.Array      # int32[N, K, P] uploaded part contents
    part_arr: jax.Array      # int32[N, K, P] arrival order of each part
    mpu_arrcnt: jax.Array    # int32[N, K] arrival counter
    last_req: jax.Array      # int32[N, K] dedup: highest applied seq per client
    writes_applied: jax.Array  # int32[N, K] ghost: content writes applied
    lc_early: jax.Array      # bool[N] ghost flag: sweep fired early
    # --- client rows 1.. (durable journal) -----------------------------
    seq: jax.Array           # int32[N]
    acked: jax.Array         # int32[N]
    opk: jax.Array           # int32[N]
    oparg: jax.Array         # int32[N]
    writes_sent: jax.Array   # int32[N, K] ghost: put/complete ops issued
    epoch: jax.Array         # int32[N]


class S3Machine(Machine):
    """1 S3 server + (N-1) clients, one object key per client."""

    PAYLOAD_WIDTH = 5
    MAX_MSGS = 1
    MAX_TIMERS = 1
    P = 4  # part slots per multipart session

    # seeded bug variants (module docstring)
    CONCAT_ARRIVAL_ORDER = False
    ABORT_KEEPS_PARTS = False
    LC_EARLY_HALF = False
    LC_TOMBSTONE_LEAK = False
    NO_DEDUP = False

    def __init__(self, num_nodes: int = 4, target_ops: int = 6):
        self.NUM_NODES = num_nodes
        self.n_clients = num_nodes - 1
        self.K = self.n_clients
        self.target_ops = target_ops

    # -- state ----------------------------------------------------------------

    def init(self, rng_key) -> S3State:
        n, k, p = self.NUM_NODES, self.K, self.P
        zn = jnp.zeros((n,), jnp.int32)
        zk = jnp.zeros((n, k), jnp.int32)
        zp = jnp.zeros((n, k, p), jnp.int32)
        return S3State(
            obj_ver=zk, obj_val=zk, obj_expected=zk, obj_mtime=zk,
            mpu_active=zk, mpu_created=zk, mpu_mask=zk,
            part_val=zp, part_arr=zp, mpu_arrcnt=zk,
            last_req=zk, writes_applied=zk,
            lc_early=jnp.zeros((n,), bool),
            seq=zn, acked=zn, opk=zn, oparg=zn,
            writes_sent=zk,
            epoch=zn,
        )

    def restart_if(self, nodes: S3State, i, cond, rng_key) -> S3State:
        # Durable on both sides: the store is the service's persistent
        # state; clients journal their program position. Restart re-fires
        # BOOT, which bumps the epoch and re-arms the retry chain.
        return nodes

    # -- timers (clients only) -------------------------------------------------

    def _tid(self, nodes: S3State, node):
        return jnp.int32(1) + 2 * nodes.epoch[node]

    def on_timer(self, nodes: S3State, node, timer_id, now_us, rand_u32) -> Tuple[S3State, Outbox]:
        outbox = self.empty_outbox()
        is_boot = timer_id == 0
        t_epoch = (timer_id - 1) // 2
        live = is_boot | (t_epoch == nodes.epoch[node])
        is_client = node != SERVER
        is_server = node == SERVER

        new_epoch = jnp.where(is_boot & live, nodes.epoch[node] + 1, nodes.epoch[node])
        nodes = update_node(nodes, node, epoch=new_epoch)

        # server: the lifecycle ticker (the on-device analogue of
        # SimServer's apply_lifecycle job) — sweep and re-arm. Without
        # it, full-age expiry after clients go quiet would be
        # unobservable (the lazy request-path sweep needs traffic).
        swept = self._sweep(nodes, now_us)
        nodes = jax.tree.map(
            lambda s, o: jnp.where(live & is_server & ~is_boot, s, o), swept, nodes
        )
        outbox = set_timer_if(
            outbox, 0, live & is_server, LC_TICK_US, self._tid(nodes, node)
        )

        done_c = nodes.acked[node] >= self.target_ops
        act = live & is_client & ~done_c

        # issue the next op once the current one is acked. The kind draw
        # is weighted like a real multipart workload (a session uploads
        # several parts per create/complete): PART 3/8, others 1/8.
        need_new = act & (nodes.acked[node] == nodes.seq[node])
        new_seq = nodes.seq[node] + 1
        kind_table = jnp.asarray(
            [OP_PUT, OP_DEL, OP_CREATE, OP_PART, OP_PART, OP_PART,
             OP_COMPLETE, OP_ABORT], jnp.int32,
        )
        kind = kind_table[rand_u32[0] % jnp.uint32(8)]
        part_ix = (rand_u32[1] % jnp.uint32(self.P)).astype(jnp.int32)
        seq_p = jnp.where(need_new, new_seq, nodes.seq[node])
        opk_p = jnp.where(need_new, kind, nodes.opk[node])
        arg_p = jnp.where(need_new, part_ix, nodes.oparg[node])
        own_key = node - 1
        is_write_kind = (opk_p == OP_PUT) | (opk_p == OP_COMPLETE)
        writes_sent = jnp.where(
            need_new & is_write_kind,
            set2d(nodes.writes_sent, node, own_key,
                  nodes.writes_sent[node, own_key] + 1),
            nodes.writes_sent,
        )
        nodes = nodes.replace(writes_sent=writes_sent)
        nodes = update_node(nodes, node, seq=seq_p, opk=opk_p, oparg=arg_p)

        # (re)send the in-flight op; re-arm the retry chain
        send = act & (seq_p > nodes.acked[node])
        outbox = send_if(
            outbox, 0, send, SERVER,
            make_payload(self.PAYLOAD_WIDTH, M_REQ, seq_p, opk_p, arg_p),
        )
        jitter = (rand_u32[2] % jnp.uint32(RETRY_US // 4)).astype(jnp.int32)
        delay = jnp.where(is_boot, jitter, jnp.int32(RETRY_US) + jitter)
        outbox = set_timer_if(
            outbox, 0, live & is_client & ~done_c, delay, self._tid(nodes, node)
        )
        return nodes, outbox

    # -- server ----------------------------------------------------------------

    def _fold_parts(self, vals, mask_bits, order) -> jax.Array:
        """h = fold(h*31 + val) over present parts in `order` (an [P]
        permutation); absent parts are skipped without consuming a fold
        step."""
        h = jnp.int32(0)
        for r in range(self.P):
            ix = order[r]
            present = ((mask_bits >> ix) & 1) > 0
            h = jnp.where(present, h * 31 + vals[ix], h)
        return h

    def _sweep(self, nodes: S3State, now_us) -> S3State:
        """Lazy lifecycle sweep (server row): expire old objects, abort
        stale multipart sessions. Ghost check: an expiry firing before
        last_modified + OBJ_AGE_US is the LC_EARLY bug."""
        age = OBJ_AGE_US // 2 if self.LC_EARLY_HALF else OBJ_AGE_US
        ver = nodes.obj_ver[SERVER]
        mtime = nodes.obj_mtime[SERVER]
        expire = (ver > 0) & (now_us >= mtime + age)
        early = expire & (now_us < mtime + OBJ_AGE_US)

        mpu_stale = (nodes.mpu_active[SERVER] > 0) & (
            now_us >= nodes.mpu_created[SERVER] + MPU_AGE_US
        )

        srow = jnp.arange(self.NUM_NODES) == SERVER
        em = srow[:, None] & expire[None, :]
        am = srow[:, None] & mpu_stale[None, :]
        return nodes.replace(
            obj_ver=jnp.where(em, 0, nodes.obj_ver),
            obj_val=(
                nodes.obj_val
                if self.LC_TOMBSTONE_LEAK
                else jnp.where(em, 0, nodes.obj_val)
            ),
            obj_expected=jnp.where(em, 0, nodes.obj_expected),
            mpu_active=jnp.where(am, 0, nodes.mpu_active),
            mpu_mask=jnp.where(am, 0, nodes.mpu_mask),
            part_val=jnp.where(am[:, :, None], 0, nodes.part_val),
            part_arr=jnp.where(am[:, :, None], 0, nodes.part_arr),
            lc_early=nodes.lc_early | (srow & jnp.any(early)),
        )

    def _apply(self, nodes: S3State, c, seq, kind, arg, now_us) -> Tuple[S3State, jax.Array]:
        """Apply one deduped client op to the server row."""
        n, K, P = self.NUM_NODES, self.K, self.P
        srow = jnp.arange(n) == SERVER
        key = jnp.clip(c - 1, 0, K - 1)
        km = jnp.arange(K) == key
        row_key = srow[:, None] & km[None, :]

        active = nodes.mpu_active[SERVER, key] > 0
        mask_bits = nodes.mpu_mask[SERVER, key]

        is_put = kind == OP_PUT
        is_del = kind == OP_DEL
        is_create = kind == OP_CREATE
        is_part = (kind == OP_PART) & active
        is_complete = (kind == OP_COMPLETE) & active & (mask_bits != 0)
        is_abort = (kind == OP_ABORT) & active
        err = (
            ((kind == OP_PART) & ~active)
            | ((kind == OP_COMPLETE) & (~active | (mask_bits == 0)))
            | ((kind == OP_ABORT) & ~active)
        )

        # content of a completed object: part-number order (the service's
        # sorted() join). The ghost is ALWAYS the honest fold; the buggy
        # variant folds in arrival order instead.
        vals = nodes.part_val[SERVER, key]
        arrs = nodes.part_arr[SERVER, key]
        index_order = jnp.arange(P, dtype=jnp.int32)
        # absent parts sort last: arrival key pushed past any real counter
        arrival_order = jnp.argsort(
            jnp.where(((mask_bits >> index_order) & 1) > 0, arrs, jnp.int32(2**30))
        ).astype(jnp.int32)
        honest = self._fold_parts(vals, mask_bits, index_order)
        built = (
            self._fold_parts(vals, mask_bits, arrival_order)
            if self.CONCAT_ARRIVAL_ORDER
            else honest
        )

        # object writes: put stores `seq`; complete stores the fold
        writes = is_put | is_complete
        new_val = jnp.where(is_put, seq, built)
        new_expected = jnp.where(is_put, seq, honest)
        dels = is_del
        nodes = nodes.replace(
            obj_ver=jnp.where(
                row_key,
                jnp.where(writes, nodes.obj_ver[SERVER, key] + 1,
                          jnp.where(dels, 0, nodes.obj_ver[SERVER, key])),
                nodes.obj_ver,
            ),
            obj_val=jnp.where(
                row_key,
                jnp.where(writes, new_val, jnp.where(dels, 0, nodes.obj_val[SERVER, key])),
                nodes.obj_val,
            ),
            obj_expected=jnp.where(
                row_key,
                jnp.where(writes, new_expected,
                          jnp.where(dels, 0, nodes.obj_expected[SERVER, key])),
                nodes.obj_expected,
            ),
            obj_mtime=jnp.where(
                row_key & writes, now_us, nodes.obj_mtime
            ),
            writes_applied=jnp.where(
                row_key & writes, nodes.writes_applied + 1, nodes.writes_applied
            ),
        )

        # session lifecycle: create opens (replacing any session, parts
        # cleared — the service keys sessions by a fresh upload_id, so a
        # new session never sees old parts); complete/abort close.
        clears = is_create | is_complete | (is_abort & ~jnp.bool_(self.ABORT_KEEPS_PARTS))
        closes = is_complete | is_abort
        part_clear = row_key[:, :, None] & clears[None, None, None]
        nodes = nodes.replace(
            mpu_active=jnp.where(
                row_key,
                jnp.where(is_create, 1, jnp.where(closes, 0, nodes.mpu_active[SERVER, key])),
                nodes.mpu_active,
            ),
            mpu_created=jnp.where(row_key & is_create, now_us, nodes.mpu_created),
            mpu_mask=jnp.where(
                row_key & clears, 0, nodes.mpu_mask
            ),
            mpu_arrcnt=jnp.where(row_key & is_create, 0, nodes.mpu_arrcnt),
            part_val=jnp.where(part_clear, 0, nodes.part_val),
            part_arr=jnp.where(part_clear, 0, nodes.part_arr),
        )

        # part upload: store content `seq` at slot `arg`, stamp arrival
        slot = jnp.clip(arg, 0, P - 1)
        pm = row_key[:, :, None] & (jnp.arange(P) == slot)[None, None, :] & is_part
        arrcnt = nodes.mpu_arrcnt[SERVER, key]
        nodes = nodes.replace(
            part_val=jnp.where(pm, seq, nodes.part_val),
            part_arr=jnp.where(pm, arrcnt, nodes.part_arr),
            mpu_mask=jnp.where(
                row_key & is_part,
                nodes.mpu_mask[SERVER, key] | (1 << slot),
                nodes.mpu_mask,
            ),
            mpu_arrcnt=jnp.where(row_key & is_part, arrcnt + 1, nodes.mpu_arrcnt),
        )

        return nodes, jnp.where(err, ST_ERR, ST_OK).astype(jnp.int32)

    # -- messages --------------------------------------------------------------

    def on_message(self, nodes: S3State, node, src, payload, now_us, rand_u32) -> Tuple[S3State, Outbox]:
        outbox = self.empty_outbox()
        mtype, seq = payload[0], payload[1]

        # ---- server: REQ -------------------------------------------------
        is_req = (node == SERVER) & (mtype == M_REQ)
        swept = self._sweep(nodes, now_us)
        key = jnp.clip(src - 1, 0, self.K - 1)
        is_dup = jnp.where(
            jnp.bool_(self.NO_DEDUP), jnp.bool_(False),
            seq <= swept.last_req[SERVER, key],
        )
        applied, status = self._apply(swept, src, seq, payload[2], payload[3], now_us)
        applied = applied.replace(
            last_req=set2d(
                applied.last_req, SERVER, key,
                jnp.maximum(applied.last_req[SERVER, key], seq),
            )
        )
        do_apply = is_req & ~is_dup
        pick = lambda ap, sw, old: jax.tree.map(  # noqa: E731
            lambda a, s, o: jnp.where(do_apply, a, jnp.where(is_req, s, o)), ap, sw, old
        )
        nodes = pick(applied, swept.replace(last_req=applied.last_req), nodes)
        outbox = send_if(
            outbox, 0, is_req, src,
            make_payload(
                self.PAYLOAD_WIDTH, M_ACK, seq,
                jnp.where(is_dup, ST_OK, status), 0,
            ),
        )

        # ---- client: ACK -------------------------------------------------
        is_ack = (node != SERVER) & (mtype == M_ACK)
        nodes = update_node(
            nodes, node,
            acked=jnp.where(
                is_ack, jnp.maximum(nodes.acked[node], jnp.minimum(seq, nodes.seq[node])),
                nodes.acked[node],
            ),
        )
        return nodes, outbox

    # -- invariants / results --------------------------------------------------

    def invariant(self, nodes: S3State, now_us):
        ver = nodes.obj_ver[SERVER]
        concat = jnp.any((ver > 0) & (nodes.obj_val[SERVER] != nodes.obj_expected[SERVER]))
        orphan = jnp.any((nodes.mpu_active[SERVER] == 0) & (nodes.mpu_mask[SERVER] != 0))
        early = nodes.lc_early[SERVER]
        partial = jnp.any((ver == 0) & (nodes.obj_val[SERVER] != 0))

        client_keys = jnp.arange(self.n_clients)
        sent = nodes.writes_sent[client_keys + 1, client_keys]
        appl = nodes.writes_applied[SERVER, client_keys]
        dup = jnp.any(appl > sent)

        ok = ~(concat | orphan | early | partial | dup)
        code = jnp.where(
            concat, MPU_CONCAT,
            jnp.where(orphan, MPU_ORPHAN,
                      jnp.where(early, LC_EARLY,
                                jnp.where(partial, LC_PARTIAL,
                                          jnp.where(dup, DUP_APPLY, 0)))),
        )
        return ok, code.astype(jnp.int32)

    def is_done(self, nodes: S3State, now_us):
        # hold the lane through the lifecycle-observation window: expiry
        # and multipart-abort behavior AFTER the clients go quiet is
        # exactly what the lifecycle invariants watch
        return jnp.all(nodes.acked[1:] >= self.target_ops) & (now_us >= OBSERVE_US)

    def summary(self, nodes: S3State):
        return {
            "objects_live": jnp.sum((nodes.obj_ver[SERVER] > 0).astype(jnp.int32)),
            "sessions_open": jnp.sum(nodes.mpu_active[SERVER]),
            "writes_applied": jnp.sum(nodes.writes_applied[SERVER]),
            "ops_acked": jnp.sum(nodes.acked[1:]),
        }
