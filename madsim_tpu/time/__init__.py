"""Virtual time — event-queue clock, timers, sleep/timeout/interval.

Reference parity (madsim/src/sim/time/):
  * `TimeHandle` over a timer heap; `advance_to_next_event` jumps the
    clock to the nearest timer (mod.rs:45-59)
  * random base wall-time around year 2022 (mod.rs:26-31), so code that
    bakes in "now" assumptions gets fuzzed
  * `Sleep` registers a timer-wake on poll, re-registering on every poll
    like the reference's naive-timer usage (sleep.rs:47-55)
  * tokio-compatible `interval` with `MissedTickBehavior` (interval.rs)
  * `advance()` manual clock jump (mod.rs:185-190)
  * simulated `Instant` / `SystemTime` — the reference does this by libc
    clock interposition (system_time.rs); in Python, user code instead
    imports these types (API discipline, checked by the determinism log).

All arithmetic is integer nanoseconds — a hard requirement for
bit-identical agreement with the TPU engine (no float latency math).
"""

from __future__ import annotations

import heapq
from typing import Any, Awaitable, Callable, List, Optional, Tuple, Union

from .. import _context
from ..errors import SimError
from ..future import PENDING, Pollable, Ready, await_

__all__ = [
    "TimeHandle",
    "Instant",
    "SystemTime",
    "UNIX_EPOCH",
    "sleep",
    "Sleep",
    "sleep_until",
    "timeout",
    "interval",
    "interval_at",
    "Interval",
    "MissedTickBehavior",
    "advance",
    "now",
    "now_ns",
    "monotonic",
    "to_ns",
]

NS = 1
US = 1_000
MS = 1_000_000
SEC = 1_000_000_000

# 2022-01-01T00:00:00Z in ns since unix epoch.
_JAN_2022_NS = 1_640_995_200 * SEC


# Native sleep pollable — resolved lazily on first sleep so that a bare
# `import madsim_tpu` never triggers the g++ build of hostcore.
_SleepGate = None
_sleep_gate_resolved = False


def _resolve_sleep_gate():
    global _SleepGate, _sleep_gate_resolved
    _sleep_gate_resolved = True
    from .. import _native

    mod = _native.get_mod()
    if mod is not None:
        _SleepGate = mod.SleepGate
    return _SleepGate


def to_ns(duration: Union[int, float]) -> int:
    """Convert seconds (int/float) to integer nanoseconds.

    The single place float durations enter; everything downstream is int.
    """
    if isinstance(duration, int):
        return duration * SEC
    return int(round(duration * SEC))


class TimeHandle:
    """The virtual clock + timer heap of one simulation.

    Reference: madsim/src/sim/time/mod.rs `TimeRuntime`/`TimeHandle`.
    """

    def __init__(self, rng) -> None:
        self._now_ns = 0
        self._heap: List[Tuple[int, int, Callable[[], None]]] = []
        self._seq = 0  # FIFO tie-break for equal deadlines (deterministic)
        # Native clock + timer heap (hostcore.TimeCore) when available —
        # the same (deadline, seq) ordering as the heapq fallback, with
        # callbacks held natively (no id->callback dict round trip).
        from .. import _native

        self._core = _native.make_time_core() if _native.available() else None
        # Random base wall clock ~year 2022 + up to one year of offset
        # (reference: sim/time/mod.rs:26-31).
        self.base_system_ns = _JAN_2022_NS + rng.gen_range(0, 365 * 24 * 3600) * SEC

    # -- clock --------------------------------------------------------------

    def now_ns(self) -> int:
        core = self._core
        return core.now_ns() if core is not None else self._now_ns

    def elapsed(self) -> float:
        return self.now_ns() / SEC

    def system_now_ns(self) -> int:
        return self.base_system_ns + self.now_ns()

    def advance_ns(self, delta_ns: int) -> None:
        """Manually jump the clock forward (reference: mod.rs:185-190)."""
        core = self._core
        if core is not None:
            core.advance_ns(delta_ns)
        else:
            self._now_ns += delta_ns

    # -- timers -------------------------------------------------------------

    def add_timer_ns(self, deadline_ns: int, callback: Callable[[], None]) -> None:
        core = self._core
        if core is not None:
            core.push(deadline_ns, callback)
        else:
            self._seq += 1
            heapq.heappush(self._heap, (deadline_ns, self._seq, callback))

    def next_event_ns(self) -> Optional[int]:
        core = self._core
        if core is not None:
            return core.peek()
        return self._heap[0][0] if self._heap else None

    def advance_to_next_event(self) -> bool:
        """Pop the nearest timer, jump the clock to it, fire the callback.

        Returns False when no timer is pending (deadlock, unless the main
        future completed). Reference: sim/time/mod.rs:45-59.
        """
        core = self._core
        if core is not None:
            return core.advance_to_next_event()
        if not self._heap:
            return False
        deadline, _seq, callback = heapq.heappop(self._heap)
        if deadline > self._now_ns:
            self._now_ns = deadline
        callback()
        return True


# -- Instant / SystemTime ---------------------------------------------------


class Instant:
    """Monotonic simulated instant (reference: system_time.rs `Instant`)."""

    __slots__ = ("_ns",)

    def __init__(self, ns: int):
        self._ns = ns

    @staticmethod
    def now() -> "Instant":
        return Instant(_context.current_time().now_ns())

    def elapsed(self) -> float:
        return (_context.current_time().now_ns() - self._ns) / SEC

    def elapsed_ns(self) -> int:
        return _context.current_time().now_ns() - self._ns

    def duration_since(self, earlier: "Instant") -> float:
        return (self._ns - earlier._ns) / SEC

    def __add__(self, secs: Union[int, float]) -> "Instant":
        return Instant(self._ns + to_ns(secs))

    def __sub__(self, other: "Instant") -> float:
        return (self._ns - other._ns) / SEC

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Instant) and self._ns == other._ns

    def __lt__(self, other: "Instant") -> bool:
        return self._ns < other._ns

    def __le__(self, other: "Instant") -> bool:
        return self._ns <= other._ns

    def __hash__(self) -> int:
        return hash(("Instant", self._ns))

    def __repr__(self) -> str:
        return f"Instant({self._ns}ns)"


class SystemTime:
    """Simulated wall clock (reference: system_time.rs `SystemTime`)."""

    __slots__ = ("_ns",)

    def __init__(self, ns_since_epoch: int):
        self._ns = ns_since_epoch

    @staticmethod
    def now() -> "SystemTime":
        return SystemTime(_context.current_time().system_now_ns())

    def duration_since(self, earlier: "SystemTime") -> float:
        if earlier._ns > self._ns:
            raise SimError("SystemTime earlier than reference point")
        return (self._ns - earlier._ns) / SEC

    def elapsed(self) -> float:
        return SystemTime.now().duration_since(self)

    def ns_since_epoch(self) -> int:
        return self._ns

    def __add__(self, secs: Union[int, float]) -> "SystemTime":
        return SystemTime(self._ns + to_ns(secs))

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, SystemTime) and self._ns == other._ns

    def __lt__(self, other: "SystemTime") -> bool:
        return self._ns < other._ns

    def __hash__(self) -> int:
        return hash(("SystemTime", self._ns))

    def __repr__(self) -> str:
        return f"SystemTime({self._ns}ns)"


UNIX_EPOCH = SystemTime(0)


# -- sleep / timeout --------------------------------------------------------


class SleepFuture(Pollable):
    """Registers a timer-wake on first poll (reference: sleep.rs:47-55).

    One timer per future: re-polls before the deadline (e.g. a race
    partner's wake) don't push duplicate timers — the armed timer fires
    at the deadline regardless (a pollable has a single awaiting task)."""

    __slots__ = ("deadline_ns", "_armed")

    def __init__(self, deadline_ns: int):
        self.deadline_ns = deadline_ns
        self._armed = False

    def poll(self, waker: Callable[[], None]):
        th = _context.current_time()
        if th.now_ns() >= self.deadline_ns:
            return Ready(None)
        if not self._armed:
            self._armed = True
            th.add_timer_ns(self.deadline_ns, waker)
        return PENDING


class Sleep(Pollable):
    """Named, resettable sleep — tokio's `Sleep` handle (reference:
    sim/time/sleep.rs `deadline`/`is_elapsed`/`reset`). Useful for
    event-driven deadline patterns (election timers, idle timeouts)
    that would otherwise be polling loops:

        timer = Sleep.after(0.15)
        ...
        timer.reset_after(0.15)   # heartbeat arrived: push the deadline
        await timer               # fires at the (latest) deadline

    A reset to an *earlier* deadline while a task is parked re-arms
    immediately; a reset to a later one turns the old timer into a
    harmless spurious wake (re-poll re-arms). After firing it can be
    reset and awaited again.
    """

    __slots__ = ("_deadline_ns", "_armed_for", "_waker")

    def __init__(self, deadline_ns: int):
        self._deadline_ns = deadline_ns
        self._armed_for: Optional[int] = None
        self._waker: Optional[Callable[[], None]] = None

    @staticmethod
    def after(duration: Union[int, float]) -> "Sleep":
        th = _context.current_time()
        return Sleep(th.now_ns() + to_ns(duration))

    @staticmethod
    def until(deadline: "Instant") -> "Sleep":
        return Sleep(deadline._ns)

    def deadline(self) -> "Instant":
        return Instant(self._deadline_ns)

    def is_elapsed(self) -> bool:
        return _context.current_time().now_ns() >= self._deadline_ns

    def reset(self, deadline: "Instant") -> None:
        self.reset_ns(deadline._ns)

    def reset_after(self, duration: Union[int, float]) -> None:
        self.reset_ns(_context.current_time().now_ns() + to_ns(duration))

    def reset_ns(self, deadline_ns: int) -> None:
        self._deadline_ns = deadline_ns
        if self._waker is not None and (
            self._armed_for is None or deadline_ns < self._armed_for
        ):
            # a parked task would otherwise sleep to the OLD (later)
            # deadline; arm the earlier one now
            self._armed_for = deadline_ns
            _context.current_time().add_timer_ns(deadline_ns, self._wake)

    def _wake(self) -> None:
        w = self._waker
        if w is not None:
            w()  # re-poll decides readiness; stale timers are spurious wakes

    def poll(self, waker: Callable[[], None]):
        th = _context.current_time()
        if th.now_ns() >= self._deadline_ns:
            self._waker = None
            return Ready(None)
        self._waker = waker
        if self._armed_for != self._deadline_ns:
            self._armed_for = self._deadline_ns
            th.add_timer_ns(self._deadline_ns, self._wake)
        return PENDING

    def drop(self) -> None:
        self._waker = None

    def __await__(self):
        return await_(self).__await__()


def _sleep_pollable(th: "TimeHandle", deadline_ns: int):
    """The sleep pollable: native gate (poll fully in C) when the clock
    core is native, else the Python SleepFuture — same semantics."""
    core = th._core
    if core is not None:
        gate = _SleepGate
        if gate is None and not _sleep_gate_resolved:
            gate = _resolve_sleep_gate()
        if gate is not None:
            return gate(deadline_ns, core)
    return SleepFuture(deadline_ns)


def sleep(duration: Union[int, float]):
    """Sleep for `duration` seconds of virtual time.

    Returns an awaitable directly (not a coroutine): sleeps are the
    single most frequent await in host sims, and skipping the coroutine
    frame per call is measurable. `await sim_time.sleep(x)` is
    unchanged for callers."""
    th = _context.current_time()
    return await_(_sleep_pollable(th, th.now_ns() + to_ns(duration)))


def sleep_ns(duration_ns: int):
    """Sleep for an integer-nanosecond duration (the framework-internal
    form; chaos latencies are always drawn in ns)."""
    th = _context.current_time()
    return await_(_sleep_pollable(th, th.now_ns() + duration_ns))


def sleep_until(deadline: Instant):
    th = _context.current_time()
    return await_(_sleep_pollable(th, deadline._ns))


class _Race(Pollable):
    __slots__ = ("pollables",)

    def __init__(self, pollables):
        self.pollables = pollables

    def poll(self, waker):
        for i, p in enumerate(self.pollables):
            r = p.poll(waker)
            if r is not PENDING:
                return Ready((i, r.value))
        return PENDING

    def drop(self) -> None:
        for p in self.pollables:
            p.drop()


class _InlineFuture(Pollable):
    """Drive an arbitrary awaitable inline within the *current* task.

    This is how the reference's `timeout` works (sim/time/mod.rs:125-140
    `select_biased!` polls the future in place): no helper task, inner
    panics propagate to the caller, and dropping on expiry cancels the
    whole future tree via GeneratorExit. It also removes a task spawn
    from every `call_timeout` — the RPC hot path.
    """

    __slots__ = ("_it", "_step")

    def __init__(self, aw):
        # coroutines drive directly; other awaitables via __await__()
        self._it = aw if hasattr(aw, "send") else aw.__await__()
        # coroutines are not iterators (no __next__) — step via send;
        # plain __await__ iterators (e.g. the native AwaitIter) via next
        send = getattr(self._it, "send", None)
        self._step = (lambda: send(None)) if send is not None else self._it.__next__

    def poll(self, waker):
        # the inner awaitable's own leaf pollables register the current
        # task's waker (re-poll-on-wake contract makes that sound)
        try:
            self._step()
        except StopIteration as e:
            return Ready(e.value)
        return PENDING

    def drop(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()


async def timeout(duration: Union[int, float], fut: Union[Pollable, Awaitable]) -> Any:
    """Await `fut` for at most `duration` virtual seconds.

    Raises built-in `TimeoutError` on expiry (reference `timeout` returns
    `Err(Elapsed)`; sim/time/mod.rs:125-140 `select_biased`). The future
    is polled inline — expiry or surrounding cancellation drops it,
    cascading through nested timeouts (reference/tokio drop semantics).
    """
    th = _context.current_time()
    deadline = _sleep_pollable(th, th.now_ns() + to_ns(duration))
    inner = fut if isinstance(fut, Pollable) else _InlineFuture(fut)
    idx, value = await await_(_Race([inner, deadline]))
    if idx == 0:
        return value
    raise TimeoutError(f"timed out after {duration}s (virtual)")


# -- interval ---------------------------------------------------------------


class MissedTickBehavior:
    """Tokio-compatible (reference: sim/time/interval.rs)."""

    Burst = "burst"
    Delay = "delay"
    Skip = "skip"


class Interval:
    def __init__(self, start_ns: int, period_ns: int):
        if period_ns <= 0:
            raise ValueError("interval period must be > 0")
        self.period_ns = period_ns
        self.missed_tick_behavior = MissedTickBehavior.Burst
        self._deadline_ns = start_ns

    async def tick(self) -> Instant:
        th = _context.current_time()
        await await_(_sleep_pollable(th, self._deadline_ns))
        now = th.now_ns()
        fired = self._deadline_ns
        b = self.missed_tick_behavior
        if b == MissedTickBehavior.Burst:
            self._deadline_ns = fired + self.period_ns
        elif b == MissedTickBehavior.Delay:
            self._deadline_ns = now + self.period_ns
        else:  # Skip: next multiple of period after now
            missed = max(0, (now - fired) // self.period_ns)
            self._deadline_ns = fired + (missed + 1) * self.period_ns
        return Instant(fired)

    def reset(self) -> None:
        th = _context.current_time()
        self._deadline_ns = th.now_ns() + self.period_ns


def interval(period: Union[int, float]) -> Interval:
    """First tick completes immediately (tokio semantics)."""
    th = _context.current_time()
    return Interval(th.now_ns(), to_ns(period))


def interval_at(start: Instant, period: Union[int, float]) -> Interval:
    return Interval(start._ns, to_ns(period))


# -- module-level clock access ----------------------------------------------


def advance(duration: Union[int, float]) -> None:
    """Manually advance virtual time (reference: mod.rs:185-190)."""
    _context.current_time().advance_ns(to_ns(duration))


def now() -> float:
    """Virtual seconds since simulation start."""
    return _context.current_time().elapsed()


def monotonic() -> float:
    """Monotonic seconds for elapsed-time measurement. In the simulator
    the virtual clock is monotonic by construction; the real-mode twin
    maps to time.monotonic() (immune to NTP steps)."""
    return _context.current_time().elapsed()


def now_ns() -> int:
    return _context.current_time().now_ns()
