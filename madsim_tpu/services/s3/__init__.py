"""Simulated S3 — aws-sdk-style fluent client + in-sim server
(reference: madsim-aws-sdk-s3).

`S3Service` is a sorted-map object store with multipart-upload state and
per-bucket lifecycle configuration (reference: src/server/service.rs:27-60+);
`SimServer` serves the request enum over `Endpoint.connect1`
(reference: src/server/rpc_server.rs:22-65); the client exposes fluent
builders (`client.put_object().bucket(b).key(k).body(data).send()`)
mirroring the aws-sdk surface (reference: src/client.rs, src/operation/*).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

from ...dual import rand, time as sim_time  # mode-selected (sim or asyncio)
from ...errors import SimError
from ...net.network import ConnectionReset, parse_addr
from ...dual import net as _dual_net
from ...dual import task as _dual_task

Endpoint = _dual_net.Endpoint
spawn = _dual_task.spawn

__all__ = ["S3Error", "S3Service", "SimServer", "Client", "Config"]


class S3Error(SimError):
    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message


class _Object:
    __slots__ = ("body", "last_modified", "etag")

    def __init__(self, body: bytes, last_modified: float):
        self.body = body
        self.last_modified = last_modified
        self.etag = hashlib.md5(body).hexdigest()


class S3Service:
    """Reference: src/server/service.rs `S3Service`."""

    def __init__(self, rng):
        self.rng = rng
        self.buckets: Dict[str, Dict[str, _Object]] = {}
        # upload_id -> (bucket, key, {part_number: bytes})
        self.uploads: Dict[str, Tuple[str, str, Dict[int, bytes]]] = {}
        self.lifecycle: Dict[str, dict] = {}

    def _bucket(self, name: str) -> Dict[str, _Object]:
        if name not in self.buckets:
            raise S3Error("NoSuchBucket", name)
        return self.buckets[name]

    # -- operations (the request enum) --

    def create_bucket(self, bucket: str) -> dict:
        if bucket in self.buckets:
            raise S3Error("BucketAlreadyExists", bucket)
        self.buckets[bucket] = {}
        return {"location": f"/{bucket}"}

    def delete_bucket(self, bucket: str) -> dict:
        if self._bucket(bucket):
            raise S3Error("BucketNotEmpty", bucket)
        del self.buckets[bucket]
        return {}

    def put_object(self, bucket: str, key: str, body: bytes, now: float) -> dict:
        b = self._bucket(bucket)
        obj = _Object(bytes(body), now)
        b[key] = obj
        return {"e_tag": obj.etag}

    def get_object(self, bucket: str, key: str) -> dict:
        b = self._bucket(bucket)
        if key not in b:
            raise S3Error("NoSuchKey", key)
        obj = b[key]
        return {"body": obj.body, "e_tag": obj.etag, "last_modified": obj.last_modified,
                "content_length": len(obj.body)}

    def head_object(self, bucket: str, key: str) -> dict:
        info = self.get_object(bucket, key)
        info.pop("body")
        return info

    def copy_object(self, src_bucket: str, src_key: str, bucket: str, key: str, now: float) -> dict:
        src = self.get_object(src_bucket, src_key)
        return self.put_object(bucket, key, src["body"], now)

    def delete_object(self, bucket: str, key: str) -> dict:
        self._bucket(bucket).pop(key, None)
        return {}

    def delete_objects(self, bucket: str, keys: List[str]) -> dict:
        b = self._bucket(bucket)
        deleted = [k for k in keys if b.pop(k, None) is not None]
        return {"deleted": deleted}

    def list_objects_v2(self, bucket: str, prefix: str = "", continuation: Optional[str] = None, max_keys: int = 1000) -> dict:
        b = self._bucket(bucket)
        keys = sorted(k for k in b if k.startswith(prefix or ""))
        if continuation:
            keys = [k for k in keys if k > continuation]
        page = keys[:max_keys]
        truncated = len(keys) > len(page)
        return {
            "contents": [
                {"key": k, "size": len(b[k].body), "e_tag": b[k].etag, "last_modified": b[k].last_modified}
                for k in page
            ],
            "is_truncated": truncated,
            "next_continuation_token": page[-1] if truncated and page else None,
            "key_count": len(page),
        }

    # -- multipart (reference: src/operation/{create,upload,complete,abort}_*) --

    def create_multipart_upload(self, bucket: str, key: str) -> dict:
        self._bucket(bucket)
        upload_id = format(self.rng.next_u64(), "032x")
        self.uploads[upload_id] = (bucket, key, {})
        return {"upload_id": upload_id}

    def upload_part(self, upload_id: str, part_number: int, body: bytes) -> dict:
        if upload_id not in self.uploads:
            raise S3Error("NoSuchUpload", upload_id)
        if part_number < 1 or part_number > 10_000:
            raise S3Error("InvalidArgument", "part number out of range")
        self.uploads[upload_id][2][part_number] = bytes(body)
        return {"e_tag": hashlib.md5(bytes(body)).hexdigest()}

    def complete_multipart_upload(self, upload_id: str, now: float) -> dict:
        if upload_id not in self.uploads:
            raise S3Error("NoSuchUpload", upload_id)
        bucket, key, parts = self.uploads.pop(upload_id)
        body = b"".join(parts[n] for n in sorted(parts))
        return self.put_object(bucket, key, body, now)

    def abort_multipart_upload(self, upload_id: str) -> dict:
        if upload_id not in self.uploads:
            raise S3Error("NoSuchUpload", upload_id)
        del self.uploads[upload_id]
        return {}

    # -- lifecycle (reference: service.rs lifecycle config) --

    def put_bucket_lifecycle_configuration(self, bucket: str, config: dict) -> dict:
        self._bucket(bucket)
        self.lifecycle[bucket] = config
        return {}

    def get_bucket_lifecycle_configuration(self, bucket: str) -> dict:
        self._bucket(bucket)
        return self.lifecycle.get(bucket, {"rules": []})


class SimServer:
    """Reference: src/server/rpc_server.rs `SimServer`."""

    def __init__(self) -> None:
        self.service: Optional[S3Service] = None

    async def serve(self, addr: Any, on_bound=None) -> None:
        self.service = S3Service(rand.thread_rng())
        ep = await Endpoint.bind(addr)
        if on_bound is not None:
            on_bound(ep)
        while True:
            tx, rx, _peer = await ep.accept1()
            spawn(self._handle(tx, rx), name="s3-conn")

    async def _handle(self, tx, rx) -> None:
        svc = self.service
        try:
            while (req := await rx.recv()) is not None:
                op, params = req
                try:
                    fn = getattr(svc, op, None)
                    if fn is None:
                        raise S3Error("NotImplemented", op)
                    if op in ("put_object", "copy_object", "complete_multipart_upload"):
                        params = {**params, "now": sim_time.now()}
                    tx.send(("ok", fn(**params)))
                except S3Error as e:
                    tx.send(("err", (e.code, e.message)))
        except ConnectionReset:
            pass
        finally:
            tx.close()  # real mode: one fd per connection must not linger


# -- client --------------------------------------------------------------------


class Config:
    """Reference: src/config.rs (endpoint_url is the only knob that
    matters in-sim)."""

    def __init__(self, endpoint_url: str):
        self.endpoint_url = endpoint_url


class _FluentOp:
    """aws-sdk fluent builder: unknown attribute calls set parameters,
    `.send()` performs the request (reference: src/operation/*.rs)."""

    def __init__(self, client: "Client", op: str):
        self._client = client
        self._op = op
        self._params: Dict[str, Any] = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def setter(value: Any) -> "_FluentOp":
            self._params[name] = value
            return self

        return setter

    async def send(self):
        return await self._client._call(self._op, self._params)


class Client:
    """Reference: src/client.rs `Client::from_conf`."""

    _OPS = [
        "create_bucket",
        "delete_bucket",
        "put_object",
        "get_object",
        "head_object",
        "copy_object",
        "delete_object",
        "delete_objects",
        "list_objects_v2",
        "create_multipart_upload",
        "upload_part",
        "complete_multipart_upload",
        "abort_multipart_upload",
        "put_bucket_lifecycle_configuration",
        "get_bucket_lifecycle_configuration",
    ]

    def __init__(self, config: Config):
        self._addr = parse_addr(config.endpoint_url.replace("http://", ""))
        self._ep: Optional[Endpoint] = None

    @staticmethod
    def from_conf(config: Config) -> "Client":
        return Client(config)

    def __getattr__(self, name: str):
        if name in Client._OPS:
            return lambda: _FluentOp(self, name)
        raise AttributeError(name)

    async def _call(self, op: str, params: Dict[str, Any]):
        if self._ep is None:
            self._ep = await Endpoint.bind(("0.0.0.0", 0))
        tx, rx = await self._ep.connect1(self._addr)
        tx.send((op, params))
        rsp = await rx.recv()
        tx.close()
        if rsp is None:
            raise S3Error("ServiceUnavailable", "s3 server unreachable")
        status, payload = rsp
        if status == "err":
            code, msg = payload
            raise S3Error(code, msg)
        return payload
