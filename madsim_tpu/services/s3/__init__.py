"""Simulated S3 — aws-sdk-style fluent client + in-sim server
(reference: madsim-aws-sdk-s3).

`S3Service` is a sorted-map object store with multipart-upload state and
per-bucket lifecycle configuration (reference: src/server/service.rs:27-60+);
`SimServer` serves the request enum over `Endpoint.connect1`
(reference: src/server/rpc_server.rs:22-65); the client exposes fluent
builders (`client.put_object().bucket(b).key(k).body(data).send()`)
mirroring the aws-sdk surface (reference: src/client.rs, src/operation/*).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

from ...dual import rand, time as sim_time  # mode-selected (sim or asyncio)
from ...errors import SimError
from ...net.network import ConnectionReset, parse_addr
from ...dual import net as _dual_net
from ...dual import task as _dual_task
from .._conn import StreamCaller

Endpoint = _dual_net.Endpoint
spawn = _dual_task.spawn

__all__ = ["S3Error", "S3Service", "SimServer", "Client", "Config"]


class S3Error(SimError):
    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message


class _Object:
    __slots__ = ("body", "last_modified", "etag", "content_type", "metadata")

    def __init__(self, body: bytes, last_modified: float,
                 content_type: str = "binary/octet-stream",
                 metadata: Optional[Dict[str, str]] = None):
        self.body = body
        self.last_modified = last_modified
        self.etag = hashlib.md5(body).hexdigest()
        self.content_type = content_type
        self.metadata = dict(metadata or {})


class S3Service:
    """Reference: src/server/service.rs `S3Service`."""

    def __init__(self, rng):
        self.rng = rng
        self.buckets: Dict[str, Dict[str, _Object]] = {}
        # upload_id -> (bucket, key, {part_number: bytes}, created_at)
        self.uploads: Dict[str, Tuple[str, str, Dict[int, bytes], float]] = {}
        self.lifecycle: Dict[str, dict] = {}

    def _bucket(self, name: str) -> Dict[str, _Object]:
        if name not in self.buckets:
            raise S3Error("NoSuchBucket", name)
        return self.buckets[name]

    # -- operations (the request enum) --

    def create_bucket(self, bucket: str) -> dict:
        if bucket in self.buckets:
            raise S3Error("BucketAlreadyExists", bucket)
        self.buckets[bucket] = {}
        return {"location": f"/{bucket}"}

    def delete_bucket(self, bucket: str) -> dict:
        if self._bucket(bucket):
            raise S3Error("BucketNotEmpty", bucket)
        del self.buckets[bucket]
        return {}

    def put_object(self, bucket: str, key: str, body: bytes, now: float,
                   content_type: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> dict:
        b = self._bucket(bucket)
        obj = _Object(bytes(body), now,
                      content_type=content_type or "binary/octet-stream",
                      metadata=metadata)
        b[key] = obj
        return {"e_tag": obj.etag}

    @staticmethod
    def _parse_range(spec: str, size: int) -> Tuple[int, int]:
        """HTTP range header subset: bytes=a-b | bytes=a- | bytes=-n."""
        if not spec.startswith("bytes="):
            raise S3Error("InvalidRange", spec)
        lo_s, _, hi_s = spec[len("bytes="):].partition("-")
        try:
            if lo_s == "":  # suffix form: last n bytes (n must be > 0)
                n = int(hi_s)
                if n <= 0 or size == 0:
                    raise S3Error("InvalidRange", f"{spec} for object of {size} bytes")
                return max(0, size - n), size - 1
            lo = int(lo_s)
            hi = int(hi_s) if hi_s else size - 1
        except ValueError as exc:
            raise S3Error("InvalidRange", spec) from exc
        if lo > hi or lo >= size:
            raise S3Error("InvalidRange", f"{spec} for object of {size} bytes")
        return lo, min(hi, size - 1)

    def get_object(self, bucket: str, key: str, range: Optional[str] = None) -> dict:
        b = self._bucket(bucket)
        if key not in b:
            raise S3Error("NoSuchKey", key)
        obj = b[key]
        body = obj.body
        out = {"e_tag": obj.etag, "last_modified": obj.last_modified,
               "content_type": obj.content_type, "metadata": dict(obj.metadata)}
        if range is not None:
            lo, hi = self._parse_range(range, len(body))
            out["body"] = body[lo:hi + 1]
            out["content_length"] = hi - lo + 1
            out["content_range"] = f"bytes {lo}-{hi}/{len(body)}"
        else:
            out["body"] = body
            out["content_length"] = len(body)
        return out

    def head_object(self, bucket: str, key: str) -> dict:
        info = self.get_object(bucket, key)
        info.pop("body")
        return info

    def copy_object(self, src_bucket: str, src_key: str, bucket: str, key: str, now: float) -> dict:
        src = self.get_object(src_bucket, src_key)
        # AWS COPY directive default: source metadata travels with the copy
        return self.put_object(bucket, key, src["body"], now,
                               content_type=src["content_type"],
                               metadata=src["metadata"])

    def delete_object(self, bucket: str, key: str) -> dict:
        self._bucket(bucket).pop(key, None)
        return {}

    def delete_objects(self, bucket: str, keys: List[str]) -> dict:
        b = self._bucket(bucket)
        deleted = [k for k in keys if b.pop(k, None) is not None]
        return {"deleted": deleted}

    def list_objects_v2(self, bucket: str, prefix: str = "",
                        continuation: Optional[str] = None, max_keys: int = 1000,
                        delimiter: Optional[str] = None,
                        start_after: Optional[str] = None) -> dict:
        """AWS semantics incl. the delimiter/common-prefixes edges a real
        app hits first: keys containing `delimiter` after `prefix` are
        rolled up into one CommonPrefix entry each; contents and common
        prefixes share the lexicographic order and the max_keys budget."""
        b = self._bucket(bucket)
        keys = sorted(k for k in b if k.startswith(prefix or ""))
        # start_after is always a plain key bound (AWS semantics)
        if start_after:
            keys = [k for k in keys if k > start_after]
        if continuation:
            # structured opaque token: "p\0<common-prefix>" means the whole
            # rolled-up group was consumed (a plain key that merely ends
            # with the delimiter, e.g. a "folder/" marker object, must NOT
            # skip its group — that was a silent-data-loss bug)
            if continuation.startswith("p\0"):
                cp = continuation[2:]
                keys = [k for k in keys if k > cp and not k.startswith(cp)]
            else:
                token = continuation[2:] if continuation.startswith("k\0") else continuation
                keys = [k for k in keys if k > token]

        entries: List[Tuple[str, Optional[str]]] = []  # (sort key, rolled prefix|None)
        seen_prefixes = set()
        for k in keys:
            if delimiter:
                rest = k[len(prefix or ""):]
                d = rest.find(delimiter)
                if d >= 0:
                    cp = (prefix or "") + rest[: d + len(delimiter)]
                    if cp not in seen_prefixes:
                        seen_prefixes.add(cp)
                        entries.append((cp, cp))
                    continue
            entries.append((k, None))

        page = entries[:max_keys]
        truncated = len(entries) > len(page)
        contents = [
            {"key": k, "size": len(b[k].body), "e_tag": b[k].etag,
             "last_modified": b[k].last_modified}
            for k, cp in page if cp is None
        ]
        common = [{"prefix": cp} for _k, cp in page if cp is not None]
        next_token = None
        if truncated and page:
            last_key, last_cp = page[-1]
            next_token = f"p\0{last_cp}" if last_cp is not None else f"k\0{last_key}"
        return {
            "contents": contents,
            "common_prefixes": common,
            "is_truncated": truncated,
            "next_continuation_token": next_token,
            "key_count": len(page),
        }

    # -- multipart (reference: src/operation/{create,upload,complete,abort}_*) --

    def create_multipart_upload(self, bucket: str, key: str, now: float = 0.0) -> dict:
        self._bucket(bucket)
        upload_id = format(self.rng.next_u64(), "032x")
        self.uploads[upload_id] = (bucket, key, {}, now)
        return {"upload_id": upload_id}

    def upload_part(self, upload_id: str, part_number: int, body: bytes) -> dict:
        if upload_id not in self.uploads:
            raise S3Error("NoSuchUpload", upload_id)
        if part_number < 1 or part_number > 10_000:
            raise S3Error("InvalidArgument", "part number out of range")
        self.uploads[upload_id][2][part_number] = bytes(body)
        return {"e_tag": hashlib.md5(bytes(body)).hexdigest()}

    def complete_multipart_upload(self, upload_id: str, now: float) -> dict:
        if upload_id not in self.uploads:
            raise S3Error("NoSuchUpload", upload_id)
        bucket, key, parts, _created = self.uploads.pop(upload_id)
        body = b"".join(parts[n] for n in sorted(parts))
        return self.put_object(bucket, key, body, now)

    def abort_multipart_upload(self, upload_id: str) -> dict:
        if upload_id not in self.uploads:
            raise S3Error("NoSuchUpload", upload_id)
        del self.uploads[upload_id]
        return {}

    # -- lifecycle (reference: service.rs lifecycle config) --

    def put_bucket_lifecycle_configuration(self, bucket: str, config: dict) -> dict:
        self._bucket(bucket)
        self.lifecycle[bucket] = config
        return {}

    def get_bucket_lifecycle_configuration(self, bucket: str) -> dict:
        self._bucket(bucket)
        return self.lifecycle.get(bucket, {"rules": []})

    def apply_lifecycle(self, now: float) -> dict:
        """Enforce lifecycle rules against the (virtual) clock — the
        background job a real S3 runs ~daily. Rule shape:
        {"id", "status" (default Enabled), "prefix", "days" (object
        expiration), "abort_multipart_days" (incomplete-upload abort)}.
        """
        expired: List[Tuple[str, str]] = []
        aborted: List[str] = []
        for bucket, cfg in self.lifecycle.items():
            b = self.buckets.get(bucket)
            if b is None:
                continue
            for rule in cfg.get("rules", []):
                if rule.get("status", "Enabled") != "Enabled":
                    continue
                prefix = rule.get("prefix", "")
                days = rule.get("days")
                if days is not None:
                    cutoff = now - days * 86400.0
                    for k in [k for k, o in b.items()
                              if k.startswith(prefix) and o.last_modified <= cutoff]:
                        del b[k]
                        expired.append((bucket, k))
                mp_days = rule.get("abort_multipart_days")
                if mp_days is not None:
                    cutoff = now - mp_days * 86400.0
                    for uid in [uid for uid, (ub, uk, _p, created) in self.uploads.items()
                                if ub == bucket and uk.startswith(prefix)
                                and created <= cutoff]:
                        del self.uploads[uid]
                        aborted.append(uid)
        return {"expired": expired, "aborted_uploads": aborted}


class SimServer:
    """Reference: src/server/rpc_server.rs `SimServer`."""

    def __init__(self, lifecycle_interval: float = 3600.0) -> None:
        # period of the lifecycle enforcement job (a real S3 runs it
        # ~daily; an hour of virtual time keeps sim behavior observable)
        self.lifecycle_interval = lifecycle_interval
        self.service: Optional[S3Service] = None

    async def serve(self, addr: Any, on_bound=None) -> None:
        self.service = S3Service(rand.thread_rng())
        ep = await Endpoint.bind(addr)
        if on_bound is not None:
            on_bound(ep)

        async def lifecycle_ticker():
            it = sim_time.interval(self.lifecycle_interval)
            while True:
                await it.tick()
                self.service.apply_lifecycle(sim_time.now())

        spawn(lifecycle_ticker(), name="s3-lifecycle-tick")
        while True:
            tx, rx, _peer = await ep.accept1()
            spawn(self._handle(tx, rx), name="s3-conn")

    async def _handle(self, tx, rx) -> None:
        svc = self.service
        try:
            while (req := await rx.recv()) is not None:
                op, params = req
                try:
                    fn = getattr(svc, op, None)
                    if fn is None:
                        raise S3Error("NotImplemented", op)
                    if op in ("put_object", "copy_object", "complete_multipart_upload",
                              "create_multipart_upload"):
                        params = {**params, "now": sim_time.now()}
                    tx.send(("ok", fn(**params)))
                except S3Error as e:
                    tx.send(("err", (e.code, e.message)))
        except ConnectionReset:
            pass
        finally:
            tx.close()  # real mode: one fd per connection must not linger


# -- client --------------------------------------------------------------------


class Config:
    """Reference: src/config.rs (endpoint_url is the only knob that
    matters in-sim)."""

    def __init__(self, endpoint_url: str):
        self.endpoint_url = endpoint_url


class _FluentOp:
    """aws-sdk fluent builder: unknown attribute calls set parameters,
    `.send()` performs the request (reference: src/operation/*.rs)."""

    def __init__(self, client: "Client", op: str):
        self._client = client
        self._op = op
        self._params: Dict[str, Any] = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def setter(value: Any) -> "_FluentOp":
            self._params[name] = value
            return self

        return setter

    async def send(self):
        return await self._client._call(self._op, self._params)


class Client:
    """Reference: src/client.rs `Client::from_conf`."""

    _OPS = [
        "create_bucket",
        "delete_bucket",
        "put_object",
        "get_object",
        "head_object",
        "copy_object",
        "delete_object",
        "delete_objects",
        "list_objects_v2",
        "create_multipart_upload",
        "upload_part",
        "complete_multipart_upload",
        "abort_multipart_upload",
        "put_bucket_lifecycle_configuration",
        "get_bucket_lifecycle_configuration",
    ]

    def __init__(self, config: Config):
        self._endpoint_url = config.endpoint_url
        self._addr = parse_addr(config.endpoint_url.replace("http://", ""))
        self._caller: Optional[StreamCaller] = None
        # real mode with an HTTP(S3) endpoint reachable: genuine REST +
        # SigV4 passthrough (reference: madsim-aws-sdk-s3 non-sim build
        # re-exporting the real aws-sdk client)
        self._real = None

    @staticmethod
    def from_conf(config: Config) -> "Client":
        return Client(config)

    def __getattr__(self, name: str):
        if name in Client._OPS:
            return lambda: _FluentOp(self, name)
        raise AttributeError(name)

    async def close(self) -> None:
        """Release the backend (REST keep-alive connection or sim fd).
        The REST close contends with in-flight requests on the
        connection lock, so it runs off the event loop."""
        if self._real is not None:
            real, self._real = self._real, None
            from ...dual import IS_SIM

            if IS_SIM:
                real.close()
            else:
                import asyncio

                await asyncio.to_thread(real.close)
        if self._caller is not None:
            self._caller.close()
            self._caller = None

    async def _call(self, op: str, params: Dict[str, Any]):
        if self._caller is None and self._real is None:
            from ...dual import IS_SIM, real_passthrough_enabled

            if not IS_SIM and real_passthrough_enabled():
                from .real_client import probe_real_s3

                self._real = await probe_real_s3(self._endpoint_url)
        if self._real is not None:
            return await self._real.call(op, params)
        if self._caller is None:
            self._caller = StreamCaller()
            await self._caller.open(self._addr)
        idem = op in ("get_object", "head_object", "list_objects_v2",
                      "get_bucket_lifecycle_configuration")
        rsp = await self._caller.call((op, params), idempotent=idem)
        if rsp is None:
            raise S3Error("ServiceUnavailable", "s3 server unreachable")
        status, payload = rsp
        if status == "err":
            code, msg = payload
            raise S3Error(code, msg)
        return payload
