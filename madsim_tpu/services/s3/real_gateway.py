"""S3HttpGateway — genuine S3 REST wire (path-style, XML) served from
the sim `S3Service` state machine over asyncio streams; the inverse of
`real_client.py` and the s3 twin of the etcd gRPC gateway.

Used by in-process tests to prove the real-mode S3 passthrough speaks
the actual protocol, and by
`python -m madsim_tpu serve --service s3 --http` to give real-mode apps
(or any S3 SDK pointed at the endpoint) an S3-compatible server.

Signatures are accepted but not verified (like minio's anonymous mode);
bind only on trusted interfaces."""

from __future__ import annotations

import asyncio
import datetime
import random
# madsim: allow-file(D001,D002) — genuine-wire S3 gateway: runs only
# against real clients on real sockets (request ids, lifecycle now).
import time
import urllib.parse
from email.utils import formatdate
from typing import Dict, Optional, Tuple

from . import S3Error, S3Service

__all__ = ["S3HttpGateway"]

_STATUS = {
    "NoSuchBucket": 404, "NoSuchKey": 404, "NoSuchUpload": 404,
    "BucketAlreadyExists": 409, "BucketNotEmpty": 409,
    "InvalidRange": 416, "InvalidArgument": 400, "NotImplemented": 501,
}
_REASONS = {200: "OK", 204: "No Content", 206: "Partial Content",
            400: "Bad Request", 404: "Not Found", 409: "Conflict",
            416: "Range Not Satisfiable", 501: "Not Implemented"}


class _Rng:
    def next_u64(self) -> int:
        return random.getrandbits(64)


def _iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, tz=datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.000Z")


def _xml_escape(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;"))


class S3HttpGateway:
    def __init__(self, lifecycle_interval: float = 3600.0):
        self.svc = S3Service(_Rng())
        self.lifecycle_interval = lifecycle_interval
        self._server: Optional[asyncio.AbstractServer] = None
        self._lc_task: Optional[asyncio.Task] = None
        # open keep-alive connections; stop() must close them or
        # wait_closed() blocks on their handlers (3.12 semantics)
        self._writers: set = set()

    async def start(self, addr: str = "127.0.0.1:0") -> int:
        host, _, port = addr.rpartition(":")
        self._server = await asyncio.start_server(self._conn, host or "127.0.0.1", int(port))

        async def lifecycle():
            while True:
                await asyncio.sleep(self.lifecycle_interval)
                self.svc.apply_lifecycle(time.time())

        self._lc_task = asyncio.ensure_future(lifecycle())
        return self._server.sockets[0].getsockname()[1]

    async def wait(self) -> None:
        """Block until the server terminates (public CLI surface)."""
        await self._server.serve_forever()

    async def serve(self, addr: str) -> None:
        await self.start(addr)
        await self.wait()

    async def stop(self) -> None:
        if self._lc_task is not None:
            self._lc_task.cancel()
        for w in list(self._writers):
            w.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- HTTP plumbing --------------------------------------------------------

    async def _conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._writers.add(writer)
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, target, _ver = line.decode().split(None, 2)
                except ValueError:
                    return
                headers: Dict[str, str] = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                n = int(headers.get("content-length", 0))
                body = await reader.readexactly(n) if n else b""
                status, out_headers, out_body = self._route(method, target, headers, body)
                reason = _REASONS.get(status, "Error")
                head = [f"HTTP/1.1 {status} {reason}"]
                out_headers.setdefault("content-length", str(len(out_body)))
                out_headers.setdefault("connection", "keep-alive")
                # S3 identity marker: every real implementation sets it,
                # and probe_real_s3 requires it (or an S3 XML root) to
                # distinguish a genuine store from a random HTTP server
                out_headers.setdefault(
                    "x-amz-request-id", f"{random.getrandbits(64):016X}"
                )
                head += [f"{k}: {v}" for k, v in out_headers.items()]
                writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
                if method != "HEAD":
                    writer.write(out_body)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            self._writers.discard(writer)
            writer.close()

    def _error(self, e: S3Error) -> Tuple[int, Dict[str, str], bytes]:
        body = (
            f'<?xml version="1.0"?><Error><Code>{_xml_escape(e.code)}</Code>'
            f"<Message>{_xml_escape(e.message)}</Message></Error>"
        ).encode()
        return _STATUS.get(e.code, 400), {"content-type": "application/xml"}, body

    @staticmethod
    def _obj_headers(info: dict) -> Dict[str, str]:
        h = {
            "etag": f'"{info["e_tag"]}"',
            "last-modified": formatdate(info["last_modified"], usegmt=True),
            "content-type": info.get("content_type", "binary/octet-stream"),
        }
        for k, v in (info.get("metadata") or {}).items():
            h[f"x-amz-meta-{k}"] = v
        return h

    # -- routing --------------------------------------------------------------

    def _route(self, method: str, target: str, headers, body) -> Tuple[int, Dict[str, str], bytes]:
        u = urllib.parse.urlsplit(target)
        q = dict(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
        parts = u.path.lstrip("/").split("/", 1)
        bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        try:
            return self._dispatch(method, bucket, key, q, headers, body)
        except S3Error as e:
            return self._error(e)

    def _dispatch(self, method, bucket, key, q, headers, body):
        svc = self.svc
        now = time.time()
        xml_hdr = {"content-type": "application/xml"}
        if not bucket:
            # ListBuckets — enough for SDK probes
            names = "".join(
                f"<Bucket><Name>{_xml_escape(b)}</Name></Bucket>" for b in sorted(svc.buckets)
            )
            return 200, xml_hdr, (
                f'<?xml version="1.0"?><ListAllMyBucketsResult><Buckets>{names}'
                f"</Buckets></ListAllMyBucketsResult>"
            ).encode()

        if not key:
            if method == "PUT" and "lifecycle" in q:
                import xml.etree.ElementTree as ET

                root = ET.fromstring(body)
                rules = []
                for r in root:
                    if not r.tag.endswith("Rule"):
                        continue
                    d = {c.tag.rsplit("}", 1)[-1]: c for c in r}
                    rule = {"id": d["ID"].text or "" if "ID" in d else "",
                            "status": d["Status"].text if "Status" in d else "Enabled"}
                    if "Filter" in d:
                        for c in d["Filter"]:
                            if c.tag.endswith("Prefix"):
                                rule["prefix"] = c.text or ""
                    if "Prefix" in d:
                        rule["prefix"] = d["Prefix"].text or ""
                    if "Expiration" in d:
                        for c in d["Expiration"]:
                            if c.tag.endswith("Days"):
                                rule["days"] = int(c.text)
                    if "AbortIncompleteMultipartUpload" in d:
                        for c in d["AbortIncompleteMultipartUpload"]:
                            if c.tag.endswith("DaysAfterInitiation"):
                                rule["abort_multipart_days"] = int(c.text)
                    rules.append(rule)
                svc.put_bucket_lifecycle_configuration(bucket, {"rules": rules})
                return 200, {}, b""
            if method == "GET" and "lifecycle" in q:
                cfg = svc.get_bucket_lifecycle_configuration(bucket)
                rules = []
                for r in cfg.get("rules", []):
                    seg = [f"<ID>{_xml_escape(r.get('id', ''))}</ID>",
                           f"<Status>{r.get('status', 'Enabled')}</Status>",
                           f"<Filter><Prefix>{_xml_escape(r.get('prefix', ''))}</Prefix></Filter>"]
                    if "days" in r:
                        seg.append(f"<Expiration><Days>{r['days']}</Days></Expiration>")
                    if "abort_multipart_days" in r:
                        seg.append(
                            "<AbortIncompleteMultipartUpload><DaysAfterInitiation>"
                            f"{r['abort_multipart_days']}"
                            "</DaysAfterInitiation></AbortIncompleteMultipartUpload>"
                        )
                    rules.append(f"<Rule>{''.join(seg)}</Rule>")
                return 200, xml_hdr, (
                    f'<?xml version="1.0"?><LifecycleConfiguration>{"".join(rules)}'
                    f"</LifecycleConfiguration>"
                ).encode()
            if method == "PUT":
                svc.create_bucket(bucket)
                return 200, {}, b""
            if method == "DELETE":
                svc.delete_bucket(bucket)
                return 204, {}, b""
            if method == "POST" and "delete" in q:
                import xml.etree.ElementTree as ET

                root = ET.fromstring(body)
                keys = [
                    c2.text or ""
                    for c in root if c.tag.endswith("Object")
                    for c2 in c if c2.tag.endswith("Key")
                ]
                out = svc.delete_objects(bucket, keys)
                deleted = "".join(
                    f"<Deleted><Key>{_xml_escape(k)}</Key></Deleted>" for k in out["deleted"]
                )
                return 200, xml_hdr, (
                    f'<?xml version="1.0"?><DeleteResult>{deleted}</DeleteResult>'
                ).encode()
            if method in ("GET", "HEAD") and q.get("list-type") == "2":
                import base64

                cont = q.get("continuation-token")
                if cont:
                    # tokens are opaque to clients (genuine S3 base64s
                    # them); the sim token contains a NUL separator that
                    # XML cannot carry raw
                    cont = base64.urlsafe_b64decode(cont).decode("utf-8")
                out = svc.list_objects_v2(
                    bucket,
                    prefix=q.get("prefix", ""),
                    continuation=cont,
                    max_keys=int(q.get("max-keys", 1000)),
                    delimiter=q.get("delimiter") or None,
                    start_after=q.get("start-after") or None,
                )
                contents = "".join(
                    "<Contents>"
                    f"<Key>{_xml_escape(c['key'])}</Key>"
                    f"<Size>{c['size']}</Size>"
                    f"<ETag>\"{c['e_tag']}\"</ETag>"
                    f"<LastModified>{_iso(c['last_modified'])}</LastModified>"
                    "</Contents>"
                    for c in out["contents"]
                )
                prefixes = "".join(
                    f"<CommonPrefixes><Prefix>{_xml_escape(cp['prefix'])}</Prefix></CommonPrefixes>"
                    for cp in out["common_prefixes"]
                )
                token = out["next_continuation_token"]
                if token:
                    token = base64.urlsafe_b64encode(token.encode("utf-8")).decode()
                token_xml = (
                    f"<NextContinuationToken>{token}</NextContinuationToken>"
                    if token else ""
                )
                return 200, xml_hdr, (
                    f'<?xml version="1.0"?><ListBucketResult>'
                    f"<IsTruncated>{'true' if out['is_truncated'] else 'false'}</IsTruncated>"
                    f"<KeyCount>{out['key_count']}</KeyCount>"
                    f"{contents}{prefixes}{token_xml}</ListBucketResult>"
                ).encode()
            raise S3Error("NotImplemented", f"{method} /{bucket}?{sorted(q)}")

        # -- object routes --
        if method == "POST" and "uploads" in q:
            out = svc.create_multipart_upload(bucket, key, now)
            return 200, xml_hdr, (
                f'<?xml version="1.0"?><InitiateMultipartUploadResult>'
                f"<Bucket>{_xml_escape(bucket)}</Bucket><Key>{_xml_escape(key)}</Key>"
                f"<UploadId>{out['upload_id']}</UploadId>"
                f"</InitiateMultipartUploadResult>"
            ).encode()
        if method == "POST" and "uploadId" in q:
            out = svc.complete_multipart_upload(q["uploadId"], now)
            return 200, xml_hdr | {"etag": f'"{out["e_tag"]}"'}, (
                f'<?xml version="1.0"?><CompleteMultipartUploadResult>'
                f"<ETag>\"{out['e_tag']}\"</ETag></CompleteMultipartUploadResult>"
            ).encode()
        if method == "PUT" and "uploadId" in q:
            out = svc.upload_part(q["uploadId"], int(q.get("partNumber", 0)), body)
            return 200, {"etag": f'"{out["e_tag"]}"'}, b""
        if method == "DELETE" and "uploadId" in q:
            svc.abort_multipart_upload(q["uploadId"])
            return 204, {}, b""
        if method == "PUT" and "x-amz-copy-source" in headers:
            src = headers["x-amz-copy-source"].lstrip("/")
            src_bucket, _, src_key = src.partition("/")
            out = svc.copy_object(
                urllib.parse.unquote(src_bucket), urllib.parse.unquote(src_key),
                bucket, key, now,
            )
            return 200, xml_hdr, (
                f'<?xml version="1.0"?><CopyObjectResult><ETag>"{out["e_tag"]}"</ETag>'
                f"<LastModified>{_iso(now)}</LastModified></CopyObjectResult>"
            ).encode()
        if method == "PUT":
            metadata = {
                k[len("x-amz-meta-"):]: v for k, v in headers.items()
                if k.startswith("x-amz-meta-")
            }
            out = svc.put_object(
                bucket, key, body, now,
                content_type=headers.get("content-type"),
                metadata=metadata or None,
            )
            return 200, {"etag": f'"{out["e_tag"]}"'}, b""
        if method == "GET":
            info = svc.get_object(bucket, key, range=headers.get("range"))
            h = self._obj_headers(info)
            if "content_range" in info:
                h["content-range"] = info["content_range"]
                return 206, h, info["body"]
            return 200, h, info["body"]
        if method == "HEAD":
            info = svc.head_object(bucket, key)
            h = self._obj_headers(info)
            h["content-length"] = str(info["content_length"])
            return 200, h, b""
        if method == "DELETE":
            svc.delete_object(bucket, key)
            return 204, {}, b""
        raise S3Error("NotImplemented", f"{method} /{bucket}/{key}")
