"""Real-client passthrough for S3 — the analogue of the reference's
non-sim build re-exporting the genuine aws-sdk-s3 client
(`/root/reference/madsim-aws-sdk-s3/src/lib.rs` non-sim re-export).

`RealS3Backend` speaks the genuine S3 REST protocol (path-style
addressing, AWS Signature V4, XML bodies) with nothing but the standard
library — the protocol, not a vendor SDK, is what the reference's dual
build guarantees. It translates the sim Client's `(op, params)` calls
into signed HTTP requests and parses responses back into the exact
payload shapes `S3Service` produces, so app code can't tell which
backend answered.

Credentials come from the standard env vars (`AWS_ACCESS_KEY_ID`,
`AWS_SECRET_ACCESS_KEY`, optional `AWS_SESSION_TOKEN`, region from
`AWS_REGION`/`AWS_DEFAULT_REGION`, default us-east-1). Works against
AWS and S3-compatible stores (minio, localstack) and against
`python -m madsim_tpu serve --service s3 --http` (real_gateway.py).

The SigV4 signer is validated against AWS's published signature test
vector (tests/test_s3_real.py)."""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import http.client
import os
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import parsedate_to_datetime
from typing import Dict, Optional, Tuple

from . import S3Error

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


# -- AWS Signature V4 (stdlib) ------------------------------------------------


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def sigv4_sign(
    method: str,
    path: str,
    query: Dict[str, str],
    headers: Dict[str, str],
    payload_hash: str,
    *,
    access_key: str,
    secret_key: str,
    region: str,
    service: str = "s3",
    amz_date: str,
) -> str:
    """Returns the Authorization header value (AWS SigV4, single chunk).

    Pure function of its inputs so it can be checked against AWS's
    published test vectors."""
    date = amz_date[:8]
    canonical_query = "&".join(
        f"{_uri_encode(k)}={_uri_encode(str(v))}" for k, v in sorted(query.items())
    )
    lower = {k.lower(): " ".join(str(v).split()) for k, v in headers.items()}
    signed_headers = ";".join(sorted(lower))
    canonical_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
    canonical_request = "\n".join(
        [method, _uri_encode(path, encode_slash=False), canonical_query,
         canonical_headers, signed_headers, payload_hash]
    )
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        ["AWS4-HMAC-SHA256", amz_date, scope,
         hashlib.sha256(canonical_request.encode()).hexdigest()]
    )
    k = _hmac(_hmac(_hmac(_hmac(b"AWS4" + secret_key.encode(), date), region), service),
              "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    return (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )


# -- XML helpers --------------------------------------------------------------


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _xml_escape(s: str) -> str:
    return s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")


def _xml_dict(elem) -> dict:
    return {_strip_ns(c.tag): c for c in elem}


def _text(elem, name: str, default: str = "") -> str:
    for c in elem:
        if _strip_ns(c.tag) == name:
            return c.text or ""
    return default


def _epoch(iso_or_http: str) -> float:
    """ISO8601 (XML) or RFC7231 (Last-Modified header) -> epoch float."""
    if not iso_or_http:
        return 0.0
    try:
        return datetime.datetime.fromisoformat(
            iso_or_http.replace("Z", "+00:00")
        ).timestamp()
    except ValueError:
        try:
            return parsedate_to_datetime(iso_or_http).timestamp()
        except (TypeError, ValueError):
            return 0.0


class RealS3Backend:
    """(op, params) -> signed REST call -> sim-shaped payload."""

    def __init__(self, host: str, port: int, *, access_key: str, secret_key: str,
                 region: str, session_token: Optional[str] = None, timeout: float = 10.0,
                 tls: bool = False):
        import threading

        self.host = host
        self.port = port
        self.tls = tls
        self.access_key = access_key
        self.secret_key = secret_key
        self.session_token = session_token
        self.region = region
        self.timeout = timeout
        # one cached keep-alive connection, serialized: http.client
        # connections are not thread-safe and asyncio.to_thread may run
        # requests on different worker threads
        self._conn_lock = threading.Lock()
        self._conn = None

    @classmethod
    def from_env(cls, endpoint_url: str, timeout: float = 10.0) -> "RealS3Backend":
        u = urllib.parse.urlparse(
            endpoint_url if "://" in endpoint_url else f"http://{endpoint_url}"
        )
        tls = u.scheme == "https"
        return cls(
            u.hostname or "127.0.0.1", u.port or (443 if tls else 80), tls=tls,
            access_key=os.environ.get("AWS_ACCESS_KEY_ID", "madsim"),
            secret_key=os.environ.get("AWS_SECRET_ACCESS_KEY", "madsim"),
            session_token=os.environ.get("AWS_SESSION_TOKEN"),
            region=os.environ.get("AWS_REGION")
            or os.environ.get("AWS_DEFAULT_REGION", "us-east-1"),
            timeout=timeout,
        )

    # -- transport ------------------------------------------------------------

    def _request_sync(self, method: str, path: str, query: Dict[str, str],
                      headers: Dict[str, str], body: bytes) -> Tuple[int, Dict[str, str], bytes]:
        payload_hash = hashlib.sha256(body).hexdigest() if body else _EMPTY_SHA256
        # madsim: allow(D001) — SigV4 signing needs the real UTC date
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
        h = dict(headers)
        default_port = 443 if self.tls else 80
        h["host"] = (
            self.host if self.port == default_port else f"{self.host}:{self.port}"
        )
        h["x-amz-date"] = amz_date
        h["x-amz-content-sha256"] = payload_hash
        if self.session_token:
            h["x-amz-security-token"] = self.session_token
        h["Authorization"] = sigv4_sign(
            method, path, query, h, payload_hash,
            access_key=self.access_key, secret_key=self.secret_key,
            region=self.region, amz_date=amz_date,
        )
        # the wire must carry EXACTLY the octets the signature
        # canonicalized: same percent-encoding for path and query
        enc_path = _uri_encode(path, encode_slash=False)
        qs = "&".join(
            f"{_uri_encode(k)}={_uri_encode(str(v))}" for k, v in sorted(query.items())
        )
        target = enc_path + (f"?{qs}" if qs else "")
        conn_cls = http.client.HTTPSConnection if self.tls else http.client.HTTPConnection
        idempotent = method in ("GET", "HEAD")
        with self._conn_lock:
            # keep-alive reuse; a stale cached connection (server closed
            # it between requests) gets one reconnect — but only when the
            # failure is provably pre-response: a SEND-time error always
            # (the server saw nothing complete), a response-time error
            # only for idempotent reads. A mutation whose response was
            # lost is ambiguous (the server may have applied it) and is
            # surfaced, never blindly re-sent — the retry discipline
            # services/_conn.py:32-37 documents for the sim protocol.
            for attempt in (0, 1):
                if self._conn is None:
                    self._conn = conn_cls(self.host, self.port, timeout=self.timeout)
                sent = False
                try:
                    self._conn.request(method, target, body=body or None, headers=h)
                    sent = True
                    rsp = self._conn.getresponse()
                    data = rsp.read()
                    return rsp.status, {k.lower(): v for k, v in rsp.getheaders()}, data
                except (http.client.HTTPException, ConnectionError, BrokenPipeError):
                    self._conn.close()
                    self._conn = None
                    if attempt or (sent and not idempotent):
                        raise
            raise AssertionError("unreachable")

    def close(self) -> None:
        with self._conn_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None

    async def _request(self, method: str, path: str, query=None, headers=None,
                       body: bytes = b"") -> Tuple[int, Dict[str, str], bytes]:
        return await asyncio.to_thread(
            self._request_sync, method, path, dict(query or {}), dict(headers or {}), body
        )

    @staticmethod
    def _raise(status: int, data: bytes) -> None:
        code, msg = "UnknownError", f"http {status}"
        if data:
            try:
                root = ET.fromstring(data)
                code = _text(root, "Code", code)
                msg = _text(root, "Message", msg)
            except ET.ParseError:
                pass
        elif status == 404:
            code = "NoSuchKey"
        raise S3Error(code, msg)

    # -- op dispatch (the SimServer request enum, over REST) ------------------

    async def call(self, op: str, p: Dict) -> Dict:
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise S3Error("NotImplemented", f"{op} has no real-mode mapping")
        return await fn(p)

    async def _op_create_bucket(self, p):
        st, _h, data = await self._request("PUT", f"/{p['bucket']}")
        if st not in (200, 204):
            self._raise(st, data)
        return {"location": f"/{p['bucket']}"}

    async def _op_delete_bucket(self, p):
        st, _h, data = await self._request("DELETE", f"/{p['bucket']}")
        if st not in (200, 204):
            self._raise(st, data)
        return {}

    async def _op_put_object(self, p):
        headers = {}
        if p.get("content_type"):
            headers["content-type"] = p["content_type"]
        for k, v in (p.get("metadata") or {}).items():
            headers[f"x-amz-meta-{k}"] = v
        body = p.get("body", b"")
        if isinstance(body, str):
            body = body.encode()
        st, h, data = await self._request(
            "PUT", f"/{p['bucket']}/{p['key']}", headers=headers, body=bytes(body)
        )
        if st != 200:
            self._raise(st, data)
        return {"e_tag": h.get("etag", "").strip('"')}

    async def _op_get_object(self, p, want_body: bool = True):
        headers = {}
        if p.get("range"):
            headers["range"] = p["range"]
        st, h, data = await self._request(
            "GET" if want_body else "HEAD", f"/{p['bucket']}/{p['key']}", headers=headers
        )
        if st not in (200, 206):
            self._raise(st, data)
        out = {
            "e_tag": h.get("etag", "").strip('"'),
            "last_modified": _epoch(h.get("last-modified", "")),
            "content_type": h.get("content-type", "binary/octet-stream"),
            "metadata": {
                k[len("x-amz-meta-"):]: v for k, v in h.items()
                if k.startswith("x-amz-meta-")
            },
        }
        if want_body:
            out["body"] = data
            out["content_length"] = len(data)
            if "content-range" in h:
                out["content_range"] = h["content-range"]
        else:
            out["content_length"] = int(h.get("content-length", 0))
        return out

    async def _op_head_object(self, p):
        return await self._op_get_object(p, want_body=False)

    async def _op_copy_object(self, p):
        headers = {"x-amz-copy-source": f"/{p['src_bucket']}/{p['src_key']}"}
        st, h, data = await self._request(
            "PUT", f"/{p['bucket']}/{p['key']}", headers=headers
        )
        if st != 200:
            self._raise(st, data)
        etag = h.get("etag", "").strip('"')
        if data:
            try:
                etag = _text(ET.fromstring(data), "ETag", etag).strip('"')
            except ET.ParseError:
                pass
        return {"e_tag": etag}

    async def _op_delete_object(self, p):
        st, _h, data = await self._request("DELETE", f"/{p['bucket']}/{p['key']}")
        if st not in (200, 204):
            self._raise(st, data)
        return {}

    async def _op_delete_objects(self, p):
        objs = "".join(
            f"<Object><Key>{_xml_escape(k)}</Key></Object>" for k in p.get("keys", [])
        )
        body = f'<?xml version="1.0"?><Delete>{objs}</Delete>'.encode()
        import base64

        headers = {"content-md5": base64.b64encode(hashlib.md5(body).digest()).decode()}
        st, _h, data = await self._request(
            "POST", f"/{p['bucket']}", query={"delete": ""}, headers=headers, body=body
        )
        if st != 200:
            self._raise(st, data)
        root = ET.fromstring(data)
        return {"deleted": [
            _text(c, "Key") for c in root if _strip_ns(c.tag) == "Deleted"
        ]}

    async def _op_list_objects_v2(self, p):
        query = {"list-type": "2"}
        if p.get("prefix"):
            query["prefix"] = p["prefix"]
        if p.get("continuation"):
            query["continuation-token"] = p["continuation"]
        if p.get("max_keys"):
            query["max-keys"] = str(p["max_keys"])
        if p.get("delimiter"):
            query["delimiter"] = p["delimiter"]
        if p.get("start_after"):
            query["start-after"] = p["start_after"]
        st, _h, data = await self._request("GET", f"/{p['bucket']}", query=query)
        if st != 200:
            self._raise(st, data)
        root = ET.fromstring(data)
        contents, common = [], []
        for c in root:
            tag = _strip_ns(c.tag)
            if tag == "Contents":
                contents.append({
                    "key": _text(c, "Key"),
                    "size": int(_text(c, "Size", "0")),
                    "e_tag": _text(c, "ETag").strip('"'),
                    "last_modified": _epoch(_text(c, "LastModified")),
                })
            elif tag == "CommonPrefixes":
                common.append({"prefix": _text(c, "Prefix")})
        token = _text(root, "NextContinuationToken") or None
        return {
            "contents": contents,
            "common_prefixes": common,
            "is_truncated": _text(root, "IsTruncated") == "true",
            "next_continuation_token": token,
            "key_count": int(_text(root, "KeyCount", "0") or 0),
        }

    async def _op_create_multipart_upload(self, p):
        st, _h, data = await self._request(
            "POST", f"/{p['bucket']}/{p['key']}", query={"uploads": ""}
        )
        if st != 200:
            self._raise(st, data)
        root = ET.fromstring(data)
        upload_id = _text(root, "UploadId")
        self._mpu = getattr(self, "_mpu", {})
        self._mpu[upload_id] = (p["bucket"], p["key"], {})
        return {"upload_id": upload_id}

    async def _op_upload_part(self, p):
        bucket, key, etags = self._mpu_entry(p["upload_id"])
        body = p.get("body", b"")
        if isinstance(body, str):
            body = body.encode()
        st, h, data = await self._request(
            "PUT", f"/{bucket}/{key}",
            query={"partNumber": str(p["part_number"]), "uploadId": p["upload_id"]},
            body=bytes(body),
        )
        if st != 200:
            self._raise(st, data)
        etag = h.get("etag", "").strip('"')
        etags[p["part_number"]] = etag
        return {"e_tag": etag}

    async def _op_complete_multipart_upload(self, p):
        bucket, key, etags = self._mpu_entry(p["upload_id"])
        parts = "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>\"{etags[n]}\"</ETag></Part>"
            for n in sorted(etags)
        )
        body = (
            f'<?xml version="1.0"?><CompleteMultipartUpload>{parts}'
            f"</CompleteMultipartUpload>"
        ).encode()
        st, h, data = await self._request(
            "POST", f"/{bucket}/{key}", query={"uploadId": p["upload_id"]}, body=body
        )
        if st != 200:
            self._raise(st, data)
        self._mpu.pop(p["upload_id"], None)
        etag = h.get("etag", "").strip('"')
        if data:
            try:
                etag = _text(ET.fromstring(data), "ETag", etag).strip('"')
            except ET.ParseError:
                pass
        return {"e_tag": etag}

    async def _op_abort_multipart_upload(self, p):
        bucket, key, _etags = self._mpu_entry(p["upload_id"])
        st, _h, data = await self._request(
            "DELETE", f"/{bucket}/{key}", query={"uploadId": p["upload_id"]}
        )
        if st not in (200, 204):
            self._raise(st, data)
        self._mpu.pop(p["upload_id"], None)
        return {}

    def _mpu_entry(self, upload_id: str):
        entry = getattr(self, "_mpu", {}).get(upload_id)
        if entry is None:
            raise S3Error("NoSuchUpload", upload_id)
        return entry

    async def _op_put_bucket_lifecycle_configuration(self, p):
        rules = []
        for r in (p.get("config") or {}).get("rules", []):
            parts = [f"<ID>{_xml_escape(r.get('id', ''))}</ID>",
                     f"<Status>{r.get('status', 'Enabled')}</Status>",
                     f"<Filter><Prefix>{_xml_escape(r.get('prefix', ''))}</Prefix></Filter>"]
            if "days" in r:
                parts.append(f"<Expiration><Days>{r['days']}</Days></Expiration>")
            if "abort_multipart_days" in r:
                parts.append(
                    "<AbortIncompleteMultipartUpload><DaysAfterInitiation>"
                    f"{r['abort_multipart_days']}"
                    "</DaysAfterInitiation></AbortIncompleteMultipartUpload>"
                )
            rules.append(f"<Rule>{''.join(parts)}</Rule>")
        body = (
            f'<?xml version="1.0"?><LifecycleConfiguration>{"".join(rules)}'
            f"</LifecycleConfiguration>"
        ).encode()
        import base64

        headers = {"content-md5": base64.b64encode(hashlib.md5(body).digest()).decode()}
        st, _h, data = await self._request(
            "PUT", f"/{p['bucket']}", query={"lifecycle": ""}, headers=headers, body=body
        )
        if st not in (200, 204):
            self._raise(st, data)
        return {}

    async def _op_get_bucket_lifecycle_configuration(self, p):
        st, _h, data = await self._request(
            "GET", f"/{p['bucket']}", query={"lifecycle": ""}
        )
        if st == 404:
            return {"rules": []}
        if st != 200:
            self._raise(st, data)
        rules = []
        root = ET.fromstring(data)
        for r in root:
            if _strip_ns(r.tag) != "Rule":
                continue
            d = _xml_dict(r)
            rule = {"id": _text(r, "ID"), "status": _text(r, "Status", "Enabled")}
            if "Filter" in d:
                rule["prefix"] = _text(d["Filter"], "Prefix")
            elif "Prefix" in d:
                rule["prefix"] = d["Prefix"].text or ""
            if "Expiration" in d:
                rule["days"] = int(_text(d["Expiration"], "Days", "0"))
            if "AbortIncompleteMultipartUpload" in d:
                rule["abort_multipart_days"] = int(
                    _text(d["AbortIncompleteMultipartUpload"], "DaysAfterInitiation", "0")
                )
            rules.append(rule)
        return {"rules": rules}


async def probe_real_s3(endpoint_url: str, timeout: float = 2.0) -> Optional[RealS3Backend]:
    """Endpoint answers HTTP like an S3 store -> backend; else None
    (caller falls back to the sim pickle protocol)."""
    backend = RealS3Backend.from_env(endpoint_url, timeout=timeout)
    try:
        st, headers, data = await backend._request("GET", "/")
    except Exception:
        return None
    # An HTTP answer alone is not enough — any web server would match,
    # locking a misconfigured app onto the REST path with opaque XML
    # errors instead of the documented sim-protocol fallback. Require an
    # S3-specific marker: the x-amz-request-id/x-amz-id-2 headers every
    # S3 implementation (AWS, MinIO, ceph-rgw, our gateway) sets, or an
    # S3 XML document root (ListAllMyBucketsResult on 200, Error with an
    # S3 error code otherwise).
    if not (100 <= st <= 599):
        return None
    hdrs = {k.lower() for k in headers} if headers else set()
    s3_marker = "x-amz-request-id" in hdrs or "x-amz-id-2" in hdrs
    if not s3_marker and data:
        try:
            root_tag = _strip_ns(ET.fromstring(data).tag)
            s3_marker = root_tag in ("ListAllMyBucketsResult", "Error")
        except ET.ParseError:
            s3_marker = False
    if not s3_marker:
        return None
    # the short PROBE deadline must not become the per-request
    # socket timeout for real operations (etcd learned this too)
    backend.timeout = 30.0
    backend.close()  # drop the probe-deadline connection
    return backend
