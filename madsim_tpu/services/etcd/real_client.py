"""Real-client passthrough for etcd — the analogue of the reference's
non-sim build re-exporting the genuine client
(`/root/reference/madsim-etcd-client/src/lib.rs:5-6`
``pub use etcd_client::*``).

Under ``MADSIM_TPU_MODE=real``, `services.etcd.Client.connect` probes
the endpoint with a genuine etcd v3 gRPC Status call; if it answers,
every Client operation is translated onto the real etcd wire protocol
(etcdserverpb / mvccpb / v3electionpb stubs generated from the bundled
protos by `madsim_tpu.grpc.build` — the same .proto ingestion the
reference drives through tonic-build). If the endpoint is not a real
etcd, the Client falls back to the sim-protocol server
(`python -m madsim_tpu serve`), preserving round-3 behavior.

No `etcd3`-style third-party client is required: grpcio + the published
v3 API field numbers *are* the genuine client, exactly as the
reference's etcd-client crate is tonic + these same protos.

Also here: `EtcdGrpcGateway`, the inverse adapter — an etcd-wire gRPC
server backed by the sim `EtcdService` state machine, used to test the
passthrough in-process and to serve real clients from
`python -m madsim_tpu serve --service etcd --grpc`.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

from .service import EtcdError, Event, KeyValue

_PROTO_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "protos")

_ns_cache = None


def protos():
    """Generated etcd stubs (KV/Watch/Lease/Maintenance/Election)."""
    global _ns_cache
    if _ns_cache is None:
        from ...grpc import build

        _ns_cache = build.load(
            os.path.join(_PROTO_DIR, "mvcc.proto"),
            os.path.join(_PROTO_DIR, "rpc.proto"),
            os.path.join(_PROTO_DIR, "election.proto"),
            includes=[_PROTO_DIR],
        )
    return _ns_cache


def _merged_methods(ns) -> Dict:
    out = {}
    for client_cls in (
        ns.KVClient, ns.WatchClient, ns.LeaseClient, ns.MaintenanceClient, ns.ElectionClient
    ):
        out.update(client_cls._METHODS)
    return out


# -- pb <-> sim-shape translation ---------------------------------------------

_CMP_RESULT = {"=": 0, ">": 1, "<": 2, "!=": 3}
_CMP_TARGET = {"version": 0, "create_revision": 1, "mod_revision": 2, "value": 3}
_CMP_FIELD = {
    "version": "version",
    "create_revision": "create_revision",
    "mod_revision": "mod_revision",
    "value": "value",
}


def _kv_from_pb(pb) -> KeyValue:
    return KeyValue(
        bytes(pb.key), bytes(pb.value), pb.create_revision, pb.mod_revision,
        pb.version, pb.lease,
    )


def _compare_pb(ns, tup):
    target, key, op, operand = tup
    if target not in _CMP_TARGET:
        raise EtcdError(f"unsupported compare target {target!r}")
    if op not in _CMP_RESULT:
        raise EtcdError(f"unsupported compare op {op!r}")
    cmp = ns.Compare(result=_CMP_RESULT[op], target=_CMP_TARGET[target], key=key)
    setattr(cmp, _CMP_FIELD[target], operand)
    return cmp


def _request_op_pb(ns, op):
    kind = op[0]
    if kind == "put":
        return ns.RequestOp(
            request_put=ns.PutRequest(key=op[1], value=op[2], lease=op[3] if len(op) > 3 else 0)
        )
    if kind == "get":
        return ns.RequestOp(request_range=ns.RangeRequest(key=op[1], range_end=op[2]))
    if kind == "delete":
        return ns.RequestOp(request_delete_range=ns.DeleteRangeRequest(key=op[1], range_end=op[2]))
    raise EtcdError(f"unsupported txn op {kind!r}")


def _response_op_sim(pb):
    which = pb.WhichOneof("response")
    if which == "response_put":
        r = pb.response_put
        return ("put", {
            "revision": r.header.revision,
            "prev_kv": _kv_from_pb(r.prev_kv) if r.HasField("prev_kv") else None,
        })
    if which == "response_range":
        r = pb.response_range
        return ("get", {
            "revision": r.header.revision,
            "kvs": [_kv_from_pb(kv) for kv in r.kvs],
            "count": r.count,
        })
    if which == "response_delete_range":
        r = pb.response_delete_range
        return ("delete", {
            "revision": r.header.revision,
            "deleted": r.deleted,
            "prev_kvs": [_kv_from_pb(kv) for kv in r.prev_kvs],
        })
    raise EtcdError(f"unsupported txn response {which!r}")


def _leader_key_sim(lk) -> dict:
    return {"name": bytes(lk.name), "key": bytes(lk.key), "rev": lk.rev, "lease": lk.lease}


def _leader_key_pb(ns, d):
    return ns.LeaderKey(name=d["name"], key=d["key"], rev=d["rev"], lease=d["lease"])


class RealWatcher:
    """Genuine-etcd watch stream with the sim `Watcher` surface
    (`async for`, `progress_revision`, `progress()`, `cancel()`)."""

    def __init__(self, ns, req_q, stream):
        self._ns = ns
        self._req_q = req_q
        self._stream = stream
        self._pending = []
        self.progress_revision = 0

    def __aiter__(self):
        return self

    async def __anext__(self) -> Event:
        while True:
            if self._pending:
                return self._pending.pop(0)
            rsp = await self._stream.message()
            if rsp is None or rsp.canceled:
                raise StopAsyncIteration
            evs = self._translate(rsp)
            if not evs:
                continue
            self._pending.extend(evs[1:])
            return evs[0]

    def _translate(self, rsp):
        if rsp.compact_revision:
            raise EtcdError(
                f"required revision has been compacted (compact_revision "
                f"{rsp.compact_revision})"
            )
        self.progress_revision = max(self.progress_revision, rsp.header.revision)
        out = []
        for ev in rsp.events:
            kind = Event.DELETE if ev.type == 1 else Event.PUT
            prev = _kv_from_pb(ev.prev_kv) if ev.HasField("prev_kv") else None
            out.append(Event(kind, _kv_from_pb(ev.kv), prev))
        return out

    async def progress(self) -> int:
        """Request + await a progress notification
        (WatchProgressRequest); events in between are buffered."""
        ns = self._ns
        await self._req_q.put(ns.WatchRequest(progress_request=ns.WatchProgressRequest()))
        while True:
            rsp = await self._stream.message()
            if rsp is None:
                raise EtcdError("watch stream closed")
            if rsp.canceled:
                raise EtcdError(
                    f"watch canceled by server: {rsp.cancel_reason or 'unknown'}"
                )
            evs = self._translate(rsp)
            if evs:
                self._pending.extend(evs)
                continue
            return self.progress_revision

    def cancel(self) -> None:
        self._req_q.put_nowait(None)


class RealObserver:
    """Election observe stream with the sim `Observer` surface."""

    def __init__(self, stream, name: bytes):
        self._stream = stream
        self._name = name

    def __aiter__(self):
        return self

    async def __anext__(self) -> dict:
        rsp = await self._stream.message()
        if rsp is None:
            raise StopAsyncIteration
        kv = rsp.kv
        return {
            "leader": {"name": self._name, "key": bytes(kv.key),
                       "rev": kv.create_revision, "lease": kv.lease},
            "is_leader": False,
            "value": bytes(kv.value),
        }

    def cancel(self) -> None:
        self._stream._call.cancel()


class RealEtcdBackend:
    """Translates the sim Client's request tuples onto genuine etcd
    gRPC, returning the exact payload shapes `EtcdService` produces —
    app code cannot tell which backend answered."""

    def __init__(self, channel, ns):
        self._chan = channel
        self._ns = ns
        # One long-lived LeaseKeepAlive bidi stream for ALL keepalives
        # (etcd clients multiplex keepalives this way); opening a fresh
        # stream per call churns grpc.aio call objects and server-side
        # generators under frequent keepalives.
        self._ka = None  # (request queue, RealStreaming) or None
        self._ka_lock = None

    @classmethod
    async def connect(cls, endpoint: str, probe_timeout: float = 2.0) -> "RealEtcdBackend":
        """Open + probe with Maintenance.Status; raises on anything that
        is not a live etcd-wire server."""
        from ...grpc.real import RealChannel

        ns = protos()
        chan = await RealChannel.connect(
            endpoint, _merged_methods(ns), timeout=probe_timeout
        )
        try:
            await chan.unary("/etcdserverpb.Maintenance/Status", ns.StatusRequest())
        except Exception:
            await chan.close()
            raise
        # the probe deadline must not become the per-RPC deadline:
        # watch/observe streams are long-lived and Campaign blocks until
        # leadership — they would all die after probe_timeout seconds
        chan.set_default_timeout(None)
        return cls(RealChannelHolder(chan), ns)

    async def close(self) -> None:
        if self._ka is not None:
            self._ka[0].put_nowait(None)  # end the feeder generator
            self._ka = None
        await self._chan.chan.close()

    async def _keep_alive_once(self, lease_id: int):
        """One keepalive round-trip on the cached bidi stream; reopens
        the stream once if the server ended it (e.g. idle timeout)."""
        import asyncio

        ns = self._ns
        if self._ka_lock is None:
            self._ka_lock = asyncio.Lock()
        async with self._ka_lock:  # pair each request with its response
            for attempt in (0, 1):
                if self._ka is None:
                    q: asyncio.Queue = asyncio.Queue()

                    async def feed(q=q):
                        while True:
                            item = await q.get()
                            if item is None:
                                return
                            yield item

                    stream = await self._chan.chan.streaming(
                        "/etcdserverpb.Lease/LeaseKeepAlive", feed()
                    )
                    self._ka = (q, stream)
                q, stream = self._ka
                q.put_nowait(ns.LeaseKeepAliveRequest(ID=lease_id))
                try:
                    rsp = await stream.message()
                except BaseException as exc:
                    # the response is (or may be) in flight: the stream
                    # cannot be reused or later keepalives would read
                    # this call's response (request/response desync)
                    self._ka = None
                    q.put_nowait(None)  # end the feeder generator
                    if not isinstance(exc, Exception):
                        raise  # cancellation propagates
                    rsp = None
                if rsp is None:
                    self._ka = None
                    if attempt == 0:
                        continue  # stream was stale; retry on a fresh one
                    raise EtcdError("lease keepalive stream closed")
                return rsp
        raise AssertionError("unreachable")

    async def call(self, req: tuple):
        """The SimServer._apply dispatch, against the real wire."""
        from ...grpc import Status as GrpcStatus

        ns = self._ns
        ch = self._chan.chan
        kind = req[0]
        try:
            if kind == "put":
                r = await ch.unary(
                    "/etcdserverpb.KV/Put",
                    ns.PutRequest(key=req[1], value=req[2], lease=req[3], prev_kv=req[4]),
                )
                return {
                    "revision": r.header.revision,
                    "prev_kv": _kv_from_pb(r.prev_kv) if r.HasField("prev_kv") else None,
                }
            if kind == "get":
                r = await ch.unary(
                    "/etcdserverpb.KV/Range",
                    ns.RangeRequest(
                        key=req[1], range_end=req[2], limit=req[3],
                        count_only=req[4], keys_only=req[5],
                    ),
                )
                return {
                    "revision": r.header.revision,
                    "kvs": [] if req[4] else [_kv_from_pb(kv) for kv in r.kvs],
                    "count": r.count,
                }
            if kind == "delete":
                r = await ch.unary(
                    "/etcdserverpb.KV/DeleteRange",
                    ns.DeleteRangeRequest(key=req[1], range_end=req[2], prev_kv=req[3]),
                )
                return {
                    "revision": r.header.revision,
                    "deleted": r.deleted,
                    "prev_kvs": [_kv_from_pb(kv) for kv in r.prev_kvs],
                }
            if kind == "txn":
                r = await ch.unary(
                    "/etcdserverpb.KV/Txn",
                    ns.TxnRequest(
                        compare=[_compare_pb(ns, c) for c in req[1]],
                        success=[_request_op_pb(ns, o) for o in req[2]],
                        failure=[_request_op_pb(ns, o) for o in req[3]],
                    ),
                )
                return {
                    "revision": r.header.revision,
                    "succeeded": r.succeeded,
                    "responses": [_response_op_sim(op) for op in r.responses],
                }
            if kind == "compact":
                r = await ch.unary(
                    "/etcdserverpb.KV/Compact", ns.CompactionRequest(revision=req[1])
                )
                return {"revision": r.header.revision, "compact_revision": req[1]}
            if kind == "lease_grant":
                r = await ch.unary(
                    "/etcdserverpb.Lease/LeaseGrant",
                    ns.LeaseGrantRequest(TTL=req[1], ID=req[2]),
                )
                if r.error:
                    raise EtcdError(r.error)
                return {"id": r.ID, "ttl": r.TTL}
            if kind == "lease_revoke":
                r = await ch.unary(
                    "/etcdserverpb.Lease/LeaseRevoke", ns.LeaseRevokeRequest(ID=req[1])
                )
                return {"revision": r.header.revision}
            if kind == "lease_keep_alive":
                rsp = await self._keep_alive_once(req[1])
                if rsp.TTL <= 0:
                    raise EtcdError("etcdserver: requested lease not found")
                return {"id": rsp.ID, "ttl": rsp.TTL}
            if kind == "lease_time_to_live":
                r = await ch.unary(
                    "/etcdserverpb.Lease/LeaseTimeToLive",
                    ns.LeaseTimeToLiveRequest(ID=req[1], keys=True),
                )
                if r.TTL < 0:
                    raise EtcdError("etcdserver: requested lease not found")
                return {"id": r.ID, "granted_ttl": r.grantedTTL, "ttl": r.TTL,
                        "keys": [bytes(k) for k in r.keys]}
            if kind == "lease_list":
                r = await ch.unary(
                    "/etcdserverpb.Lease/LeaseLeases", ns.LeaseLeasesRequest()
                )
                return {"leases": sorted(s.ID for s in r.leases)}
            if kind == "campaign":
                # genuine Campaign blocks until leadership; the Client's
                # poll loop then sees is_leader on the first iteration
                r = await ch.unary(
                    "/v3electionpb.Election/Campaign",
                    ns.CampaignRequest(name=req[1], value=req[2], lease=req[3]),
                )
                return {
                    "leader": _leader_key_sim(r.leader),
                    "is_leader": True,
                    "value": req[2],
                }
            if kind == "leader":
                r = await ch.unary(
                    "/v3electionpb.Election/Leader", ns.LeaderRequest(name=req[1])
                )
                kv = r.kv
                return {
                    "leader": {"name": req[1], "key": bytes(kv.key),
                               "rev": kv.create_revision, "lease": kv.lease},
                    "is_leader": False,
                    "value": bytes(kv.value),
                }
            if kind == "proclaim":
                await ch.unary(
                    "/v3electionpb.Election/Proclaim",
                    ns.ProclaimRequest(leader=_leader_key_pb(ns, req[1]), value=req[2]),
                )
                return {"ok": True}
            if kind == "resign":
                await ch.unary(
                    "/v3electionpb.Election/Resign",
                    ns.ResignRequest(leader=_leader_key_pb(ns, req[1])),
                )
                return {"ok": True}
            if kind == "status":
                r = await ch.unary(
                    "/etcdserverpb.Maintenance/Status", ns.StatusRequest()
                )
                return {"version": r.version, "db_size": r.dbSize,
                        "revision": r.header.revision}
            if kind in ("dump", "load"):
                raise EtcdError(f"{kind} is sim-only (a genuine etcd has no TOML state API)")
            raise EtcdError(f"unknown request {kind}")
        except GrpcStatus as st:
            raise EtcdError(st.message or f"etcd rpc failed (code {st.code})") from None

    async def watch(self, lo: bytes, hi: bytes, opts: dict) -> RealWatcher:
        import asyncio

        ns = self._ns
        filters = []
        if "noput" in opts.get("filters", ()):
            filters.append(0)
        if "nodelete" in opts.get("filters", ()):
            filters.append(1)
        create = ns.WatchCreateRequest(
            key=lo, range_end=hi,
            start_revision=opts.get("start_revision", 0),
            progress_notify=opts.get("progress_notify", False),
            prev_kv=opts.get("prev_kv", False),
            filters=filters,
        )
        q: asyncio.Queue = asyncio.Queue()
        await q.put(ns.WatchRequest(create_request=create))

        async def feed():
            while True:
                item = await q.get()
                if item is None:
                    return
                yield item

        stream = await self._chan.chan.streaming("/etcdserverpb.Watch/Watch", feed())
        head = await stream.message()
        if head is not None and head.compact_revision:
            raise EtcdError(
                f"required revision has been compacted (compact_revision "
                f"{head.compact_revision})"
            )
        if head is None or not head.created:
            raise EtcdError(f"watch failed: {head}")
        return RealWatcher(ns, q, stream)

    async def observe(self, name: bytes) -> RealObserver:
        ns = self._ns
        stream = await self._chan.chan.server_streaming(
            "/v3electionpb.Election/Observe", ns.LeaderRequest(name=name)
        )
        return RealObserver(stream, name)


class RealChannelHolder:
    """Tiny indirection so the backend survives channel recreation."""

    def __init__(self, chan):
        self.chan = chan


async def try_connect_real(endpoints: Sequence[str], probe_timeout: float = 2.0) -> Optional[RealEtcdBackend]:
    """Probe each endpoint for a genuine etcd; None -> caller falls back
    to the sim-protocol server (the reference's dual behavior)."""
    for ep in endpoints:
        target = ep if isinstance(ep, str) else f"{ep[0]}:{ep[1]}"
        try:
            return await RealEtcdBackend.connect(target, probe_timeout)
        except Exception:
            continue
    return None
