"""EtcdGrpcGateway — the inverse of `real_client.py`: a genuine
etcd-wire gRPC server (etcdserverpb/mvccpb/v3electionpb over grpc.aio)
backed by the sim `EtcdService` state machine.

Used two ways:
  * in-process tests proving the real-client passthrough speaks the
    actual etcd protocol (tests/test_etcd_real.py) without needing an
    etcd binary;
  * `python -m madsim_tpu serve --service etcd --grpc` — real-mode
    apps (or genuine etcd clients) get an etcd-compatible server whose
    semantics are bit-aligned with the simulated one (beyond the
    reference, whose SimServer exists only inside the sim).

Runs on asyncio (real mode); virtual-time has no meaning here, so lease
TTLs tick on wall-clock seconds like genuine etcd.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

from ...grpc import Code, Status
from .service import EtcdError, EtcdService, Event
from .real_client import protos

__all__ = ["EtcdGrpcGateway"]

_CMP_OP = {0: "=", 1: ">", 2: "<", 3: "!="}
_CMP_TARGET = {0: "version", 1: "create_revision", 2: "mod_revision", 3: "value"}


class _Rng:
    def gen_range(self, lo: int, hi: int) -> int:
        # madsim: allow(D002) — real-gateway lease ids face real
        # clients; sim mode injects the seeded Rng instead
        return random.randrange(lo, hi)


def _err(e: EtcdError) -> Status:
    msg = str(e)
    code = Code.NOT_FOUND if "not found" in msg else (
        Code.OUT_OF_RANGE if "compacted" in msg else Code.UNKNOWN
    )
    return Status(code, msg)


class _Base:
    def __init__(self, gw: "EtcdGrpcGateway"):
        self.gw = gw
        self.ns = gw.ns
        self.svc = gw.svc

    def hdr(self):
        return self.ns.ResponseHeader(revision=self.svc.revision)

    def kv_pb(self, kv):
        return self.ns.KeyValue(
            key=kv.key, value=kv.value, create_revision=kv.create_revision,
            mod_revision=kv.mod_revision, version=kv.version, lease=kv.lease,
        )


class _KV(_Base):
    async def range(self, request):
        r = request.into_inner()
        try:
            out = self.svc.get(
                bytes(r.key), range_end=bytes(r.range_end), limit=r.limit,
                count_only=r.count_only, keys_only=r.keys_only,
            )
        except EtcdError as e:
            raise _err(e)
        return self.ns.RangeResponse(
            header=self.hdr(), kvs=[self.kv_pb(kv) for kv in out["kvs"]],
            count=out["count"],
        )

    async def put(self, request):
        r = request.into_inner()
        try:
            out = self.svc.put(bytes(r.key), bytes(r.value), lease=r.lease, prev_kv=r.prev_kv)
        except EtcdError as e:
            raise _err(e)
        rsp = self.ns.PutResponse(header=self.hdr())
        if out.get("prev_kv") is not None:
            rsp.prev_kv.CopyFrom(self.kv_pb(out["prev_kv"]))
        return rsp

    async def delete_range(self, request):
        r = request.into_inner()
        try:
            out = self.svc.delete(bytes(r.key), range_end=bytes(r.range_end), prev_kv=r.prev_kv)
        except EtcdError as e:
            raise _err(e)
        return self.ns.DeleteRangeResponse(
            header=self.hdr(), deleted=out["deleted"],
            prev_kvs=[self.kv_pb(kv) for kv in out["prev_kvs"]],
        )

    def _sim_compare(self, c):
        which = c.WhichOneof("target_union")
        operand = getattr(c, which) if which else 0
        if isinstance(operand, (bytes, bytearray, memoryview)):
            operand = bytes(operand)
        return (_CMP_TARGET[c.target], bytes(c.key), _CMP_OP[c.result], operand)

    def _sim_op(self, op):
        which = op.WhichOneof("request")
        if which == "request_put":
            p = op.request_put
            return ("put", bytes(p.key), bytes(p.value), p.lease)
        if which == "request_range":
            p = op.request_range
            return ("get", bytes(p.key), bytes(p.range_end))
        if which == "request_delete_range":
            p = op.request_delete_range
            return ("delete", bytes(p.key), bytes(p.range_end))
        raise Status(Code.UNIMPLEMENTED, f"txn op {which}")

    def _pb_response_op(self, kind, out):
        ns = self.ns
        if kind == "put":
            rsp = ns.PutResponse(header=ns.ResponseHeader(revision=out["revision"]))
            if out.get("prev_kv") is not None:
                rsp.prev_kv.CopyFrom(self.kv_pb(out["prev_kv"]))
            return ns.ResponseOp(response_put=rsp)
        if kind == "get":
            return ns.ResponseOp(response_range=ns.RangeResponse(
                header=ns.ResponseHeader(revision=out["revision"]),
                kvs=[self.kv_pb(kv) for kv in out["kvs"]], count=out["count"],
            ))
        return ns.ResponseOp(response_delete_range=ns.DeleteRangeResponse(
            header=ns.ResponseHeader(revision=out["revision"]), deleted=out["deleted"],
            prev_kvs=[self.kv_pb(kv) for kv in out["prev_kvs"]],
        ))

    async def txn(self, request):
        r = request.into_inner()
        try:
            out = self.svc.txn(
                [self._sim_compare(c) for c in r.compare],
                [self._sim_op(o) for o in r.success],
                [self._sim_op(o) for o in r.failure],
            )
        except EtcdError as e:
            raise _err(e)
        return self.ns.TxnResponse(
            header=self.hdr(), succeeded=out["succeeded"],
            responses=[self._pb_response_op(k, o) for k, o in out["responses"]],
        )

    async def compact(self, request):
        r = request.into_inner()
        try:
            self.svc.compact(r.revision)
        except EtcdError as e:
            raise _err(e)
        return self.ns.CompactionResponse(header=self.hdr())


class _Lease(_Base):
    async def lease_grant(self, request):
        r = request.into_inner()
        try:
            out = self.svc.lease_grant(r.TTL, r.ID)
        except EtcdError as e:
            return self.ns.LeaseGrantResponse(header=self.hdr(), error=str(e))
        return self.ns.LeaseGrantResponse(header=self.hdr(), ID=out["id"], TTL=out["ttl"])

    async def lease_revoke(self, request):
        try:
            self.svc.lease_revoke(request.into_inner().ID)
        except EtcdError as e:
            raise _err(e)
        return self.ns.LeaseRevokeResponse(header=self.hdr())

    async def lease_keep_alive(self, stream):
        while (req := await stream.message()) is not None:
            try:
                out = self.svc.lease_keep_alive(req.ID)
                yield self.ns.LeaseKeepAliveResponse(
                    header=self.hdr(), ID=out["id"], TTL=out["ttl"]
                )
            except EtcdError:
                # genuine etcd reports an expired lease as TTL=0, stream open
                yield self.ns.LeaseKeepAliveResponse(header=self.hdr(), ID=req.ID, TTL=0)

    async def lease_time_to_live(self, request):
        r = request.into_inner()
        try:
            out = self.svc.lease_time_to_live(r.ID)
        except EtcdError:
            return self.ns.LeaseTimeToLiveResponse(header=self.hdr(), ID=r.ID, TTL=-1)
        return self.ns.LeaseTimeToLiveResponse(
            header=self.hdr(), ID=out["id"], TTL=out["ttl"], grantedTTL=out["granted_ttl"],
            keys=out.get("keys", []),
        )

    async def lease_leases(self, request):
        out = self.svc.lease_list()
        return self.ns.LeaseLeasesResponse(
            header=self.hdr(),
            leases=[self.ns.LeaseStatus(ID=i) for i in out["leases"]],
        )


class _Watch(_Base):
    async def watch(self, stream):
        """One queue carries both client requests and store events, so
        there is a single await point (no racy cancellation of a
        half-consumed request iterator). Watches multiplex over the
        stream keyed by watch_id, like genuine etcd: each
        create_request gets its own id (client-chosen via
        WatchCreateRequest.watch_id or server-assigned), events carry
        it, and cancel_request tears down only that watch."""
        ns = self.ns
        q: asyncio.Queue = asyncio.Queue()
        # watch_id -> (svc watcher entry, filters, want_prev)
        watches: dict = {}
        next_id = [1]

        async def reader():
            while True:
                req = await stream.message()
                q.put_nowait(("req", req, None))
                if req is None:
                    return

        rt = asyncio.ensure_future(reader())
        try:
            while True:
                tag, item, wid = await q.get()
                if tag == "ev":
                    if wid not in watches:
                        continue  # canceled while queued
                    _entry, filters, want_prev = watches[wid]
                    ev = item
                    if ev.kind == Event.PUT and 0 in filters:
                        continue
                    if ev.kind == Event.DELETE and 1 in filters:
                        continue
                    pb = ns.Event(
                        type=1 if ev.kind == Event.DELETE else 0, kv=self.kv_pb(ev.kv)
                    )
                    if want_prev and ev.prev_kv is not None:
                        pb.prev_kv.CopyFrom(self.kv_pb(ev.prev_kv))
                    yield ns.WatchResponse(header=self.hdr(), watch_id=wid, events=[pb])
                    continue
                req = item
                if req is None:
                    return
                which = req.WhichOneof("request_union")
                if which == "create_request":
                    c = req.create_request
                    wid = c.watch_id or next_id[0]
                    if wid in watches:
                        # real etcd: re-using a live id cancels the
                        # request, never silently replaces the watcher
                        yield ns.WatchResponse(
                            header=self.hdr(), watch_id=wid, canceled=True,
                            cancel_reason="watcher with ID exists",
                        )
                        continue
                    next_id[0] = max(next_id[0], wid) + 1
                    lo, hi = bytes(c.key), bytes(c.range_end)
                    backlog = []
                    if c.start_revision:
                        try:
                            backlog = self.svc.history_since(c.start_revision, lo, hi)
                        except EtcdError:
                            yield ns.WatchResponse(
                                header=self.hdr(), watch_id=wid, canceled=True,
                                compact_revision=max(
                                    self.svc.compact_revision, self.svc.history_floor, 1
                                ),
                            )
                            continue
                    # snapshot -> register -> THEN yield: the yield
                    # suspends this generator (other tasks may mutate the
                    # store), so the watcher must exist before it or
                    # events in that window would be lost. No awaits
                    # between history_since and add_watcher => no gap,
                    # no duplicate.
                    entry = self.svc.add_watcher(
                        lo, hi, lambda ev, w=wid: q.put_nowait(("ev", ev, w))
                    )
                    watches[wid] = (entry, set(c.filters), c.prev_kv)
                    for ev in backlog:
                        q.put_nowait(("ev", ev, wid))
                    yield ns.WatchResponse(header=self.hdr(), watch_id=wid, created=True)
                elif which == "progress_request":
                    yield ns.WatchResponse(header=self.hdr(), watch_id=-1)
                elif which == "cancel_request":
                    wid = req.cancel_request.watch_id
                    if wid in watches:
                        self.svc.remove_watcher(watches.pop(wid)[0])
                    yield ns.WatchResponse(header=self.hdr(), watch_id=wid, canceled=True)
        finally:
            rt.cancel()
            for entry, _f, _p in watches.values():
                self.svc.remove_watcher(entry)


class _Election(_Base):
    def _lk(self, d):
        return self.ns.LeaderKey(
            name=d["name"], key=d["key"], rev=d["rev"], lease=d["lease"]
        )

    async def campaign(self, request):
        r = request.into_inner()
        # genuine etcd blocks until this candidate leads
        while True:
            try:
                info = self.svc.campaign(bytes(r.name), bytes(r.value), r.lease)
            except EtcdError as e:
                raise _err(e)
            if info["is_leader"]:
                return self.ns.CampaignResponse(
                    header=self.hdr(), leader=self._lk(info["leader"])
                )
            await asyncio.sleep(0.05)

    async def proclaim(self, request):
        r = request.into_inner()
        d = {"name": bytes(r.leader.name), "key": bytes(r.leader.key),
             "rev": r.leader.rev, "lease": r.leader.lease}
        try:
            self.svc.proclaim(d, bytes(r.value))
        except EtcdError as e:
            raise _err(e)
        return self.ns.ProclaimResponse(header=self.hdr())

    async def leader(self, request):
        try:
            info = self.svc.leader(bytes(request.into_inner().name))
        except EtcdError as e:
            raise _err(e)
        lk = info["leader"]
        return self.ns.LeaderResponse(
            header=self.hdr(),
            kv=self.ns.KeyValue(key=lk["key"], value=info["value"],
                                create_revision=lk["rev"], lease=lk["lease"]),
        )

    async def observe(self, request):
        name = bytes(request.into_inner().name)
        lo, hi = self.svc._election_prefix(name)
        q: asyncio.Queue = asyncio.Queue()
        entry = self.svc.add_watcher(lo, hi, q.put_nowait)
        try:
            info = self.svc.is_leader(name, b"")
            if info["leader"] is not None:
                yield self._leader_rsp(info)
            while True:
                await q.get()
                info = self.svc.is_leader(name, b"")
                if info["leader"] is not None:
                    yield self._leader_rsp(info)
        finally:
            self.svc.remove_watcher(entry)

    def _leader_rsp(self, info):
        lk = info["leader"]
        return self.ns.LeaderResponse(
            header=self.hdr(),
            kv=self.ns.KeyValue(key=lk["key"], value=info["value"],
                                create_revision=lk["rev"], lease=lk["lease"]),
        )

    async def resign(self, request):
        r = request.into_inner()
        d = {"name": bytes(r.leader.name), "key": bytes(r.leader.key),
             "rev": r.leader.rev, "lease": r.leader.lease}
        try:
            self.svc.resign(d)
        except EtcdError as e:
            raise _err(e)
        return self.ns.ResignResponse(header=self.hdr())


class _Maintenance(_Base):
    async def status(self, request):
        out = self.svc.status()
        return self.ns.StatusResponse(
            header=self.hdr(), version=out["version"], dbSize=out["db_size"]
        )


class EtcdGrpcGateway:
    """etcd-wire gRPC server over a sim `EtcdService`."""

    def __init__(self, history_limit: int = 10_000):
        self.ns = protos()
        self.svc = EtcdService(_Rng(), history_limit=history_limit)
        self._router = None
        self._tick_task: Optional[asyncio.Task] = None

    async def start(self, addr: str = "127.0.0.1:0") -> int:
        from ...grpc.real import RealRouter

        ns = self.ns
        self._router = (
            RealRouter()
            .add_service(ns.KVServer(_KV(self)))
            .add_service(ns.LeaseServer(_Lease(self)))
            .add_service(ns.WatchServer(_Watch(self)))
            .add_service(ns.ElectionServer(_Election(self)))
            .add_service(ns.MaintenanceServer(_Maintenance(self)))
        )
        port = await self._router.start(addr)

        async def tick():
            while True:
                await asyncio.sleep(1.0)
                self.svc.tick()

        self._tick_task = asyncio.ensure_future(tick())
        return port

    async def wait(self) -> None:
        """Block until the server terminates (public CLI surface)."""
        await self._router._server.wait_for_termination()

    async def serve(self, addr: str) -> None:
        await self.start(addr)
        await self.wait()

    async def stop(self) -> None:
        if self._tick_task is not None:
            self._tick_task.cancel()
        if self._router is not None:
            await self._router.stop()
