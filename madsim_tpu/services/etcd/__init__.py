"""Simulated etcd v3 — client + in-sim server
(reference: madsim-etcd-client).

`SimServer` speaks a request protocol over `Endpoint.connect1`
(reference: src/server.rs:104-167) with an injectable `timeout_rate`
(:21-24); `Client` exposes the etcd-client surface: kv / lease /
election / maintenance / watch, plus state `dump`/`load`
(reference: src/sim.rs:27-78). The reference's watch API is a type stub
(src/watch.rs:1-8); here it is fully functional (streaming put/delete
events over a held-open connection).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

from ...dual import rand, time as sim_time  # mode-selected (sim or asyncio)
from ...errors import SimError
from ...net.network import ConnectionReset, parse_addr
from ...dual import net as _dual_net
from ...dual import task as _dual_task

Endpoint = _dual_net.Endpoint
spawn = _dual_task.spawn
from .._conn import StreamCaller
from .service import EtcdError, EtcdService, Event, KeyValue, MAX_REQUEST_BYTES

__all__ = [
    "Client",
    "SimServer",
    "WatchFilter",
    "EtcdError",
    "KeyValue",
    "Event",
    "Txn",
    "Compare",
    "TxnOp",
]

Key = Union[str, bytes]


def _b(x: Key) -> bytes:
    return x.encode() if isinstance(x, str) else bytes(x)


def _prefix_end(key: bytes) -> bytes:
    """The etcd range_end for a prefix scan: key with last byte +1."""
    for i in reversed(range(len(key))):
        if key[i] < 0xFF:
            return key[:i] + bytes([key[i] + 1])
    return b"\xff" * (len(key) + 1)


# -- txn building blocks (reference: etcd-client Compare/Txn/TxnOp) ----------


class Compare:
    def __init__(self, target: str, key: Key, op: str, operand: Any):
        self.tuple = (target, _b(key), op, operand if not isinstance(operand, (str, bytes)) else _b(operand))

    @staticmethod
    def value(key: Key, op: str, v: Key) -> "Compare":
        return Compare("value", key, op, v)

    @staticmethod
    def version(key: Key, op: str, v: int) -> "Compare":
        return Compare("version", key, op, v)

    @staticmethod
    def create_revision(key: Key, op: str, v: int) -> "Compare":
        return Compare("create_revision", key, op, v)

    @staticmethod
    def mod_revision(key: Key, op: str, v: int) -> "Compare":
        return Compare("mod_revision", key, op, v)


class TxnOp:
    @staticmethod
    def put(key: Key, value: Key, lease: int = 0) -> tuple:
        return ("put", _b(key), _b(value), lease)

    @staticmethod
    def get(key: Key, prefix: bool = False) -> tuple:
        k = _b(key)
        return ("get", k, _prefix_end(k) if prefix else b"")

    @staticmethod
    def delete(key: Key, prefix: bool = False) -> tuple:
        k = _b(key)
        return ("delete", k, _prefix_end(k) if prefix else b"")


class Txn:
    def __init__(self) -> None:
        self._when: List[tuple] = []
        self._then: List[tuple] = []
        self._else: List[tuple] = []

    def when(self, compares: Sequence[Compare]) -> "Txn":
        self._when = [c.tuple for c in compares]
        return self

    def and_then(self, ops: Sequence[tuple]) -> "Txn":
        self._then = list(ops)
        return self

    def or_else(self, ops: Sequence[tuple]) -> "Txn":
        self._else = list(ops)
        return self


# -- server -------------------------------------------------------------------


class SimServer:
    """Reference: src/server.rs `SimServer` (+ sim.rs builder)."""

    def __init__(self, timeout_rate: float = 0.0, progress_interval: float = 1.0,
                 history_limit: int = 10_000):
        self.timeout_rate = timeout_rate
        # period of watch progress notifications (etcd's is ~10 min wall
        # time; 1 s of virtual time keeps sim tests snappy)
        self.progress_interval = progress_interval
        self.history_limit = history_limit
        self.service: Optional[EtcdService] = None

    async def serve(self, addr: Any, on_bound=None) -> None:
        rng = rand.thread_rng()
        self.service = EtcdService(rng, history_limit=self.history_limit)
        ep = await Endpoint.bind(addr)
        if on_bound is not None:
            on_bound(ep)

        async def ticker():
            # 1 s lease countdown (reference: service.rs:25-35)
            it = sim_time.interval(1.0)
            while True:
                await it.tick()
                self.service.tick()

        spawn(ticker(), name="etcd-lease-tick")
        while True:
            tx, rx, peer = await ep.accept1()
            spawn(self._handle(tx, rx), name="etcd-conn")

    async def _handle(self, tx, rx) -> None:
        """One connection serves one long-lived subscription (watch/
        observe) or a loop of unary requests — the same dual shape the
        kafka/s3 servers speak, so real-mode clients can keep one
        persistent stream (StreamCaller) instead of a socket per call."""
        svc = self.service
        rng = rand.thread_rng()
        try:
            while True:
                req = await rx.recv()
                if req is None:
                    return
                if self.timeout_rate > 0 and rng.gen_bool(self.timeout_rate):
                    tx.send(("err", "etcdserver: request timed out"))
                    continue
                kind = req[0]
                if kind == "watch":
                    await self._watch(tx, rx, req[1], req[2],
                                      req[3] if len(req) > 3 else {})
                    return
                if kind == "observe":
                    await self._observe(tx, rx, req[1])
                    return
                try:
                    result = self._apply(svc, req)
                    tx.send(("ok", result))
                except EtcdError as e:
                    tx.send(("err", str(e)))
        except ConnectionReset:
            pass
        finally:
            tx.close()  # real mode: a finished connection must not linger

    def _apply(self, svc: EtcdService, req: tuple):
        kind = req[0]
        if kind == "put":
            return svc.put(req[1], req[2], lease=req[3], prev_kv=req[4])
        if kind == "get":
            return svc.get(req[1], range_end=req[2], limit=req[3], count_only=req[4], keys_only=req[5])
        if kind == "delete":
            return svc.delete(req[1], range_end=req[2], prev_kv=req[3])
        if kind == "txn":
            return svc.txn(req[1], req[2], req[3])
        if kind == "lease_grant":
            return svc.lease_grant(req[1], req[2])
        if kind == "lease_revoke":
            return svc.lease_revoke(req[1])
        if kind == "lease_keep_alive":
            return svc.lease_keep_alive(req[1])
        if kind == "lease_time_to_live":
            return svc.lease_time_to_live(req[1])
        if kind == "lease_list":
            return svc.lease_list()
        if kind == "campaign":
            return svc.campaign(req[1], req[2], req[3])
        if kind == "leader":
            return svc.leader(req[1])
        if kind == "proclaim":
            return svc.proclaim(req[1], req[2])
        if kind == "resign":
            return svc.resign(req[1])
        if kind == "compact":
            return svc.compact(req[1])
        if kind == "status":
            return svc.status()
        if kind == "dump":
            return svc.dump()
        if kind == "load":
            return svc.load(req[1])
        raise EtcdError(f"unknown request {kind}")

    async def _watch(self, tx, rx, lo: bytes, hi: bytes, opts: dict) -> None:
        """WatchCreateRequest options (reference class: etcd v3 watch —
        the reference sim's watch.rs is a type stub; this is functional):
        `filters` ("noput"/"nodelete"), `prev_kv`, `start_revision`
        (history replay, ErrCompacted past the compaction point), and
        `progress_notify` (periodic revision heartbeats; the client can
        also request one on demand, like WatchProgressRequest)."""
        svc = self.service
        filters = set(opts.get("filters", ()))
        want_prev = opts.get("prev_kv", False)
        start_rev = opts.get("start_revision", 0)
        entry_box: list = [None]

        def emit(ev: Event) -> None:
            # a future start_revision is a resume point: hold the watch
            # and deliver nothing below it (real etcd parks the watcher
            # until the store revision catches up)
            if start_rev and ev.kv.mod_revision < start_rev:
                return
            if ev.kind == Event.PUT and "noput" in filters:
                return
            if ev.kind == Event.DELETE and "nodelete" in filters:
                return
            if not want_prev and ev.prev_kv is not None:
                ev = Event(ev.kind, ev.kv, None)
            self._safe_send(tx, ("event", ev), entry_box)

        # no awaits between head/replay/subscribe: the deterministic
        # executor makes this block atomic, so replay never races a
        # concurrent put (no gap, no duplicate)
        if start_rev:
            try:
                backlog = svc.history_since(start_rev, lo, hi)
            except EtcdError as e:
                tx.send(("err", str(e)))
                return
            tx.send(("ok", {"watching": True}))
            for ev in backlog:
                emit(ev)
        else:
            tx.send(("ok", {"watching": True}))
        entry_box[0] = entry = svc.add_watcher(lo, hi, emit)

        stop = [False]
        if opts.get("progress_notify", False):
            async def ticker():
                while not stop[0]:
                    await sim_time.sleep(self.progress_interval)
                    if stop[0]:
                        return
                    try:
                        tx.send(("progress", svc.revision))
                    except ConnectionReset:
                        return

            spawn(ticker(), name="etcd-watch-progress")

        # hold open until the client goes away; serve manual progress
        # requests in the meantime
        while (req := await rx.recv()) is not None:
            if req and req[0] == "progress_req":
                # distinct tag: an on-demand reply must reflect the
                # revision at request-processing time, so the client must
                # not satisfy it with a stale queued periodic notification
                try:
                    tx.send(("progress_resp", svc.revision))
                except ConnectionReset:
                    break
        stop[0] = True
        svc.remove_watcher(entry)

    def _safe_send(self, tx, msg, entry_box) -> None:
        try:
            tx.send(msg)
        except ConnectionReset:
            if entry_box[0] is not None:
                self.service.remove_watcher(entry_box[0])

    async def _observe(self, tx, rx, name: bytes) -> None:
        """Stream leadership changes (reference: election observe)."""
        svc = self.service
        lo, hi = svc._election_prefix(name)

        def on_change(_ev: Event) -> None:
            try:
                info = svc.is_leader(name, b"")
                if info["leader"] is not None:
                    tx.send(("leader", info))
            except ConnectionReset:
                svc.remove_watcher(entry)

        entry = svc.add_watcher(lo, hi, on_change)
        info = svc.is_leader(name, b"")
        tx.send(("ok", {"observing": True}))
        if info["leader"] is not None:
            tx.send(("leader", info))
        while (await rx.recv()) is not None:
            pass
        svc.remove_watcher(entry)


# -- client -------------------------------------------------------------------


class WatchFilter:
    """Event-type filters for watch (reference class: etcd v3
    WatchCreateRequest.filters)."""

    NOPUT = "noput"
    NODELETE = "nodelete"


class Watcher:
    """Async stream of watch events (functional, unlike the reference's
    stub watch.rs). Progress notifications never surface as events:
    they update `progress_revision` (the keyspace revision the stream is
    guaranteed to have reached) and can be requested on demand with
    `progress()`."""

    def __init__(self, tx, rx):
        self._tx = tx
        self._rx = rx
        self._pending: List[tuple] = []
        self.progress_revision = 0

    def __aiter__(self) -> "Watcher":
        return self

    async def __anext__(self) -> Event:
        while True:
            msg = self._pending.pop(0) if self._pending else await self._rx.recv()
            if msg is None:
                raise StopAsyncIteration
            if msg[0] in ("progress", "progress_resp"):
                self.progress_revision = msg[1]
                continue
            return msg[1]

    async def progress(self) -> int:
        """Request + await a progress notification (reference class:
        etcd WatchProgressRequest); events arriving in between are
        buffered for the next `__anext__`. Only the tagged on-demand
        reply resolves the call — a stale queued periodic notification
        must not masquerade as "synced through the current revision"."""
        self._tx.send(("progress_req",))
        while True:
            msg = await self._rx.recv()
            if msg is None:
                raise EtcdError("watch stream closed")
            if msg[0] == "progress":
                self.progress_revision = msg[1]
                continue
            if msg[0] == "progress_resp":
                self.progress_revision = msg[1]
                return msg[1]
            self._pending.append(msg)

    def cancel(self) -> None:
        self._tx.close()


class Observer:
    def __init__(self, tx, rx):
        self._tx = tx
        self._rx = rx

    def __aiter__(self) -> "Observer":
        return self

    async def __anext__(self) -> dict:
        msg = await self._rx.recv()
        if msg is None:
            raise StopAsyncIteration
        return msg[1]

    def cancel(self) -> None:
        self._tx.close()


class Client:
    """etcd-client surface (reference: src/sim.rs:27-78 `Client` with
    kv/lease/election/maintenance sub-clients, flattened pythonically)."""

    def __init__(self, addr):
        self._addr = addr
        self._caller = StreamCaller()
        # real mode with a genuine etcd reachable: every op goes through
        # the etcd wire protocol instead of the sim pickle protocol
        # (reference: madsim-etcd-client/src/lib.rs:5-6 `pub use
        # etcd_client::*` in the non-sim build)
        self._real = None

    @staticmethod
    async def connect(endpoints: Union[str, Sequence[str]], timeout: Optional[float] = None) -> "Client":
        if isinstance(endpoints, str):
            endpoints = [endpoints]
        from ...dual import IS_SIM, real_passthrough_enabled

        if not IS_SIM and real_passthrough_enabled():
            from .real_client import try_connect_real

            backend = await try_connect_real(endpoints, probe_timeout=timeout or 2.0)
            if backend is not None:
                client = Client(endpoints[0])
                client._real = backend
                return client
        client = Client(parse_addr(endpoints[0]))
        await client._caller.open(client._addr)
        return client

    async def close(self) -> None:
        if self._real is not None:
            await self._real.close()
        if self._caller is not None:
            self._caller.close()

    # reads are safe to transparently re-send after an ambiguous response
    # loss in real mode; mutations (put/txn/delete/lease_grant/campaign)
    # are not — a blind retry could double-apply against MVCC revisions
    _IDEMPOTENT = {"get", "leader", "status", "dump",
                   "lease_time_to_live", "lease_list"}

    async def _call(self, req: tuple):
        if self._real is not None:
            return await self._real.call(req)
        rsp = await self._caller.call(req, idempotent=req[0] in self._IDEMPOTENT)
        if rsp is None:
            raise EtcdError("etcd server unavailable")
        status, payload = rsp
        if status == "err":
            raise EtcdError(payload)
        return payload

    # -- kv --

    async def put(self, key: Key, value: Key, lease: int = 0, prev_kv: bool = False):
        return await self._call(("put", _b(key), _b(value), lease, prev_kv))

    async def get(
        self,
        key: Key,
        prefix: bool = False,
        range_end: Optional[Key] = None,
        limit: int = 0,
        count_only: bool = False,
        keys_only: bool = False,
    ):
        k = _b(key)
        end = _b(range_end) if range_end is not None else (_prefix_end(k) if prefix else b"")
        return await self._call(("get", k, end, limit, count_only, keys_only))

    async def delete(self, key: Key, prefix: bool = False, prev_kv: bool = False):
        k = _b(key)
        end = _prefix_end(k) if prefix else b""
        return await self._call(("delete", k, end, prev_kv))

    async def txn(self, txn: Txn):
        return await self._call(("txn", txn._when, txn._then, txn._else))

    # -- lease --

    async def lease_grant(self, ttl: int, lease_id: int = 0):
        return await self._call(("lease_grant", ttl, lease_id))

    async def lease_revoke(self, lease_id: int):
        return await self._call(("lease_revoke", lease_id))

    async def lease_keep_alive(self, lease_id: int):
        return await self._call(("lease_keep_alive", lease_id))

    async def lease_time_to_live(self, lease_id: int):
        return await self._call(("lease_time_to_live", lease_id))

    async def leases(self):
        return await self._call(("lease_list",))

    # -- election --

    async def campaign(self, name: Key, value: Key, lease: int, poll_interval: float = 0.1):
        """Blocks until this candidate is the leader
        (reference: election campaign semantics)."""
        while True:
            info = await self._call(("campaign", _b(name), _b(value), lease))
            if info["is_leader"]:
                return info
            await sim_time.sleep(poll_interval)

    async def leader(self, name: Key):
        return await self._call(("leader", _b(name)))

    async def proclaim(self, value: Key, leader: dict):
        return await self._call(("proclaim", leader["leader"], _b(value)))

    async def resign(self, leader: dict):
        return await self._call(("resign", leader["leader"]))

    async def observe(self, name: Key) -> Observer:
        if self._real is not None:
            return await self._real.observe(_b(name))
        tx, rx = await self._open_sub()
        tx.send(("observe", _b(name)))
        head = await rx.recv()
        if head is None or head[0] != "ok":
            tx.close()  # both ends release the failed subscription
            raise EtcdError(f"observe failed: {head}")
        return Observer(tx, rx)

    async def _open_sub(self):
        """Dedicated channel for a subscription; server-down surfaces as
        the typed error, not a raw OSError."""
        try:
            return await self._caller.open_stream()
        except ConnectionReset as e:
            raise EtcdError(f"etcd server unavailable: {e}") from e

    # -- watch --

    async def watch(
        self,
        key: Key,
        prefix: bool = False,
        range_end: Optional[Key] = None,
        start_revision: int = 0,
        filters: Sequence[str] = (),
        prev_kv: bool = False,
        progress_notify: bool = False,
    ) -> Watcher:
        """WatchCreateRequest surface: `start_revision` replays history
        from that revision (ErrCompacted if compacted away), `filters`
        drop event kinds (WatchFilter.NOPUT/NODELETE), `prev_kv`
        includes each event's previous value, `progress_notify` enables
        periodic revision heartbeats."""
        k = _b(key)
        if range_end is not None:
            hi = _b(range_end)
        else:
            hi = _prefix_end(k) if prefix else b""
        if self._real is not None:
            return await self._real.watch(k, hi, {
                "start_revision": start_revision,
                "filters": tuple(filters),
                "prev_kv": prev_kv,
                "progress_notify": progress_notify,
            })
        tx, rx = await self._open_sub()
        tx.send(("watch", k, hi, {
            "start_revision": start_revision,
            "filters": tuple(filters),
            "prev_kv": prev_kv,
            "progress_notify": progress_notify,
        }))
        head = await rx.recv()
        if head is None or head[0] != "ok":
            tx.close()  # both ends release the failed subscription
            raise EtcdError(f"watch failed: {head}")
        return Watcher(tx, rx)

    async def compact(self, revision: int):
        """Discard watchable history below `revision` (etcd compaction)."""
        return await self._call(("compact", revision))

    # -- maintenance / persistence --

    async def status(self):
        return await self._call(("status",))

    async def dump(self) -> str:
        return await self._call(("dump",))

    async def load(self, text: str):
        return await self._call(("load", text))
