"""The etcd state machine: MVCC-revisioned KV, leases, elections, watch.

Reference: madsim-etcd-client/src/service.rs — put/get/delete/txn over a
sorted map (:191+), leases with TTL decremented by a 1 s background tick
(:25-35,:398,:466), campaign/proclaim/leader/observe/resign elections
(:487+, election.rs), request size limit 1.5 MiB (:36-40), state
dump/load (:160).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...errors import SimError

MAX_REQUEST_BYTES = int(1.5 * 1024 * 1024)  # reference: service.rs:36-40


class EtcdError(SimError):
    pass


class KeyValue:
    __slots__ = ("key", "value", "create_revision", "mod_revision", "version", "lease")

    def __init__(self, key: bytes, value: bytes, create_revision: int, mod_revision: int, version: int, lease: int):
        self.key = key
        self.value = value
        self.create_revision = create_revision
        self.mod_revision = mod_revision
        self.version = version
        self.lease = lease

    def to_dict(self) -> dict:
        return {
            "key": self.key.decode("latin1"),
            "value": self.value.decode("latin1"),
            "create_revision": self.create_revision,
            "mod_revision": self.mod_revision,
            "version": self.version,
            "lease": self.lease,
        }

    @staticmethod
    def from_dict(d: dict) -> "KeyValue":
        return KeyValue(
            d["key"].encode("latin1"),
            d["value"].encode("latin1"),
            d["create_revision"],
            d["mod_revision"],
            d["version"],
            d["lease"],
        )


class Event:
    PUT = "put"
    DELETE = "delete"

    def __init__(self, kind: str, kv: KeyValue, prev_kv: Optional[KeyValue]):
        self.kind = kind
        self.kv = kv
        self.prev_kv = prev_kv


class EtcdService:
    """Reference: service.rs `EtcdService`."""

    def __init__(self, rng, history_limit: int = 10_000,
                 lease_expiry_off_by_one: bool = False):
        self.rng = rng
        # watchable-history bound: exceeding it auto-compacts the oldest
        # whole revisions away (a real etcd bounds history by compaction
        # too; without this a write-heavy run leaks one Event per put)
        self.history_limit = history_limit
        # TEST-ONLY seeded bug for the bidirectional service
        # differential (tests/test_differential_services.py): the expiry
        # sweep's revoke loop starts at index 1 — the classic off-by-one
        # — leaking the first attached key of every EXPIRED lease.
        # Explicit lease_revoke calls are unaffected. Never set this
        # outside tests.
        self.lease_expiry_off_by_one = lease_expiry_off_by_one
        self.revision = 1
        self.kv: Dict[bytes, KeyValue] = {}
        # lease id -> (granted_ttl, remaining_ttl)
        self.leases: Dict[int, List[int]] = {}
        self.lease_keys: Dict[int, set] = {}
        # watchers: fn(event) -> None (detached on error by caller)
        self.watchers: List[Tuple[bytes, bytes, Callable[[Event], None]]] = []
        # event history for watch start_revision replay (bounded by
        # compaction, like etcd's MVCC keyspace history); deque so the
        # steady-state trim is O(1) per write, not a list rebuild
        self.history: "deque[Tuple[int, Event]]" = deque()
        # compact_revision: the revision a client last compacted at
        # (etcd's compactMainRev — compact() below it is ErrCompacted).
        # history_floor: the lowest revision whose events are still
        # replayable for watch(start_revision) — raised by compaction,
        # by the bounded-history trim, and by load() (which has no
        # history at all). Kept separate so a load at revision R doesn't
        # make compact(R) impossible (see load()).
        self.compact_revision = 0
        self.history_floor = 0

    # -- helpers --------------------------------------------------------------

    def _bump(self) -> int:
        self.revision += 1
        return self.revision

    @staticmethod
    def _in_range(key: bytes, lo: bytes, hi: bytes) -> bool:
        """Range convention shared by get/delete/watch/replay:
        hi == b"" means the single key `lo`, not unbounded-above
        (watch previously disagreed with _keys_in here and delivered
        every key >= lo to a single-key watcher)."""
        if hi == b"":
            return key == lo
        return lo <= key < hi

    def _notify(self, ev: Event) -> None:
        self.history.append((ev.kv.mod_revision, ev))
        if len(self.history) > self.history_limit:
            # drop whole revisions only: a range delete emits several
            # events at one revision, and replaying half of one would
            # silently lose data
            boundary = self.history[0][0]
            while len(self.history) > self.history_limit:
                boundary = self.history.popleft()[0]
            while self.history and self.history[0][0] == boundary:
                self.history.popleft()
            self.history_floor = max(self.history_floor, boundary + 1)
        for lo, hi, cb in list(self.watchers):
            if self._in_range(ev.kv.key, lo, hi):
                cb(ev)

    def history_since(self, start_revision: int, lo: bytes, hi: bytes) -> List[Event]:
        """Replay events at mod_revision >= start_revision in [lo, hi).
        Raises if the range was compacted away (etcd: ErrCompacted —
        only revisions strictly BELOW the compaction point are gone;
        compact(R) retains the events at R itself)."""
        if start_revision < max(self.history_floor, self.compact_revision):
            raise EtcdError("etcdserver: mvcc: required revision has been compacted")
        return [
            ev for rev, ev in self.history
            if rev >= start_revision and self._in_range(ev.kv.key, lo, hi)
        ]

    def compact(self, revision: int) -> dict:
        """Discard event history below `revision`
        (reference class: etcd Maintenance/KV compact)."""
        if revision > self.revision:
            raise EtcdError("etcdserver: mvcc: required revision is a future revision")
        if revision <= self.compact_revision:
            raise EtcdError("etcdserver: mvcc: required revision has been compacted")
        self.compact_revision = revision
        self.history_floor = max(self.history_floor, revision)
        self.history = deque((r, e) for r, e in self.history if r >= revision)
        return {"revision": self.revision, "compact_revision": revision}

    def add_watcher(self, lo: bytes, hi: bytes, cb: Callable[[Event], None]):
        entry = (lo, hi, cb)
        self.watchers.append(entry)
        return entry

    def remove_watcher(self, entry) -> None:
        try:
            self.watchers.remove(entry)
        except ValueError:
            pass

    @staticmethod
    def _range(key: bytes, range_end: bytes) -> Tuple[bytes, bytes]:
        return key, range_end

    def _keys_in(self, lo: bytes, hi: bytes) -> List[bytes]:
        if hi == b"":
            return [lo] if lo in self.kv else []
        return sorted(k for k in self.kv if lo <= k < hi)

    # -- kv --------------------------------------------------------------------

    def put(self, key: bytes, value: bytes, lease: int = 0, prev_kv: bool = False):
        if len(key) + len(value) > MAX_REQUEST_BYTES:
            raise EtcdError("etcdserver: request is too large")
        if lease and lease not in self.leases:
            raise EtcdError("etcdserver: requested lease not found")
        rev = self._bump()
        old = self.kv.get(key)
        new = KeyValue(
            key,
            value,
            old.create_revision if old else rev,
            rev,
            old.version + 1 if old else 1,
            lease,
        )
        self.kv[key] = new
        if old is not None and old.lease and old.lease != lease:
            self.lease_keys.get(old.lease, set()).discard(key)
        if lease:
            self.lease_keys.setdefault(lease, set()).add(key)
        self._notify(Event(Event.PUT, new, old))
        return {"revision": rev, "prev_kv": old if prev_kv else None}

    def get(
        self,
        key: bytes,
        range_end: bytes = b"",
        limit: int = 0,
        count_only: bool = False,
        keys_only: bool = False,
    ):
        keys = self._keys_in(key, range_end)
        kvs = [self.kv[k] for k in keys]
        count = len(kvs)
        if limit:
            kvs = kvs[:limit]
        if keys_only:
            kvs = [KeyValue(kv.key, b"", kv.create_revision, kv.mod_revision, kv.version, kv.lease) for kv in kvs]
        return {"revision": self.revision, "kvs": [] if count_only else kvs, "count": count}

    def delete(self, key: bytes, range_end: bytes = b"", prev_kv: bool = False):
        keys = self._keys_in(key, range_end)
        deleted = []
        if keys:
            rev = self._bump()
            for k in keys:
                old = self.kv.pop(k)
                deleted.append(old)
                if old.lease:
                    self.lease_keys.get(old.lease, set()).discard(k)
                tomb = KeyValue(k, b"", 0, rev, 0, 0)
                self._notify(Event(Event.DELETE, tomb, old))
        return {
            "revision": self.revision,
            "deleted": len(deleted),
            "prev_kvs": deleted if prev_kv else [],
        }

    # -- txn --------------------------------------------------------------------

    def txn(self, compares: List[tuple], then_ops: List[tuple], else_ops: List[tuple]):
        ok = all(self._compare(c) for c in compares)
        ops = then_ops if ok else else_ops
        responses = [self._apply_op(op) for op in ops]
        return {"revision": self.revision, "succeeded": ok, "responses": responses}

    def _compare(self, c: tuple) -> bool:
        target, key, op, operand = c
        kv = self.kv.get(key)
        if target == "value":
            actual: Any = kv.value if kv else b""
        elif target == "create_revision":
            actual = kv.create_revision if kv else 0
        elif target == "mod_revision":
            actual = kv.mod_revision if kv else 0
        elif target == "version":
            actual = kv.version if kv else 0
        else:
            raise EtcdError(f"bad compare target {target}")
        if op == "=":
            return actual == operand
        if op == "!=":
            return actual != operand
        if op == ">":
            return actual > operand
        if op == "<":
            return actual < operand
        raise EtcdError(f"bad compare op {op}")

    def _apply_op(self, op: tuple):
        kind = op[0]
        if kind == "put":
            return ("put", self.put(op[1], op[2], lease=op[3]))
        if kind == "get":
            return ("get", self.get(op[1], range_end=op[2]))
        if kind == "delete":
            return ("delete", self.delete(op[1], range_end=op[2]))
        raise EtcdError(f"bad txn op {kind}")

    # -- leases (reference: service.rs:25-35 tick + :398+) ----------------------

    def lease_grant(self, ttl: int, lease_id: int = 0):
        if lease_id == 0:
            while True:
                lease_id = self.rng.gen_range(1, 1 << 62)
                if lease_id not in self.leases:
                    break
        if lease_id in self.leases:
            raise EtcdError("etcdserver: lease already exists")
        self.leases[lease_id] = [ttl, ttl]
        self.lease_keys.setdefault(lease_id, set())
        return {"id": lease_id, "ttl": ttl}

    def lease_revoke(self, lease_id: int):
        if lease_id not in self.leases:
            raise EtcdError("etcdserver: requested lease not found")
        del self.leases[lease_id]
        for key in sorted(self.lease_keys.pop(lease_id, set())):
            self.delete(key)
        return {"revision": self.revision}

    def lease_keep_alive(self, lease_id: int):
        if lease_id not in self.leases:
            raise EtcdError("etcdserver: requested lease not found")
        granted = self.leases[lease_id][0]
        self.leases[lease_id][1] = granted
        return {"id": lease_id, "ttl": granted}

    def lease_time_to_live(self, lease_id: int):
        if lease_id not in self.leases:
            raise EtcdError("etcdserver: requested lease not found")
        granted, remaining = self.leases[lease_id]
        return {"id": lease_id, "granted_ttl": granted, "ttl": remaining,
                "keys": sorted(self.lease_keys.get(lease_id, set()))}

    def lease_list(self):
        return {"leases": sorted(self.leases)}

    def tick(self) -> None:
        """1-second lease countdown (reference: service.rs:25-35 spawned
        tick task; expiry deletes attached keys)."""
        self.advance(1)

    def advance(self, n: int) -> None:
        """`n` ticks at once — lease accounting is linear in elapsed
        time, so this equals n sequential tick() calls. Used by the
        service-differential harness as its virtual-time bridge
        (differential_services.py: 1 machine µs = 1 tick)."""
        if n <= 0:
            return
        expired = []
        for lease_id, pair in self.leases.items():
            pair[1] -= n
            if pair[1] <= 0:
                expired.append(lease_id)
        for lease_id in expired:
            if self.lease_expiry_off_by_one:
                # seeded bug (see __init__): skip the first attached key
                del self.leases[lease_id]
                for key in sorted(self.lease_keys.pop(lease_id, set()))[1:]:
                    self.delete(key)
                continue
            self.lease_revoke(lease_id)

    # -- elections (reference: service.rs:487+, election.rs) --------------------

    def _election_prefix(self, name: bytes) -> Tuple[bytes, bytes]:
        return name + b"/", name + b"0"  # '/'+1 == '0'

    def campaign(self, name: bytes, value: bytes, lease: int):
        """Create the candidate key; caller loops until it is the leader."""
        key = name + b"/" + format(lease, "x").encode()
        if key not in self.kv:
            self.put(key, value, lease=lease)
        return self.is_leader(name, key)

    def is_leader(self, name: bytes, key: bytes) -> dict:
        lo, hi = self._election_prefix(name)
        keys = self._keys_in(lo, hi)
        if not keys:
            return {"leader": None, "is_leader": False}
        leader_key = min(keys, key=lambda k: self.kv[k].create_revision)
        kv = self.kv[leader_key]
        return {
            "leader": {"name": name, "key": leader_key, "rev": kv.create_revision, "lease": kv.lease},
            "is_leader": leader_key == key,
            "value": kv.value,
        }

    def leader(self, name: bytes) -> dict:
        info = self.is_leader(name, b"")
        if info["leader"] is None:
            raise EtcdError("election: no leader")
        return info

    def proclaim(self, leader: dict, value: bytes):
        key = leader["key"]
        kv = self.kv.get(key)
        if kv is None or kv.create_revision != leader["rev"]:
            raise EtcdError("election: session expired")
        return self.put(key, value, lease=kv.lease)

    def resign(self, leader: dict):
        return self.delete(leader["key"])

    # -- maintenance / persistence ----------------------------------------------

    def status(self) -> dict:
        return {"version": "madsim-tpu-etcd", "db_size": len(self.kv), "revision": self.revision}

    def dump(self) -> str:
        """Serialize full state (reference: service.rs:160 dump as TOML;
        JSON here — same capability, stdlib-friendly)."""
        import json

        return json.dumps(
            {
                "revision": self.revision,
                "compact_revision": self.compact_revision,
                "kv": [kv.to_dict() for kv in self.kv.values()],
                "leases": {str(k): v for k, v in self.leases.items()},
                "lease_keys": {str(k): sorted(x.decode("latin1") for x in v) for k, v in self.lease_keys.items()},
            }
        )

    def load(self, text: str) -> None:
        import json

        data = json.loads(text)
        self.revision = data["revision"]
        # loaded state has no event history: watchers cannot replay
        # revisions up to and including the load point (the floor sits
        # one past the last missing revision or a
        # start_revision==revision watch would silently skip that
        # revision's events). compact_revision stays at its dumped
        # value so compact(current revision) still works after a
        # restore, like real etcd.
        self.history = deque()
        self.history_floor = self.revision + 1
        self.compact_revision = data.get("compact_revision", 0)
        self.kv = {}
        for d in data["kv"]:
            kv = KeyValue.from_dict(d)
            self.kv[kv.key] = kv
        self.leases = {int(k): list(v) for k, v in data["leases"].items()}
        self.lease_keys = {
            int(k): {x.encode("latin1") for x in v} for k, v in data["lease_keys"].items()
        }
