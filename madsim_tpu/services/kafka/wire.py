"""Genuine Kafka wire-protocol codec (stdlib-only).

The shared encoding layer for `wire_gateway.KafkaWireGateway` (serves
the real protocol from the sim `Broker`) and
`real_client.KafkaWireClient` (speaks it to genuine brokers) — the
madsim-rdkafka analogue: where the reference vendors the complete
genuine rdkafka API for its non-sim build
(/root/reference/madsim-rdkafka/src/lib.rs:5-12, src/std/), this build
implements the actual Kafka protocol natively so sim-tested code runs
against real brokers with no third-party client.

Covers the classic (non-flexible) protocol era every broker still
serves: int16-length strings, int32-length byte blobs, int32-count
arrays, and BOTH record formats —

* MessageSet v1 (magic 1, CRC-32/IEEE via zlib.crc32): Produce v0-v2 /
  Fetch v0-v3 payloads, what pre-0.11 clients speak;
* RecordBatch v2 (magic 2, CRC-32C, zigzag varints): Produce v3+ /
  Fetch v4+, the only format that carries record headers.

Schemas follow the published Kafka protocol guide (kafka.apache.org/
protocol); field order and sizes must match bit-for-bit to interoperate,
which is the entire point of this module.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, List, Optional, Sequence, Tuple

__all__ = [
    "ApiKey",
    "Err",
    "Reader",
    "Writer",
    "encode_message_set",
    "decode_record_blob",
    "encode_record_batch",
    "crc32c",
    "encode_subscription",
    "decode_subscription",
    "encode_assignment",
    "decode_assignment",
]


class ApiKey:
    PRODUCE = 0
    FETCH = 1
    LIST_OFFSETS = 2
    METADATA = 3
    OFFSET_COMMIT = 8
    OFFSET_FETCH = 9
    FIND_COORDINATOR = 10
    JOIN_GROUP = 11
    HEARTBEAT = 12
    LEAVE_GROUP = 13
    SYNC_GROUP = 14
    DESCRIBE_GROUPS = 15
    API_VERSIONS = 18
    CREATE_TOPICS = 19


MAX_DECOMPRESSED_BATCH = 64 * 1024 * 1024  # bound for peer-supplied gzip


class UnsupportedCodec(ValueError):
    """A record blob carries a compression codec this stdlib codec does
    not implement — surfaced loudly instead of decoding garbage."""


class Err:
    """Kafka numeric error codes (the subset this codec surfaces)."""

    NONE = 0
    OFFSET_OUT_OF_RANGE = 1
    CORRUPT_MESSAGE = 2
    UNKNOWN_TOPIC_OR_PARTITION = 3
    NOT_LEADER_FOR_PARTITION = 6
    MESSAGE_TOO_LARGE = 10
    COORDINATOR_NOT_AVAILABLE = 15
    NOT_COORDINATOR = 16
    ILLEGAL_GENERATION = 22
    INCONSISTENT_GROUP_PROTOCOL = 23
    UNKNOWN_MEMBER_ID = 25
    INVALID_SESSION_TIMEOUT = 26
    REBALANCE_IN_PROGRESS = 27
    TOPIC_ALREADY_EXISTS = 36
    INVALID_PARTITIONS = 37
    INVALID_REQUEST = 42
    UNSUPPORTED_VERSION = 35


# -- primitive readers/writers ------------------------------------------------


class Reader:
    """Sequential big-endian reader over one frame."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _take(self, n: int) -> bytes:
        if n < 0:
            raise ValueError(f"negative read of {n} at {self.pos}")
        b = self.buf[self.pos : self.pos + n]
        if len(b) < n:
            raise ValueError(f"frame truncated at {self.pos}+{n}")
        self.pos += n
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8")

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        return self._take(n)

    def array(self, elem) -> list:
        n = self.i32()
        if n < 0:
            return []
        return [elem() for _ in range(n)]

    def varint(self) -> int:
        """Zigzag-decoded signed varint (RecordBatch v2 records)."""
        shift = 0
        result = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            result |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (result >> 1) ^ -(result & 1)

    def remaining(self) -> int:
        return len(self.buf) - self.pos


class Writer:
    """Sequential big-endian writer building one frame."""

    __slots__ = ("parts",)

    def __init__(self) -> None:
        self.parts: List[bytes] = []

    def i8(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">b", v))
        return self

    def i16(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">h", v))
        return self

    def i32(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">i", v))
        return self

    def i64(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">q", v))
        return self

    def u32(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">I", v))
        return self

    def raw(self, b: bytes) -> "Writer":
        self.parts.append(b)
        return self

    def string(self, s: Optional[str]) -> "Writer":
        if s is None:
            return self.i16(-1)
        b = s.encode("utf-8")
        return self.i16(len(b)).raw(b)

    def bytes_(self, b: Optional[bytes]) -> "Writer":
        if b is None:
            return self.i32(-1)
        return self.i32(len(b)).raw(b)

    def array(self, items: Sequence, elem) -> "Writer":
        self.i32(len(items))
        for it in items:
            elem(it)
        return self

    def varint(self, v: int) -> "Writer":
        """Zigzag-encoded signed varint."""
        u = ((v << 1) ^ (v >> 63)) & ((1 << 64) - 1)
        out = bytearray()
        while True:
            if u < 0x80:
                out.append(u)
                break
            out.append((u & 0x7F) | 0x80)
            u >>= 7
        self.parts.append(bytes(out))
        return self

    def build(self) -> bytes:
        return b"".join(self.parts)


# -- CRC-32C (Castagnoli), required by RecordBatch v2 -------------------------

_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    table = _CRC32C_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- record formats -----------------------------------------------------------
# One produce/fetch payload is a "record blob": self-describing by the
# magic byte at a fixed offset, so decode_record_blob handles whatever
# era the peer speaks.

Record = Tuple[int, Optional[bytes], Optional[bytes], int, List[Tuple[str, bytes]]]
# (offset, key, value, timestamp_ms, headers)


def encode_message_set(records: Sequence[Record]) -> bytes:
    """MessageSet with magic-1 messages (CRC-32/IEEE; no headers —
    pre-0.11 clients cannot represent them)."""
    w = Writer()
    for offset, key, value, ts_ms, _headers in records:
        m = Writer()
        m.i8(1).i8(0).i64(ts_ms)  # magic, attributes, timestamp
        m.bytes_(key).bytes_(value)
        body = m.build()
        crc = zlib.crc32(body) & 0xFFFFFFFF
        msg = struct.pack(">I", crc) + body
        w.i64(offset).i32(len(msg)).raw(msg)
    return w.build()


def encode_record_batch(records: Sequence[Record]) -> bytes:
    """One RecordBatch v2 (magic 2) holding `records`; offsets must be
    contiguous ascending (the broker-side invariant of one batch)."""
    if not records:
        return b""
    base_offset = records[0][0]
    first_ts = records[0][3]
    max_ts = max(r[3] for r in records)
    body = Writer()
    for offset, key, value, ts_ms, headers in records:
        r = Writer()
        r.i8(0)  # attributes
        r.varint(ts_ms - first_ts)
        r.varint(offset - base_offset)
        if key is None:
            r.varint(-1)
        else:
            r.varint(len(key)).raw(key)
        if value is None:
            r.varint(-1)
        else:
            r.varint(len(value)).raw(value)
        r.varint(len(headers))
        for hk, hv in headers:
            hkb = hk.encode("utf-8")
            r.varint(len(hkb)).raw(hkb)
            if hv is None:
                r.varint(-1)
            else:
                r.varint(len(hv)).raw(hv)
        rec = r.build()
        body.varint(len(rec)).raw(rec)
    records_blob = body.build()
    # attributes..records: the CRC-covered region
    covered = (
        Writer()
        .i16(0)  # attributes (no compression, no txn)
        .i32(len(records) - 1)  # lastOffsetDelta
        .i64(first_ts)
        .i64(max_ts)
        .i64(-1)  # producerId
        .i16(-1)  # producerEpoch
        .i32(-1)  # baseSequence
        .i32(len(records))
        .raw(records_blob)
        .build()
    )
    head = (
        Writer()
        .i32(-1)  # partitionLeaderEpoch
        .i8(2)  # magic
        .u32(crc32c(covered))
        .raw(covered)
        .build()
    )
    return Writer().i64(base_offset).i32(len(head)).raw(head).build()


def decode_record_blob(blob: bytes) -> List[Record]:
    """Decode a produce/fetch payload of either format (self-describing
    via the magic byte); concatenated batches/sets are walked to the
    end, partial trailing data (fetch truncation) is ignored."""
    out: List[Record] = []
    r = Reader(blob)
    plain_budget = MAX_DECOMPRESSED_BATCH  # aggregate across ALL batches
    while r.remaining() >= 12:
        start = r.pos
        try:
            base_offset = r.i64()
            size = r.i32()
            if size < 0 or r.remaining() < size:
                break  # truncated trailer
            if size < 5:
                break
            # magic sits at byte 4 of the entry in BOTH formats:
            # v0/v1 message = crc(4) magic(1);
            # v2 batch = partitionLeaderEpoch(4) magic(1).
            magic = r.buf[r.pos + 4]
            if magic == 2:
                _ple = r.i32()
                _magic = r.i8()
                _crc = r.u32()
                attrs = r.i16()
                codec = attrs & 0x7
                if codec not in (0, 1):  # 1 = gzip (stdlib-decodable)
                    raise UnsupportedCodec(
                        f"compressed record batch (codec {codec}) not supported"
                    )
                _last_delta = r.i32()
                first_ts = r.i64()
                _max_ts = r.i64()
                _pid = r.i64()
                _pepoch = r.i16()
                _bseq = r.i32()
                n = r.i32()
                if codec == 1:
                    # gzip: the records section (after the count) is one
                    # compressed blob to the end of the batch. Bounded
                    # decompression: peer-controlled bytes must not be
                    # able to balloon memory (a ~1 MB bomb can expand
                    # 1000x), and a lying size field must not read
                    # backwards (_take rejects negative spans).
                    comp = r._take(start + 12 + size - r.pos)
                    try:
                        d = zlib.decompressobj(wbits=31)  # gzip framing
                        # the budget is shared across every batch in the
                        # blob: many small bombs must not add up past it
                        plain = d.decompress(comp, plain_budget + 1)
                        if len(plain) > plain_budget or d.unconsumed_tail:
                            raise UnsupportedCodec(
                                f"gzip batches exceed {MAX_DECOMPRESSED_BATCH} "
                                f"bytes decompressed"
                            )
                        if not d.eof:
                            # size-complete batch but the gzip stream is
                            # cut short: always corruption, never fetch
                            # truncation — reject loudly (a silent 0-
                            # record decode would let the gateway ACK a
                            # produce while dropping its records)
                            raise UnsupportedCodec("truncated gzip batch")
                        plain_budget -= len(plain)
                        sub = Reader(plain)
                    except UnsupportedCodec:
                        raise
                    except Exception as exc:  # noqa: BLE001
                        raise UnsupportedCodec(f"bad gzip batch: {exc}") from None
                else:
                    sub = r
                for _ in range(n):
                    rec_len = sub.varint()
                    rec_end = sub.pos + rec_len
                    _rattrs = sub.i8()
                    ts_delta = sub.varint()
                    off_delta = sub.varint()
                    klen = sub.varint()
                    key = sub._take(klen) if klen >= 0 else None
                    vlen = sub.varint()
                    value = sub._take(vlen) if vlen >= 0 else None
                    headers: List[Tuple[str, bytes]] = []
                    for _h in range(sub.varint()):
                        hklen = sub.varint()
                        hk = sub._take(hklen).decode("utf-8")
                        hvlen = sub.varint()
                        hv = sub._take(hvlen) if hvlen >= 0 else None
                        headers.append((hk, hv))
                    sub.pos = rec_end
                    out.append(
                        (base_offset + off_delta, key, value,
                         first_ts + ts_delta, headers)
                    )
            else:
                _crc = r.u32()
                _magic = r.i8()
                _attrs = r.i8()
                if _attrs & 0x7:  # compression codec bits
                    raise UnsupportedCodec(
                        f"compressed message set (codec {_attrs & 0x7}) not supported"
                    )
                ts_ms = r.i64() if _magic == 1 else -1
                key = r.bytes_()
                value = r.bytes_()
                out.append((base_offset, key, value, ts_ms, []))
            # step exactly one entry (v2 batch already consumed fully)
            r.pos = start + 12 + size
        except UnsupportedCodec:
            raise  # loud: the peer used compression we cannot decode
        except (ValueError, IndexError):
            break
    return out


# -- ConsumerProtocol (group membership metadata/assignment) ------------------


def encode_subscription(topics: Sequence[str], userdata: bytes = b"") -> bytes:
    w = Writer().i16(0)
    w.array(sorted(topics), lambda t: w.string(t))
    w.bytes_(userdata)
    return w.build()


def decode_subscription(blob: bytes) -> List[str]:
    try:
        r = Reader(blob)
        _version = r.i16()
        return [t for t in r.array(r.string) if t is not None]
    except (ValueError, IndexError):
        return []


def encode_assignment(parts: Sequence[Tuple[str, int]], userdata: bytes = b"") -> bytes:
    by_topic: dict = {}
    for t, p in parts:
        by_topic.setdefault(t, []).append(p)
    w = Writer().i16(0)

    def topic(item):
        t, ps = item
        w.string(t)
        w.array(sorted(ps), w.i32)

    w.array(sorted(by_topic.items()), topic)
    w.bytes_(userdata)
    return w.build()


def decode_assignment(blob: bytes) -> List[Tuple[str, int]]:
    try:
        r = Reader(blob)
        _version = r.i16()
        out: List[Tuple[str, int]] = []

        def topic():
            t = r.string()
            for p in r.array(r.i32):
                out.append((t, p))

        r.array(topic)
        return out
    except (ValueError, IndexError):
        return []
