"""Simulated Kafka — broker + producer/consumer/admin clients
(reference: madsim-rdkafka sim side, src/sim/).

`Broker` keeps topics/partitions with offsets, watermarks and
timestamp->offset lookup (reference: src/sim/broker.rs:12-60);
`SimBroker` serves the request protocol {CreateTopic, Produce, Fetch,
FetchMetadata, FetchWatermarks, OffsetsForTimes}
(reference: src/sim/sim_broker.rs:14-77). Client surface:
`ClientConfig` (string-keyed, reference: src/sim/config.rs),
`BaseProducer` (buffered + flush + fake transactions,
reference: src/sim/producer/base_producer.rs:154-330), `FutureProducer`
(delivery future, future_producer.rs:191-300), `BaseConsumer` /
`StreamConsumer` with assign/seek/poll/stream
(reference: src/sim/consumer.rs:50-470), `AdminClient` (src/sim/admin.rs).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ...dual import rand, time as sim_time  # mode-selected (sim or asyncio)
from ...errors import SimError
from ...net.network import ConnectionReset, parse_addr
from ...dual import net as _dual_net
from ...dual import task as _dual_task

Endpoint = _dual_net.Endpoint
spawn = _dual_task.spawn
from ...net.rpc import hash_str
from .._conn import StreamCaller

__all__ = [
    "KafkaError",
    "ErrorCode",
    "Broker",
    "SimBroker",
    "ClientConfig",
    "BaseProducer",
    "FutureProducer",
    "BaseConsumer",
    "StreamConsumer",
    "AdminClient",
    "NewTopic",
    "Offset",
    "Message",
]


class ErrorCode:
    """rdkafka-style error codes (reference: RDKafkaErrorCode; apps match
    on these, not on message strings)."""

    UNKNOWN_TOPIC_OR_PART = "UnknownTopicOrPartition"
    TOPIC_ALREADY_EXISTS = "TopicAlreadyExists"
    MSG_SIZE_TOO_LARGE = "MessageSizeTooLarge"
    OFFSET_OUT_OF_RANGE = "OffsetOutOfRange"
    INVALID_ARG = "InvalidArgument"
    TIMED_OUT = "TimedOut"
    INVALID_TXN_STATE = "InvalidTxnState"
    UNKNOWN_GROUP = "UnknownGroup"
    FAIL = "Fail"


class KafkaError(SimError):
    def __init__(self, message: str, code: str = ErrorCode.FAIL):
        super().__init__(message)
        self.code = code


class Message:
    """A delivered record (reference: BorrowedMessage surface, incl.
    headers — src/sim/producer records carry OwnedHeaders)."""

    __slots__ = ("topic", "partition", "offset", "key", "payload", "timestamp", "headers")

    def __init__(self, topic: str, partition: int, offset: int, key: Optional[bytes], payload: Optional[bytes], timestamp: int, headers: Optional[List[Tuple[str, bytes]]] = None):
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.key = key
        self.payload = payload
        self.timestamp = timestamp
        self.headers = headers or []

    def __repr__(self) -> str:  # pragma: no cover
        return f"Message({self.topic}[{self.partition}]@{self.offset})"


class Offset:
    """Seek positions (reference: rdkafka Offset enum)."""

    Beginning = "beginning"
    End = "end"
    Stored = "stored"  # the group's committed offset (needs group.id)

    @staticmethod
    def at(n: int) -> int:
        return n


class NewTopic:
    def __init__(self, name: str, num_partitions: int = 1):
        self.name = name
        self.num_partitions = num_partitions


# -- broker state (reference: src/sim/broker.rs) ------------------------------


class Partition:
    __slots__ = ("records",)

    def __init__(self) -> None:
        # list of (key, payload, timestamp_ms, headers); offset == index
        self.records: List[Tuple[Optional[bytes], Optional[bytes], int, list]] = []

    @property
    def high_watermark(self) -> int:
        return len(self.records)


class Broker:
    """Reference: broker.rs:12-60 (+ committed-offset store, the
    group-coordinator subset: one member per group, no rebalancing)."""

    def __init__(self, message_max_bytes: int = 1_000_000) -> None:
        self.topics: Dict[str, List[Partition]] = {}
        self._rr: Dict[str, int] = {}
        self.message_max_bytes = message_max_bytes
        # (group, topic, partition) -> committed offset
        self.committed_offsets: Dict[Tuple[str, str, int], int] = {}

    def create_topic(self, name: str, partitions: int) -> None:
        if name in self.topics:
            raise KafkaError(
                f"topic already exists: {name}", ErrorCode.TOPIC_ALREADY_EXISTS
            )
        if partitions < 1:
            raise KafkaError("partitions must be >= 1", ErrorCode.INVALID_ARG)
        self.topics[name] = [Partition() for _ in range(partitions)]
        self._rr[name] = 0

    def _partition(self, topic: str, partition: int) -> Partition:
        parts = self.topics.get(topic)
        if parts is None:
            raise KafkaError(f"unknown topic: {topic}", ErrorCode.UNKNOWN_TOPIC_OR_PART)
        if not (0 <= partition < len(parts)):
            raise KafkaError(
                f"unknown partition: {topic}[{partition}]",
                ErrorCode.UNKNOWN_TOPIC_OR_PART,
            )
        return parts[partition]

    def pick_partition(self, topic: str, key: Optional[bytes]) -> int:
        parts = self.topics.get(topic)
        if parts is None:
            raise KafkaError(f"unknown topic: {topic}", ErrorCode.UNKNOWN_TOPIC_OR_PART)
        if key is not None:
            return hash_str(key.decode("latin1")) % len(parts)
        idx = self._rr[topic] % len(parts)
        self._rr[topic] += 1
        return idx

    def produce(self, topic: str, partition: Optional[int], key: Optional[bytes], payload: Optional[bytes], ts_ms: int, headers: Optional[list] = None) -> Tuple[int, int]:
        size = len(key or b"") + len(payload or b"")
        if size > self.message_max_bytes:
            raise KafkaError(
                f"message size {size} > message.max.bytes {self.message_max_bytes}",
                ErrorCode.MSG_SIZE_TOO_LARGE,
            )
        if partition is None or partition < 0:
            partition = self.pick_partition(topic, key)
        part = self._partition(topic, partition)
        part.records.append((key, payload, ts_ms, list(headers or [])))
        return partition, len(part.records) - 1

    def fetch(self, topic: str, partition: int, offset: int, max_records: int) -> List[Message]:
        part = self._partition(topic, partition)
        out = []
        for off in range(max(0, offset), min(len(part.records), offset + max_records)):
            key, payload, ts, headers = part.records[off]
            out.append(Message(topic, partition, off, key, payload, ts, headers))
        return out

    def watermarks(self, topic: str, partition: int) -> Tuple[int, int]:
        part = self._partition(topic, partition)
        return (0, part.high_watermark)

    def offsets_for_time(self, topic: str, partition: int, ts_ms: int) -> Optional[int]:
        """First offset with timestamp >= ts_ms (reference: broker.rs
        timestamp->offset lookup)."""
        part = self._partition(topic, partition)
        for off, (_k, _p, ts, _h) in enumerate(part.records):
            if ts >= ts_ms:
                return off
        return None

    def metadata(self) -> Dict[str, int]:
        return {name: len(parts) for name, parts in self.topics.items()}

    # -- committed offsets (the consumer-group subset) --

    def commit_offsets(self, group: str, offsets: Dict[Tuple[str, int], int]) -> None:
        if not group:
            raise KafkaError("group.id required to commit", ErrorCode.UNKNOWN_GROUP)
        for (topic, partition), off in offsets.items():
            self._partition(topic, partition)  # validates
            self.committed_offsets[(group, topic, partition)] = off

    def committed(self, group: str, topic: str, partition: int) -> Optional[int]:
        self._partition(topic, partition)
        return self.committed_offsets.get((group, topic, partition))


# -- server --------------------------------------------------------------------


class SimBroker:
    """Reference: sim_broker.rs:14-77.

    `message_max_bytes` is the broker-side limit (like a real broker's
    message.max.bytes); the client's ClientConfig key of the same name is
    its own produce-time check — raise BOTH to ship larger messages."""

    def __init__(self, message_max_bytes: int = 1_000_000) -> None:
        self.broker = Broker(message_max_bytes=message_max_bytes)

    async def serve(self, addr: Any, on_bound=None) -> None:
        ep = await Endpoint.bind(addr)
        if on_bound is not None:
            on_bound(ep)
        while True:
            tx, rx, _peer = await ep.accept1()
            spawn(self._handle(tx, rx), name="kafka-conn")

    async def _handle(self, tx, rx) -> None:
        b = self.broker
        try:
            while (req := await rx.recv()) is not None:
                kind = req[0]
                try:
                    if kind == "create_topic":
                        b.create_topic(req[1], req[2])
                        rsp: Any = None
                    elif kind == "produce":
                        rsp = b.produce(req[1], req[2], req[3], req[4], req[5], req[6])
                    elif kind == "fetch":
                        rsp = b.fetch(req[1], req[2], req[3], req[4])
                    elif kind == "metadata":
                        rsp = b.metadata()
                    elif kind == "watermarks":
                        rsp = b.watermarks(req[1], req[2])
                    elif kind == "offsets_for_time":
                        rsp = b.offsets_for_time(req[1], req[2], req[3])
                    elif kind == "commit_offsets":
                        b.commit_offsets(req[1], req[2])
                        rsp = None
                    elif kind == "committed":
                        rsp = b.committed(req[1], req[2], req[3])
                    else:
                        raise KafkaError(f"unknown request {kind}", ErrorCode.INVALID_ARG)
                    tx.send(("ok", rsp))
                except KafkaError as e:
                    tx.send(("err", (e.code, str(e))))
        except ConnectionReset:
            pass
        finally:
            tx.close()  # real mode: one fd per connection must not linger


# -- client config (reference: src/sim/config.rs) -------------------------------


class ClientConfig:
    def __init__(self, conf: Optional[Dict[str, str]] = None):
        self.conf: Dict[str, str] = dict(conf or {})

    def set(self, key: str, value: str) -> "ClientConfig":
        self.conf[key] = value
        return self

    def get(self, key: str, default: str = "") -> str:
        return self.conf.get(key, default)

    def _addr(self):
        servers = self.conf.get("bootstrap.servers")
        if not servers:
            raise KafkaError("bootstrap.servers not set", ErrorCode.INVALID_ARG)
        return parse_addr(servers.split(",")[0])

    async def create_base_producer(self) -> "BaseProducer":
        return await BaseProducer._create(self)

    async def create_future_producer(self) -> "FutureProducer":
        p = FutureProducer()
        p._inner = await BaseProducer._create(self)
        return p

    async def create_base_consumer(self) -> "BaseConsumer":
        return await BaseConsumer._create(self)

    async def create_stream_consumer(self) -> "StreamConsumer":
        c = StreamConsumer()
        c.__dict__.update((await BaseConsumer._create(self)).__dict__)
        return c

    async def create_admin(self) -> "AdminClient":
        return await AdminClient._create(self)


class _Conn:
    """Broker connection handle over the shared StreamCaller (per-call
    channels in sim; a persistent locked stream in real mode — see
    services/_conn.py for the rationale)."""

    def __init__(self) -> None:
        self._caller = StreamCaller()

    async def open(self, addr) -> None:
        await self._caller.open(addr)

    # commit_offsets is value-idempotent: it overwrites the same absolute
    # offset, so re-sending after an ambiguous response loss cannot
    # duplicate anything (and not retrying makes auto-commit poll() skip
    # a delivered message whose position already advanced)
    _IDEMPOTENT = {"fetch", "metadata", "watermarks", "offsets_for_time",
                   "committed", "commit_offsets"}

    async def call(self, req: tuple):
        rsp = await self._caller.call(req, idempotent=req[0] in self._IDEMPOTENT)
        if rsp is None:
            raise KafkaError("broker unavailable", ErrorCode.TIMED_OUT)
        status, payload = rsp
        if status == "err":
            code, msg = payload
            raise KafkaError(msg, code)
        return payload


# -- producers -------------------------------------------------------------------


class BaseRecord:
    """Reference: rdkafka BaseRecord/FutureRecord (+ OwnedHeaders as a
    plain list of (name, value) pairs)."""

    def __init__(self, topic: str, key: Optional[bytes] = None, payload: Optional[bytes] = None, partition: Optional[int] = None, timestamp: Optional[int] = None, headers: Optional[List[Tuple[str, bytes]]] = None):
        self.topic = topic
        self.key = key
        self.payload = payload
        self.partition = partition
        self.timestamp = timestamp
        self.headers = list(headers or [])


FutureRecord = BaseRecord


class BaseProducer:
    """Buffered producer: `send` queues locally, `flush` ships to the
    broker; fake transactions are buffer fences
    (reference: base_producer.rs:154-330)."""

    def __init__(self) -> None:
        self._conn = _Conn()
        self._buffer: List[BaseRecord] = []
        self._in_txn = False
        self._max_bytes = 1_000_000

    @staticmethod
    async def _create(cfg: ClientConfig) -> "BaseProducer":
        p = BaseProducer()
        await p._conn.open(cfg._addr())
        # rdkafka rejects oversized messages at produce() time, before
        # any broker round trip (config: message.max.bytes)
        p._max_bytes = int(cfg.get("message.max.bytes", "1000000"))
        return p

    def _check_size(self, record: BaseRecord) -> None:
        size = len(record.key or b"") + len(record.payload or b"")
        if size > self._max_bytes:
            raise KafkaError(
                f"message size {size} > message.max.bytes {self._max_bytes}",
                ErrorCode.MSG_SIZE_TOO_LARGE,
            )

    def send(self, record: BaseRecord) -> None:
        self._check_size(record)
        self._buffer.append(record)

    async def flush(self) -> List[Tuple[int, int]]:
        out = []
        buffered, self._buffer = self._buffer, []
        for r in buffered:
            ts = r.timestamp if r.timestamp is not None else int(sim_time.now() * 1000)
            out.append(await self._conn.call(("produce", r.topic, r.partition, r.key, r.payload, ts, r.headers)))
        return out

    # fake transactions (reference: base_producer.rs transactions are
    # acknowledged but not isolated)
    def init_transactions(self) -> None:
        pass

    def begin_transaction(self) -> None:
        if self._in_txn:
            raise KafkaError("transaction already in progress", ErrorCode.INVALID_TXN_STATE)
        self._in_txn = True

    async def commit_transaction(self) -> None:
        if not self._in_txn:
            raise KafkaError("no transaction in progress", ErrorCode.INVALID_TXN_STATE)
        await self.flush()
        self._in_txn = False

    def abort_transaction(self) -> None:
        self._buffer.clear()
        self._in_txn = False


class DeliveryFuture:
    """Reference: future_producer.rs `DeliveryFuture`.

    Delivery errors (timeouts, broker unreachable) surface to the
    awaiter, not as a simulation-aborting task panic."""

    def __init__(self, coro):

        async def captured():
            try:
                return ("ok", await coro)
            except Exception as exc:  # noqa: BLE001
                return ("err", exc)

        self._handle = spawn(captured(), name="kafka-delivery")

    def __await__(self):
        return self._await().__await__()

    async def _await(self):
        status, value = await self._handle
        if status == "err":
            raise value
        return value


class FutureProducer:
    """Reference: future_producer.rs:191-300."""

    def __init__(self) -> None:
        self._inner: Optional[BaseProducer] = None

    def send(self, record: BaseRecord, timeout: Optional[float] = None) -> DeliveryFuture:
        async def deliver():
            self._inner._check_size(record)
            ts = record.timestamp if record.timestamp is not None else int(sim_time.now() * 1000)
            call = self._inner._conn.call(("produce", record.topic, record.partition, record.key, record.payload, ts, record.headers))
            if timeout is not None:
                return await sim_time.timeout(timeout, call)
            return await call

        return DeliveryFuture(deliver())

    async def send_and_wait(self, record: BaseRecord, timeout: Optional[float] = None) -> Tuple[int, int]:
        return await self.send(record, timeout)


# -- consumers --------------------------------------------------------------------


class BaseConsumer:
    """Manual-assignment consumer (reference: consumer.rs:50-470)."""

    def __init__(self) -> None:
        self._conn = _Conn()
        # (topic, partition) -> next offset
        self._positions: Dict[Tuple[str, int], int] = {}
        self._poll_interval = 0.01
        self._group = ""
        self._auto_commit = True
        self._auto_reset = "earliest"

    @staticmethod
    async def _create(cfg: ClientConfig) -> "BaseConsumer":
        c = BaseConsumer()
        await c._conn.open(cfg._addr())
        c._auto_reset = cfg.get("auto.offset.reset", "earliest")
        c._group = cfg.get("group.id", "")
        c._auto_commit = cfg.get("enable.auto.commit", "true") not in ("false", "0")
        return c

    async def subscribe(self, topics: Sequence[str]) -> None:
        """Assign all partitions of the topics. With a `group.id`, each
        partition resumes from the group's committed offset when one
        exists, else from `auto.offset.reset` (the single-member
        consumer-group subset: offsets persist at the broker, but there
        is no rebalancing across members)."""
        meta = await self._conn.call(("metadata",))
        for t in topics:
            if t not in meta:
                raise KafkaError(f"unknown topic: {t}", ErrorCode.UNKNOWN_TOPIC_OR_PART)
            for partid in range(meta[t]):
                start: Union[str, int] = (
                    Offset.Stored
                    if self._group
                    else (Offset.Beginning if self._auto_reset == "earliest" else Offset.End)
                )
                await self.assign(t, partid, start)

    async def assign(self, topic: str, partition: int, offset: Union[str, int] = Offset.Beginning) -> None:
        if offset == Offset.Stored:
            if not self._group:
                raise KafkaError("Offset.Stored needs group.id", ErrorCode.UNKNOWN_GROUP)
            stored = await self._conn.call(("committed", self._group, topic, partition))
            if stored is not None:
                self._positions[(topic, partition)] = stored
                return
            offset = Offset.Beginning if self._auto_reset == "earliest" else Offset.End
        lo, hi = await self._conn.call(("watermarks", topic, partition))
        if offset == Offset.Beginning:
            pos = lo
        elif offset == Offset.End:
            pos = hi
        else:
            pos = int(offset)
        self._positions[(topic, partition)] = pos

    async def seek(self, topic: str, partition: int, offset: Union[str, int]) -> None:
        if (topic, partition) not in self._positions:
            raise KafkaError(f"not assigned: {topic}[{partition}]", ErrorCode.INVALID_ARG)
        await self.assign(topic, partition, offset)

    # -- committed offsets (consumer-group subset) --

    async def commit(self) -> None:
        """Commit current positions to the broker for this group.id."""
        if not self._group:
            raise KafkaError("commit needs group.id", ErrorCode.UNKNOWN_GROUP)
        await self._conn.call(("commit_offsets", self._group, dict(self._positions)))

    async def committed(self, topic: str, partition: int) -> Optional[int]:
        if not self._group:
            raise KafkaError("committed needs group.id", ErrorCode.UNKNOWN_GROUP)
        return await self._conn.call(("committed", self._group, topic, partition))

    async def poll(self, timeout: Optional[float] = None) -> Optional[Message]:
        """Next message across assigned partitions, or None on timeout.
        With group.id + enable.auto.commit, the new position is committed
        after each delivered message (interval-batching simplified to
        per-message; same observable at-least-once semantics)."""
        deadline = sim_time.monotonic() + timeout if timeout is not None else None
        while True:
            for (topic, part), pos in sorted(self._positions.items()):
                msgs = await self._conn.call(("fetch", topic, part, pos, 1))
                if msgs:
                    self._positions[(topic, part)] = msgs[0].offset + 1
                    if self._group and self._auto_commit:
                        await self._conn.call(
                            ("commit_offsets", self._group, {(topic, part): msgs[0].offset + 1})
                        )
                    return msgs[0]
            if deadline is not None and sim_time.monotonic() >= deadline:
                return None
            await sim_time.sleep(self._poll_interval)

    async def fetch_watermarks(self, topic: str, partition: int) -> Tuple[int, int]:
        return tuple(await self._conn.call(("watermarks", topic, partition)))

    async def offsets_for_timestamp(self, topic: str, partition: int, ts_ms: int) -> Optional[int]:
        return await self._conn.call(("offsets_for_time", topic, partition, ts_ms))

    async def fetch_metadata(self) -> Dict[str, int]:
        return await self._conn.call(("metadata",))


class StreamConsumer(BaseConsumer):
    """Reference: consumer.rs `StreamConsumer` (async recv/stream)."""

    async def recv(self) -> Message:
        msg = await self.poll(timeout=None)
        assert msg is not None
        return msg

    def stream(self):
        return self

    def __aiter__(self) -> "StreamConsumer":
        return self

    async def __anext__(self) -> Message:
        return await self.recv()


# -- admin -----------------------------------------------------------------------


class AdminClient:
    """Reference: src/sim/admin.rs."""

    def __init__(self) -> None:
        self._conn = _Conn()

    @staticmethod
    async def _create(cfg: ClientConfig) -> "AdminClient":
        a = AdminClient()
        await a._conn.open(cfg._addr())
        return a

    async def create_topics(self, topics: Sequence[NewTopic]) -> List[Tuple[str, Optional[str]]]:
        """Per-topic results, rdkafka-style: (name, None) on success or
        (name, error_string) — creating an existing topic is not fatal
        (reference: admin.rs TopicResult semantics)."""
        results: List[Tuple[str, Optional[str]]] = []
        for t in topics:
            try:
                await self._conn.call(("create_topic", t.name, t.num_partitions))
                results.append((t.name, None))
            except KafkaError as e:
                results.append((t.name, str(e)))
        return results
