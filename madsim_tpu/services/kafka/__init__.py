"""Simulated Kafka — broker + producer/consumer/admin clients
(reference: madsim-rdkafka sim side, src/sim/).

`Broker` keeps topics/partitions with offsets, watermarks and
timestamp->offset lookup (reference: src/sim/broker.rs:12-60);
`SimBroker` serves the request protocol {CreateTopic, Produce, Fetch,
FetchMetadata, FetchWatermarks, OffsetsForTimes}
(reference: src/sim/sim_broker.rs:14-77). Client surface:
`ClientConfig` (string-keyed, reference: src/sim/config.rs),
`BaseProducer` (buffered + flush + fake transactions,
reference: src/sim/producer/base_producer.rs:154-330), `FutureProducer`
(delivery future, future_producer.rs:191-300), `BaseConsumer` /
`StreamConsumer` with assign/seek/poll/stream
(reference: src/sim/consumer.rs:50-470), `AdminClient` (src/sim/admin.rs).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ...dual import rand, time as sim_time  # mode-selected (sim or asyncio)
from ...errors import SimError
from ...net.network import ConnectionReset, parse_addr
from ...dual import net as _dual_net
from ...dual import task as _dual_task

Endpoint = _dual_net.Endpoint
spawn = _dual_task.spawn
from ...net.rpc import hash_str
from .._conn import StreamCaller

__all__ = [
    "KafkaError",
    "ErrorCode",
    "Broker",
    "SimBroker",
    "ClientConfig",
    "BaseProducer",
    "FutureProducer",
    "BaseConsumer",
    "StreamConsumer",
    "AdminClient",
    "NewTopic",
    "Offset",
    "Message",
]


class ErrorCode:
    """rdkafka-style error codes (reference: RDKafkaErrorCode; apps match
    on these, not on message strings)."""

    UNKNOWN_TOPIC_OR_PART = "UnknownTopicOrPartition"
    TOPIC_ALREADY_EXISTS = "TopicAlreadyExists"
    MSG_SIZE_TOO_LARGE = "MessageSizeTooLarge"
    OFFSET_OUT_OF_RANGE = "OffsetOutOfRange"
    INVALID_ARG = "InvalidArgument"
    TIMED_OUT = "TimedOut"
    INVALID_TXN_STATE = "InvalidTxnState"
    UNKNOWN_GROUP = "UnknownGroup"
    UNKNOWN_MEMBER_ID = "UnknownMemberId"
    ILLEGAL_GENERATION = "IllegalGeneration"
    REBALANCE_IN_PROGRESS = "RebalanceInProgress"
    FAIL = "Fail"


class KafkaError(SimError):
    def __init__(self, message: str, code: str = ErrorCode.FAIL):
        super().__init__(message)
        self.code = code


class Message:
    """A delivered record (reference: BorrowedMessage surface, incl.
    headers — src/sim/producer records carry OwnedHeaders)."""

    __slots__ = ("topic", "partition", "offset", "key", "payload", "timestamp", "headers")

    def __init__(self, topic: str, partition: int, offset: int, key: Optional[bytes], payload: Optional[bytes], timestamp: int, headers: Optional[List[Tuple[str, bytes]]] = None):
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.key = key
        self.payload = payload
        self.timestamp = timestamp
        self.headers = headers or []

    def __repr__(self) -> str:  # pragma: no cover
        return f"Message({self.topic}[{self.partition}]@{self.offset})"


class Offset:
    """Seek positions (reference: rdkafka Offset enum)."""

    Beginning = "beginning"
    End = "end"
    Stored = "stored"  # the group's committed offset (needs group.id)

    @staticmethod
    def at(n: int) -> int:
        return n


class NewTopic:
    def __init__(self, name: str, num_partitions: int = 1):
        self.name = name
        self.num_partitions = num_partitions


# -- broker state (reference: src/sim/broker.rs) ------------------------------


class _GroupMember:
    __slots__ = ("topics", "last_hb_ms", "session_ms")

    def __init__(self, topics: Sequence[str], now_ms: int, session_ms: int):
        self.topics = list(topics)
        self.last_hb_ms = now_ms
        self.session_ms = session_ms


class _Group:
    """Consumer-group coordinator state: generation-fenced membership
    with broker-computed assignments (the classic-protocol subset:
    join/sync/heartbeat/leave, range or roundrobin strategy)."""

    __slots__ = ("generation", "members", "assignments", "next_member", "strategy")

    def __init__(self) -> None:
        self.generation = 0
        self.members: Dict[str, _GroupMember] = {}
        self.assignments: Dict[str, List[Tuple[str, int]]] = {}
        self.next_member = 0
        self.strategy = "range"


class Partition:
    __slots__ = ("records",)

    def __init__(self) -> None:
        # list of (key, payload, timestamp_ms, headers); offset == index
        self.records: List[Tuple[Optional[bytes], Optional[bytes], int, list]] = []

    @property
    def high_watermark(self) -> int:
        return len(self.records)


class Broker:
    """Reference: broker.rs:12-60 (+ committed-offset store and a
    consumer-group coordinator — classic-protocol subset with
    join/sync/heartbeat/leave, range/roundrobin assignment,
    session-timeout eviction and generation-fenced commits; the
    reference sim has no groups at all)."""

    def __init__(self, message_max_bytes: int = 1_000_000,
                 expire_on_traffic: bool = True) -> None:
        self.topics: Dict[str, List[Partition]] = {}
        self._rr: Dict[str, int] = {}
        self.message_max_bytes = message_max_bytes
        # (group, topic, partition) -> committed offset
        self.committed_offsets: Dict[Tuple[str, str, int], int] = {}
        self.groups: Dict[str, _Group] = {}
        # True (default): member expiry sweeps on member traffic, like a
        # coordinator checking sessions inline. False: expiry runs ONLY
        # via sweep_expired() — the timer-driven coordinator model, used
        # by the cross-engine differential to align eviction moments
        # with the device machine's session tick exactly.
        self.expire_on_traffic = expire_on_traffic

    def create_topic(self, name: str, partitions: int) -> None:
        if name in self.topics:
            raise KafkaError(
                f"topic already exists: {name}", ErrorCode.TOPIC_ALREADY_EXISTS
            )
        if partitions < 1:
            raise KafkaError("partitions must be >= 1", ErrorCode.INVALID_ARG)
        self.topics[name] = [Partition() for _ in range(partitions)]
        self._rr[name] = 0
        # groups with members already subscribed to this topic rebalance
        # to pick up its partitions (rdkafka: subscribing to a
        # not-yet-created topic is not fatal — a metadata refresh assigns
        # it once it exists; members learn via the heartbeat fence)
        for g in self.groups.values():
            if any(name in m.topics for m in g.members.values()):
                self._rebalance(g)

    def _partition(self, topic: str, partition: int) -> Partition:
        parts = self.topics.get(topic)
        if parts is None:
            raise KafkaError(f"unknown topic: {topic}", ErrorCode.UNKNOWN_TOPIC_OR_PART)
        if not (0 <= partition < len(parts)):
            raise KafkaError(
                f"unknown partition: {topic}[{partition}]",
                ErrorCode.UNKNOWN_TOPIC_OR_PART,
            )
        return parts[partition]

    def pick_partition(self, topic: str, key: Optional[bytes]) -> int:
        parts = self.topics.get(topic)
        if parts is None:
            raise KafkaError(f"unknown topic: {topic}", ErrorCode.UNKNOWN_TOPIC_OR_PART)
        if key is not None:
            return hash_str(key.decode("latin1")) % len(parts)
        idx = self._rr[topic] % len(parts)
        self._rr[topic] += 1
        return idx

    def produce(self, topic: str, partition: Optional[int], key: Optional[bytes], payload: Optional[bytes], ts_ms: int, headers: Optional[list] = None) -> Tuple[int, int]:
        size = len(key or b"") + len(payload or b"")
        if size > self.message_max_bytes:
            raise KafkaError(
                f"message size {size} > message.max.bytes {self.message_max_bytes}",
                ErrorCode.MSG_SIZE_TOO_LARGE,
            )
        if partition is None or partition < 0:
            partition = self.pick_partition(topic, key)
        part = self._partition(topic, partition)
        part.records.append((key, payload, ts_ms, list(headers or [])))
        return partition, len(part.records) - 1

    def fetch(self, topic: str, partition: int, offset: int, max_records: int) -> List[Message]:
        part = self._partition(topic, partition)
        out = []
        for off in range(max(0, offset), min(len(part.records), offset + max_records)):
            key, payload, ts, headers = part.records[off]
            out.append(Message(topic, partition, off, key, payload, ts, headers))
        return out

    def watermarks(self, topic: str, partition: int) -> Tuple[int, int]:
        part = self._partition(topic, partition)
        return (0, part.high_watermark)

    def offsets_for_time(self, topic: str, partition: int, ts_ms: int) -> Optional[int]:
        """First offset with timestamp >= ts_ms (reference: broker.rs
        timestamp->offset lookup)."""
        part = self._partition(topic, partition)
        for off, (_k, _p, ts, _h) in enumerate(part.records):
            if ts >= ts_ms:
                return off
        return None

    def metadata(self) -> Dict[str, int]:
        return {name: len(parts) for name, parts in self.topics.items()}

    # -- committed offsets (the consumer-group subset) --

    def commit_offsets(
        self,
        group: str,
        offsets: Dict[Tuple[str, int], int],
        member_id: Optional[str] = None,
        generation: Optional[int] = None,
        now_ms: int = 0,
    ) -> None:
        """With (member_id, generation), the commit is generation-fenced:
        a zombie member that missed a rebalance cannot clobber the new
        owner's progress (classic-protocol commit semantics). Without
        them, a simple consumer commits unfenced (real Kafka's
        generation -1 path)."""
        if not group:
            raise KafkaError("group.id required to commit", ErrorCode.UNKNOWN_GROUP)
        if member_id is not None:
            # timer-driven mode: commits validate but do NOT refresh the
            # session (heartbeat-only liveness)
            self._coord_group(group, member_id, now_ms,
                              generation, ErrorCode.ILLEGAL_GENERATION,
                              refresh=self.expire_on_traffic)
        for (topic, partition), off in offsets.items():
            self._partition(topic, partition)  # validates
            self.committed_offsets[(group, topic, partition)] = off

    def committed(self, group: str, topic: str, partition: int) -> Optional[int]:
        self._partition(topic, partition)
        return self.committed_offsets.get((group, topic, partition))

    # -- group coordinator (classic protocol subset) --

    def _rebalance(self, g: _Group) -> None:
        g.generation += 1
        g.assignments = {m: [] for m in g.members}
        members = sorted(g.members)
        topics = sorted({t for m in g.members.values() for t in m.topics})
        if g.strategy == "roundrobin":
            # one circular pass over ALL topic-partitions (Kafka's
            # RoundRobinAssignor: interleaving per topic would hand every
            # single-partition topic to the same first member)
            idx = 0
            for topic in topics:
                parts = self.topics.get(topic)
                if parts is None or not any(
                    topic in g.members[m].topics for m in members
                ):
                    continue
                for p in range(len(parts)):
                    while topic not in g.members[members[idx % len(members)]].topics:
                        idx += 1
                    g.assignments[members[idx % len(members)]].append((topic, p))
                    idx += 1
            return
        for topic in topics:
            parts = self.topics.get(topic)
            if parts is None:
                continue
            subs = [m for m in members if topic in g.members[m].topics]
            if not subs:
                continue
            # range: contiguous chunks per topic; the first n % m members
            # get one extra partition (real range-assignor arithmetic)
            base, extra = divmod(len(parts), len(subs))
            start = 0
            for idx, m in enumerate(subs):
                take = base + (1 if idx < extra else 0)
                for p in range(start, start + take):
                    g.assignments[m].append((topic, p))
                start += take

    def _expire_members(self, g: _Group, now_ms: int) -> None:
        dead = [
            m for m, info in g.members.items()
            if now_ms - info.last_hb_ms > info.session_ms
        ]
        for m in dead:
            del g.members[m]
        if dead:
            self._rebalance(g)

    def sweep_expired(self, group: str, now_ms: int) -> None:
        """Timer-driven expiry sweep: evict members whose sessions
        lapsed and rebalance (the coordinator's periodic job; with
        `expire_on_traffic=False` this is the ONLY eviction path)."""
        g = self.groups.get(group)
        if g is not None:
            self._expire_members(g, now_ms)

    def join_group(
        self,
        group: str,
        member_id: Optional[str],
        topics: Sequence[str],
        session_ms: int,
        strategy: str,
        now_ms: int,
    ) -> Tuple[str, int]:
        if not group:
            raise KafkaError("group.id required to join", ErrorCode.UNKNOWN_GROUP)
        g = self.groups.setdefault(group, _Group())
        if self.expire_on_traffic:
            self._expire_members(g, now_ms)
        if not g.members and strategy:
            g.strategy = strategy  # first joiner picks the strategy
        if member_id is None or member_id not in g.members:
            if member_id is None:
                member_id = f"{group}-member-{g.next_member}"
                g.next_member += 1
            g.members[member_id] = _GroupMember(topics, now_ms, session_ms)
            self._rebalance(g)
        else:
            mem = g.members[member_id]
            mem.last_hb_ms = now_ms
            if sorted(mem.topics) != sorted(topics):
                mem.topics = list(topics)
                self._rebalance(g)
            # plain re-join after a rebalance notice: current generation
        return member_id, g.generation

    def sync_group(self, group: str, member_id: str, generation: int, now_ms: int) -> List[Tuple[str, int]]:
        g = self._coord_group(group, member_id, now_ms, generation)
        return list(g.assignments.get(member_id, []))

    def heartbeat(self, group: str, member_id: str, generation: int, now_ms: int) -> None:
        self._coord_group(group, member_id, now_ms, generation)

    def leave_group(self, group: str, member_id: str, now_ms: int) -> None:
        g = self.groups.get(group)
        if g is None:
            return
        if member_id in g.members:
            del g.members[member_id]
            self._rebalance(g)
        if self.expire_on_traffic:
            self._expire_members(g, now_ms)

    def describe_group(self, group: str, now_ms: int = 0) -> dict:
        g = self.groups.get(group)
        if g is None:
            raise KafkaError(f"unknown group: {group}", ErrorCode.UNKNOWN_GROUP)
        # reflect session-timeout semantics even when no member traffic
        # triggers eviction (a dead group would otherwise show its
        # corpse's assignments forever)
        if self.expire_on_traffic:
            self._expire_members(g, now_ms)
        return {
            "generation": g.generation,
            "strategy": g.strategy,
            "members": {m: list(info.topics) for m, info in g.members.items()},
            "assignments": {m: list(a) for m, a in g.assignments.items()},
        }

    def _coord_group(
        self,
        group: str,
        member_id: str,
        now_ms: int,
        generation: Optional[int] = None,
        stale_code: str = ErrorCode.REBALANCE_IN_PROGRESS,
        refresh: bool = True,
    ) -> _Group:
        """Resolve + expire the group, validate the member, and (when
        `generation` is given) fence it — the single fencing path for
        sync/heartbeat/fenced-commit. A live check refreshes the
        member's heartbeat clock — except commits in timer-driven mode
        (`refresh=False`): there session liveness is heartbeat-only, so
        an in-flight commit from a dying member cannot stretch its
        session past what the heartbeat record supports."""
        g = self.groups.get(group)
        if g is not None and self.expire_on_traffic:
            self._expire_members(g, now_ms)
        if g is None or member_id not in g.members:
            raise KafkaError(f"unknown member: {member_id}", ErrorCode.UNKNOWN_MEMBER_ID)
        if generation is not None and generation != g.generation:
            raise KafkaError(
                f"generation {generation} != {g.generation}", stale_code
            )
        if refresh:
            g.members[member_id].last_hb_ms = now_ms
        return g


# -- server --------------------------------------------------------------------


class SimBroker:
    """Reference: sim_broker.rs:14-77.

    `message_max_bytes` is the broker-side limit (like a real broker's
    message.max.bytes); the client's ClientConfig key of the same name is
    its own produce-time check — raise BOTH to ship larger messages."""

    def __init__(self, message_max_bytes: int = 1_000_000) -> None:
        self.broker = Broker(message_max_bytes=message_max_bytes)

    async def serve(self, addr: Any, on_bound=None) -> None:
        ep = await Endpoint.bind(addr)
        if on_bound is not None:
            on_bound(ep)
        while True:
            tx, rx, _peer = await ep.accept1()
            spawn(self._handle(tx, rx), name="kafka-conn")

    async def _handle(self, tx, rx) -> None:
        b = self.broker
        try:
            while (req := await rx.recv()) is not None:
                kind = req[0]
                now_ms = int(sim_time.now() * 1000)  # one clock per request
                try:
                    if kind == "create_topic":
                        b.create_topic(req[1], req[2])
                        rsp: Any = None
                    elif kind == "produce":
                        rsp = b.produce(req[1], req[2], req[3], req[4], req[5], req[6])
                    elif kind == "fetch":
                        rsp = b.fetch(req[1], req[2], req[3], req[4])
                    elif kind == "metadata":
                        rsp = b.metadata()
                    elif kind == "watermarks":
                        rsp = b.watermarks(req[1], req[2])
                    elif kind == "offsets_for_time":
                        rsp = b.offsets_for_time(req[1], req[2], req[3])
                    elif kind == "commit_offsets":
                        if len(req) > 3:  # generation-fenced commit
                            b.commit_offsets(req[1], req[2], req[3], req[4],
                                             now_ms=now_ms)
                        else:
                            b.commit_offsets(req[1], req[2])
                        rsp = None
                    elif kind == "committed":
                        rsp = b.committed(req[1], req[2], req[3])
                    elif kind == "join_group":
                        rsp = b.join_group(req[1], req[2], req[3], req[4], req[5], now_ms)
                    elif kind == "sync_group":
                        rsp = b.sync_group(req[1], req[2], req[3], now_ms)
                    elif kind == "heartbeat":
                        b.heartbeat(req[1], req[2], req[3], now_ms)
                        rsp = None
                    elif kind == "leave_group":
                        b.leave_group(req[1], req[2], now_ms)
                        rsp = None
                    elif kind == "describe_group":
                        rsp = b.describe_group(req[1], now_ms)
                    else:
                        raise KafkaError(f"unknown request {kind}", ErrorCode.INVALID_ARG)
                    tx.send(("ok", rsp))
                except KafkaError as e:
                    tx.send(("err", (e.code, str(e))))
        except ConnectionReset:
            pass
        finally:
            tx.close()  # real mode: one fd per connection must not linger


# -- client config (reference: src/sim/config.rs) -------------------------------


class ClientConfig:
    def __init__(self, conf: Optional[Dict[str, str]] = None):
        self.conf: Dict[str, str] = dict(conf or {})

    def set(self, key: str, value: str) -> "ClientConfig":
        self.conf[key] = value
        return self

    def get(self, key: str, default: str = "") -> str:
        return self.conf.get(key, default)

    def _addr(self):
        servers = self.conf.get("bootstrap.servers")
        if not servers:
            raise KafkaError("bootstrap.servers not set", ErrorCode.INVALID_ARG)
        return parse_addr(servers.split(",")[0])

    async def create_base_producer(self) -> "BaseProducer":
        return await BaseProducer._create(self)

    async def create_future_producer(self) -> "FutureProducer":
        p = FutureProducer()
        p._inner = await BaseProducer._create(self)
        return p

    async def create_base_consumer(self) -> "BaseConsumer":
        return await BaseConsumer._create(self)

    async def create_stream_consumer(self) -> "StreamConsumer":
        c = StreamConsumer()
        c.__dict__.update((await BaseConsumer._create(self)).__dict__)
        return c

    async def create_admin(self) -> "AdminClient":
        return await AdminClient._create(self)


class _Conn:
    """Broker connection handle over the shared StreamCaller (per-call
    channels in sim; a persistent locked stream in real mode — see
    services/_conn.py for the rationale)."""

    def __init__(self) -> None:
        self._caller = StreamCaller()
        # real mode with a genuine broker at bootstrap.servers: the data
        # plane speaks the genuine Kafka wire protocol natively
        # (real_client.RealKafkaConn, stdlib-only — the analogue of the
        # reference vendoring real rdkafka, madsim-rdkafka/src/lib.rs:5-12)
        self._real = None

    async def open(self, addr) -> None:
        from ...dual import IS_SIM, real_passthrough_enabled

        if not IS_SIM and real_passthrough_enabled():
            from .real_client import RealKafkaConn, probe_real_kafka

            host, port = addr
            if await probe_real_kafka(host, port):
                self._real = RealKafkaConn(f"{host}:{port}")
                return
        await self._caller.open(addr)

    # commit_offsets is value-idempotent: it overwrites the same absolute
    # offset, so re-sending after an ambiguous response loss cannot
    # duplicate anything (and not retrying makes auto-commit poll() skip
    # a delivered message whose position already advanced)
    # group ops: heartbeat/sync re-send the same generation check and
    # leave is a no-op on a gone member; join_group is NOT idempotent
    # when member_id is None (a re-send would register a ghost member)
    _IDEMPOTENT = {"fetch", "metadata", "watermarks", "offsets_for_time",
                   "committed", "commit_offsets", "heartbeat", "sync_group",
                   "leave_group", "describe_group"}

    def close(self) -> None:
        """Release the backend: the wire client's broker sockets or the
        sim-protocol stream fd (both teardown paths are non-blocking)."""
        real, self._real = self._real, None
        self._caller.close()
        if real is not None:
            real.close()

    async def call(self, req: tuple):
        if self._real is not None:
            return await self._real.call(req)
        rsp = await self._caller.call(req, idempotent=req[0] in self._IDEMPOTENT)
        if rsp is None:
            raise KafkaError("broker unavailable", ErrorCode.TIMED_OUT)
        status, payload = rsp
        if status == "err":
            code, msg = payload
            raise KafkaError(msg, code)
        return payload


# -- producers -------------------------------------------------------------------


class BaseRecord:
    """Reference: rdkafka BaseRecord/FutureRecord (+ OwnedHeaders as a
    plain list of (name, value) pairs)."""

    def __init__(self, topic: str, key: Optional[bytes] = None, payload: Optional[bytes] = None, partition: Optional[int] = None, timestamp: Optional[int] = None, headers: Optional[List[Tuple[str, bytes]]] = None):
        self.topic = topic
        self.key = key
        self.payload = payload
        self.partition = partition
        self.timestamp = timestamp
        self.headers = list(headers or [])


FutureRecord = BaseRecord


class BaseProducer:
    """Buffered producer: `send` queues locally, `flush` ships to the
    broker; fake transactions are buffer fences
    (reference: base_producer.rs:154-330)."""

    def __init__(self) -> None:
        self._conn = _Conn()
        self._buffer: List[BaseRecord] = []
        self._in_txn = False
        self._max_bytes = 1_000_000

    @staticmethod
    async def _create(cfg: ClientConfig) -> "BaseProducer":
        p = BaseProducer()
        await p._conn.open(cfg._addr())
        # rdkafka rejects oversized messages at produce() time, before
        # any broker round trip (config: message.max.bytes)
        p._max_bytes = int(cfg.get("message.max.bytes", "1000000"))
        return p

    def close(self) -> None:
        """Release the connection (genuine-lib clients or the sim fd)."""
        self._conn.close()

    def _check_size(self, record: BaseRecord) -> None:
        size = len(record.key or b"") + len(record.payload or b"")
        if size > self._max_bytes:
            raise KafkaError(
                f"message size {size} > message.max.bytes {self._max_bytes}",
                ErrorCode.MSG_SIZE_TOO_LARGE,
            )

    def send(self, record: BaseRecord) -> None:
        self._check_size(record)
        self._buffer.append(record)

    async def flush(self) -> List[Tuple[int, int]]:
        out = []
        buffered, self._buffer = self._buffer, []
        for r in buffered:
            ts = r.timestamp if r.timestamp is not None else int(sim_time.now() * 1000)
            out.append(await self._conn.call(("produce", r.topic, r.partition, r.key, r.payload, ts, r.headers)))
        return out

    # fake transactions (reference: base_producer.rs transactions are
    # acknowledged but not isolated)
    def init_transactions(self) -> None:
        pass

    def begin_transaction(self) -> None:
        if self._in_txn:
            raise KafkaError("transaction already in progress", ErrorCode.INVALID_TXN_STATE)
        self._in_txn = True

    async def commit_transaction(self) -> None:
        if not self._in_txn:
            raise KafkaError("no transaction in progress", ErrorCode.INVALID_TXN_STATE)
        await self.flush()
        self._in_txn = False

    def abort_transaction(self) -> None:
        self._buffer.clear()
        self._in_txn = False


class DeliveryFuture:
    """Reference: future_producer.rs `DeliveryFuture`.

    Delivery errors (timeouts, broker unreachable) surface to the
    awaiter, not as a simulation-aborting task panic."""

    def __init__(self, coro):

        async def captured():
            try:
                return ("ok", await coro)
            except Exception as exc:  # noqa: BLE001
                return ("err", exc)

        self._handle = spawn(captured(), name="kafka-delivery")

    def __await__(self):
        return self._await().__await__()

    async def _await(self):
        status, value = await self._handle
        if status == "err":
            raise value
        return value


class FutureProducer:
    """Reference: future_producer.rs:191-300."""

    def __init__(self) -> None:
        self._inner: Optional[BaseProducer] = None

    def close(self) -> None:
        if self._inner is not None:
            self._inner.close()

    def send(self, record: BaseRecord, timeout: Optional[float] = None) -> DeliveryFuture:
        async def deliver():
            self._inner._check_size(record)
            ts = record.timestamp if record.timestamp is not None else int(sim_time.now() * 1000)
            call = self._inner._conn.call(("produce", record.topic, record.partition, record.key, record.payload, ts, record.headers))
            if timeout is not None:
                return await sim_time.timeout(timeout, call)
            return await call

        return DeliveryFuture(deliver())

    async def send_and_wait(self, record: BaseRecord, timeout: Optional[float] = None) -> Tuple[int, int]:
        return await self.send(record, timeout)


# -- consumers --------------------------------------------------------------------


class BaseConsumer:
    """Manual-assignment consumer (reference: consumer.rs:50-470)."""

    def __init__(self) -> None:
        self._conn = _Conn()
        # (topic, partition) -> next offset
        self._positions: Dict[Tuple[str, int], int] = {}
        self._poll_interval = 0.01
        self._group = ""
        self._auto_commit = True
        self._auto_reset = "earliest"
        # group membership (classic protocol, driven from poll())
        self._member_id: Optional[str] = None
        self._generation = -1
        self._sub_topics: List[str] = []
        self._session_ms = 10_000
        self._hb_interval = 3.0
        self._strategy = "range"
        self._next_hb = 0.0

    @staticmethod
    async def _create(cfg: ClientConfig) -> "BaseConsumer":
        c = BaseConsumer()
        await c._conn.open(cfg._addr())
        c._auto_reset = cfg.get("auto.offset.reset", "earliest")
        c._group = cfg.get("group.id", "")
        c._auto_commit = cfg.get("enable.auto.commit", "true") not in ("false", "0")
        c._session_ms = int(cfg.get("session.timeout.ms", "10000"))
        c._hb_interval = int(cfg.get("heartbeat.interval.ms", "3000")) / 1000.0
        c._strategy = cfg.get("partition.assignment.strategy", "range")
        return c

    async def subscribe(self, topics: Sequence[str]) -> None:
        """With a `group.id`: join the consumer group — the broker's
        coordinator assigns this member a share of the partitions
        (range or roundrobin per `partition.assignment.strategy`) and
        rebalances as members join/leave/expire; each owned partition
        resumes from the group's committed offset. Without one: assign
        all partitions from `auto.offset.reset`."""
        meta = await self._conn.call(("metadata",))
        if self._group:
            # group mode: unknown topics are not fatal (rdkafka queues an
            # UNKNOWN_TOPIC_OR_PART event but keeps the subscription; the
            # broker rebalances us in when the topic is created)
            self._sub_topics = list(topics)
            await self._rejoin()
            return
        for t in topics:
            if t not in meta:
                raise KafkaError(f"unknown topic: {t}", ErrorCode.UNKNOWN_TOPIC_OR_PART)
        for t in topics:
            for partid in range(meta[t]):
                start: Union[str, int] = (
                    Offset.Beginning if self._auto_reset == "earliest" else Offset.End
                )
                await self.assign(t, partid, start)

    async def unsubscribe(self) -> None:
        """Leave the group (partitions move to the remaining members)."""
        if self._member_id is not None:
            await self._conn.call(("leave_group", self._group, self._member_id))
            self._member_id = None
            self._generation = -1
        self._positions.clear()
        self._sub_topics = []

    async def close(self) -> None:
        """Commit progress (auto-commit mode) and leave the group."""
        if self._member_id is not None and self._auto_commit and self._positions:
            try:
                await self._commit_positions(dict(self._positions))
            except KafkaError:
                pass  # mid-rebalance: the new owner resumes from the last commit
        await self.unsubscribe()
        self._conn.close()

    # -- group protocol plumbing (poll-driven, like rdkafka) --

    async def _rejoin(self) -> None:
        while True:
            mid, gen = await self._conn.call(
                ("join_group", self._group, self._member_id, list(self._sub_topics),
                 self._session_ms, self._strategy)
            )
            self._member_id, self._generation = mid, gen
            try:
                parts = await self._conn.call(("sync_group", self._group, mid, gen))
                break
            except KafkaError as e:
                if e.code != ErrorCode.REBALANCE_IN_PROGRESS:
                    raise
                # another member joined between our join and sync: loop
                # (not recursion — churny groups would grow the stack)
        old = self._positions
        self._positions = {}
        for (t, p) in parts:
            if (t, p) in old:
                self._positions[(t, p)] = old[(t, p)]  # keep live position
            else:
                await self.assign(t, p, Offset.Stored)
        self._next_hb = sim_time.monotonic() + self._hb_interval

    async def _heartbeat_tick(self) -> None:
        if self._member_id is None or sim_time.monotonic() < self._next_hb:
            return
        try:
            await self._conn.call(
                ("heartbeat", self._group, self._member_id, self._generation)
            )
            self._next_hb = sim_time.monotonic() + self._hb_interval
        except KafkaError as e:
            if e.code in (ErrorCode.REBALANCE_IN_PROGRESS, ErrorCode.ILLEGAL_GENERATION):
                await self._rejoin()
            elif e.code == ErrorCode.UNKNOWN_MEMBER_ID:
                # evicted: rejoin as a new member. In-memory positions are
                # stale — another member may have consumed and committed
                # past them while we were out; keeping them would rewind
                # the group's committed offsets on our next auto-commit.
                self._member_id = None
                self._positions.clear()
                await self._rejoin()
            else:
                raise

    async def _commit_positions(self, offsets: Dict[Tuple[str, int], int]) -> None:
        if self._member_id is not None:
            await self._conn.call(
                ("commit_offsets", self._group, offsets, self._member_id, self._generation)
            )
        else:
            await self._conn.call(("commit_offsets", self._group, offsets))

    async def assign(self, topic: str, partition: int, offset: Union[str, int] = Offset.Beginning) -> None:
        if offset == Offset.Stored:
            if not self._group:
                raise KafkaError("Offset.Stored needs group.id", ErrorCode.UNKNOWN_GROUP)
            stored = await self._conn.call(("committed", self._group, topic, partition))
            if stored is not None:
                self._positions[(topic, partition)] = stored
                return
            offset = Offset.Beginning if self._auto_reset == "earliest" else Offset.End
        lo, hi = await self._conn.call(("watermarks", topic, partition))
        if offset == Offset.Beginning:
            pos = lo
        elif offset == Offset.End:
            pos = hi
        else:
            pos = int(offset)
        self._positions[(topic, partition)] = pos

    async def seek(self, topic: str, partition: int, offset: Union[str, int]) -> None:
        if (topic, partition) not in self._positions:
            raise KafkaError(f"not assigned: {topic}[{partition}]", ErrorCode.INVALID_ARG)
        await self.assign(topic, partition, offset)

    # -- committed offsets (consumer-group subset) --

    async def commit(self) -> None:
        """Commit current positions to the broker for this group.id
        (generation-fenced when this consumer is a group member)."""
        if not self._group:
            raise KafkaError("commit needs group.id", ErrorCode.UNKNOWN_GROUP)
        await self._commit_positions(dict(self._positions))

    async def committed(self, topic: str, partition: int) -> Optional[int]:
        if not self._group:
            raise KafkaError("committed needs group.id", ErrorCode.UNKNOWN_GROUP)
        return await self._conn.call(("committed", self._group, topic, partition))

    async def poll(self, timeout: Optional[float] = None) -> Optional[Message]:
        """Next message across assigned partitions, or None on timeout.
        With group.id + enable.auto.commit, the new position is committed
        after each delivered message (interval-batching simplified to
        per-message; same observable at-least-once semantics)."""
        deadline = sim_time.monotonic() + timeout if timeout is not None else None
        while True:
            await self._heartbeat_tick()  # drives rebalances, like rdkafka
            for (topic, part), pos in sorted(self._positions.items()):
                msgs = await self._conn.call(("fetch", topic, part, pos, 1))
                if msgs:
                    self._positions[(topic, part)] = msgs[0].offset + 1
                    if self._group and self._auto_commit:
                        try:
                            await self._commit_positions(
                                {(topic, part): msgs[0].offset + 1}
                            )
                        except KafkaError as e:
                            if e.code in (ErrorCode.REBALANCE_IN_PROGRESS,
                                          ErrorCode.ILLEGAL_GENERATION,
                                          ErrorCode.UNKNOWN_MEMBER_ID):
                                # mid-rebalance: deliver the message
                                # (at-least-once) and rejoin on the next
                                # poll's heartbeat
                                self._next_hb = 0.0
                            else:
                                raise
                    return msgs[0]
            if deadline is not None and sim_time.monotonic() >= deadline:
                return None
            await sim_time.sleep(self._poll_interval)

    async def fetch_watermarks(self, topic: str, partition: int) -> Tuple[int, int]:
        return tuple(await self._conn.call(("watermarks", topic, partition)))

    async def offsets_for_timestamp(self, topic: str, partition: int, ts_ms: int) -> Optional[int]:
        return await self._conn.call(("offsets_for_time", topic, partition, ts_ms))

    async def fetch_metadata(self) -> Dict[str, int]:
        return await self._conn.call(("metadata",))


class StreamConsumer(BaseConsumer):
    """Reference: consumer.rs `StreamConsumer` (async recv/stream)."""

    async def recv(self) -> Message:
        msg = await self.poll(timeout=None)
        assert msg is not None
        return msg

    def stream(self):
        return self

    def __aiter__(self) -> "StreamConsumer":
        return self

    async def __anext__(self) -> Message:
        return await self.recv()


# -- admin -----------------------------------------------------------------------


class AdminClient:
    """Reference: src/sim/admin.rs."""

    def __init__(self) -> None:
        self._conn = _Conn()

    @staticmethod
    async def _create(cfg: ClientConfig) -> "AdminClient":
        a = AdminClient()
        await a._conn.open(cfg._addr())
        return a

    def close(self) -> None:
        self._conn.close()

    async def create_topics(self, topics: Sequence[NewTopic]) -> List[Tuple[str, Optional[str]]]:
        """Per-topic results, rdkafka-style: (name, None) on success or
        (name, error_string) — creating an existing topic is not fatal
        (reference: admin.rs TopicResult semantics)."""
        results: List[Tuple[str, Optional[str]]] = []
        for t in topics:
            try:
                await self._conn.call(("create_topic", t.name, t.num_partitions))
                results.append((t.name, None))
            except KafkaError as e:
                results.append((t.name, str(e)))
        return results

    async def describe_group(self, group: str) -> dict:
        """Coordinator view of a consumer group: generation, strategy,
        members with their subscriptions, and current assignments."""
        return await self._conn.call(("describe_group", group))
