"""KafkaWireGateway — the genuine Kafka wire protocol served from the
sim `Broker` state machine over asyncio streams; the kafka twin of
`EtcdGrpcGateway` and `S3HttpGateway`, completing the passthrough triad
(VERDICT r4 directive 1).

Real clients (kafka-python, librdkafka, or this repo's own
`KafkaWireClient`) can point at the gateway and produce/fetch/commit/
coordinate exactly as against a real broker: the gateway answers
ApiVersions, Metadata, Produce (v0-v3), Fetch (v0-v4), ListOffsets,
CreateTopics, FindCoordinator, OffsetCommit/Fetch, DescribeGroups and
the classic group protocol (JoinGroup/SyncGroup/Heartbeat/LeaveGroup)
with bit-accurate frames. Record payloads use RecordBatch v2 for
Fetch v4+ (headers preserved) and MessageSet v1 below that.

The group protocol is served with broker-side assignment: the sim
`Broker`'s coordinator (range/roundrobin, session-timeout eviction,
generation fencing) owns assignments, and SyncGroup returns them in
ConsumerProtocol form regardless of what a leader submitted — a genuine
client still sees a fully conformant join/sync/heartbeat cycle.

Reference: madsim-rdkafka's non-sim build vendors genuine rdkafka
(/root/reference/madsim-rdkafka/src/lib.rs:5-12); here the real-mode
surface is the broker side of the same wire.
"""

from __future__ import annotations

import asyncio
import struct
# madsim: allow-file(D001) — genuine-wire Kafka gateway: log append
# timestamps are protocol fields real clients read; real mode only.
import time
from typing import Dict, List, Optional, Tuple

from . import Broker, ErrorCode, KafkaError
from .wire import (
    ApiKey,
    Err,
    Reader,
    UnsupportedCodec,
    Writer,
    decode_record_blob,
    decode_subscription,
    encode_assignment,
    encode_message_set,
    encode_record_batch,
    encode_subscription,
)

__all__ = ["KafkaWireGateway"]

_CODE_MAP = {
    ErrorCode.UNKNOWN_TOPIC_OR_PART: Err.UNKNOWN_TOPIC_OR_PARTITION,
    ErrorCode.TOPIC_ALREADY_EXISTS: Err.TOPIC_ALREADY_EXISTS,
    ErrorCode.MSG_SIZE_TOO_LARGE: Err.MESSAGE_TOO_LARGE,
    ErrorCode.OFFSET_OUT_OF_RANGE: Err.OFFSET_OUT_OF_RANGE,
    ErrorCode.INVALID_ARG: Err.INVALID_REQUEST,
    ErrorCode.UNKNOWN_GROUP: Err.COORDINATOR_NOT_AVAILABLE,
    ErrorCode.UNKNOWN_MEMBER_ID: Err.UNKNOWN_MEMBER_ID,
    ErrorCode.ILLEGAL_GENERATION: Err.ILLEGAL_GENERATION,
    ErrorCode.REBALANCE_IN_PROGRESS: Err.REBALANCE_IN_PROGRESS,
}

# (api_key, min_version, max_version) advertised by ApiVersions; genuine
# clients pick call versions from these ranges (kafka-python infers a
# ~0.11-era broker, matching what the gateway actually parses).
_SUPPORTED: List[Tuple[int, int, int]] = [
    (ApiKey.PRODUCE, 0, 3),
    (ApiKey.FETCH, 0, 4),
    (ApiKey.LIST_OFFSETS, 0, 1),
    (ApiKey.METADATA, 0, 1),
    (ApiKey.OFFSET_COMMIT, 0, 2),
    (ApiKey.OFFSET_FETCH, 0, 1),
    (ApiKey.FIND_COORDINATOR, 0, 0),
    (ApiKey.JOIN_GROUP, 0, 1),
    (ApiKey.HEARTBEAT, 0, 0),
    (ApiKey.LEAVE_GROUP, 0, 0),
    (ApiKey.SYNC_GROUP, 0, 0),
    (ApiKey.DESCRIBE_GROUPS, 0, 0),
    (ApiKey.API_VERSIONS, 0, 0),
    (ApiKey.CREATE_TOPICS, 0, 0),
]

_NODE_ID = 0  # the gateway is a single-broker "cluster"


def _kafka_code(e: KafkaError) -> int:
    return _CODE_MAP.get(e.code, Err.INVALID_REQUEST)


class KafkaWireGateway:
    """Serve the genuine Kafka protocol from a sim Broker."""

    def __init__(self, broker: Optional[Broker] = None,
                 advertised_host: str = "127.0.0.1"):
        self.broker = broker if broker is not None else Broker()
        self.advertised_host = advertised_host
        self.advertised_port = 0  # set on start()
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()

    # -- lifecycle ----------------------------------------------------------

    async def start(self, addr: str = "127.0.0.1:0") -> int:
        host, _, port = addr.rpartition(":")
        self._server = await asyncio.start_server(
            self._conn, host or "127.0.0.1", int(port)
        )
        self.advertised_port = self._server.sockets[0].getsockname()[1]
        return self.advertised_port

    async def wait(self) -> None:
        await self._server.serve_forever()

    async def serve(self, addr: str) -> None:
        await self.start(addr)
        await self.wait()

    async def stop(self) -> None:
        for w in list(self._writers):
            w.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- connection loop ----------------------------------------------------

    async def _conn(self, reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        try:
            while True:
                head = await reader.readexactly(4)
                (n,) = struct.unpack(">i", head)
                if n <= 0 or n > 64 * 1024 * 1024:
                    return
                frame = await reader.readexactly(n)
                r = Reader(frame)
                api_key = r.i16()
                api_version = r.i16()
                correlation_id = r.i32()
                _client_id = r.string()
                body = self._dispatch(api_key, api_version, r)
                if body is None:
                    continue  # acks=0 produce: real brokers send nothing
                rsp = struct.pack(">i", correlation_id) + body
                writer.write(struct.pack(">i", len(rsp)) + rsp)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            self._writers.discard(writer)
            writer.close()

    def _dispatch(self, api_key: int, v: int, r: Reader) -> bytes:
        now_ms = int(time.time() * 1000)
        if api_key == ApiKey.API_VERSIONS:
            # v1+ requests get UNSUPPORTED_VERSION in v0 encoding — the
            # standard downgrade dance (librdkafka opens with v3 and
            # retries with v0 on code 35); the version array still rides
            # along so the client can pick without a second round-trip
            return self._api_versions(
                Err.UNSUPPORTED_VERSION if v > 0 else Err.NONE
            )
        if api_key == ApiKey.METADATA:
            return self._metadata(v, r)
        if api_key == ApiKey.PRODUCE:
            return self._produce(v, r, now_ms)
        if api_key == ApiKey.FETCH:
            return self._fetch(v, r)
        if api_key == ApiKey.LIST_OFFSETS:
            return self._list_offsets(v, r)
        if api_key == ApiKey.CREATE_TOPICS:
            return self._create_topics(r)
        if api_key == ApiKey.FIND_COORDINATOR:
            return self._find_coordinator(r)
        if api_key == ApiKey.OFFSET_COMMIT:
            return self._offset_commit(v, r, now_ms)
        if api_key == ApiKey.OFFSET_FETCH:
            return self._offset_fetch(r)
        if api_key == ApiKey.DESCRIBE_GROUPS:
            return self._describe_groups(r, now_ms)
        if api_key == ApiKey.JOIN_GROUP:
            return self._join_group(v, r, now_ms)
        if api_key == ApiKey.SYNC_GROUP:
            return self._sync_group(r, now_ms)
        if api_key == ApiKey.HEARTBEAT:
            return self._heartbeat(r, now_ms)
        if api_key == ApiKey.LEAVE_GROUP:
            return self._leave_group(r, now_ms)
        # unknown api: an empty error response would desync framing —
        # close instead (matches broker behavior for unsupported keys)
        raise ValueError(f"unsupported api_key {api_key}")

    # -- api bodies ---------------------------------------------------------

    def _api_versions(self, code: int = Err.NONE) -> bytes:
        w = Writer().i16(code)
        w.array(_SUPPORTED, lambda t: w.i16(t[0]).i16(t[1]).i16(t[2]))
        return w.build()

    def _metadata(self, v: int, r: Reader) -> bytes:
        n = r.i32()
        topics = [t for t in (r.string() for _ in range(max(0, n))) if t is not None]
        # v0: null or empty array = all topics; v1+: null = all topics,
        # empty = NONE (the published semantics real clients rely on)
        if n < 0 or (v == 0 and n == 0):
            names = list(self.broker.topics)
        else:
            names = topics
        w = Writer()
        # brokers
        def broker_entry(_):
            w.i32(_NODE_ID).string(self.advertised_host).i32(self.advertised_port)
            if v >= 1:
                w.string(None)  # rack

        w.array([0], broker_entry)
        if v >= 1:
            w.i32(_NODE_ID)  # controller_id

        def topic_entry(name: str):
            parts = self.broker.topics.get(name)
            err = Err.NONE if parts is not None else Err.UNKNOWN_TOPIC_OR_PARTITION
            w.i16(err).string(name)
            if v >= 1:
                w.i8(0)  # is_internal
            plist = list(range(len(parts or ())))

            def part_entry(pid: int):
                w.i16(Err.NONE).i32(pid).i32(_NODE_ID)
                w.array([_NODE_ID], w.i32)  # replicas
                w.array([_NODE_ID], w.i32)  # isr

            w.array(plist, part_entry)

        w.array(names, topic_entry)
        return w.build()

    def _produce(self, v: int, r: Reader, now_ms: int) -> Optional[bytes]:
        if v >= 3:
            _txn_id = r.string()
        acks = r.i16()
        _timeout = r.i32()
        results: List[Tuple[str, List[Tuple[int, int, int]]]] = []
        for _ in range(r.i32()):
            topic = r.string() or ""
            parts: List[Tuple[int, int, int]] = []
            for _p in range(r.i32()):
                partition = r.i32()
                blob = r.bytes_() or b""
                try:
                    base = -1
                    for _off, key, value, ts_ms, headers in decode_record_blob(blob):
                        if ts_ms < 0:
                            ts_ms = now_ms
                        _pt, off = self.broker.produce(
                            topic, partition, key, value, ts_ms, headers
                        )
                        if base < 0:
                            base = off
                    parts.append((partition, Err.NONE, base))
                except UnsupportedCodec:
                    parts.append((partition, Err.CORRUPT_MESSAGE, -1))
                except KafkaError as e:
                    parts.append((partition, _kafka_code(e), -1))
            results.append((topic, parts))
        if acks == 0:
            return None  # fire-and-forget: a response would desync framing
        w = Writer()

        def topic_entry(item):
            topic, parts = item
            w.string(topic)

            def part_entry(p):
                partition, err, base = p
                w.i32(partition).i16(err).i64(base)
                if v >= 2:
                    w.i64(-1)  # log_append_time

            w.array(parts, part_entry)

        w.array(results, topic_entry)
        if v >= 1:
            w.i32(0)  # throttle_time_ms
        return w.build()

    def _fetch(self, v: int, r: Reader) -> bytes:
        _replica = r.i32()
        _max_wait = r.i32()
        _min_bytes = r.i32()
        if v >= 3:
            _max_bytes = r.i32()
        if v >= 4:
            _isolation = r.i8()
        reqs: List[Tuple[str, List[Tuple[int, int, int]]]] = []
        for _ in range(r.i32()):
            topic = r.string() or ""
            parts = []
            for _p in range(r.i32()):
                parts.append((r.i32(), r.i64(), r.i32()))
            reqs.append((topic, parts))
        w = Writer()
        if v >= 1:
            w.i32(0)  # throttle_time_ms

        def topic_entry(item):
            topic, parts = item
            w.string(topic)

            def part_entry(p):
                partition, offset, _max_bytes_p = p
                try:
                    msgs = self.broker.fetch(topic, partition, offset, 1000)
                    _lo, hi = self.broker.watermarks(topic, partition)
                    recs = [
                        (m.offset, m.key, m.payload, m.timestamp, m.headers)
                        for m in msgs
                    ]
                    blob = (
                        encode_record_batch(recs)
                        if v >= 4
                        else encode_message_set(recs)
                    )
                    w.i32(partition).i16(Err.NONE).i64(hi)
                    if v >= 4:
                        w.i64(hi)  # last_stable_offset (no txns)
                        w.array([], lambda a: None)  # aborted_transactions
                    w.bytes_(blob)
                except KafkaError as e:
                    w.i32(partition).i16(_kafka_code(e)).i64(-1)
                    if v >= 4:
                        w.i64(-1)
                        w.array([], lambda a: None)
                    w.bytes_(b"")

            w.array(parts, part_entry)

        w.array(reqs, topic_entry)
        return w.build()

    def _list_offsets(self, v: int, r: Reader) -> bytes:
        _replica = r.i32()
        reqs = []
        for _ in range(r.i32()):
            topic = r.string() or ""
            parts = []
            for _p in range(r.i32()):
                partition = r.i32()
                ts = r.i64()
                if v == 0:
                    _max_num = r.i32()
                parts.append((partition, ts))
            reqs.append((topic, parts))
        w = Writer()

        def topic_entry(item):
            topic, parts = item
            w.string(topic)

            def part_entry(p):
                partition, ts = p
                try:
                    lo, hi = self.broker.watermarks(topic, partition)
                    if ts == -2:  # earliest
                        off = lo
                    elif ts == -1:  # latest
                        off = hi
                    else:
                        got = self.broker.offsets_for_time(topic, partition, ts)
                        off = -1 if got is None else got
                    if v == 0:
                        w.i32(partition).i16(Err.NONE)
                        w.array([off] if off >= 0 else [], w.i64)
                    else:
                        w.i32(partition).i16(Err.NONE).i64(-1).i64(off)
                except KafkaError as e:
                    if v == 0:
                        w.i32(partition).i16(_kafka_code(e)).array([], w.i64)
                    else:
                        w.i32(partition).i16(_kafka_code(e)).i64(-1).i64(-1)

            w.array(parts, part_entry)

        w.array(reqs, topic_entry)
        return w.build()

    def _create_topics(self, r: Reader) -> bytes:
        results: List[Tuple[str, int]] = []
        for _ in range(r.i32()):
            name = r.string() or ""
            num_partitions = r.i32()
            _repl = r.i16()
            for _a in range(max(0, r.i32())):  # assignments
                r.i32()
                r.array(r.i32)
            for _c in range(max(0, r.i32())):  # configs
                r.string()
                r.string()
            try:
                self.broker.create_topic(name, num_partitions)
                results.append((name, Err.NONE))
            except KafkaError as e:
                code = (
                    Err.INVALID_PARTITIONS
                    if e.code == ErrorCode.INVALID_ARG
                    else _kafka_code(e)
                )
                results.append((name, code))
        _timeout = r.i32()
        w = Writer()
        w.array(results, lambda t: w.string(t[0]).i16(t[1]))
        return w.build()

    def _find_coordinator(self, r: Reader) -> bytes:
        _group = r.string()
        return (
            Writer()
            .i16(Err.NONE)
            .i32(_NODE_ID)
            .string(self.advertised_host)
            .i32(self.advertised_port)
            .build()
        )

    def _offset_commit(self, v: int, r: Reader, now_ms: int) -> bytes:
        group = r.string() or ""
        member_id = None
        generation = None
        if v >= 1:
            generation = r.i32()
            member_id = r.string()
        if v >= 2:
            _retention = r.i64()
        reqs = []
        for _ in range(r.i32()):
            topic = r.string() or ""
            parts = []
            for _p in range(r.i32()):
                partition = r.i32()
                offset = r.i64()
                if v == 1:
                    _ts = r.i64()
                _meta = r.string()
                parts.append((partition, offset))
            reqs.append((topic, parts))
        results = []
        for topic, parts in reqs:
            out = []
            for partition, offset in parts:
                try:
                    if member_id and generation is not None and generation >= 0:
                        self.broker.commit_offsets(
                            group, {(topic, partition): offset},
                            member_id, generation, now_ms=now_ms,
                        )
                    else:
                        self.broker.commit_offsets(
                            group, {(topic, partition): offset}
                        )
                    out.append((partition, Err.NONE))
                except KafkaError as e:
                    out.append((partition, _kafka_code(e)))
            results.append((topic, out))
        w = Writer()

        def topic_entry(item):
            topic, parts = item
            w.string(topic)
            w.array(parts, lambda p: w.i32(p[0]).i16(p[1]))

        w.array(results, topic_entry)
        return w.build()

    def _offset_fetch(self, r: Reader) -> bytes:
        group = r.string() or ""
        reqs = []
        for _ in range(r.i32()):
            topic = r.string() or ""
            parts = r.array(r.i32)
            reqs.append((topic, parts))
        w = Writer()

        def topic_entry(item):
            topic, parts = item
            w.string(topic)

            def part_entry(partition):
                try:
                    off = self.broker.committed(group, topic, partition)
                    w.i32(partition).i64(-1 if off is None else off)
                    w.string(None).i16(Err.NONE)
                except KafkaError as e:
                    w.i32(partition).i64(-1).string(None).i16(_kafka_code(e))

            w.array(parts, part_entry)

        w.array(reqs, topic_entry)
        return w.build()

    def _describe_groups(self, r: Reader, now_ms: int) -> bytes:
        groups = [g for g in r.array(r.string) if g is not None]
        w = Writer()

        def group_entry(group: str):
            try:
                info = self.broker.describe_group(group, now_ms)
            except KafkaError:
                # real brokers answer unknown groups as state "Dead"
                w.i16(Err.NONE).string(group).string("Dead")
                w.string("consumer").string("")
                w.array([], lambda m: None)
                return
            w.i16(Err.NONE).string(group).string("Stable")
            w.string("consumer").string(info["strategy"])

            def member_entry(item):
                member_id, topics = item
                w.string(member_id).string(member_id).string("/127.0.0.1")
                w.bytes_(encode_subscription(topics))
                w.bytes_(
                    encode_assignment(info["assignments"].get(member_id, []))
                )

            w.array(sorted(info["members"].items()), member_entry)

        w.array(groups, group_entry)
        return w.build()

    # -- classic group protocol --------------------------------------------

    def _join_group(self, v: int, r: Reader, now_ms: int) -> bytes:
        group = r.string() or ""
        session_ms = r.i32()
        if v >= 1:
            _rebalance_timeout = r.i32()
        member_id = r.string() or ""
        _protocol_type = r.string()
        protocols: List[Tuple[str, bytes]] = []
        for _ in range(r.i32()):
            pname = r.string() or ""
            pmeta = r.bytes_() or b""
            protocols.append((pname, pmeta))
        if not protocols:
            return Writer().i16(Err.INCONSISTENT_GROUP_PROTOCOL).i32(-1) \
                .string("").string("").string("").array([], lambda m: None).build()
        strategy, meta = protocols[0]
        topics = decode_subscription(meta)
        try:
            mid, generation = self.broker.join_group(
                group, member_id or None, topics, session_ms,
                strategy if strategy in ("range", "roundrobin") else "range",
                now_ms,
            )
        except KafkaError as e:
            return Writer().i16(_kafka_code(e)).i32(-1).string("") \
                .string("").string("").array([], lambda m: None).build()
        g = self.broker.groups[group]
        leader = sorted(g.members)[0]
        w = Writer()
        w.i16(Err.NONE).i32(generation).string(g.strategy)
        w.string(leader).string(mid)
        member_list = sorted(g.members.items()) if mid == leader else []

        def member_entry(item):
            m, info = item
            w.string(m).bytes_(encode_subscription(info.topics))

        w.array(member_list, member_entry)
        return w.build()

    def _sync_group(self, r: Reader, now_ms: int) -> bytes:
        group = r.string() or ""
        generation = r.i32()
        member_id = r.string() or ""
        for _ in range(r.i32()):  # leader-submitted assignments: broker-
            r.string()  #           side assignment is authoritative here
            r.bytes_()
        try:
            parts = self.broker.sync_group(group, member_id, generation, now_ms)
        except KafkaError as e:
            return Writer().i16(_kafka_code(e)).bytes_(b"").build()
        return Writer().i16(Err.NONE).bytes_(encode_assignment(parts)).build()

    def _heartbeat(self, r: Reader, now_ms: int) -> bytes:
        group = r.string() or ""
        generation = r.i32()
        member_id = r.string() or ""
        try:
            self.broker.heartbeat(group, member_id, generation, now_ms)
        except KafkaError as e:
            return Writer().i16(_kafka_code(e)).build()
        return Writer().i16(Err.NONE).build()

    def _leave_group(self, r: Reader, now_ms: int) -> bytes:
        group = r.string() or ""
        member_id = r.string() or ""
        try:
            self.broker.leave_group(group, member_id, now_ms)
        except KafkaError as e:
            return Writer().i16(_kafka_code(e)).build()
        return Writer().i16(Err.NONE).build()
